"""Two-process fleet-observatory smoke: ``make fleet-obs-smoke``.

The full r23 fleet stack, one command, no accelerator: 2 real ranks
over the eager host ring, step-marked train loops, a chaos-injected
``stop:<ms>`` stall on rank 1 that HEALS in place through the retry
ladder (the test_observability recipe — timeout 600 ms x 6 attempts,
400 ms backoff), while the driver polls the live ``/fleet`` endpoint
mid-run. Asserts:

1. **live fleet aggregation mid-run** — ``/fleet`` on rank 0 answers
   while both ranks are training, with a ledger row per rank;
2. **exact reconciliation** — every rank's rank-seconds buckets sum to
   its window TO THE MICROSECOND, with ``unattributed`` under 1%
   (the r17 standard applied fleet-wide);
3. **SLO attribution** — rank 1's own SLO check over its own ledger
   books the SIGSTOP gap to ``stall``, breaches ``stall_ms < 500``,
   and records a typed ``slo_breach`` ring event naming rank 1 with
   phase ``stall``, which the post-run ``report.py --fleet`` over the
   black-box dumps surfaces again — live verdict and post-mortem
   verdict from one evidence trail.
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

STALL_MS = 2500
WARMUP_STEPS = 3
SMOKE_SLO = ("stall_ms < 500",)


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker(tmpdir):
    import numpy as np

    from horovod_tpu.common import eager_ops
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.telemetry import fleet, slo

    b = HorovodBasics()
    b.init()
    rank, size = b.rank(), b.size()
    if rank == 1:
        # Fires on the op AFTER the warmup steps (one op per step);
        # heals in place via the retry ladder (env set by the driver).
        b.set_fault_inject_spec(f"1:{WARMUP_STEPS}:stop:{STALL_MS}")
    x = np.full(2048, float(rank + 1), np.float32)

    def step(i, name):
        b.step_mark(True)
        out = eager_ops.allreduce_async(x, name).synchronize()
        assert out[0] == 3.0, out[0]
        b.step_mark(False)

    for i in range(WARMUP_STEPS):
        step(i, f"warm.{i}")
    # Handshake: both ranks up with debug servers answering; the driver
    # polls /fleet live, then says go. The wait sits BETWEEN step
    # windows, so the ledger books it as idle, not unattributed.
    with open(os.path.join(tmpdir, f"ready.{rank}"), "w") as f:
        f.write("ready")
    deadline = time.monotonic() + 60
    while not os.path.exists(os.path.join(tmpdir, "go")):
        assert time.monotonic() < deadline, "driver never said go"
        time.sleep(0.05)
    # The stall step: rank 1 SIGSTOPs mid-op and resumes; rank 0 rides
    # the retry ladder until the transfer completes. Nobody faults.
    step(WARMUP_STEPS, "stall")
    step(WARMUP_STEPS + 1, "post")

    # Local ledger + SLO check over this rank's OWN ring: per-rank
    # evaluation makes breach attribution exact by construction.
    events = b.events(8192)
    ledger = fleet.ledger_from_events(events, rank=rank)
    buckets = ledger["buckets"]
    assert sum(buckets.values()) == ledger["window_us"], \
        f"rank {rank}: buckets do not reconcile: {ledger}"
    assert buckets["unattributed"] < 0.01 * ledger["window_us"], \
        f"rank {rank}: unattributed {buckets['unattributed']} us " \
        f"of {ledger['window_us']}: {buckets}"
    engine = slo.SloEngine(SMOKE_SLO)
    breaches = engine.evaluate(
        {rank: fleet.ledger_signals(ledger)},
        {rank: fleet.dominant_phase(ledger)})
    if rank == 1:
        assert breaches, f"rank 1 saw no stall_ms breach: {ledger}"
        assert breaches[0].phase == "stall", breaches
    engine.record(b, breaches)

    # One live dump per rank: the post-mortem side of the same trail.
    from horovod_tpu.telemetry import critpath

    dump_dir = os.environ["HVDTPU_FLEET_DUMPS"]
    os.makedirs(dump_dir, exist_ok=True)
    critpath.write_event_dump(
        os.path.join(dump_dir, f"blackbox-rank{rank}.jsonl"),
        rank, size, b.events(8192))
    time.sleep(0.5)  # r12 ordering: sockets stay open for the peer
    b.shutdown()
    print(f"FLEET_SMOKE_OK rank={rank} "
          f"window_us={ledger['window_us']} "
          f"stall_us={buckets['stall']} "
          f"unattributed_us={buckets['unattributed']} "
          f"breaches={len(breaches)}")
    return 0


def _get_json(url, timeout=20):
    return json.loads(urllib.request.urlopen(url, timeout=timeout).read())


def main():
    if "--worker" in sys.argv:
        return worker(os.environ["HVDTPU_SMOKE_TMP"])

    from horovod_tpu.telemetry import fleet

    size = 2
    port = _free_port()
    dbg_port = _free_port()
    with tempfile.TemporaryDirectory() as tmpdir:
        dump_dir = os.path.join(tmpdir, "dumps")
        procs = []
        for rank in range(size):
            env = dict(os.environ,
                       HOROVOD_RANK=str(rank), HOROVOD_SIZE=str(size),
                       HOROVOD_CONTROLLER_ADDR="127.0.0.1",
                       HOROVOD_CONTROLLER_PORT=str(port),
                       # The heal recipe: the stall outlasts one
                       # timeout but not the ladder — the world
                       # survives and the ledger books the gap.
                       HOROVOD_WIRE_TIMEOUT_MS="600",
                       HOROVOD_WIRE_RETRY_ATTEMPTS="6",
                       HOROVOD_WIRE_RETRY_BACKOFF_MS="400",
                       HOROVOD_DEBUG_PORT=str(dbg_port),
                       HVDTPU_FLEET_DUMPS=dump_dir,
                       HVDTPU_SMOKE_TMP=tmpdir,
                       JAX_PLATFORMS="cpu")
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "horovod_tpu.telemetry.fleet_smoke", "--worker"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))

        # Phase 1: both ranks warmed up -> /fleet on rank 0 aggregates
        # the LIVE fleet (rank 0 polls both debug servers, itself
        # included — the server is threaded).
        deadline = time.monotonic() + 60
        while not all(os.path.exists(os.path.join(tmpdir, f"ready.{r}"))
                      for r in range(size)):
            if time.monotonic() > deadline:
                for p in procs:
                    p.kill()
                print("fleet-obs-smoke: FAILED (workers never ready)")
                return 1
            time.sleep(0.05)
        view = _get_json(f"http://127.0.0.1:{dbg_port}/fleet")
        assert view["size"] == size and view["reachable"] == size, view
        for r in range(size):
            entry = view["ranks"][str(r)]
            ledger = entry["ledger"]
            assert sum(ledger["buckets"].values()) \
                == ledger["window_us"], entry
        assert view["fleet"]["window_us"] > 0, view
        print(f"fleet-obs-smoke: /fleet live mid-run — {size}/{size} "
              f"ranks reachable, fleet utilization "
              f"{view['fleet']['utilization']:.1%}")

        # Phase 2: release the stall step and let the workers finish
        # their own reconciliation + SLO assertions.
        with open(os.path.join(tmpdir, "go"), "w") as f:
            f.write("go")
        failed = False
        for rank, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
                out = "TIMEOUT"
            ok = p.returncode == 0 and "FLEET_SMOKE_OK" in out
            print(out.strip())
            if not ok:
                print(f"rank {rank} FAILED (rc={p.returncode})")
                failed = True
        if failed:
            return 1

        # Phase 3: post-mortem over the same evidence — the recorded
        # breach event must name rank 1 with phase stall, and every
        # rank's buckets must reconcile exactly in the offline ledger
        # too.
        analysis = fleet.analyze(dump_dir, objectives=SMOKE_SLO)
        for r, ledger in analysis["per_rank"].items():
            assert sum(ledger["buckets"].values()) \
                == ledger["window_us"], (r, ledger)
            assert ledger["buckets"]["unattributed"] \
                < 0.01 * ledger["window_us"], (r, ledger["buckets"])
        recorded = [b for b in analysis["slo"]["breach_events"]
                    if b["objective"] == "stall_ms"]
        assert any(b["breach_rank"] == 1 and b["phase"] == "stall"
                   for b in recorded), analysis["slo"]
        print(fleet.format_fleet(analysis))

        # And the CLI renders the same verdict (report.py --fleet).
        cli = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.telemetry.report",
             "--fleet", "--slo", "stall_ms < 500", dump_dir],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert cli.returncode == 0, cli.stderr[-500:]
        assert "breach [stall_ms] rank 1" in cli.stdout, cli.stdout
        print(f"fleet-obs-smoke: OK (live /fleet + worker-side "
              f"reconciliation + post-mortem breach attribution all "
              f"agree: rank 1, phase stall)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
