"""Telemetry-driven autoscaler: elastic as capacity management.

The r12-r14 elastic machinery treats world-size change as a FAULT
response (a peer dies, survivors shrink; a host returns, the parole
door regrows). This module closes the observability loop the other way
round: the signals the runtime already serves — queue depth, straggler
skew, step-time trend, heal/fault rates, paroled joiners waiting at
the door (``/healthz``, docs/scale.md signal table) — drive the SAME
rejoin/shrink machinery to grow or shrink the world under load.

Three layers, deliberately separable:

- :class:`Signals` — one observation; :func:`collect_signals` fills it
  from the live core (the same fields ``/healthz`` exports, so a
  driver-side autoscaler polling HTTP computes identical decisions);
- :class:`AutoscalePolicy` — a PURE decision function over an
  observation stream: deterministic, no clock reads, no side effects;
  hysteresis (consecutive-breach streaks, an up/down deadband, and a
  post-action cooldown) guarantees a flapping signal never oscillates
  the world size (pinned by tests/single/test_autoscale.py);
- :class:`Autoscaler` — the driver glue: applies decisions through
  ``grow``/``shrink`` callbacks (in driverless worlds: spawn a worker
  that knocks on the parole door / ``hvd.elastic.shrink``).

Reference analog: none in upstream Horovod — its elastic driver only
reacts to discovery changes; the policy shape (breach streaks +
cooldown around a deadband) is the classic k8s-HPA stabilization
recipe applied to training-runtime signals.
"""

from collections import deque
from dataclasses import dataclass, field

# THE serving-field sentinel set (docs/serving.md): every consumer —
# /healthz (telemetry/debug_server.py), collect_signals below, and
# serving_signals itself — shares this one literal, so the pinned
# field names/defaults can never drift apart. kv_blocks_* -1 means
# "no pool in this process", distinct from a pool momentarily empty.
SERVING_SIGNAL_DEFAULTS = {
    "serving_queue_depth": 0,
    "inflight_sequences": 0,
    "kv_blocks_free": -1,
    "kv_blocks_total": -1,
    # r19: rolling-window request-latency pressure (the autoscaler can
    # see latency, not just queue depth) + eviction amplification
    # (recomputed prefill tokens per useful generated token — the
    # pool-thrash signal; docs/serving.md "Request lifecycle &
    # tracing"). Zeros = "no service live or nothing measured yet".
    "serving_p50_ms": 0.0,
    "serving_p99_ms": 0.0,
    "requests_served": 0,
    "recomputed_prefill_tokens": 0,
    "useful_tokens": 0,
    "eviction_amplification": 0.0,
}


def read_serving_signals():
    """The live decode service's signal dict, or the defaults. Lazy
    ``sys.modules`` lookup: a non-serving process never imports the
    serving package for its health check."""
    import sys

    svc = sys.modules.get("horovod_tpu.serving.service")
    if svc is not None:
        try:
            return svc.serving_signals()
        except Exception:  # noqa: BLE001 — signals must come back
            pass
    return dict(SERVING_SIGNAL_DEFAULTS)


def read_fleet_signals():
    """The fleet observatory's signal dict, or zeros. Same lazy
    ``sys.modules`` discipline as :func:`read_serving_signals`: a
    process that never served ``/fleet`` never imports the fleet
    module, and a live observatory is read from its LAST poll — an
    observation must never trigger a fleet-wide HTTP sweep."""
    import sys

    out = {"slo_breaches": 0, "fleet_utilization": 0.0,
           "rank_seconds_unattributed_share": 0.0}
    fleet = sys.modules.get("horovod_tpu.telemetry.fleet")
    if fleet is None or fleet._observatory is None:
        return out
    try:
        obs = fleet._observatory
        out["slo_breaches"] = len(obs.engine.breaches)
        view = getattr(obs, "last_view", None)
        if view:
            out["fleet_utilization"] = view["fleet"]["utilization"]
            total_s = view["fleet"]["window_us"] / 1e6
            if total_s > 0:
                out["rank_seconds_unattributed_share"] = round(
                    view["fleet"]["rank_seconds"]["unattributed"]
                    / total_s, 6)
    except Exception:  # noqa: BLE001 — signals must come back anyway
        pass
    return out


@dataclass
class Signals:
    """One autoscaler observation (field meanings in docs/scale.md)."""

    t: float                       # observation time, seconds (any
    #                                monotonic origin; the policy only
    #                                differences it for cooldowns)
    world_size: int
    queue_depth: int = 0           # pending collectives in the core
    straggler_skew_ms: float = 0.0  # negotiation skew p90
    step_time_ms: float = 0.0      # step-time EWMA (0 = unknown)
    heal_rate: float = 0.0         # wire heals since last observation
    fault_rate: float = 0.0        # faults since last observation
    pending_rejoiners: int = 0     # paroled joiners waiting at the door
    # Step-anatomy additions (r17, defaulted so pre-r17 observation
    # sources — recorded traces, older /healthz payloads — still
    # construct Signals unchanged): the overlap ledger's combined
    # hidden/total wire fraction and cumulative exposed wire wall time
    # (docs/metrics.md "Overlap ledger").
    overlap_efficiency: float = 0.0
    exposed_wire_ms: float = 0.0
    # Serving-lane additions (r18, same back-compat discipline —
    # defaults keep pre-serving observation sources constructing):
    # the decode service's /healthz field set (docs/serving.md).
    # kv_blocks_* default -1 = "no pool in this process", distinct
    # from a real pool that is momentarily empty.
    serving_queue_depth: int = 0
    inflight_sequences: int = 0
    kv_blocks_free: int = -1
    kv_blocks_total: int = -1
    # r19 serving additions (same back-compat discipline; decision-
    # invariant today — the policy reads none of them): rolling-window
    # request latency so a latency-pressured but short-queued service
    # is VISIBLE to a future policy, and eviction amplification
    # (recomputed prefill tokens / useful tokens — KV-pool thrash).
    serving_p50_ms: float = 0.0
    serving_p99_ms: float = 0.0
    requests_served: int = 0
    recomputed_prefill_tokens: int = 0
    useful_tokens: int = 0
    eviction_amplification: float = 0.0
    # r23 fleet/SLO additions (same back-compat discipline; decision-
    # invariant today): the fleet observatory's view — cumulative SLO
    # breaches it has evaluated, breaches since the last observation,
    # the fleet-wide utilization from its last poll (0 = no fleet view
    # in this process), and the share of this fleet's rank-seconds the
    # ledger could not attribute (docs/fleet.md) — so a future policy
    # can scale on "the fleet is breaching/idle", not just local queue
    # pressure.
    slo_breaches: int = 0
    slo_breach_rate: float = 0.0
    fleet_utilization: float = 0.0
    rank_seconds_unattributed_share: float = 0.0


@dataclass
class Decision:
    action: str                    # "up" | "down" | "hold"
    target_size: int
    reason: str


@dataclass
class AutoscalePolicy:
    """Pure hysteresis policy: ``decide`` maps an observation stream to
    scale decisions, deterministically.

    Scale-up pressure: ``queue_depth > up_queue_depth`` or the
    step-time EWMA exceeding ``up_step_time_ratio`` x its own slow
    baseline. Scale-down pressure: queue at/below ``down_queue_depth``
    AND straggler skew under ``down_skew_ms`` (an idle world that is
    also not limping). A breach only becomes a decision after
    ``up_consecutive``/``down_consecutive`` observations in a row, any
    decision opens a ``cooldown_s`` window of forced holds, and the
    deadband between the up and down conditions means a signal flapping
    around either threshold resets the opposite streak instead of
    reversing the world — the three stabilizers that make oscillation
    structurally impossible (test_autoscale.py pins a flap trace).

    Instability gates scaling entirely: while faults/heals are moving
    (``fault_rate``/``heal_rate`` > 0) the policy holds — resizing a
    world that is mid-recovery would race the elastic machinery it
    drives.
    """

    min_size: int = 1
    max_size: int = 256
    step: int = 1                  # ranks per decision
    up_queue_depth: int = 8
    up_step_time_ratio: float = 1.5
    down_queue_depth: int = 0
    down_skew_ms: float = 50.0
    up_consecutive: int = 3
    down_consecutive: int = 6
    cooldown_s: float = 30.0
    baseline_alpha: float = 0.05   # slow step-time baseline EWMA

    _up_streak: int = field(default=0, repr=False)
    _down_streak: int = field(default=0, repr=False)
    _cooldown_until: float = field(default=float("-inf"), repr=False)
    _baseline_ms: float = field(default=0.0, repr=False)

    def _overloaded(self, s):
        if s.queue_depth > self.up_queue_depth:
            return f"queue_depth {s.queue_depth} > {self.up_queue_depth}"
        if (self._baseline_ms > 0.0 and s.step_time_ms
                > self.up_step_time_ratio * self._baseline_ms):
            return (f"step_time {s.step_time_ms:.1f}ms > "
                    f"{self.up_step_time_ratio:.2f}x baseline "
                    f"{self._baseline_ms:.1f}ms")
        return None

    def _idle(self, s):
        return (s.queue_depth <= self.down_queue_depth
                and s.straggler_skew_ms <= self.down_skew_ms)

    def decide(self, s):
        """One observation -> one :class:`Decision` (pure; mutates only
        the policy's own streak/cooldown/baseline state)."""
        # The baseline tracks step time through every observation —
        # including holds — so "1.5x slower than usual" means usual for
        # THIS model/world, not a configured absolute.
        if s.step_time_ms > 0.0:
            self._baseline_ms = (
                s.step_time_ms if self._baseline_ms == 0.0
                else (1 - self.baseline_alpha) * self._baseline_ms
                + self.baseline_alpha * s.step_time_ms)

        if s.fault_rate > 0 or s.heal_rate > 0:
            self._up_streak = self._down_streak = 0
            return Decision("hold", s.world_size,
                            "unstable: faults/heals in flight")
        if s.t < self._cooldown_until:
            return Decision("hold", s.world_size,
                            "cooldown after last resize")

        overload = self._overloaded(s)
        if overload is not None:
            self._down_streak = 0
            self._up_streak += 1
            if (self._up_streak >= self.up_consecutive
                    and s.world_size < self.max_size):
                self._up_streak = 0
                self._cooldown_until = s.t + self.cooldown_s
                target = min(s.world_size + self.step, self.max_size)
                return Decision("up", target, overload)
            return Decision("hold", s.world_size,
                            f"overload streak {self._up_streak}/"
                            f"{self.up_consecutive}: {overload}")
        if self._idle(s):
            self._up_streak = 0
            self._down_streak += 1
            if (self._down_streak >= self.down_consecutive
                    and s.world_size > self.min_size):
                self._down_streak = 0
                self._cooldown_until = s.t + self.cooldown_s
                target = max(s.world_size - self.step, self.min_size)
                return Decision("down", target, "idle: queue drained, "
                                "skew low")
            return Decision("hold", s.world_size,
                            f"idle streak {self._down_streak}/"
                            f"{self.down_consecutive}")
        # Deadband: neither overloaded nor idle — both streaks reset,
        # so a signal flapping across one threshold can never bank
        # progress toward the opposite action.
        self._up_streak = self._down_streak = 0
        return Decision("hold", s.world_size, "in deadband")


def collect_signals(basics=None, t=None):
    """Fill a :class:`Signals` from the live core — the same values
    ``/healthz`` serves, so in-process and HTTP-polling autoscalers see
    one truth. Rate fields are diffs against the previous call."""
    import time as _time

    from horovod_tpu.common.basics import HorovodBasics

    b = basics or HorovodBasics()
    snap = b.metrics_snapshot()
    elastic = snap.get("elastic", {})
    straggler = snap.get("straggler", {})
    global _last_counters
    fleet = read_fleet_signals()
    faults = int(elastic.get("faults_detected", 0))
    heals = int(elastic.get("heals", 0))
    breaches = int(fleet["slo_breaches"])
    prev = _last_counters or {"faults": faults, "heals": heals,
                              "breaches": breaches}
    _last_counters = {"faults": faults, "heals": heals,
                      "breaches": breaches}
    pending = 0
    try:
        from horovod_tpu.common import elastic as hvd_elastic

        if hvd_elastic._door is not None:
            pending = hvd_elastic._door.pending_count()
    except Exception:  # noqa: BLE001 — signals must come back anyway
        pass
    step_ms = 0.0
    try:
        from horovod_tpu.telemetry.step_timer import step_time_ewma_ms

        step_ms = step_time_ewma_ms() or 0.0
    except Exception:  # noqa: BLE001
        pass
    overlap = snap.get("wire", {}).get("overlap", {})
    serving = read_serving_signals()
    return Signals(
        t=_time.monotonic() if t is None else t,
        world_size=b.size() if b.is_initialized() else 1,
        queue_depth=b.queue_depth(),
        straggler_skew_ms=float(
            straggler.get("skew_us", {}).get("p90_us", 0)) / 1000.0,
        step_time_ms=step_ms,
        heal_rate=float(heals - prev["heals"]),
        fault_rate=float(faults - prev["faults"]),
        pending_rejoiners=pending,
        overlap_efficiency=float(
            overlap.get("overlap_efficiency", 0.0)),
        exposed_wire_ms=float(overlap.get("exposed_wire_ms", 0.0)),
        serving_queue_depth=int(serving["serving_queue_depth"]),
        inflight_sequences=int(serving["inflight_sequences"]),
        kv_blocks_free=int(serving["kv_blocks_free"]),
        kv_blocks_total=int(serving["kv_blocks_total"]),
        serving_p50_ms=float(serving.get("serving_p50_ms", 0.0)),
        serving_p99_ms=float(serving.get("serving_p99_ms", 0.0)),
        requests_served=int(serving.get("requests_served", 0)),
        recomputed_prefill_tokens=int(
            serving.get("recomputed_prefill_tokens", 0)),
        useful_tokens=int(serving.get("useful_tokens", 0)),
        eviction_amplification=float(
            serving.get("eviction_amplification", 0.0)),
        slo_breaches=breaches,
        slo_breach_rate=float(breaches - prev.get("breaches", breaches)),
        fleet_utilization=float(fleet["fleet_utilization"]),
        rank_seconds_unattributed_share=float(
            fleet["rank_seconds_unattributed_share"]),
    )


_last_counters = None


class Autoscaler:
    """Driver glue: observe -> decide -> act.

    ``grow(decision)`` / ``shrink(decision)`` apply the resize — in a
    driverless world, grow spawns (or admits) a worker that enters
    through the parole door and is absorbed at the next commit
    (``hvd.elastic`` rejoin path), shrink calls
    :func:`horovod_tpu.common.elastic.shrink`. Both default to no-ops
    so an observe-only autoscaler can log decisions first.

    IMPORTANT (SPMD): when every rank runs its own Autoscaler, the
    DECISION must be rank-uniform — feed the policy rank-0's signals
    (broadcast them) or run the autoscaler on rank 0 / the driver only;
    a per-rank decision from per-rank signals would desynchronize the
    world (the same agreement rule as the rejoin-poll collective).
    """

    def __init__(self, policy=None, collect=None, grow=None, shrink=None,
                 history=256):
        self.policy = policy or AutoscalePolicy()
        self.collect = collect or collect_signals
        self.grow = grow
        self.shrink = shrink
        # Bounded: a driver polling every few seconds for weeks must not
        # grow without limit; the newest window is what debugging wants.
        self.history = deque(maxlen=history)

    def step(self):
        """One observe/decide/act cycle; returns the Decision."""
        s = self.collect()
        d = self.policy.decide(s)
        self.history.append((s, d))
        if d.action == "up" and self.grow is not None:
            self.grow(d)
        elif d.action == "down" and self.shrink is not None:
            self.shrink(d)
        return d
