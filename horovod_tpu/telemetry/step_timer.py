"""Per-step wall-time, MFU, goodput, and bubble accounting.

:class:`StepTimer` is the step-level half of the telemetry subsystem:
the core registry (``telemetry.snapshot()``) counts what the runtime
moved; the timer relates those counters to *steps* — wall time per
step, model-FLOPs utilization from compiled cost analysis, wire
goodput, and measured-vs-predicted collective byte reconciliation
(predictions from :mod:`horovod_tpu.telemetry.predict`).

The bubble helpers compare a *measured* pipeline idle fraction against
``parallel.pipeline``'s analytic schedules (gpipe ``2(S-1)/(2M+2(S-1))``,
lockstep/true 1F1B, interleaved ``2(S-1)/(2MV+2(S-1))`` straight from
``build_interleaved_schedule``) so a perf PR can show its bubble win as
a number instead of an equation.
"""

import time

from horovod_tpu.telemetry import core as _core

# Peak dense-matmul FLOP/s by accelerator generation (same table the
# bench uses; substring-matched against device_kind, longest key first).
_PEAK_FLOPS = {"v4": 275e12, "v5e": 197e12, "v5 lite": 197e12,
               "v5": 459e12, "v6e": 918e12, "trillium": 918e12,
               "axon": 918e12, "cpu": 1e12}


def _device_peak_flops():
    try:
        import jax

        kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
        for key, val in sorted(_PEAK_FLOPS.items(),
                               key=lambda kv: -len(kv[0])):
            if key in kind:
                return val
    except Exception:  # noqa: BLE001 — no jax / no backend: caller
        pass           # must pass peak_flops explicitly for MFU
    return _PEAK_FLOPS["cpu"]


def compiled_flops(compiled):
    """Total FLOPs of one execution of a compiled jax program.

    ``compiled`` is the result of ``fn.lower(*args).compile()``;
    ``cost_analysis()`` returns a dict on current jax and a one-element
    list of dicts on older versions. Returns ``None`` when the backend
    does not report flops (some CPU paths).
    """
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = cost.get("flops")
        return float(flops) if flops and flops > 0 else None
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None


# ---- process-wide step-time EWMA --------------------------------------
# One number every observer agrees on: /healthz exports it and the
# autoscaler's step-time-trend signal reads it (docs/scale.md) — fed by
# whichever StepTimer instance is driving the training loop. EWMA so a
# single GC pause cannot flip a scaling decision on its own.
_STEP_EWMA_ALPHA = 0.1
_step_ewma_ms = 0.0


def _update_step_ewma(ms):
    global _step_ewma_ms
    _step_ewma_ms = (ms if _step_ewma_ms == 0.0 else
                     (1 - _STEP_EWMA_ALPHA) * _step_ewma_ms
                     + _STEP_EWMA_ALPHA * ms)


def step_time_ewma_ms():
    """The process's step-time EWMA in ms (0.0 until the first
    ``end_step``)."""
    return _step_ewma_ms


class StepTimer:
    """Accumulates per-step measurements; renders one summary row.

    Usage::

        timer = StepTimer(flops_per_step=..., predicted_bytes_per_step=...)
        for batch in data:
            with timer.step():
                loss, carry = step(carry, batch)
        row = timer.summary()

    ``block=True`` (default) blocks on the step outputs inside
    :meth:`end_step` so wall times mean what they say; pass ``False``
    when the surrounding harness already paces dispatch (then only the
    aggregate over many steps is meaningful).

    Collective bytes per step come from diffing the core metrics
    snapshot at step boundaries — zero instrumentation inside the step
    — and reconcile against ``predicted_bytes_per_step`` (from
    ``telemetry.predict``; the acceptance bar is 1%).
    """

    def __init__(self, flops_per_step=None, peak_flops=None,
                 predicted_bytes_per_step=None, block=True,
                 byte_op_classes=None):
        self.flops_per_step = flops_per_step
        self.peak_flops = peak_flops
        self.predicted_bytes_per_step = predicted_bytes_per_step
        self.block = block
        self.byte_op_classes = byte_op_classes
        self.step_times = []
        self.bytes_per_step = []
        # (tx, tx_logical) transport-byte deltas per step — diverge
        # only under wire compression (core.wire_bytes).
        self.wire_bytes_per_step = []
        # (intra_tx, intra_tx_logical, cross_tx, cross_tx_logical)
        # deltas per step: the per-plane split of the same transport
        # traffic (core.wire_plane_bytes) — cross is the DCN-priced
        # inter-slice hop of the hierarchical decomposition.
        self.plane_bytes_per_step = []
        # Per-step overlap ledger rows (docs/metrics.md "Overlap
        # ledger"): {plane: (exposed_us, hidden_us, total_us)} straight
        # from the core's interval-union math over the step window this
        # timer's own marks opened (exposed + hidden == total exactly).
        self.overlap_per_step = []
        self._step_id = None
        self._t0 = None
        self._bytes0 = None
        self._wire0 = None
        self._plane0 = None
        self._outputs = None

    # -- flops sources --------------------------------------------------

    def add_flops_from_compiled(self, compiled, calls=1):
        """Accumulate ``calls`` executions of a compiled program into
        ``flops_per_step`` (e.g. grad program x microbatches + apply)."""
        f = compiled_flops(compiled)
        if f is not None:
            self.flops_per_step = (self.flops_per_step or 0.0) + f * calls
        return f

    # -- per-step recording ---------------------------------------------

    def _read_bytes(self):
        # One snapshot serves the logical-payload, wire-vs-logical,
        # per-plane, and overlap-ledger reads alike.
        try:
            snap = _core.snapshot()
        except Exception:  # noqa: BLE001 — core not built/loaded: the
            return None, None, None, None  # timer still measures wall
        return (_core.total_collective_bytes(
                    snap, op_classes=self.byte_op_classes),
                _core.wire_bytes(snap),
                _core.wire_plane_bytes(snap),
                _core.wire_overlap(snap))

    def start_step(self):
        self._bytes0, self._wire0, self._plane0, _ = self._read_bytes()
        # Open the core-side step window (kStepBegin + overlap ledger,
        # docs/metrics.md "Step anatomy") AFTER the byte snapshot so
        # the window brackets exactly what this step moves.
        try:
            self._step_id = _core.step_mark(True, owner="StepTimer")
        except Exception:  # noqa: BLE001 — core not built/loaded
            self._step_id = None
        self._t0 = time.perf_counter()

    def end_step(self, outputs=None):
        if self._t0 is None:
            raise RuntimeError("end_step() without start_step()")
        if self.block and outputs is not None:
            try:
                import jax

                jax.block_until_ready(outputs)
            except Exception:  # noqa: BLE001 — non-jax outputs
                pass
        self.step_times.append(time.perf_counter() - self._t0)
        _update_step_ewma(self.step_times[-1] * 1000.0)
        # Close the window BEFORE the snapshot: the ledger folds the
        # step's wire spans on kStepEnd, so the read below sees this
        # step's union accounting in wire.overlap.*.last_*.
        if self._step_id is not None:
            # One owner per window: if another driver re-opened the
            # window mid-step (the fused optimizer's implicit boundary
            # racing this explicit scope), the ledger attribution below
            # would be a half-window masquerading as the full step —
            # refuse loudly instead of recording garbage.
            owner = _core.window_owner()
            if owner != "StepTimer":
                self._step_id = None
                self._t0 = None
                raise RuntimeError(
                    "StepTimer.end_step(): the step window this timer "
                    f"opened is now owned by {owner!r} — two step "
                    "drivers are marking boundaries in the same "
                    "iteration; scope the step with ONE of the "
                    "explicit StepTimer or the fused optimizer's "
                    "implicit boundary (docs/metrics.md)")
            try:
                _core.step_mark(False)
            except Exception:  # noqa: BLE001
                pass
        b1, w1, p1, ov = self._read_bytes()
        if self._bytes0 is not None and b1 is not None:
            self.bytes_per_step.append(b1 - self._bytes0)
        if self._wire0 is not None and w1 is not None:
            self.wire_bytes_per_step.append(
                (w1[0] - self._wire0[0], w1[1] - self._wire0[1]))
        if self._plane0 is not None and p1 is not None:
            self.plane_bytes_per_step.append(
                tuple(a - b for a, b in zip(p1, self._plane0)))
        if self._step_id is not None and ov:
            self.overlap_per_step.append({
                plane: (ov[plane]["last_exposed_us"],
                        ov[plane]["last_hidden_us"],
                        ov[plane]["last_total_us"])
                for plane in ("intra", "cross") if plane in ov})
        self._step_id = None
        self._t0 = None

    class _Step:
        def __init__(self, timer):
            self._timer = timer

        def __enter__(self):
            self._timer.start_step()
            return self._timer

        def __exit__(self, exc_type, exc, tb):
            if exc_type is None:
                self._timer.end_step(self._timer._outputs)
            self._timer._outputs = None
            return False

    def step(self):
        """Context manager timing one step. To block on the step's
        outputs, hand them over via :meth:`set_outputs` inside the
        ``with`` body (or call start/end explicitly)."""
        return StepTimer._Step(self)

    def set_outputs(self, outputs):
        self._outputs = outputs
        return outputs

    def wrap(self, step_fn):
        """Instrument ``step_fn(carry, batch) -> (loss, carry)``: every
        call is timed (and, with ``block=True``, synchronized)."""
        def timed_step(carry, batch):
            self.start_step()
            out = step_fn(carry, batch)
            self.end_step(out)
            return out

        return timed_step

    # -- aggregates ------------------------------------------------------

    @property
    def steps(self):
        return len(self.step_times)

    def mean_step_s(self, skip_first=True):
        """Mean step wall time; the first recorded step is dropped by
        default (it carries compilation)."""
        times = self.step_times
        if skip_first and len(times) > 1:
            times = times[1:]
        return sum(times) / len(times) if times else None

    def mfu(self, skip_first=True):
        dt = self.mean_step_s(skip_first)
        if not dt or not self.flops_per_step:
            return None
        peak = self.peak_flops or _device_peak_flops()
        return self.flops_per_step / dt / peak

    def measured_bytes_per_step(self, skip_first=True):
        vals = self.bytes_per_step
        if skip_first and len(vals) > 1:
            vals = vals[1:]
        return sum(vals) / len(vals) if vals else None

    def byte_reconciliation(self):
        """measured / predicted collective bytes per step (1.0 = the
        static predictor and the runtime counters agree)."""
        measured = self.measured_bytes_per_step()
        if not measured or not self.predicted_bytes_per_step:
            return None
        return measured / self.predicted_bytes_per_step

    def wire_goodput_gbps(self, skip_first=True):
        """Collective payload moved per second of step wall time, in
        GB/s — the goodput column (payload only: negotiation frames and
        protocol overhead excluded by construction). LOGICAL bytes by
        design: compression makes the wire cheaper, not the payload
        smaller — see :meth:`wire_compression_ratio` for the wire side."""
        dt = self.mean_step_s(skip_first)
        bytes_ = self.measured_bytes_per_step(skip_first)
        if not dt or bytes_ is None:
            return None
        return bytes_ / dt / 1e9

    def wire_compression_ratio(self, skip_first=True):
        """Transport bytes / full-width bytes over the recorded steps:
        1.0 uncompressed, ~0.5 with bf16-on-wire fp32 traffic (the
        wire-vs-logical reconciliation of ``docs/wire.md``). The first
        step is dropped by default, matching every other aggregate (its
        compile-time one-off traffic would dilute the quotient)."""
        vals = self.wire_bytes_per_step
        if skip_first and len(vals) > 1:
            vals = vals[1:]
        tx = sum(w[0] for w in vals)
        txl = sum(w[1] for w in vals)
        return tx / txl if txl else None

    def plane_wire_summary(self, skip_first=True):
        """Per-plane transport accounting over the recorded steps:
        ``{plane: {tx_bytes_per_step, goodput_gbps,
        compression_ratio}}`` for ``intra`` (ICI-priced/local hops) and
        ``cross`` (the DCN-priced inter-slice hop the hierarchical
        decomposition books separately). Per-plane compression is the
        point: ``HOROVOD_CROSS_PLANE_COMPRESSION`` moves only the cross
        ratio to ~0.5 while intra stays 1.0, and the two byte streams
        must sum exactly to the total wire counters (pinned in ``make
        reshard-smoke``). ``None`` when no plane deltas were recorded."""
        vals = self.plane_bytes_per_step
        if skip_first and len(vals) > 1:
            vals = vals[1:]
        if not vals:
            return None
        dt = self.mean_step_s(skip_first)
        n = len(vals)
        out = {}
        for plane, (itx, itxl) in (("intra", (0, 1)), ("cross", (2, 3))):
            tx = sum(v[itx] for v in vals)
            txl = sum(v[itxl] for v in vals)
            out[plane] = {
                "tx_bytes_per_step": tx / n,
                "goodput_gbps": (tx / n / dt / 1e9) if dt else None,
                "compression_ratio": (tx / txl) if txl else None,
            }
        return out

    def overlap_summary(self, skip_first=True):
        """Per-plane step-anatomy ledger over the recorded steps
        (docs/metrics.md "Overlap ledger"): ``{plane:
        {mean_exposed_wire_ms, mean_hidden_wire_ms,
        mean_total_wire_ms, overlap_efficiency}}`` plus a combined
        ``overlap_efficiency`` across planes. ``exposed`` is wire time
        that ran while an API thread sat blocked in ``synchronize``
        (the host had nothing to do but watch the wire); ``hidden =
        total - exposed`` is wire time that drained while the host
        kept computing or dispatching — the compute/collective overlap
        win the jit-lane fusion schedule moves (docs/fusion.md).
        exposed + hidden == total exactly, per step, by construction. The ``mean_`` prefix is deliberate: the
        snapshot's ``wire.overlap`` and ``/healthz`` expose CUMULATIVE
        ``exposed_wire_ms`` totals under the unprefixed names — the
        two shapes must not share a key. ``None`` until a step
        recorded ledger rows."""
        vals = self.overlap_per_step
        if skip_first and len(vals) > 1:
            vals = vals[1:]
        if not vals:
            return None
        n = len(vals)
        out = {}
        all_exp = all_tot = 0
        for plane in ("intra", "cross"):
            exp = sum(v[plane][0] for v in vals if plane in v)
            hid = sum(v[plane][1] for v in vals if plane in v)
            tot = sum(v[plane][2] for v in vals if plane in v)
            all_exp += exp
            all_tot += tot
            out[plane] = {
                "mean_exposed_wire_ms": exp / 1000.0 / n,
                "mean_hidden_wire_ms": hid / 1000.0 / n,
                "mean_total_wire_ms": tot / 1000.0 / n,
                "overlap_efficiency": (hid / tot) if tot else 0.0,
            }
        out["overlap_efficiency"] = (
            (all_tot - all_exp) / all_tot if all_tot else 0.0)
        return out

    def summary(self):
        """One JSON-ready row of everything the timer knows."""
        snap = None
        try:
            snap = _core.snapshot()
        except Exception:  # noqa: BLE001
            pass
        row = {
            "steps": self.steps,
            "mean_step_s": self.mean_step_s(),
            "mfu": self.mfu(),
            "flops_per_step": self.flops_per_step,
            "bytes_per_step": self.measured_bytes_per_step(),
            "predicted_bytes_per_step": self.predicted_bytes_per_step,
            "byte_reconciliation": self.byte_reconciliation(),
            "wire_goodput_gbps": self.wire_goodput_gbps(),
            "wire_compression_ratio": self.wire_compression_ratio(),
            "plane_wire": self.plane_wire_summary(),
            "overlap": self.overlap_summary(),
        }
        if snap and snap.get("initialized"):
            row["cache_hit_rate"] = snap["cache"]["hit_rate"]
            row["cycle_stalls"] = snap["cycle"]["stalls"]
        return row


# ---- pipeline bubble accounting ---------------------------------------


def analytic_bubble(schedule, S, M, num_virtual=1):
    """The schedule's predicted idle fraction, from the same closed
    forms / tables the engines execute (``parallel.pipeline``; same
    numbers bench.py's ``pipeline_bubble`` rows emit). Schedules:
    ``gpipe``, ``1f1b`` (lockstep), ``interleaved_1f1b``."""
    if schedule == "gpipe":
        return 2 * (S - 1) / (2 * M + 2 * (S - 1))
    if schedule == "1f1b":
        return 2 * (S - 1) / (M + 2 * (S - 1))
    if schedule == "interleaved_1f1b":
        from horovod_tpu.parallel.pipeline import build_interleaved_schedule

        return build_interleaved_schedule(S, num_virtual, M).bubble_fraction
    raise ValueError(f"unknown schedule {schedule!r}")


def measured_bubble(step_time_s, subtick_time_s, M, num_virtual=1):
    """Measured idle fraction: each device runs ``2*M*V`` useful
    fwd/bwd subticks per step, so work time is ``2*M*V*subtick`` and
    everything else in the step wall time is bubble (plus comms — on
    hardware, measure ``subtick_time_s`` by timing the stage program
    standalone)."""
    work = 2.0 * M * num_virtual * subtick_time_s
    if step_time_s <= 0:
        raise ValueError("step_time_s must be positive")
    return max(0.0, 1.0 - work / step_time_s)


def bubble_report(schedule, S, M, num_virtual, step_time_s,
                  subtick_time_s):
    """Measured vs analytic bubble for one pipeline configuration.

    ``excess`` is the gap the analytic model cannot explain —
    scheduling overhead, comms not overlapped, stragglers — i.e. the
    actionable number."""
    measured = measured_bubble(step_time_s, subtick_time_s, M,
                               num_virtual)
    analytic = analytic_bubble(schedule, S, M, num_virtual)
    return {
        "schedule": schedule, "S": S, "M": M, "V": num_virtual,
        "measured_bubble": round(measured, 4),
        "analytic_bubble": round(analytic, 4),
        "excess": round(measured - analytic, 4),
        "step_time_s": step_time_s,
        "subtick_time_s": subtick_time_s,
    }
