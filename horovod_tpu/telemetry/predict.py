"""Static collective-byte predictors, built on the hvdlint jaxpr walker.

The lint world (``analysis/extract``) already knows how to turn any
traced program into its ordered collective signature; telemetry reuses
that walker as the *expected* side of the expected-vs-actual byte
reconciliation — one extractor, so the static analyzer and the runtime
counters can never disagree about what a program was supposed to move.

Two entry points:

- :func:`collective_bytes` — per-step bytes of any traceable SPMD
  program (psum/all_gather/... volumes, loops expanded by trip count).
- :func:`eager_allreduce_bytes` — the eager data-parallel step: one
  allreduce per gradient leaf. The gradient tree is traced as its
  in-graph equivalent (``psum`` of every ``grad`` leaf over a
  synthetic axis) and walked by the same extractor, so the predicted
  volume is literally the walker's sum over that signature.
"""

import numpy as np

from horovod_tpu.analysis.extract import extract, linearize


def _dtype_bytes(dtype_str):
    """Per-element bytes of a Collective's dtype tag. Mixed-dtype
    collectives join sorted names with commas; all repo collectives are
    homogeneous, so taking the first is exact today and a documented
    approximation otherwise."""
    name = dtype_str.split(",")[0] if dtype_str else "float32"
    try:
        return np.dtype(name).itemsize
    except TypeError:
        try:
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, name)).itemsize
        except (ImportError, AttributeError, TypeError):
            return 4


def signature_bytes(signature):
    """Sum payload bytes over a linearized collective signature."""
    return sum(c.nelems * _dtype_bytes(c.dtype)
               for c in linearize(signature))


def collective_bytes(fn, *args, axis_env=None):
    """Predicted per-call collective payload bytes of ``fn(*args)``.

    ``axis_env`` is a list of ``(axis_name, size)`` pairs binding the
    collective axes (same contract as ``analysis.lint``); args may be
    abstract (``jax.ShapeDtypeStruct``). Traced with ``jax.make_jaxpr``
    — no devices, mesh, or shard_map needed, so the predictor runs on
    the jax 0.4.x boxes too.
    """
    import jax

    jaxpr = jax.make_jaxpr(fn, axis_env=tuple(axis_env or ()))(*args)
    return signature_bytes(extract(jaxpr).signature)


def eager_allreduce_bytes(loss_fn, params, batch, size=2, axis="hvd"):
    """Predicted per-step wire bytes of the eager data-parallel step.

    The eager path allreduces every gradient leaf (grouped or not, the
    payload volume is the same); its in-graph equivalent is a ``psum``
    of each leaf over one axis, which is what gets traced and walked
    here. ``size`` only names the axis width for tracing — the
    per-rank payload volume (what the core's byte counters record on
    this rank) does not depend on it.
    """
    import jax

    def step_signature(p, b):
        grads = jax.grad(loss_fn)(p, b)
        return jax.tree.map(lambda g: jax.lax.psum(g, axis), grads)

    return collective_bytes(step_signature, params, batch,
                            axis_env=[(axis, size)])


def grad_tree_bytes(loss_fn, params, batch):
    """Gradient-tree byte volume via ``jax.eval_shape`` — the
    walker-free cross-check for :func:`eager_allreduce_bytes` (the two
    must agree exactly; the telemetry tests pin it)."""
    import jax

    shapes = jax.eval_shape(jax.grad(loss_fn), params, batch)
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree.leaves(shapes))
