"""Static collective-byte predictors, built on the hvdlint jaxpr walker.

The lint world (``analysis/extract``) already knows how to turn any
traced program into its ordered collective signature; telemetry reuses
that walker as the *expected* side of the expected-vs-actual byte
reconciliation — one extractor, so the static analyzer and the runtime
counters can never disagree about what a program was supposed to move.

Two entry points:

- :func:`collective_bytes` — per-step bytes of any traceable SPMD
  program (psum/all_gather/... volumes, loops expanded by trip count).
- :func:`eager_allreduce_bytes` — the eager data-parallel step: one
  allreduce per gradient leaf. The gradient tree is traced as its
  in-graph equivalent (``psum`` of every ``grad`` leaf over a
  synthetic axis) and walked by the same extractor, so the predicted
  volume is literally the walker's sum over that signature.
"""

import numpy as np

from horovod_tpu.analysis.extract import extract, linearize


def _dtype_bytes(dtype_str):
    """Per-element bytes of a Collective's dtype tag. Mixed-dtype
    collectives join sorted names with commas; all repo collectives are
    homogeneous, so taking the first is exact today and a documented
    approximation otherwise."""
    name = dtype_str.split(",")[0] if dtype_str else "float32"
    try:
        return np.dtype(name).itemsize
    except TypeError:
        try:
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, name)).itemsize
        except (ImportError, AttributeError, TypeError):
            return 4


def signature_bytes(signature):
    """Sum payload bytes over a linearized collective signature."""
    return sum(c.nelems * _dtype_bytes(c.dtype)
               for c in linearize(signature))


def collective_bytes(fn, *args, axis_env=None):
    """Predicted per-call collective payload bytes of ``fn(*args)``.

    ``axis_env`` is a list of ``(axis_name, size)`` pairs binding the
    collective axes (same contract as ``analysis.lint``); args may be
    abstract (``jax.ShapeDtypeStruct``). Traced with ``jax.make_jaxpr``
    — no devices, mesh, or shard_map needed, so the predictor runs on
    the jax 0.4.x boxes too.
    """
    import jax

    jaxpr = jax.make_jaxpr(fn, axis_env=tuple(axis_env or ()))(*args)
    return signature_bytes(extract(jaxpr).signature)


def eager_allreduce_bytes(loss_fn, params, batch, size=2, axis="hvd"):
    """Predicted per-step wire bytes of the eager data-parallel step.

    The eager path allreduces every gradient leaf (grouped or not, the
    payload volume is the same); its in-graph equivalent is a ``psum``
    of each leaf over one axis, which is what gets traced and walked
    here. ``size`` only names the axis width for tracing — the
    per-rank payload volume (what the core's byte counters record on
    this rank) does not depend on it.
    """
    import jax

    def step_signature(p, b):
        grads = jax.grad(loss_fn)(p, b)
        return jax.tree.map(lambda g: jax.lax.psum(g, axis), grads)

    return collective_bytes(step_signature, params, batch,
                            axis_env=[(axis, size)])


def zero_signature_bytes(signature, size):
    """Sum payload bytes over a signature the way the RUNTIME counters
    account the ZeRO collective mix: reduce-scatter/psum_scatter at the
    full input width (the core books the enqueued tensor), all_gather
    at the GATHERED output width (the core books ``managed_output`` —
    ``size`` x the per-rank operand). One convention on both sides is
    what lets the reconciliation hold to <1% on the mixed
    reduce-scatter + allgather step (docs/zero.md)."""
    total = 0
    for c in linearize(signature):
        n = c.nelems * _dtype_bytes(c.dtype)
        if c.prim == "all_gather":
            n *= size
        total += n
    return total


def eager_zero_bytes(loss_fn, params, batch, size=2, axis="hvd",
                     bucket_bytes=None):
    """Predicted per-step wire payload bytes of the eager ZeRO-1 step
    (``hvd.DistributedFusedAdam(zero=True)``): one reduce-scatter per
    padded gradient bucket down, one allgather of the updated param
    shards per bucket up. The in-graph equivalent is built from the
    SAME ``parallel.zero.zero_bucket_layout`` the optimizer executes —
    padding included — and walked by the same extractor, so predicted
    and measured can only diverge if the runtime moves something the
    layout does not know about."""
    import jax

    from horovod_tpu.parallel.zero import (
        DEFAULT_BUCKET_BYTES,
        zero_bucket_layout,
    )

    bucket_bytes = bucket_bytes or DEFAULT_BUCKET_BYTES

    def step_signature(p, b):
        grads = jax.grad(loss_fn)(p, b)
        leaves, _ = jax.tree.flatten(grads)
        layout = zero_bucket_layout(leaves, size, bucket_bytes)
        out = []
        for flat in layout.pack(leaves):
            shard = jax.lax.psum_scatter(flat, axis,
                                         scatter_dimension=0, tiled=True)
            out.append(jax.lax.all_gather(shard, axis, axis=0,
                                          tiled=True))
        return out

    jaxpr = jax.make_jaxpr(step_signature,
                           axis_env=((axis, size),))(params, batch)
    return zero_signature_bytes(extract(jaxpr).signature, size)


def zero_layout_bytes(layout):
    """Walker-free cross-check for :func:`eager_zero_bytes`: per step,
    every padded bucket crosses once as a reduce-scatter input and once
    as a gathered allgather output — ``2 x padded x itemsize`` per
    bucket (the two must agree exactly; pinned in tests)."""
    return sum(2 * b.padded * b.dtype.itemsize for b in layout.buckets)


def hier_allreduce_wire_bytes(count, itemsize, size, local_size, rank,
                              compress_cross=False, compressed=False):
    """Per-rank, PER-PLANE transport tx bytes of one hierarchical
    cross-plane allreduce: ``{"intra": ..., "cross": ...}`` — the
    expected side of the core's split wire counters
    (``wire.cross_tx_bytes`` vs total; csrc/metrics.cc). Delegates to
    the reshard module so the predictor and the planner share ONE
    implementation of the ring segment math (exact reconciliation is
    pinned in ``make reshard-smoke``)."""
    from horovod_tpu.parallel.reshard import hier_wire_bytes

    return hier_wire_bytes(count, itemsize, size, local_size, rank,
                           compress_cross=compress_cross,
                           compressed=compressed)


def flat_ring_wire_bytes(count, itemsize, size, rank, compressed=False):
    """Per-rank transport tx bytes of one flat host-ring allreduce
    (the hierarchical predictor's baseline)."""
    from horovod_tpu.parallel.reshard import flat_allreduce_wire_bytes

    return flat_allreduce_wire_bytes(count, itemsize, size, rank,
                                     compressed=compressed)


def redistribute_bytes(plan, rank=None):
    """Predicted transport tx bytes of a :class:`ReshardPlan` (this
    rank, or the whole world) — what the reshard-smoke reconciles
    against the measured wire counters to < 1%."""
    return plan.wire_tx_bytes(rank)


def grad_tree_bytes(loss_fn, params, batch):
    """Gradient-tree byte volume via ``jax.eval_shape`` — the
    walker-free cross-check for :func:`eager_allreduce_bytes` (the two
    must agree exactly; the telemetry tests pin it)."""
    import jax

    shapes = jax.eval_shape(jax.grad(loss_fn), params, batch)
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree.leaves(shapes))
