"""Parsed access to the native core's metrics registry.

Thin on purpose: the counters live in C++ (``csrc/metrics.h``) where
the background loop records them for free; this module only parses the
JSON snapshot and derives the handful of aggregates the exporters and
:class:`~horovod_tpu.telemetry.step_timer.StepTimer` need.
"""

from horovod_tpu.common.basics import HorovodBasics

_basics = HorovodBasics()


def snapshot():
    """One point-in-time dict of every core counter.

    Safe to call at any moment (before ``hvd.init()`` it returns zeroed
    counters with ``initialized: False``); cheap enough for per-step
    use — one ctypes call plus a small ``json.loads``. Counters are
    monotonic for the process lifetime: consumers diff snapshots rather
    than resetting (see ``docs/metrics.md`` for the catalog).
    """
    return _basics.metrics_snapshot()


def metrics_reset():
    """Zero the registry (tests / interactive sessions only). Also
    forgets the open window's owner: the core discards the window
    itself, so a stale owner would wrongly block the next driver."""
    global _window_owner
    _basics.metrics_reset()
    _window_owner = None


def wire_bytes(snap=None):
    """``(tx_bytes, tx_logical_bytes)`` of the host-ring transport.

    ``tx_bytes`` is what actually crossed the wire; ``tx_logical_bytes``
    the same traffic at full tensor width. They diverge exactly by the
    bf16 wire-compression saving (``HOROVOD_WIRE_COMPRESSION``, see
    ``docs/wire.md``) — and both differ from :func:`total_collective_bytes`,
    which counts logical PAYLOAD (the ring moves ~2(N-1)/N x payload
    per rank).
    """
    if snap is None:
        snap = snapshot()
    w = snap.get("wire", {})
    return w.get("tx_bytes", 0), w.get("tx_logical_bytes", 0)


def wire_plane_bytes(snap=None):
    """Per-plane transport tx accounting as a 4-tuple
    ``(intra_tx, intra_tx_logical, cross_tx, cross_tx_logical)``.

    The core books the cross-slice hop of the hierarchical
    decomposition separately (``wire.cross_*``, the DCN-priced fabric
    — docs/redistribute.md) *inside* the totals, so intra here is
    total minus cross. The pair of pairs lets per-plane goodput and
    compression ratios reconcile independently (cross-hop-only bf16
    moves cross to ~0.5 while intra stays 1.0).
    """
    if snap is None:
        snap = snapshot()
    w = snap.get("wire", {})
    cross = w.get("cross_tx_bytes", 0)
    cross_l = w.get("cross_tx_logical_bytes", 0)
    return (w.get("tx_bytes", 0) - cross,
            w.get("tx_logical_bytes", 0) - cross_l, cross, cross_l)


#: who opened the currently-open step window (None = no window, or a
#: legacy caller that did not declare itself). Owner strings in use:
#: "StepTimer" (explicit scope) and "optimizer" (the fused optimizer's
#: implicit boundary). Core step ids RESTART after metrics_reset(), so
#: id comparison alone cannot tell "my window" from "someone else's
#: window that reused my id" — the owner can.
_window_owner = None


def step_mark(begin=True, owner=None):
    """Mark a step boundary (see ``HorovodBasics.step_mark``); returns
    the step id. The StepTimer calls this at its own boundaries so the
    core-side overlap ledger and the Python wall clock scope the same
    window.

    ``owner`` names the driver opening the window; it is recorded
    python-side (:func:`window_owner`) so the two step-scoping drivers
    — an explicit StepTimer scope and the fused optimizer's implicit
    boundary — can detect each other and keep ONE owner per window
    instead of silently fragmenting the overlap ledger's attribution.
    A ``begin=False`` close always clears the owner.
    """
    global _window_owner
    sid = _basics.step_mark(begin)
    _window_owner = owner if begin else None
    return sid


def step_id():
    """The currently open step id, or -1."""
    return _basics.step_id()


def window_owner():
    """Who opened the currently-open step window (``step_mark``'s
    ``owner``), or None when no window is open / the opener did not
    declare itself."""
    return _window_owner


def wire_overlap(snap=None):
    """The per-step wire overlap ledger (``wire.overlap`` of the
    snapshot, docs/metrics.md): cumulative + last-step exposed/hidden/
    total wire time per plane, and the combined ``overlap_efficiency``.
    Empty dict when the core is unavailable."""
    if snap is None:
        snap = snapshot()
    return snap.get("wire", {}).get("overlap", {})


def events(last_n=0):
    """The newest ``last_n`` structured ring events (non-consuming;
    see ``docs/metrics.md`` for the event catalog)."""
    return _basics.events(last_n)


def events_drain():
    """Consume and return every ring event since the last drain."""
    return _basics.events_drain()


def total_collective_bytes(snap=None, planes=("ops", "device_ops"),
                           op_classes=None):
    """Sum payload bytes across op classes and planes of a snapshot.

    ``op_classes`` restricts the sum (e.g. ``("allreduce",)`` for
    gradient-traffic accounting); default is everything that moved.
    """
    if snap is None:
        snap = snapshot()
    total = 0
    for plane in planes:
        for op, counters in snap.get(plane, {}).items():
            if op_classes is not None and op not in op_classes:
                continue
            total += counters.get("bytes", 0)
    return total
