"""Live per-rank introspection endpoint (``HOROVOD_DEBUG_PORT``).

An opt-in daemon HTTP thread per rank — the window into a live or
WEDGED process that post-mortems cannot give (a post-mortem needs the
process to have noticed its fault; a rank blocked on a SIGSTOPped peer
has not, and SIGKILLing it to find out destroys the evidence). Rank r
listens on ``HOROVOD_DEBUG_PORT + r`` (ranks on one host must not
collide), bound to loopback unless ``HOROVOD_DEBUG_HOST`` widens it,
and serves:

- ``/healthz`` — epoch, world size, loop state, last fault record, and
  the elastic heal/retry counters as one JSON object; the liveness
  probe an operator (or k8s) polls.
- ``/metrics`` — the existing Prometheus text formatter
  (:func:`horovod_tpu.telemetry.exporters._flatten_prom`) over a fresh
  core snapshot: point a Prometheus scrape at the debug port directly,
  no textfile hop.
- ``/events`` — the newest event-ring tail as JSON
  (``?n=<count>``, default 256) — the flight recorder, live.
- ``/requests`` — in-flight serving requests (``?n=<count>``, default
  64): rid, current lifecycle phase, time in that phase, total age —
  the live side of the request span ledger
  (:mod:`horovod_tpu.telemetry.reqtrace`); empty on non-serving ranks.
- ``/stacks`` — a ``faulthandler`` dump of every Python thread: where
  exactly a wedged rank is stuck (ctypes waits release the GIL, so the
  server thread answers even while the main thread blocks inside a
  collective on a dead peer — the situation introspection exists for).

Everything is served by stdlib ``http.server`` on a daemon thread:
zero dependencies, zero cost until the first request, and the process
never waits on it at shutdown.
"""

import faulthandler
import json
import os
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

_server = None
_thread = None
_lock = threading.Lock()
_start_time = None


def _healthz(basics):
    lib = basics.lib
    initialized = bool(lib.hvdtpu_is_initialized())
    out = {
        "initialized": initialized,
        "rank": lib.hvdtpu_rank() if initialized else -1,
        "size": lib.hvdtpu_size() if initialized else -1,
        "epoch": int(lib.hvdtpu_epoch()),
        "loop_failed": bool(lib.hvdtpu_loop_failed()),
        "last_fault": basics.last_fault(),
        "uptime_s": round(time.monotonic() - _start_time, 3)
        if _start_time is not None else None,
        "debug_port": debug_port(),
    }
    # The autoscaler's signal set (docs/scale.md signal table): one
    # endpoint serves everything the scaling policy consumes, so a
    # driver-side autoscaler needs no second scrape.
    out["queue_depth"] = int(lib.hvdtpu_queue_depth())
    try:
        from horovod_tpu.telemetry.step_timer import step_time_ewma_ms

        out["step_time_ewma_ms"] = round(step_time_ewma_ms(), 3)
    except Exception:  # noqa: BLE001
        out["step_time_ewma_ms"] = 0.0
    pending = 0
    try:
        import sys

        hvd_elastic = sys.modules.get("horovod_tpu.common.elastic")
        if hvd_elastic is not None and hvd_elastic._door is not None:
            pending = hvd_elastic._door.pending_count()
    except Exception:  # noqa: BLE001
        pass
    out["pending_rejoiners"] = pending
    # Serving-lane fields (docs/serving.md): queue depth, in-flight
    # sequences, and paged-KV pool occupancy — the load-balancer /
    # autoscaler signal set for a decode rank. Always present
    # (autoscale.SERVING_SIGNAL_DEFAULTS sentinels when no service is
    # live) so the /healthz field set stays pinned.
    try:
        from horovod_tpu.telemetry.autoscale import read_serving_signals

        out.update(read_serving_signals())
    except Exception:  # noqa: BLE001 — health must answer anyway
        pass
    # Fleet/SLO fields (docs/fleet.md): the observatory's verdicts —
    # cumulative breaches, last fleet utilization, unattributed
    # rank-seconds share. Zeros when no observatory is live in this
    # process (same pinned-field-set discipline as the serving
    # sentinels above).
    try:
        from horovod_tpu.telemetry.autoscale import read_fleet_signals

        out.update(read_fleet_signals())
    except Exception:  # noqa: BLE001 — health must answer anyway
        pass
    try:
        snap = basics.metrics_snapshot()
        out["elastic"] = {
            k: v for k, v in snap.get("elastic", {}).items()
            if k != "detect_us"
        }
        out["cycles"] = snap.get("cycle", {}).get("count", 0)
        out["straggler_skew_ms"] = round(
            snap.get("straggler", {}).get("skew_us", {}).get("p90_us", 0)
            / 1000.0, 3)
        # Step-anatomy overlap ledger (docs/metrics.md): the combined
        # hidden/total wire fraction inside step windows, and the
        # cumulative wall time the steps spent with wire in flight —
        # the overlap-efficiency trend signal the autoscaler and
        # perfwatch read off this endpoint.
        ov = snap.get("wire", {}).get("overlap", {})
        out["overlap_efficiency"] = round(
            ov.get("overlap_efficiency", 0.0), 6)
        out["exposed_wire_ms"] = round(
            ov.get("exposed_wire_ms", 0.0), 3)
    except Exception as e:  # noqa: BLE001 — health must answer anyway
        out["metrics_error"] = str(e)
        out["straggler_skew_ms"] = 0.0
        out["overlap_efficiency"] = 0.0
        out["exposed_wire_ms"] = 0.0
    return out


def _stacks():
    """All-thread tracebacks via faulthandler (signal-safe C-level
    walker — it renders frames even when a thread holds odd state),
    plus the thread-name table faulthandler does not print."""
    names = {
        t.ident: f"{t.name}{' daemon' if t.daemon else ''}"
        for t in threading.enumerate()
    }
    with tempfile.TemporaryFile(mode="w+") as f:
        faulthandler.dump_traceback(file=f, all_threads=True)
        f.seek(0)
        dump = f.read()
    header = "\n".join(f"thread 0x{ident:x}: {name}"
                       for ident, name in names.items() if ident)
    return header + "\n\n" + dump


class _Handler(BaseHTTPRequestHandler):
    basics = None  # class attr, set by maybe_start

    def log_message(self, *args):  # silence per-request stderr lines
        pass

    def _reply(self, code, body, ctype="application/json"):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        # The bound port on every response: with HOROVOD_DEBUG_PORT=0
        # (ephemeral bind, large co-located worlds) this is how an
        # operator who found ONE endpoint learns the authoritative
        # port to record for this rank.
        self.send_header("X-Hvdtpu-Debug-Port",
                         str(self.server.server_address[1]))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        url = urlparse(self.path)
        try:
            if url.path in ("/healthz", "/health"):
                self._reply(200, json.dumps(_healthz(self.basics)))
            elif url.path == "/metrics":
                from horovod_tpu.telemetry.exporters import _flatten_prom

                snap = self.basics.metrics_snapshot()
                self._reply(200,
                            _flatten_prom(snap, snap.get("rank", -1)),
                            ctype="text/plain; version=0.0.4")
            elif url.path == "/events":
                n = int(parse_qs(url.query).get("n", ["256"])[0])
                self._reply(200, json.dumps(self.basics.events(n)))
            elif url.path == "/requests":
                # In-flight serving requests with current phase + age
                # (docs/serving.md "Request lifecycle & tracing"): the
                # live side of the reqtrace span ledger — answers on
                # any rank, empty list when nothing is being served.
                from horovod_tpu.telemetry import reqtrace

                n = int(parse_qs(url.query).get("n", ["64"])[0])
                self._reply(200, json.dumps(reqtrace.live_requests(n)))
            elif url.path == "/stacks":
                self._reply(200, _stacks(), ctype="text/plain")
            elif url.path == "/fleet":
                # Live fleet aggregation (docs/fleet.md): polls every
                # rank's debug server into one per-rank utilization /
                # SLO view. Answered from whichever rank the operator
                # asked (the observatory is lazy per process); the
                # server is threaded, so polling our own /healthz and
                # /events from inside this handler cannot deadlock.
                from horovod_tpu.telemetry import fleet

                obs = fleet.maybe_observatory(self.basics)
                self._reply(200, json.dumps(obs.fleet_json()))
            else:
                self._reply(404, json.dumps({
                    "error": f"unknown path {url.path}",
                    "endpoints": ["/healthz", "/metrics", "/events",
                                  "/requests", "/stacks", "/fleet"]}))
        except Exception as e:  # noqa: BLE001 — a broken endpoint must
            # not kill the server thread (introspection of a sick
            # process is exactly when internals throw)
            try:
                self._reply(500, json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}))
            except Exception:  # noqa: BLE001 — client went away
                pass


def start(basics, port, host="127.0.0.1"):
    """Start the debug server on `port` (exact — callers resolve the
    per-rank offset). Returns the bound port. Idempotent per process.

    Binds loopback by default: the endpoints expose thread stacks and
    runtime internals with no auth, so reaching them from off-host is
    an explicit opt-in (``HOROVOD_DEBUG_HOST=0.0.0.0`` — e.g. for a
    k8s liveness probe against the pod IP)."""
    global _server, _thread, _start_time
    with _lock:
        if _server is not None:
            return _server.server_address[1]
        handler = type("BoundHandler", (_Handler,), {"basics": basics})
        _server = ThreadingHTTPServer((host, port), handler)
        _server.daemon_threads = True
        _start_time = time.monotonic()
        _thread = threading.Thread(target=_server.serve_forever,
                                   name="hvdtpu-debug-server",
                                   daemon=True)
        _thread.start()
        return _server.server_address[1]


def debug_port():
    """The port this process's debug server is bound to, or ``None``
    when it is not running. THE way to discover the endpoint under
    ``HOROVOD_DEBUG_PORT=0`` (ephemeral bind); also echoed on every
    response as the ``X-Hvdtpu-Debug-Port`` header and in
    ``/healthz``."""
    with _lock:
        return _server.server_address[1] if _server is not None else None


def maybe_start(basics):
    """Start iff ``HOROVOD_DEBUG_PORT`` is set: rank r binds port+r
    (rank from the live core when initialized, else HOROVOD_RANK).

    ``HOROVOD_DEBUG_PORT=0`` binds an EPHEMERAL port instead: base+rank
    collides when many simulated or co-located ranks share one host
    (two processes with the same HOROVOD_RANK, or more ranks than the
    port range) — with 0 every rank gets its own kernel-assigned port,
    discoverable via ``hvd.debug_port()`` / the ``X-Hvdtpu-Debug-Port``
    header. Returns the bound port or ``None``; negative disables."""
    base = os.environ.get("HOROVOD_DEBUG_PORT")
    if not base:
        return None
    base = int(base)
    if base < 0:
        return None
    if base == 0:
        return start(basics, 0,
                     host=os.environ.get("HOROVOD_DEBUG_HOST",
                                         "127.0.0.1"))
    rank = 0
    try:
        if basics.lib.hvdtpu_is_initialized():
            rank = max(basics.lib.hvdtpu_rank(), 0)
        else:
            rank = int(os.environ.get("HOROVOD_RANK", "0"))
    except Exception:  # noqa: BLE001
        pass
    host = os.environ.get("HOROVOD_DEBUG_HOST", "127.0.0.1")
    return start(basics, base + rank, host=host)


def stop():
    """Shut the server down (called from hvd.shutdown; safe if never
    started)."""
    global _server, _thread
    with _lock:
        if _server is None:
            return
        _server.shutdown()
        _server.server_close()
        _server = None
        _thread = None
