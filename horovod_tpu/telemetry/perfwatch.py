"""Perf-regression sentinel over telemetry series.

``python -m horovod_tpu.telemetry.perfwatch`` consumes either a
:class:`~horovod_tpu.telemetry.exporters.MetricsScraper` JSONL flight
recorder or bench JSON rows (``bench.py`` output / the committed
``BENCH_r0*.json`` trajectory) and answers ONE question with an exit
code CI can gate on: did step time, bus bandwidth, or overlap
efficiency regress?

Two detectors, both deliberately simple enough to reason about:

- **EWMA baseline breach** (:func:`detect`): the baseline tracks the
  series with a slow EWMA that is FROZEN while a point breaches — the
  regression must not teach the baseline that slow is normal. A breach
  only counts after ``consecutive`` points in a row exceed the relative
  threshold in the bad direction, so a one-sample GC pause or a ±5%
  noise floor stays quiet (tests/single/test_perfwatch.py pins both).
- **Changepoint localization** (:func:`changepoint`): the two-segment
  split minimizing summed squared error — *where* the regime shifted,
  reported as the first row index of the new regime (the commit-range
  bisector's input).

``--budget`` turns the report into a gate: nonzero exit on any flagged
regression — the CI lane and the autoscaler's instability gate consume
it. ``bench.py --diff old.json new.json`` is the two-point companion
(explicit per-row deltas between two bench row files).

Rows carry a ``schema`` version (stamped by ``bench.py``'s ``emit``);
mixed schema versions in one input are refused loudly instead of
mis-compared (exit 2).
"""

import argparse
import json
import sys

# Fields that IDENTIFY a row (the join/grouping key) rather than
# measure it — shared with bench.py's --diff so the two tools can never
# disagree about what distinguishes rows of one metric family.
ROW_IDENTITY_FIELDS = ("metric", "config", "name", "schedule", "bench",
                       "ranks", "bytes", "payload_bytes", "bucket_bytes",
                       "V", "accum", "dtype", "op",
                       # Multi-channel wire rows (ring_busbw striped
                       # lanes): the stripe width identifies a series —
                       # a K=1 and a K=4 row must never cross-join into
                       # one EWMA baseline.
                       "channels",
                       # Serving rows (serving_latency): the offered
                       # load and KV block geometry identify a series —
                       # interleaving different traces or block sizes
                       # into one EWMA baseline would flag every config
                       # transition as a regression.
                       "arrival_rps", "block_size")

# Watched series and their bad direction: step time up = slower,
# busbw/efficiency/MFU down = slower. Matched against the REAL bench
# row fields (`step_s`/`sec_per_step` on the loopback lanes,
# `busbw_gbps` inside flattened `points`, `value` on the MFU headline
# rows) AND the derived scraper series below.
DEFAULT_WATCH = {
    "mean_step_s": "up",
    "step_s": "up",
    "sec_per_step": "up",
    "step_time_ms": "up",
    "ms_per_step": "up",
    "busbw_gbps": "down",
    # Transport-time bus bandwidth of the same rows (the striping
    # acceptance number — busbw minus the fixed API-path overhead).
    "wire_gbps": "down",
    "overlap_efficiency": "down",
    "mfu": "down",
    # Serving rows (bench.py --serving / serving_latency family):
    # request latency percentiles regress UP, sustained decode
    # throughput regresses DOWN — watched from day one so the CI gate
    # covers the serving lane the moment it emits rows.
    "p50_ms": "up",
    "p99_ms": "up",
    "sustained_tok_s": "down",
    "tok_s": "down",
    # Instrumentation-cost rows (events_overhead, the r19
    # serving_trace_overhead lane): the flight recorder / request
    # tracing getting more expensive IS a perf regression.
    "overhead_pct": "up",
    # Fleet rank-seconds rows (bench.py --fleet-util, docs/fleet.md):
    # utilization falling, the unattributed share growing, breaches
    # appearing, or the aggregation itself slowing down at fleet scale
    # are each regressions in their own right.
    "utilization": "down",
    "unattributed_share": "up",
    "breaches": "up",
    "analyze_s": "up",
}


def field_direction(metric, field):
    """Bad direction for one (metric, field), or None = unwatched. The
    generic bench headline `value` is watchable only when the metric
    name says what it measures (MFU/busbw: down = regression)."""
    if field == "value":
        m = (metric or "").lower()
        return "down" if ("mfu" in m or "busbw" in m) else None
    return DEFAULT_WATCH.get(field)


def flatten_rows(rows):
    """Expand rows whose measurements live in a nested ``points`` list
    (the ring_busbw/hier_busbw shape) into one pseudo-row per point,
    carrying the parent's identity fields — so per-size busbw series
    are watchable and diffable like top-level fields."""
    out = []
    for row in rows:
        points = row.get("points")
        if not isinstance(points, list):
            out.append(row)
            continue
        ident = {f: row[f] for f in ROW_IDENTITY_FIELDS if f in row}
        ident["schema"] = row.get("schema", 0)
        for point in points:
            if isinstance(point, dict):
                out.append({**ident, **point})
    return out


def detect(series, direction="up", rel_threshold=0.25, alpha=0.2,
           consecutive=2, warmup=3):
    """EWMA-baseline breach detection over one series.

    Returns ``{"regressed", "index", "ratio", "baseline"}``: ``index``
    is the FIRST point of the flagged breach streak, ``ratio`` the
    worst point/baseline ratio seen, ``baseline`` the frozen baseline
    at flag time. The baseline absorbs only non-breaching points —
    otherwise a slow drift into the regression would mask it — and the
    first ``warmup`` points only feed the baseline (a cold EWMA flags
    its own second sample).
    """
    m = None
    streak_start = None
    streak = 0
    worst = 1.0
    flagged = None
    for i, x in enumerate(series):
        if m is None:
            m = x
            continue
        ratio = (x / m) if m else 1.0
        breach = (i >= warmup and m > 0
                  and (ratio > 1 + rel_threshold if direction == "up"
                       else ratio < 1 - rel_threshold))
        if breach:
            if streak == 0:
                streak_start = i
            streak += 1
            if direction == "up":
                worst = max(worst, ratio)
            else:
                worst = min(worst, ratio)
            if streak >= consecutive and flagged is None:
                flagged = streak_start
        else:
            streak = 0
            # A transient streak that never flagged must not leave its
            # magnitude behind: `ratio` reports the flagged regression,
            # not an unrelated earlier outlier.
            if flagged is None:
                worst = 1.0
            m = (1 - alpha) * m + alpha * x
    return {
        "regressed": flagged is not None,
        "index": flagged,
        "ratio": round(worst, 4),
        "baseline": round(m, 6) if m is not None else None,
    }


def changepoint(series):
    """Two-segment least-squares changepoint: the split index i (first
    point of the new regime) minimizing SSE(x[:i]) + SSE(x[i:]), plus
    the mean shift ratio across it. ``(None, 1.0)`` below 4 points."""
    n = len(series)
    if n < 4:
        return None, 1.0

    def sse(xs):
        if not xs:
            return 0.0
        mu = sum(xs) / len(xs)
        return sum((x - mu) ** 2 for x in xs)

    best_i, best_cost = None, None
    for i in range(1, n):
        cost = sse(series[:i]) + sse(series[i:])
        if best_cost is None or cost < best_cost:
            best_i, best_cost = i, cost
    before = sum(series[:best_i]) / best_i
    after = sum(series[best_i:]) / (n - best_i)
    shift = (after / before) if before else 1.0
    return best_i, round(shift, 4)


# ---- input readers ----------------------------------------------------


def load_rows(path):
    """Rows from a bench/scrape file: JSONL (one object per line, the
    bench and scraper formats), a JSON array, or a driver artifact
    whose ``tail`` string embeds JSON rows between log lines (the
    committed ``BENCH_r0*.json`` shape)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, list):
            return doc
        if isinstance(doc, dict) and isinstance(doc.get("tail"), str):
            return _rows_from_lines(doc["tail"].splitlines())
        if isinstance(doc, dict):
            return [doc]
    except json.JSONDecodeError:
        pass
    return _rows_from_lines(text.splitlines())


def _rows_from_lines(lines):
    rows = []
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows


def check_schema(rows, what="rows"):
    """One ``schema`` version per input, or refuse loudly: silently
    comparing rows whose field meanings moved between formats is how a
    regression hides inside a renamed column. Absent stamps (pre-schema
    rows) count as version 0 and stay comparable with each other."""
    versions = {int(r.get("schema", 0)) for r in rows}
    if len(versions) > 1:
        raise SystemExit(
            f"perfwatch: refusing to compare {what} with MIXED schema "
            f"versions {sorted(versions)} — re-emit with one bench/"
            "scraper generation (rows are stamped by bench.py emit())")
    return versions.pop() if versions else 0


def bench_series(rows):
    """``{(identity, field): [values...]}`` for every watched numeric
    field, in row order. Rows are grouped by their FULL identity
    (:data:`ROW_IDENTITY_FIELDS`), not just the metric name — one
    metric family emits one row per config/size (zero_sweep's
    replicated vs zero1, ring_busbw's per-payload points), and
    interleaving those regimes into one series would make the EWMA
    baseline oscillate and flag every config transition."""
    series = {}
    for row in flatten_rows(rows):
        ident = "/".join(str(row[f]) for f in ROW_IDENTITY_FIELDS
                         if f in row and row[f] is not None)
        for field, v in row.items():
            if field_direction(row.get("metric"), field) is None:
                continue
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                series.setdefault((ident or "?", field),
                                  []).append(float(v))
    return series


def scraper_series(rows):
    """Derived interval series from MetricsScraper JSONL snapshots:

    - ``busbw_gbps``: wire tx rate between scrapes;
    - ``overlap_efficiency``: Δhidden / Δtotal of the overlap ledger
      (per-interval, so a late-run regression is not averaged away by
      the cumulative quotient);
    - ``step_time_ms``: Δwall / Δledger-steps while steps advance.
    """
    out = {("scrape", "busbw_gbps"): [],
           ("scrape", "overlap_efficiency"): [],
           ("scrape", "step_time_ms"): []}
    prev = None
    for row in rows:
        wire = row.get("wire", {})
        ov = wire.get("overlap", {})
        cur = {
            "ts": row.get("ts", 0.0),
            "tx": wire.get("tx_bytes", 0),
            "hidden": (ov.get("intra", {}).get("hidden_us", 0)
                       + ov.get("cross", {}).get("hidden_us", 0)),
            "total": (ov.get("intra", {}).get("total_us", 0)
                      + ov.get("cross", {}).get("total_us", 0)),
            "steps": ov.get("steps", 0),
        }
        if prev is not None:
            dt = cur["ts"] - prev["ts"]
            if dt > 0:
                out[("scrape", "busbw_gbps")].append(
                    (cur["tx"] - prev["tx"]) / dt / 1e9)
            dtot = cur["total"] - prev["total"]
            if dtot > 0:
                out[("scrape", "overlap_efficiency")].append(
                    (cur["hidden"] - prev["hidden"]) / dtot)
            dsteps = cur["steps"] - prev["steps"]
            if dsteps > 0 and dt > 0:
                out[("scrape", "step_time_ms")].append(
                    dt * 1000.0 / dsteps)
        prev = cur
    return {k: v for k, v in out.items() if v}


def watch(series_map, rel_threshold=0.25, consecutive=2, min_points=4):
    """Run both detectors over every watched series; returns a list of
    verdict dicts (one per series with enough points)."""
    verdicts = []
    for (metric, field), series in sorted(series_map.items()):
        if len(series) < min_points:
            continue
        direction = field_direction(metric, field) or "up"
        d = detect(series, direction=direction,
                   rel_threshold=rel_threshold, consecutive=consecutive)
        cp_index, cp_shift = changepoint(series)
        verdicts.append({
            "metric": metric, "field": field, "points": len(series),
            "direction": direction, **d,
            "changepoint_index": cp_index,
            "changepoint_shift": cp_shift,
        })
    return verdicts


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.telemetry.perfwatch",
        description="EWMA-baseline + changepoint perf-regression "
                    "sentinel over scraper JSONL or bench JSON rows")
    ap.add_argument("--jsonl", default=None,
                    help="MetricsScraper JSONL flight recorder")
    ap.add_argument("--bench", nargs="*", default=None,
                    help="bench row files (JSONL / JSON array / "
                         "BENCH_r0*.json driver artifacts), "
                         "concatenated in order")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="relative breach threshold (default 0.25)")
    ap.add_argument("--consecutive", type=int, default=2,
                    help="breaches in a row before flagging")
    ap.add_argument("--budget", action="store_true",
                    help="gate mode: exit 1 on any flagged regression")
    ap.add_argument("--json", action="store_true",
                    help="emit verdicts as JSON rows")
    args = ap.parse_args(argv)

    if not args.jsonl and not args.bench:
        ap.error("need --jsonl and/or --bench input")
    series_map = {}
    if args.jsonl:
        rows = load_rows(args.jsonl)
        check_schema(rows, what=args.jsonl)
        series_map.update(scraper_series(rows))
    if args.bench:
        rows = []
        for path in args.bench:
            rows.extend(load_rows(path))
        check_schema(rows, what="bench rows")
        series_map.update(bench_series(rows))

    verdicts = watch(series_map, rel_threshold=args.threshold,
                     consecutive=args.consecutive)
    regressed = [v for v in verdicts if v["regressed"]]
    for v in verdicts:
        if args.json:
            print(json.dumps(v))
        else:
            flag = "REGRESSED" if v["regressed"] else "ok"
            where = (f" at row {v['index']} (changepoint "
                     f"{v['changepoint_index']}, shift "
                     f"{v['changepoint_shift']}x)"
                     if v["regressed"] else "")
            print(f"{v['metric']}.{v['field']}: {flag} "
                  f"[{v['points']} pts, worst {v['ratio']}x "
                  f"baseline]{where}")
    if not verdicts:
        print("perfwatch: no watchable series found "
              f"({len(series_map)} candidates below min points)")
        if args.budget:
            # A gate with nothing to gate on must FAIL, not pass: a
            # renamed field or a wrong path would otherwise ship a 2x
            # regression under a green check (the same fail-loud rule
            # as the schema guard). Distinct code so CI can tell
            # "misconfigured input" from "regression found".
            print("perfwatch: --budget with zero watchable series — "
                  "failing the gate (wrong path or renamed fields?)",
                  file=sys.stderr)
            return 2
    if args.budget and regressed:
        print(f"perfwatch: {len(regressed)} regression(s) over budget",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
