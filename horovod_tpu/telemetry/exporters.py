"""Background metrics scraper with pluggable exporters.

:class:`MetricsScraper` snapshots the core registry on an interval and
fans each snapshot out to any of three sinks:

- **JSONL flight recorder** — one line per scrape (timestamped, rank-
  tagged), size-capped by rotating to ``<path>.1`` — the post-mortem
  artifact: when a run dies, the tail holds the last known counters.
- **Prometheus textfile** — ``hvdtpu_*`` samples written atomically
  (tmp + rename) for the node-exporter textfile collector.
- **Console table** — a compact operator view on stderr.

All sinks also work one-shot via :meth:`MetricsScraper.scrape_once`.
"""

import json
import os
import sys
import threading
import time

from horovod_tpu.telemetry import core as _core


def _flatten_prom(snap, rank):
    """Flatten a snapshot into Prometheus text-format lines."""
    lines = [
        "# HELP hvdtpu_op_bytes_total payload bytes moved per op class",
        "# TYPE hvdtpu_op_bytes_total counter",
    ]
    label = f'rank="{rank}"'
    for plane_key, plane in (("host", "ops"), ("device", "device_ops")):
        for op, c in snap.get(plane, {}).items():
            for field in ("responses", "tensors", "bytes"):
                lines.append(
                    f'hvdtpu_op_{field}_total{{op="{op}",'
                    f'plane="{plane_key}",{label}}} {c.get(field, 0)}')
    for hist in ("negotiation_us", "queue_us", "wire_us"):
        h = snap.get(hist, {})
        for field in ("count", "sum_us", "p50_us", "p99_us", "max_us"):
            lines.append(
                f'hvdtpu_{hist}_{field}{{{label}}} {h.get(field, 0)}')
    cache = snap.get("cache", {})
    for field in ("hits", "misses", "entries", "hit_bytes"):
        lines.append(f'hvdtpu_cache_{field}{{{label}}} '
                     f'{cache.get(field, 0)}')
    lines.append(f'hvdtpu_cache_hit_rate{{{label}}} '
                 f'{cache.get("hit_rate", 0.0)}')
    cyc = snap.get("cycle", {})
    for field in ("count", "stalls", "overrun_us"):
        lines.append(f'hvdtpu_cycle_{field}{{{label}}} '
                     f'{cyc.get(field, 0)}')
    fus = snap.get("fusion", {})
    for field in ("fused_responses", "fill_bytes", "capacity_bytes"):
        lines.append(f'hvdtpu_fusion_{field}{{{label}}} '
                     f'{fus.get(field, 0)}')
    lines.append(f'hvdtpu_fusion_fill_ratio{{{label}}} '
                 f'{fus.get("fill_ratio", 0.0)}')
    wire = snap.get("wire", {})
    for field in ("tx_bytes", "rx_bytes", "tx_logical_bytes",
                  "rx_logical_bytes", "cross_tx_bytes", "cross_rx_bytes",
                  "cross_tx_logical_bytes", "cross_rx_logical_bytes"):
        lines.append(f'hvdtpu_wire_{field}_total{{{label}}} '
                     f'{wire.get(field, 0)}')
    for field in ("compression_ratio", "cross_compression_ratio"):
        lines.append(f'hvdtpu_wire_{field}{{{label}}} '
                     f'{wire.get(field, 1.0)}')
    # Per-stripe-channel wire counters (HOROVOD_WIRE_CHANNELS,
    # docs/wire.md): the buckets sum exactly to tx/rx_bytes, so a
    # dead or slow stripe alerts as imbalance instead of averaging
    # away under the totals.
    for chan in wire.get("channels", []):
        clabel = f'channel="{chan.get("channel", 0)}",{label}'
        lines.append(f'hvdtpu_wire_channel_tx_bytes_total{{{clabel}}} '
                     f'{chan.get("tx_bytes", 0)}')
        lines.append(f'hvdtpu_wire_channel_rx_bytes_total{{{clabel}}} '
                     f'{chan.get("rx_bytes", 0)}')
    # Syscall accounting (docs/wire.md "Syscall budget"): send/recv
    # INVOCATIONS per plane/channel plus calls-per-GB — the io_uring
    # baseline (ROADMAP item 3). One increment per call issued, EAGAIN
    # spins included, so a stall that burns syscalls without moving
    # payload shows up here first.
    sc = wire.get("syscalls", {})
    for field, direction in (("tx_calls", "tx"), ("rx_calls", "rx")):
        lines.append(f'hvdtpu_wire_syscalls_total{{direction='
                     f'"{direction}",{label}}} {sc.get(field, 0)}')
        lines.append(f'hvdtpu_wire_cross_syscalls_total{{direction='
                     f'"{direction}",{label}}} '
                     f'{sc.get("cross_" + field, 0)}')
    lines.append(f'hvdtpu_wire_syscalls_per_gb{{{label}}} '
                 f'{sc.get("per_gb", 0.0)}')
    for chan in sc.get("channels", []):
        clabel = f'channel="{chan.get("channel", 0)}",{label}'
        for field, direction in (("tx_calls", "tx"), ("rx_calls", "rx")):
            lines.append(f'hvdtpu_wire_channel_syscalls_total{{'
                         f'direction="{direction}",{clabel}}} '
                         f'{chan.get(field, 0)}')
    # Step-anatomy overlap ledger (docs/metrics.md): exposed vs hidden
    # wire time per plane — the overlap-efficiency trend perfwatch and
    # the fusion-work acceptance criterion watch.
    ov = wire.get("overlap", {})
    lines.append(f'hvdtpu_overlap_steps_total{{{label}}} '
                 f'{ov.get("steps", 0)}')
    lines.append(f'hvdtpu_overlap_unattributed_us_total{{{label}}} '
                 f'{ov.get("unattributed_us", 0)}')
    lines.append(f'hvdtpu_overlap_efficiency{{{label}}} '
                 f'{ov.get("overlap_efficiency", 0.0)}')
    for plane in ("intra", "cross"):
        p = ov.get(plane, {})
        for field in ("exposed_us", "hidden_us", "total_us"):
            lines.append(
                f'hvdtpu_overlap_{field}_total{{plane="{plane}",'
                f'{label}}} {p.get(field, 0)}')
        lines.append(
            f'hvdtpu_overlap_plane_efficiency{{plane="{plane}",'
            f'{label}}} {p.get("overlap_efficiency", 0.0)}')
    # Elastic fault lifecycle (docs/elastic.md): the counters an
    # alerting rule watches — faults/heals/retries/CRC errors moving is
    # the flaky-host signal, epoch divergence the split-brain one.
    el = snap.get("elastic", {})
    for field in ("faults_detected", "faults_recovered",
                  "ranks_blacklisted", "ranks_rejoined", "heals",
                  "retries", "crc_errors"):
        lines.append(f'hvdtpu_elastic_{field}_total{{{label}}} '
                     f'{el.get(field, 0)}')
    lines.append(f'hvdtpu_elastic_epoch{{{label}}} '
                 f'{el.get("epoch", 0)}')
    det = el.get("detect_us", {})
    for field in ("count", "p50_us", "p99_us", "max_us"):
        lines.append(f'hvdtpu_elastic_detect_{field}{{{label}}} '
                     f'{det.get(field, 0)}')
    # Serving-lane gauges (docs/serving.md): queue/pool pressure,
    # rolling request-latency percentiles, and eviction amplification
    # (recomputed prefill tokens / useful tokens — KV-pool thrash).
    # Sourced from the live service's signal set, sentinel defaults
    # when no service runs in this process — the field set can never
    # differ between a serving and a training scrape.
    try:
        from horovod_tpu.telemetry.autoscale import read_serving_signals

        serving = read_serving_signals()
    except Exception:  # noqa: BLE001 — the scrape must come back
        serving = {}
    for field, v in sorted(serving.items()):
        lines.append(f'hvdtpu_serving_{field}{{{label}}} {v}')
    for r, n in enumerate(
            snap.get("straggler", {}).get("last_rank_counts", [])):
        lines.append(
            f'hvdtpu_straggler_last_total{{{label},'
            f'straggler="{r}"}} {n}')
    lines.append(f'hvdtpu_errors_total{{{label}}} '
                 f'{snap.get("errors", 0)}')
    return "\n".join(lines) + "\n"


def _console_table(snap, stream):
    ops = snap.get("ops", {})
    dev = snap.get("device_ops", {})
    cache = snap.get("cache", {})
    cyc = snap.get("cycle", {})
    q = snap.get("queue_us", {})
    print(f"-- hvdtpu metrics (rank {snap.get('rank')}/"
          f"{snap.get('size')}) --", file=stream)
    print(f"{'op':<14}{'plane':<8}{'responses':>10}{'tensors':>10}"
          f"{'bytes':>14}", file=stream)
    for plane_name, plane in (("host", ops), ("device", dev)):
        for op, c in plane.items():
            print(f"{op:<14}{plane_name:<8}{c['responses']:>10}"
                  f"{c['tensors']:>10}{c['bytes']:>14}", file=stream)
    print(f"queue p50/p99: {q.get('p50_us', 0)}/{q.get('p99_us', 0)} us"
          f"  cache hit rate: {cache.get('hit_rate', 0.0):.3f}"
          f"  cycles: {cyc.get('count', 0)}"
          f" (stalls {cyc.get('stalls', 0)})", file=stream)


class MetricsScraper:
    """Periodic snapshot -> exporters, on a daemon thread.

    ``jsonl_path`` / ``prom_path`` / ``console`` pick the sinks (any
    subset). ``start()`` launches the loop; ``stop()`` flushes one last
    scrape so short runs still leave a record.
    """

    def __init__(self, interval_s=10.0, jsonl_path=None, prom_path=None,
                 console=False, console_stream=None,
                 jsonl_max_bytes=16 << 20):
        self.interval_s = float(interval_s)
        self.jsonl_path = jsonl_path
        self.prom_path = prom_path
        self.console = console
        self.console_stream = console_stream or sys.stderr
        self.jsonl_max_bytes = jsonl_max_bytes
        self._stop = threading.Event()
        self._thread = None
        self.scrapes = 0

    def scrape_once(self):
        snap = _core.snapshot()
        rank = snap.get("rank", -1)
        row = {"ts": time.time(), **snap}
        # Serving signal set on every scrape row (defaults when no
        # service is live): the JSONL flight recorder is the offline
        # twin of /healthz, and a post-mortem of a serving incident
        # needs the latency/amplification trail next to the wire
        # counters (docs/serving.md).
        try:
            from horovod_tpu.telemetry.autoscale import (
                read_serving_signals,
            )

            row["serving"] = read_serving_signals()
        except Exception:  # noqa: BLE001 — the scrape must come back
            pass
        if self.jsonl_path:
            self._write_jsonl(row)
        if self.prom_path:
            self._write_prom(snap, rank)
        if self.console:
            _console_table(snap, self.console_stream)
        self.scrapes += 1
        return row

    def _write_jsonl(self, row):
        path = self.jsonl_path
        try:
            if (os.path.exists(path)
                    and os.path.getsize(path) > self.jsonl_max_bytes):
                os.replace(path, path + ".1")  # keep one generation
        except OSError:
            pass
        with open(path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def _write_prom(self, snap, rank):
        tmp = self.prom_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(_flatten_prom(snap, rank))
        os.replace(tmp, self.prom_path)  # textfile collector needs atomic

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception as e:  # noqa: BLE001 — the scraper must
                # never take the training process down with it
                print(f"hvdtpu metrics scraper error: {e}",
                      file=sys.stderr)

    def start(self):
        if self._thread is not None:
            raise RuntimeError("scraper already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvdtpu-metrics-scraper")
        self._thread.start()
        return self

    def stop(self, final_scrape=True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 5)
            self._thread = None
        if final_scrape:
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
