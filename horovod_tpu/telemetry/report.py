"""Cross-rank timeline merge with straggler attribution.

Each rank records its own Chrome-trace timeline
(``hvd.start_timeline(f"/tmp/tl.{rank}.json")``); this module merges
them into ONE Perfetto-loadable trace and attributes negotiation
stragglers::

    python -m horovod_tpu.telemetry.report /tmp/tl.*.json \
        -o merged.json --skew-json skew.json

Clock alignment: per-rank timestamps are steady-clock-relative to each
rank's own start. The ``CLOCK_SYNC`` header event (``csrc/timeline.cc``)
carries each trace's t=0 as wall-clock unix microseconds, which puts
all ranks on one axis up to NTP skew; without it (older traces) the
fallback aligns on ``NEGOTIATE`` end events — the coordinator's
response broadcast reaches every rank near-simultaneously, so the
median per-rank offset over matched events is a robust clock estimate.

Straggler attribution: a tensor's ``NEGOTIATE`` begin marks the moment
that rank submitted the request. After alignment, the last begin among
ranks for each (tensor, occurrence) is the rank the collective waited
for; aggregated, that is the per-rank skew table (the live counterpart
is the coordinator's ``straggler`` section in ``hvd.metrics()``).
"""

import argparse
import json
import statistics
import sys
from collections import defaultdict

# --post-mortem switches to the streaming k-way merge above this many
# dumps (large simulated/real worlds; docs/scale.md).
_STREAM_THRESHOLD = 16


def load_timeline(path):
    """Load one rank's timeline; returns (rank, events). Tolerates the
    writer's trailing ``{}`` sentinel and in-progress traces (truncated
    final line)."""
    try:
        with open(path) as f:
            events = json.load(f)
    except json.JSONDecodeError:
        # Trace still being written (no closing "]"): recover line-wise.
        events = []
        with open(path) as f:
            for line in f:
                line = line.strip().rstrip(",")
                if not line or line in ("[", "]"):
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if ev:
                    events.append(ev)
    events = [e for e in events if e]  # drop the {} sentinel
    rank = None
    for e in events:
        if e.get("name") == "CLOCK_SYNC":
            rank = e.get("args", {}).get("rank")
            break
    if rank is None:
        ranks = {e.get("pid") for e in events if "pid" in e}
        rank = min(ranks) if ranks else 0
    return rank, events


def _clock_sync_us(events):
    for e in events:
        if e.get("name") == "CLOCK_SYNC":
            return e.get("args", {}).get("unix_us")
    return None


def _negotiate_occurrences(events, phase):
    """{(tensor, k): ts} for the k-th NEGOTIATE begin/end per tensor."""
    per_tensor = defaultdict(list)
    for e in events:
        if e.get("name") == "NEGOTIATE" and e.get("ph") == phase:
            tensor = e.get("args", {}).get("tensor")
            if tensor is not None:
                per_tensor[tensor].append(e["ts"])
    out = {}
    for tensor, times in per_tensor.items():
        for k, ts in enumerate(sorted(times)):
            out[(tensor, k)] = ts
    return out


def compute_offsets(traces):
    """Per-rank offsets (added to each rank's ts) onto a common axis.

    Returns ``{rank: offset_us}`` with the earliest-starting rank at
    its original coordinates. Prefers CLOCK_SYNC; falls back to the
    NEGOTIATE-end median match.
    """
    syncs = {rank: _clock_sync_us(events) for rank, events in traces}
    if all(s is not None for s in syncs.values()) and syncs:
        base = min(syncs.values())
        return {rank: s - base for rank, s in syncs.items()}

    ranks = [rank for rank, _ in traces]
    ref_rank = ranks[0]
    ref_ends = _negotiate_occurrences(dict(traces)[ref_rank], "E")
    offsets = {ref_rank: 0}
    for rank, events in traces:
        if rank == ref_rank:
            continue
        ends = _negotiate_occurrences(events, "E")
        deltas = [ref_ends[key] - ts for key, ts in ends.items()
                  if key in ref_ends]
        offsets[rank] = int(statistics.median(deltas)) if deltas else 0
    base = min(offsets.values(), default=0)
    return {rank: off - base for rank, off in offsets.items()}


def straggler_table(traces, offsets, top_n=10):
    """Per-rank skew aggregation over aligned NEGOTIATE begins.

    Returns ``{"per_rank": {rank: {last_count, mean_skew_us,
    max_skew_us, events}}, "worst_tensors": [...]}`` where a rank's
    skew on one collective is its submit time minus the earliest
    rank's.
    """
    begins = {rank: _negotiate_occurrences(events, "B")
              for rank, events in traces}
    keys = None
    for rank, occ in begins.items():
        keys = set(occ) if keys is None else keys & set(occ)
    keys = keys or set()

    per_rank = {rank: {"last_count": 0, "skews": []} for rank in begins}
    spreads = []
    for key in keys:
        arrivals = {rank: begins[rank][key] + offsets[rank]
                    for rank in begins}
        first = min(arrivals.values())
        last_rank = max(arrivals, key=arrivals.get)
        spread = arrivals[last_rank] - first
        per_rank[last_rank]["last_count"] += 1
        for rank, ts in arrivals.items():
            per_rank[rank]["skews"].append(ts - first)
        spreads.append((spread, key[0], key[1], last_rank))

    table = {}
    for rank, d in sorted(per_rank.items()):
        skews = d["skews"]
        table[rank] = {
            "last_count": d["last_count"],
            "events": len(skews),
            "mean_skew_us": (sum(skews) / len(skews)) if skews else 0.0,
            "max_skew_us": max(skews) if skews else 0,
        }
    spreads.sort(reverse=True)
    worst = [{"tensor": t, "occurrence": k, "spread_us": s,
              "last_rank": r} for s, t, k, r in spreads[:top_n]]
    return {"per_rank": table, "worst_tensors": worst,
            "matched_events": len(keys)}


def merge(paths, align=True, events_paths=None):
    """Merge per-rank timeline files.

    Returns ``(merged_events, skew)``: one Chrome-trace event list
    (per-rank ts shifted onto the common axis, pid = rank, process
    names labeled) and the straggler table. ``events_paths`` optionally
    folds per-rank event-ring dumps (black-box JSONL, see
    :mod:`horovod_tpu.telemetry.postmortem`) in as extra per-rank
    tracks — chunk-level wire activity, heal-ladder steps, and fault
    milestones land on the same axis as the per-op spans, aligned
    through each dump's wall/steady anchor pair against the traces'
    CLOCK_SYNC anchors.
    """
    traces = [load_timeline(p) for p in paths]
    seen = set()
    for rank, _ in traces:
        if rank in seen:
            raise ValueError(f"duplicate rank {rank} across input "
                             "traces — pass one timeline per rank")
        seen.add(rank)
    offsets = compute_offsets(traces) if align else \
        {rank: 0 for rank, _ in traces}
    merged = []
    for rank, events in traces:
        named = False
        for e in events:
            e = dict(e)
            e["pid"] = rank
            if "ts" in e:
                e["ts"] = e["ts"] + offsets[rank]
            if e.get("name") == "process_name":
                named = True
            merged.append(e)
        if not named:
            merged.append({"name": "process_name", "ph": "M",
                           "pid": rank,
                           "args": {"name": f"rank {rank}"}})
    if events_paths:
        from horovod_tpu.telemetry import postmortem

        # The merged axis puts rank r's trace event at
        # (wall - sync_r) + offsets[r], so the wall base that lands
        # rank r's ring events on ITS OWN trace rows is
        # sync_r - offsets[r]. Under full CLOCK_SYNC alignment that is
        # the same value for every rank (min(sync)); under --no-align
        # or the NEGOTIATE-median fallback the bases differ per rank,
        # and a single global anchor would shear the event tracks off
        # the op spans they annotate.
        syncs = {rank: _clock_sync_us(events) for rank, events in traces}
        bases = {rank: s - offsets[rank]
                 for rank, s in syncs.items() if s is not None}
        fallback = min(bases.values()) if bases else None
        for path in postmortem.collect_paths(events_paths):
            # A process appends one dump per fault and each dump is the
            # ring tail at that moment — successive dumps overlap, so
            # fold each event ONCE (seq is per-process monotonic):
            # rendering every dump verbatim would duplicate the shared
            # window at identical timestamps, while keeping only the
            # last would drop events that aged out of the ring between
            # faults.
            seen_seqs = set()
            for dump in postmortem.load_blackbox(path):
                hdr = dump["header"]
                fresh = [e for e in dump["events"]
                         if e.get("seq") not in seen_seqs]
                if not fresh:
                    continue
                seen_seqs.update(e.get("seq") for e in fresh)
                base = bases.get(hdr.get("rank"), fallback)
                if base is None:  # no anchored trace anywhere: events-
                    base = hdr["unix_us"]  # only, relative to dump time
                merged.extend(postmortem.events_to_trace_events(
                    {"header": hdr, "events": fresh}, base))
    merged.sort(key=lambda e: e.get("ts", 0))
    skew = straggler_table(traces, offsets)
    return merged, skew


def attach_fault_events(skew, snapshot_paths):
    """Fold per-rank metrics snapshots' ``elastic`` section into the
    straggler table (docs/elastic.md): per rank, faults detected /
    recovered, ranks it saw blacklisted, its membership epoch, and its
    median detection latency. A rank that keeps re-detecting faults (or
    sits at a lower epoch than its peers) is the flaky host the
    straggler table alone cannot name — skew attributes slowness,
    fault events attribute churn.
    """
    per_rank = {}
    for path in snapshot_paths:
        with open(path) as f:
            snap = json.load(f)
        el = snap.get("elastic", {})
        per_rank[snap.get("rank", -1)] = {
            "epoch": el.get("epoch", 0),
            "faults_detected": el.get("faults_detected", 0),
            "faults_recovered": el.get("faults_recovered", 0),
            "ranks_blacklisted": el.get("ranks_blacklisted", 0),
            "detect_p50_us": el.get("detect_us", {}).get("p50_us", 0),
        }
    skew["fault_events"] = per_rank
    for rank, d in skew["per_rank"].items():
        if rank in per_rank:
            d["faults_detected"] = per_rank[rank]["faults_detected"]
            d["epoch"] = per_rank[rank]["epoch"]
    return skew


def format_skew_table(skew):
    faults = skew.get("fault_events") or {}
    hdr = (f"{'rank':>5} {'last':>7} {'events':>7} "
           f"{'mean skew us':>13} {'max skew us':>12}")
    if faults:
        hdr += f" {'epoch':>6} {'faults':>7} {'det p50 us':>11}"
    lines = [hdr]
    for rank, d in sorted(skew["per_rank"].items()):
        row = (f"{rank:>5} {d['last_count']:>7} {d['events']:>7} "
               f"{d['mean_skew_us']:>13.1f} {d['max_skew_us']:>12}")
        if faults:
            fe = faults.get(rank, {})
            row += (f" {fe.get('epoch', '-'):>6} "
                    f"{fe.get('faults_detected', '-'):>7} "
                    f"{fe.get('detect_p50_us', '-'):>11}")
        lines.append(row)
    for w in skew["worst_tensors"][:5]:
        lines.append(f"  worst: {w['tensor']}#{w['occurrence']} "
                     f"spread {w['spread_us']} us "
                     f"(last: rank {w['last_rank']})")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.telemetry.report",
        description="Merge per-rank hvdtpu timelines into one "
                    "Perfetto-loadable trace with straggler attribution")
    ap.add_argument("timelines", nargs="+",
                    help="per-rank timeline JSON files (or, with "
                         "--post-mortem, black-box JSONL dumps / the "
                         "dump directory)")
    ap.add_argument("-o", "--output", default="merged_timeline.json",
                    help="merged trace output path")
    ap.add_argument("--skew-json", default=None,
                    help="also write the straggler table as JSON")
    ap.add_argument("--no-align", action="store_true",
                    help="skip clock alignment (trust raw timestamps)")
    ap.add_argument("--snapshots", nargs="*", default=None,
                    help="per-rank hvd.metrics() snapshot JSON files: "
                         "folds elastic fault events (epoch, faults, "
                         "detection latency) into the straggler table")
    ap.add_argument("--events", nargs="*", default=None,
                    help="per-rank event-ring dumps (black-box JSONL): "
                         "rendered as extra Perfetto tracks on the "
                         "merged timeline")
    ap.add_argument("--post-mortem", action="store_true",
                    help="positional args are black-box JSONL dumps "
                         "(or their directory): merge them into one "
                         "causal cross-rank fault timeline naming the "
                         "root-cause rank(s); -o writes the analysis "
                         "as JSON")
    ap.add_argument("--stream", action="store_true",
                    help="with --post-mortem: force the streaming "
                         "merge (bounded memory, timeline tail only). "
                         "Selected automatically above %d dumps."
                         % _STREAM_THRESHOLD)
    ap.add_argument("--requests", action="store_true",
                    help="positional args are per-rank event dumps "
                         "(black-box JSONL or live write_event_dump "
                         "traces, or their directory): stitch each "
                         "request's cross-rank span chain off the "
                         "`request` events and decompose the tail "
                         "latency band by lifecycle phase; -o writes "
                         "the analysis (report + per-rid chains) as "
                         "JSON")
    ap.add_argument("--pct", type=float, default=99.0,
                    help="with --requests: the percentile band to "
                         "attribute (default 99)")
    ap.add_argument("--critical-path", action="store_true",
                    help="positional args are per-rank event dumps "
                         "(black-box JSONL or live write_event_dump "
                         "traces, or their directory): merge the step "
                         "windows across ranks and name, per step, the "
                         "rank and phase (compute/negotiation/wire/"
                         "stall) that bounded it; -o writes the "
                         "analysis as JSON")
    ap.add_argument("--fleet", action="store_true",
                    help="positional args are per-rank event dumps (or "
                         "their directory): decompose every rank's "
                         "wall time into the rank-seconds buckets "
                         "(docs/fleet.md), render the fleet "
                         "utilization table with worst-rank "
                         "attribution, and report SLO breaches — both "
                         "breach events recorded in the dumps and a "
                         "re-evaluation of the ledger signals; -o "
                         "writes the analysis as JSON")
    ap.add_argument("--slo", default=None,
                    help="with --fleet: ';'-separated SLO objectives "
                         "to evaluate instead of the defaults (e.g. "
                         "'stall_ms < 500; serving_p99_ms < 250')")
    args = ap.parse_args(argv)

    if args.requests:
        from horovod_tpu.telemetry import reqtrace

        chains = reqtrace.stitch(args.timelines)
        analysis = reqtrace.tail_report(chains, pct=args.pct)
        print(reqtrace.format_requests(analysis))
        if args.output != "merged_timeline.json":
            with open(args.output, "w") as f:
                json.dump({"report": analysis,
                           "chains": {str(r): c
                                      for r, c in sorted(chains.items())}},
                          f, indent=2)
            print(f"wrote {args.output}")
        return 0

    if args.critical_path:
        from horovod_tpu.telemetry import critpath

        analysis = critpath.critical_path(args.timelines)
        print(critpath.format_critical_path(analysis))
        if args.output != "merged_timeline.json":
            with open(args.output, "w") as f:
                json.dump(analysis, f, indent=2)
            print(f"wrote {args.output}")
        return 0

    if args.fleet:
        from horovod_tpu.telemetry import fleet

        analysis = fleet.analyze(args.timelines,
                                 objectives=args.slo)
        print(fleet.format_fleet(analysis))
        if args.output != "merged_timeline.json":
            with open(args.output, "w") as f:
                json.dump(analysis, f, indent=2)
            print(f"wrote {args.output}")
        return 0

    if args.post_mortem:
        from horovod_tpu.telemetry import postmortem

        paths = postmortem.collect_paths(args.timelines)
        if args.stream or len(paths) > _STREAM_THRESHOLD:
            # Hundreds of dumps: the eager merge's global annotate+sort
            # is quadratic-feeling at fleet scale; the k-way streaming
            # pass returns identical verdicts in seconds (docs/scale.md).
            analysis = postmortem.merge_post_mortem_streaming(paths)
        else:
            analysis = postmortem.merge_post_mortem(paths)
        print(postmortem.format_post_mortem(analysis))
        if args.output != "merged_timeline.json":
            with open(args.output, "w") as f:
                json.dump(analysis, f, indent=2)
            print(f"wrote {args.output}")
        return 0

    merged, skew = merge(args.timelines, align=not args.no_align,
                         events_paths=args.events)
    if args.snapshots:
        attach_fault_events(skew, args.snapshots)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    print(f"wrote {args.output} ({len(merged)} events, "
          f"{len(args.timelines)} ranks)")
    print(format_skew_table(skew))
    if args.skew_json:
        with open(args.skew_json, "w") as f:
            json.dump(skew, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
