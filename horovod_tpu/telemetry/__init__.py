"""horovod_tpu.telemetry — live metrics for the runtime.

The observability layer the trace files never gave the operator
(reference analog: none in-core — upstream Horovod's only windows are
the Chrome timeline and the autotune log). Three pieces:

1. **Core counters** — :func:`snapshot` parses the native core's
   ``hvdtpu_metrics_snapshot()`` JSON (per-op-class counts/bytes,
   negotiation/queue/wire latency histograms, fusion fill, cycle
   stalls, cache hit rate, coordinator straggler table); surfaced to
   frontends as ``hvd.metrics()``. :class:`MetricsScraper` runs a
   background exporter loop (JSONL flight recorder, Prometheus
   textfile, console table).

2. **Step accounting** — :class:`StepTimer` turns per-step wall time
   into MFU (FLOPs from ``lowered.compile().cost_analysis()``), wire
   goodput, and measured-vs-predicted collective bytes, with the
   static predictor reusing the ``analysis/extract`` jaxpr walker
   (:mod:`horovod_tpu.telemetry.predict`). Pipeline bubble helpers
   compare measured idle fractions against ``parallel.pipeline``'s
   analytic schedules.

3. **Cross-rank merge** — ``python -m horovod_tpu.telemetry.report``
   merges per-rank timeline JSONs into one Perfetto-loadable trace
   with clock alignment and per-tensor straggler attribution.

4. **Flight recorder & forensics** — the core's always-on structured
   event ring (:func:`events` / :func:`events_drain`) feeds black-box
   per-rank JSONL dumps on every typed fault;
   :mod:`~horovod_tpu.telemetry.postmortem` merges them into one
   causal cross-rank timeline (``report --post-mortem``) naming the
   root-cause rank, and :mod:`~horovod_tpu.telemetry.debug_server`
   (``HOROVOD_DEBUG_PORT``) serves ``/healthz`` ``/metrics``
   ``/events`` ``/stacks`` per rank, live.

5. **Request anatomy** — the serving lane records rid-tagged
   ``request`` lifecycle events (queued/prefill/kv_ship/decode/
   requeue) through the same ring;
   :mod:`~horovod_tpu.telemetry.reqtrace` stitches per-rank dumps into
   gap-free per-request span chains (``report --requests`` decomposes
   the tail-latency band by phase) and feeds the debug server's
   ``/requests`` live in-flight view.

6. **Step anatomy** — :func:`step_mark` windows (driven by
   :class:`StepTimer` and the eager optimizer) scope every event to a
   step; the core's overlap ledger (``wire.overlap``) splits wire time
   into exposed vs hidden per plane,
   :mod:`~horovod_tpu.telemetry.critpath` attributes each step's wall
   time to the blocking rank and phase across ranks
   (``report --critical-path``), and
   :mod:`~horovod_tpu.telemetry.perfwatch` gates CI on step-time/
   busbw/overlap-efficiency regressions (``perfwatch --budget``).

See ``docs/metrics.md`` for the counter catalog and walkthroughs.
"""

from horovod_tpu.telemetry.core import (  # noqa: F401
    events,
    events_drain,
    metrics_reset,
    snapshot,
    step_id,
    step_mark,
    total_collective_bytes,
    wire_overlap,
    wire_plane_bytes,
)
from horovod_tpu.telemetry.critpath import (  # noqa: F401
    critical_path,
    format_critical_path,
    write_event_dump,
)
from horovod_tpu.telemetry.exporters import MetricsScraper  # noqa: F401
from horovod_tpu.telemetry.reqtrace import (  # noqa: F401
    format_requests,
    live_requests,
    record_request,
    stitch_requests,
    tail_report,
)
from horovod_tpu.telemetry.postmortem import (  # noqa: F401
    format_post_mortem,
    merge_post_mortem,
    merge_post_mortem_streaming,
)
from horovod_tpu.telemetry.step_timer import (  # noqa: F401
    StepTimer,
    analytic_bubble,
    bubble_report,
    measured_bubble,
)
