"""Fleet observatory: cross-lane rank-seconds ledger + live aggregation.

ROADMAP item 5 (train/serve colocation) needs one number per rank per
window: where did every rank-second go? This module decomposes each
rank's wall time into the BUCKETS vocabulary below — train compute,
*exposed* wire (the r17/r21 overlap-ledger measure: wire under an
API-thread wait), negotiation/control, the serving lane's
prefill/decode/queued phases (r19 reqtrace), stall/heal evidence, truly
idle gaps between steps — with the r17 exact-reconciliation standard:
**the buckets sum to the window to the microsecond**, and whatever the
runtime recorded no evidence for is booked ``unattributed``, never
silently absorbed.

Three consumers:

- :func:`analyze` / ``report.py --fleet`` — post-mortem fleet view over
  per-rank black-box dumps (fault dumps or
  :func:`critpath.write_event_dump` live dumps): per-rank utilization
  table, fleet-wide rank-seconds, worst-rank attribution via critpath,
  and the SLO verdicts (both breach events found in the dumps and a
  re-evaluation of the ledger-derived signals).
- :class:`FleetObservatory` — live driver/rank-0 aggregator polling
  every rank's debug server (``/healthz`` + ``/events``) into fleet
  time series, served at the ``/fleet`` debug endpoint. Each poll
  evaluates the declared SLOs (:mod:`telemetry.slo`) per rank and
  records typed ``slo_breach`` ring events.
- ``bench.py --fleet-util`` — the perfwatch-gated ``fleet_utilization``
  row over the simworld synthesized fleet (docs/benchmarks.md).

Bucket claiming is by PRIORITY (stall > exposed wire > negotiation >
serving decode > prefill > queued), each bucket claiming only wall time
no higher-priority bucket already covered — phases may overlap on the
wall clock (a negotiation cycle under a wire span), and double-counting
would break reconciliation. ``compute`` then claims the step-window
remainder, ``idle`` the gaps BETWEEN step windows, and ``unattributed``
is the exact integer remainder (docs/fleet.md).
"""

import json
import os
import time
import urllib.request
from collections import deque

from horovod_tpu.telemetry import critpath, postmortem, slo

# Rank-seconds bucket vocabulary — index-ABI with csrc/events.cc
# kRankBucketNames (the kSloBreach dominant-phase arg; pinned in
# analysis/model/abi.py). Order is also the claiming priority for the
# interval buckets (stall first), with the three derived buckets
# (compute/idle/unattributed) computed afterwards.
BUCKETS = (
    "compute",
    "exposed_wire",
    "negotiation",
    "serving_prefill",
    "serving_decode",
    "serving_queued",
    "stall",
    "idle",
    "unattributed",
)

# Claiming priority for the event-derived interval buckets.
_CLAIM_ORDER = ("stall", "exposed_wire", "negotiation", "serving_decode",
                "serving_prefill", "serving_queued")

# Serving request-lifecycle phase -> ledger bucket (REQUEST_PHASES,
# docs/serving.md): active compute phases map to their own buckets,
# every waiting/transit phase is queued-idle. "done" closes the rid.
_SERVING_BUCKET = {
    "prefill": "serving_prefill",
    "decode_active": "serving_decode",
    "queued": "serving_queued",
    "kv_ship": "serving_queued",
    "decode_wait": "serving_queued",
    "evicted_requeue": "serving_queued",
    "fault_requeue": "serving_queued",
}


def _serving_intervals(dump):
    """Per-bucket wall intervals from the rid-tagged ``request`` events
    (each marks the instant a rid ENTERS a phase; the interval runs to
    its next transition, or to the dump's last event for a rid still
    open — the live truth at dump time)."""
    hdr = dump["header"]
    out = {"serving_prefill": [], "serving_decode": [],
           "serving_queued": []}
    open_phase = {}  # rid -> (bucket, start_wall)
    last_wall = None
    for ev in dump["events"]:
        wall = critpath._wall(ev, hdr)
        last_wall = wall
        if ev.get("type") != "request":
            continue
        rid = ev.get("rid")
        prev = open_phase.pop(rid, None)
        if prev is not None and wall > prev[1]:
            out[prev[0]].append((prev[1], wall))
        bucket = _SERVING_BUCKET.get(ev.get("phase_name"))
        if bucket is not None:
            open_phase[rid] = (bucket, wall)
    if last_wall is not None:
        for bucket, start in open_phase.values():
            if last_wall > start:
                out[bucket].append((start, last_wall))
    return out


def ledger_from_dump(dump, window=None):
    """Decompose one rank's dump into the rank-seconds BUCKETS.

    ``window`` is ``(lo_us, hi_us)`` on the dump's wall axis; the
    default is the rank's own observed span — opening at the FIRST STEP
    MARK when the rank is step-marked (startup before the first marked
    step — imports, rendezvous, debug-server binds — is not
    schedulable rank-time), else at the first event, and closing at the
    last event either way. That is what keeps ``unattributed`` honest:
    time outside the flight recorder's view is not in the window at
    all, and what IS in the window but carries no evidence stays
    visible as a remainder instead of being absorbed.

    Returns ``{"rank", "lo_us", "hi_us", "window_us", "buckets":
    {name: us}, "utilization"}`` with ``sum(buckets.values()) ==
    window_us`` EXACTLY (integer microseconds; the r17 reconciliation
    standard)."""
    hdr = dump["header"]
    events = dump["events"]
    walls = [critpath._wall(ev, hdr) for ev in events]
    steps = sorted(critpath.step_windows(dump).values())
    if window is not None:
        lo, hi = int(window[0]), int(window[1])
    elif walls:
        lo, hi = (steps[0][0] if steps else min(walls)), max(walls)
    else:
        lo = hi = 0
    window_us = max(hi - lo, 0)
    buckets = {name: 0 for name in BUCKETS}
    result = {
        "rank": hdr.get("rank", -1),
        "lo_us": lo,
        "hi_us": hi,
        "window_us": window_us,
        "buckets": buckets,
        "utilization": 0.0,
    }
    if window_us == 0:
        return result

    phases = critpath.phase_intervals(dump)
    intervals = {
        "stall": phases["stall"],
        "exposed_wire": phases["wire"],
        "negotiation": phases["negotiation"],
        **_serving_intervals(dump),
    }

    # Priority claiming: each bucket's contribution is the measure its
    # intervals add to the UNION of everything claimed so far — exact
    # integer math, no double counting (module docstring).
    covered = []
    claimed = 0

    def claim(new):
        nonlocal claimed
        covered.extend(new)
        total = critpath.union_measure(covered, lo, hi)
        delta = total - claimed
        claimed = total
        return delta

    for name in _CLAIM_ORDER:
        buckets[name] = claim(intervals[name])

    # compute: the in-step remainder; idle: the gaps BETWEEN steps.
    buckets["compute"] = claim(steps)
    gaps = [(steps[i][1], steps[i + 1][0])
            for i in range(len(steps) - 1)]
    buckets["idle"] = claim(gaps)
    buckets["unattributed"] = window_us - claimed

    useful = (buckets["compute"] + buckets["exposed_wire"]
              + buckets["negotiation"] + buckets["serving_prefill"]
              + buckets["serving_decode"])
    result["utilization"] = round(useful / window_us, 6)
    return result


def ledger_from_events(events, rank=-1, window=None):
    """The live twin of :func:`ledger_from_dump`: ring-event dicts
    straight from ``hvd.events()`` (axis = the process's own steady
    ``ts_us`` — no wall alignment needed within one rank)."""
    dump = {"header": {"rank": rank, "unix_us": 0, "steady_us": 0},
            "events": list(events)}
    return ledger_from_dump(dump, window=window)


def ledger_signals(ledger):
    """SLO signals derived from one rank's ledger (the names are the
    :data:`telemetry.slo.OBJECTIVES` vocabulary)."""
    w = ledger["window_us"]
    b = ledger["buckets"]
    return {
        "stall_ms": round(b["stall"] / 1000.0, 3),
        "queued_idle_share": round(b["serving_queued"] / w, 6)
        if w else 0.0,
    }


def dominant_phase(ledger):
    """The rank's dominant ATTRIBUTED bucket — the phase a breach names
    (idle/unattributed are absences of evidence, not phases)."""
    best, best_us = "", -1
    for name in BUCKETS:
        if name in ("idle", "unattributed"):
            continue
        if ledger["buckets"][name] > best_us:
            best, best_us = name, ledger["buckets"][name]
    return best if best_us > 0 else ""


def _breach_events(dumps):
    """slo_breach events recorded live, folded out of the dumps (once
    per (rank, seq) — re-dumps repeat ring tails)."""
    seen = set()
    out = []
    for rank, dump in sorted(dumps.items()):
        for ev in dump["events"]:
            if ev.get("type") != "slo_breach":
                continue
            key = (rank, ev.get("seq"))
            if key in seen:
                continue
            seen.add(key)
            out.append({
                "source_rank": rank,
                "objective": ev.get("objective_name"),
                "breach_rank": ev.get("breach_rank"),
                "value": ev.get("value"),
                "phase": ev.get("phase_name"),
                "wall_us": critpath._wall(ev, dump["header"]),
            })
    return out


def analyze(paths_or_dir, dump_index=-1, objectives=None, window=None):
    """Post-mortem fleet analysis over per-rank black-box dumps: the
    ``report.py --fleet`` engine (and the simworld acceptance lane).

    Per-rank ledgers use each rank's own observed window (cross-rank
    clock skew must not leak into reconciliation); the fleet aggregates
    are sums/means over them. Worst-rank attribution rides critpath's
    blocking-rank verdicts when step windows exist. SLO verdicts
    combine breach events found IN the dumps (recorded live) with a
    fresh evaluation of the ledger-derived signals, so a fleet whose
    live engine never ran still gets judged."""
    paths = postmortem.collect_paths(paths_or_dir)
    dumps = {}
    for path in paths:
        loaded = postmortem.load_blackbox(path)
        if loaded:
            dump = loaded[dump_index]
            dumps[dump["header"].get("rank", -1)] = dump
    if not dumps:
        raise ValueError(f"no event dumps found in {paths_or_dir!r}")

    ledgers = {r: ledger_from_dump(d, window=window)
               for r, d in sorted(dumps.items())}

    fleet_buckets = {name: sum(l["buckets"][name]
                               for l in ledgers.values())
                     for name in BUCKETS}
    total_us = sum(l["window_us"] for l in ledgers.values())

    # Worst-rank attribution via critpath (module docstring): the rank
    # that bounded the most steps. Dump sets without step windows
    # (pure serving lanes) fall back to lowest utilization.
    worst_rank, worst_via = None, "utilization"
    try:
        cp = critpath.critical_path(paths_or_dir, dump_index)
        if cp["blocking_counts"]:
            worst_rank = max(cp["blocking_counts"],
                             key=cp["blocking_counts"].get)
            worst_via = "critpath"
    except ValueError:
        cp = None
    if worst_rank is None and ledgers:
        worst_rank = min(ledgers, key=lambda r: ledgers[r]["utilization"])

    engine = slo.SloEngine(objectives if objectives is not None
                           else slo.DEFAULT_OBJECTIVES)
    per_rank_signals = {r: ledger_signals(l) for r, l in ledgers.items()}
    phases = {r: dominant_phase(l) for r, l in ledgers.items()}
    evaluated = engine.evaluate(per_rank_signals, phases)

    return {
        "ranks": sorted(ledgers),
        "per_rank": ledgers,
        "fleet": {
            "window_us": total_us,
            "rank_seconds": {name: round(us / 1e6, 6)
                             for name, us in fleet_buckets.items()},
            "utilization": round(
                sum(l["utilization"] * l["window_us"]
                    for l in ledgers.values()) / total_us, 6)
            if total_us else 0.0,
            "worst_rank": worst_rank,
            "worst_via": worst_via,
        },
        "slo": {
            "objectives": [f"{o.name} {o.op} {o.threshold:g}"
                           for o in engine.objectives],
            "breach_events": _breach_events(dumps),
            "evaluated": [vars(b) for b in evaluated],
        },
        "critpath": {k: cp[k] for k in ("blocking_counts",
                                        "phase_counts")} if cp else None,
    }


def format_fleet(analysis, max_ranks=64):
    """Operator-facing rendering: the per-rank utilization table, the
    fleet rank-seconds line, worst-rank attribution, and the SLO
    verdicts."""
    lines = []
    fleet = analysis["fleet"]
    rs = fleet["rank_seconds"]
    occupied = {k: v for k, v in rs.items() if v > 0}
    lines.append(
        f"fleet: {len(analysis['ranks'])} ranks, "
        f"{fleet['window_us'] / 1e6:.3f} rank-seconds observed, "
        f"utilization {fleet['utilization']:.1%}")
    lines.append("rank-seconds: " + ", ".join(
        f"{k}={v:.3f}s" for k, v in sorted(
            occupied.items(), key=lambda kv: -kv[1])))
    if fleet["worst_rank"] is not None:
        lines.append(f"worst rank: {fleet['worst_rank']} "
                     f"(via {fleet['worst_via']})")
    header = (f"{'rank':>5} {'window ms':>10} {'util':>6} "
              + " ".join(f"{name:>15}" for name in BUCKETS))
    lines.append(header)
    for rank in analysis["ranks"][:max_ranks]:
        l = analysis["per_rank"][rank]
        lines.append(
            f"{rank:>5} {l['window_us'] / 1000.0:>10.1f} "
            f"{l['utilization']:>6.1%} "
            + " ".join(f"{l['buckets'][name] / 1000.0:>13.1f}ms"
                       for name in BUCKETS))
    if len(analysis["ranks"]) > max_ranks:
        lines.append(f"... {len(analysis['ranks']) - max_ranks} more "
                     f"ranks")
    breaches = analysis["slo"]["breach_events"]
    evaluated = analysis["slo"]["evaluated"]
    if breaches or evaluated:
        lines.append(f"slo: {len(breaches)} recorded breach event(s), "
                     f"{len(evaluated)} from re-evaluation")
        for b in breaches:
            lines.append(f"  breach [{b['objective']}] rank "
                         f"{b['breach_rank']} value={b['value']} "
                         f"phase={b['phase']}")
        for b in evaluated:
            lines.append(f"  breach [{b['objective']}] rank {b['rank']} "
                         f"value={b['value']:g} phase={b['phase']}")
    else:
        lines.append("slo: no breaches")
    return "\n".join(lines)


# ---- live aggregation -------------------------------------------------


def _http_json(url, timeout):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


class FleetObservatory:
    """Live fleet aggregator: poll every rank's debug server into one
    time series, evaluate the declared SLOs per poll, and serve the
    combined view (the ``/fleet`` endpoint payload).

    ``endpoints`` is ``{rank: "host:port"}``; when omitted it is
    derived the way the debug servers themselves bind (r16):
    ``HOROVOD_DEBUG_PORT + rank`` for ``HOROVOD_SIZE`` ranks on
    loopback. Ephemeral-port worlds (``HOROVOD_DEBUG_PORT=0``) must
    pass explicit endpoints — there is nothing to derive.

    Each poll fetches ``/healthz`` (the autoscaler signal set) and the
    ``/events`` tail (the per-rank ledger input). SLOs are evaluated
    per rank over healthz signals + ledger-derived signals; breaches
    are recorded into the LOCAL ring via ``basics.record_slo`` when a
    ``basics`` was given (rank 0's black box then carries the fleet's
    verdicts), and always kept on ``engine.breaches``.
    """

    def __init__(self, endpoints=None, basics=None, objectives=None,
                 timeout=2.0, events_tail=4096, history=256):
        self.endpoints = dict(endpoints) if endpoints else None
        self.basics = basics
        self.timeout = float(timeout)
        self.events_tail = int(events_tail)
        if objectives is None:
            objectives = os.environ.get("HOROVOD_SLO") or \
                slo.DEFAULT_OBJECTIVES
        self.engine = slo.SloEngine(objectives)
        self.history = deque(maxlen=int(history))
        # Last /fleet view, read (not recomputed) by
        # autoscale.read_fleet_signals — an autoscaler observation
        # must never trigger a fleet-wide HTTP sweep.
        self.last_view = None

    def resolve_endpoints(self):
        if self.endpoints is not None:
            return self.endpoints
        base = int(os.environ.get("HOROVOD_DEBUG_PORT", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "0") or 0)
        if base <= 0 or size <= 0:
            return {}
        host = os.environ.get("HOROVOD_DEBUG_HOST", "127.0.0.1")
        if host == "0.0.0.0":  # bind-all is not a dial-able address
            host = "127.0.0.1"
        self.endpoints = {r: f"{host}:{base + r}" for r in range(size)}
        return self.endpoints

    def poll(self):
        """One fleet sweep. Unreachable ranks are reported, not fatal —
        a fleet view that dies with its sickest rank is useless."""
        sample = {"ts": time.time(), "ranks": {}, "breaches": []}
        per_rank_signals, phases = {}, {}
        for rank, addr in sorted(self.resolve_endpoints().items()):
            entry = {"endpoint": addr}
            try:
                health = _http_json(f"http://{addr}/healthz",
                                    self.timeout)
                events = _http_json(
                    f"http://{addr}/events?n={self.events_tail}",
                    self.timeout)
                ledger = ledger_from_events(events, rank=rank)
                entry["healthz"] = health
                entry["ledger"] = ledger
                signals = {
                    name: health[name] for name in slo.OBJECTIVES
                    if name in health
                }
                signals.update(ledger_signals(ledger))
                per_rank_signals[rank] = signals
                phases[rank] = dominant_phase(ledger)
            except Exception as e:  # noqa: BLE001 — sick ranks stay rows
                entry["error"] = f"{type(e).__name__}: {e}"
            sample["ranks"][rank] = entry
        breaches = self.engine.evaluate(per_rank_signals, phases)
        if breaches and self.basics is not None:
            self.engine.record(self.basics, breaches)
        sample["breaches"] = [vars(b) for b in breaches]
        self.history.append(sample)
        return sample

    def fleet_json(self):
        """The ``/fleet`` payload: a fresh poll plus the aggregate view
        and the utilization series polled so far."""
        sample = self.poll()
        ledgers = {r: e["ledger"] for r, e in sample["ranks"].items()
                   if "ledger" in e}
        total_us = sum(l["window_us"] for l in ledgers.values())
        view = {
            "ts": sample["ts"],
            "size": len(sample["ranks"]),
            "reachable": len(ledgers),
            "ranks": sample["ranks"],
            "fleet": {
                "window_us": total_us,
                "rank_seconds": {
                    name: round(sum(l["buckets"][name]
                                    for l in ledgers.values()) / 1e6, 6)
                    for name in BUCKETS
                },
                "utilization": round(
                    sum(l["utilization"] * l["window_us"]
                        for l in ledgers.values()) / total_us, 6)
                if total_us else 0.0,
                "worst_rank": min(
                    ledgers, key=lambda r: ledgers[r]["utilization"])
                if ledgers else None,
            },
            "slo": {
                "objectives": [f"{o.name} {o.op} {o.threshold:g}"
                               for o in self.engine.objectives],
                "breaches": sample["breaches"],
                "breaches_total": len(self.engine.breaches),
            },
            "series": {
                "utilization": [
                    {str(r): e["ledger"]["utilization"]
                     for r, e in s["ranks"].items() if "ledger" in e}
                    for s in self.history
                ],
            },
        }
        self.last_view = view
        return view


_observatory = None
_observatory_lock = __import__("threading").Lock()


def maybe_observatory(basics):
    """The process-wide observatory the ``/fleet`` debug endpoint
    serves from (lazy — a fleet poll costs one HTTP round per rank, so
    nothing happens until someone asks)."""
    global _observatory
    with _observatory_lock:
        if _observatory is None:
            _observatory = FleetObservatory(basics=basics)
        return _observatory


def reset_observatory():
    """Test isolation: drop the process-wide observatory (endpoint
    derivation caches env)."""
    global _observatory
    with _observatory_lock:
        _observatory = None
