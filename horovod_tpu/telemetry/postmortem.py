"""Black-box post-mortem: merge per-rank event-ring dumps into one
causal cross-rank fault timeline.

When the native core records a typed fault (``PeerFailure`` /
``WireCorruption``) it dumps the tail of its structured event ring to a
per-rank JSONL file *before* any handle wakes an API thread
(``DumpBlackBox`` in ``csrc/operations.cc``) — so even a job that dies
ugly leaves, per surviving rank, the causal window that led there. This
module is the offline half::

    python -m horovod_tpu.telemetry.report --post-mortem \
        /tmp/hvdtpu_blackbox/blackbox-rank*.jsonl

Clock alignment reuses the CLOCK_SYNC contract of the Perfetto merge:
each dump's header carries a ``(unix_us, steady_us)`` pair sampled
together at dump time, so every rank's steady-clock event timestamps
map onto one wall-clock axis (up to NTP skew, same bound as the trace
merge).

Attribution separates **root-cause death from secondary timeouts**, the
same proof-vs-suspicion discipline as the elastic layer
(docs/elastic.md): a rank named by a *certain* fault record (EOF/RST/
probe sweep) is provably dead — root cause. A rank that is merely
*suspected* (timeout) but wrote its own black-box dump is demonstrably
alive — its naming was a secondary timeout (it was quiet because it was
itself blocked on the real casualty). The **first-stalled rank** is the
one whose last forward-progress event (wire chunk/span, response
launch, negotiation end) is earliest on the merged axis — among
survivors, that is the rank the stall propagated *from*.
"""

import json
import os
from collections import defaultdict

# Event types that constitute forward progress for first-stall analysis.
PROGRESS_TYPES = ("wire_chunk", "wire_span", "response_launch",
                  "negotiate_end")


def default_blackbox_dir():
    """Where the core dumps land when HOROVOD_BLACKBOX_DIR is unset
    (must mirror DumpBlackBox in csrc/operations.cc)."""
    env = os.environ.get("HOROVOD_BLACKBOX_DIR", "")
    if env and env not in ("off", "none", "0"):
        return env
    return os.path.join(os.environ.get("TMPDIR") or "/tmp",
                        "hvdtpu_blackbox")


def load_blackbox(path):
    """Parse one per-rank black-box JSONL file into a list of dumps
    (a process appends one dump per fault): each is
    ``{"header": {...}, "events": [...]}``. Tolerates a truncated final
    line (the process may have died mid-write)."""
    dumps = []
    current = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a dying process
            if row.get("kind") == "blackbox_header":
                current = {"header": row, "events": []}
                dumps.append(current)
            elif current is not None:
                current["events"].append(row)
    return dumps


def collect_paths(paths_or_dir):
    """Expand a directory (or mixed list) into blackbox JSONL paths."""
    if isinstance(paths_or_dir, str):
        paths_or_dir = [paths_or_dir]
    out = []
    for p in paths_or_dir:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.startswith("blackbox-") and f.endswith(".jsonl")))
        else:
            out.append(p)
    return out


def _wall_us(event, header):
    """Steady-clock event timestamp -> wall clock, via the header's
    (unix_us, steady_us) anchor pair (the CLOCK_SYNC contract)."""
    return event["ts_us"] - header["steady_us"] + header["unix_us"]


def merge_post_mortem(paths_or_dir, dump_index=-1):
    """Merge per-rank black-box dumps into one causal analysis.

    ``dump_index`` selects which dump per file when a process recorded
    several faults (-1 = the latest). Returns a dict with:

    - ``timeline``: every rank's events on one wall axis, sorted —
      each entry carries ``rank``, ``wall_us``, ``t_ms`` (relative to
      the earliest event) and the original event fields;
    - ``root_cause_ranks``: provably dead (or corrupting) ranks;
    - ``secondary_suspects``: ranks named only by timeout suspicion
      that demonstrably survived (wrote their own dump);
    - ``first_stalled_rank`` and ``last_progress_us`` per rank;
    - ``per_rank``: each survivor's fault record + event count.
    """
    paths = collect_paths(paths_or_dir)
    ranks = {}
    for path in paths:
        dumps = load_blackbox(path)
        if not dumps:
            continue
        dump = dumps[dump_index]
        rank = dump["header"].get("rank", -1)
        ranks[rank] = dump
    if not ranks:
        raise ValueError(f"no black-box dumps found in {paths_or_dir!r}")

    survivors = set(ranks)
    certain, suspected, corrupting = set(), set(), set()
    per_rank = {}
    for rank, dump in sorted(ranks.items()):
        fault = dump["header"].get("fault", {})
        named = set(fault.get("ranks", []))
        if fault.get("kind") == "corruption":
            # Corruption names a live-but-poisoning peer: root cause
            # of THIS fault even though the process survives (and may
            # itself have dumped, oblivious).
            corrupting |= named
        elif fault.get("certain"):
            certain |= named
        else:
            suspected |= named
        per_rank[rank] = {
            "epoch": dump["header"].get("epoch"),
            "fault": fault,
            "events": len(dump["events"]),
        }

    # A dump is proof of life at fault time, and it BEATS a peer's
    # "certain" EOF attribution: survivors tearing their sockets down
    # after recording their own fault feed late-classifying peers EOFs
    # on live ranks (the r12 ordering gotcha) — offline, the dump's
    # existence filters those artifacts out. What remains certain and
    # dump-less is provably dead: root cause.
    root_cause = sorted((certain - survivors) | corrupting)
    secondary = sorted(((certain | suspected) & survivors) - corrupting)
    if not root_cause:
        # No proof anywhere: the suspects that did NOT dump are the
        # best remaining explanation (they never noticed a fault —
        # consistent with being the casualty).
        root_cause = sorted(suspected - survivors)

    timeline = []
    for rank, dump in ranks.items():
        hdr = dump["header"]
        for ev in dump["events"]:
            entry = dict(ev)
            entry["rank"] = rank
            entry["wall_us"] = _wall_us(ev, hdr)
            timeline.append(entry)
    timeline.sort(key=lambda e: e["wall_us"])
    t0 = timeline[0]["wall_us"] if timeline else 0
    for e in timeline:
        e["t_ms"] = round((e["wall_us"] - t0) / 1000.0, 3)

    # First-stalled: progress only counts BEFORE the stall was first
    # noticed anywhere (the earliest retry-ladder window or fault on
    # the merged axis) — a SIGSTOPped rank that later resumes, retries,
    # and faults records plenty of late activity, but its last progress
    # *before the stall surfaced* is what betrays that it froze first
    # while its peers were still launching work against it.
    stall_marks = [e["wall_us"] for e in timeline
                   if e["type"] in ("retry_window", "fault", "crc_error")]
    cutoff = min(stall_marks) if stall_marks else None
    last_progress = {}
    for e in timeline:
        if e["type"] not in PROGRESS_TYPES:
            continue
        if cutoff is not None and e["wall_us"] > cutoff:
            continue
        rank = e["rank"]
        if e["wall_us"] > last_progress.get(rank, float("-inf")):
            last_progress[rank] = e["wall_us"]
    first_stalled = None
    if last_progress:
        first_stalled = min(last_progress, key=last_progress.get)
    for rank, us in last_progress.items():
        per_rank[rank]["last_progress_ms"] = round((us - t0) / 1000.0, 3)

    return {
        "ranks": sorted(survivors),
        "root_cause_ranks": root_cause,
        "secondary_suspects": secondary,
        "first_stalled_rank": first_stalled,
        "per_rank": per_rank,
        "timeline": timeline,
    }


def format_post_mortem(analysis, tail=40):
    """Operator-facing text rendering of :func:`merge_post_mortem`."""
    lines = []
    rc = analysis["root_cause_ranks"]
    lines.append(
        f"root cause: rank(s) {rc}" if rc else
        "root cause: none provable (no certain attribution in any dump)")
    if analysis["secondary_suspects"]:
        lines.append("secondary timeouts (suspected but alive): "
                     f"{analysis['secondary_suspects']}")
    if analysis["first_stalled_rank"] is not None:
        lines.append(
            f"first stalled: rank {analysis['first_stalled_rank']} "
            "(earliest last-progress event)")
    for rank, d in sorted(analysis["per_rank"].items()):
        fault = d.get("fault", {})
        lines.append(
            f"  rank {rank}: epoch {d.get('epoch')}, "
            f"{d['events']} events, fault kind={fault.get('kind')} "
            f"certain={fault.get('certain')} ranks={fault.get('ranks')} "
            f"last progress {d.get('last_progress_ms', '-')} ms")
    lines.append(f"causal timeline (last {tail} of "
                 f"{len(analysis['timeline'])} events):")
    for e in analysis["timeline"][-tail:]:
        args = {k: v for k, v in e.items()
                if k not in ("rank", "wall_us", "t_ms", "ts_us", "seq",
                             "type")}
        lines.append(f"  {e['t_ms']:>10.3f} ms  rank {e['rank']}  "
                     f"{e['type']}  {args}")
    return "\n".join(lines)


# ---- events -> Perfetto -----------------------------------------------


def events_to_trace_events(dump, base_unix_us, tid=990):
    """Render one dump's ring events as Chrome-trace events on the
    merged axis (``ts = wall_us - base_unix_us``): ``wire_span``
    becomes a complete ('X') span ending at its record time, everything
    else an instant ('i') — so chunk-level wire activity and heal-ladder
    steps land on the same Perfetto timeline as the per-op spans."""
    hdr = dump["header"]
    rank = hdr.get("rank", -1)
    out = [{
        "name": "thread_name", "ph": "M", "pid": rank, "tid": tid,
        "args": {"name": "events"},
    }]
    for ev in dump["events"]:
        wall = _wall_us(ev, hdr)
        ts = wall - base_unix_us
        args = {k: v for k, v in ev.items()
                if k not in ("ts_us", "seq", "type")}
        if ev.get("type") == "wire_span":
            dur = max(int(ev.get("dur_us", 0)), 1)
            out.append({"name": f"wire_span p{ev.get('plane', 0)}",
                        "ph": "X", "ts": ts - dur, "dur": dur,
                        "pid": rank, "tid": tid, "args": args})
        else:
            out.append({"name": ev.get("type", "event"), "ph": "i",
                        "ts": ts, "pid": rank, "tid": tid, "s": "t",
                        "args": args})
    return out
