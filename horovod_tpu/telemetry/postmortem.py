"""Black-box post-mortem: merge per-rank event-ring dumps into one
causal cross-rank fault timeline.

When the native core records a typed fault (``PeerFailure`` /
``WireCorruption``) it dumps the tail of its structured event ring to a
per-rank JSONL file *before* any handle wakes an API thread
(``DumpBlackBox`` in ``csrc/operations.cc``) — so even a job that dies
ugly leaves, per surviving rank, the causal window that led there. This
module is the offline half::

    python -m horovod_tpu.telemetry.report --post-mortem \
        /tmp/hvdtpu_blackbox/blackbox-rank*.jsonl

Clock alignment reuses the CLOCK_SYNC contract of the Perfetto merge:
each dump's header carries a ``(unix_us, steady_us)`` pair sampled
together at dump time, so every rank's steady-clock event timestamps
map onto one wall-clock axis (up to NTP skew, same bound as the trace
merge).

Attribution separates **root-cause death from secondary timeouts**, the
same proof-vs-suspicion discipline as the elastic layer
(docs/elastic.md): a rank named by a *certain* fault record (EOF/RST/
probe sweep) is provably dead — root cause. A rank that is merely
*suspected* (timeout) but wrote its own black-box dump is demonstrably
alive — its naming was a secondary timeout (it was quiet because it was
itself blocked on the real casualty). The **first-stalled rank** is the
one whose last forward-progress event (wire chunk/span, response
launch, negotiation end) is earliest on the merged axis — among
survivors, that is the rank the stall propagated *from*.
"""

import heapq
import json
import os
from collections import defaultdict, deque

# Event types that constitute forward progress for first-stall analysis.
PROGRESS_TYPES = ("wire_chunk", "wire_span", "response_launch",
                  "negotiate_end")


def _fold_slo_breaches(timeline):
    """SLO breach events out of a merged timeline, folded ONCE per
    (source rank, ring seq): a process re-dumps its ring tail on every
    fault, so the same recorded breach can reach the merge several
    times — the verdict list must not multiply with the fault count
    (docs/fleet.md)."""
    seen = set()
    out = []
    for e in timeline:
        if e.get("type") != "slo_breach":
            continue
        key = (e.get("rank"), e.get("seq"))
        if key in seen:
            continue
        seen.add(key)
        out.append({
            "source_rank": e.get("rank"),
            "objective": e.get("objective_name"),
            "breach_rank": e.get("breach_rank"),
            "value": e.get("value"),
            "phase": e.get("phase_name"),
            "t_ms": e.get("t_ms"),
        })
    return out


def default_blackbox_dir():
    """Where the core dumps land when HOROVOD_BLACKBOX_DIR is unset
    (must mirror DumpBlackBox in csrc/operations.cc)."""
    env = os.environ.get("HOROVOD_BLACKBOX_DIR", "")
    if env and env not in ("off", "none", "0"):
        return env
    return os.path.join(os.environ.get("TMPDIR") or "/tmp",
                        "hvdtpu_blackbox")


def load_blackbox(path):
    """Parse one per-rank black-box JSONL file into a list of dumps
    (a process appends one dump per fault): each is
    ``{"header": {...}, "events": [...]}``. Tolerates a truncated final
    line (the process may have died mid-write)."""
    dumps = []
    current = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a dying process
            if row.get("kind") == "blackbox_header":
                current = {"header": row, "events": []}
                dumps.append(current)
            elif current is not None:
                current["events"].append(row)
    return dumps


def collect_paths(paths_or_dir):
    """Expand a directory (or mixed list) into blackbox JSONL paths."""
    if isinstance(paths_or_dir, str):
        paths_or_dir = [paths_or_dir]
    out = []
    for p in paths_or_dir:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.startswith("blackbox-") and f.endswith(".jsonl")))
        else:
            out.append(p)
    return out


def _wall_us(event, header):
    """Steady-clock event timestamp -> wall clock, via the header's
    (unix_us, steady_us) anchor pair (the CLOCK_SYNC contract)."""
    return event["ts_us"] - header["steady_us"] + header["unix_us"]


def merge_post_mortem(paths_or_dir, dump_index=-1):
    """Merge per-rank black-box dumps into one causal analysis.

    ``dump_index`` selects which dump per file when a process recorded
    several faults (-1 = the latest). Returns a dict with:

    - ``timeline``: every rank's events on one wall axis, sorted —
      each entry carries ``rank``, ``wall_us``, ``t_ms`` (relative to
      the earliest event) and the original event fields;
    - ``root_cause_ranks``: provably dead (or corrupting) ranks;
    - ``secondary_suspects``: ranks named only by timeout suspicion
      that demonstrably survived (wrote their own dump);
    - ``first_stalled_rank`` and ``last_progress_us`` per rank;
    - ``per_rank``: each survivor's fault record + event count.
    """
    paths = collect_paths(paths_or_dir)
    ranks = {}
    for path in paths:
        dumps = load_blackbox(path)
        if not dumps:
            continue
        dump = dumps[dump_index]
        rank = dump["header"].get("rank", -1)
        ranks[rank] = dump
    if not ranks:
        raise ValueError(f"no black-box dumps found in {paths_or_dir!r}")

    survivors = set(ranks)
    certain, suspected, corrupting = set(), set(), set()
    per_rank = {}
    for rank, dump in sorted(ranks.items()):
        fault = dump["header"].get("fault", {})
        named = set(fault.get("ranks", []))
        if fault.get("kind") == "corruption":
            # Corruption names a live-but-poisoning peer: root cause
            # of THIS fault even though the process survives (and may
            # itself have dumped, oblivious).
            corrupting |= named
        elif fault.get("certain"):
            certain |= named
        else:
            suspected |= named
        per_rank[rank] = {
            "epoch": dump["header"].get("epoch"),
            "fault": fault,
            "events": len(dump["events"]),
        }

    # A dump is proof of life at fault time, and it BEATS a peer's
    # "certain" EOF attribution: survivors tearing their sockets down
    # after recording their own fault feed late-classifying peers EOFs
    # on live ranks (the r12 ordering gotcha) — offline, the dump's
    # existence filters those artifacts out. What remains certain and
    # dump-less is provably dead: root cause.
    root_cause = sorted((certain - survivors) | corrupting)
    secondary = sorted(((certain | suspected) & survivors) - corrupting)
    if not root_cause:
        # No proof anywhere: the suspects that did NOT dump are the
        # best remaining explanation (they never noticed a fault —
        # consistent with being the casualty).
        root_cause = sorted(suspected - survivors)

    timeline = []
    for rank, dump in ranks.items():
        hdr = dump["header"]
        for ev in dump["events"]:
            entry = dict(ev)
            entry["rank"] = rank
            entry["wall_us"] = _wall_us(ev, hdr)
            timeline.append(entry)
    timeline.sort(key=lambda e: e["wall_us"])
    t0 = timeline[0]["wall_us"] if timeline else 0
    for e in timeline:
        e["t_ms"] = round((e["wall_us"] - t0) / 1000.0, 3)

    # First-stalled: progress only counts BEFORE the stall was first
    # noticed anywhere (the earliest retry-ladder window or fault on
    # the merged axis) — a SIGSTOPped rank that later resumes, retries,
    # and faults records plenty of late activity, but its last progress
    # *before the stall surfaced* is what betrays that it froze first
    # while its peers were still launching work against it.
    stall_marks = [e["wall_us"] for e in timeline
                   if e["type"] in ("retry_window", "fault", "crc_error")]
    cutoff = min(stall_marks) if stall_marks else None
    last_progress = {}
    for e in timeline:
        if e["type"] not in PROGRESS_TYPES:
            continue
        if cutoff is not None and e["wall_us"] > cutoff:
            continue
        rank = e["rank"]
        if e["wall_us"] > last_progress.get(rank, float("-inf")):
            last_progress[rank] = e["wall_us"]
    first_stalled = None
    if last_progress:
        first_stalled = min(last_progress, key=last_progress.get)
    for rank, us in last_progress.items():
        per_rank[rank]["last_progress_ms"] = round((us - t0) / 1000.0, 3)

    return {
        "ranks": sorted(survivors),
        "root_cause_ranks": root_cause,
        "secondary_suspects": secondary,
        "first_stalled_rank": first_stalled,
        "per_rank": per_rank,
        "timeline": timeline,
        "slo_breaches": _fold_slo_breaches(timeline),
    }


def _load_dump_at(path, dump_index=-1):
    """Parse ONE dump from a per-rank file without materializing the
    others: for the common ``dump_index=-1`` the file is scanned once
    and only events after the LAST header are retained — memory stays
    one-dump-bounded however many faults the process logged."""
    if dump_index != -1:
        dumps = load_blackbox(path)
        return dumps[dump_index] if dumps else None
    current = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a dying process
            if row.get("kind") == "blackbox_header":
                current = {"header": row, "events": []}
            elif current is not None:
                current["events"].append(row)
    return current


def _scan_last_dump(path):
    """One O(1)-memory pass over a per-rank file: locate the LAST dump
    and summarize it without retaining its events. Returns ``None`` if
    the file holds no dump, else a dict with the header, the byte
    offset of the first event line, the event count, whether the events
    are already ts-ordered (ring snapshots normally are), and this
    rank's contribution to the global stall cutoff (min wall time of
    retry/fault/crc events)."""
    info = None
    with open(path) as f:
        while True:
            line = f.readline()
            if not line:
                break
            s = line.strip()
            if not s:
                continue
            try:
                row = json.loads(s)
            except json.JSONDecodeError:
                continue  # torn tail of a dying process
            if row.get("kind") == "blackbox_header":
                info = {"path": path, "header": row, "offset": f.tell(),
                        "events": 0, "in_order": True, "cutoff": None,
                        "_last_ts": None}
            elif info is not None:
                info["events"] += 1
                ts = row.get("ts_us", 0)
                if info["_last_ts"] is not None and ts < info["_last_ts"]:
                    info["in_order"] = False
                info["_last_ts"] = ts
                if row.get("type") in ("retry_window", "fault",
                                       "crc_error"):
                    wall = _wall_us(row, info["header"])
                    info["cutoff"] = (wall if info["cutoff"] is None
                                      else min(info["cutoff"], wall))
    return info


def _iter_dump_events(info, rank):
    """Lazily re-read one rank's last-dump events from disk (the offset
    :func:`_scan_last_dump` found), yielding ``(wall_us, rank, event)``
    in file order — the sorted-stream contract ``heapq.merge`` needs.
    The rare unsorted snapshot falls back to materializing just this
    rank (bounded by one ring tail)."""
    if not info["in_order"]:
        events = []
        with open(info["path"]) as f:
            f.seek(info["offset"])
            for line in f:
                row = _event_row(line)
                if row is _STOP:
                    break
                if row is not None:
                    events.append(row)
        events.sort(key=lambda e: e.get("ts_us", 0))
        for ev in events:
            yield (_wall_us(ev, info["header"]), rank, ev)
        return
    with open(info["path"]) as f:
        f.seek(info["offset"])
        for line in f:
            row = _event_row(line)
            if row is _STOP:
                break
            if row is not None:
                yield (_wall_us(row, info["header"]), rank, row)


_STOP = object()


def _event_row(line):
    s = line.strip()
    if not s:
        return None
    try:
        row = json.loads(s)
    except json.JSONDecodeError:
        return None
    # A header can only follow the scanned offset if the scan raced a
    # NEW dump being appended; everything past it belongs to that later
    # dump, not the one being merged.
    if row.get("kind") == "blackbox_header":
        return _STOP
    return row


def merge_post_mortem_streaming(paths_or_dir, dump_index=-1, tail=512):
    """`merge_post_mortem` for LARGE worlds: same verdicts, streaming
    merge, bounded timeline.

    The eager merge materializes every rank's full event window as
    per-event dicts on one list and sorts it globally — fine at 2-8
    dumps, a multi-gigabyte sort at 256 ranks x 8k events with per-event
    wall/t_ms annotation. Here each file is scanned once in O(1) memory
    (header, event count, stall-cutoff contribution, sortedness), then
    the wall-aligned per-rank streams are re-read lazily from disk
    through one ``heapq.merge`` k-way pass (one open fd per rank):
    root-cause / secondary verdicts, per-rank last progress, and the
    newest ``tail`` timeline entries (annotated only on retention) are
    computed in that pass with O(ranks + tail) live memory — an
    unsorted snapshot (rare) materializes only that rank, bounded by
    one ring tail.

    Returns the `merge_post_mortem` dict with ``timeline`` holding only
    the newest ``tail`` entries plus ``timeline_total`` (the full
    merged event count); :func:`format_post_mortem` renders either.
    """
    paths = collect_paths(paths_or_dir)
    ranks = {}
    for path in paths:
        if dump_index == -1:
            info = _scan_last_dump(path)
        else:
            # Selecting an OLDER dump is a small-scale forensic move —
            # the eager loader is fine there; the scan path exists for
            # the latest-dump fleet merge.
            dump = _load_dump_at(path, dump_index)
            info = None
            if dump is not None:
                events = sorted(dump["events"],
                                key=lambda e: e.get("ts_us", 0))
                cut = None
                for ev in events:
                    if ev.get("type") in ("retry_window", "fault",
                                          "crc_error"):
                        wall = _wall_us(ev, dump["header"])
                        cut = wall if cut is None else min(cut, wall)
                info = {"header": dump["header"], "events": len(events),
                        "cutoff": cut, "_materialized": events}
        if info is None:
            continue
        ranks[info["header"].get("rank", -1)] = info
    if not ranks:
        raise ValueError(f"no black-box dumps found in {paths_or_dir!r}")

    survivors = set(ranks)
    certain, suspected, corrupting = set(), set(), set()
    per_rank = {}
    # Pass 0 came from the file scans: verdict sets off the headers,
    # the stall cutoff (a global MIN — order-free), per-rank event
    # counts. No event is resident yet.
    cutoff = None
    for rank, info in sorted(ranks.items()):
        hdr = info["header"]
        fault = hdr.get("fault", {})
        named = set(fault.get("ranks", []))
        if fault.get("kind") == "corruption":
            corrupting |= named
        elif fault.get("certain"):
            certain |= named
        else:
            suspected |= named
        per_rank[rank] = {
            "epoch": hdr.get("epoch"),
            "fault": fault,
            "events": info["events"],
        }
        if info["cutoff"] is not None:
            cutoff = (info["cutoff"] if cutoff is None
                      else min(cutoff, info["cutoff"]))
    root_cause = sorted((certain - survivors) | corrupting)
    secondary = sorted(((certain | suspected) & survivors) - corrupting)
    if not root_cause:
        root_cause = sorted(suspected - survivors)

    def rank_stream(rank, info):
        if "_materialized" in info:
            hdr = info["header"]
            return ((_wall_us(ev, hdr), rank, ev)
                    for ev in info["_materialized"])
        return _iter_dump_events(info, rank)

    merged = heapq.merge(*(rank_stream(r, i) for r, i in ranks.items()))
    last_progress = {}
    window = deque(maxlen=max(int(tail), 1))
    total = 0
    t0 = None
    # SLO breaches are collected DURING the pass, not from the bounded
    # tail window — a breach early in a long run is exactly the entry
    # the post-mortem must not age out (folding in _fold_slo_breaches).
    breach_rows = []
    for wall, rank, ev in merged:
        total += 1
        if t0 is None:
            t0 = wall
        window.append((wall, rank, ev))
        if ev.get("type") == "slo_breach":
            row = dict(ev)
            row["rank"] = rank
            row["t_ms"] = round((wall - t0) / 1000.0, 3)
            breach_rows.append(row)
        if ev.get("type") not in PROGRESS_TYPES:
            continue
        if cutoff is not None and wall > cutoff:
            continue
        if wall > last_progress.get(rank, float("-inf")):
            last_progress[rank] = wall
    first_stalled = None
    if last_progress:
        first_stalled = min(last_progress, key=last_progress.get)
    for rank, us in last_progress.items():
        per_rank[rank]["last_progress_ms"] = round(
            (us - (t0 or 0)) / 1000.0, 3)

    timeline = []
    for wall, rank, ev in window:
        entry = dict(ev)
        entry["rank"] = rank
        entry["wall_us"] = wall
        entry["t_ms"] = round((wall - (t0 or 0)) / 1000.0, 3)
        timeline.append(entry)

    return {
        "ranks": sorted(survivors),
        "root_cause_ranks": root_cause,
        "secondary_suspects": secondary,
        "first_stalled_rank": first_stalled,
        "per_rank": per_rank,
        "timeline": timeline,
        "timeline_total": total,
        "slo_breaches": _fold_slo_breaches(breach_rows),
    }


def format_post_mortem(analysis, tail=40):
    """Operator-facing text rendering of :func:`merge_post_mortem`."""
    lines = []
    rc = analysis["root_cause_ranks"]
    lines.append(
        f"root cause: rank(s) {rc}" if rc else
        "root cause: none provable (no certain attribution in any dump)")
    if analysis["secondary_suspects"]:
        lines.append("secondary timeouts (suspected but alive): "
                     f"{analysis['secondary_suspects']}")
    if analysis["first_stalled_rank"] is not None:
        lines.append(
            f"first stalled: rank {analysis['first_stalled_rank']} "
            "(earliest last-progress event)")
    for rank, d in sorted(analysis["per_rank"].items()):
        fault = d.get("fault", {})
        lines.append(
            f"  rank {rank}: epoch {d.get('epoch')}, "
            f"{d['events']} events, fault kind={fault.get('kind')} "
            f"certain={fault.get('certain')} ranks={fault.get('ranks')} "
            f"last progress {d.get('last_progress_ms', '-')} ms")
    for b in analysis.get("slo_breaches", []):
        lines.append(f"  slo breach [{b['objective']}] rank "
                     f"{b['breach_rank']} value={b['value']} "
                     f"phase={b['phase']} at {b['t_ms']} ms "
                     f"(recorded by rank {b['source_rank']})")
    total = analysis.get("timeline_total", len(analysis["timeline"]))
    lines.append(f"causal timeline (last {tail} of {total} events):")
    for e in analysis["timeline"][-tail:]:
        args = {k: v for k, v in e.items()
                if k not in ("rank", "wall_us", "t_ms", "ts_us", "seq",
                             "type")}
        lines.append(f"  {e['t_ms']:>10.3f} ms  rank {e['rank']}  "
                     f"{e['type']}  {args}")
    return "\n".join(lines)


# ---- events -> Perfetto -----------------------------------------------


def events_to_trace_events(dump, base_unix_us, tid=990, req_tid=2000):
    """Render one dump's ring events as Chrome-trace events on the
    merged axis (``ts = wall_us - base_unix_us``): ``wire_span``
    becomes a complete ('X') span ending at its record time, everything
    else an instant ('i') — so chunk-level wire activity and heal-ladder
    steps land on the same Perfetto timeline as the per-op spans.

    Serving ``request`` events get PER-REQUEST tracks instead: each
    rid's lifecycle transitions within this dump become named phase
    spans ('X', one row per rid at ``tid = req_tid + rid``) — a
    request's residency across queued/prefill/kv_ship/decode/requeue
    phases reads as one lane on the rank that observed it
    (cross-rank chains are :mod:`reqtrace`'s job; Perfetto shows each
    rank's view)."""
    hdr = dump["header"]
    rank = hdr.get("rank", -1)
    out = [{
        "name": "thread_name", "ph": "M", "pid": rank, "tid": tid,
        "args": {"name": "events"},
    }]
    requests = defaultdict(list)
    for ev in dump["events"]:
        wall = _wall_us(ev, hdr)
        ts = wall - base_unix_us
        args = {k: v for k, v in ev.items()
                if k not in ("ts_us", "seq", "type")}
        if ev.get("type") == "request":
            requests[ev.get("rid", -1)].append((ts, ev))
            continue
        if ev.get("type") == "wire_span":
            dur = max(int(ev.get("dur_us", 0)), 1)
            out.append({"name": f"wire_span p{ev.get('plane', 0)}",
                        "ph": "X", "ts": ts - dur, "dur": dur,
                        "pid": rank, "tid": tid, "args": args})
        else:
            out.append({"name": ev.get("type", "event"), "ph": "i",
                        "ts": ts, "pid": rank, "tid": tid, "s": "t",
                        "args": args})
    for rid, transitions in sorted(requests.items()):
        transitions.sort(key=lambda t: (t[0], t[1].get("seq", 0)))
        rid_tid = req_tid + (rid if isinstance(rid, int)
                             and rid >= 0 else 0)
        out.append({"name": "thread_name", "ph": "M", "pid": rank,
                    "tid": rid_tid, "args": {"name": f"rid {rid}"}})
        for (t0, ev0), (t1, _ev1) in zip(transitions, transitions[1:]):
            out.append({
                "name": ev0.get("phase_name", "request"), "ph": "X",
                "ts": t0, "dur": max(t1 - t0, 1), "pid": rank,
                "tid": rid_tid,
                "args": {"rid": rid, "aux": ev0.get("aux", 0)}})
        last_ts, last_ev = transitions[-1]
        out.append({"name": last_ev.get("phase_name", "request"),
                    "ph": "i", "ts": last_ts, "pid": rank,
                    "tid": rid_tid, "s": "t",
                    "args": {"rid": rid,
                             "aux": last_ev.get("aux", 0)}})
    return out
