"""Declarative SLO engine over per-rank fleet signals (docs/fleet.md).

An *objective* is one line of grammar::

    serving_p99_ms < 250        # breach when the signal rises past 250
    overlap_efficiency > 0.4    # breach when it falls below 0.4
    step_time_ewma_ms drift> 1.5  # breach when it exceeds 1.5x the
                                  # engine's own frozen EWMA baseline
    stall_ms < 500              # ledger stall bucket per window

The signal names are the OBJECTIVES vocabulary — index-ABI with
``csrc/events.h SloObjective`` / ``kSloObjectiveNames`` (pinned in
``analysis/model/abi.py``), because breaches cross into the C event
ring by id: :meth:`SloEngine.record` emits one ``slo_breach`` event per
breach (``hvdtpu_record_slo``) naming the breaching rank and its
dominant rank-seconds bucket, which the black-box dump carries into the
post-mortem fold (telemetry/postmortem.py) and ``autoscale.Signals``
consumes live (``slo_breaches``/``slo_breach_rate``).

Evaluation is PER RANK — each objective is judged against each rank's
own signal value — so breach attribution is exact by construction: the
breaching rank is the rank whose signal breached, never a fleet
average. The engine is a pure function of the observation stream plus
its own drift baselines (the AutoscalePolicy discipline, docs/scale.md)
— no core, no processes, deterministic under replay.
"""

from dataclasses import dataclass

# ONE vocabulary: objective/signal names, index-ABI with csrc/events.h
# SloObjective and kSloObjectiveNames (analysis/model/abi.py pins all
# three sides). Value encoding on the wire is integral: *_ms objectives
# record rounded milliseconds, ratio objectives record permille.
OBJECTIVES = (
    "serving_p99_ms",
    "step_time_ewma_ms",
    "overlap_efficiency",
    "queued_idle_share",
    "stall_ms",
)

# Ratio-valued objectives (breach values recorded as permille; the rest
# are millisecond-valued and recorded as rounded ms).
_RATIO_OBJECTIVES = frozenset(("overlap_efficiency", "queued_idle_share"))

# Drift baselines need this many samples before judging — a cold engine
# must not flag the first observation against an empty baseline.
_DRIFT_WARMUP = 3


@dataclass(frozen=True)
class Objective:
    """One parsed objective. ``op`` is ``"<"`` (breach when the signal
    rises past ``threshold``), ``">"`` (breach when it falls below), or
    ``"drift>"`` (breach when it exceeds ``threshold`` x the engine's
    per-rank EWMA baseline of the same signal)."""

    name: str
    op: str
    threshold: float

    def breached(self, value, baseline=None):
        if self.op == "<":
            return value > self.threshold
        if self.op == ">":
            return value < self.threshold
        # drift>: judged against the engine's baseline (None = still
        # warming up — never a breach).
        if baseline is None or baseline <= 0:
            return False
        return value > self.threshold * baseline


@dataclass(frozen=True)
class Breach:
    """One typed breach: objective name, the breaching rank, the
    observed value, and the rank's dominant rank-seconds bucket
    (``""`` when no ledger rode along)."""

    objective: str
    rank: int
    value: float
    phase: str = ""


def parse(spec):
    """Parse one objective line (grammar in the module docstring).
    Raises ``ValueError`` on an unknown signal name or operator —
    a typo'd SLO must fail loudly, not silently never breach."""
    parts = spec.split()
    if len(parts) != 3:
        raise ValueError(f"SLO objective {spec!r}: expected "
                         f"'<signal> <op> <threshold>'")
    name, op, thr = parts
    if name not in OBJECTIVES:
        raise ValueError(f"SLO objective {spec!r}: unknown signal "
                         f"{name!r} (one of {', '.join(OBJECTIVES)})")
    if op not in ("<", ">", "drift>"):
        raise ValueError(f"SLO objective {spec!r}: unknown operator "
                         f"{op!r} (one of <, >, drift>)")
    return Objective(name, op, float(thr))


def parse_all(specs):
    """Parse an iterable of objective lines (or one ``;``/newline-
    separated string) into a tuple of :class:`Objective`."""
    if isinstance(specs, str):
        specs = [s for chunk in specs.splitlines()
                 for s in chunk.split(";")]
    out = []
    for s in specs:
        s = s.strip()
        if s:
            out.append(s if isinstance(s, Objective) else parse(s))
    return tuple(out)


# The default SLO set the fleet observatory evaluates when the operator
# declares none (HOROVOD_SLO overrides; docs/fleet.md). Thresholds are
# deliberately loose — defaults must flag pathology (a multi-second
# stall, a halved step time), not tuning headroom.
DEFAULT_OBJECTIVES = (
    "serving_p99_ms < 2000",
    "step_time_ewma_ms drift> 2.0",
    "stall_ms < 500",
)


class SloEngine:
    """Evaluate declared objectives against per-rank signal dicts and
    (optionally) record typed breach events into the C event ring."""

    def __init__(self, objectives=DEFAULT_OBJECTIVES, baseline_alpha=0.3):
        self.objectives = parse_all(objectives)
        self.baseline_alpha = float(baseline_alpha)
        # (rank, signal) -> [ewma, samples] for drift> objectives. The
        # baseline only learns from NON-breaching observations (the
        # perfwatch frozen-baseline rule): a sustained regression must
        # not teach the engine that slow is normal.
        self._baselines = {}
        self.breaches = []  # every breach ever evaluated, in order

    def _baseline(self, rank, name):
        ent = self._baselines.get((rank, name))
        if ent is None or ent[1] < _DRIFT_WARMUP:
            return None
        return ent[0]

    def _learn(self, rank, name, value):
        ent = self._baselines.setdefault((rank, name), [float(value), 0])
        a = self.baseline_alpha
        ent[0] = (1 - a) * ent[0] + a * float(value)
        ent[1] += 1

    def evaluate(self, per_rank, phases=None):
        """Judge every objective against every rank's signals.

        ``per_rank`` is ``{rank: {signal_name: value}}`` (missing
        signals are simply not judged — a train-only rank carries no
        ``serving_p99_ms``); ``phases`` optionally maps rank -> its
        dominant rank-seconds bucket name (``fleet.dominant_phase``).
        Returns the new :class:`Breach` list (also appended to
        ``self.breaches``).
        """
        out = []
        for rank in sorted(per_rank):
            signals = per_rank[rank]
            phase = (phases or {}).get(rank, "")
            for obj in self.objectives:
                if obj.name not in signals:
                    continue
                value = float(signals[obj.name])
                if obj.op == "drift>":
                    base = self._baseline(rank, obj.name)
                    hit = obj.breached(value, base)
                    if not hit:
                        self._learn(rank, obj.name, value)
                else:
                    hit = obj.breached(value)
                if hit:
                    out.append(Breach(obj.name, int(rank), value, phase))
        self.breaches.extend(out)
        return out

    def record(self, basics, breaches):
        """Emit one ``slo_breach`` ring event per breach through
        ``hvdtpu_record_slo`` (ids resolved against the pinned
        OBJECTIVES / fleet.BUCKETS tables). Safe before ``init()``."""
        from horovod_tpu.telemetry import fleet

        for b in breaches:
            value = (int(round(b.value * 1000))
                     if b.objective in _RATIO_OBJECTIVES
                     else int(round(b.value)))
            bucket = (fleet.BUCKETS.index(b.phase)
                      if b.phase in fleet.BUCKETS else -1)
            basics.record_slo(OBJECTIVES.index(b.objective), b.rank,
                              value, bucket)
        return len(breaches)
