"""Elastic training: the worker-side retry loop and state machinery.

Reference analog: ``horovod/common/elastic.py`` (``State``,
``ObjectState``, ``run_fn``) + §3.4 of SURVEY.md: training wraps in
``@hvd.elastic.run``; a failed collective raises ``HorovodInternalError``
→ restore last commit; a topology change raises ``HostsUpdatedInterrupt``
→ re-rendezvous without rollback. ``reset()`` tears the core down and
re-initializes against the driver's rendezvous (new rank/size/epoch).
"""

import copy
import os
import socket
import uuid

from horovod_tpu.common import eager_ops
from horovod_tpu.common.basics import HorovodBasics
from horovod_tpu.common.exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
)

_basics = HorovodBasics()


def _is_elastic():
    return bool(os.environ.get("HOROVOD_RDZV_ADDR"))


# Frontends register topology-dependent re-initialization here (e.g. the
# jax frontend re-attempts xla_ici enable); reset() runs them after the
# new epoch's core is up.
_post_reset_hooks = []


def register_post_reset_hook(fn):
    if fn not in _post_reset_hooks:
        _post_reset_hooks.append(fn)


def unregister_post_reset_hook(fn):
    try:
        _post_reset_hooks.remove(fn)
    except ValueError:
        pass


def _worker_id():
    wid = os.environ.get("HOROVOD_WORKER_ID")
    if not wid:
        wid = f"{socket.gethostname()}:{uuid.uuid4().hex[:8]}"
        os.environ["HOROVOD_WORKER_ID"] = wid
    return wid


def survivors():
    """Rank-consistent survivor list for the CURRENT fault, or ``None``
    when no driver-less agreement is possible.

    THE r12 gotcha, codified: the list is derived from
    ``last_fault()["ranks"]`` gated on ``["certain"]`` — never from
    per-rank suspicion, because a timeout may name a different live
    neighbor on each rank and split-brain the re-formation rendezvous.
    The only exception is a 2-rank world, where the suspect is
    necessarily the only other rank. Every survivor computes the
    IDENTICAL list (the core's socket probe sweep converges on the same
    provably-dead set), which is exactly what ``reinit`` requires.
    Returns ``None`` (use the full re-initialization path) when there
    is no unrecovered fault, the record is suspicion-only at size > 2,
    or the fault is wire corruption (the peer is alive — shrinking it
    out would be wrong).
    """
    if not _basics.is_initialized():
        return None
    fault = _basics.last_fault()
    if fault is None or fault.get("recovered"):
        return None
    if fault.get("kind") == "corruption":
        # The "dead" rank is a live peer behind a corrupting link:
        # shrinking it out would evict a healthy worker.
        return None
    dead = {int(r) for r in fault.get("ranks") or ()}
    size = _basics.size()
    if not dead or not (fault.get("certain") or size == 2):
        return None
    return [r for r in range(size) if r not in dead]


# ---- blacklist parole: the rejoin door (docs/elastic.md) -------------
# Driver-less scale-up: rank 0 keeps a TCP "door" open
# (HOROVOD_REJOIN_PORT on every rank enables it). A returning host
# connects, says hello, and is held on parole; at the next epoch
# transition every survivor asks the door for the epoch's FROZEN joiner
# count (frozen once per target epoch, so all survivors agree), the
# world re-forms with that many -1 slots, and the door releases each
# joiner its assignment (rank/size/epoch/controller endpoint) so it can
# initialize straight into the regrown ring via HOROVOD_JOIN_EPOCH.


def _rejoin_port():
    port = os.environ.get("HOROVOD_REJOIN_PORT")
    return int(port) if port else 0


def _rejoin_addr():
    return os.environ.get(
        "HOROVOD_REJOIN_ADDR",
        os.environ.get("HOROVOD_CONTROLLER_ADDR", "127.0.0.1"))


class _ParoleDoor:
    """Rank 0's rejoin listener. Hellos are held pending; ``freeze``
    snapshots the pending set per target epoch (idempotent — the
    survivor-agreement primitive); ``release`` hands each frozen joiner
    its assignment."""

    def __init__(self, port):
        import threading

        self._lock = threading.Lock()
        self._pending = []   # [(conn, hello)]
        self._frozen = {}    # epoch -> [(conn, hello)]; NEVER popped —
        self._released = set()  # a survivor may poll the count AFTER
        # rank 0 released the assignments, and must still see the same
        # number (the agreement would otherwise split-brain the
        # re-formation world size).
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("", port))
        self._sock.listen(16)
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        import threading

        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        import json

        try:
            conn.settimeout(30)
            msg = json.loads(conn.makefile("r").readline())
        except (OSError, ValueError):
            conn.close()
            return
        if msg.get("op") == "hello":
            conn.settimeout(None)
            with self._lock:
                self._pending.append((conn, msg))
        elif msg.get("op") == "poll":
            count = self.freeze(int(msg["epoch"]))
            try:
                conn.sendall(
                    (json.dumps({"count": count}) + "\n").encode())
            except OSError:
                pass
            finally:
                conn.close()
        else:
            conn.close()

    def pending_count(self):
        with self._lock:
            return len(self._pending)

    def freeze(self, epoch):
        with self._lock:
            if epoch not in self._frozen:
                self._frozen[epoch] = self._pending
                self._pending = []
            return len(self._frozen[epoch])

    def release(self, epoch, assignments):
        import json

        with self._lock:
            if epoch in self._released:
                return
            self._released.add(epoch)
            held = list(self._frozen.get(epoch, ()))
        for (conn, _), asg in zip(held, assignments):
            try:
                conn.sendall((json.dumps(asg) + "\n").encode())
            except OSError:
                pass
            finally:
                conn.close()


_door = None


def _ensure_door():
    """Open the door on rank 0 when parole is enabled (idempotent)."""
    global _door
    if (_door is None and _rejoin_port() and _basics.is_initialized()
            and _basics.rank() == 0):
        _door = _ParoleDoor(_rejoin_port())
    return _door


# Per-epoch poll counter: the commit-time rejoin check is a collective,
# so its tensor name must match on every rank — including a joiner whose
# process-lifetime counter starts fresh. (epoch, n) resets n at every
# epoch transition, which all members observe together.
_rejoin_poll_state = {"epoch": None, "n": 0}


def _poll_rejoiners():
    """Commit-time scale-up check (driver-less only): the agreed count
    of paroled joiners waiting at the door. Collective — rank 0's local
    count is MAX-reduced so every rank raises (or not) at the SAME
    step; an inconsistent per-rank decision would desynchronize the
    SPMD loop and fault it.

    ``HOROVOD_REJOIN_POLL=0`` disables the commit-time check (and its
    per-commit collective): joiners are then absorbed only at
    fault-driven epoch transitions — the "never interrupt healthy
    training" policy."""
    if _is_elastic() or not _rejoin_port() or not _basics.is_initialized():
        return 0
    if os.environ.get("HOROVOD_REJOIN_POLL", "1") == "0":
        return 0
    door = _ensure_door()
    local = door.pending_count() if door is not None else 0
    if _basics.size() == 1:
        return local
    import numpy as np

    state = _rejoin_poll_state
    epoch = _basics.epoch()
    if state["epoch"] != epoch:
        state["epoch"] = epoch
        state["n"] = 0
    name = f"elastic.rejoin_poll.{epoch}.{state['n']}"
    state["n"] += 1
    out = eager_ops.allreduce_async(
        np.array([local], dtype=np.int64), name,
        op=eager_ops.ReduceOp.MAX).synchronize()
    return int(out[0])


def _freeze_joiners(target_epoch):
    """The frozen joiner count for ``target_epoch`` — identical on
    every survivor (the door freezes once per epoch; rank 0 asks
    in-process, the rest over TCP). The freeze/poll latency lands on
    the control-plane phase profile (``parole_freeze``,
    docs/scale.md): it sits on the epoch-transition critical path and
    its TCP round is an O(survivors) suspect at large worlds."""
    import time as _time

    t0 = _time.monotonic()
    try:
        return _freeze_joiners_inner(target_epoch)
    finally:
        _basics.record_phase("parole_freeze",
                             int((_time.monotonic() - t0) * 1e6))


def _freeze_joiners_inner(target_epoch):
    if _is_elastic() or not _rejoin_port():
        return 0
    if _basics.rank() == 0:
        door = _ensure_door()
        return door.freeze(target_epoch) if door is not None else 0
    import json
    import time as _time

    # The count MUST match rank 0's or the re-formation world sizes
    # split-brain (mismatched rendezvous -> -4 -> full re-init
    # everywhere, stranding any released joiner). Retry transient door
    # failures before giving up; a persistently unreachable door (rank
    # 0's process gone) legitimately means "no joiners" — the full
    # re-init fallback is the right recovery there anyway.
    for attempt in range(3):
        try:
            with socket.create_connection(
                    (_rejoin_addr(), _rejoin_port()), timeout=10) as s:
                s.sendall((json.dumps(
                    {"op": "poll", "epoch": target_epoch}) + "\n").encode())
                s.settimeout(10)
                line = s.makefile("r").readline()
            return int(json.loads(line)["count"])
        except (OSError, ValueError):
            if attempt == 2:
                import warnings

                warnings.warn(
                    "rejoin-door poll failed 3x; assuming 0 joiners for "
                    f"epoch {target_epoch} (world-size agreement may "
                    "degrade to the full re-init fallback)",
                    RuntimeWarning, stacklevel=2)
                return 0
            _time.sleep(0.2 * (attempt + 1))
    return 0


def rejoin(addr=None, port=None, timeout=None):
    """Blacklist parole, joiner side (docs/elastic.md): re-enter a
    driver-less elastic job as a FRESH process after this host's old
    rank was fenced out (or to scale the world up).

    Connects to the survivors' rejoin door, waits to be absorbed by
    their next epoch transition, then initializes the core straight
    into the regrown ring at the assigned rank/epoch. Returns the
    assignment dict. Training state flows in through the normal
    ``hvd.elastic.run`` path: the first ``state.sync()`` broadcasts the
    survivors' last commit (``parallel.reshard.reshard_rows``
    re-balances row-sharded/ZeRO state)."""
    import json

    addr = addr or _rejoin_addr()
    port = int(port or _rejoin_port())
    if not port:
        raise ValueError(
            "rejoin needs HOROVOD_REJOIN_PORT (or port=) — the door the "
            "survivors' rank 0 keeps open")
    if timeout is None:
        timeout = float(os.environ.get("HOROVOD_START_TIMEOUT", 60))
    s = socket.create_connection((addr, port), timeout=timeout)
    try:
        s.sendall((json.dumps({"op": "hello", "worker": _worker_id(),
                               "host": socket.gethostname()})
                   + "\n").encode())
        s.settimeout(timeout)
        line = s.makefile("r").readline()
    finally:
        s.close()
    if not line:
        raise RuntimeError(
            "rejoin door closed without an assignment (no epoch "
            "transition absorbed this worker within the timeout)")
    asg = json.loads(line)
    os.environ.update({
        "HOROVOD_RANK": str(asg["rank"]),
        "HOROVOD_SIZE": str(asg["size"]),
        "HOROVOD_LOCAL_RANK": str(asg["rank"]),
        "HOROVOD_LOCAL_SIZE": str(asg["size"]),
        "HOROVOD_CROSS_RANK": "0",
        "HOROVOD_CROSS_SIZE": "1",
        "HOROVOD_CONTROLLER_ADDR": asg["controller_addr"],
        "HOROVOD_CONTROLLER_PORT": str(asg["controller_port"]),
        "HOROVOD_JOIN_EPOCH": str(asg["epoch"]),
    })
    try:
        _basics.init()
    finally:
        os.environ.pop("HOROVOD_JOIN_EPOCH", None)  # one-shot
    return asg


def shrink(victims):
    """Voluntary world shrink (the autoscaler's scale-down leg,
    docs/scale.md): re-form the ring WITHOUT ``victims`` at the next
    epoch — no fault, no blacklist, the negotiated-shutdown drain keeps
    every in-flight collective intact.

    Collective: every rank (victims included) must call it at the same
    logical point with the SAME victim set — the drain is a negotiated
    shutdown, so the survivors' reinit blocks until every rank's
    shutdown bit (a victim's arrives via its full ``shutdown()``) has
    reached the coordinator. Survivors return True at the new epoch;
    a victim tears its core down and returns False — the process is
    free to exit, or to knock on the parole door later when the
    autoscaler grows the world again (``hvd.elastic.rejoin``).
    """
    victims = {int(v) for v in victims}
    size = _basics.size()
    rank = _basics.rank()
    bad = [v for v in victims if v < 0 or v >= size]
    if bad or len(victims) >= size:
        raise ValueError(
            f"shrink(victims={sorted(victims)}): victims must be a "
            f"proper subset of range({size})")
    if rank in victims:
        _basics.shutdown()
        return False
    target_epoch = int(_basics.epoch()) + 1
    _disable_xla_ici()
    _basics.reinit([r for r in range(size) if r not in victims],
                   target_epoch)
    for hook in _post_reset_hooks:
        hook()
    return True


def init():
    """Initialize the core; in elastic mode, first obtain this epoch's rank
    assignment from the driver's rendezvous server."""
    if not _is_elastic():
        # Bare-mpirun launch (no horovodrun, no env): derive identity and
        # the rendezvous endpoint from the MPI world if one is running
        # (reference analog: initializing on an existing MPI_COMM_WORLD,
        # common/mpi/mpi_context.cc). HOROVOD_CONTROLLER=mpi goes
        # further: control AND ring data ride mpi4py point-to-point —
        # zero TCP sockets (firewalled MPI-only fabrics).
        from horovod_tpu.common.mpi_bootstrap import (
            bootstrap_mpi_control,
            maybe_bootstrap_from_mpi,
        )

        if not bootstrap_mpi_control():
            maybe_bootstrap_from_mpi()
        _basics.init()
        _ensure_door()  # blacklist parole (HOROVOD_REJOIN_PORT)
        return
    from horovod_tpu.runner.elastic.rendezvous import RendezvousClient
    from horovod_tpu.runner.elastic.worker import notification_manager

    client = RendezvousClient(os.environ["HOROVOD_RDZV_ADDR"],
                              os.environ["HOROVOD_RDZV_PORT"])
    notify_port = notification_manager.init()
    last_epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", 0))
    client.register(_worker_id(), os.environ.get("HOROVOD_HOSTNAME",
                                                 socket.gethostname()),
                    int(os.environ.get("HOROVOD_LOCAL_RANK", 0)),
                    notify_port, last_epoch=last_epoch)
    timeout = float(os.environ.get("HOROVOD_START_TIMEOUT", 60))
    asg = client.poll_assignment(_worker_id(), timeout,
                                 min_epoch=last_epoch + 1)
    os.environ["HOROVOD_ELASTIC_EPOCH"] = str(asg["epoch"])
    os.environ.update({
        "HOROVOD_RANK": str(asg["rank"]),
        "HOROVOD_SIZE": str(asg["size"]),
        "HOROVOD_LOCAL_RANK": str(asg["local_rank"]),
        "HOROVOD_LOCAL_SIZE": str(asg["local_size"]),
        "HOROVOD_CROSS_RANK": str(asg["cross_rank"]),
        "HOROVOD_CROSS_SIZE": str(asg["cross_size"]),
        "HOROVOD_CONTROLLER_ADDR": asg["controller_addr"],
        "HOROVOD_CONTROLLER_PORT": str(asg["controller_port"]),
    })
    _basics.init()


def _disable_xla_ici():
    # The xla_ici device data plane binds the OLD topology (mesh size,
    # jax.distributed world); its callback must not survive into the new
    # epoch. sys.modules check so torch/tf-only elastic processes never
    # pull jax in here. The jax frontend's post-reset hook re-attempts
    # enable for the new epoch (succeeds when the world size is unchanged;
    # warns or raises otherwise — jax.distributed cannot re-initialize
    # with a different world in-process).
    import sys

    xla_ici = sys.modules.get("horovod_tpu.jax.xla_ici")
    if xla_ici is not None:
        xla_ici.disable()


def _reset_driverless():
    """Driver-less epoch transition, shrink AND grow in one in-place
    re-formation: survivors agree on the dead set from the core's fault
    record (via :func:`survivors` — the socket probe sweep makes
    SIGKILLed peers visible identically everywhere), drop them, absorb
    any paroled joiners frozen at the door, and re-form via
    ``hvdtpu_reinit`` at the next epoch — no process restart, no
    checkpoint round-trip. Also handles the pure scale-up case (healthy
    loop interrupted by a pending joiner). Returns True when this path
    applied; False defers to the full shutdown+init path.

    Limits (docs/elastic.md): the coordinator of the new epoch is the
    lowest surviving old rank, reached at the SAME
    ``HOROVOD_CONTROLLER_ADDR`` — so without a driver, rank 0's host
    must survive (always true on single-host jobs; the driver's
    re-rendezvous covers host loss).
    """
    if not _basics.is_initialized():
        return False
    faulted = bool(_basics.lib.hvdtpu_loop_failed())
    if faulted:
        alive = survivors()
        if alive is None:
            # Suspicion-only (or corruption) at size > 2: no rank-
            # consistent survivor set exists. Full re-init recovers
            # without risking a split-brain shrink.
            return False
        fault = _basics.last_fault()
        old_rank = _basics.rank()
        if old_rank not in alive:
            # Deliberately NOT a HorovodInternalError: being fenced out
            # is terminal for this process, not a recoverable collective
            # failure — it must escape the elastic retry loop. The host
            # can come back through the parole door (hvd.elastic.rejoin)
            # as a fresh process.
            raise RuntimeError(
                f"rank {old_rank} was declared dead by its peers "
                f"(fault: {fault.get('reason')}); cannot rejoin epoch "
                f"{fault.get('epoch', 0) + 1} in-process — restart and "
                "use hvd.elastic.rejoin() (blacklist parole)")
        target_epoch = int(fault.get("epoch", 0)) + 1
    else:
        alive = list(range(_basics.size()))
        target_epoch = int(_basics.epoch()) + 1
    joiners = _freeze_joiners(target_epoch)
    if not faulted and joiners == 0:
        return False  # nothing to do in place; take the full path
    new_world = alive + [-1] * joiners
    if joiners > 0 and _basics.rank() == 0 and _door is not None:
        # Assignments go out BEFORE the (blocking) rendezvous so the
        # joiners can reach it. Joiner slots take the top new ranks.
        _door.release(target_epoch, [
            {"rank": len(alive) + i,
             "size": len(new_world),
             "epoch": target_epoch,
             "controller_addr": os.environ.get(
                 "HOROVOD_CONTROLLER_ADDR", "127.0.0.1"),
             "controller_port": int(os.environ.get(
                 "HOROVOD_CONTROLLER_PORT", 29500))}
            for i in range(joiners)])
    _disable_xla_ici()
    try:
        _basics.reinit(new_world, target_epoch)
    except RuntimeError as e:
        # The re-formation rendezvous itself failed (e.g. another
        # survivor died mid-recovery, or a paroled joiner vanished
        # before connecting). The core restored the pre-attempt state;
        # fall back to the full shutdown+init path instead of killing
        # the job.
        import warnings

        warnings.warn(f"in-place ring re-formation failed ({e}); "
                      "falling back to full re-initialization",
                      RuntimeWarning, stacklevel=2)
        return False
    return True


def reset():
    """Tear down and re-form/re-rendezvous (elastic epoch transition).

    Three paths, in order: (1) driver mode re-rendezvouses against the
    elastic driver (new rank/size/epoch env); (2) without a driver, a
    core-reported peer fault and/or a paroled joiner re-forms the ring
    IN PLACE over survivors + joiner slots (``hvdtpu_reinit`` — no
    process restart; the heal-vs-shrink-vs-rejoin table lives in
    docs/elastic.md); (3) otherwise full shutdown + init at the same
    world.
    """
    if not _is_elastic() and _reset_driverless():
        for hook in _post_reset_hooks:
            hook()
        return
    _basics.shutdown()
    _disable_xla_ici()
    init()
    for hook in _post_reset_hooks:
        hook()


def _poll_hosts_updated():
    if not _is_elastic():
        return False, False
    from horovod_tpu.runner.elastic.worker import notification_manager

    return notification_manager.poll_hosts_updated()


class State:
    """Base elastic state: commit/restore/sync + reset callbacks.

    Reference analog: horovod/common/elastic.py State.
    """

    def __init__(self):
        self._reset_callbacks = []

    def register_reset_callbacks(self, callbacks):
        """Callbacks run after every re-rendezvous (e.g. rescale the
        learning rate to the new world size)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def commit(self):
        """Checkpoint to (host) memory and surface any pending topology
        change as HostsUpdatedInterrupt — the reference's commit contract."""
        self.save()
        self.check_host_updates()

    def check_host_updates(self):
        updated, skip_sync = _poll_hosts_updated()
        if updated:
            raise HostsUpdatedInterrupt(skip_sync)
        # Driver-less scale-up: a paroled joiner at the door interrupts
        # every rank at the same commit (the poll is a collective), and
        # reset() regrows the world in place.
        if _poll_rejoiners() > 0:
            raise HostsUpdatedInterrupt(False)

    # Subclass surface:
    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass


def _broadcast_object(obj, root_rank=0, name="elastic.obj",
                      process_set_id=0):
    """Pickle-broadcast via two eager broadcasts (length, then payload).
    Only the root pickles; other ranks' ``obj`` is never serialized."""
    import pickle

    import numpy as np

    if _basics.rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    else:
        payload = np.zeros(0, dtype=np.uint8)
    n = eager_ops.broadcast_async(
        np.array([payload.size], dtype=np.int64), root_rank,
        f"{name}.len", process_set_id=process_set_id).synchronize()[0]
    buf = payload if _basics.rank() == root_rank else np.zeros(
        int(n), dtype=np.uint8)
    out = eager_ops.broadcast_async(
        buf, root_rank, f"{name}.payload",
        process_set_id=process_set_id).synchronize()
    return pickle.loads(out.tobytes())


def _allgather_object(obj, name="allgather.obj", process_set_id=0):
    """Pickle-gather an arbitrary object from every rank: list indexed by
    rank. Shared wire protocol (length vector, then concatenated payload)
    for every frontend's ``allgather_object``."""
    import pickle

    import numpy as np

    payload = np.frombuffer(pickle.dumps(obj, pickle.HIGHEST_PROTOCOL),
                            dtype=np.uint8)
    sizes = eager_ops.allgather_async(
        np.array([payload.size], dtype=np.int64), f"{name}.len",
        process_set_id=process_set_id).synchronize()
    data = eager_ops.allgather_async(
        payload, f"{name}.data",
        process_set_id=process_set_id).synchronize()
    out, off = [], 0
    for s in sizes:
        out.append(pickle.loads(data[off:off + int(s)].tobytes()))
        off += int(s)
    return out


def _sync_state(state, name, attr="_saved"):
    """Shared sync protocol for State subclasses that keep their snapshot
    in one attribute: rank 0 snapshots, everyone adopts its broadcast,
    then restores. No-op at size 1."""
    if _basics.size() == 1:
        return
    if _basics.rank() == 0:
        state.save()  # non-root snapshots are overwritten below
    setattr(state, attr,
            _broadcast_object(getattr(state, attr), name=name))
    state.restore()


class ObjectState(State):
    """Elastic state over arbitrary picklable attributes.

    Reference analog: horovod/common/elastic.py ObjectState — attributes
    set via kwargs are committed/restored/synced as one pickled unit.
    """

    def __init__(self, **kwargs):
        super().__init__()
        self._saved_state = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def save(self):
        self._saved_state = {
            k: copy.deepcopy(getattr(self, k)) for k in self._saved_state}

    def restore(self):
        for k, v in self._saved_state.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        _sync_state(self, "elastic.object_state", attr="_saved_state")


def _is_internal_error(exc):
    """HorovodInternalError, possibly wrapped: frameworks that run our
    ops inside their own executors re-raise with the original only in
    the message/cause chain (e.g. tf.py_function surfaces it as
    tf.errors.UnknownError whose message embeds the repr)."""
    # The textual fallback only fires for known framework wrapper types:
    # a user RuntimeError that merely *mentions* the class name must not
    # be swallowed into a silent restore/retry loop.
    def _is_framework_wrapper(e):
        return any(cls.__module__.startswith("tensorflow.")
                   and cls.__name__ == "OpError"
                   for cls in type(e).__mro__)

    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, HorovodInternalError):
            return True
        txt = str(exc)
        if _is_framework_wrapper(exc) and (
                "HorovodInternalError:" in txt
                or "HorovodInternalError(" in txt):
            import warnings

            warnings.warn(
                "elastic recovery triggered by textual match inside a "
                f"framework-wrapped error ({type(exc).__name__}); the "
                "original HorovodInternalError was not in the __cause__ "
                "chain", RuntimeWarning, stacklevel=3)
            return True
        # Walk explicit `raise ... from X` chains only. Implicit
        # __context__ must not count: `except HorovodInternalError:
        # raise RuntimeError("aborting")` is a deliberate abort, not a
        # recoverable failure.
        exc = exc.__cause__
    return False


def run_fn(func):
    """Wrap an elastic train function: sync → run → recover loop.

    Reference analog: horovod/common/elastic.py run_fn. Usage::

        @hvd.elastic.run
        def train(state, ...): ...
    """

    def wrapper(state, *args, **kwargs):
        skip_sync = False
        while True:
            # sync() runs collectives, so it sits INSIDE the recovery
            # scope: a host lost right after reset must loop, not raise.
            try:
                if not skip_sync:
                    state.sync()
                return func(state, *args, **kwargs)
            except HostsUpdatedInterrupt as e:
                skip_sync = e.skip_sync
            except Exception as e:  # noqa: BLE001 — see _is_internal_error
                if not _is_internal_error(e):
                    raise
                if os.environ.get("HOROVOD_ELASTIC_VERBOSE"):
                    import traceback

                    print(f"[elastic] recovering from: {e!r}",
                          file=__import__('sys').stderr)
                    traceback.print_exc()
                state.restore()
                skip_sync = False
            reset()
            state.on_reset()

    return wrapper
