"""Elastic training: the worker-side retry loop and state machinery.

Reference analog: ``horovod/common/elastic.py`` (``State``,
``ObjectState``, ``run_fn``) + §3.4 of SURVEY.md: training wraps in
``@hvd.elastic.run``; a failed collective raises ``HorovodInternalError``
→ restore last commit; a topology change raises ``HostsUpdatedInterrupt``
→ re-rendezvous without rollback. ``reset()`` tears the core down and
re-initializes against the driver's rendezvous (new rank/size/epoch).
"""

import copy
import os
import socket
import uuid

from horovod_tpu.common import eager_ops
from horovod_tpu.common.basics import HorovodBasics
from horovod_tpu.common.exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
)

_basics = HorovodBasics()


def _is_elastic():
    return bool(os.environ.get("HOROVOD_RDZV_ADDR"))


# Frontends register topology-dependent re-initialization here (e.g. the
# jax frontend re-attempts xla_ici enable); reset() runs them after the
# new epoch's core is up.
_post_reset_hooks = []


def register_post_reset_hook(fn):
    if fn not in _post_reset_hooks:
        _post_reset_hooks.append(fn)


def unregister_post_reset_hook(fn):
    try:
        _post_reset_hooks.remove(fn)
    except ValueError:
        pass


def _worker_id():
    wid = os.environ.get("HOROVOD_WORKER_ID")
    if not wid:
        wid = f"{socket.gethostname()}:{uuid.uuid4().hex[:8]}"
        os.environ["HOROVOD_WORKER_ID"] = wid
    return wid


def init():
    """Initialize the core; in elastic mode, first obtain this epoch's rank
    assignment from the driver's rendezvous server."""
    if not _is_elastic():
        # Bare-mpirun launch (no horovodrun, no env): derive identity and
        # the rendezvous endpoint from the MPI world if one is running
        # (reference analog: initializing on an existing MPI_COMM_WORLD,
        # common/mpi/mpi_context.cc). HOROVOD_CONTROLLER=mpi goes
        # further: control AND ring data ride mpi4py point-to-point —
        # zero TCP sockets (firewalled MPI-only fabrics).
        from horovod_tpu.common.mpi_bootstrap import (
            bootstrap_mpi_control,
            maybe_bootstrap_from_mpi,
        )

        if not bootstrap_mpi_control():
            maybe_bootstrap_from_mpi()
        _basics.init()
        return
    from horovod_tpu.runner.elastic.rendezvous import RendezvousClient
    from horovod_tpu.runner.elastic.worker import notification_manager

    client = RendezvousClient(os.environ["HOROVOD_RDZV_ADDR"],
                              os.environ["HOROVOD_RDZV_PORT"])
    notify_port = notification_manager.init()
    last_epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", 0))
    client.register(_worker_id(), os.environ.get("HOROVOD_HOSTNAME",
                                                 socket.gethostname()),
                    int(os.environ.get("HOROVOD_LOCAL_RANK", 0)),
                    notify_port, last_epoch=last_epoch)
    timeout = float(os.environ.get("HOROVOD_START_TIMEOUT", 60))
    asg = client.poll_assignment(_worker_id(), timeout,
                                 min_epoch=last_epoch + 1)
    os.environ["HOROVOD_ELASTIC_EPOCH"] = str(asg["epoch"])
    os.environ.update({
        "HOROVOD_RANK": str(asg["rank"]),
        "HOROVOD_SIZE": str(asg["size"]),
        "HOROVOD_LOCAL_RANK": str(asg["local_rank"]),
        "HOROVOD_LOCAL_SIZE": str(asg["local_size"]),
        "HOROVOD_CROSS_RANK": str(asg["cross_rank"]),
        "HOROVOD_CROSS_SIZE": str(asg["cross_size"]),
        "HOROVOD_CONTROLLER_ADDR": asg["controller_addr"],
        "HOROVOD_CONTROLLER_PORT": str(asg["controller_port"]),
    })
    _basics.init()


def _disable_xla_ici():
    # The xla_ici device data plane binds the OLD topology (mesh size,
    # jax.distributed world); its callback must not survive into the new
    # epoch. sys.modules check so torch/tf-only elastic processes never
    # pull jax in here. The jax frontend's post-reset hook re-attempts
    # enable for the new epoch (succeeds when the world size is unchanged;
    # warns or raises otherwise — jax.distributed cannot re-initialize
    # with a different world in-process).
    import sys

    xla_ici = sys.modules.get("horovod_tpu.jax.xla_ici")
    if xla_ici is not None:
        xla_ici.disable()


def _reinit_survivors():
    """Driver-less recovery: survivors agree on the dead set from the
    core's fault record (the socket probe sweep makes SIGKILLed peers
    visible identically on every survivor), drop them, and re-form the
    N-1 ring in place via ``hvdtpu_reinit`` at the next epoch — no
    process restart, no checkpoint round-trip. Returns True when this
    path applied; False defers to the full shutdown+init path.

    Limits (docs/elastic.md): the coordinator of the new epoch is the
    lowest surviving old rank, reached at the SAME
    ``HOROVOD_CONTROLLER_ADDR`` — so without a driver, rank 0's host
    must survive (always true on single-host jobs; the driver's
    re-rendezvous covers host loss).
    """
    if not _basics.is_initialized() or not _basics.lib.hvdtpu_loop_failed():
        return False
    fault = _basics.last_fault()
    if fault is None or fault.get("recovered"):
        return False
    dead = {int(r) for r in fault.get("ranks") or ()}
    old_size, old_rank = _basics.size(), _basics.rank()
    # Driver-less re-formation needs every survivor to derive the SAME
    # survivor set. Only PROVEN attribution (EOF/RST/probe — "certain")
    # guarantees that; a timeout suspicion may name a different live
    # neighbor on each rank and split-brain the rendezvous. Exception:
    # at size 2 the suspected peer is necessarily the only other rank.
    if not dead or not (fault.get("certain") or old_size == 2):
        return False
    survivors = [r for r in range(old_size) if r not in dead]
    if old_rank in dead or not survivors:
        # Deliberately NOT a HorovodInternalError: being fenced out is
        # terminal for this process, not a recoverable collective
        # failure — it must escape the elastic retry loop.
        raise RuntimeError(
            f"rank {old_rank} was declared dead by its peers "
            f"(fault: {fault.get('reason')}); cannot rejoin epoch "
            f"{fault.get('epoch', 0) + 1} in-process")
    _disable_xla_ici()
    try:
        _basics.reinit(survivors, int(fault.get("epoch", 0)) + 1)
    except RuntimeError as e:
        # The re-formation rendezvous itself failed (e.g. another
        # survivor died mid-recovery). The core restored the
        # pre-attempt state; fall back to the full shutdown+init path
        # instead of killing the job.
        import warnings

        warnings.warn(f"in-place ring re-formation failed ({e}); "
                      "falling back to full re-initialization",
                      RuntimeWarning, stacklevel=2)
        return False
    return True


def reset():
    """Tear down and re-form/re-rendezvous (elastic epoch transition).

    Three paths, in order: (1) driver mode re-rendezvouses against the
    elastic driver (new rank/size/epoch env); (2) without a driver, a
    core-reported peer fault re-forms the ring over survivors IN PLACE
    (``hvdtpu_reinit`` — no process restart); (3) otherwise full
    shutdown + init at the same world.
    """
    if not _is_elastic() and _reinit_survivors():
        for hook in _post_reset_hooks:
            hook()
        return
    _basics.shutdown()
    _disable_xla_ici()
    init()
    for hook in _post_reset_hooks:
        hook()


def _poll_hosts_updated():
    if not _is_elastic():
        return False, False
    from horovod_tpu.runner.elastic.worker import notification_manager

    return notification_manager.poll_hosts_updated()


class State:
    """Base elastic state: commit/restore/sync + reset callbacks.

    Reference analog: horovod/common/elastic.py State.
    """

    def __init__(self):
        self._reset_callbacks = []

    def register_reset_callbacks(self, callbacks):
        """Callbacks run after every re-rendezvous (e.g. rescale the
        learning rate to the new world size)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def commit(self):
        """Checkpoint to (host) memory and surface any pending topology
        change as HostsUpdatedInterrupt — the reference's commit contract."""
        self.save()
        self.check_host_updates()

    def check_host_updates(self):
        updated, skip_sync = _poll_hosts_updated()
        if updated:
            raise HostsUpdatedInterrupt(skip_sync)

    # Subclass surface:
    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass


def _broadcast_object(obj, root_rank=0, name="elastic.obj",
                      process_set_id=0):
    """Pickle-broadcast via two eager broadcasts (length, then payload).
    Only the root pickles; other ranks' ``obj`` is never serialized."""
    import pickle

    import numpy as np

    if _basics.rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    else:
        payload = np.zeros(0, dtype=np.uint8)
    n = eager_ops.broadcast_async(
        np.array([payload.size], dtype=np.int64), root_rank,
        f"{name}.len", process_set_id=process_set_id).synchronize()[0]
    buf = payload if _basics.rank() == root_rank else np.zeros(
        int(n), dtype=np.uint8)
    out = eager_ops.broadcast_async(
        buf, root_rank, f"{name}.payload",
        process_set_id=process_set_id).synchronize()
    return pickle.loads(out.tobytes())


def _allgather_object(obj, name="allgather.obj", process_set_id=0):
    """Pickle-gather an arbitrary object from every rank: list indexed by
    rank. Shared wire protocol (length vector, then concatenated payload)
    for every frontend's ``allgather_object``."""
    import pickle

    import numpy as np

    payload = np.frombuffer(pickle.dumps(obj, pickle.HIGHEST_PROTOCOL),
                            dtype=np.uint8)
    sizes = eager_ops.allgather_async(
        np.array([payload.size], dtype=np.int64), f"{name}.len",
        process_set_id=process_set_id).synchronize()
    data = eager_ops.allgather_async(
        payload, f"{name}.data",
        process_set_id=process_set_id).synchronize()
    out, off = [], 0
    for s in sizes:
        out.append(pickle.loads(data[off:off + int(s)].tobytes()))
        off += int(s)
    return out


def _sync_state(state, name, attr="_saved"):
    """Shared sync protocol for State subclasses that keep their snapshot
    in one attribute: rank 0 snapshots, everyone adopts its broadcast,
    then restores. No-op at size 1."""
    if _basics.size() == 1:
        return
    if _basics.rank() == 0:
        state.save()  # non-root snapshots are overwritten below
    setattr(state, attr,
            _broadcast_object(getattr(state, attr), name=name))
    state.restore()


class ObjectState(State):
    """Elastic state over arbitrary picklable attributes.

    Reference analog: horovod/common/elastic.py ObjectState — attributes
    set via kwargs are committed/restored/synced as one pickled unit.
    """

    def __init__(self, **kwargs):
        super().__init__()
        self._saved_state = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def save(self):
        self._saved_state = {
            k: copy.deepcopy(getattr(self, k)) for k in self._saved_state}

    def restore(self):
        for k, v in self._saved_state.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        _sync_state(self, "elastic.object_state", attr="_saved_state")


def _is_internal_error(exc):
    """HorovodInternalError, possibly wrapped: frameworks that run our
    ops inside their own executors re-raise with the original only in
    the message/cause chain (e.g. tf.py_function surfaces it as
    tf.errors.UnknownError whose message embeds the repr)."""
    # The textual fallback only fires for known framework wrapper types:
    # a user RuntimeError that merely *mentions* the class name must not
    # be swallowed into a silent restore/retry loop.
    def _is_framework_wrapper(e):
        return any(cls.__module__.startswith("tensorflow.")
                   and cls.__name__ == "OpError"
                   for cls in type(e).__mro__)

    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, HorovodInternalError):
            return True
        txt = str(exc)
        if _is_framework_wrapper(exc) and (
                "HorovodInternalError:" in txt
                or "HorovodInternalError(" in txt):
            import warnings

            warnings.warn(
                "elastic recovery triggered by textual match inside a "
                f"framework-wrapped error ({type(exc).__name__}); the "
                "original HorovodInternalError was not in the __cause__ "
                "chain", RuntimeWarning, stacklevel=3)
            return True
        # Walk explicit `raise ... from X` chains only. Implicit
        # __context__ must not count: `except HorovodInternalError:
        # raise RuntimeError("aborting")` is a deliberate abort, not a
        # recoverable failure.
        exc = exc.__cause__
    return False


def run_fn(func):
    """Wrap an elastic train function: sync → run → recover loop.

    Reference analog: horovod/common/elastic.py run_fn. Usage::

        @hvd.elastic.run
        def train(state, ...): ...
    """

    def wrapper(state, *args, **kwargs):
        skip_sync = False
        while True:
            # sync() runs collectives, so it sits INSIDE the recovery
            # scope: a host lost right after reset must loop, not raise.
            try:
                if not skip_sync:
                    state.sync()
                return func(state, *args, **kwargs)
            except HostsUpdatedInterrupt as e:
                skip_sync = e.skip_sync
            except Exception as e:  # noqa: BLE001 — see _is_internal_error
                if not _is_internal_error(e):
                    raise
                if os.environ.get("HOROVOD_ELASTIC_VERBOSE"):
                    import traceback

                    print(f"[elastic] recovering from: {e!r}",
                          file=__import__('sys').stderr)
                    traceback.print_exc()
                state.restore()
                skip_sync = False
            reset()
            state.on_reset()

    return wrapper
