"""Auto-generated collective names, elastic-safe.

Unnamed collectives get ``<kind>.noname.<n>`` names from a per-process
counter. The counter participates in elastic recovery: every rank must
restart it at 0 on re-init, or a survivor's counters would mismatch
freshly-respawned peers' names for every unnamed collective. Frontends
create one namer each via ``make_auto_namer()``; the reset hook is
self-registered.
"""

import threading


def make_auto_namer():
    """Return an ``auto_name(kind) -> str`` bound to fresh counters that
    clear on every elastic reset."""
    lock = threading.Lock()
    counters = {}

    def auto_name(kind):
        with lock:
            n = counters.get(kind, 0)
            counters[kind] = n + 1
        return f"{kind}.noname.{n}"

    def _reset():
        with lock:
            counters.clear()

    from horovod_tpu.common import elastic as _elastic

    _elastic.register_post_reset_hook(_reset)
    return auto_name
