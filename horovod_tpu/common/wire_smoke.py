"""Multi-channel striped wire smoke (``make wire-smoke``).

Proves the striped transport end to end on loopback, no jax needed:

1. Selftest rcs: the uncompressed ring at K in {1, 4} is BIT-IDENTICAL
   to the ring-order reference (incl. the N=2 shared-socket case and
   CRC framing), and the SIMD kernels match scalar bit-for-bit.
2. Byte reconciliation on a REAL 2-rank job at K=4: the per-channel
   tx/rx counters sum exactly to the wire totals, every established
   channel moved bytes (a dead stripe must show as imbalance, and a
   healthy run must have none), and uncompressed wire == logical.
3. K=1 vs K=4 transport bandwidth at 16 MiB: the striped engine's
   wire-time goodput must beat the single-socket baseline by a real
   margin (>= 1.25x here — a smoke bound chosen to stay green under
   CI load; the 2x acceptance number lives in ``bench.py
   --ring-busbw``'s per-K rows where the driver tracks it).

Exit 0 on success; prints one WIRE_SMOKE json line per check.
"""

import json
import os
import sys


def _selftest_checks():
    from horovod_tpu.common import basics

    b = basics.HorovodBasics()
    rc = b.simd_selftest()
    assert rc == 0, f"simd_selftest rc={rc}"
    for channels in (1, 4):
        for ranks in (2, 4):
            for count in (1025, 300001):
                rc, err = b.ring_selftest(ranks, count, chunk_bytes=65536,
                                          channels=channels)
                assert rc == 0 and err == 0.0, (channels, ranks, count,
                                                rc, err)
    saved = b.wire_crc()
    b.set_wire_crc(True)
    try:
        rc, err = b.ring_selftest(2, 5000, chunk_bytes=1024, channels=4)
        assert rc == 0 and err == 0.0, ("crc", rc, err)
    finally:
        b.set_wire_crc(saved)
    print("WIRE_SMOKE " + json.dumps({"check": "selftests", "ok": True}),
          flush=True)


_RECON_CHILD = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, os.environ["HVDTPU_REPO"])
from horovod_tpu.common import basics, eager_ops
b = basics.HorovodBasics()
b.init()
rank, size = b.rank(), b.size()
x = np.full((1 << 22,), float(rank + 1), np.float32)  # 16 MiB
for i in range(4):
    eager_ops.allreduce_async(x, f"recon.{i}").synchronize()
snap = b.metrics_snapshot()
wire = snap["wire"]
chans = wire["channels"]
est = b.wire_channels_established()
out = {
    "established": est,
    "channels": len(chans),
    "tx": wire["tx_bytes"],
    "rx": wire["rx_bytes"],
    "tx_logical": wire["tx_logical_bytes"],
    "chan_tx_sum": sum(c["tx_bytes"] for c in chans),
    "chan_rx_sum": sum(c["rx_bytes"] for c in chans),
    # At N=2 the paired plan runs each socket one-way (tx on one
    # parity, rx on the other), so the liveness floor is per-channel
    # TRAFFIC (tx+rx), not per-direction.
    "chan_min_traffic": min(c["tx_bytes"] + c["rx_bytes"] for c in chans),
}
b.shutdown()
if rank == 0:
    print("RECON " + json.dumps(out), flush=True)
"""


def _reconciliation_check():
    import bench

    out = bench._run_loopback_ranks(
        _RECON_CHILD, "RECON", 2,
        {"HOROVOD_WIRE_CHANNELS": "4", "HOROVOD_WIRE_COMPRESSION": "0",
         "HOROVOD_RING_CHUNK_BYTES": str(1024 * 1024)})
    assert out["established"] == 4, out
    # Exact per-channel reconciliation: stripes sum to the totals, and
    # on a healthy K=4 run every channel carried traffic.
    assert out["chan_tx_sum"] == out["tx"], out
    assert out["chan_rx_sum"] == out["rx"], out
    assert out["tx"] == out["tx_logical"], out  # uncompressed: wire==logical
    assert out["channels"] == 4 and out["chan_min_traffic"] > 0, out
    print("WIRE_SMOKE " + json.dumps(
        {"check": "byte_reconciliation", "ok": True, **out}), flush=True)


def _busbw_check():
    import bench

    sizes = json.dumps([1 << 24])
    results = {}
    for name, knobs in (
        ("k1", {"HOROVOD_RING_CHUNK_BYTES": str(256 * 1024),
                "HOROVOD_WIRE_CHANNELS": "1"}),
        ("k4", {"HOROVOD_RING_CHUNK_BYTES": str(1024 * 1024),
                "HOROVOD_WIRE_CHANNELS": "4"}),
    ):
        pts = bench._run_loopback_ranks(
            bench._RING_BUSBW_CHILD, "RING_BUSBW_POINTS", 2,
            dict(knobs, HOROVOD_WIRE_COMPRESSION="0",
                 RING_BUSBW_SIZES=sizes))
        results[name] = pts[0]
    ratio = results["k4"]["wire_gbps"] / results["k1"]["wire_gbps"]
    print("WIRE_SMOKE " + json.dumps(
        {"check": "busbw", "k1_wire_gbps": results["k1"]["wire_gbps"],
         "k4_wire_gbps": results["k4"]["wire_gbps"],
         "k1_busbw_gbps": results["k1"]["busbw_gbps"],
         "k4_busbw_gbps": results["k4"]["busbw_gbps"],
         "wire_ratio_k4_over_k1": round(ratio, 3)}), flush=True)
    assert ratio >= 1.25, (
        f"striped wire goodput only {ratio:.2f}x the K=1 baseline "
        f"({results})")


def main():
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    sys.path.insert(0, repo)
    os.environ.setdefault("HVDTPU_REPO", repo)
    _selftest_checks()
    _reconciliation_check()
    _busbw_check()
    print("WIRE_SMOKE " + json.dumps({"check": "all", "ok": True}),
          flush=True)


if __name__ == "__main__":
    main()
