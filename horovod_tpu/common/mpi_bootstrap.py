"""Bootstrap rank/size/rendezvous from an existing MPI communicator.

Reference analog: ``horovod/common/mpi/mpi_context.cc`` — upstream can
initialize on an already-running MPI world (scripts launched by a plain
``mpirun`` with no horovodrun, or embedding frameworks that own
MPI_COMM_WORLD). Ours keeps the TCP control plane, but derives the
worker identity and rendezvous endpoint from the communicator:

- rank/size come from the comm;
- local_rank/local_size from a shared-memory split (``Split_type``);
- cross_rank/cross_size from a split keyed by local_rank;
- rank 0 opens the controller port and broadcasts ``host:port``.

Engaged by ``hvd.init()`` only when HOROVOD_RANK is absent from the env
(a launcher always sets it) and either the embedding program already
imported mpi4py, or an MPI launcher's own env vars prove we are running
under mpirun/srun — exactly the "running under mpirun without
horovodrun" case. A bare ``from mpi4py import MPI`` calls MPI_Init as an
import side effect, and a failing MPI_Init (stale PMI env under a
different launcher) aborts the process before any try/except runs — so
the import only happens behind the launcher-env gate, with
``mpi4py.rc.initialize`` disabled and MPI_Init invoked explicitly.
"""

import os
import socket
import sys

# Env vars only an MPI-capable launcher sets on its children. Presence of
# any of these is the precondition for importing mpi4py ourselves.
# Deliberately NOT SLURM_PROCID: sbatch/srun set it on every task of
# every job, MPI or not — srun's MPI plugins announce themselves through
# PMI_SIZE / PMIX_RANK, which is the evidence an MPI runtime can
# actually bootstrap here.
_LAUNCHER_ENVS = (
    "OMPI_COMM_WORLD_SIZE",   # Open MPI orted
    "PMI_SIZE",               # MPICH / Hydra / PMI-1 (incl. srun --mpi=pmi2)
    "MV2_COMM_WORLD_SIZE",    # MVAPICH2
)

# PMIx sets no standard size var itself; under srun --mpi=pmix the step
# task count is the size evidence.
_PMIX_SIZE_ENVS = ("SLURM_STEP_NUM_TASKS", "SLURM_NTASKS")


def _under_mpi_launcher(environ):
    """Launcher evidence check. Size evidence must also say >1 — an
    '-np 1' world has nothing to bootstrap and is not worth an
    MPI_Init (which under a half-configured PMI env can still
    hard-abort)."""

    def _gt1(val):
        try:
            return int(val) > 1
        except (TypeError, ValueError):
            return False

    for var in _LAUNCHER_ENVS:
        if _gt1(environ.get(var)):
            return True
    if "PMIX_RANK" in environ:
        return any(_gt1(environ.get(v)) for v in _PMIX_SIZE_ENVS)
    return False


def _routable_ip():
    """Best-effort routable IPv4 address for this host (the UDP-connect
    trick the NIC-discovery task service uses), falling back to
    resolver-reported non-loopback IPv4 addresses, then the hostname.

    IPv4 only BY DESIGN: the control plane (csrc/wire.cc) listens and
    connects AF_INET, so publishing an IPv6 literal here would hand
    workers an endpoint they can never reach.
    """
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        pass
    finally:
        s.close()
    # Egress-filtered hosts where the UDP-connect trick finds nothing:
    # any non-loopback IPv4 the resolver maps the hostname to.
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       socket.AF_INET):
            addr = info[4][0]
            if not addr.startswith("127."):
                return addr
    except OSError:
        pass
    return socket.gethostname()


def _mpi_world(environ):
    """(MPI module, COMM_WORLD) for a genuinely running MPI program, else
    None. Never initializes an MPI runtime unless a launcher env var
    proves one is expected."""
    mod = sys.modules.get("mpi4py")
    MPI = getattr(mod, "MPI", None) if mod is not None else None
    if MPI is None:
        if not _under_mpi_launcher(environ):
            return None
        try:
            import mpi4py

            # Import must stay side-effect free; Init runs explicitly
            # below. (MPI_Init failure under a broken PMI bootstrap can
            # still hard-abort — pre-init errors bypass error handlers —
            # but the launcher gate means one was genuinely expected.)
            mpi4py.rc.initialize = False
            from mpi4py import MPI
        except Exception:
            return None
    try:
        if not MPI.Is_initialized():
            if not _under_mpi_launcher(environ):
                # Embedding program imported mpi4py but never brought the
                # world up, and no launcher is present: not an MPI run.
                return None
            MPI.Init()
            # We initialized, so we must finalize — an Init-without-
            # Finalize exit makes mpirun report the whole (successful)
            # job as failed. Guarded: an embedding program or mpi4py's
            # own atexit hook may get there first.
            import atexit

            atexit.register(
                lambda: MPI.Finalize()
                if MPI.Is_initialized() and not MPI.Is_finalized()
                else None)
        return MPI, MPI.COMM_WORLD
    except Exception:
        return None


def maybe_bootstrap_from_mpi(environ=os.environ):
    """Populate HOROVOD_* env from MPI when launched by bare mpirun.

    Returns True when the env was populated from a communicator.
    No-op (False) when a launcher already provided HOROVOD_RANK, or when
    there is no usable MPI world.
    """
    if "HOROVOD_RANK" in environ:
        return False
    world = _mpi_world(environ)
    if world is None:
        return False
    MPI, comm = world
    if comm.Get_size() <= 1:
        return False

    identity = _identity_env(MPI, comm)
    rank = int(identity["HOROVOD_RANK"])

    # Rank 0 owns the controller endpoint; everyone learns it via bcast
    # (the comm plays the role horovodrun's env injection plays).
    if rank == 0:
        port = environ.get("HOROVOD_CONTROLLER_PORT")
        if not port:
            # Same ephemeral-port probe the launcher uses; the brief
            # close->rebind window is shared with every free_port()
            # user in the runner.
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port = str(s.getsockname()[1])
            s.close()
        # Publish a routable IP, not the bare hostname: peer hosts in
        # containerized MPI clusters often cannot resolve each other's
        # hostnames.
        endpoint = (_routable_ip(), port)
    else:
        endpoint = None
    host, port = comm.bcast(endpoint, root=0)

    environ.update(identity)
    environ.update({
        "HOROVOD_CONTROLLER_ADDR": host,
        "HOROVOD_CONTROLLER_PORT": str(port),
    })
    return True


def _identity_env(MPI, comm):
    """The six HOROVOD_* identity vars from a communicator: global
    rank/size, shared-memory local split, cross split keyed by local
    rank. One derivation shared by the TCP and MPI control paths."""
    rank, size = comm.Get_rank(), comm.Get_size()
    local_comm = comm.Split_type(MPI.COMM_TYPE_SHARED, key=rank)
    cross_comm = comm.Split(color=local_comm.Get_rank(), key=rank)
    return {
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_comm.Get_rank()),
        "HOROVOD_LOCAL_SIZE": str(local_comm.Get_size()),
        "HOROVOD_CROSS_RANK": str(cross_comm.Get_rank()),
        "HOROVOD_CROSS_SIZE": str(cross_comm.Get_size()),
    }


# ---- HOROVOD_CONTROLLER=mpi: the zero-TCP control + data planes ------
#
# Reference analog: horovod/common/mpi_controller.cc — upstream's MPI
# controller negotiates with MPI_Gatherv/MPI_Bcast and moves host
# tensors with MPI collectives, so a firewalled MPI-only fabric never
# needs ad-hoc sockets. Ours keeps ONE controller (csrc/controller.cc)
# and swaps the WIRE underneath it: with HOROVOD_CONTROLLER=mpi the
# C core routes control frames (tag 0) and ring data chunks (tag 1)
# through the callbacks registered here, which relay over mpi4py
# point-to-point. Zero TCP sockets are opened in this mode
# (tests/parallel/test_mpi_control.py pins that).

# The ctypes callback objects MUST outlive the background thread — a
# GC'd CFUNCTYPE leaves the C side calling freed memory.
_transport_refs = []


def _register_external_transport(comm):
    """Register mpi4py-backed send/recv callbacks with the core.

    Contract (csrc/wire.h): send must be buffered/asynchronous (isend —
    a blocking ring send would deadlock); recv with cap==0 blocks for
    the next (peer, tag) message, holds it, and returns its length,
    then a second call copies it out. The core invokes both callbacks
    only from its single background thread (the wire.h contract), so
    the shared state below (``held``, ``inflight``, the comm) needs no
    synchronization TODAY. The lock converts the silent-corruption
    failure mode of a contract violation (interleaved two-phase recv,
    concurrent comm access from an MPI built without
    MPI_THREAD_MULTIPLE) into a visible stall instead; it does NOT
    make a threaded data plane safe — a second caller blocking on
    ``_send`` while ``_recv`` holds the lock across a network wait is
    a ring deadlock, which is why wire.h says a threaded plane must
    revisit the contract (per-peer locks + a non-blocking probe), not
    just rely on this lock. Real-MPI caveat: the MPI library must
    provide MPI_THREAD_MULTIPLE if the main thread also uses the comm
    after init (ours does not)."""
    import ctypes
    import threading

    from horovod_tpu.common.basics import HorovodBasics

    held = {}           # (peer, tag) -> bytes, for two-phase recv
    inflight = []       # isend requests not yet completed
    lock = threading.Lock()  # guards held/inflight/comm (see docstring)

    send_t = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int, ctypes.c_int,
                              ctypes.c_void_p, ctypes.c_longlong)
    recv_t = ctypes.CFUNCTYPE(ctypes.c_longlong, ctypes.c_int,
                              ctypes.c_int, ctypes.c_void_p,
                              ctypes.c_longlong)

    def _send(peer, tag, buf, length):
        try:
            data = ctypes.string_at(buf, length) if length else b""
            with lock:
                inflight.append(comm.isend(data, dest=peer, tag=tag))
                # Opportunistic completion sweep keeps the request list
                # bounded without ever blocking the sender.
                inflight[:] = [r for r in inflight if not _done(r)]
            return 0
        except Exception:  # noqa: BLE001 — surfaces as a Status error
            return -1

    def _done(req):
        try:
            flag = req.test()
        except Exception:  # noqa: BLE001
            return True
        # mpi4py returns (flag, msg); fakes may return a bare bool.
        return bool(flag[0] if isinstance(flag, tuple) else flag)

    def _recv(peer, tag, buf, cap):
        try:
            # The lock is held ACROSS the blocking comm.recv by design:
            # serializing every comm access is what an MPI built
            # without MPI_THREAD_MULTIPLE requires, and the cap==0 /
            # copy-out phases of one message must not interleave with
            # another caller's.
            with lock:
                key = (peer, tag)
                msg = held.pop(key, None)
                if msg is None:
                    msg = comm.recv(source=peer, tag=tag)
                if cap == 0:
                    if msg:
                        held[key] = msg  # empty msgs need no phase 2
                    return len(msg)
                if cap < len(msg):
                    held[key] = msg
                    return -2
                ctypes.memmove(buf, msg, len(msg))
                return len(msg)
        except Exception:  # noqa: BLE001
            return -1

    send_cb = send_t(_send)
    recv_cb = recv_t(_recv)
    _transport_refs.extend([send_cb, recv_cb, comm])
    lib = HorovodBasics().lib
    lib.hvdtpu_set_external_transport(
        ctypes.cast(send_cb, ctypes.c_void_p),
        ctypes.cast(recv_cb, ctypes.c_void_p))


def bootstrap_mpi_control(environ=os.environ):
    """Engage the zero-TCP MPI control+data planes when
    ``HOROVOD_CONTROLLER=mpi``: derive identity from the communicator
    (unless a launcher already set HOROVOD_RANK) and register the
    message transport. Returns True when engaged."""
    if environ.get("HOROVOD_CONTROLLER") != "mpi":
        return False
    world = _mpi_world(environ)
    if world is None:
        raise RuntimeError(
            "HOROVOD_CONTROLLER=mpi requires a running MPI world "
            "(mpi4py importable and launched under an MPI launcher)")
    MPI, comm = world
    if "HOROVOD_RANK" not in environ:
        environ.update(_identity_env(MPI, comm))
    _register_external_transport(comm)
    return True
