"""Bootstrap rank/size/rendezvous from an existing MPI communicator.

Reference analog: ``horovod/common/mpi/mpi_context.cc`` — upstream can
initialize on an already-running MPI world (scripts launched by a plain
``mpirun`` with no horovodrun, or embedding frameworks that own
MPI_COMM_WORLD). Ours keeps the TCP control plane, but derives the
worker identity and rendezvous endpoint from the communicator:

- rank/size come from the comm;
- local_rank/local_size from a shared-memory split (``Split_type``);
- cross_rank/cross_size from a split keyed by local_rank;
- rank 0 opens the controller port and broadcasts ``host:port``.

Engaged by ``hvd.init()`` only when HOROVOD_RANK is absent from the env
(a launcher always sets it) and either the embedding program already
imported mpi4py, or an MPI launcher's own env vars prove we are running
under mpirun/srun — exactly the "running under mpirun without
horovodrun" case. A bare ``from mpi4py import MPI`` calls MPI_Init as an
import side effect, and a failing MPI_Init (stale PMI env under a
different launcher) aborts the process before any try/except runs — so
the import only happens behind the launcher-env gate, with
``mpi4py.rc.initialize`` disabled and MPI_Init invoked explicitly.
"""

import os
import socket
import sys

# Env vars only an MPI-capable launcher sets on its children. Presence of
# any of these is the precondition for importing mpi4py ourselves.
# Deliberately NOT SLURM_PROCID: sbatch/srun set it on every task of
# every job, MPI or not — srun's MPI plugins announce themselves through
# PMI_SIZE / PMIX_RANK, which is the evidence an MPI runtime can
# actually bootstrap here.
_LAUNCHER_ENVS = (
    "OMPI_COMM_WORLD_SIZE",   # Open MPI orted
    "PMI_SIZE",               # MPICH / Hydra / PMI-1 (incl. srun --mpi=pmi2)
    "MV2_COMM_WORLD_SIZE",    # MVAPICH2
)

# PMIx sets no standard size var itself; under srun --mpi=pmix the step
# task count is the size evidence.
_PMIX_SIZE_ENVS = ("SLURM_STEP_NUM_TASKS", "SLURM_NTASKS")


def _under_mpi_launcher(environ):
    """Launcher evidence check. Size evidence must also say >1 — an
    '-np 1' world has nothing to bootstrap and is not worth an
    MPI_Init (which under a half-configured PMI env can still
    hard-abort)."""

    def _gt1(val):
        try:
            return int(val) > 1
        except (TypeError, ValueError):
            return False

    for var in _LAUNCHER_ENVS:
        if _gt1(environ.get(var)):
            return True
    if "PMIX_RANK" in environ:
        return any(_gt1(environ.get(v)) for v in _PMIX_SIZE_ENVS)
    return False


def _routable_ip():
    """Best-effort routable IPv4 address for this host (the UDP-connect
    trick the NIC-discovery task service uses), falling back to
    resolver-reported non-loopback IPv4 addresses, then the hostname.

    IPv4 only BY DESIGN: the control plane (csrc/wire.cc) listens and
    connects AF_INET, so publishing an IPv6 literal here would hand
    workers an endpoint they can never reach.
    """
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        pass
    finally:
        s.close()
    # Egress-filtered hosts where the UDP-connect trick finds nothing:
    # any non-loopback IPv4 the resolver maps the hostname to.
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       socket.AF_INET):
            addr = info[4][0]
            if not addr.startswith("127."):
                return addr
    except OSError:
        pass
    return socket.gethostname()


def _mpi_world(environ):
    """(MPI module, COMM_WORLD) for a genuinely running MPI program, else
    None. Never initializes an MPI runtime unless a launcher env var
    proves one is expected."""
    mod = sys.modules.get("mpi4py")
    MPI = getattr(mod, "MPI", None) if mod is not None else None
    if MPI is None:
        if not _under_mpi_launcher(environ):
            return None
        try:
            import mpi4py

            # Import must stay side-effect free; Init runs explicitly
            # below. (MPI_Init failure under a broken PMI bootstrap can
            # still hard-abort — pre-init errors bypass error handlers —
            # but the launcher gate means one was genuinely expected.)
            mpi4py.rc.initialize = False
            from mpi4py import MPI
        except Exception:
            return None
    try:
        if not MPI.Is_initialized():
            if not _under_mpi_launcher(environ):
                # Embedding program imported mpi4py but never brought the
                # world up, and no launcher is present: not an MPI run.
                return None
            MPI.Init()
            # We initialized, so we must finalize — an Init-without-
            # Finalize exit makes mpirun report the whole (successful)
            # job as failed. Guarded: an embedding program or mpi4py's
            # own atexit hook may get there first.
            import atexit

            atexit.register(
                lambda: MPI.Finalize()
                if MPI.Is_initialized() and not MPI.Is_finalized()
                else None)
        return MPI, MPI.COMM_WORLD
    except Exception:
        return None


def maybe_bootstrap_from_mpi(environ=os.environ):
    """Populate HOROVOD_* env from MPI when launched by bare mpirun.

    Returns True when the env was populated from a communicator.
    No-op (False) when a launcher already provided HOROVOD_RANK, or when
    there is no usable MPI world.
    """
    if "HOROVOD_RANK" in environ:
        return False
    world = _mpi_world(environ)
    if world is None:
        return False
    MPI, comm = world
    if comm.Get_size() <= 1:
        return False

    rank, size = comm.Get_rank(), comm.Get_size()
    local_comm = comm.Split_type(MPI.COMM_TYPE_SHARED, key=rank)
    local_rank = local_comm.Get_rank()
    local_size = local_comm.Get_size()
    cross_comm = comm.Split(color=local_rank, key=rank)
    cross_rank = cross_comm.Get_rank()
    cross_size = cross_comm.Get_size()

    # Rank 0 owns the controller endpoint; everyone learns it via bcast
    # (the comm plays the role horovodrun's env injection plays).
    if rank == 0:
        port = environ.get("HOROVOD_CONTROLLER_PORT")
        if not port:
            # Same ephemeral-port probe the launcher uses; the brief
            # close->rebind window is shared with every free_port()
            # user in the runner.
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port = str(s.getsockname()[1])
            s.close()
        # Publish a routable IP, not the bare hostname: peer hosts in
        # containerized MPI clusters often cannot resolve each other's
        # hostnames.
        endpoint = (_routable_ip(), port)
    else:
        endpoint = None
    host, port = comm.bcast(endpoint, root=0)

    environ.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CROSS_RANK": str(cross_rank),
        "HOROVOD_CROSS_SIZE": str(cross_size),
        "HOROVOD_CONTROLLER_ADDR": host,
        "HOROVOD_CONTROLLER_PORT": str(port),
    })
    return True
