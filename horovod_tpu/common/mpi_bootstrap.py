"""Bootstrap rank/size/rendezvous from an existing MPI communicator.

Reference analog: ``horovod/common/mpi/mpi_context.cc`` — upstream can
initialize on an already-running MPI world (scripts launched by a plain
``mpirun`` with no horovodrun, or embedding frameworks that own
MPI_COMM_WORLD). Ours keeps the TCP control plane, but derives the
worker identity and rendezvous endpoint from the communicator:

- rank/size come from the comm;
- local_rank/local_size from a shared-memory split (``Split_type``);
- cross_rank/cross_size from a split keyed by local_rank;
- rank 0 opens the controller port and broadcasts ``host:port``.

Engaged by ``hvd.init()`` only when HOROVOD_RANK is absent from the env
(a launcher always sets it) and ``mpi4py`` is importable with MPI
already initialized — exactly the "running under mpirun without
horovodrun" case.
"""

import os
import socket


def _routable_ip():
    """Best-effort routable address for this host (the UDP-connect trick
    the NIC-discovery task service uses); hostname as fallback."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostname()
    finally:
        s.close()


def _mpi_comm():
    """The world communicator, or None when this process isn't an MPI
    program (mpi4py missing, or MPI not initialized)."""
    try:
        from mpi4py import MPI
    except Exception:
        return None
    try:
        if not MPI.Is_initialized():
            return None
        return MPI.COMM_WORLD
    except Exception:
        return None


def maybe_bootstrap_from_mpi(environ=os.environ):
    """Populate HOROVOD_* env from MPI when launched by bare mpirun.

    Returns True when the env was populated from a communicator.
    No-op (False) when a launcher already provided HOROVOD_RANK, or when
    there is no usable MPI world.
    """
    if "HOROVOD_RANK" in environ:
        return False
    comm = _mpi_comm()
    if comm is None or comm.Get_size() <= 1:
        return False
    from mpi4py import MPI

    rank, size = comm.Get_rank(), comm.Get_size()
    local_comm = comm.Split_type(MPI.COMM_TYPE_SHARED, key=rank)
    local_rank = local_comm.Get_rank()
    local_size = local_comm.Get_size()
    cross_comm = comm.Split(color=local_rank, key=rank)
    cross_rank = cross_comm.Get_rank()
    cross_size = cross_comm.Get_size()

    # Rank 0 owns the controller endpoint; everyone learns it via bcast
    # (the comm plays the role horovodrun's env injection plays).
    if rank == 0:
        port = environ.get("HOROVOD_CONTROLLER_PORT")
        if not port:
            # Same ephemeral-port probe the launcher uses; the brief
            # close->rebind window is shared with every free_port()
            # user in the runner.
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port = str(s.getsockname()[1])
            s.close()
        # Publish a routable IP, not the bare hostname: peer hosts in
        # containerized MPI clusters often cannot resolve each other's
        # hostnames.
        endpoint = (_routable_ip(), port)
    else:
        endpoint = None
    host, port = comm.bcast(endpoint, root=0)

    environ.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CROSS_RANK": str(cross_rank),
        "HOROVOD_CROSS_SIZE": str(cross_size),
        "HOROVOD_CONTROLLER_ADDR": host,
        "HOROVOD_CONTROLLER_PORT": str(port),
    })
    return True
