"""ctypes binding to the native core runtime.

Reference analog: ``horovod/common/basics.py`` (HorovodBasics loads the
per-framework ``.so`` and exposes init/shutdown/rank/size/...). Ours binds one
framework-agnostic core library; the async-collective handle pattern follows
``horovod/torch/handle_manager.h``.
"""

import ctypes
import os
import subprocess
import threading

_LIB_NAME = "libhvdtpu_core.so"


def _lib_path():
    # HVDTPU_CORE_LIB selects an alternate core build by file name —
    # the sanitizer smoke test (tests/single/test_sanitizer_smoke.py)
    # points it at libhvdtpu_core_tsan.so under an LD_PRELOADed
    # libtsan runtime (make core-tsan / core-asan).
    name = os.environ.get("HVDTPU_CORE_LIB", _LIB_NAME)
    return os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "lib", name)


def _repo_root():
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


_load_lock = threading.Lock()
_lib = None


def load_library():
    """Load (building on demand if needed) the native core."""
    global _lib
    with _load_lock:
        if _lib is not None:
            return _lib
        path = _lib_path()
        if not os.path.exists(path):
            # Dev-tree convenience: build via make (reference: setup.py+CMake).
            makefile = os.path.join(_repo_root(), "Makefile")
            if os.path.exists(makefile):
                subprocess.run(["make", "-s", "core"], cwd=_repo_root(),
                               check=True)
        lib = ctypes.CDLL(path)

        i64 = ctypes.c_int64
        i32 = ctypes.c_int
        dbl = ctypes.c_double
        p = ctypes.c_void_p
        i64p = ctypes.POINTER(ctypes.c_int64)
        cstr = ctypes.c_char_p

        lib.hvdtpu_init.restype = i32
        lib.hvdtpu_set_external_transport.restype = None
        lib.hvdtpu_set_external_transport.argtypes = [p, p]
        lib.hvdtpu_shutdown.restype = i32
        lib.hvdtpu_is_initialized.restype = i32
        lib.hvdtpu_loop_failed.restype = i32
        for fn in ("rank", "size", "local_rank", "local_size", "cross_rank",
                   "cross_size"):
            getattr(lib, f"hvdtpu_{fn}").restype = i32

        lib.hvdtpu_enqueue_allreduce.restype = i32
        lib.hvdtpu_enqueue_allreduce.argtypes = [
            cstr, p, p, i32, i64p, i32, i32, dbl, dbl, i32]
        lib.hvdtpu_enqueue_grouped_allreduce.restype = i32
        lib.hvdtpu_enqueue_grouped_allreduce.argtypes = [
            i32, ctypes.POINTER(cstr), ctypes.POINTER(p), ctypes.POINTER(p),
            ctypes.POINTER(i32), ctypes.POINTER(i64p), i32, i32, dbl, dbl,
            i32, ctypes.POINTER(i32)]
        lib.hvdtpu_enqueue_allgather.restype = i32
        lib.hvdtpu_enqueue_allgather.argtypes = [cstr, p, i32, i64p, i32, i32, i32, i32]
        lib.hvdtpu_enqueue_broadcast.restype = i32
        lib.hvdtpu_enqueue_broadcast.argtypes = [cstr, p, i32, i64p, i32, i32,
                                                 i32]
        lib.hvdtpu_enqueue_alltoall.restype = i32
        lib.hvdtpu_enqueue_alltoall.argtypes = [cstr, p, i32, i64p, i32, i64p,
                                                i32]
        lib.hvdtpu_enqueue_reducescatter.restype = i32
        lib.hvdtpu_enqueue_reducescatter.argtypes = [
            cstr, p, i32, i64p, i32, i32, dbl, dbl, i32, i32, i32]
        lib.hvdtpu_enqueue_barrier.restype = i32
        lib.hvdtpu_enqueue_barrier.argtypes = [i32]
        lib.hvdtpu_set_device_callback.restype = i32
        lib.hvdtpu_set_device_callback.argtypes = [p]
        lib.hvdtpu_enqueue_device.restype = i32
        lib.hvdtpu_enqueue_device.argtypes = [
            i32, cstr, i32, i64p, i32, i32, i32, i32, i32, i32]
        lib.hvdtpu_next_group_id.restype = i32
        lib.hvdtpu_next_group_id.argtypes = []
        lib.hvdtpu_enqueue_join.restype = i32
        lib.hvdtpu_enqueue_join.argtypes = []
        lib.hvdtpu_last_joined_rank.restype = i32
        lib.hvdtpu_last_joined_rank.argtypes = []
        lib.hvdtpu_add_process_set.restype = i32
        lib.hvdtpu_add_process_set.argtypes = [
            ctypes.POINTER(ctypes.c_int32), i32]
        for fn in ("remove_process_set", "process_set_size",
                   "process_set_rank"):
            getattr(lib, f"hvdtpu_{fn}").restype = i32
            getattr(lib, f"hvdtpu_{fn}").argtypes = [i32]

        lib.hvdtpu_poll.restype = i32
        lib.hvdtpu_poll.argtypes = [i32]
        lib.hvdtpu_wait.restype = i32
        lib.hvdtpu_wait.argtypes = [i32]
        lib.hvdtpu_error_string.restype = cstr
        lib.hvdtpu_error_string.argtypes = [i32]
        lib.hvdtpu_result_ndim.restype = i32
        lib.hvdtpu_result_ndim.argtypes = [i32]
        lib.hvdtpu_result_shape.restype = i32
        lib.hvdtpu_result_shape.argtypes = [i32, i64p]
        lib.hvdtpu_result_size_bytes.restype = i64
        lib.hvdtpu_result_size_bytes.argtypes = [i32]
        lib.hvdtpu_result_copy.restype = i32
        lib.hvdtpu_result_copy.argtypes = [i32, p, i64]
        lib.hvdtpu_release.restype = i32
        lib.hvdtpu_release.argtypes = [i32]

        lib.hvdtpu_metrics_snapshot.restype = i64
        lib.hvdtpu_metrics_snapshot.argtypes = [p, i64]
        lib.hvdtpu_metrics_reset.restype = i32
        lib.hvdtpu_metrics_reset.argtypes = []
        lib.hvdtpu_record_phase.restype = None
        lib.hvdtpu_record_phase.argtypes = [i32, i64]
        lib.hvdtpu_record_request.restype = None
        lib.hvdtpu_record_request.argtypes = [i32, i64, i64]
        lib.hvdtpu_record_slo.restype = None
        lib.hvdtpu_record_slo.argtypes = [i32, i32, i64, i64]
        lib.hvdtpu_step_mark.restype = i64
        lib.hvdtpu_step_mark.argtypes = [i32]
        lib.hvdtpu_step_id.restype = i64
        lib.hvdtpu_step_id.argtypes = []
        lib.hvdtpu_queue_depth.restype = i64
        lib.hvdtpu_queue_depth.argtypes = []
        lib.hvdtpu_simworld_run.restype = i32
        lib.hvdtpu_simworld_run.argtypes = [i32, i32, i64, i32, i32, i32,
                                            p, i64]
        lib.hvdtpu_events_drain.restype = i64
        lib.hvdtpu_events_drain.argtypes = [p, i64]
        lib.hvdtpu_events_peek.restype = i64
        lib.hvdtpu_events_peek.argtypes = [p, i64, i64]
        lib.hvdtpu_events_enabled.restype = i32
        lib.hvdtpu_events_enabled.argtypes = []
        lib.hvdtpu_set_events_enabled.restype = None
        lib.hvdtpu_set_events_enabled.argtypes = [i32]
        lib.hvdtpu_events_head.restype = i64
        lib.hvdtpu_events_head.argtypes = []
        lib.hvdtpu_start_timeline.restype = i32
        lib.hvdtpu_start_timeline.argtypes = [cstr]
        lib.hvdtpu_stop_timeline.restype = i32
        lib.hvdtpu_stop_timeline.argtypes = []
        lib.hvdtpu_fusion_threshold_bytes.restype = i64
        lib.hvdtpu_cycle_time_ms.restype = dbl
        lib.hvdtpu_set_fusion_threshold_bytes.argtypes = [i64]
        lib.hvdtpu_set_cycle_time_ms.argtypes = [dbl]
        lib.hvdtpu_ring_chunk_bytes.restype = i64
        lib.hvdtpu_set_ring_chunk_bytes.argtypes = [i64]
        lib.hvdtpu_wire_compression.restype = i32
        lib.hvdtpu_set_wire_compression.argtypes = [i32]
        lib.hvdtpu_wire_codec.restype = i32
        lib.hvdtpu_set_wire_codec.argtypes = [i32]
        lib.hvdtpu_wire_channels.restype = i64
        lib.hvdtpu_set_wire_channels.argtypes = [i64]
        lib.hvdtpu_wire_channels_established.restype = i32
        lib.hvdtpu_wire_channels_established.argtypes = []
        lib.hvdtpu_simd_enabled.restype = i32
        lib.hvdtpu_simd_enabled.argtypes = []
        lib.hvdtpu_set_simd_enabled.argtypes = [i32]
        lib.hvdtpu_simd_selftest.restype = i32
        lib.hvdtpu_simd_selftest.argtypes = []
        lib.hvdtpu_int8_roundtrip.restype = i64
        lib.hvdtpu_int8_roundtrip.argtypes = [p, i64, p, dbl]
        lib.hvdtpu_wire_timeout_ms.restype = i64
        lib.hvdtpu_wire_timeout_ms.argtypes = []
        lib.hvdtpu_set_wire_timeout_ms.restype = None
        lib.hvdtpu_set_wire_timeout_ms.argtypes = [i64]
        lib.hvdtpu_wire_retry_attempts.restype = i64
        lib.hvdtpu_wire_retry_attempts.argtypes = []
        lib.hvdtpu_set_wire_retry_attempts.restype = None
        lib.hvdtpu_set_wire_retry_attempts.argtypes = [i64]
        lib.hvdtpu_wire_retry_backoff_ms.restype = i64
        lib.hvdtpu_wire_retry_backoff_ms.argtypes = []
        lib.hvdtpu_set_wire_retry_backoff_ms.restype = None
        lib.hvdtpu_set_wire_retry_backoff_ms.argtypes = [i64]
        lib.hvdtpu_wire_crc.restype = i32
        lib.hvdtpu_wire_crc.argtypes = []
        lib.hvdtpu_set_wire_crc.restype = None
        lib.hvdtpu_set_wire_crc.argtypes = [i32]
        lib.hvdtpu_set_fault_inject_spec.restype = i32
        lib.hvdtpu_set_fault_inject_spec.argtypes = [cstr]
        lib.hvdtpu_epoch.restype = i64
        lib.hvdtpu_epoch.argtypes = []
        lib.hvdtpu_last_fault.restype = i64
        lib.hvdtpu_last_fault.argtypes = [p, i64]
        lib.hvdtpu_reinit.restype = i32
        lib.hvdtpu_reinit.argtypes = [ctypes.POINTER(ctypes.c_int32), i32,
                                      i64]
        lib.hvdtpu_set_fault_inject.restype = i32
        lib.hvdtpu_set_fault_inject.argtypes = [i32, i64]
        lib.hvdtpu_ring_selftest.restype = i32
        lib.hvdtpu_ring_selftest.argtypes = [
            i32, i64, i32, i32, i64, i32, dbl, i32,
            ctypes.POINTER(ctypes.c_double)]
        lib.hvdtpu_hier_selftest.restype = i32
        lib.hvdtpu_hier_selftest.argtypes = [
            i32, i32, i64, i32, i32, i64, i32, i32, dbl, i32,
            ctypes.POINTER(ctypes.c_double)]
        lib.hvdtpu_cross_plane.restype = i32
        lib.hvdtpu_cross_plane.argtypes = []
        lib.hvdtpu_hier_split.restype = i32
        lib.hvdtpu_hier_split.argtypes = []
        lib.hvdtpu_set_hier_split.restype = None
        lib.hvdtpu_set_hier_split.argtypes = [i32]
        lib.hvdtpu_cross_compression.restype = i32
        lib.hvdtpu_cross_compression.argtypes = []
        lib.hvdtpu_ring_owned_segment.restype = i32
        lib.hvdtpu_ring_owned_segment.argtypes = [i32, i32, i32]
        lib.hvdtpu_ring_send_segment.restype = i32
        lib.hvdtpu_ring_send_segment.argtypes = [i32, i32, i32, i32]
        for fn in ("response_cache_hits", "response_cache_misses",
                   "response_cache_entries"):
            getattr(lib, f"hvdtpu_{fn}").restype = i64
            getattr(lib, f"hvdtpu_{fn}").argtypes = []

        _lib = lib
        return _lib


class HorovodBasics:
    """Python surface of the core C API, shared by every frontend.

    Reference analog: horovod/common/basics.py HorovodBasics.
    """

    def __init__(self):
        self._lib = None

    @property
    def lib(self):
        if self._lib is None:
            self._lib = load_library()
        return self._lib

    # Launcher-env fallbacks: under mpirun/srun/jsrun the per-rank layout
    # arrives in the launcher's own variables, not HOROVOD_* (reference
    # analog: MPIContext owning rank/size; gloo path's env contract).
    # Ordered HOROVOD_* first so horovodrun's explicit assignment wins.
    _ENV_FALLBACKS = {
        "HOROVOD_RANK": ("OMPI_COMM_WORLD_RANK", "PMIX_RANK", "PMI_RANK",
                         "SLURM_PROCID", "JSM_NAMESPACE_RANK"),
        "HOROVOD_SIZE": ("OMPI_COMM_WORLD_SIZE", "PMI_SIZE", "SLURM_NTASKS",
                         "JSM_NAMESPACE_SIZE"),
        "HOROVOD_LOCAL_RANK": ("OMPI_COMM_WORLD_LOCAL_RANK", "PMI_LOCAL_RANK",
                               "SLURM_LOCALID", "JSM_NAMESPACE_LOCAL_RANK"),
        "HOROVOD_LOCAL_SIZE": ("OMPI_COMM_WORLD_LOCAL_SIZE", "PMI_LOCAL_SIZE",
                               "SLURM_TASKS_PER_NODE"),
    }

    @staticmethod
    def _translate_launcher_env():
        import os

        for target, sources in HorovodBasics._ENV_FALLBACKS.items():
            if os.environ.get(target):
                continue
            for src in sources:
                val = os.environ.get(src)
                if val:
                    # SLURM_TASKS_PER_NODE can be '4(x2)'; take the number.
                    os.environ[target] = val.split("(")[0].split(",")[0]
                    break

    def init(self):
        self._translate_launcher_env()
        if self.lib.hvdtpu_init() != 0:
            raise RuntimeError(
                "Horovod initialization failed (see stderr log)")
        # Opt-in live introspection (HOROVOD_DEBUG_PORT, docs/
        # metrics.md): a per-rank daemon HTTP thread serving /healthz,
        # /metrics, /events, /stacks — so a live or wedged rank can be
        # inspected without SIGKILL. Never fatal: observability must
        # not take the job down.
        import os as _os

        if _os.environ.get("HOROVOD_DEBUG_PORT"):
            try:
                from horovod_tpu.telemetry import debug_server

                debug_server.maybe_start(self)
            except Exception as e:  # noqa: BLE001
                import sys as _sys

                print(f"hvdtpu debug server not started: {e}",
                      file=_sys.stderr)

    def shutdown(self):
        import sys as _sys

        ds = _sys.modules.get("horovod_tpu.telemetry.debug_server")
        if ds is not None:  # only loaded when HOROVOD_DEBUG_PORT was set
            try:
                ds.stop()
            except Exception:  # noqa: BLE001
                pass
        self.lib.hvdtpu_shutdown()

    def is_initialized(self):
        return bool(self.lib.hvdtpu_is_initialized())

    def _checked(self, value, what):
        if value < 0:
            raise ValueError(
                f"hvd.{what}() called before hvd.init(); call hvd.init() first")
        return value

    def rank(self):
        return self._checked(self.lib.hvdtpu_rank(), "rank")

    def size(self):
        return self._checked(self.lib.hvdtpu_size(), "size")

    def local_rank(self):
        return self._checked(self.lib.hvdtpu_local_rank(), "local_rank")

    def local_size(self):
        return self._checked(self.lib.hvdtpu_local_size(), "local_size")

    def cross_rank(self):
        return self._checked(self.lib.hvdtpu_cross_rank(), "cross_rank")

    def cross_size(self):
        return self._checked(self.lib.hvdtpu_cross_size(), "cross_size")

    def is_homogeneous(self):
        return True

    def start_timeline(self, file_path, mark_cycles=False):
        """Begin recording a Chrome-trace timeline at runtime.

        Reference analog: ``hvd.start_timeline`` (horovod/common/basics.py).
        """
        del mark_cycles  # cycle marks are env-controlled at init
        rc = self.lib.hvdtpu_start_timeline(str(file_path).encode())
        if rc != 0:
            raise ValueError(
                f"could not start timeline at {file_path!r} "
                "(is Horovod initialized and the path writable?)")

    def stop_timeline(self):
        """Stop a runtime-started timeline and flush the JSON file."""
        self.lib.hvdtpu_stop_timeline()

    def metrics_snapshot(self):
        """One JSON snapshot of the native core's metrics registry.

        Returns a dict (see ``docs/metrics.md`` for the counter catalog).
        Works before ``init()`` too — counters are process-lifetime and
        the snapshot then carries ``initialized: False``. The parsed
        surface for operators is ``horovod_tpu.telemetry.snapshot()`` /
        ``hvd.metrics()``; this is the raw binding they share.
        """
        import ctypes as _ct
        import json as _json

        lib = self.lib
        # Two-call pattern with a retry loop: counters move between the
        # sizing call and the copy, so the JSON can grow a few bytes.
        cap = int(lib.hvdtpu_metrics_snapshot(None, 0)) + 256
        while True:
            buf = _ct.create_string_buffer(cap)
            need = int(lib.hvdtpu_metrics_snapshot(buf, cap))
            if need < cap:
                return _json.loads(buf.value.decode())
            cap = need + 256

    def metrics_reset(self):
        """Zero every counter in the metrics registry (histograms too).

        Scrapers normally diff monotonic snapshots instead; reset exists
        for test isolation and interactive sessions.
        """
        self.lib.hvdtpu_metrics_reset()

    # Control-plane phase ids (csrc/metrics.h ControlPhase) — the ONE
    # name order the snapshot keys, the kPhase events, and this binding
    # all follow (docs/scale.md).
    CONTROL_PHASES = ("rendezvous", "gather", "broadcast", "probe_sweep",
                      "reinit", "parole_freeze")

    def record_phase(self, phase, dur_us):
        """Record one control-plane phase duration into the per-phase
        scaling profile (histogram + ``phase`` event). ``phase`` is a
        name from :data:`CONTROL_PHASES` or its index; used by the
        Python-side phases (the parole-door freeze) so they land on the
        same profile as the native ones. Valid before ``init()``."""
        if isinstance(phase, str):
            phase = self.CONTROL_PHASES.index(phase)
        self.lib.hvdtpu_record_phase(int(phase), int(dur_us))

    def record_request(self, phase, rid, aux=0):
        """Record one serving-request lifecycle transition (``request``
        event, csrc/events.h RequestPhase): the rid enters ``phase``
        (an index into :data:`horovod_tpu.telemetry.reqtrace.
        REQUEST_PHASES`, which mirrors the C table) now. The serving
        lane calls this through :func:`telemetry.reqtrace.
        record_request` (which also keeps the live in-flight table the
        ``/requests`` debug endpoint serves). Valid before ``init()``."""
        self.lib.hvdtpu_record_request(int(phase), int(rid), int(aux))

    def record_slo(self, objective, breach_rank, value, bucket=-1):
        """Record one SLO breach (``slo_breach`` event, csrc/events.h
        SloObjective): ``objective`` is an index into
        :data:`horovod_tpu.telemetry.slo.OBJECTIVES` (which mirrors the
        C table), ``breach_rank`` the breaching rank, ``value`` the
        observed measurement (integral — ms or permille per objective),
        ``bucket`` the dominant rank-seconds ledger bucket (an index
        into :data:`horovod_tpu.telemetry.fleet.BUCKETS`, -1 unknown).
        The SLO engine calls this through
        :meth:`telemetry.slo.SloEngine.record`. Valid before
        ``init()``."""
        self.lib.hvdtpu_record_slo(int(objective), int(breach_rank),
                                   int(value), int(bucket))

    def step_mark(self, begin=True):
        """Mark a training-step boundary for the step-anatomy layer
        (docs/metrics.md): ``begin=True`` opens a new step window with
        a fresh monotonic id (closing a still-open one first — boundary
        semantics) and returns the id; ``begin=False`` closes the open
        window and returns its id (-1 if none). ``step_begin``/
        ``step_end`` events land in the flight recorder and the wire
        overlap ledger aggregates between the marks. Valid before
        ``init()``. Driven by :class:`~horovod_tpu.telemetry.step_timer.
        StepTimer` and the eager optimizer step; call directly only
        when neither scopes your loop."""
        return int(self.lib.hvdtpu_step_mark(1 if begin else 0))

    def step_id(self):
        """The currently open step id, or -1 — how an implicit step
        driver (the eager optimizer boundary) defers to an explicit
        scope (StepTimer)."""
        return int(self.lib.hvdtpu_step_id())

    def queue_depth(self):
        """Live pending-tensor gauge: collectives enqueued by API
        threads that the background loop has not finished executing.
        The queue-depth signal the autoscaler reads off ``/healthz``
        (docs/scale.md). 0 before ``init()``."""
        return int(self.lib.hvdtpu_queue_depth())

    def simworld_run(self, ranks, tree_fanout=0, elems=1024, rounds=3,
                     kill_rank=-1, kill_round=-1):
        """Run one simulated `ranks`-rank world in-process (thread per
        rank over socketpairs — ``csrc/simworld.cc``) and return its
        JSON report as a dict: world standup, per-round negotiation+
        allreduce latency, and the per-phase control-plane profile the
        scaling curves are built from (docs/scale.md). Refuses to run
        next to a live core (rc -5): it resets the phase histograms.
        Raises RuntimeError on a non-injected failure."""
        import ctypes as _ct
        import json as _json

        buf = _ct.create_string_buffer(1 << 16)
        rc = self.lib.hvdtpu_simworld_run(
            int(ranks), int(tree_fanout), int(elems), int(rounds),
            int(kill_rank), int(kill_round), buf, len(buf))
        out = _json.loads(buf.value.decode()) if buf.value else {}
        out["rc"] = rc
        if rc != 0:
            reasons = {-1: "bad arguments", -2: "fd budget/socketpair",
                       -3: "a rank failed", -4: "allreduce mismatch",
                       -5: "core already initialized in this process",
                       -6: "injected kill surfaced no typed fault"}
            raise RuntimeError(
                f"simworld_run(ranks={ranks}, tree_fanout={tree_fanout})"
                f" failed: {reasons.get(rc, rc)}: "
                f"{out.get('error', '')}")
        return out

    def events(self, last_n=0):
        """The newest ``last_n`` events of the core's structured event
        ring (``0`` = the whole live window, up to the ring capacity),
        as a list of dicts — NON-consuming, so concurrent consumers
        (the debug server's ``/events``, a black-box dump in flight)
        are unaffected. Each event carries ``seq``, ``ts_us`` (steady
        clock), ``type``, and per-type named args; catalog in
        ``docs/metrics.md``. Works before ``init()``."""
        import ctypes as _ct
        import json as _json

        lib = self.lib
        cap = int(lib.hvdtpu_events_peek(None, 0, int(last_n))) + 4096
        while True:
            buf = _ct.create_string_buffer(cap)
            need = int(lib.hvdtpu_events_peek(buf, cap, int(last_n)))
            if need < cap:
                return _json.loads(buf.value.decode())
            cap = need + 4096

    def events_drain(self):
        """Consume every event recorded since the last drain (ring-
        capacity bounded) and return them as a list of dicts. ONE
        logical consumer per process by contract — scrapers that tail
        the ring use this; ad-hoc inspection uses :meth:`events`."""
        import ctypes as _ct
        import json as _json

        lib = self.lib
        cap = int(lib.hvdtpu_events_drain(None, 0)) + 4096
        while True:
            buf = _ct.create_string_buffer(cap)
            need = int(lib.hvdtpu_events_drain(buf, cap))
            if need < cap:
                return _json.loads(buf.value.decode())
            cap = need + 4096

    def events_enabled(self):
        """Whether the event ring records (``HOROVOD_EVENTS``; on by
        default — recording is wait-free and bounded-memory)."""
        return bool(self.lib.hvdtpu_events_enabled())

    def set_events_enabled(self, on):
        self.lib.hvdtpu_set_events_enabled(1 if on else 0)

    def ring_chunk_bytes(self):
        """Chunk granularity of the chunk-pipelined host ring
        (``HOROVOD_RING_CHUNK_BYTES``; <= 0 = bulk-synchronous path).
        See ``docs/wire.md``."""
        return self.lib.hvdtpu_ring_chunk_bytes()

    def set_ring_chunk_bytes(self, nbytes):
        """Set the ring chunk granularity. Must be set identically on
        every rank — the chunk split is the wire framing."""
        self.lib.hvdtpu_set_ring_chunk_bytes(int(nbytes))

    def wire_compression(self):
        """Whether fp32 allreduce payloads cross the wire as bf16
        (``HOROVOD_WIRE_COMPRESSION``); accumulation stays f32."""
        return bool(self.lib.hvdtpu_wire_compression())

    def set_wire_compression(self, on):
        """Toggle bf16-on-wire compression (rank-uniform, like the
        chunk knob; numerics contract in ``docs/wire.md``)."""
        self.lib.hvdtpu_set_wire_compression(1 if on else 0)

    def wire_codec(self):
        """Wire codec mode behind the compression knob: 0 off, 1 bf16
        (``HOROVOD_WIRE_COMPRESSION=1``/``bf16``), 2 int8
        blockwise-scaled (``int8`` — one f32 scale per 256 elems, f32
        accumulate; the EQuARX recipe). See ``docs/wire.md``."""
        return int(self.lib.hvdtpu_wire_codec())

    def set_wire_codec(self, mode):
        """Select the wire codec (rank-uniform, like the chunk knob)."""
        self.lib.hvdtpu_set_wire_codec(int(mode))

    def wire_channels(self):
        """Active stripe width of the multi-channel wire transport
        (``HOROVOD_WIRE_CHANNELS``): chunk i of every ring step rides
        channel ``i % K`` over K parallel sockets per neighbor. See
        ``docs/wire.md``."""
        return int(self.lib.hvdtpu_wire_channels())

    def set_wire_channels(self, k):
        """Set the active stripe width (rank-uniform — the stripe
        schedule is the wire framing; clamped to the established
        socket count at use sites)."""
        self.lib.hvdtpu_set_wire_channels(int(k))

    def wire_channels_established(self):
        """Stripe sockets established per neighbor pair this
        generation (env-derived at rendezvous; 1 before init)."""
        return int(self.lib.hvdtpu_wire_channels_established())

    def simd_enabled(self):
        """Whether the explicit-SIMD reduce/codec paths are active
        (``HOROVOD_SIMD``; bit-identical to scalar by contract)."""
        return bool(self.lib.hvdtpu_simd_enabled())

    def set_simd_enabled(self, on):
        self.lib.hvdtpu_set_simd_enabled(1 if on else 0)

    def simd_selftest(self):
        """Pin the SIMD kernels bit-identical to the scalar reference
        across unaligned offsets/tail lengths (0 = pass; negative
        names the divergent kernel — csrc/ring_selftest.cc)."""
        return int(self.lib.hvdtpu_simd_selftest())

    def wire_timeout_ms(self):
        """Wire progress deadline (``HOROVOD_WIRE_TIMEOUT_MS``): a peer
        making no wire progress for this long is declared failed with a
        typed, recoverable error instead of hanging the ring. <= 0
        disables the deadline. See ``docs/elastic.md``."""
        return self.lib.hvdtpu_wire_timeout_ms()

    def set_wire_timeout_ms(self, ms):
        """Set the wire progress deadline (process-global, like the ring
        knobs; valid before init)."""
        self.lib.hvdtpu_set_wire_timeout_ms(int(ms))

    def wire_retry_attempts(self):
        """Healing-ladder depth (``HOROVOD_WIRE_RETRY_ATTEMPTS``): extra
        exponential-backoff windows a stalled transfer waits out before
        a timeout escalates to a fault. 0 = healing off (the r12
        behavior). See ``docs/wire.md``."""
        return self.lib.hvdtpu_wire_retry_attempts()

    def set_wire_retry_attempts(self, n):
        self.lib.hvdtpu_set_wire_retry_attempts(int(n))

    def wire_retry_backoff_ms(self):
        """Base backoff of the healing ladder
        (``HOROVOD_WIRE_RETRY_BACKOFF_MS``); window i waits
        ``backoff << min(i, 6)`` ms."""
        return self.lib.hvdtpu_wire_retry_backoff_ms()

    def set_wire_retry_backoff_ms(self, ms):
        self.lib.hvdtpu_set_wire_retry_backoff_ms(int(ms))

    def wire_crc(self):
        """Whether host-ring transfers carry per-chunk CRC32C framing
        (``HOROVOD_WIRE_CRC``): silent corruption becomes a NAK/resend
        heal or a typed ``WireCorruption``. MUST be rank-uniform — the
        framing IS the wire format. See ``docs/wire.md``."""
        return bool(self.lib.hvdtpu_wire_crc())

    def set_wire_crc(self, on):
        self.lib.hvdtpu_set_wire_crc(1 if on else 0)

    def epoch(self):
        """Membership epoch of the current ring generation (0 for a
        fresh init; bumped by every :meth:`reinit`)."""
        return self.lib.hvdtpu_epoch()

    def last_fault(self):
        """The core's last fault record, or ``None`` if no collective
        has failed on a lost peer.

        Returns a dict: ``{"epoch": int, "ranks": [int, ...],
        "certain": bool, "reason": str, "detect_ms": int,
        "recovered": bool}`` — ranks in the numbering of the epoch that
        faulted. ``certain`` is True when every rank is PROVABLY dead
        (EOF/RST/probe sweep) — the precondition for driver-less
        re-formation; a timeout-only suspicion sets it False. See
        ``docs/elastic.md`` for the attribution guarantees.
        """
        import ctypes as _ct
        import json as _json

        lib = self.lib
        cap = int(lib.hvdtpu_last_fault(None, 0)) + 64
        buf = _ct.create_string_buffer(cap)
        lib.hvdtpu_last_fault(buf, cap)
        rec = _json.loads(buf.value.decode())
        if not rec.get("faulted"):
            return None
        rec.pop("faulted", None)
        return rec

    def reinit(self, ranks, epoch):
        """Re-form the ring over surviving OLD ranks at a new epoch
        without process restart (collective among the members). A ``-1``
        entry is a JOINER slot: a fresh process initializing with
        ``HOROVOD_JOIN_EPOCH=epoch`` takes that new rank — the
        blacklist-parole scale-up path. A healthy loop drains via the
        negotiated shutdown first, so voluntary grow works without a
        fault. Raises on failure with the core's reason code. See
        ``docs/elastic.md``."""
        import ctypes as _ct

        ranks = [int(r) for r in ranks]
        arr = (_ct.c_int32 * len(ranks))(*ranks)
        rc = self.lib.hvdtpu_reinit(arr, len(ranks), int(epoch))
        if rc != 0:
            reasons = {-1: "not initialized / bad ranks",
                       -3: "this rank is not in the survivor set",
                       -4: "re-formation rendezvous failed",
                       -5: "not supported on the external (MPI) "
                           "transport — recover via the driver"}
            raise RuntimeError(
                f"hvdtpu_reinit(ranks={ranks}, epoch={epoch}) failed: "
                f"{reasons.get(rc, rc)}")

    def set_fault_inject(self, rank, op_index):
        """Arm deterministic fault injection: `rank` SIGKILLs itself at
        the top of its `op_index`-th executed collective
        (``HOROVOD_FAULT_INJECT``'s programmatic twin; rank < 0
        disarms). The primitive the chaos lane is built on."""
        if self.lib.hvdtpu_set_fault_inject(int(rank), int(op_index)) != 0:
            raise RuntimeError("set_fault_inject requires hvd.init()")

    def set_fault_inject_spec(self, spec):
        """Arm the full chaos grammar
        (``<rank>:<op>[:kill|stop:<ms>|reset|flip:<bit>|delay:<ms>]``,
        docs/elastic.md): SIGKILL, a timed SIGSTOP stall, peer-socket
        reset, a wire bit-flip (negative bit = persistent), or a
        straggler delay at a deterministic collective index. Raises on
        a malformed spec (the trigger stays disarmed)."""
        rc = self.lib.hvdtpu_set_fault_inject_spec(str(spec).encode())
        if rc == -1:
            raise RuntimeError("set_fault_inject_spec requires hvd.init()")
        if rc != 0:
            raise ValueError(
                f"malformed fault-injection spec {spec!r} (expected "
                "<rank>:<op>[:kill|stop:<ms>|reset|flip:<bit>|"
                "delay:<ms>])")

    def ring_owned_segment(self, rank, size, rot=0):
        """Which buffer segment ``rank`` owns (holds fully reduced)
        after the ring reduce phase at rotation ``rot`` — THE encoding
        of the r10 segment-rotation trap, straight from the C++ engine
        (``csrc/ring_ops.h RingOwnedSegment``). rot=0 is the allreduce
        rotation (rank r owns segment ``(r+1) % size``, what the
        compressed allgather finalizes); rot=-1 is the reduce-scatter
        rotation (rank r owns its own segment r — the ZeRO shard
        boundary contract, ``docs/zero.md``)."""
        return self.lib.hvdtpu_ring_owned_segment(int(rank), int(size),
                                                  int(rot))

    def ring_send_segment(self, rank, step, size, rot=0):
        """Segment ``rank`` sends at reduce-phase ``step`` under
        rotation ``rot`` (see :meth:`ring_owned_segment`)."""
        return self.lib.hvdtpu_ring_send_segment(int(rank), int(step),
                                                 int(size), int(rot))

    def ring_selftest(self, ranks, count, dtype=6, op=1, chunk_bytes=None,
                      compression=False, postscale=1.0, channels=1):
        """In-process loopback proof of the ring engine (no init needed).

        Runs one allreduce over ``ranks`` socketpair-connected data
        planes with explicit knobs and checks against a bulk ring-order
        reference (``csrc/ring_selftest.cc``). ``channels`` = stripe
        sockets per neighbor pair (``HOROVOD_WIRE_CHANNELS``);
        ``compression`` accepts False/0, True/1 (bf16) or 2 (int8
        blockwise). Returns ``(rc, max_abs_err)``: rc 0 = pass;
        uncompressed passes — striped or not — are bit-identical
        (err 0.0), compressed passes report the wire-rounding error
        for the caller to bound. ``dtype``/``op`` take the core enums
        (6 = float32, 1 = SUM).
        """
        import ctypes as _ct

        if chunk_bytes is None:
            chunk_bytes = self.ring_chunk_bytes()
        err = _ct.c_double()
        rc = self.lib.hvdtpu_ring_selftest(
            int(ranks), int(count), int(dtype), int(op), int(chunk_bytes),
            int(compression), float(postscale), int(channels),
            _ct.byref(err))
        return rc, err.value

    #: HOROVOD_CROSS_PLANE mode names in core enum order.
    CROSS_PLANE_MODES = ("auto", "ici", "ring", "hier")

    def cross_plane(self):
        """The cross-plane topology descriptor (``HOROVOD_CROSS_PLANE``)
        as one of ``"auto"|"ici"|"ring"|"hier"`` — how collectives pick
        (or compose) the ICI device plane and the host/DCN ring. Fixed
        at init; see ``docs/redistribute.md``."""
        return self.CROSS_PLANE_MODES[self.lib.hvdtpu_cross_plane()]

    def hier_split(self):
        """Active hierarchy split point of the cross-plane allreduce:
        0 = flat host ring, ``s >= 2`` = intra-slice group size of the
        three-phase decomposition (reduce-scatter intra, allreduce of
        the 1/s shards inter, allgather intra). -1 before init."""
        return self.lib.hvdtpu_hier_split()

    def set_hier_split(self, split):
        """Set the hierarchy split point. MUST be rank-uniform — the
        split decides which plane sequence every collective decomposes
        into (the autotuner syncs its moves via the ResponseList)."""
        self.lib.hvdtpu_set_hier_split(int(split))

    def cross_compression(self):
        """Whether the bf16 wire codec rides the inter-slice hop only
        (``HOROVOD_CROSS_PLANE_COMPRESSION``) — cheap wire on the
        DCN-priced fabric, full width intra-slice."""
        return bool(self.lib.hvdtpu_cross_compression())

    def hier_selftest(self, ranks, local_size, count, dtype=6, op=1,
                      chunk_bytes=None, compression=0, exact_fill=True,
                      postscale=1.0, channels=1):
        """In-process loopback proof of the hierarchical cross-plane
        allreduce at an emulated ``ranks/local_size`` slices x
        ``local_size`` ranks topology (no init needed).

        ``compression``: 0 = none, 1 = every hop, 2 = the inter-slice
        hop only. ``channels`` = stripe sockets per pair (every plane
        of the decomposition stripes). With ``exact_fill`` (small
        integers — exact in f32 and bf16) an uncompressed pass must be
        BIT-IDENTICAL to the flat ring reference. Returns
        ``(rc, max_abs_err)``; rc 0 = pass, -4 = bit-exactness
        violated, -5 = ranks disagree.
        """
        import ctypes as _ct

        if chunk_bytes is None:
            chunk_bytes = self.ring_chunk_bytes()
        err = _ct.c_double()
        rc = self.lib.hvdtpu_hier_selftest(
            int(ranks), int(local_size), int(count), int(dtype), int(op),
            int(chunk_bytes), int(compression), 1 if exact_fill else 0,
            float(postscale), int(channels), _ct.byref(err))
        return rc, err.value

    def response_cache_stats(self):
        """(hits, misses, entries) of the negotiation response cache.

        Reference analog: horovod/common/response_cache.h — the steady-state
        bitvector path; hits grow once a training loop reaches steady state.
        """
        return (self.lib.hvdtpu_response_cache_hits(),
                self.lib.hvdtpu_response_cache_misses(),
                self.lib.hvdtpu_response_cache_entries())

    # ---- capability surface -------------------------------------------
    # Frontends re-export exactly these names (single source of truth).
    CAPABILITY_NAMES = (
        "gloo_built", "gloo_enabled", "mpi_built", "mpi_enabled",
        "mpi_threads_supported", "xla_built", "xla_enabled", "nccl_built",
        "cuda_built", "rocm_built", "ccl_built", "ddl_built",
        "tf_native_ops_built", "tf_native_ops_buildable")

    # Reference analog: horovod/common/basics.py mpi_built/gloo_built/
    # nccl_built/... — scripts probe these to pick code paths. Mapping:
    # the TCP controller+ring plays Gloo's role (always built in), MPI is
    # supported as a LAUNCH mode (mpirun env pickup, not an MPI library
    # link), the xla_ici device plane replaces NCCL, and the CUDA/ROCm/
    # oneCCL/DDL backends have no TPU analog.

    def gloo_built(self, verbose=False):
        """The built-in TCP controller + ring collectives (Gloo's role)."""
        del verbose
        return True

    def gloo_enabled(self):
        return True

    def mpi_built(self, verbose=False):
        """True: mpirun/srun/jsrun launches are supported via env pickup
        (HOROVOD_* derived from OMPI/SLURM/LSF variables)."""
        del verbose
        return True

    def mpi_enabled(self):
        import os

        # Same launcher variables _ENV_FALLBACKS accepts for rank pickup.
        return any(v in os.environ
                   for v in self._ENV_FALLBACKS["HOROVOD_RANK"])

    def mpi_threads_supported(self):
        # The controller owns all communication from one background
        # thread; user threads only enqueue (thread-safe queue).
        return True

    def xla_built(self, verbose=False):
        """Whether the xla_ici device data plane is importable."""
        del verbose
        try:
            import jax  # noqa: F401

            return True
        except ImportError:  # pragma: no cover
            return False

    def xla_enabled(self):
        """Whether the device data plane is ACTIVE in this process."""
        import sys

        mod = sys.modules.get("horovod_tpu.jax.xla_ici")
        return bool(mod is not None and mod.active())

    def tf_native_ops_built(self, verbose=False):
        """Whether the native TF op library (CPU kernels + in-jit XLA
        custom-calls, csrc/tf_ops.cc) has actually been BUILT here.

        Strict by design (ADVICE r2): headers merely being present does
        not prove the on-demand build will succeed — see
        ``tf_native_ops_buildable`` for that weaker probe.
        """
        del verbose
        import os

        lib = os.path.join(os.path.dirname(_lib_path()), "libhvdtpu_tf.so")
        return os.path.exists(lib)

    def tf_native_ops_buildable(self, verbose=False):
        """Whether the native TF op library could be built on demand
        (tf2xla headers ship with the installed TF). Weaker than
        ``tf_native_ops_built``: the build can still fail on
        compiler/ABI mismatch."""
        del verbose
        import os

        if self.tf_native_ops_built():
            return True
        try:
            import tensorflow as tf  # noqa: F401

            return os.path.isdir(os.path.join(
                os.path.dirname(tf.__file__), "include", "tensorflow",
                "compiler", "tf2xla"))
        except ImportError:
            return False

    def nccl_built(self, verbose=False):
        del verbose
        return False  # the xla_ici device plane plays NCCL's role

    def cuda_built(self, verbose=False):
        del verbose
        return False

    def rocm_built(self, verbose=False):
        del verbose
        return False

    def ccl_built(self, verbose=False):
        del verbose
        return False

    def ddl_built(self, verbose=False):
        del verbose
        return False
