"""Framework-agnostic exceptions.

Reference analog: ``horovod/common/exceptions.py`` (HorovodInternalError,
HostsUpdatedInterrupt) — the exceptions elastic mode catches to drive
restore/re-rendezvous (SURVEY.md §3.4).
"""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective fails (peer death, shape
    mismatch, shutdown mid-flight). Elastic mode catches this to roll back
    to the last committed state."""


class HorovodPeerFailureError(HorovodInternalError):
    """A specific peer died or went unresponsive mid-collective — the
    typed form of :class:`HorovodInternalError` raised when the native
    core stops on a peer failure (``hvdtpu_last_fault``).

    Carries the core's attribution so recovery glue can re-form the ring
    over survivors without a full re-rendezvous (``docs/elastic.md``):

    - ``fault_ranks``: global ranks (old numbering) declared dead —
      exact for SIGKILL/EOF (every survivor converges on the same set
      via the socket probe sweep), best-effort for silent stalls;
    - ``epoch``: the membership epoch that faulted;
    - ``detect_ms``: how long the failing operation ran before the
      typed error surfaced (bounded by ``HOROVOD_WIRE_TIMEOUT_MS``).

    Still a :class:`HorovodInternalError`: every existing elastic catch
    block recovers from it unchanged.
    """

    def __init__(self, message, fault_ranks=(), epoch=0, detect_ms=None):
        super().__init__(message)
        self.fault_ranks = tuple(fault_ranks)
        self.epoch = epoch
        self.detect_ms = detect_ms


class HorovodWireCorruptionError(HorovodPeerFailureError):
    """A CRC-protected wire chunk failed integrity verification past the
    retry budget (``HOROVOD_WIRE_CRC``, ``docs/wire.md``) — the link to
    a LIVE peer is corrupting data.

    The typed guarantee: corrupted bytes were NEVER reduced into a
    result (the receiver only hands a chunk onward after its CRC32C
    verifies). ``fault_ranks`` names the sending peer and ``chunk`` the
    failing chunk index. Still a :class:`HorovodInternalError`, so
    elastic recovery rolls back and re-forms — but the core records the
    fault as suspicion, not proof of death, so driver-less recovery
    re-initializes the full world instead of shrinking out a live rank.
    """

    def __init__(self, message, fault_ranks=(), epoch=0, detect_ms=None,
                 chunk=None):
        super().__init__(message, fault_ranks=fault_ranks, epoch=epoch,
                         detect_ms=detect_ms)
        self.chunk = chunk


class HostsUpdatedInterrupt(Exception):
    """Raised in elastic mode when the discovery script reports a host
    topology change; training re-rendezvouses without state rollback.

    ``skip_sync`` mirrors the reference: when True the worker set only
    grew, so existing ranks keep their state without a broadcast.
    """

    def __init__(self, skip_sync=False):
        super().__init__()
        self.skip_sync = skip_sync


class HorovodVersionMismatchError(ImportError):
    """Frontend/core version skew detected at import time."""
