"""Framework-agnostic exceptions.

Reference analog: ``horovod/common/exceptions.py`` (HorovodInternalError,
HostsUpdatedInterrupt) — the exceptions elastic mode catches to drive
restore/re-rendezvous (SURVEY.md §3.4).
"""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective fails (peer death, shape
    mismatch, shutdown mid-flight). Elastic mode catches this to roll back
    to the last committed state."""


class HostsUpdatedInterrupt(Exception):
    """Raised in elastic mode when the discovery script reports a host
    topology change; training re-rendezvouses without state rollback.

    ``skip_sync`` mirrors the reference: when True the worker set only
    grew, so existing ranks keep their state without a broadcast.
    """

    def __init__(self, skip_sync=False):
        super().__init__()
        self.skip_sync = skip_sync


class HorovodVersionMismatchError(ImportError):
    """Frontend/core version skew detected at import time."""
