"""Eager host-side collective ops over numpy buffers — the shared engine
behind every frontend's ``allreduce_async`` / ``synchronize`` pair.

Reference analog: the per-framework C bindings
(``horovod/torch/mpi_ops_v2.cc``, ``horovod/tensorflow/mpi_ops.cc``) that
adapt framework tensors onto ``EnqueueTensorAllreduce``/... Ours adapts any
array exposing the buffer protocol (numpy; jax/torch frontends convert).
"""

import ctypes

import numpy as np

from horovod_tpu.common import exceptions as _exceptions
from horovod_tpu.common.basics import HorovodBasics

_basics = HorovodBasics()

# Must match csrc/common.h DataType.
_DTYPE_TO_ENUM = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.float16): 4,
    # bfloat16 registered lazily below (ml_dtypes ships with jax).
    np.dtype(np.float32): 6,
    np.dtype(np.float64): 7,
    np.dtype(np.bool_): 8,
    np.dtype(np.uint16): 9,
}

try:
    import ml_dtypes

    _DTYPE_TO_ENUM[np.dtype(ml_dtypes.bfloat16)] = 5
except ImportError:  # pragma: no cover
    pass


class ReduceOp:
    """Reduction ops. Reference analog: horovod ReduceOp / hvd.Sum etc."""

    AVERAGE = 0
    SUM = 1
    MIN = 2
    MAX = 3
    PRODUCT = 4
    ADASUM = 5


def _dtype_enum(dtype):
    try:
        return _DTYPE_TO_ENUM[np.dtype(dtype)]
    except KeyError:
        raise ValueError(f"Unsupported dtype for hvdtpu collectives: {dtype}")


def _shape_array(shape):
    return (ctypes.c_int64 * max(len(shape), 1))(*shape)


def _as_contig(array):
    # NOT np.ascontiguousarray: that promotes 0-d arrays to 1-d, breaking
    # scalar allreduce round-trip shape (hvd.allreduce(scalar) must return
    # a scalar, as the reference does).
    arr = np.asarray(array)
    if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return arr


class Handle:
    """An in-flight collective. Keeps the host buffers alive until done.

    Reference analog: the integer handles of horovod/torch/mpi_ops.py
    (``synchronize``/``poll``).
    """

    def __init__(self, raw, inputs, output, gathered, dtype):
        self._raw = raw
        self._inputs = inputs        # pinned until completion
        self._output = output        # allreduce/broadcast result buffer
        self._gathered = gathered    # True => fetch managed output
        self._dtype = dtype
        self._done = False

    @property
    def raw(self):
        return self._raw

    def poll(self):
        lib = _basics.lib
        rc = lib.hvdtpu_poll(self._raw)
        if rc < 0:
            raise ValueError(f"invalid Horovod handle {self._raw}")
        return rc == 1

    def synchronize(self):
        if self._done:
            raise ValueError("handle already synchronized")
        lib = _basics.lib
        rc = lib.hvdtpu_wait(self._raw)
        if rc != 0:
            err = lib.hvdtpu_error_string(self._raw)
            msg = err.decode() if err else "unknown error"
            lib.hvdtpu_release(self._raw)
            self._done = True
            raise _internal_error(msg)
        if self._gathered:
            ndim = lib.hvdtpu_result_ndim(self._raw)
            shape_buf = (ctypes.c_int64 * max(ndim, 1))()
            lib.hvdtpu_result_shape(self._raw, shape_buf)
            shape = tuple(shape_buf[i] for i in range(ndim))
            out = np.empty(shape, dtype=self._dtype)
            nbytes = out.nbytes
            if nbytes:
                lib.hvdtpu_result_copy(
                    self._raw, out.ctypes.data_as(ctypes.c_void_p), nbytes)
            else:
                out = np.empty(shape, dtype=self._dtype)
            result = out
        else:
            result = self._output
        lib.hvdtpu_release(self._raw)
        self._done = True
        self._inputs = None
        return result


# Canonical definitions live in common/exceptions.py; re-exported here so
# eager-op callers and elastic-mode catch blocks see the same class.
HorovodInternalError = _exceptions.HorovodInternalError
HorovodPeerFailureError = _exceptions.HorovodPeerFailureError
HorovodWireCorruptionError = _exceptions.HorovodWireCorruptionError
HorovodVersionMismatchError = _exceptions.HorovodVersionMismatchError


def _internal_error(msg):
    """Build the recoverable error for a failed collective: the typed
    :class:`HorovodWireCorruptionError` when a CRC-protected link
    corrupted past the retry budget, :class:`HorovodPeerFailureError`
    (with the core's fault attribution) when the runtime stopped on a
    lost peer, the plain :class:`HorovodInternalError` otherwise."""
    fault = _basics.last_fault()
    # A recovered record belongs to a previous epoch: an ordinary error
    # in the re-formed ring must not masquerade as a peer failure.
    if fault is not None and not fault.get("recovered"):
        if fault.get("kind") == "corruption":
            return HorovodWireCorruptionError(
                f"{msg}: {fault.get('reason', '')}",
                fault_ranks=fault.get("ranks", ()),
                epoch=fault.get("epoch", 0),
                detect_ms=fault.get("detect_ms"),
                chunk=fault.get("chunk"))
        return HorovodPeerFailureError(
            msg, fault_ranks=fault.get("ranks", ()),
            epoch=fault.get("epoch", 0),
            detect_ms=fault.get("detect_ms"))
    return HorovodInternalError(msg)


def _check_handle(h, name):
    if h < 0:
        if _basics.lib.hvdtpu_loop_failed():
            # The background loop died on a control- or data-plane
            # failure (a peer was lost): the elastic-recoverable
            # condition, same as a collective failing in flight.
            raise _internal_error(
                f"cannot enqueue {name}: collective runtime failed "
                "(peer lost)")
        raise RuntimeError(
            f"Failed to enqueue {name} (is Horovod initialized and running?)")
    return h


def allreduce_async(array, name, op=ReduceOp.SUM, prescale_factor=1.0,
                    postscale_factor=1.0, process_set_id=0):
    arr = _as_contig(array)
    out = np.empty_like(arr)
    lib = _basics.lib
    h = lib.hvdtpu_enqueue_allreduce(
        name.encode(), arr.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p), arr.ndim,
        _shape_array(arr.shape), _dtype_enum(arr.dtype), int(op),
        float(prescale_factor), float(postscale_factor), int(process_set_id))
    return Handle(_check_handle(h, "allreduce"), (arr,), out, False, arr.dtype)


def grouped_allreduce_async(arrays, names, op=ReduceOp.SUM,
                            prescale_factor=1.0, postscale_factor=1.0,
                            process_set_id=0):
    """Enqueue a list of same-dtype tensors as one atomic negotiation group.

    Reference analog: grouped allreduce via horovod/common/group_table.cc —
    all tensors in the group negotiate and fuse together.
    Returns a list of Handles (one per tensor).
    """
    n = len(arrays)
    if n == 0:
        return []
    if len(names) != n:
        raise ValueError(
            f"grouped_allreduce: {n} arrays but {len(names)} names")
    arrs = [_as_contig(a) for a in arrays]
    dtype = arrs[0].dtype
    if any(a.dtype != dtype for a in arrs):
        raise ValueError("grouped_allreduce requires a single common dtype")
    outs = [np.empty_like(a) for a in arrs]
    c_names = (ctypes.c_char_p * n)(*[s.encode() for s in names])
    c_inputs = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs])
    c_outputs = (ctypes.c_void_p * n)(
        *[o.ctypes.data_as(ctypes.c_void_p).value for o in outs])
    c_ndims = (ctypes.c_int * n)(*[a.ndim for a in arrs])
    shape_bufs = [_shape_array(a.shape) for a in arrs]
    c_shapes = (ctypes.POINTER(ctypes.c_int64) * n)(
        *[ctypes.cast(b, ctypes.POINTER(ctypes.c_int64)) for b in shape_bufs])
    c_handles = (ctypes.c_int * n)()
    lib = _basics.lib
    rc = lib.hvdtpu_enqueue_grouped_allreduce(
        n, c_names, c_inputs, c_outputs, c_ndims, c_shapes,
        _dtype_enum(dtype), int(op), float(prescale_factor),
        float(postscale_factor), int(process_set_id), c_handles)
    handles = [Handle(c_handles[i], (arrs[i],), outs[i], False, dtype)
               for i in range(max(rc, 0))]
    if rc < n:
        # rc == 0: the core pre-validated (nulls, duplicate names,
        # in-flight collisions) and enqueued nothing. rc > 0 can only be
        # the shutdown race, where the loop-exit orphan sweep fails the
        # queued prefix — so draining here sees errors, never a hang
        # (atomic groups otherwise wait for their missing members).
        for h in handles:
            try:
                h.synchronize()
            except HorovodInternalError:
                pass
        if _basics.lib.hvdtpu_loop_failed():
            raise _internal_error(
                "cannot enqueue grouped allreduce: collective runtime "
                "failed (peer lost)")
        raise RuntimeError(
            f"Failed to enqueue grouped allreduce (tensor {max(rc, 0)})")
    return handles


def allgather_async(array, name, process_set_id=0, group_id=-1,
                    group_size=0):
    arr = _as_contig(array)
    lib = _basics.lib
    h = lib.hvdtpu_enqueue_allgather(
        name.encode(), arr.ctypes.data_as(ctypes.c_void_p), arr.ndim,
        _shape_array(arr.shape), _dtype_enum(arr.dtype), int(process_set_id),
        int(group_id), int(group_size))
    return Handle(_check_handle(h, "allgather"), (arr,), None, True, arr.dtype)


def grouped_allgather_async(arrays, names, process_set_id=0):
    """Allgather a list of tensors as ONE negotiation group: the
    coordinator holds every member until the whole group is ready on all
    ranks, so the gathers complete atomically (reference analog:
    hvd.grouped_allgather; group_table.cc machinery — responses stay
    per-tensor, only allreduce buffer-fuses)."""
    gid = _basics.lib.hvdtpu_next_group_id() if len(arrays) > 1 else -1
    return [allgather_async(a, n, process_set_id=process_set_id,
                            group_id=gid, group_size=len(arrays))
            for a, n in zip(arrays, names)]


def broadcast_async(array, root_rank, name, process_set_id=0):
    # In-place on a private copy; synchronize() returns the broadcast value.
    arr = np.array(array, copy=True, order="C")
    lib = _basics.lib
    h = lib.hvdtpu_enqueue_broadcast(
        name.encode(), arr.ctypes.data_as(ctypes.c_void_p), arr.ndim,
        _shape_array(arr.shape), _dtype_enum(arr.dtype), int(root_rank),
        int(process_set_id))
    return Handle(_check_handle(h, "broadcast"), (arr,), arr, False, arr.dtype)


def alltoall_async(array, splits, name, process_set_id=0):
    arr = _as_contig(array)
    lib = _basics.lib
    if splits is not None:
        splits_arr = np.ascontiguousarray(np.asarray(splits, dtype=np.int64))
        splits_ptr = splits_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    else:
        splits_arr = None
        splits_ptr = None
    h = lib.hvdtpu_enqueue_alltoall(
        name.encode(), arr.ctypes.data_as(ctypes.c_void_p), arr.ndim,
        _shape_array(arr.shape), _dtype_enum(arr.dtype), splits_ptr,
        int(process_set_id))
    return Handle(_check_handle(h, "alltoall"), (arr, splits_arr), None, True,
                  arr.dtype)


def reducescatter_async(array, name, op=ReduceOp.SUM, prescale_factor=1.0,
                        postscale_factor=1.0, process_set_id=0,
                        group_id=-1, group_size=0):
    arr = _as_contig(array)
    lib = _basics.lib
    h = lib.hvdtpu_enqueue_reducescatter(
        name.encode(), arr.ctypes.data_as(ctypes.c_void_p), arr.ndim,
        _shape_array(arr.shape), _dtype_enum(arr.dtype), int(op),
        float(prescale_factor), float(postscale_factor), int(process_set_id),
        int(group_id), int(group_size))
    return Handle(_check_handle(h, "reducescatter"), (arr,), None, True,
                  arr.dtype)


def grouped_reducescatter_async(arrays, names, op=ReduceOp.SUM,
                                process_set_id=0):
    """Reduce-scatter a list of tensors as ONE negotiation group
    (atomic completion; see grouped_allgather_async)."""
    gid = _basics.lib.hvdtpu_next_group_id() if len(arrays) > 1 else -1
    return [reducescatter_async(a, n, op=op,
                                process_set_id=process_set_id,
                                group_id=gid, group_size=len(arrays))
            for a, n in zip(arrays, names)]


def barrier(process_set_id=0):
    lib = _basics.lib
    h = lib.hvdtpu_enqueue_barrier(int(process_set_id))
    Handle(_check_handle(h, "barrier"), (), None, False, None).synchronize()


def join():
    """This rank is out of data: contribute zeros to other ranks' collectives
    until every rank joins. Blocks; returns the last rank to join.

    Reference analog: ``hvd.join`` (horovod/torch/mpi_ops.py: join →
    horovod_join in operations.cc).
    """
    lib = _basics.lib
    h = lib.hvdtpu_enqueue_join()
    Handle(_check_handle(h, "join"), (), None, False, None).synchronize()
    return int(lib.hvdtpu_last_joined_rank())
