"""Process sets: collectives over subgroups of ranks.

Reference analog: ``horovod/common/process_sets.py`` (``ProcessSet``,
``hvd.add_process_set``, ``hvd.remove_process_set``, ``global_process_set``).
Registration must happen in the same order on every rank; ``add_process_set``
ends with a global barrier so no rank can use a set before every rank has
registered it.
"""

import ctypes

from horovod_tpu.common.basics import HorovodBasics

_basics = HorovodBasics()

# id -> sorted member ranks, for consumers that only hold an id (e.g. the
# xla_ici data plane mapping a fused response's process set onto a device
# sub-mesh). Populated by add_process_set on this rank.
_members_by_id = {}


def members_of(process_set_id):
    """Member ranks of a registered set; the world for id 0; None if the
    id was never registered on this rank."""
    if process_set_id == 0:
        n = _basics.size()
        return list(range(n)) if n and n > 0 else None
    return _members_by_id.get(process_set_id)


class ProcessSet:
    """A subgroup of ranks collectives can run over.

    Pass either to ``add_process_set`` or use the module-level helper with a
    plain rank list. ``process_set_id`` is assigned at registration.
    """

    process_set_id = None

    def __init__(self, ranks):
        self.ranks = None if ranks is None else sorted(int(r) for r in ranks)

    def size(self):
        """Number of ranks in the set (or None before registration)."""
        if self.process_set_id is None:
            return None if self.ranks is None else len(self.ranks)
        n = _basics.lib.hvdtpu_process_set_size(self.process_set_id)
        return None if n < 0 else n

    def rank(self):
        """This process's rank within the set, or None if not included."""
        if self.process_set_id is None:
            return None
        r = _basics.lib.hvdtpu_process_set_rank(self.process_set_id)
        return None if r < 0 else r

    def included(self):
        """Whether this process belongs to the set."""
        return self.rank() is not None

    def __index__(self):  # ops accept ProcessSet wherever an id is expected
        if self.process_set_id is None:
            raise ValueError(
                "ProcessSet is not registered; call hvd.add_process_set first")
        return self.process_set_id

    def __repr__(self):
        return (f"ProcessSet(id={self.process_set_id}, "
                f"ranks={self.ranks})")


global_process_set = ProcessSet(None)
global_process_set.process_set_id = 0


def _barrier():
    from horovod_tpu.common import eager_ops

    eager_ops.barrier()


def add_process_set(process_set):
    """Register a new process set (collective: every rank must call this with
    the same ranks, in the same order as any other add_process_set calls).

    Accepts a ``ProcessSet`` or a list of ranks; returns the registered
    ``ProcessSet`` with ``process_set_id`` assigned.
    """
    ps = process_set if isinstance(process_set, ProcessSet) \
        else ProcessSet(process_set)
    if ps.process_set_id is not None:
        raise ValueError(f"{ps!r} is already registered")
    if not ps.ranks:
        raise ValueError("a process set needs at least one rank")
    arr = (ctypes.c_int32 * len(ps.ranks))(*ps.ranks)
    set_id = _basics.lib.hvdtpu_add_process_set(arr, len(ps.ranks))
    if set_id < 0:
        raise ValueError(f"invalid process set ranks {ps.ranks}")
    ps.process_set_id = set_id
    _members_by_id[set_id] = list(ps.ranks)
    # No rank may enqueue on the new set before every rank registered it.
    _barrier()
    return ps


def remove_process_set(process_set):
    """Deregister a process set (same same-order requirement as add)."""
    ps_id = int(process_set)
    if ps_id == 0:
        raise ValueError("cannot remove the global process set")
    _barrier()  # drain any in-flight collectives on the set first
    rc = _basics.lib.hvdtpu_remove_process_set(ps_id)
    _members_by_id.pop(ps_id, None)
    if isinstance(process_set, ProcessSet):
        process_set.process_set_id = None
    return rc == 0
