"""Spark integration.

Reference analog: ``horovod/spark/__init__.py`` — ``horovod.spark.run(fn)``
executes ``fn`` on ``num_proc`` Spark executors as one barrier-stage job
with the collective core initialized, and returns each rank's result.
Estimator-style training (fit a model on a DataFrame) lives in
``horovod_tpu.spark.keras`` / ``horovod_tpu.spark.torch``; artifact
persistence in ``horovod_tpu.spark.common.store``.

pyspark is optional at import time: only ``run``/estimator ``fit`` require
it (reference behaves the same — horovod.spark imports pyspark lazily
inside run()).
"""

from horovod_tpu.spark.runner import run, run_elastic  # noqa: F401
from horovod_tpu.spark.common.store import (  # noqa: F401
    DBFSLocalStore,
    FilesystemStore,
    HDFSStore,
    LocalStore,
    Store,
)
