"""Spark Keras estimator.

Reference analog: ``horovod/spark/keras/estimator.py`` (KerasEstimator →
KerasModel): ``fit(df)`` materializes the DataFrame to the store as
parquet, trains with ``horovod_tpu.spark.run`` (every executor wraps the
optimizer in ``horovod_tpu.keras.DistributedOptimizer``), and returns a
model wrapper whose ``transform(df)`` appends predictions.

The petastorm reader of the reference is replaced by a pandas/pyarrow
parquet path — the store's data is plain parquet either way.
"""

import numpy as np

from horovod_tpu.spark.common.fit import (  # noqa: F401 — re-exported
    AsyncParquetBatchReader,
    _df_to_parquet,
    _load_np,
    collect_trained,
    split_validation,
    stage_train_data,
    use_streaming,
)
from horovod_tpu.spark.common.params import EstimatorParams


class KerasEstimator(EstimatorParams):
    """fit(df) -> KerasModel. Params mirror the reference estimator."""

    def __init__(self, **kwargs):
        self.custom_objects = kwargs.pop("custom_objects", None)
        super().__init__(**kwargs)

    def fit(self, df, spark=None):
        from horovod_tpu.spark import run as spark_run

        train_path = stage_train_data(self, df)
        # validation= (fraction or marker column) splits the STAGED
        # parquet — reference estimator contract (validation /
        # validation_steps_per_epoch params).
        train_path, val_path = split_validation(
            train_path, self.validation, seed=self.random_seed or 0)

        # Locals only below: the train closure must not capture self, or
        # cloudpickle ships the live model/store to executors alongside
        # the explicit HDF5 bytes (and fails outright on unpicklable
        # TF internals).
        model_bytes = _serialize_keras(self.model)
        custom_objects = self.custom_objects
        params = dict(
            train_path=train_path, feature_cols=tuple(self.feature_cols),
            label_cols=tuple(self.label_cols), batch_size=self.batch_size,
            epochs=self.epochs, loss=self.loss, metrics=tuple(self.metrics),
            verbose=self.verbose,
            streaming=use_streaming(self.inmemory_cache_all, train_path),
            shuffle=bool(self.shuffle_buffer_size),
            val_path=val_path,
            val_steps=self.validation_steps_per_epoch,
            seed=self.random_seed or 0)

        def train():
            import horovod_tpu.keras as hvd

            hvd.init()
            model = _deserialize_keras(model_bytes, custom_objects)
            opt = hvd.DistributedOptimizer(model.optimizer)
            model.compile(optimizer=opt, loss=params["loss"] or model.loss,
                          metrics=list(params["metrics"]))
            callbacks = [hvd.callbacks.BroadcastGlobalVariablesCallback(0),
                         hvd.callbacks.MetricAverageCallback()]
            verbose = params["verbose"] if hvd.rank() == 0 else 0

            # Per-epoch validation from the staged val split (sharded
            # across ranks like training — the split preserves the
            # per-file layout; MetricAverageCallback averages val_*
            # metrics across ranks). Streaming mode streams validation
            # too: the val split inherits the reason streaming was
            # chosen.
            val_kwargs = {}
            val_reader = None
            if params["val_path"] and params["streaming"]:
                from horovod_tpu.spark.common.fit import ParquetBatchReader

                val_reader = ParquetBatchReader(
                    params["val_path"], params["feature_cols"],
                    params["label_cols"], params["batch_size"],
                    rank=hvd.rank(), size=hvd.size())
                val_steps = len(val_reader)
                if params["val_steps"]:
                    val_steps = min(val_steps, params["val_steps"])
            elif params["val_path"]:
                vx, vy = _load_np(params["val_path"],
                                  params["feature_cols"],
                                  params["label_cols"], hvd.rank(),
                                  hvd.size())
                if params["val_steps"]:
                    n = min(len(vx),
                            params["val_steps"] * params["batch_size"])
                    vx, vy = vx[:n], vy[:n]
                val_kwargs = {"validation_data": (vx, vy)}
            if params["streaming"]:
                # Large dataset: stream batches from the staged parquet
                # with background prefetch instead of materializing the
                # whole shard (the petastorm reader path).
                reader = AsyncParquetBatchReader(
                    path=params["train_path"],
                    feature_cols=params["feature_cols"],
                    label_cols=params["label_cols"],
                    batch_size=params["batch_size"],
                    rank=hvd.rank(), size=hvd.size(),
                    shuffle=params["shuffle"], seed=params["seed"])
                steps = len(reader)

                # One fit call per keras epoch, each on a FRESH reader
                # pass: keras/tf.data prefetching can pull batches past
                # the steps_per_epoch boundary, which with a single
                # infinite generator would drift the reader's epoch (and
                # its per-epoch shuffle order) out of alignment with
                # keras epochs. Stateful callbacks carry across the
                # calls; histories are concatenated.
                history = {}
                try:
                    for epoch in range(params["epochs"]):
                        if val_reader is not None:
                            # Fresh streaming pass per epoch (generator
                            # validation_data requires explicit steps).
                            val_kwargs = {
                                "validation_data": iter(val_reader),
                                "validation_steps": val_steps,
                            }
                        hist = model.fit(iter(reader),
                                         steps_per_epoch=steps,
                                         epochs=epoch + 1,
                                         initial_epoch=epoch,
                                         verbose=verbose,
                                         callbacks=callbacks,
                                         **val_kwargs)
                        for k, v in hist.history.items():
                            history.setdefault(k, []).extend(v)
                finally:
                    reader.close_async_loader()
            else:
                x, y = _load_np(params["train_path"],
                                params["feature_cols"],
                                params["label_cols"], hvd.rank(),
                                hvd.size())
                history = model.fit(x, y, batch_size=params["batch_size"],
                                    epochs=params["epochs"],
                                    verbose=verbose, callbacks=callbacks,
                                    **val_kwargs).history
            if hvd.rank() == 0:
                return _serialize_keras(model), history
            return None

        results = spark_run(train, num_proc=self.num_proc, spark=spark)
        trained_bytes, history = collect_trained(results)
        return KerasModel(trained_bytes, self.feature_cols, self.label_cols,
                          self.custom_objects, history)


class KerasModel:
    """The fitted transformer (reference: KerasModel.transform)."""

    def __init__(self, model_bytes, feature_cols, label_cols, custom_objects,
                 history=None):
        self._model_bytes = model_bytes
        self.feature_cols = tuple(feature_cols)
        self.label_cols = tuple(label_cols)
        self.custom_objects = custom_objects
        self.history = history
        self._model = None

    def getModel(self):
        if self._model is None:
            self._model = _deserialize_keras(self._model_bytes,
                                             self.custom_objects)
        return self._model

    def transform(self, df):
        model_bytes = self._model_bytes
        feature_cols = self.feature_cols
        custom_objects = self.custom_objects
        out_col = self.label_cols[0] + "__output"

        def predict(iterator):
            model = _deserialize_keras(model_bytes, custom_objects)
            for pdf in iterator:
                x = np.stack([np.asarray(v, np.float32) for v in
                              pdf[list(feature_cols)].to_numpy().tolist()])
                if x.ndim == 3 and x.shape[1] == 1:
                    x = x[:, 0]
                pdf[out_col] = list(model.predict(x, verbose=0))
                yield pdf

        schema = df.schema.add(out_col, "array<float>")
        return df.mapInPandas(predict, schema=schema)


def _serialize_keras(model):
    import io

    import h5py

    buf = io.BytesIO()
    with h5py.File(buf, "w") as f:
        model.save(f)
    return buf.getvalue()


def _deserialize_keras(blob, custom_objects=None):
    import io

    import h5py
    import tensorflow as tf

    with h5py.File(io.BytesIO(blob), "r") as f:
        return tf.keras.models.load_model(
            f, custom_objects=dict(custom_objects or {}))
