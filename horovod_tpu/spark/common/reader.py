"""Streaming parquet batch reader for the estimator data path.

Reference analog: petastorm's ``make_batch_reader`` +
``BatchedDataLoader`` (``horovod/spark/common/store.py`` data path) —
the reference streams training data from the store's parquet files so
datasets far larger than worker RAM can be fitted. This is the
TPU-build equivalent, founded on pyarrow instead of petastorm:

- **Sharding by row group** (petastorm's unit): every rank takes row
  groups round-robin, so shards balance even when file sizes don't.
- **Bounded memory**: one row group is decoded at a time via
  ``pyarrow.parquet``; batches are sliced out and the remainder carried
  into the next row group.
- **Async prefetch**: ``AsyncParquetBatchReader`` mixes in
  ``horovod_tpu.data.AsyncDataLoaderMixin`` so decoding overlaps the
  train step (the petastorm reader-pool analog).
"""

import numpy as np

from horovod_tpu.data import AsyncDataLoaderMixin, BaseDataLoader


def frame_to_xy(df, feature_cols, label_cols):
    """pandas frame -> (x, y) arrays; vector-valued feature columns
    (lists from Spark VectorUDT staging) are stacked.

    Features cast to float32 (model inputs). Labels KEEP integer dtypes
    — classification targets round-trip as ints through the reader
    (sparse-categorical/cross-entropy losses need them); everything else
    (floats, bools — BCE wants float targets) normalizes to float32.
    """
    x = np.stack([np.asarray(v, np.float32)
                  for v in df[list(feature_cols)].to_numpy().tolist()])
    if x.ndim == 3 and x.shape[1] == 1:
        x = x[:, 0]
    y = df[list(label_cols)].to_numpy()
    if not np.issubdtype(y.dtype, np.integer):
        y = y.astype(np.float32)
    return x, y


def _parquet_files(path):
    import os

    return sorted(
        os.path.join(path, f) for f in os.listdir(path)
        if f.endswith(".parquet"))


def staged_bytes(path):
    """Total on-disk size of a staged parquet directory."""
    import os

    return sum(os.path.getsize(f) for f in _parquet_files(path))


class ParquetBatchReader(BaseDataLoader):
    """Iterate (x, y) numpy batches from a staged parquet directory.

    One pass per ``__iter__`` call; wrap with ``AsyncParquetBatchReader``
    for prefetch. ``shuffle`` permutes the row-group visit order per
    epoch (petastorm's ``shuffle_row_groups``) — rows within a group
    keep their order, the standard bounded-memory trade.
    """

    def __init__(self, path, feature_cols, label_cols, batch_size,
                 rank=0, size=1, shuffle=False, seed=0):
        import pyarrow.parquet as pq

        self._feature_cols = tuple(feature_cols)
        self._label_cols = tuple(label_cols)
        self._batch_size = int(batch_size)
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0

        groups = []  # (file, row_group_index, num_rows)
        for f in _parquet_files(path):
            meta = pq.ParquetFile(f).metadata
            for g in range(meta.num_row_groups):
                groups.append((f, g, meta.row_group(g).num_rows))
        if not groups:
            raise ValueError(f"no parquet row groups under {path}")
        if len(groups) >= size:
            shard = groups[rank::size]
            # Every rank must issue the SAME number of batches per epoch
            # (the train loops run one collective per batch; a longer
            # shard would deadlock on unmatched allreduces). All ranks
            # see the full group list, so each derives the common step
            # count locally and truncates its own tail.
            steps = [
                -(-sum(n for _, _, n in groups[r::size]) // batch_size)
                for r in range(size)]
            self._steps = max(min(steps), 1)
        else:
            # Degenerate staging (fewer row groups than ranks): every
            # rank reads everything — replicated but collectively equal.
            shard = list(groups)
            self._steps = max(
                -(-sum(n for _, _, n in shard) // batch_size), 1)
        self._shard = shard
        self._rows = sum(n for _, _, n in shard)

    @property
    def rows(self):
        return self._rows

    def __len__(self):
        """Batches per epoch — identical on every rank (the minimum over
        shards, so distributed train loops stay collectively matched)."""
        return self._steps

    def _iterate(self):
        import pyarrow.parquet as pq

        order = list(range(len(self._shard)))
        if self._shuffle:
            rng = np.random.RandomState(self._seed + self._epoch)
            rng.shuffle(order)
        self._epoch += 1

        cols = list(self._feature_cols) + list(self._label_cols)
        carry_x, carry_y = None, None
        bs = self._batch_size
        emitted = 0
        for i in order:
            if emitted >= self._steps:
                return
            f, g, _ = self._shard[i]
            table = pq.ParquetFile(f).read_row_group(g, columns=cols)
            x, y = frame_to_xy(table.to_pandas(), self._feature_cols,
                               self._label_cols)
            if carry_x is not None:
                x = np.concatenate([carry_x, x])
                y = np.concatenate([carry_y, y])
            n_full = (len(x) // bs) * bs
            for off in range(0, n_full, bs):
                yield x[off:off + bs], y[off:off + bs]
                emitted += 1
                if emitted >= self._steps:
                    return
            carry_x = x[n_full:] if n_full < len(x) else None
            carry_y = y[n_full:] if carry_x is not None else None
        if carry_x is not None and len(carry_x) and emitted < self._steps:
            yield carry_x, carry_y


class AsyncParquetBatchReader(AsyncDataLoaderMixin, ParquetBatchReader):
    """ParquetBatchReader with background prefetch (petastorm's
    reader-pool role)."""
