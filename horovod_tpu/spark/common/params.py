"""Estimator hyper-parameter bag.

Reference analog: ``horovod/spark/common/params.py`` (EstimatorParams —
a pyspark.ml Params subclass). Ours is a plain attribute bag so it works
without pyspark; the estimator API surface (feature_cols/label_cols/
batch_size/epochs/store/...) matches the reference's param names.
"""


class EstimatorParams:
    _defaults = dict(
        num_proc=None,
        model=None,
        optimizer=None,
        loss=None,
        metrics=(),
        feature_cols=("features",),
        label_cols=("label",),
        batch_size=32,
        epochs=1,
        validation=None,
        shuffle_buffer_size=None,
        verbose=1,
        store=None,
        callbacks=(),
        random_seed=None,
        run_id=None,
        train_steps_per_epoch=None,
        # Reference param (petastorm estimators): True loads the whole
        # shard into memory, False streams from parquet; None = auto by
        # staged size (HOROVOD_SPARK_INMEMORY_THRESHOLD_MB, default 512).
        inmemory_cache_all=None,
        validation_steps_per_epoch=None,
    )

    def __init__(self, **kwargs):
        unknown = set(kwargs) - set(self._defaults)
        if unknown:
            raise TypeError(f"unknown estimator params: {sorted(unknown)}")
        for key, default in self._defaults.items():
            setattr(self, key, kwargs.get(key, default))

    # pyspark.ml-style getters the reference exposes.
    def __getattr__(self, item):
        if item.startswith("get"):
            name = item[3:].lstrip("_")
            snake = "".join(
                f"_{c.lower()}" if c.isupper() else c for c in name
            ).lstrip("_")
            if snake in self._defaults:
                return lambda: getattr(self, snake)
        raise AttributeError(item)
