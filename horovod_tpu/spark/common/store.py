"""Artifact stores for estimator training.

Reference analog: ``horovod/spark/common/store.py`` — ``Store`` is where
estimators persist intermediate train/val data, checkpoints, and logs
(``LocalStore``/``HDFSStore``/``DBFSLocalStore`` upstream). Ours:
``FilesystemStore`` covers any fsspec-style mounted path (local disk, NFS,
GCS via gcsfuse on TPU VMs — the TPU-idiomatic equivalent of HDFS).
No Spark dependency: usable from plain scripts and tests.
"""

import contextlib
import os
import shutil
import tempfile


class Store:
    """Abstract artifact store (reference: store.Store)."""

    def get_train_data_path(self, idx=None):
        raise NotImplementedError()

    def get_val_data_path(self, idx=None):
        raise NotImplementedError()

    def get_test_data_path(self, idx=None):
        raise NotImplementedError()

    def get_checkpoint_path(self, run_id):
        raise NotImplementedError()

    def get_logs_path(self, run_id):
        raise NotImplementedError()

    def exists(self, path):
        raise NotImplementedError()

    def read(self, path):
        raise NotImplementedError()

    def write(self, path, data):
        raise NotImplementedError()

    def sync_fn(self, run_id):
        """Return a fn(local_dir) that persists a local run dir into the
        store (reference: Store.sync_fn used by estimator callbacks)."""
        raise NotImplementedError()

    @staticmethod
    def create(prefix_path, *args, **kwargs):
        """Factory mirroring the reference's Store.create dispatch."""
        return FilesystemStore(prefix_path, *args, **kwargs)


class FilesystemStore(Store):
    """Store rooted at a mounted filesystem prefix."""

    def __init__(self, prefix_path, train_path=None, val_path=None,
                 test_path=None, runs_path=None):
        self.prefix_path = os.path.abspath(prefix_path)
        self._train = train_path or os.path.join(self.prefix_path,
                                                 "intermediate_train_data")
        self._val = val_path or os.path.join(self.prefix_path,
                                             "intermediate_val_data")
        self._test = test_path or os.path.join(self.prefix_path,
                                               "intermediate_test_data")
        self._runs = runs_path or os.path.join(self.prefix_path, "runs")
        os.makedirs(self.prefix_path, exist_ok=True)

    def _with_idx(self, base, idx):
        return base if idx is None else f"{base}.{idx}"

    def get_train_data_path(self, idx=None):
        return self._with_idx(self._train, idx)

    def get_val_data_path(self, idx=None):
        return self._with_idx(self._val, idx)

    def get_test_data_path(self, idx=None):
        return self._with_idx(self._test, idx)

    def get_run_path(self, run_id):
        return os.path.join(self._runs, run_id)

    def get_checkpoint_path(self, run_id):
        return os.path.join(self.get_run_path(run_id), "checkpoint")

    def get_logs_path(self, run_id):
        return os.path.join(self.get_run_path(run_id), "logs")

    def exists(self, path):
        return os.path.exists(path)

    def read(self, path):
        with open(path, "rb") as f:
            return f.read()

    def write(self, path, data):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic publish (rank-0 writer, many readers)

    def sync_fn(self, run_id):
        run_path = self.get_run_path(run_id)

        def fn(local_run_path):
            os.makedirs(run_path, exist_ok=True)
            shutil.copytree(local_run_path, run_path, dirs_exist_ok=True)

        return fn

    @contextlib.contextmanager
    def local_run_dir(self, run_id):
        """Scratch dir that syncs into the store on clean exit."""
        d = tempfile.mkdtemp(prefix=f"hvdtpu-{run_id}-")
        try:
            yield d
            self.sync_fn(run_id)(d)
        finally:
            shutil.rmtree(d, ignore_errors=True)


class LocalStore(FilesystemStore):
    """Reference-compat alias (horovod.spark.common.store.LocalStore)."""
