"""Artifact stores for estimator training.

Reference analog: ``horovod/spark/common/store.py`` — ``Store`` is where
estimators persist intermediate train/val data, checkpoints, and logs
(``LocalStore``/``HDFSStore``/``DBFSLocalStore`` upstream). Ours:
``FilesystemStore`` covers any fsspec-style mounted path (local disk, NFS,
GCS via gcsfuse on TPU VMs — the TPU-idiomatic equivalent of HDFS).
No Spark dependency: usable from plain scripts and tests.
"""

import contextlib
import os
import shutil
import tempfile


class Store:
    """Abstract artifact store (reference: store.Store)."""

    def get_train_data_path(self, idx=None):
        raise NotImplementedError()

    def get_val_data_path(self, idx=None):
        raise NotImplementedError()

    def get_test_data_path(self, idx=None):
        raise NotImplementedError()

    def get_checkpoint_path(self, run_id):
        raise NotImplementedError()

    def get_logs_path(self, run_id):
        raise NotImplementedError()

    def exists(self, path):
        raise NotImplementedError()

    def read(self, path):
        raise NotImplementedError()

    def write(self, path, data):
        raise NotImplementedError()

    def sync_fn(self, run_id):
        """Return a fn(local_dir) that persists a local run dir into the
        store (reference: Store.sync_fn used by estimator callbacks)."""
        raise NotImplementedError()

    @staticmethod
    def create(prefix_path, *args, **kwargs):
        """Factory mirroring the reference's Store.create dispatch:
        hdfs:// prefixes get the HDFS store, dbfs:/ the Databricks FUSE
        mount, anything else a plain filesystem store."""
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith("dbfs:/") \
                or prefix_path.startswith("/dbfs/"):
            return DBFSLocalStore(prefix_path, *args, **kwargs)
        return FilesystemStore(prefix_path, *args, **kwargs)


class _LayoutMixin:
    """The store path layout, shared by every concrete store. Paths are
    POSIX-style on all backends (local, FUSE mounts, HDFS)."""

    def _init_layout(self, prefix_path, train_path, val_path, test_path,
                     runs_path):
        self._train = train_path or os.path.join(prefix_path,
                                                 "intermediate_train_data")
        self._val = val_path or os.path.join(prefix_path,
                                             "intermediate_val_data")
        self._test = test_path or os.path.join(prefix_path,
                                               "intermediate_test_data")
        self._runs = runs_path or os.path.join(prefix_path, "runs")

    def _with_idx(self, base, idx):
        return base if idx is None else f"{base}.{idx}"

    def get_train_data_path(self, idx=None):
        return self._with_idx(self._train, idx)

    def get_val_data_path(self, idx=None):
        return self._with_idx(self._val, idx)

    def get_test_data_path(self, idx=None):
        return self._with_idx(self._test, idx)

    def get_run_path(self, run_id):
        return os.path.join(self._runs, run_id)

    def get_checkpoint_path(self, run_id):
        return os.path.join(self.get_run_path(run_id), "checkpoint")

    def get_logs_path(self, run_id):
        return os.path.join(self.get_run_path(run_id), "logs")


class FilesystemStore(_LayoutMixin, Store):
    """Store rooted at a mounted filesystem prefix. Directories are
    created lazily on first write, so constructing a store (e.g. via
    Store.create dispatch) never touches the filesystem."""

    def __init__(self, prefix_path, train_path=None, val_path=None,
                 test_path=None, runs_path=None):
        self.prefix_path = os.path.abspath(prefix_path)
        self._init_layout(self.prefix_path, train_path, val_path,
                          test_path, runs_path)

    def exists(self, path):
        return os.path.exists(path)

    def read(self, path):
        with open(path, "rb") as f:
            return f.read()

    def write(self, path, data):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic publish (rank-0 writer, many readers)

    def sync_fn(self, run_id):
        run_path = self.get_run_path(run_id)

        def fn(local_run_path):
            os.makedirs(run_path, exist_ok=True)
            shutil.copytree(local_run_path, run_path, dirs_exist_ok=True)

        return fn

    @contextlib.contextmanager
    def local_run_dir(self, run_id):
        """Scratch dir that syncs into the store on clean exit."""
        d = tempfile.mkdtemp(prefix=f"hvdtpu-{run_id}-")
        try:
            yield d
            self.sync_fn(run_id)(d)
        finally:
            shutil.rmtree(d, ignore_errors=True)


class LocalStore(FilesystemStore):
    """Reference-compat alias (horovod.spark.common.store.LocalStore)."""


class DBFSLocalStore(FilesystemStore):
    """Databricks DBFS store via the FUSE mount (reference:
    store.DBFSLocalStore): ``dbfs:/path`` addresses ``/dbfs/path``, after
    which it is an ordinary filesystem store."""

    def __init__(self, prefix_path, *args, **kwargs):
        super().__init__(self.normalize_datasets_path(prefix_path),
                         *args, **kwargs)

    @staticmethod
    def normalize_datasets_path(path):
        if path.startswith("dbfs:/"):
            return "/dbfs/" + path[len("dbfs:/"):].lstrip("/")
        return path


class HDFSStore(_LayoutMixin, Store):
    """HDFS-backed store (reference: store.HDFSStore), gated on a working
    libhdfs via ``pyarrow.fs.HadoopFileSystem``. The TPU-idiomatic
    deployment usually prefers a mounted FilesystemStore (NFS/gcsfuse),
    but jobs migrating from the reference keep their hdfs:// URLs."""

    def __init__(self, prefix_path, train_path=None, val_path=None,
                 test_path=None, runs_path=None, **hdfs_kwargs):
        try:
            from pyarrow import fs as _pafs

            self._fs = _pafs.HadoopFileSystem.from_uri(prefix_path)[0] \
                if hasattr(_pafs.HadoopFileSystem, "from_uri") \
                else _pafs.HadoopFileSystem(**hdfs_kwargs)
        except Exception as e:  # noqa: BLE001 — missing libhdfs/classpath
            raise ImportError(
                "HDFSStore needs pyarrow with a working libhdfs "
                "(HADOOP_HOME/CLASSPATH); for mounted storage use "
                f"FilesystemStore instead ({e})") from e
        # Strip the scheme+authority: pyarrow's fs takes plain paths.
        if "://" in prefix_path:
            rest = prefix_path.split("://", 1)[1].split("/", 1)
            self.prefix_path = "/" + (rest[1] if len(rest) > 1 else "")
        else:
            self.prefix_path = prefix_path
        self._init_layout(self.prefix_path, train_path, val_path,
                          test_path, runs_path)

    def exists(self, path):
        from pyarrow import fs as _pafs

        return self._fs.get_file_info(path).type != _pafs.FileType.NotFound

    def read(self, path):
        with self._fs.open_input_stream(path) as f:
            return f.read()

    def write(self, path, data):
        # Write-then-rename, like FilesystemStore: readers polling
        # exists() must never observe a partially-written file.
        self._fs.create_dir(path.rsplit("/", 1)[0], recursive=True)
        tmp = path + ".tmp"
        with self._fs.open_output_stream(tmp) as f:
            f.write(data)
        self._fs.move(tmp, path)

    def sync_fn(self, run_id):
        run_path = self.get_run_path(run_id)

        def fn(local_run_path):
            for root, _, files in os.walk(local_run_path):
                rel = os.path.relpath(root, local_run_path)
                dest = run_path if rel == "." else f"{run_path}/{rel}"
                for name in files:
                    with open(os.path.join(root, name), "rb") as f:
                        self.write(f"{dest}/{name}", f.read())

        return fn
