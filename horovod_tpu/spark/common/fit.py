"""Shared estimator fit scaffold: stage the DataFrame, collect results.

Reference analog: the common flow of ``horovod/spark/*/estimator.py`` —
every estimator materializes the DataFrame to the store as parquet,
launches training via ``horovod_tpu.spark.run``, and unwraps rank 0's
returned model.
"""

import os

import numpy as np

from horovod_tpu.spark.common.reader import (  # noqa: F401 — re-exported
    AsyncParquetBatchReader,
    ParquetBatchReader,
    frame_to_xy,
    staged_bytes,
)


def _df_to_parquet(df, path, num_proc):
    df.repartition(max(num_proc or 1, 1)).write.mode("overwrite").parquet(path)


def _load_np(path, feature_cols, label_cols, rank, size):
    """Whole-shard in-memory load (small datasets / inmemory_cache_all)."""
    import pandas as pd

    files = sorted(
        os.path.join(path, f) for f in os.listdir(path)
        if f.endswith(".parquet"))
    shard = files[rank::size] or files  # every rank needs >=1 shard
    frames = [pd.read_parquet(f) for f in shard]
    df = pd.concat(frames, ignore_index=True)
    return frame_to_xy(df, feature_cols, label_cols)


def use_streaming(inmemory_cache_all, train_path):
    """Stream from parquet, or load the shard in memory? Mirrors the
    reference's inmemory_cache_all petastorm switch; None decides by the
    staged size so big datasets never materialize whole."""
    if inmemory_cache_all is not None:
        return not inmemory_cache_all
    threshold_mb = float(os.environ.get(
        "HOROVOD_SPARK_INMEMORY_THRESHOLD_MB", "512"))
    return staged_bytes(train_path) > threshold_mb * (1 << 20)


def stage_train_data(estimator, df):
    """Validate the store and write the DataFrame as parquet; returns the
    staged path."""
    if estimator.store is None:
        raise ValueError(
            f"{type(estimator).__name__} needs a store= to stage data")
    train_path = estimator.store.get_train_data_path(estimator.run_id)
    _df_to_parquet(df, train_path, estimator.num_proc)
    return train_path


def collect_trained(results):
    """Unwrap the non-None (rank 0) result from a spark_run result list."""
    trained = next((r for r in results if r is not None), None)
    if trained is None:
        raise RuntimeError(
            "no rank returned a trained model — rank 0's result is missing")
    return trained
