"""Shared estimator fit scaffold: stage the DataFrame, collect results.

Reference analog: the common flow of ``horovod/spark/*/estimator.py`` —
every estimator materializes the DataFrame to the store as parquet,
launches training via ``horovod_tpu.spark.run``, and unwraps rank 0's
returned model.
"""

import os

import numpy as np

from horovod_tpu.spark.common.reader import (  # noqa: F401 — re-exported
    AsyncParquetBatchReader,
    ParquetBatchReader,
    _parquet_files,
    frame_to_xy,
    staged_bytes,
)


def _df_to_parquet(df, path, num_proc):
    df.repartition(max(num_proc or 1, 1)).write.mode("overwrite").parquet(path)


def _load_np(path, feature_cols, label_cols, rank, size):
    """Whole-shard in-memory load (small datasets / inmemory_cache_all)."""
    import pandas as pd

    files = sorted(
        os.path.join(path, f) for f in os.listdir(path)
        if f.endswith(".parquet"))
    shard = files[rank::size] or files  # every rank needs >=1 shard
    frames = [pd.read_parquet(f) for f in shard]
    df = pd.concat(frames, ignore_index=True)
    return frame_to_xy(df, feature_cols, label_cols)


def use_streaming(inmemory_cache_all, train_path):
    """Stream from parquet, or load the shard in memory? Mirrors the
    reference's inmemory_cache_all petastorm switch; None decides by the
    staged size so big datasets never materialize whole."""
    if inmemory_cache_all is not None:
        return not inmemory_cache_all
    threshold_mb = float(os.environ.get(
        "HOROVOD_SPARK_INMEMORY_THRESHOLD_MB", "512"))
    return staged_bytes(train_path) > threshold_mb * (1 << 20)


def stage_train_data(estimator, df):
    """Validate the store and write the DataFrame as parquet; returns the
    staged path."""
    if estimator.store is None:
        raise ValueError(
            f"{type(estimator).__name__} needs a store= to stage data")
    train_path = estimator.store.get_train_data_path(estimator.run_id)
    _df_to_parquet(df, train_path, estimator.num_proc)
    return train_path


def split_validation(train_path, validation, seed=0):
    """Split staged parquet into train/validation (reference analog:
    the estimators' ``validation`` param — a float fraction for a
    random row split, or a column name whose truthy rows are the
    validation set).

    Operates on the STAGED parquet (pyarrow, one row group in memory at
    a time), so it works identically for every estimator and is
    testable without Spark. Returns ``(new_train_path, val_path)`` —
    two sibling directories next to ``train_path``; the original stays
    untouched.
    """
    import pyarrow as pa
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    if validation is None:
        return train_path, None
    by_column = isinstance(validation, str)
    if not by_column and not (0.0 < float(validation) < 1.0):
        raise ValueError(
            f"validation must be a column name or a fraction in (0, 1); "
            f"got {validation!r}")

    out_train = train_path.rstrip("/") + "_train_split"
    out_val = train_path.rstrip("/") + "_val_split"
    for d in (out_train, out_val):
        os.makedirs(d, exist_ok=True)
        for f in os.listdir(d):
            os.remove(os.path.join(d, f))

    rng = np.random.RandomState(seed)
    # One output file PER SOURCE FILE (same basename): the readers and
    # _load_np shard by file/row group, so collapsing the num_proc-
    # partitioned staging into one file would silently put every rank
    # on the identical full split.
    writers = {}
    rows = {"train": 0, "val": 0}

    def append(which, base, table):
        rows[which] += table.num_rows
        if table.num_rows == 0:
            return
        key = (which, base)
        if key not in writers:
            writers[key] = pq.ParquetWriter(
                os.path.join(out_train if which == "train" else out_val,
                             base), table.schema)
        writers[key].write_table(table)

    for f in _parquet_files(train_path):
        base = os.path.basename(f)
        pf = pq.ParquetFile(f)
        for g in range(pf.metadata.num_row_groups):
            table = pf.read_row_group(g)
            if by_column:
                if validation not in table.column_names:
                    raise ValueError(
                        f"validation column {validation!r} not in staged "
                        f"data ({table.column_names})")
                mask = np.asarray(
                    table[validation].to_pandas().astype(bool))
                table = table.drop_columns([validation])
            else:
                mask = rng.random_sample(table.num_rows) < float(validation)
            mask = pa.array(mask)
            append("val", base, table.filter(mask))
            append("train", base, table.filter(pc.invert(mask)))
    for w in writers.values():
        w.close()
    if rows["train"] == 0:
        raise ValueError(
            f"validation={validation!r} selected every staged row — "
            "nothing left to train on")
    if rows["val"] == 0:
        return train_path, None  # nothing selected: keep original staging
    return out_train, out_val


def epoch_val_loss(val_path, feature_cols, label_cols, batch_size, rank,
                   size, batch_loss, average_fn):
    """One BATCHED validation pass over the staged val split (bounded
    memory — the same reader machinery as training): returns the
    cross-rank average of this rank's row-weighted mean loss. Shared by
    the torch and lightning estimators' per-epoch hooks."""
    reader = ParquetBatchReader(val_path, feature_cols, label_cols,
                                batch_size, rank=rank, size=size)
    total, n = 0.0, 0
    for xb, yb in reader:
        total += float(batch_loss(xb, yb)) * len(xb)
        n += len(xb)
    return average_fn(total / max(n, 1))


def collect_trained(results):
    """Unwrap the non-None (rank 0) result from a spark_run result list."""
    trained = next((r for r in results if r is not None), None)
    if trained is None:
        raise RuntimeError(
            "no rank returned a trained model — rank 0's result is missing")
    return trained
