"""Spark Lightning estimator.

Reference analog: ``horovod/spark/lightning/estimator.py``
(``TorchEstimator`` over LightningModules → ``TorchModel``). The
reference drives a real ``pytorch_lightning.Trainer``; here the
lightning *protocol* is duck-typed — any ``torch.nn.Module`` that
implements ``training_step(batch, batch_idx)`` and
``configure_optimizers()`` (optionally ``on_train_epoch_end()``)
trains, which includes genuine ``pytorch_lightning.LightningModule``
instances, without requiring the pytorch_lightning package in the
image. Staging flow matches the Torch estimator: DataFrame → parquet in
the store → ``horovod_tpu.spark.run`` → fitted transformer.
"""

import contextlib

from horovod_tpu.spark.common.fit import (
    _load_np,
    collect_trained,
    stage_train_data,
)
from horovod_tpu.spark.common.params import EstimatorParams
from horovod_tpu.spark.torch import (
    TorchModel,
    _deserialize_torch,
    _serialize_torch,
)


def _sched_entry(s):
    """Normalize one scheduler spec to {scheduler, interval, frequency}
    (lightning's lr_scheduler dict form; bare schedulers step per epoch)."""
    if isinstance(s, dict):
        return {"scheduler": s["scheduler"],
                "interval": s.get("interval", "epoch"),
                "frequency": s.get("frequency", 1)}
    return {"scheduler": s, "interval": "epoch", "frequency": 1}


def _unpack_optimizers(cfg):
    """Normalize configure_optimizers()'s forms: a single optimizer, a
    list of optimizers, a list/tuple of per-optimizer dicts, a
    (optimizers, schedulers) tuple, or a dict with 'optimizer'
    (+ optional 'lr_scheduler'). Scheduler specs keep their lightning
    interval/frequency metadata."""
    if isinstance(cfg, dict):
        scheds = cfg.get("lr_scheduler")
        scheds = [_sched_entry(scheds)] if scheds is not None else []
        return [cfg["optimizer"]], scheds
    if isinstance(cfg, tuple) and len(cfg) == 2 \
            and isinstance(cfg[0], (list, tuple)):
        opts, scheds = cfg
        return list(opts), [_sched_entry(s) for s in scheds]
    if isinstance(cfg, (list, tuple)):
        opts, scheds = [], []
        for item in cfg:
            o, s = _unpack_optimizers(item)
            opts.extend(o)
            scheds.extend(s)
        return opts, scheds
    return [cfg], []


def _step_loss(out):
    """training_step may return the loss tensor or a dict with 'loss'."""
    if isinstance(out, dict):
        return out["loss"]
    return out


def _param_ids(base_opt):
    return {id(p) for g in base_opt.param_groups for p in g["params"]}


@contextlib.contextmanager
def _toggle_optimizer(all_params, active_ids, other_ids):
    """Lightning's toggle_optimizer: while one optimizer trains, params
    owned by the *other* optimizers (and not shared with the active one)
    get requires_grad=False so its loss cannot deposit gradients into
    them (a GAN generator loss flows through the discriminator but must
    not train it). Params owned by no optimizer are left alone."""
    prev = [(p, p.requires_grad) for p in all_params]
    for p in all_params:
        if id(p) in other_ids and id(p) not in active_ids:
            p.requires_grad_(False)
    try:
        yield
    finally:
        for p, rg in prev:
            p.requires_grad_(rg)


def _named_params_for(model, base_opt, opt_idx):
    """Scoped (name, param) pairs for one optimizer's param groups —
    names must be distinct across optimizers for the collective layer."""
    by_id = {id(p): n for n, p in model.named_parameters()}
    out = []
    for gi, group in enumerate(base_opt.param_groups):
        for pi, p in enumerate(group["params"]):
            name = by_id.get(id(p), f"g{gi}.p{pi}")
            out.append((f"opt{opt_idx}.{name}", p))
    return out


def train_protocol_model(model, x_t, y_t, batch_size, epochs,
                         distributed=True, batch_iter=None,
                         on_epoch_end=None):
    """Run the lightning-protocol training loop on host tensors.

    ``batch_iter``: optional callable returning one epoch's iterable of
    ``(x, y)`` numpy batches (the streaming parquet reader path); when
    given, ``x_t``/``y_t``/``batch_size`` are ignored.
    ``on_epoch_end``: optional callable ``(model, epoch)`` invoked after
    each epoch (after the module's own on_train_epoch_end) — the
    estimator's per-epoch validation hook.

    With ``distributed=True`` every optimizer is wrapped in
    ``horovod_tpu.torch.DistributedOptimizer`` and parameters/optimizer
    state broadcast from rank 0 first (requires an initialized core).
    Multiple optimizers follow lightning's contract:
    ``training_step(batch, batch_idx, optimizer_idx)`` runs once per
    optimizer per batch under ``toggle_optimizer`` semantics (the other
    optimizers' params are frozen, so cross-optimizer losses cannot
    deposit gradients — or, distributed, enqueue stray allreduces).
    Schedulers honor lightning's ``interval``/``frequency`` metadata.
    """
    base_opts, scheds = _unpack_optimizers(model.configure_optimizers())
    if not base_opts:
        raise ValueError("configure_optimizers() returned no optimizer")
    multi = len(base_opts) > 1
    ids_per_opt = [_param_ids(bo) for bo in base_opts]
    if multi and distributed:
        for a in range(len(ids_per_opt)):
            for b in range(a + 1, len(ids_per_opt)):
                if ids_per_opt[a] & ids_per_opt[b]:
                    raise NotImplementedError(
                        "distributed multi-optimizer training with "
                        "parameters shared between optimizers is not "
                        "supported — each shared param would register "
                        "one gradient hook per optimizer")
    opts = list(base_opts)
    if distributed:
        import horovod_tpu.torch as hvd

        opts = [hvd.DistributedOptimizer(
                    bo, named_parameters=_named_params_for(model, bo, oi))
                for oi, bo in enumerate(base_opts)]
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        for bo in base_opts:
            hvd.broadcast_optimizer_state(bo, root_rank=0)
    all_params = list(model.parameters())
    others_per_opt = [
        set().union(*(s for j, s in enumerate(ids_per_opt) if j != oi))
        if multi else set()
        for oi in range(len(ids_per_opt))]
    model.train()
    global_step = 0

    def epoch_batches():
        if batch_iter is not None:
            import numpy as np
            import torch

            for xb, yb in batch_iter():
                yield (torch.from_numpy(np.ascontiguousarray(xb)),
                       torch.from_numpy(np.ascontiguousarray(yb)))
            return
        for i in range(0, x_t.shape[0], batch_size):
            yield (x_t[i:i + batch_size], y_t[i:i + batch_size])

    for epoch in range(epochs):
        for batch_idx, batch in enumerate(epoch_batches()):
            for oi, opt in enumerate(opts):
                with contextlib.ExitStack() as stack:
                    if multi:
                        stack.enter_context(
                            _toggle_optimizer(all_params, ids_per_opt[oi],
                                              others_per_opt[oi]))
                    opt.zero_grad()
                    loss = _step_loss(
                        model.training_step(batch, batch_idx, oi) if multi
                        else model.training_step(batch, batch_idx))
                    loss.backward()
                    opt.step()
            global_step += 1
            for s in scheds:
                if s["interval"] == "step" \
                        and global_step % s["frequency"] == 0:
                    s["scheduler"].step()
        for s in scheds:
            if s["interval"] == "epoch" \
                    and (epoch + 1) % s["frequency"] == 0:
                s["scheduler"].step()
        epoch_end = getattr(model, "on_train_epoch_end", None)
        if callable(epoch_end):
            epoch_end()
        if on_epoch_end is not None:
            on_epoch_end(model, epoch)
    return model


class LightningEstimator(EstimatorParams):
    """fit(df) -> LightningModel. Params mirror the reference estimator
    (the reference's ``TorchEstimator`` in ``horovod.spark.lightning``)."""

    def fit(self, df, spark=None):
        from horovod_tpu.spark import run as spark_run
        from horovod_tpu.spark.common.fit import split_validation

        train_path = stage_train_data(self, df)
        train_path, val_path = split_validation(
            train_path, self.validation, seed=self.random_seed or 0)

        # Locals only below (see KerasEstimator): the closure must not
        # capture self.
        model_bytes = _serialize_torch(self.model)
        from horovod_tpu.spark.common.fit import use_streaming

        params = dict(
            train_path=train_path, feature_cols=tuple(self.feature_cols),
            label_cols=tuple(self.label_cols), batch_size=self.batch_size,
            epochs=self.epochs,
            streaming=use_streaming(self.inmemory_cache_all, train_path),
            shuffle=bool(self.shuffle_buffer_size),
            val_path=val_path, seed=self.random_seed or 0)

        def train():
            import numpy as np
            import torch

            import horovod_tpu.torch as hvd

            hvd.init()
            model = _deserialize_torch(model_bytes)

            val_history = []
            on_epoch_end = None
            if params["val_path"]:
                from horovod_tpu.spark.common.fit import epoch_val_loss

                def on_epoch_end(m, epoch):
                    # validation_step if the module defines it
                    # (lightning protocol), else training_step under
                    # no_grad; one batched pass, averaged across ranks.
                    step_fn = getattr(m, "validation_step", None) \
                        or m.training_step

                    def batch_loss(xb, yb):
                        m.eval()
                        with torch.no_grad():
                            vl = _step_loss(step_fn(
                                (torch.from_numpy(np.ascontiguousarray(xb)),
                                 torch.from_numpy(np.ascontiguousarray(yb))),
                                0))
                        m.train()
                        return vl

                    val_history.append(epoch_val_loss(
                        params["val_path"], params["feature_cols"],
                        params["label_cols"], params["batch_size"],
                        hvd.rank(), hvd.size(), batch_loss,
                        lambda v: float(hvd.allreduce(
                            torch.tensor([v]), op=hvd.Average))))

            if params["streaming"]:
                from horovod_tpu.spark.common.fit import \
                    AsyncParquetBatchReader

                reader = AsyncParquetBatchReader(
                    path=params["train_path"],
                    feature_cols=params["feature_cols"],
                    label_cols=params["label_cols"],
                    batch_size=params["batch_size"],
                    rank=hvd.rank(), size=hvd.size(),
                    shuffle=params["shuffle"], seed=params["seed"])
                try:
                    train_protocol_model(
                        model, None, None, params["batch_size"],
                        params["epochs"],
                        batch_iter=lambda: iter(reader),
                        on_epoch_end=on_epoch_end)
                finally:
                    reader.close_async_loader()
            else:
                x, y = _load_np(params["train_path"],
                                params["feature_cols"],
                                params["label_cols"], hvd.rank(),
                                hvd.size())
                train_protocol_model(
                    model, torch.from_numpy(np.ascontiguousarray(x)),
                    torch.from_numpy(np.ascontiguousarray(y)),
                    params["batch_size"], params["epochs"],
                    on_epoch_end=on_epoch_end)
            if hvd.rank() == 0:
                return _serialize_torch(model), {"val_loss": val_history}
            return None

        results = spark_run(train, num_proc=self.num_proc, spark=spark)
        model_bytes_out, history = collect_trained(results)
        return LightningModel(model_bytes_out, self.feature_cols,
                              self.label_cols, history=history)


class LightningModel(TorchModel):
    """Transformer over the fitted module (same surface as TorchModel —
    the reference's ``TorchModel`` in ``horovod.spark.lightning``)."""
