"""Spark Torch estimator.

Reference analog: ``horovod/spark/torch/estimator.py`` (TorchEstimator →
TorchModel). Same staging flow as the Keras estimator: DataFrame →
parquet in the store → ``horovod_tpu.spark.run`` training with the torch
frontend's ``DistributedOptimizer`` → fitted transformer.
"""

import io

import numpy as np

from horovod_tpu.spark.common.fit import (
    AsyncParquetBatchReader,
    _load_np,
    use_streaming,
    collect_trained,
    stage_train_data,
)
from horovod_tpu.spark.common.params import EstimatorParams


def _serialize_torch(model):
    import torch

    buf = io.BytesIO()
    torch.save(model, buf)
    return buf.getvalue()


def _deserialize_torch(blob):
    import torch

    return torch.load(io.BytesIO(blob), weights_only=False)


class TorchEstimator(EstimatorParams):
    def __init__(self, **kwargs):
        self.optimizer_factory = kwargs.pop("optimizer_factory", None)
        super().__init__(**kwargs)

    def fit(self, df, spark=None):
        from horovod_tpu.spark import run as spark_run
        from horovod_tpu.spark.common.fit import split_validation

        train_path = stage_train_data(self, df)
        # validation= (fraction or marker column): split the staged
        # parquet; per-epoch val loss lands in the returned model's
        # history (reference estimator contract).
        train_path, val_path = split_validation(
            train_path, self.validation, seed=self.random_seed or 0)

        model_bytes = _serialize_torch(self.model)
        loss_fn = self.loss
        opt_factory = self.optimizer_factory
        params = dict(
            train_path=train_path, feature_cols=tuple(self.feature_cols),
            label_cols=tuple(self.label_cols), batch_size=self.batch_size,
            epochs=self.epochs,
            streaming=use_streaming(self.inmemory_cache_all, train_path),
            shuffle=bool(self.shuffle_buffer_size),
            val_path=val_path, seed=self.random_seed or 0)

        def train():
            import torch

            import horovod_tpu.torch as hvd

            hvd.init()
            model = _deserialize_torch(model_bytes)
            base_opt = (opt_factory(model.parameters()) if opt_factory
                        else torch.optim.SGD(model.parameters(), lr=0.01))
            opt = hvd.DistributedOptimizer(
                base_opt, named_parameters=model.named_parameters())
            hvd.broadcast_parameters(model.state_dict(), root_rank=0)
            hvd.broadcast_optimizer_state(base_opt, root_rank=0)
            criterion = loss_fn or torch.nn.MSELoss()

            def step(xb_t, yb_t):
                opt.zero_grad()
                loss = criterion(model(xb_t), yb_t)
                loss.backward()
                opt.step()

            val_history = []

            def epoch_end():
                # Per-epoch validation loss: one BATCHED pass over the
                # staged val split (bounded memory), rank-averaged so
                # every rank records the global value.
                if not params["val_path"]:
                    return
                from horovod_tpu.spark.common.fit import epoch_val_loss

                def batch_loss(xb, yb):
                    model.eval()
                    with torch.no_grad():
                        vl = criterion(
                            model(torch.from_numpy(np.ascontiguousarray(xb))),
                            torch.from_numpy(np.ascontiguousarray(yb)))
                    model.train()
                    return vl

                val_history.append(epoch_val_loss(
                    params["val_path"], params["feature_cols"],
                    params["label_cols"], params["batch_size"],
                    hvd.rank(), hvd.size(), batch_loss,
                    lambda v: float(hvd.allreduce(
                        torch.tensor([v]), op=hvd.Average))))

            if params["streaming"]:
                # Stream + prefetch from the staged parquet (petastorm
                # reader path) instead of materializing the shard.
                reader = AsyncParquetBatchReader(
                    path=params["train_path"],
                    feature_cols=params["feature_cols"],
                    label_cols=params["label_cols"],
                    batch_size=params["batch_size"],
                    rank=hvd.rank(), size=hvd.size(),
                    shuffle=params["shuffle"], seed=params["seed"])
                try:
                    for _ in range(params["epochs"]):
                        for xb, yb in reader:
                            step(torch.from_numpy(np.ascontiguousarray(xb)),
                                 torch.from_numpy(np.ascontiguousarray(yb)))
                        epoch_end()
                finally:
                    reader.close_async_loader()
            else:
                x, y = _load_np(params["train_path"],
                                params["feature_cols"],
                                params["label_cols"], hvd.rank(),
                                hvd.size())
                # Convert the shard ONCE; batches are views.
                x_t = torch.from_numpy(np.ascontiguousarray(x))
                y_t = torch.from_numpy(np.ascontiguousarray(y))
                bs = params["batch_size"]
                for _ in range(params["epochs"]):
                    for i in range(0, len(x_t), bs):
                        step(x_t[i:i + bs], y_t[i:i + bs])
                    epoch_end()
            if hvd.rank() == 0:
                return _serialize_torch(model), {"val_loss": val_history}
            return None

        results = spark_run(train, num_proc=self.num_proc, spark=spark)
        model_bytes_out, history = collect_trained(results)
        return TorchModel(model_bytes_out, self.feature_cols,
                          self.label_cols, history=history)


class TorchModel:
    def __init__(self, model_bytes, feature_cols, label_cols,
                 history=None):
        self._model_bytes = model_bytes
        self.feature_cols = tuple(feature_cols)
        self.label_cols = tuple(label_cols)
        self.history = history
        self._model = None

    def getModel(self):
        if self._model is None:
            self._model = _deserialize_torch(self._model_bytes)
        return self._model

    def transform(self, df):
        import torch

        model_bytes = self._model_bytes
        feature_cols = self.feature_cols
        out_col = self.label_cols[0] + "__output"

        def predict(iterator):
            model = _deserialize_torch(model_bytes)
            model.eval()
            for pdf in iterator:
                x = np.stack([np.asarray(v, np.float32) for v in
                              pdf[list(feature_cols)].to_numpy().tolist()])
                if x.ndim == 3 and x.shape[1] == 1:
                    x = x[:, 0]
                with torch.no_grad():
                    out = model(torch.from_numpy(x)).numpy()
                pdf[out_col] = list(out)
                yield pdf

        schema = df.schema.add(out_col, "array<float>")
        return df.mapInPandas(predict, schema=schema)
