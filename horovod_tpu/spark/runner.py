"""``horovod_tpu.spark.run`` — launch the collective core inside Spark
executors.

Reference analog: ``horovod/spark/runner.py`` (``_run``): the Spark driver
starts a driver service, submits a **barrier-stage** job of ``num_proc``
tasks, every task registers its NIC info, the driver computes the rank
layout and a routable controller address, and each task then runs the
user fn with ``HOROVOD_RANK``/``HOROVOD_SIZE``/controller env set so
``hvd.init()`` inside the fn rendezvouses across executors. Results are
returned per rank through the job itself (reference returns them via the
driver RPC; barrier tasks can simply return).
"""

import os
import sys


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark.run requires pyspark, which is not installed "
            "in this environment.") from e
    return pyspark


def _executor_env(rank, num_proc, controller_addr, controller_port,
                  extra_env):
    env = {
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(num_proc),
        # Executor-local rank/size are refined at runtime by hostname
        # grouping below; DP collectives only need rank/size + controller.
        "HOROVOD_CONTROLLER_ADDR": controller_addr,
        "HOROVOD_CONTROLLER_PORT": str(controller_port),
    }
    env.update(extra_env or {})
    return env


def run(fn, args=(), kwargs=None, num_proc=None, extra_env=None,
        start_timeout=120, verbose=False, spark=None):
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` executors; return the
    list of per-rank results ordered by rank."""
    _require_pyspark()
    from pyspark.sql import SparkSession

    spark = spark or SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)
    kwargs = kwargs or {}

    from horovod_tpu.runner import util

    # The controller (rank 0's listen socket) binds inside the rank-0
    # EXECUTOR, not on the Spark driver — so the bootstrap address must be
    # rank 0's executor host, which every task learns from the barrier
    # address table below. Only the port is fixed ahead of time.
    controller_port = util.free_port()
    env_base = dict(extra_env or {})
    env_base.setdefault("HOROVOD_START_TIMEOUT", str(start_timeout))

    def task_fn(iterator):
        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        # Local rank/size from the barrier address table (reference:
        # task service registration + host grouping in _run).
        infos = ctx.getTaskInfos()
        hosts = [t.address.rsplit(":", 1)[0] for t in infos]
        my_host = hosts[rank]
        same = [i for i, h in enumerate(hosts) if h == my_host]
        # Rank 0 hosts the controller: everyone dials partition 0's host.
        env = _executor_env(rank, num_proc, hosts[0], controller_port,
                            env_base)
        env["HOROVOD_LOCAL_RANK"] = str(same.index(rank))
        env["HOROVOD_LOCAL_SIZE"] = str(len(same))
        env["HOROVOD_CROSS_RANK"] = str(sorted(set(hosts)).index(my_host))
        env["HOROVOD_CROSS_SIZE"] = str(len(set(hosts)))
        os.environ.update(env)
        ctx.barrier()
        if verbose:
            print(f"[horovod_tpu.spark] rank {rank} on {my_host} starting",
                  file=sys.stderr)
        result = fn(*args, **kwargs)
        return [(rank, result)]

    rdd = sc.parallelize(range(num_proc), num_proc)
    pairs = rdd.barrier().mapPartitions(task_fn).collect()
    return [r for _, r in sorted(pairs)]


def run_elastic(fn, args=(), kwargs=None, num_proc=None, min_np=None,
                max_np=None, **run_kwargs):
    """Elastic Spark launch. The reference implements this via its elastic
    driver over Spark task services; here elasticity inside a fixed
    barrier job degrades to a static run (Spark itself re-submits failed
    barrier stages whole), so this wraps ``run`` with the elastic state
    objects still usable inside ``fn``."""
    return run(fn, args=args, kwargs=kwargs, num_proc=num_proc or max_np,
               **run_kwargs)
