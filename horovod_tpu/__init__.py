"""horovod_tpu — a TPU-native distributed training framework.

A ground-up rebuild of the capabilities of Horovod (reference:
``zhouxhao/horovod``, layout-identical to upstream ``horovod/horovod``):
the familiar ``hvd.init`` / ``hvd.allreduce`` / ``DistributedOptimizer``
API and the ``horovodrun`` launcher, re-founded on JAX/XLA for TPU.

Architecture (see SURVEY.md for the reference analysis):

- ``csrc/``            — the native C++ core runtime: background coordination
                         loop, coordinator-rank tensor negotiation, response
                         cache, tensor-fusion buffer, TCP control plane and a
                         ring-collective CPU data plane (the Gloo analog).
                         Reference: ``horovod/common/`` (operations.cc,
                         controller.cc, tensor_queue.cc, ...).
- ``horovod_tpu.jax``  — the new JAX frontend (reference has none; API parity
                         with ``horovod/torch/__init__.py`` + eager ops).
- ``horovod_tpu.torch``— PyTorch frontend (reference: ``horovod/torch/``).
- ``horovod_tpu.parallel`` — TPU-native in-graph SPMD path: device meshes,
                         sharding rules, ring-attention sequence parallelism.
                         Net-new vs the reference (SURVEY.md §5.7).
- ``horovod_tpu.runner`` — the ``horovodrun`` launcher (reference:
                         ``horovod/runner/``).
"""

from horovod_tpu.version import __version__  # noqa: F401


def run(*args, **kwargs):
    """In-python local launcher (reference analog: ``horovod.run``)."""
    from horovod_tpu.runner import run as _run

    return _run(*args, **kwargs)
