"""Two-process jit-lane fusion smoke: ``make fusion-smoke``.

Launches 2 real ranks over the eager host ring and gates the whole
compute/collective fusion lane end to end, no accelerator
(docs/fusion.md):

- **hvdlint C7** passes on the registered fused step
  (``zero1_fused_step`` — the interleaved jaxpr) and the check's
  firing path works (the deliberately tail-bunched shape trips it);
- **ledger invariant** — on a real fused 2-rank run, per plane,
  ``exposed + hidden == total`` exactly, the overlap ledger recorded
  every timed step, and the fused schedule actually hid wire time
  (``hidden > 0``: reduce-scatters drained while segments dispatched);
- **schedule-knob identity** — ``HOROVOD_JIT_FUSION`` flips the
  schedule, never the math: fused and unfused loss trajectories and
  final params are BIT-identical (``tests/parallel/test_fusion.py``
  pins the same contract in the tier-1 quick lane).
"""

import os
import subprocess
import sys

STEPS = 4
_SHAPES = {"w1": (32, 64), "w2": (64, 32), "b2": (32,), "w3": (32, 8)}


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _lint_gate():
    """C7 both ways, host-side (no ring needed): the shipped fused
    program lints clean, and a tail-bunched fixture still fires —
    a vacuously-quiet check must not gate anything."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from horovod_tpu import analysis
    from horovod_tpu.analysis.lint import main as lint_main

    rc = lint_main(["--program", "zero1_fused_step"])
    assert rc == 0, f"hvdlint zero1_fused_step rc={rc}"

    def bunched(x, w):
        a = x @ w
        b = jnp.tanh(a) @ w
        s1 = lax.psum_scatter(a.reshape(-1), "data",
                              scatter_dimension=0, tiled=True)
        s2 = lax.psum_scatter(b.reshape(-1), "data",
                              scatter_dimension=0, tiled=True)
        return (lax.all_gather(s1, "data", axis=0, tiled=True),
                lax.all_gather(s2, "data", axis=0, tiled=True))

    x = jnp.ones((16, 16))
    diags = analysis.lint(bunched, (x, x), axis_env=[("data", 2)])
    assert [d.id for d in diags] == ["C7"], diags
    print("FUSION_SMOKE_LINT_OK (C7 clean on zero1_fused_step, "
          "fires on the bunched fixture)")


def worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu.jax as hvd
    from horovod_tpu import telemetry
    from horovod_tpu.parallel import fusion
    from horovod_tpu.telemetry.step_timer import StepTimer

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    try:
        keys = jax.random.split(jax.random.PRNGKey(0), len(_SHAPES))
        params = {name: (jnp.zeros(shape) if len(shape) == 1 else
                         jax.random.normal(k, shape) * 0.1)
                  for k, (name, shape) in zip(keys, _SHAPES.items())}
        batch = {"x": jax.random.normal(jax.random.PRNGKey(7), (8, 32)),
                 "y": jax.random.normal(jax.random.PRNGKey(8), (8, 8))}

        def loss_fn(p, b):
            h = jnp.tanh(b["x"] @ p["w1"])
            h = jnp.tanh(h @ p["w2"] + p["b2"])
            return jnp.mean((h @ p["w3"] - b["y"]) ** 2)

        init, step, finish = hvd.make_fused_train_step(
            loss_fn, 1e-2, bucket_bytes=4096)

        def run(fused, timer=None):
            fusion.set_jit_fusion(fused)
            carry = init(jax.tree.map(jnp.array, params))
            losses = []
            for _ in range(STEPS):
                if timer is not None:
                    timer.start_step()
                loss, carry = step(carry, batch)
                losses.append(np.asarray(loss))
                if timer is not None:
                    timer.end_step(loss)
            p, _ = finish(carry)
            return losses, p

        # (1) the fused lane under a StepTimer: ledger invariant.
        telemetry.metrics_reset()
        timer = StepTimer()
        losses_f, params_f = run(True, timer)
        ov = telemetry.wire_overlap()
        assert ov.get("steps", 0) >= STEPS, ov
        hidden_us = 0
        for plane in ("intra", "cross"):
            p = ov[plane]
            assert p["exposed_us"] + p["hidden_us"] == p["total_us"], ov
            hidden_us += p["hidden_us"]
        # The fused schedule hid wire under segment dispatch: some
        # reduce-scatter/allgather time ran with no API thread blocked.
        assert hidden_us > 0, ov

        # (2) the unfused escape hatch: bit-identical trajectory.
        losses_u, params_u = run(False)
        bits = lambda a: np.asarray(a, np.float32).view(np.uint32)  # noqa: E731
        for lf, lu in zip(losses_f, losses_u):
            assert np.array_equal(bits(lf), bits(lu)), (lf, lu)
        for k in params:
            assert np.array_equal(bits(params_f[k]), bits(params_u[k])), k

        print(f"FUSION_SMOKE_OK rank={rank} steps={ov['steps']} "
              f"hidden_us={hidden_us} "
              f"total_us={ov['intra']['total_us']}")
    finally:
        fusion.set_jit_fusion(None)
        hvd.shutdown()


def main():
    if "--worker" in sys.argv:
        worker()
        return 0

    _lint_gate()

    size = 2
    port = _free_port()
    procs = []
    for rank in range(size):
        env = dict(os.environ,
                   HOROVOD_RANK=str(rank), HOROVOD_SIZE=str(size),
                   HOROVOD_CONTROLLER_ADDR="127.0.0.1",
                   HOROVOD_CONTROLLER_PORT=str(port),
                   JAX_PLATFORMS="cpu")
        env.pop("HOROVOD_JIT_FUSION", None)  # the worker flips in-process
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.jax.fusion_smoke",
             "--worker"],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    failed = False
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out = "TIMEOUT"
        ok = p.returncode == 0 and "FUSION_SMOKE_OK" in out
        print(out.strip())
        if not ok:
            print(f"rank {rank} FAILED (rc={p.returncode})")
            failed = True
    if failed:
        return 1
    print("fusion-smoke: OK (C7 gate, exposed+hidden==total with "
          "hidden>0 on the fused lane, fused/unfused bit-identity)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
