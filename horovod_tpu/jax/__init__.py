"""horovod_tpu.jax — the JAX frontend (``import horovod_tpu.jax as hvd``).

Net-new relative to the reference (which has tensorflow/torch/keras/mxnet
frontends — SURVEY.md §2.3); API shape mirrors ``horovod/torch/__init__.py``
so a Horovod user finds the familiar surface:

    hvd.init(); hvd.rank(); hvd.size()
    hvd.allreduce(x) / hvd.allreduce_async / hvd.synchronize
    hvd.broadcast_parameters(params, root_rank=0)
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))

Two data planes:
- eager (this module): host-side fused ring collectives via the native core
  — works per-process like the reference, any backend.
- in-graph (``horovod_tpu.parallel``): psum/all_gather over a jax Mesh
  compiled by XLA onto TPU ICI — the TPU-native fast path.
"""

from horovod_tpu.common.process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    global_process_set,
    remove_process_set,
)
from horovod_tpu.common.exceptions import (  # noqa: F401
    HorovodInternalError,
    HorovodPeerFailureError,
    HorovodWireCorruptionError,
    HostsUpdatedInterrupt,
)
from horovod_tpu.jax.compression import Compression  # noqa: F401
from horovod_tpu.jax.functions import (  # noqa: F401
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from horovod_tpu.jax.mpi_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    join,
    broadcast,
    broadcast_async,
    cross_rank,
    cross_size,
    grouped_allgather,
    grouped_allgather_async,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_reducescatter,
    grouped_reducescatter_async,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    debug_port,
    events,
    metrics,
    metrics_reset,
    poll,
    rank,
    reducescatter,
    reducescatter_async,
    shutdown,
    size,
    start_timeline,
    step_mark,
    stop_timeline,
    synchronize,
)
from horovod_tpu.jax.optimizer import (  # noqa: F401
    DistributedFusedAdam,
    DistributedGradientTransformation,
    DistributedOptimizer,
    allreduce_gradients,
    make_fused_train_step,
)

# Resharding engine (docs/redistribute.md): hvd.redistribute moves a
# jax array between shardings with the minimal collective sequence —
# the shared primitive of checkpoint resharding (train on N, serve on
# M) and elastic re-formation.
from horovod_tpu.parallel.reshard import redistribute  # noqa: E402,F401

from horovod_tpu.jax import elastic  # noqa: E402,F401

# Capability surface (reference analog: hvd.mpi_built()/gloo_built()/...).
from horovod_tpu.jax.mpi_ops import (  # noqa: F401,E402
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    xla_built,
    xla_enabled,
)
