"""DistributedOptimizer for optax: allreduce-averaged gradients.

Reference analog: ``horovod/torch/optimizer.py`` ``_DistributedOptimizer``
(per-param async allreduce hooks + step-time synchronize) and
``horovod/tensorflow/gradient_aggregation.py`` (backward_passes_per_step
local aggregation). In optax terms this is a ``GradientTransformation``
that allreduces the incoming gradient pytree — grouped/fused in the native
core — before handing it to the wrapped transformation.
"""

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.jax import mpi_ops
from horovod_tpu.jax.compression import Compression


def allreduce_gradients(grads, op=mpi_ops.Average,
                        compression=Compression.none, prefix="grad",
                        donate=False):
    """Allreduce a gradient pytree across ranks (eager path).

    Leaves are enqueued as one negotiation group per dtype so the core
    fuses them into large buffers (reference: tensor fusion,
    HOROVOD_FUSION_THRESHOLD).

    ``donate=True`` promises the caller will not read ``grads`` again
    (the usual case — the reduced tree replaces them): on the device
    data plane the fused program reuses the gradients' HBM for the
    results, halving the collective's peak footprint.
    """
    leaves, treedef = jax.tree.flatten(grads)
    del grads  # with donate, no live ref may outlast the collective
    compressed, ctxs = [], []
    for leaf in leaves:
        c, ctx = compression.compress(jnp.asarray(leaf))
        compressed.append(c)
        ctxs.append(ctx)
    del leaves
    names = [f"{prefix}.{i}" for i in range(len(compressed))]
    handles = mpi_ops.grouped_allreduce_async(compressed, names, op=op,
                                              donate=donate)
    del compressed
    reduced = [compression.decompress(h.synchronize(), ctx)
               for h, ctx in zip(handles, ctxs)]
    return jax.tree.unflatten(treedef, reduced)


def DistributedGradientTransformation(optimizer, op=mpi_ops.Average,
                                      compression=Compression.none,
                                      backward_passes_per_step=1):
    """Wrap an optax GradientTransformation so update() sees gradients
    allreduce-averaged across all ranks.

    With ``backward_passes_per_step > 1`` gradients are accumulated
    locally and only allreduced (and applied) every Nth call — the
    reference's LocalGradientAggregationHelper. Between allreduce steps
    the update is zero (parameters unchanged), matching the reference's
    semantics of skipping apply.
    """
    if backward_passes_per_step == 1:
        def update(grads, state, params=None):
            reduced = allreduce_gradients(grads, op=op,
                                          compression=compression)
            return optimizer.update(reduced, state, params)

        return optax.GradientTransformation(optimizer.init, update)

    def init(params):
        return {
            "inner": optimizer.init(params),
            "acc": jax.tree.map(jnp.zeros_like, params),
            "counter": 0,
        }

    def update(grads, state, params=None):
        acc = jax.tree.map(lambda a, g: a + g, state["acc"], grads)
        counter = state["counter"] + 1
        if counter < backward_passes_per_step:
            zero = jax.tree.map(jnp.zeros_like, grads)
            return zero, {"inner": state["inner"], "acc": acc,
                          "counter": counter}
        scale = 1.0 / backward_passes_per_step
        acc = jax.tree.map(lambda a: a * scale, acc)
        reduced = allreduce_gradients(acc, op=op, compression=compression)
        updates, inner = optimizer.update(reduced, state["inner"], params)
        return updates, {"inner": inner,
                         "acc": jax.tree.map(jnp.zeros_like, acc),
                         "counter": 0}

    return optax.GradientTransformation(init, update)


# Reference-familiar name.
DistributedOptimizer = DistributedGradientTransformation


def DistributedFusedAdam(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
                         op=mpi_ops.Average,
                         compression=Compression.none):
    """Eager-Horovod counterpart of the single-pass fused update
    (``parallel.precision.fused_adam``): allreduce the gradient pytree
    across ranks (donated — the fused device program reuses the
    gradients' HBM), then apply adam in ONE jitted pass over params
    (no updates tree, no separate ``optax.apply_updates`` pass over
    param-sized arrays).

    Protocol matches ``FusedOptimizer`` (``init(params) -> state``,
    ``apply(params, grads, state) -> (params, state)``) for use in an
    eager step loop::

        opt = hvd.DistributedFusedAdam(3e-4)
        state = opt.init(params)
        loss, grads = grad_fn(params, batch)        # jitted fwd+bwd
        params, state = opt.apply(params, grads, state)

    The allreduce is an eager collective (enqueue -> negotiate ->
    cached device-program replay), so ``apply`` itself must stay
    OUTSIDE jit; the update math runs as its own jitted program — the
    same split-program layout ``bench.py``'s eager row measures.
    """
    from horovod_tpu.parallel.precision import FusedOptimizer, fused_adam

    inner = fused_adam(learning_rate, b1=b1, b2=b2, eps=eps)

    # Grads are NOT donated into the update jit: they arrive as
    # donation-aliased outputs of the device-plane program and XLA
    # refuses to re-donate an aliased buffer (see bench.py's eager
    # apply_fn). params/state donation is what bounds the peak.
    jitted_apply = jax.jit(inner.apply, donate_argnums=(0, 2))

    def apply(params, grads, state):
        grads = allreduce_gradients(grads, op=op,
                                    compression=compression,
                                    donate=True)
        return jitted_apply(params, grads, state)

    return FusedOptimizer(init=inner.init, apply=apply)
