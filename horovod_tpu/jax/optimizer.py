"""DistributedOptimizer for optax: allreduce-averaged gradients.

Reference analog: ``horovod/torch/optimizer.py`` ``_DistributedOptimizer``
(per-param async allreduce hooks + step-time synchronize) and
``horovod/tensorflow/gradient_aggregation.py`` (backward_passes_per_step
local aggregation). In optax terms this is a ``GradientTransformation``
that allreduces the incoming gradient pytree — grouped/fused in the native
core — before handing it to the wrapped transformation.
"""

import functools

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.jax import mpi_ops
from horovod_tpu.jax.compression import Compression


# Step scoping from the eager optimizer (docs/metrics.md "Step
# anatomy"): each fused-optimizer apply() is a step BOUNDARY — the
# previous implicit window closes and the next opens, so window k spans
# "apply k returned" to "apply k+1 returned" = one full train step
# (grad compute + allreduce + update). Defers to an explicit scope: a
# StepTimer that opened a step the optimizer did not is driving the
# marks, and a second driver would fragment its windows. Deference is
# decided by the window OWNER, not the step id: core step ids restart
# after metrics_reset(), so an id-only comparison can mistake a
# StepTimer window that reused our last id for our own stale window
# and steal it mid-step (the overlap ledger then folds one step's wire
# spans into two half-windows and the attribution is garbage).
_last_boundary_id = None


def _mark_optimizer_step():
    global _last_boundary_id
    try:
        from horovod_tpu.telemetry import core as _tcore

        if _tcore.window_owner() not in (None, "optimizer"):
            return  # an explicit scope (StepTimer) owns the window
        open_id = _tcore.step_id()
        if open_id >= 0 and open_id != _last_boundary_id:
            return  # an undeclared driver opened it — leave it alone
        _last_boundary_id = _tcore.step_mark(True, owner="optimizer")
    except Exception:  # noqa: BLE001 — telemetry must never take the
        pass           # training step down


def allreduce_gradients(grads, op=mpi_ops.Average,
                        compression=Compression.none, prefix="grad",
                        donate=False):
    """Allreduce a gradient pytree across ranks (eager path).

    Leaves are enqueued as one negotiation group per dtype so the core
    fuses them into large buffers (reference: tensor fusion,
    HOROVOD_FUSION_THRESHOLD).

    ``donate=True`` promises the caller will not read ``grads`` again
    (the usual case — the reduced tree replaces them): on the device
    data plane the fused program reuses the gradients' HBM for the
    results, halving the collective's peak footprint.
    """
    leaves, treedef = jax.tree.flatten(grads)
    del grads  # with donate, no live ref may outlast the collective
    compressed, ctxs = [], []
    for leaf in leaves:
        c, ctx = compression.compress(jnp.asarray(leaf))
        compressed.append(c)
        ctxs.append(ctx)
    del leaves
    names = [f"{prefix}.{i}" for i in range(len(compressed))]
    handles = mpi_ops.grouped_allreduce_async(compressed, names, op=op,
                                              donate=donate)
    del compressed
    reduced = [compression.decompress(h.synchronize(), ctx)
               for h, ctx in zip(handles, ctxs)]
    return jax.tree.unflatten(treedef, reduced)


def DistributedGradientTransformation(optimizer, op=mpi_ops.Average,
                                      compression=Compression.none,
                                      backward_passes_per_step=1):
    """Wrap an optax GradientTransformation so update() sees gradients
    allreduce-averaged across all ranks.

    With ``backward_passes_per_step > 1`` gradients are accumulated
    locally and only allreduced (and applied) every Nth call — the
    reference's LocalGradientAggregationHelper. Between allreduce steps
    the update is zero (parameters unchanged), matching the reference's
    semantics of skipping apply.
    """
    if backward_passes_per_step == 1:
        def update(grads, state, params=None):
            reduced = allreduce_gradients(grads, op=op,
                                          compression=compression)
            return optimizer.update(reduced, state, params)

        return optax.GradientTransformation(optimizer.init, update)

    def init(params):
        return {
            "inner": optimizer.init(params),
            "acc": jax.tree.map(jnp.zeros_like, params),
            "counter": 0,
        }

    def update(grads, state, params=None):
        acc = jax.tree.map(lambda a, g: a + g, state["acc"], grads)
        counter = state["counter"] + 1
        if counter < backward_passes_per_step:
            zero = jax.tree.map(jnp.zeros_like, grads)
            return zero, {"inner": state["inner"], "acc": acc,
                          "counter": counter}
        scale = 1.0 / backward_passes_per_step
        acc = jax.tree.map(lambda a: a * scale, acc)
        reduced = allreduce_gradients(acc, op=op, compression=compression)
        updates, inner = optimizer.update(reduced, state["inner"], params)
        return updates, {"inner": inner,
                         "acc": jax.tree.map(jnp.zeros_like, acc),
                         "counter": 0}

    return optax.GradientTransformation(init, update)


# Reference-familiar name.
DistributedOptimizer = DistributedGradientTransformation


def DistributedFusedAdam(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
                         op=mpi_ops.Average,
                         compression=Compression.none,
                         zero=False, bucket_bytes=None, overlap=True):
    """Eager-Horovod counterpart of the single-pass fused update
    (``parallel.precision.fused_adam``): allreduce the gradient pytree
    across ranks (donated — the fused device program reuses the
    gradients' HBM), then apply adam in ONE jitted pass over params
    (no updates tree, no separate ``optax.apply_updates`` pass over
    param-sized arrays).

    Protocol matches ``FusedOptimizer`` (``init(params) -> state``,
    ``apply(params, grads, state) -> (params, state)``) for use in an
    eager step loop::

        opt = hvd.DistributedFusedAdam(3e-4)
        state = opt.init(params)
        loss, grads = grad_fn(params, batch)        # jitted fwd+bwd
        params, state = opt.apply(params, grads, state)

    The allreduce is an eager collective (enqueue -> negotiate ->
    cached device-program replay), so ``apply`` itself must stay
    OUTSIDE jit; the update math runs as its own jitted program — the
    same split-program layout ``bench.py``'s eager row measures.

    ``zero=True`` switches to the ZeRO-1 sharded path (docs/zero.md):
    gradients are packed into fused buckets (``bucket_bytes``,
    shard-aligned by construction — ``parallel.zero``) and
    **reduce-scattered** instead of allreduced, each rank runs the
    identical adam kernel on its 1/N (params, mu, nu) shards, and the
    updated param shards are **allgathered** back. Per-rank optimizer
    state drops N-fold. With ``overlap=True`` (default) the lane is
    pipelined per bucket: every reduce-scatter is in flight before the
    first shard update runs, and each bucket's allgather is issued the
    moment its update finishes — wire time hides under the remaining
    buckets' update compute (the fused computation-collective recipe of
    arXiv:2305.06942); ``overlap=False`` runs the three phases
    bulk-synchronously (the ``zero_sweep`` comparison point). In zero
    mode ``compression`` applies to the param-allgather payload (e.g.
    ``Compression.bf16`` halves the up-phase wire for fp32 params;
    every rank — shard owners included — consumes the decompressed
    bits, so the result stays rank-consistent), and the gradient
    reduce-scatter rides the core's ``HOROVOD_WIRE_COMPRESSION``
    bf16-on-wire path.
    """
    from horovod_tpu.parallel.precision import FusedOptimizer, fused_adam

    if zero:
        zopt = _zero_fused_adam(learning_rate, b1, b2, eps, op=op,
                                compression=compression,
                                bucket_bytes=bucket_bytes,
                                overlap=overlap)
        return FusedOptimizer(init=zopt.init,
                              apply=_boundary_marked(zopt.apply),
                              hyper=zopt.hyper)

    inner = fused_adam(learning_rate, b1=b1, b2=b2, eps=eps)

    # Grads are NOT donated into the update jit: they arrive as
    # donation-aliased outputs of the device-plane program and XLA
    # refuses to re-donate an aliased buffer (see bench.py's eager
    # apply_fn). params/state donation is what bounds the peak.
    jitted_apply = jax.jit(inner.apply, donate_argnums=(0, 2))

    def apply(params, grads, state):
        grads = allreduce_gradients(grads, op=op,
                                    compression=compression,
                                    donate=True)
        return jitted_apply(params, grads, state)

    return FusedOptimizer(init=inner.init,
                          apply=_boundary_marked(apply),
                          hyper=inner.hyper)


def _boundary_marked(apply_fn):
    """Wrap an optimizer apply so every completed update marks a step
    boundary (see :func:`_mark_optimizer_step`)."""
    @functools.wraps(apply_fn)
    def apply(params, grads, state):
        out = apply_fn(params, grads, state)
        _mark_optimizer_step()
        return out

    return apply


def _zero_fused_adam(learning_rate, b1, b2, eps, op, compression,
                     bucket_bytes, overlap):
    """The eager ZeRO-1 lane behind ``DistributedFusedAdam(zero=True)``.

    One negotiation name per bucket per phase (``zero.rs.i`` /
    ``zero.ag.i``) so the steady-state response cache stays hot. The
    pipelined order is: issue EVERY bucket's reduce-scatter first (the
    background thread negotiates and executes them while Python works),
    then walk the buckets in order — synchronize bucket i's shard,
    run its jitted shard-adam, fire its allgather, move on — so bucket
    i's allgather and bucket i+1..K's reduce-scatters overlap bucket
    i+1's update compute. Synchronizing the allgathers last drains the
    pipe.
    """
    from horovod_tpu.parallel.precision import (
        FusedOptimizer,
        _adam_leaf,
        _bias_corrections,
    )
    from horovod_tpu.parallel.zero import (
        DEFAULT_BUCKET_BYTES,
        zero_bucket_layout,
    )

    bucket_bytes = bucket_bytes or DEFAULT_BUCKET_BYTES
    cache = {}  # treedef -> layout

    def _layout(leaves, treedef):
        if treedef not in cache:
            cache[treedef] = zero_bucket_layout(
                leaves, mpi_ops.size(), bucket_bytes)
        return cache[treedef]

    # mu/nu are donated (replaced every step); p/g shards arrive as
    # fresh collective outputs or slices and must stay un-donated.
    @functools.partial(jax.jit, donate_argnums=(2, 3))
    def shard_adam(p_shard, g_shard, mu, nu, count):
        bc1, bc2 = _bias_corrections(count, b1, b2)
        return _adam_leaf(p_shard, g_shard, mu, nu, learning_rate, b1,
                          b2, eps, bc1, bc2, p_shard.dtype)

    def init(params):
        leaves, treedef = jax.tree.flatten(params)
        layout = _layout(leaves, treedef)
        n = layout.n_shards
        shard = lambda b: jnp.zeros(  # noqa: E731
            (b.shard_elems(n),), b.dtype)
        return {
            "count": jnp.zeros((), jnp.int32),
            "mu": [shard(b) for b in layout.buckets],
            "nu": [shard(b) for b in layout.buckets],
        }

    def apply(params, grads, state):
        rank = mpi_ops.rank()
        g_leaves, treedef = jax.tree.flatten(grads)
        del grads
        layout = _layout(g_leaves, treedef)
        p_leaves = treedef.flatten_up_to(params)
        count = state["count"] + 1
        # Phase down: EVERY bucket's reduce-scatter goes in flight
        # before any update runs (overlap) / is drained immediately
        # (phase-separated baseline).
        rs = []
        for i, flat in enumerate(layout.pack(g_leaves)):
            h = mpi_ops.reducescatter_async(flat, name=f"zero.rs.{i}",
                                            op=op)
            rs.append(h if overlap else h.synchronize())
        del g_leaves
        # Update + phase up, pipelined per bucket. The param shard is
        # assembled directly from the overlapping leaf slices
        # (layout.pack_shard) — packing the FULL padded bucket only to
        # slice out 1/N of it would waste (N-1)/N of the copy on the
        # hot eager path.
        ag, ctxs, new_mu, new_nu = [], [], [], []
        for i in range(len(layout.buckets)):
            g_shard = rs[i].synchronize() if overlap else rs[i]
            p_shard = layout.pack_shard(p_leaves, i, rank)
            p2, mu2, nu2 = shard_adam(p_shard, g_shard, state["mu"][i],
                                      state["nu"][i], count)
            new_mu.append(mu2)
            new_nu.append(nu2)
            c, ctx = compression.compress(p2)
            ctxs.append(ctx)
            if overlap:
                ag.append(mpi_ops.allgather_async(c, name=f"zero.ag.{i}"))
            else:
                ag.append(c)
        if not overlap:
            ag = mpi_ops.grouped_allgather_async(
                ag, names=[f"zero.ag.{i}" for i in range(len(ag))])
        new_flat = [compression.decompress(h.synchronize(), ctx)
                    for h, ctx in zip(ag, ctxs)]
        params = jax.tree.unflatten(treedef, layout.unpack(new_flat))
        return params, {"count": count, "mu": new_mu, "nu": new_nu}

    return FusedOptimizer(init=init, apply=apply,
                          hyper={"kind": "adam", "zero1": True,
                                 "learning_rate": learning_rate,
                                 "b1": b1, "b2": b2, "eps": eps})


def make_fused_train_step(loss_fn, learning_rate, b1=0.9, b2=0.999,
                          eps=1e-8, op=mpi_ops.Average,
                          compression=Compression.none,
                          bucket_bytes=None):
    """The host-lane fused ZeRO-1 train step: per-bucket reduce-scatter
    interleaved with the jitted backward (docs/fusion.md).

    The backward is traced once and SPLIT at bucket-readiness
    boundaries (``parallel.fusion.grad_bucket_cuts`` /
    ``segment_closed_jaxpr``): the step loop runs the compute segments
    back-to-back and, at each boundary, fires the eager reduce-scatter
    for every gradient bucket that segment completed — so the wire
    drains bucket k while segments k+1.. are still computing, exactly
    the eager lane's overlap recipe applied to a jitted backward. Each
    bucket's shard-adam and param allgather then pipeline as in
    ``DistributedFusedAdam(zero=True)``, but the allgathers'
    SYNCHRONIZATION is deferred into the NEXT step: ``step`` returns
    with the gathers still in flight (carried as ``pending``), and the
    next call drains them right before the forward needs the updated
    params — the up-phase wire overlaps the inter-step host work and
    shows up as hidden time in the next step's overlap window.

    ``HOROVOD_JIT_FUSION=0`` (or ``hvd.init(jit_fusion=False)``)
    switches the SAME step to the unfused schedule — monolithic grad
    program, bulk-synchronous reduce-scatter / update / allgather
    phases, params materialized before ``step`` returns. Both lanes run
    identical collectives with identical operands in the same per-axis
    order, so the knob changes the schedule, never the math: loss
    trajectories are bit-identical (tests/parallel/test_fusion.py).

    Returns ``(init, step, finish)``::

        init(params)          -> carry
        step(carry, batch)    -> (loss, carry)     # params may lag one
        finish(carry)         -> (params, carry)   # drain in-flight AG

    ``finish`` must be called before reading params (checkpoint, eval)
    in the fused schedule; it is a no-op when nothing is pending.
    """
    from horovod_tpu.parallel import fusion
    from horovod_tpu.parallel.precision import (
        _adam_leaf,
        _bias_corrections,
    )
    from horovod_tpu.parallel.zero import (
        DEFAULT_BUCKET_BYTES,
        zero_bucket_layout,
    )

    bucket_bytes = bucket_bytes or DEFAULT_BUCKET_BYTES
    progs = {}  # (treedef, batch structure) -> traced/segmented lane

    @functools.partial(jax.jit, donate_argnums=(2, 3))
    def shard_adam(p_shard, g_shard, mu, nu, count):
        bc1, bc2 = _bias_corrections(count, b1, b2)
        return _adam_leaf(p_shard, g_shard, mu, nu, learning_rate, b1,
                          b2, eps, bc1, bc2, p_shard.dtype)

    def _lane(p_leaves, treedef, b_leaves, btree):
        key = (treedef, btree,
               tuple((l.shape, jnp.dtype(l.dtype).name)
                     for l in (*p_leaves, *b_leaves)))
        if key in progs:
            return progs[key]
        layout = zero_bucket_layout(p_leaves, mpi_ops.size(),
                                    bucket_bytes)
        n_p = len(p_leaves)

        def flat_grad(*flat):
            p = jax.tree.unflatten(treedef, flat[:n_p])
            d = jax.tree.unflatten(btree, flat[n_p:])
            loss, grads = jax.value_and_grad(loss_fn)(p, d)
            return (loss, *treedef.flatten_up_to(grads))

        closed = jax.make_jaxpr(flat_grad)(*p_leaves, *b_leaves)
        cuts, ready = fusion.grad_bucket_cuts(closed, layout)
        prog = fusion.segment_closed_jaxpr(closed, cuts)
        # boundary k fires after segment k (prefix length bounds[k+1]):
        # bucket b joins the FIRST boundary whose prefix covers its
        # last producing equation.
        bounds = [0, *cuts, len(closed.jaxpr.eqns)]
        at_boundary = [[] for _ in range(len(bounds) - 1)]
        for bi, r in enumerate(ready):
            k = next(k for k in range(len(bounds) - 1)
                     if bounds[k + 1] >= r)
            at_boundary[k].append(bi)
        issue_order = sorted(range(len(layout.buckets)),
                             key=ready.__getitem__)
        grad_vars = closed.jaxpr.outvars[1:]
        # One packer jit per bucket: same dynamic_update_slice chain as
        # BucketLayout.pack, over just that bucket's leaves — shared by
        # both schedules so the wire sees identical operands.
        packers = []
        for b in layout.buckets:
            def pack(*leaves, _b=b):
                flat = jnp.zeros((_b.padded,), _b.dtype)
                for leaf, off in zip(leaves, _b.offsets):
                    flat = jax.lax.dynamic_update_slice(
                        flat, leaf.reshape(-1).astype(_b.dtype), (off,))
                return flat
            packers.append(jax.jit(pack))
        monolithic = jax.jit(flat_grad)
        lane = (layout, prog, at_boundary, issue_order, grad_vars,
                packers, monolithic)
        progs[key] = lane
        return lane

    def init(params):
        leaves, _ = jax.tree.flatten(params)
        layout = zero_bucket_layout(leaves, mpi_ops.size(),
                                    bucket_bytes)
        n = layout.n_shards
        shard = lambda b: jnp.zeros(  # noqa: E731
            (b.shard_elems(n),), b.dtype)
        state = {"count": jnp.zeros((), jnp.int32),
                 "mu": [shard(b) for b in layout.buckets],
                 "nu": [shard(b) for b in layout.buckets]}
        return (params, state, None)

    def _drain(params, pending):
        """Resolve the previous step's in-flight allgathers into the
        updated params (no-op when nothing is pending)."""
        if pending is None:
            return params
        handles, ctxs, layout, treedef = pending
        new_flat = [compression.decompress(h.synchronize(), ctx)
                    for h, ctx in zip(handles, ctxs)]
        return jax.tree.unflatten(treedef, layout.unpack(new_flat))

    def _leaf_val(env, v):
        return v.val if isinstance(v, fusion._jcore.Literal) else env[v]

    def step(carry, batch):
        params, state, pending = carry
        params = _drain(params, pending)
        fused = fusion.jit_fusion_enabled()
        rank = mpi_ops.rank()
        p_leaves, treedef = jax.tree.flatten(params)
        b_leaves, btree = jax.tree.flatten(batch)
        (layout, prog, at_boundary, issue_order, grad_vars, packers,
         monolithic) = _lane(p_leaves, treedef, b_leaves, btree)
        count = state["count"] + 1
        rs = {}
        if fused:
            def on_boundary(k, env):
                # Fire the reduce-scatter of every bucket this segment
                # finished; the remaining segments compute over it.
                for bi in at_boundary[k]:
                    b = layout.buckets[bi]
                    flat = packers[bi](*(
                        _leaf_val(env, grad_vars[li]) for li in b.indices))
                    rs[bi] = mpi_ops.reducescatter_async(
                        flat, name=f"fusion.rs.{bi}", op=op)
            outs, _ = prog.run(*p_leaves, *b_leaves,
                               on_boundary=on_boundary)
            loss = outs[0]
        else:
            outs = monolithic(*p_leaves, *b_leaves)
            loss, g_leaves = outs[0], list(outs[1:])
            # Unfused: bulk-synchronous phase — every scatter drained
            # before any update runs (the pre-fusion split schedule).
            for bi, b in enumerate(layout.buckets):
                flat = packers[bi](*(g_leaves[li] for li in b.indices))
                rs[bi] = mpi_ops.reducescatter_async(
                    flat, name=f"fusion.rs.{bi}", op=op)
            rs = {bi: h.synchronize() for bi, h in rs.items()}
        new_mu = list(state["mu"])
        new_nu = list(state["nu"])
        ag, ctxs = [None] * len(layout.buckets), [None] * len(
            layout.buckets)
        for bi in issue_order:
            g_shard = rs[bi].synchronize() if fused else rs[bi]
            p_shard = layout.pack_shard(p_leaves, bi, rank)
            p2, mu2, nu2 = shard_adam(p_shard, g_shard, new_mu[bi],
                                      new_nu[bi], count)
            new_mu[bi], new_nu[bi] = mu2, nu2
            c, ctx = compression.compress(p2)
            ctxs[bi] = ctx
            ag[bi] = mpi_ops.allgather_async(c, name=f"fusion.ag.{bi}")
        state = {"count": count, "mu": new_mu, "nu": new_nu}
        pending = (ag, ctxs, layout, treedef)
        if not fused:
            # Unfused: params materialize before the step returns.
            params = _drain(params, pending)
            pending = None
        _mark_optimizer_step()
        return loss, (params, state, pending)

    def finish(carry):
        """Drain any in-flight allgathers; returns
        ``(params, carry)`` with the carry safe to keep stepping."""
        params, state, pending = carry
        params = _drain(params, pending)
        return params, (params, state, None)

    return init, step, finish
