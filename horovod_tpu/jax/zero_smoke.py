"""Two-process ZeRO-1 smoke: ``make zero-smoke``.

Launches 2 real ranks over the eager host ring and proves the whole
ZeRO lane end to end, no accelerator (mirroring ``make metrics-smoke``):

- ``hvd.DistributedFusedAdam(zero=True)`` steps land BIT-comparable to
  the replicated fused adam fed the rank-mean gradients (the ZeRO
  restructure is a memory/wire change, not a numerics change);
- per-rank optimizer state is measured at ~1/N of the replicated
  state's bytes (the headline ZeRO-1 memory cut);
- the metrics snapshot books the new collective mix (reducescatter
  down, allgather up, ZERO allreduces) and the ops-logical bytes
  reconcile with the layout predictor
  (``telemetry.predict.zero_layout_bytes``) within 1%.
"""

import os
import subprocess
import sys

STEPS = 4
_SHAPES = [(64, 32), (33,), (32, 16), (129,)]


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu.jax as hvd
    from horovod_tpu import telemetry
    from horovod_tpu.parallel.precision import fused_adam
    from horovod_tpu.parallel.zero import (
        optimizer_state_bytes,
        zero_bucket_layout,
    )
    from horovod_tpu.telemetry.predict import zero_layout_bytes

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    try:
        params = {f"p{i}": jnp.full(s, 0.05 * (i + 1), jnp.float32)
                  for i, s in enumerate(_SHAPES)}
        # Rank-varying grads whose mean is known on every rank.
        grads = {f"p{i}": jnp.full(s, 0.1 * (rank + 1) * (i - 1.5),
                                   jnp.float32)
                 for i, s in enumerate(_SHAPES)}
        gmean = {f"p{i}": jnp.full(s, 0.1 * (i - 1.5) *
                                   (size + 1) / 2.0, jnp.float32)
                 for i, s in enumerate(_SHAPES)}

        bucket_bytes = 8 * 1024
        zopt = hvd.DistributedFusedAdam(1e-2, zero=True,
                                        bucket_bytes=bucket_bytes)
        ref = fused_adam(1e-2)
        zstate, rstate = zopt.init(params), ref.init(params)
        zp = jax.tree.map(jnp.array, params)
        rp = jax.tree.map(jnp.array, params)

        telemetry.metrics_reset()
        for _ in range(STEPS):
            zp, zstate = zopt.apply(zp, grads, zstate)
            rp, rstate = ref.apply(rp, gmean, rstate)
        snap = telemetry.snapshot()

        # 1) parity with the replicated update on the mean gradients.
        for k in params:
            np.testing.assert_allclose(
                np.asarray(zp[k]), np.asarray(rp[k]), rtol=1e-5,
                atol=1e-7, err_msg=k)

        # 2) the ZeRO-1 memory cut: per-rank mu/nu at ~1/N (padding and
        # the step counter are the only slack).
        zbytes = optimizer_state_bytes(zstate)
        rbytes = optimizer_state_bytes(rstate)
        assert zbytes < rbytes / size * 1.10, (zbytes, rbytes, size)

        # 3) collective mix: reduce-scatter down + allgather up, zero
        # allreduces; logical bytes reconcile with the layout.
        layout = zero_bucket_layout(list(params.values()), size,
                                    bucket_bytes)
        predicted = zero_layout_bytes(layout) * STEPS
        moved = (snap["ops"].get("reducescatter", {}).get("bytes", 0)
                 + snap["ops"].get("allgather", {}).get("bytes", 0))
        assert snap["ops"].get("allreduce", {}).get("tensors", 0) == 0, \
            snap["ops"]
        assert predicted > 0 and abs(moved / predicted - 1.0) < 0.01, (
            moved, predicted)

        print(f"ZERO_SMOKE_OK rank={rank} opt_bytes={zbytes} "
              f"replicated={rbytes} moved={moved} predicted={predicted}")
    finally:
        hvd.shutdown()


def main():
    if "--worker" in sys.argv:
        worker()
        return 0

    size = 2
    port = _free_port()
    procs = []
    for rank in range(size):
        env = dict(os.environ,
                   HOROVOD_RANK=str(rank), HOROVOD_SIZE=str(size),
                   HOROVOD_CONTROLLER_ADDR="127.0.0.1",
                   HOROVOD_CONTROLLER_PORT=str(port),
                   JAX_PLATFORMS="cpu")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.jax.zero_smoke",
             "--worker"],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    failed = False
    stats = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out = "TIMEOUT"
        ok = p.returncode == 0 and "ZERO_SMOKE_OK" in out
        print(out.strip())
        if not ok:
            print(f"rank {rank} FAILED (rc={p.returncode})")
            failed = True
        else:
            stats.append(out)
    if failed:
        return 1
    print(f"zero-smoke: OK ({size} ranks — sharded/replicated parity, "
          f"1/N optimizer bytes, RS+AG byte reconciliation)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
