"""Pytree/object broadcast helpers for the JAX frontend.

Reference analog: ``horovod/torch/functions.py`` (broadcast_parameters,
broadcast_optimizer_state, broadcast_object) — re-expressed functionally:
JAX arrays are immutable, so these return the broadcast pytree instead of
mutating in place.
"""

import io
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.jax import mpi_ops


def broadcast_parameters(params, root_rank=0, prefix="parameters"):
    """Broadcast a pytree of arrays from root_rank; returns the new pytree.

    Used to synchronize initial model parameters across ranks before
    training (reference: hvd.broadcast_parameters called after model
    construction and before the first step).
    """
    leaves, treedef = jax.tree.flatten(params)
    handles = []
    for i, leaf in enumerate(leaves):
        handles.append(mpi_ops.broadcast_async(
            jnp.asarray(leaf), root_rank, name=f"{prefix}.{i}"))
    out = [h.synchronize() for h in handles]
    return jax.tree.unflatten(treedef, out)


def broadcast_optimizer_state(opt_state, root_rank=0):
    """Broadcast an optax optimizer state pytree from root_rank.

    Array leaves broadcast natively; non-array leaves (step counters are
    arrays in optax, but schedules may close over python scalars) ride
    along via broadcast_object.
    """
    leaves, treedef = jax.tree.flatten(opt_state)
    array_ix = [i for i, l in enumerate(leaves)
                if isinstance(l, (jax.Array, np.ndarray))]
    array_set = set(array_ix)
    other_ix = [i for i in range(len(leaves)) if i not in array_set]
    arrays = broadcast_parameters([leaves[i] for i in array_ix], root_rank,
                                  prefix="opt_state")
    others = broadcast_object([leaves[i] for i in other_ix], root_rank,
                              name="opt_state.pyleaves")
    out = list(leaves)
    for i, v in zip(array_ix, arrays):
        out[i] = v
    for i, v in zip(other_ix, others):
        out[i] = v
    return jax.tree.unflatten(treedef, out)


def broadcast_object(obj, root_rank=0, name=None):
    """Pickle-broadcast an arbitrary python object from root_rank.

    Reference analog: hvd.broadcast_object (horovod/torch/functions.py):
    length first, then the payload as a byte tensor.
    """
    name = name or "broadcast_object"
    if mpi_ops.rank() == root_rank:
        buf = io.BytesIO()
        pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
        payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
    else:
        payload = np.zeros(0, dtype=np.uint8)

    nbytes = np.array([payload.size], dtype=np.int64)
    nbytes = np.asarray(
        mpi_ops.broadcast(nbytes, root_rank, name=f"{name}.len"))
    if mpi_ops.rank() != root_rank:
        payload = np.zeros(int(nbytes[0]), dtype=np.uint8)
    data = np.asarray(
        mpi_ops.broadcast(payload, root_rank, name=f"{name}.data"))
    return pickle.loads(data.tobytes())


def allgather_object(obj, name=None):
    """Gather an arbitrary python object from every rank; returns a list
    indexed by rank. Reference analog: hvd.allgather_object."""
    from horovod_tpu.common.elastic import _allgather_object

    return _allgather_object(obj, name=name or "allgather_object")
