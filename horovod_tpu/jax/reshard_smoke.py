"""Four-process cross-plane + redistribute smoke: ``make reshard-smoke``.

Launches 4 real ranks as an emulated 2-slice x 2-rank topology
(``HOROVOD_CROSS_PLANE=hier``, host-major layout env) over TCP loopback
and proves the cross-plane lane end to end, kill-free, no accelerator:

- **hierarchical train step parity** — an eager data-parallel SGD loop
  (AVERAGE allreduce per step) under the hierarchical decomposition
  lands EXACTLY on the locally replayed trajectory (integer-valued
  grads: association-free), with every step's cross-plane wire bytes
  equal to the per-plane predictor
  (``telemetry.predict.hier_allreduce_wire_bytes``) to the byte;
- **checkpoint reshard** — a 4-way row-sharded "checkpoint" is
  redistributed to the serve layout (2 uneven shards + replicas) and
  back via ``parallel.reshard.execute_plan``; contents round-trip and
  measured-vs-predicted wire bytes reconcile < 1% (byte-exact here);
- **cross-plane byte bound** — the hierarchical allreduce's cross-hop
  bytes stay <= ~(1/local_size + eps) of what the flat ring would have
  pushed through the slice boundary (the ISSUE-8 acceptance ratio).
"""

import os
import subprocess
import sys

_STEPS = 4
_DIM = 8192 + 37
_LOCAL = 2
_SIZE = 4
_ROWS = 37


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker():
    import numpy as np

    from horovod_tpu.common import basics, eager_ops as ops
    from horovod_tpu.parallel.reshard import (
        Layout,
        execute_plan,
        plan_redistribute,
        simulate_plan,
    )
    from horovod_tpu.telemetry.predict import (
        flat_ring_wire_bytes,
        hier_allreduce_wire_bytes,
    )

    b = basics.HorovodBasics()
    b.init()
    rank, size = b.rank(), b.size()
    try:
        assert b.cross_plane() == "hier", b.cross_plane()
        assert b.hier_split() == _LOCAL, b.hier_split()

        # ---- 1) hierarchical train-step parity + exact byte books ----
        grid = np.arange(_DIM, dtype=np.float32) % 9 - 4  # exact ints
        params = np.zeros(_DIM, np.float64)
        replay = np.zeros(_DIM, np.float64)
        lr = 0.1
        cross_moved = 0
        snap0 = b.metrics_snapshot()["wire"]
        for step in range(_STEPS):
            b.step_mark(True)  # scope each train step for the ledger
            g = grid * float(rank + 1 + step)
            mean = ops.allreduce_async(
                g, f"train.{step}", op=ops.ReduceOp.AVERAGE).synchronize()
            params -= lr * mean.astype(np.float64)
            gmean = grid * (sum(range(1, size + 1)) / size + step)
            replay -= lr * gmean.astype(np.float64)
            b.step_mark(False)
        snap1 = b.metrics_snapshot()["wire"]
        np.testing.assert_array_equal(params, replay)
        # Overlap-ledger reconciliation on the hierarchical lane
        # (docs/metrics.md "Overlap ledger"): per plane, exposed +
        # hidden == total EXACTLY, every step window was booked, and
        # the cross-plane hop recorded ledger time inside the steps —
        # the per-plane step anatomy the fusion work will be judged on.
        ov0, ov1 = snap0["overlap"], snap1["overlap"]
        assert ov1["steps"] - ov0["steps"] == _STEPS, (ov0, ov1)
        for plane in ("intra", "cross"):
            p = ov1[plane]
            assert p["exposed_us"] + p["hidden_us"] == p["total_us"], ov1
            assert p["total_us"] > ov0[plane]["total_us"], (plane, ov1)
        pred = hier_allreduce_wire_bytes(_DIM, 4, size, _LOCAL, rank)
        cross_moved = snap1["cross_tx_bytes"] - snap0["cross_tx_bytes"]
        total_moved = snap1["tx_bytes"] - snap0["tx_bytes"]
        assert cross_moved == _STEPS * pred["cross"], \
            (cross_moved, _STEPS * pred["cross"])
        assert total_moved == _STEPS * (pred["cross"] + pred["intra"])
        # Per-plane split (telemetry.core.wire_plane_bytes, the r15
        # StepTimer surface): intra = total - cross must reconcile to
        # the byte against the SAME planner math, independently.
        from horovod_tpu.telemetry.core import wire_plane_bytes

        intra_now = wire_plane_bytes()[0]
        intra0 = snap0["tx_bytes"] - snap0["cross_tx_bytes"]
        assert intra_now - intra0 == _STEPS * pred["intra"], \
            (intra_now - intra0, _STEPS * pred["intra"])

        # Acceptance ratio: cross-plane bytes <= ~(1/local_size + eps)
        # of the flat ring's DCN traffic. The flat ring is LOCALITY-
        # BLIND — it streams the whole 2(N-1)/N x payload per rank with
        # no idea where the slice boundary sits, so its bytes price at
        # DCN rates; only the hierarchical decomposition confines the
        # expensive fabric to the 1/local_size shards.
        flat_dcn = sum(flat_ring_wire_bytes(_DIM, 4, size, r)
                       for r in range(size))
        world_cross = sum(
            hier_allreduce_wire_bytes(_DIM, 4, size, _LOCAL, r)["cross"]
            for r in range(size))
        ratio = world_cross / flat_dcn
        assert ratio <= 1.0 / _LOCAL + 0.05, ratio

        # ---- 2) checkpoint reshard: train layout -> serve layout -----
        full = np.arange(_ROWS * 4, dtype=np.float32).reshape(_ROWS, 4)
        train = Layout.sharded(_ROWS, size)
        serve = Layout.from_rows([(0, 20), (20, 17), (37, 0), (37, 0)])
        s, c = train.rows[rank]
        local = full[s:s + c]
        sim = [full[a:a + n] for a, n in train.rows]
        moved_total, pred_total = 0, 0
        for src_l, dst_l, tag in ((train, serve, "to-serve"),
                                  (serve, Layout.replicated(size), "rep"),
                                  (Layout.replicated(size), train,
                                   "back")):
            plan = plan_redistribute(full.shape, np.float32, src_l, dst_l)
            w0 = b.metrics_snapshot()["wire"]["tx_bytes"]
            local = execute_plan(plan, local, name=f"ckpt.{tag}")
            moved = b.metrics_snapshot()["wire"]["tx_bytes"] - w0
            sim = simulate_plan(plan, sim)
            np.testing.assert_array_equal(local, sim[rank])
            moved_total += moved
            pred_total += plan.wire_tx_bytes(rank)
        np.testing.assert_array_equal(local, full[s:s + c])
        err = abs(moved_total - pred_total) / max(pred_total, 1)
        assert err < 0.01, (moved_total, pred_total)

        print(f"RESHARD_SMOKE_OK rank={rank} cross_ratio={ratio:.4f} "
              f"train_cross={cross_moved} reshard_moved={moved_total} "
              f"reshard_predicted={pred_total}")
    finally:
        b.shutdown()


def main():
    if "--worker" in sys.argv:
        worker()
        return 0

    port = _free_port()
    procs = []
    for rank in range(_SIZE):
        env = dict(os.environ,
                   HOROVOD_RANK=str(rank), HOROVOD_SIZE=str(_SIZE),
                   HOROVOD_LOCAL_RANK=str(rank % _LOCAL),
                   HOROVOD_LOCAL_SIZE=str(_LOCAL),
                   HOROVOD_CROSS_RANK=str(rank // _LOCAL),
                   HOROVOD_CROSS_SIZE=str(_SIZE // _LOCAL),
                   HOROVOD_CROSS_PLANE="hier",
                   HOROVOD_CONTROLLER_ADDR="127.0.0.1",
                   HOROVOD_CONTROLLER_PORT=str(port),
                   JAX_PLATFORMS="cpu")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.jax.reshard_smoke",
             "--worker"],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    failed = False
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out = "TIMEOUT"
        ok = p.returncode == 0 and "RESHARD_SMOKE_OK" in out
        print(out.strip())
        if not ok:
            print(f"rank {rank} FAILED (rc={p.returncode})")
            failed = True
    if failed:
        return 1
    print(f"reshard-smoke: OK ({_SIZE} ranks as 2 slices — hierarchical "
          "train parity, exact per-plane byte books, checkpoint reshard "
          "round-trip with <1% reconciliation)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
