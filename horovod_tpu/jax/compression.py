"""Gradient compression for eager allreduce.

Reference analog: ``horovod/torch/compression.py`` — ``Compression.none`` /
``Compression.fp16`` compress the payload before allreduce and decompress
after (2x smaller wire traffic for fp32 grads).
"""

import jax.numpy as jnp


class Compressor:
    """Interface for compressing/decompressing a tensor around allreduce."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) where context is whatever
        decompress needs."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast fp32/fp64 to fp16 for the wire; restore original dtype after.

    On TPU prefer bfloat16 (same byte savings, fp32-range exponent):
    use ``BFloat16Compressor``.
    """

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.float16:
            return tensor.astype(jnp.float16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class BFloat16Compressor(Compressor):
    """TPU-native 2x compression: bfloat16 keeps fp32 exponent range so
    gradient overflow handling is unnecessary (net-new vs reference)."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.bfloat16:
            return tensor.astype(jnp.bfloat16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class Compression:
    """Option group, mirroring hvd.Compression in the reference."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BFloat16Compressor
