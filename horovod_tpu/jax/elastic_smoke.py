"""Two-process elastic smoke: ``make elastic-smoke``.

Launches 2 real ranks over the eager host ring and proves the
preemption-native recovery lane end to end, no accelerator (mirroring
``make zero-smoke``; docs/elastic.md):

- rank 1 is killed by deterministic fault injection
  (``HOROVOD_FAULT_INJECT``) at a precise collective mid-training;
- rank 0, wrapped in ``hvd.elastic.run`` with a committed ``JaxState``,
  gets the typed recoverable error, re-forms a 1-rank ring IN PLACE
  (``hvdtpu_reinit`` — no process restart, no checkpoint round-trip),
  restores the last commit, and finishes training;
- the final params land exactly on the reference trajectory (2-rank
  mean grads through the last commit, solo grads after), and the
  metrics snapshot books the fault lifecycle (detected / recovered /
  blacklisted, epoch bump, detection latency).
"""

import os
import subprocess
import sys

STEPS = 6
FAIL_STEP = 3
DIM = 129
LR = 0.1
# state.sync() costs 2 broadcasts (ops 0-1); step s's allreduce is op
# 2 + s, so rank 1 dies at the top of step FAIL_STEP.
KILL_OP = 2 + FAIL_STEP


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu.jax as hvd
    from horovod_tpu.common.basics import HorovodBasics

    b = HorovodBasics()
    hvd.elastic.init()
    start_rank = hvd.rank()

    def grad(step, rank):
        return np.full(DIM, 0.01 * (step + 1) * (rank + 1), np.float32)

    state = hvd.elastic.JaxState(params=jnp.zeros(DIM, jnp.float32),
                                 step=0)

    @hvd.elastic.run
    def train(state):
        while state.step < STEPS:
            mean = hvd.allreduce(grad(state.step, hvd.rank()),
                                 name=f"g.{state.step}.{b.epoch()}",
                                 op=hvd.Average)
            state.params = state.params - LR * jnp.asarray(mean)
            state.step += 1
            state.commit()
        return state.params

    params = np.asarray(train(state))
    # Rank 1 dies inside the loop; only rank 0 reaches this point.
    assert start_rank == 0, start_rank
    assert hvd.size() == 1 and b.epoch() == 1, (hvd.size(), b.epoch())

    fault = b.last_fault()
    assert fault is not None and fault["ranks"] == [1], fault
    assert fault["recovered"] is True, fault

    ref = np.zeros(DIM, np.float64)
    for s in range(STEPS):
        world = (1, 2) if s < FAIL_STEP else (1,)
        ref -= LR * 0.01 * (s + 1) * sum(world) / len(world)
    np.testing.assert_allclose(params, ref, rtol=1e-5, atol=1e-7)

    snap = b.metrics_snapshot()
    el = snap["elastic"]
    assert el["epoch"] == 1, el
    assert el["faults_detected"] >= 1, el
    assert el["faults_recovered"] == 1, el
    assert el["ranks_blacklisted"] == 1, el
    assert el["detect_us"]["count"] >= 1, el

    print(f"ELASTIC_SMOKE_OK rank={start_rank} epoch={el['epoch']} "
          f"detected={el['faults_detected']} "
          f"detect_p50_us={el['detect_us']['p50_us']} "
          f"blacklisted={el['ranks_blacklisted']}")
    hvd.shutdown()


def main():
    if "--worker" in sys.argv:
        worker()
        return 0

    size = 2
    port = _free_port()
    procs = []
    for rank in range(size):
        env = dict(os.environ,
                   HOROVOD_RANK=str(rank), HOROVOD_SIZE=str(size),
                   HOROVOD_LOCAL_RANK=str(rank),
                   HOROVOD_LOCAL_SIZE=str(size),
                   HOROVOD_CONTROLLER_ADDR="127.0.0.1",
                   HOROVOD_CONTROLLER_PORT=str(port),
                   HOROVOD_WIRE_TIMEOUT_MS="4000",
                   HOROVOD_FAULT_INJECT=f"1:{KILL_OP}",
                   JAX_PLATFORMS="cpu")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.jax.elastic_smoke",
             "--worker"],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    failed = False
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out = "TIMEOUT"
        print(out.strip())
        if rank == 0:
            if p.returncode != 0 or "ELASTIC_SMOKE_OK" not in out:
                print(f"rank 0 FAILED (rc={p.returncode})")
                failed = True
        else:
            # The victim must die by SIGKILL at the injected collective,
            # never exit cleanly and never hang.
            if p.returncode != -9:
                print(f"victim rank {rank} did not die by injection "
                      f"(rc={p.returncode})")
                failed = True
    if failed:
        return 1
    print("elastic-smoke: OK (2->1 kill-and-recover: typed error, "
          "in-place ring re-formation, resume from last commit, "
          "fault telemetry)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
