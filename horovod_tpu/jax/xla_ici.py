"""xla_ici device data plane: eager collectives as cached XLA programs.

Reference analog: the NCCL data-plane backend
(``horovod/common/ops/nccl_operations.cc``) plus the fusion buffer
(``horovod/common/fusion_buffer_manager.cc``) — re-founded on XLA per
SURVEY.md §7's key insight: Horovod's response cache ≅ a compiled-
executable cache. Each fused group of device tensors becomes ONE jitted
program — device-side concat → ``psum`` over the mesh axis → split, with
pre/postscale folded in — compiled once per (op, shapes, dtype, scales,
process-set) signature and replayed every later step. The C++ core keeps
what it's good at: negotiation, ordering, fusion grouping, the response
cache, and join handling over the host network. Because every member rank
receives the identical fused ResponseList, the per-rank program launches
line up into one collective over ICI (TPU pods) or the gloo CPU backend
(tests).

Topology: one device per rank ("rank-per-chip"). Multi-process runs
require ``jax.distributed`` to be initialized with ``process_id`` equal to
the Horovod rank; ``enable()`` does this itself from the controller
address when possible.
"""

import ctypes
import os
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.common import process_sets
from horovod_tpu.common.basics import HorovodBasics
from horovod_tpu.common.eager_ops import _DTYPE_TO_ENUM, ReduceOp
from horovod_tpu.common.exceptions import HorovodInternalError

_basics = HorovodBasics()

_ENUM_TO_DTYPE = {v: k for k, v in _DTYPE_TO_ENUM.items()}

# Response::ResponseType values (csrc/message.h) — the callback's op_class.
_OP_ALLREDUCE = 0
_OP_ALLGATHER = 1
_OP_BROADCAST = 2
_OP_ALLTOALL = 3
_OP_REDUCESCATTER = 4

_EXEC_FN = ctypes.CFUNCTYPE(
    ctypes.c_int32,                    # return: 0 ok, nonzero = error
    ctypes.c_int32,                    # op_class
    ctypes.c_int32,                    # n fused tensors
    ctypes.POINTER(ctypes.c_char_p),   # names
    ctypes.POINTER(ctypes.c_int64),    # shapes_flat [ndim, dims...]*n
    ctypes.c_int32,                    # dtype enum
    ctypes.c_int32,                    # reduce_op
    ctypes.c_int32,                    # root_rank
    ctypes.c_int32,                    # process_set_id
    ctypes.POINTER(ctypes.c_int64),    # rank_sizes (allgather first dims)
    ctypes.c_int32,                    # n_rank_sizes
    ctypes.POINTER(ctypes.c_char),     # err buffer
    ctypes.c_int32)                    # err capacity


def _decode_shapes(shapes_p, n):
    shapes, pos = [], 0
    for _ in range(n):
        ndim = int(shapes_p[pos])
        pos += 1
        shapes.append(tuple(int(shapes_p[pos + j]) for j in range(ndim)))
        pos += ndim
    return shapes


def _nelem(shape):
    n = 1
    for d in shape:
        n *= d
    return n


def _row_elems(rest):
    """Elements per first-dim row: 1 for scalar rows (rest == ()), the
    true product otherwise — including 0 for zero-size trailing dims
    (``x or 1`` would corrupt those)."""
    return _nelem(rest) if rest else 1


def _distributed_initialized():
    """Whether jax.distributed.initialize already ran — checked WITHOUT
    touching the backend (jax.process_count() would initialize it, locking
    in a single-process topology)."""
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:  # pragma: no cover - private API moved
        return False


class XlaIciDataPlane:
    """Executes the core's fused device responses as cached XLA programs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active = False
        self._rank = 0
        self._size = 1
        self._devices = None          # rank -> jax device
        self._local_device = None
        self._inputs = {}             # (ps_id, name) -> (array, pre, post,
                                      #                   donate)
        self._outputs = {}            # (ps_id, name) -> jax array
        self._exec_cache = {}         # signature -> jitted program
        self._cb_ref = None           # keep the CFUNCTYPE alive
        self._retained_topology = None  # topology the cache compiled for
        self.cache_reuses = 0         # enables that kept the cache
        self.cache_invalidations = 0  # enables that had to clear it

    # -- lifecycle ---------------------------------------------------------

    @property
    def active(self):
        return self._active

    def enable(self):
        """Bind one device per rank and register with the core.

        Multi-process: initializes ``jax.distributed`` against the
        controller host (port = HOROVOD_XLA_COORD_PORT or controller
        port + 1) unless the caller already did.

        Plane selection (``HOROVOD_CROSS_PLANE``, docs/redistribute.md):
        ``ring`` forces every collective onto the host ring — the
        device plane refuses to activate so frontends transparently
        fall back; ``ici``/``auto``/``hier`` all want this plane up
        (under ``hier`` the HOST side of a device-ineligible collective
        still decomposes hierarchically in the core).
        """
        if self._active:
            return
        if cross_plane_mode() == "ring":
            raise RuntimeError(
                "HOROVOD_CROSS_PLANE=ring forces host-ring collectives; "
                "the xla_ici device plane stays disabled under it")
        rank, size = _basics.rank(), _basics.size()
        if rank < 0:
            raise RuntimeError("hvd.init() must run before the XLA data "
                               "plane is enabled")
        if size > 1:
            if not _distributed_initialized():
                addr = os.environ.get("HOROVOD_CONTROLLER_ADDR", "127.0.0.1")
                port = int(os.environ.get(
                    "HOROVOD_XLA_COORD_PORT",
                    int(os.environ.get("HOROVOD_CONTROLLER_PORT", 29500)) + 1))
                # Must run BEFORE the backend client exists (so don't probe
                # jax.default_backend() here). The CPU collectives setting
                # is inert on TPU.
                try:
                    jax.config.update("jax_cpu_collectives_implementation",
                                      "gloo")
                except Exception:  # backend already up; keep its setting
                    pass
                jax.distributed.initialize(
                    coordinator_address=f"{addr}:{port}",
                    num_processes=size, process_id=rank)
            if jax.process_count() != size or jax.process_index() != rank:
                raise RuntimeError(
                    f"jax.distributed topology (process "
                    f"{jax.process_index()}/{jax.process_count()}) does not "
                    f"match Horovod rank {rank}/{size}")
            by_proc = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, []).append(d)
            self._devices = []
            for p in range(size):
                devs = by_proc.get(p)
                if not devs:
                    raise RuntimeError(f"no jax device for process {p}")
                # Rank-per-chip: one device per process. If a process owns
                # several (e.g. CPU tests), every rank still uses its first
                # so device lists agree across ranks.
                self._devices.append(devs[0])
            self._local_device = self._devices[rank]
        else:
            self._local_device = jax.local_devices()[0]
            self._devices = [self._local_device]
        self._rank, self._size = rank, size
        # Elastic fast re-init (SURVEY §7 hard part: "recovery requires
        # tearing down and re-creating the PJRT client/mesh; slow — needs
        # a cached-topology fast path"): compiled executables stay valid
        # as long as (rank, size, device list) — everything their meshes
        # and shard layouts close over — is unchanged. The common
        # recovery case (a worker replaced at the same size) re-enables
        # with the identical topology on every surviving rank, so the
        # whole executable cache replays instead of recompiling. Any
        # topology drift invalidates the lot.
        topology = (rank, size, tuple(self._devices))
        if self._exec_cache:
            if topology == self._retained_topology:
                self.cache_reuses += 1
            else:
                self._exec_cache.clear()
                self.cache_invalidations += 1
        self._retained_topology = topology
        self._cb_ref = _EXEC_FN(self._execute)
        _basics.lib.hvdtpu_set_device_callback(
            ctypes.cast(self._cb_ref, ctypes.c_void_p))
        self._active = True

    def disable(self):
        if not self._active:
            return
        _basics.lib.hvdtpu_set_device_callback(None)
        self._active = False
        self._cb_ref = None
        # In-flight payloads die with the epoch; the executable cache is
        # RETAINED against self._retained_topology — enable() decides
        # whether the next epoch can reuse it (elastic fast re-init) or
        # must recompile (topology changed).
        with self._lock:
            self._inputs.clear()
            self._outputs.clear()

    def executable_cache_size(self):
        return len(self._exec_cache)

    def invalidate(self):
        """Drop every retained executable NOW (not at the next enable).

        The fast re-init retention assumes the jax backend client
        persists across the disable/enable cycle — true for in-process
        elastic recovery, where jax.distributed cannot re-initialize a
        different world anyway. Anything that genuinely tears down and
        recreates the PJRT client must call this first: retained
        executables pin the OLD client's devices until enable() sees
        the topology changed."""
        self._exec_cache.clear()
        self._retained_topology = None

    # -- frontend side -----------------------------------------------------

    def register_input(self, name, process_set_id, array, prescale=1.0,
                       postscale=1.0, donate=False):
        arr = jax.device_put(array, self._local_device)
        with self._lock:
            self._inputs[(process_set_id, name)] = (arr, float(prescale),
                                                    float(postscale),
                                                    bool(donate))
        return arr

    def pop_output(self, name, process_set_id):
        with self._lock:
            return self._outputs.pop((process_set_id, name))

    def drop(self, name, process_set_id):
        """Release any buffers pinned for a failed collective (ERROR
        response or enqueue failure — the callback never ran, so nothing
        else pops the input and the HBM would stay pinned)."""
        with self._lock:
            self._inputs.pop((process_set_id, name), None)
            self._outputs.pop((process_set_id, name), None)

    # -- core side (background thread) ------------------------------------

    def _execute(self, op_class, n, names_p, shapes_p, dtype, reduce_op,
                 root_rank, ps_id, sizes_p, n_sizes, err_p, err_cap):
        try:
            names = [names_p[i].decode() for i in range(n)]
            shapes = _decode_shapes(shapes_p, n)
            np_dtype = _ENUM_TO_DTYPE[dtype]
            rank_sizes = tuple(int(sizes_p[i]) for i in range(n_sizes))
            self._run(op_class, names, shapes, np_dtype, reduce_op,
                      root_rank, ps_id, rank_sizes)
            return 0
        except Exception as e:  # noqa: BLE001 — crosses the C boundary
            msg = f"xla_ici: {type(e).__name__}: {e}".encode()[:err_cap - 1]
            ctypes.memmove(err_p, msg + b"\0", len(msg) + 1)
            return 1

    def _members(self, ps_id):
        members = process_sets.members_of(ps_id)
        if members is None:
            raise ValueError(f"unknown process set {ps_id}")
        return tuple(members)

    def _take_inputs(self, names, shapes, np_dtype, ps_id):
        """Local contributions in fused order; zeros for names this rank
        never enqueued (join support). Third return: whether EVERY input
        in the group was registered with donate=True (donation is
        all-or-nothing per fused program)."""
        arrs, scales = [], []
        donate = True
        with self._lock:
            pending = [self._inputs.pop((ps_id, nm), None) for nm in names]
        for nm, shape, p in zip(names, shapes, pending):
            if p is None:
                arrs.append(jnp.zeros(shape, np_dtype))
                scales.append((1.0, 1.0))
                donate = False
            else:
                arr, pre, post, don = p
                if arr.dtype != np_dtype:
                    arr = arr.astype(np_dtype)
                arrs.append(arr)
                scales.append((pre, post))
                donate = donate and don
        return arrs, tuple(scales), donate

    def _mesh(self, members):
        return Mesh(np.array([self._devices[r] for r in members]), ("hvd",))

    def _global(self, mesh, group, local_2d):
        """Lift this rank's (1, k) block to the global (group, k) array."""
        shard = jax.device_put(local_2d, self._local_device)
        return jax.make_array_from_single_device_arrays(
            (group,) + tuple(local_2d.shape[1:]),
            NamedSharding(mesh, P("hvd")), [shard])

    def _store(self, names, ps_id, outs):
        with self._lock:
            for nm, o in zip(names, outs):
                self._outputs[(ps_id, nm)] = o

    def _run(self, op_class, names, shapes, np_dtype, reduce_op, root_rank,
             ps_id, rank_sizes):
        members = self._members(ps_id)
        group = len(members)
        mesh = self._mesh(members)
        if op_class == _OP_ALLREDUCE:
            arrs, scales, donate = self._take_inputs(names, shapes,
                                                     np_dtype, ps_id)
            sig = (op_class, members, np_dtype.str, tuple(shapes), reduce_op,
                   scales, donate)
            fn = self._exec_cache.get(sig)
            if fn is None:
                if group == 1:
                    fn = _build_allreduce_local(reduce_op, scales, donate)
                else:
                    fn = _build_allreduce(mesh, group, shapes, reduce_op,
                                          scales, donate)
                self._exec_cache[sig] = fn
            if group == 1:
                # Single-member set: the reduction is identity × scales,
                # so the program takes the arrays in their ORIGINAL
                # shapes — no flat staging copies, no concat buffer, and
                # with donation the outputs alias the inputs outright
                # (zero HBM transient; at flagship gradient sizes the
                # concat path's transients would not even fit next to
                # the model). One executable call replaces ~2n per-
                # tensor lifts — the dominant dispatch cost on
                # high-latency transports.
                outs = list(fn(*arrs))
                del arrs
                self._store(names, ps_id, outs)
                return
            # Reshape + lift one tensor at a time, RELEASING the flat
            # staging copy's predecessor as we go — with donation active
            # the fused program then runs with only one generation of
            # buffers live (the HBM fusion-buffer story, SURVEY §7).
            gins = []
            for i in range(len(arrs)):
                gins.append(self._global(mesh, group,
                                         arrs[i].reshape(1, -1)))
                arrs[i] = None
            del arrs
            # Outputs come back already in their final shapes (reshape
            # folded into the compiled program — no host-side copy).
            outs = [g.addressable_data(0) for g in fn(*gins)]
            self._store(names, ps_id, outs)
        elif op_class == _OP_BROADCAST:
            arrs, _, _ = self._take_inputs(names, shapes, np_dtype, ps_id)
            root_pos = members.index(root_rank)
            sig = (op_class, members, np_dtype.str, tuple(shapes), root_pos)
            fn = self._exec_cache.get(sig)
            if fn is None:
                fn = _build_broadcast(mesh, root_pos)
                self._exec_cache[sig] = fn
            g = self._global(mesh, group, arrs[0].reshape(1, -1))
            out = fn(g).addressable_data(0).reshape(shapes[0])
            self._store(names, ps_id, [out])
        elif op_class == _OP_ALLGATHER:
            # rank_sizes: per-member first dims (ragged allgather). This
            # rank's contribution is zero-padded to the max first dim so
            # shards are uniform; the program slices the padding back out.
            shape = shapes[0]
            rest = shape[1:] if shape else ()
            dims = rank_sizes if rank_sizes else (shape[0] if shape else 1,)
            max_d = max(max(dims), 1)
            my_rows = dims[members.index(self._rank)]
            arrs, _, _ = self._take_inputs(
                names, [(my_rows,) + rest], np_dtype, ps_id)
            local = arrs[0].reshape(my_rows, _row_elems(rest))
            pad = max_d - local.shape[0]
            if pad:
                local = jnp.concatenate(
                    [local, jnp.zeros((pad, local.shape[1]), np_dtype)])
            sig = (op_class, members, np_dtype.str, dims, rest)
            fn = self._exec_cache.get(sig)
            if fn is None:
                fn = _build_allgather(mesh, dims)
                self._exec_cache[sig] = fn
            g = self._global(mesh, group, local[None])
            out = fn(g).addressable_data(0).reshape((sum(dims),) + rest)
            self._store(names, ps_id, [out])
        elif op_class == _OP_ALLTOALL:
            # Equal splits only (the coordinator enforces identical
            # shapes): rank r's block j goes to rank j, landing at
            # position r — one lax.all_to_all, static shapes.
            shape = shapes[0]
            first = shape[0] if shape else 1
            rest = shape[1:] if shape else ()
            if first % group:
                raise ValueError(
                    f"device alltoall first dim {first} not divisible by "
                    f"group size {group}")
            arrs, _, _ = self._take_inputs(names, shapes, np_dtype, ps_id)
            sig = (op_class, members, np_dtype.str, tuple(shape))
            fn = self._exec_cache.get(sig)
            if fn is None:
                fn = _build_alltoall(mesh, group)
                self._exec_cache[sig] = fn
            g = self._global(mesh, group,
                             arrs[0].reshape(1, first, _row_elems(rest)))
            out = fn(g).addressable_data(0).reshape((first,) + rest)
            self._store(names, ps_id, [out])
        elif op_class == _OP_REDUCESCATTER:
            arrs, scales, _ = self._take_inputs(names, shapes, np_dtype,
                                                ps_id)
            shape = shapes[0]
            first = shape[0] if shape else 1
            rest = shape[1:] if shape else ()
            # First dim split as evenly as possible, remainder to lower
            # member positions — same convention as the host ring
            # (csrc/operations.cc REDUCESCATTER).
            q, rem = divmod(first, group)
            rows = [q + (1 if r < rem else 0) for r in range(group)]
            my_pos = members.index(self._rank)
            off = sum(rows[:my_pos])
            sig = (op_class, members, np_dtype.str, tuple(shape), reduce_op,
                   scales, my_pos)
            fn = self._exec_cache.get(sig)
            if fn is None:
                fn = _build_reducescatter(mesh, group, reduce_op, scales[0],
                                          off, rows[my_pos])
                self._exec_cache[sig] = fn
            g = self._global(mesh, group,
                             arrs[0].reshape(1, first, _row_elems(rest)))
            out = fn(g).addressable_data(0).reshape((rows[my_pos],) + rest)
            self._store(names, ps_id, [out])
        else:
            raise ValueError(f"unsupported device op_class {op_class}")


def _shard_map(fn, mesh, in_specs, out_specs):
    # check_vma off: outputs ARE replicated (psum/pmin/... results), but
    # the checker can't always prove it through the slice/scale epilogue.
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    # jax 0.4.x boxes: the experimental spelling, where the replication
    # checker is still called check_rep.
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _adasum_combine(x, group):
    """Adasum on the device plane: recursive-doubling pairwise combine
    (reference analog: ops/adasum_gpu_operations.cc — a first-class GPU
    op upstream; here one XLA program over the mesh axis).

    Each stage pairs rank i with i^d and combines
    ``(1 - a.b/(2|a|^2)) a + (1 - a.b/(2|b|^2)) b`` — symmetric, so both
    partners hold the identical result and distances double. Dots run in
    fp32 regardless of payload dtype (csrc/adasum.cc does the same for
    half/bf16). Requires a power-of-two group; the frontend falls back
    to the host path otherwise.
    """
    orig = x.dtype
    x = x.astype(jnp.float32)
    d = 1
    while d < group:
        y = lax.ppermute(x, "hvd", [(i, i ^ d) for i in range(group)])
        dot = jnp.sum(x * y)
        na = jnp.sum(x * x)
        nb = jnp.sum(y * y)
        ca = jnp.where(na > 0, 1.0 - dot / (2.0 * na), 1.0)
        cb = jnp.where(nb > 0, 1.0 - dot / (2.0 * nb), 1.0)
        x = ca * x + cb * y
        d *= 2
    return x.astype(orig)


def _reduce(buf, reduce_op, group):
    if reduce_op in (ReduceOp.SUM, ReduceOp.AVERAGE):
        red = lax.psum(buf, "hvd")
        if reduce_op == ReduceOp.AVERAGE:
            red = red / group if jnp.issubdtype(red.dtype, jnp.floating) \
                else red // group
        return red
    if reduce_op == ReduceOp.MIN:
        return lax.pmin(buf, "hvd")
    if reduce_op == ReduceOp.MAX:
        return lax.pmax(buf, "hvd")
    if reduce_op == ReduceOp.PRODUCT:
        return jnp.prod(lax.all_gather(buf, "hvd"), axis=0)
    raise ValueError(f"reduce op {reduce_op} is not supported on the XLA "
                     "data plane (Adasum rides the host path)")


def _build_allreduce_local(reduce_op, scales, donate):
    """The group-size-1 allreduce program: every reduce op over a single
    member is the identity (sum/avg/min/max/product of one contribution;
    Adasum's pairwise combine has no partner), so the compiled program
    is just the pre/post scales — and with donation, pure buffer
    aliasing. Original shapes in, original shapes out."""

    def inner(*xs):
        outs = []
        for x, (pre, post) in zip(xs, scales):
            if pre != 1.0:
                x = x * np.asarray(pre, x.dtype)
            if post != 1.0:
                x = x * np.asarray(post, x.dtype)
            outs.append(x)
        return tuple(outs)

    return jax.jit(
        inner,
        donate_argnums=tuple(range(len(scales))) if donate else ())


def _build_allreduce(mesh, group, shapes, reduce_op, scales, donate=False):
    """One program for the fused group: concat → reduce → split →
    reshape-to-final. This IS the fusion buffer — it lives in HBM for
    the duration of the program and XLA fuses the scale/concat/split
    elementwise work around the collective (reference analog:
    MemcpyInFusionBuffer + cuda_kernels.cu, done here by the compiler).
    ``donate=True`` additionally donates the input blocks so the
    outputs reuse their HBM (reference analog: the in-place fusion
    buffer — safe only when the frontend promised the inputs are dead,
    see ``enqueue_device(donate=...)``)."""
    sizes = [max(_nelem(s), 1) for s in shapes]

    def inner(*blocks):  # each (1, size_i)
        parts = []
        for b, (pre, _) in zip(blocks, scales):
            x = b.reshape(-1)
            if pre != 1.0:
                x = x * np.asarray(pre, x.dtype)
            parts.append(x)
        if reduce_op == ReduceOp.ADASUM:
            # Adasum is PER-TENSOR (the dot products that make it scale
            # insensitive are per-gradient — reference
            # Adasum::DispatchFusedAllreduce walks the fusion buffer
            # tensor-by-tensor), so no concat fusion here; the stages
            # still share the program and its collectives schedule.
            red_parts = [_adasum_combine(p, group) for p in parts]
        else:
            buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            red = _reduce(buf, reduce_op, group)
            red_parts = None
        outs, off = [], 0
        for i, (sz, (_, post)) in enumerate(zip(sizes, scales)):
            if red_parts is not None:
                o = red_parts[i]
            else:
                o = lax.slice_in_dim(red, off, off + sz)
                off += sz
            if post != 1.0:
                o = o * np.asarray(post, o.dtype)
            # Final shape comes out of the compiled program directly so
            # the host never reshape-copies the result.
            outs.append(o.reshape(shapes[i] if shapes[i] else ()))
        return tuple(outs)

    k = len(shapes)
    out_specs = tuple(P(*(None,) * len(s)) if s else P() for s in shapes)
    return jax.jit(_shard_map(inner, mesh, (P("hvd"),) * k, out_specs),
                   donate_argnums=tuple(range(k)) if donate else ())


def _build_broadcast(mesh, root_pos):
    def inner(block):  # (1, n)
        x = block.reshape(-1)
        idx = lax.axis_index("hvd")
        if jnp.issubdtype(x.dtype, jnp.bool_):
            contrib = jnp.where(idx == root_pos, x.astype(jnp.uint8),
                                jnp.zeros_like(x, jnp.uint8))
            return lax.psum(contrib, "hvd").astype(jnp.bool_)
        contrib = jnp.where(idx == root_pos, x, jnp.zeros_like(x))
        return lax.psum(contrib, "hvd")

    return jax.jit(_shard_map(inner, mesh, P("hvd"), P(None)))


def _build_allgather(mesh, dims):
    def inner(block):  # (1, max_d, restf)
        g = lax.all_gather(block[0], "hvd")  # (group, max_d, restf)
        segs = [lax.slice_in_dim(g[i], 0, d) for i, d in enumerate(dims)]
        return jnp.concatenate(segs, axis=0)

    return jax.jit(_shard_map(inner, mesh, P("hvd"), P(None)))


def _build_alltoall(mesh, group):
    def inner(block):  # (1, first, restf)
        x = block[0]
        first, restf = x.shape
        x = x.reshape(group, first // group, restf)
        y = lax.all_to_all(x, "hvd", split_axis=0, concat_axis=0)
        return y.reshape(1, first, restf)

    # Output differs per rank: stays sharded over "hvd", each process
    # reads its own shard.
    return jax.jit(_shard_map(inner, mesh, P("hvd"), P("hvd")))


def _build_reducescatter(mesh, group, reduce_op, scale, off, nrows):
    pre, post = scale

    def inner(block):  # (1, first, restf)
        x = block[0]
        if pre != 1.0:
            x = x * np.asarray(pre, x.dtype)
        red = _reduce(x, reduce_op, group)
        out = lax.slice_in_dim(red, off, off + nrows)
        if post != 1.0:
            out = out * np.asarray(post, out.dtype)
        return out

    return jax.jit(_shard_map(inner, mesh, P("hvd"), P(None)))


# Module-level singleton; frontends share it.
_data_plane = XlaIciDataPlane()


def cross_plane_mode():
    """The job's cross-plane topology descriptor — the core's parsed
    ``HOROVOD_CROSS_PLANE`` when it is initialized (covers the legacy
    ``HOROVOD_HIERARCHICAL_ALLREDUCE`` mapping), else the raw env.
    One of ``"auto" | "ici" | "ring" | "hier"``."""
    if _basics.lib.hvdtpu_is_initialized():
        return HorovodBasics.CROSS_PLANE_MODES[
            _basics.lib.hvdtpu_cross_plane()]
    mode = os.environ.get("HOROVOD_CROSS_PLANE", "").strip().lower()
    if mode in HorovodBasics.CROSS_PLANE_MODES:
        return mode
    if os.environ.get("HOROVOD_HIERARCHICAL_ALLREDUCE", "0") not in \
            ("", "0"):
        return "hier"
    return "auto"


def data_plane():
    return _data_plane


def active():
    return _data_plane.active


def enable():
    _data_plane.enable()


def disable():
    _data_plane.disable()


class DeviceHandle:
    """An in-flight device collective; ``synchronize`` returns the jax
    array produced by the data plane (payload never left HBM)."""

    def __init__(self, raw, name, process_set_id):
        self._raw = raw
        self._name = name
        self._ps = process_set_id
        self._done = False

    def poll(self):
        rc = _basics.lib.hvdtpu_poll(self._raw)
        if rc < 0:
            raise ValueError(f"invalid Horovod handle {self._raw}")
        return rc == 1

    def synchronize(self):
        if self._done:
            raise ValueError("handle already synchronized")
        lib = _basics.lib
        rc = lib.hvdtpu_wait(self._raw)
        self._done = True
        if rc != 0:
            err = lib.hvdtpu_error_string(self._raw)
            msg = err.decode() if err else "unknown error"
            lib.hvdtpu_release(self._raw)
            _data_plane.drop(self._name, self._ps)
            raise HorovodInternalError(msg)
        lib.hvdtpu_release(self._raw)
        return _data_plane.pop_output(self._name, self._ps)


# Response::ResponseType values accepted by hvdtpu_enqueue_device.
_ENQUEUE_OPS = {
    "allreduce": _OP_ALLREDUCE,
    "allgather": _OP_ALLGATHER,
    "broadcast": _OP_BROADCAST,
    "alltoall": _OP_ALLTOALL,
    "reducescatter": _OP_REDUCESCATTER,
}


def alltoall_group_size(process_set_id):
    """Member count of the set, for the frontend's equal-split check."""
    members = process_sets.members_of(int(process_set_id))
    return len(members) if members else 0


def adasum_device_supported(process_set_id, dtype):
    """Device-plane Adasum serves power-of-two float groups; anything
    else rides the host path (csrc/adasum.cc)."""
    if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return False
    n = alltoall_group_size(process_set_id)
    return n > 0 and (n & (n - 1)) == 0


def enqueue_device(kind, array, name, reduce_op=ReduceOp.SUM,
                   prescale_factor=1.0, postscale_factor=1.0, root_rank=0,
                   process_set_id=0, group_id=-1, group_size=0,
                   donate=False):
    """Register the device array and enqueue its negotiation-only request.

    The returned DeviceHandle's ``synchronize()`` yields the result as a
    jax array on this rank's device.

    ``donate=True`` (allreduce only) promises the caller will not read
    ``array`` again: the fused program then donates its HBM to the
    result, halving the collective's peak footprint. The input array is
    INVALID afterwards (jax donation semantics) — never set this for
    buffers aliased outside jax (e.g. the torch dlpack bridge).
    """
    ps_id = int(process_set_id)
    arr = _data_plane.register_input(name, ps_id, array, prescale_factor,
                                     postscale_factor, donate=donate)
    shape = (ctypes.c_int64 * max(arr.ndim, 1))(*arr.shape)
    dtype = _DTYPE_TO_ENUM[np.dtype(arr.dtype)]
    h = _basics.lib.hvdtpu_enqueue_device(
        _ENQUEUE_OPS[kind], name.encode(), arr.ndim, shape, dtype,
        int(reduce_op), int(root_rank), ps_id, int(group_id),
        int(group_size))
    if h < 0:
        _data_plane.drop(name, ps_id)
        raise RuntimeError(f"failed to enqueue device {kind} (is the XLA "
                           "data plane enabled and Horovod running?)")
    return DeviceHandle(h, name, ps_id)


def grouped_allreduce_device(tensors, names, reduce_op=ReduceOp.SUM,
                             prescale_factor=1.0, postscale_factor=1.0,
                             process_set_id=0, donate=False):
    """Atomically-negotiated grouped allreduce on device arrays: all
    tensors fuse into ONE XLA program (reference analog: grouped
    allreduce via group_table.cc, on the device data plane).

    Validates BEFORE enqueueing anything: a half-enqueued atomic group
    can never complete, hanging every member rank.
    """
    if len(names) != len(tensors):
        raise ValueError(f"grouped_allreduce: {len(tensors)} tensors but "
                         f"{len(names)} names")
    if len(set(names)) != len(names):
        raise ValueError(f"grouped_allreduce: duplicate names in {names}")
    if not (_data_plane.active and _basics.is_initialized()):
        raise RuntimeError("grouped_allreduce_device requires hvd.init() "
                           "and an active XLA data plane")
    gid = _basics.lib.hvdtpu_next_group_id() if len(tensors) > 1 else -1
    return [enqueue_device("allreduce", t, nm, reduce_op=reduce_op,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor,
                           process_set_id=process_set_id, group_id=gid,
                           group_size=len(tensors), donate=donate)
            for t, nm in zip(tensors, names)]
