"""JAX eager collective ops: hvd.allreduce & friends over jax arrays.

Reference analog: ``horovod/torch/mpi_ops.py`` (allreduce/allreduce_async/
synchronize/poll over framework tensors) — the reference has no JAX
frontend; this is the net-new ``horovod.jax`` from SURVEY.md §7 step 2.

Eager path: the jax array is brought to host, enqueued on the native core
(background negotiation + fused ring collectives over the control-plane
sockets), and the result re-wrapped as a jax array. For the in-graph
TPU-native path (psum over an ICI mesh inside jit), see
``horovod_tpu.parallel``.
"""

import os


import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.common import eager_ops
from horovod_tpu.common.eager_ops import ReduceOp
from horovod_tpu.jax import xla_ici

# Reference-compatible reduce-op aliases (horovod/torch/mpi_ops.py).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
Adasum = ReduceOp.ADASUM

_basics = eager_ops._basics

# In elastic mode (HOROVOD_RDZV_ADDR set) init consults the driver's
# rendezvous for this epoch's rank assignment; static mode unchanged.
from horovod_tpu.common import elastic as _elastic_init_mod


def _maybe_enable_xla_data_plane():
    """HOROVOD_XLA_DATA_PLANE: 1 = require, 0 = off, auto (default) =
    enable when jax is on TPU (the configuration the backend exists for)."""
    flag = os.environ.get("HOROVOD_XLA_DATA_PLANE", "auto").lower()
    if flag in ("0", "false", "off"):
        return
    if flag in ("1", "true", "on"):
        xla_ici.enable()
        return
    try:
        # Probe the platform from env first: jax.default_backend() would
        # initialize the backend, which must not happen before
        # jax.distributed.initialize in multi-process mode.
        platforms = os.environ.get("JAX_PLATFORMS", "")
        on_tpu = any(p in platforms for p in ("tpu", "axon"))
        if not on_tpu and not platforms:
            if _basics.size() <= 1:
                on_tpu = jax.default_backend() in ("tpu", "axon")
            else:
                # Multi-process: the backend must stay untouched until
                # jax.distributed.initialize, so probe for libtpu (how jax
                # itself detects TPU) instead of default_backend().
                import importlib.util

                on_tpu = importlib.util.find_spec("libtpu") is not None
        if on_tpu:
            xla_ici.enable()
    except Exception as e:  # noqa: BLE001 — auto mode degrades to host path
        import warnings

        warnings.warn(f"xla_ici data plane unavailable, using host "
                      f"collectives: {e}")


def init(jit_fusion=None):
    """Initialize the runtime. ``jit_fusion`` (tri-state) overrides the
    ``HOROVOD_JIT_FUSION`` env knob for jit-lane compute/collective
    fusion (docs/fusion.md): ``False`` restores the unfused split-step
    schedule, ``True`` forces fusion on, ``None`` (default) follows the
    environment."""
    if jit_fusion is not None:
        from horovod_tpu.parallel import fusion as _fusion

        _fusion.set_jit_fusion(jit_fusion)
    _elastic_init_mod.init()
    _maybe_enable_xla_data_plane()


# Elastic reset tears the data plane down with the old topology; try to
# bring it back up for the new epoch.
_elastic_init_mod.register_post_reset_hook(_maybe_enable_xla_data_plane)


def shutdown():
    # Flush any in-flight xprof trace first (users often skip
    # stop_timeline on teardown), then keep the device callback
    # registered until the background loop has drained (it may still be
    # executing device responses) before dropping it.
    _stop_xprof()
    _basics.shutdown()
    xla_ici.disable()


_xprof_active = False


def start_timeline(file_path, mark_cycles=False, xprof_dir=None):
    """Begin the runtime Chrome-trace timeline; optionally start a
    ``jax.profiler`` trace alongside so device-side XLA execution shows
    up in xprof/TensorBoard next to the negotiation timeline (reference
    analog: hvd.start_timeline + NVTX ranges for nsight;
    common/timeline.cc). ``xprof_dir`` defaults to
    ``HOROVOD_TIMELINE_XPROF`` when set.
    """
    global _xprof_active
    _basics.start_timeline(file_path, mark_cycles)
    xprof_dir = xprof_dir or os.environ.get("HOROVOD_TIMELINE_XPROF")
    if xprof_dir and not _xprof_active:
        jax.profiler.start_trace(str(xprof_dir))
        _xprof_active = True


def _stop_xprof():
    global _xprof_active
    if _xprof_active:
        jax.profiler.stop_trace()
        _xprof_active = False


def stop_timeline():
    _stop_xprof()
    _basics.stop_timeline()


def metrics():
    """Live snapshot of the native core's metrics registry, as a dict.

    Counter catalog in ``docs/metrics.md``: per-op-class counts/bytes
    (host ring and device plane), negotiation/queue/wire latency
    histograms, fusion-buffer fill, cycle stalls, response-cache hit
    rate, and the coordinator's per-rank straggler table. Counters are
    process-lifetime monotonic — diff snapshots to rate. For periodic
    export (JSONL flight recorder, Prometheus textfile, console) see
    ``horovod_tpu.telemetry.MetricsScraper``; for per-step MFU/goodput
    accounting see ``horovod_tpu.telemetry.StepTimer``.
    """
    from horovod_tpu import telemetry

    return telemetry.snapshot()


def metrics_reset():
    """Zero the metrics registry (tests / interactive use)."""
    from horovod_tpu import telemetry

    telemetry.metrics_reset()


def events(last_n=0):
    """The newest ``last_n`` events of the core's structured event ring
    (``0`` = the whole live window), as a list of dicts — the always-on
    flight recorder behind black-box post-mortems (docs/metrics.md).

    Non-consuming: safe alongside the debug server's ``/events`` and
    the core's own fault dumps. Each event carries ``seq``, ``ts_us``
    (steady clock), ``type`` (``negotiate_begin``, ``response_launch``,
    ``wire_chunk``, ``retry_window``, ``fault``, ``knob_adopt``, ...)
    and per-type named args. For the cross-rank forensic merge see
    ``python -m horovod_tpu.telemetry.report --post-mortem``.
    """
    return _basics.events(last_n)


def debug_port():
    """The bound port of this rank's debug server, or ``None`` when it
    is not running — THE discovery path under ``HOROVOD_DEBUG_PORT=0``
    (ephemeral bind for co-located/simulated large worlds; the port is
    also echoed as the ``X-Hvdtpu-Debug-Port`` response header and in
    ``/healthz``). See docs/metrics.md / docs/scale.md."""
    from horovod_tpu.telemetry import debug_server

    return debug_server.debug_port()


def step_mark(begin=True):
    """Mark a training-step boundary for the step-anatomy layer
    (docs/metrics.md "Step anatomy"): ``step_begin``/``step_end``
    events scope every other flight-recorder event to a step window and
    the wire overlap ledger unions the wire spans inside it. Driven
    automatically by ``telemetry.StepTimer`` and
    ``hvd.DistributedFusedAdam``; call directly only when neither
    scopes your loop. Returns the step id."""
    return _basics.step_mark(begin)


is_initialized = _basics.is_initialized
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
cross_rank = _basics.cross_rank
cross_size = _basics.cross_size
is_homogeneous = _basics.is_homogeneous

for _cap in _basics.CAPABILITY_NAMES:
    globals()[_cap] = getattr(_basics, _cap)

from horovod_tpu.common.auto_name import make_auto_namer

_auto_name = make_auto_namer()



def _to_host(tensor):
    """jax/np array -> contiguous numpy view on host."""
    return np.asarray(tensor)


class Handle:
    """In-flight eager collective; ``synchronize`` returns a jax array."""

    def __init__(self, inner):
        self._inner = inner

    def poll(self):
        return self._inner.poll()

    def synchronize(self):
        out = self._inner.synchronize()
        return jnp.asarray(out)


def _device_path(tensor, op=None, process_set_id=0):
    """Route through the xla_ici data plane? Only for accelerator-resident
    jax arrays. Adasum runs on-device for power-of-two float groups (the
    recursive-doubling XLA program); otherwise it keeps the host path."""
    if not (xla_ici.active() and isinstance(tensor, jax.Array)):
        return False
    if op == Adasum:
        return xla_ici.adasum_device_supported(process_set_id,
                                               tensor.dtype)
    return True


def allreduce_async(tensor, name=None, op=Average, prescale_factor=1.0,
                    postscale_factor=1.0, process_set_id=0, donate=False):
    """``donate=True`` promises the input array will not be read again;
    on the device data plane the fused program then reuses its HBM for
    the result (the input is invalid afterwards). The host path ignores
    it (the host copy is already detached from the device buffer)."""
    if _device_path(tensor, op, process_set_id):
        return xla_ici.enqueue_device(
            "allreduce", tensor, name or _auto_name("allreduce"),
            reduce_op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set_id=process_set_id,
            donate=donate)
    arr = _to_host(tensor)
    inner = eager_ops.allreduce_async(
        arr, name or _auto_name("allreduce"), op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set_id=process_set_id)
    return Handle(inner)


def allreduce(tensor, name=None, op=Average, prescale_factor=1.0,
              postscale_factor=1.0, process_set_id=0):
    return allreduce_async(tensor, name, op, prescale_factor,
                           postscale_factor, process_set_id).synchronize()


def grouped_allreduce_async(tensors, names=None, op=Average,
                            prescale_factor=1.0, postscale_factor=1.0,
                            process_set_id=0, donate=False):
    """Allreduce a list of tensors as one negotiation group (they fuse and
    complete atomically). Reference analog: hvd.grouped_allreduce
    (horovod/common/group_table.cc). ``donate`` as in
    :func:`allreduce_async` (device plane only)."""
    if names is None:
        base = _auto_name("grouped_allreduce")
        names = [f"{base}.{i}" for i in range(len(tensors))]
    if (tensors and all(_device_path(t, op, process_set_id)
                        for t in tensors)
            and len({t.dtype for t in tensors}) == 1):
        return xla_ici.grouped_allreduce_device(
            tensors, names, reduce_op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set_id=process_set_id,
            donate=donate)
    arrs = [_to_host(t) for t in tensors]
    if arrs and all(a.dtype == arrs[0].dtype for a in arrs):
        inners = eager_ops.grouped_allreduce_async(
            arrs, names, op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set_id=process_set_id)
        return [Handle(i) for i in inners]
    # Mixed dtypes: fall back to per-tensor enqueue (still fuses per-dtype
    # in the core's fusion buffer, just not negotiated atomically).
    return [allreduce_async(t, n, op, prescale_factor, postscale_factor,
                            process_set_id, donate=donate)
            for t, n in zip(tensors, names)]


def grouped_allreduce(tensors, names=None, op=Average, prescale_factor=1.0,
                      postscale_factor=1.0, process_set_id=0):
    handles = grouped_allreduce_async(tensors, names, op, prescale_factor,
                                      postscale_factor, process_set_id)
    return [h.synchronize() for h in handles]


def allgather_async(tensor, name=None, process_set_id=0):
    if _device_path(tensor):
        return xla_ici.enqueue_device(
            "allgather", tensor, name or _auto_name("allgather"),
            process_set_id=process_set_id)
    arr = _to_host(tensor)
    inner = eager_ops.allgather_async(arr, name or _auto_name("allgather"),
                                      process_set_id=process_set_id)
    return Handle(inner)


def allgather(tensor, name=None, process_set_id=0):
    return allgather_async(tensor, name, process_set_id).synchronize()


def grouped_allgather_async(tensors, names=None, process_set_id=0):
    """Allgather a list of tensors as ONE negotiation group: atomic
    completion across ranks (reference analog: hvd.grouped_allgather;
    same group-promotion machinery as grouped allreduce — responses
    stay per-tensor, only allreduce buffer-fuses)."""
    if names is None:
        base = _auto_name("grouped_allgather")
        names = [f"{base}.{i}" for i in range(len(tensors))]
    if tensors and all(_device_path(t) for t in tensors):
        gid = (_basics.lib.hvdtpu_next_group_id()
               if len(tensors) > 1 else -1)
        return [xla_ici.enqueue_device(
                    "allgather", t, nm, process_set_id=process_set_id,
                    group_id=gid, group_size=len(tensors))
                for t, nm in zip(tensors, names)]
    arrs = [_to_host(t) for t in tensors]
    inners = eager_ops.grouped_allgather_async(
        arrs, list(names), process_set_id=process_set_id)
    return [Handle(i) for i in inners]


def grouped_allgather(tensors, names=None, process_set_id=0):
    handles = grouped_allgather_async(tensors, names, process_set_id)
    return [h.synchronize() for h in handles]


def broadcast_async(tensor, root_rank, name=None, process_set_id=0):
    if _device_path(tensor):
        return xla_ici.enqueue_device(
            "broadcast", tensor, name or _auto_name("broadcast"),
            root_rank=root_rank, process_set_id=process_set_id)
    arr = _to_host(tensor)
    inner = eager_ops.broadcast_async(arr, root_rank,
                                      name or _auto_name("broadcast"),
                                      process_set_id=process_set_id)
    return Handle(inner)


def broadcast(tensor, root_rank, name=None, process_set_id=0):
    return broadcast_async(tensor, root_rank, name,
                           process_set_id).synchronize()


def alltoall_async(tensor, splits=None, name=None, process_set_id=0):
    # Equal-split alltoall can run as ONE static XLA program — but only
    # the user knows every rank contributes the same shape (a rank can't
    # see its peers' shapes when routing, and the host ring legitimately
    # supports ragged splits=None). Opt in with HOROVOD_XLA_ALLTOALL=1;
    # mismatched shapes then fail loudly at negotiation.
    if (_device_path(tensor) and splits is None
            and os.environ.get("HOROVOD_XLA_ALLTOALL", "0").lower()
            in ("1", "true", "on")):
        n = xla_ici.alltoall_group_size(process_set_id)
        if n > 0 and tensor.ndim > 0 and tensor.shape[0] % n == 0:
            return xla_ici.enqueue_device(
                "alltoall", tensor, name or _auto_name("alltoall"),
                process_set_id=process_set_id)
    arr = _to_host(tensor)
    inner = eager_ops.alltoall_async(arr, splits,
                                     name or _auto_name("alltoall"),
                                     process_set_id=process_set_id)
    return Handle(inner)


def alltoall(tensor, splits=None, name=None, process_set_id=0):
    return alltoall_async(tensor, splits, name, process_set_id).synchronize()


def reducescatter_async(tensor, name=None, op=Average, prescale_factor=1.0,
                        postscale_factor=1.0, process_set_id=0):
    # Adasum reducescatter stays on the host path (the device program's
    # reducer has no per-shard adasum form).
    if op != Adasum and _device_path(tensor, op):
        return xla_ici.enqueue_device(
            "reducescatter", tensor, name or _auto_name("reducescatter"),
            reduce_op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set_id=process_set_id)
    arr = _to_host(tensor)
    inner = eager_ops.reducescatter_async(
        arr, name or _auto_name("reducescatter"), op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set_id=process_set_id)
    return Handle(inner)


def reducescatter(tensor, name=None, op=Average, prescale_factor=1.0,
                  postscale_factor=1.0, process_set_id=0):
    return reducescatter_async(tensor, name, op, prescale_factor,
                               postscale_factor, process_set_id).synchronize()


def grouped_reducescatter_async(tensors, names=None, op=Average,
                                process_set_id=0):
    """Reduce-scatter a list of tensors as ONE negotiation group
    (atomic completion; reference analog: hvd.grouped_reducescatter)."""
    if names is None:
        base = _auto_name("grouped_reducescatter")
        names = [f"{base}.{i}" for i in range(len(tensors))]
    if (tensors and op != Adasum
            and all(_device_path(t, op) for t in tensors)):
        gid = (_basics.lib.hvdtpu_next_group_id()
               if len(tensors) > 1 else -1)
        return [xla_ici.enqueue_device(
                    "reducescatter", t, nm, reduce_op=op,
                    process_set_id=process_set_id, group_id=gid,
                    group_size=len(tensors))
                for t, nm in zip(tensors, names)]
    arrs = [_to_host(t) for t in tensors]
    inners = eager_ops.grouped_reducescatter_async(
        arrs, list(names), op=op, process_set_id=process_set_id)
    return [Handle(i) for i in inners]


def grouped_reducescatter(tensors, names=None, op=Average,
                          process_set_id=0):
    handles = grouped_reducescatter_async(tensors, names, op,
                                          process_set_id)
    return [h.synchronize() for h in handles]


def synchronize(handle):
    return handle.synchronize()


def poll(handle):
    return handle.poll()


def barrier(process_set_id=0):
    eager_ops.barrier(process_set_id=process_set_id)


def join():
    """Block until every rank has joined; contribute zeros meanwhile.

    Reference analog: ``hvd.join`` (horovod/torch/mpi_ops.py).
    Returns the last rank to join.
    """
    return eager_ops.join()
