"""Elastic training for the JAX frontend.

Reference analog: ``horovod/torch/elastic/state.py`` (TorchState) adapted
to pytrees — the reference has no JAX frontend (SURVEY.md §2.3); the
commit/restore/sync contract is identical: ``commit()`` snapshots to host
memory, ``restore()`` rolls back after a failed collective, ``sync()``
broadcasts rank 0's state after a re-rendezvous.

Usage::

    state = hvd.elastic.JaxState(params=params, opt_state=opt_state, step=0)

    @hvd.elastic.run
    def train(state):
        while state.step < total_steps:
            ... state.params, state.opt_state = update(...)
            if state.step % 10 == 0:
                state.commit()
            state.step += 1
"""

import copy

import jax
import numpy as np

from horovod_tpu.common import elastic as _elastic
from horovod_tpu.common.elastic import State

run = _elastic.run_fn
init = _elastic.init
reset = _elastic.reset
ObjectState = _elastic.ObjectState
survivors = _elastic.survivors
rejoin = _elastic.rejoin


def _to_host(tree):
    """Device pytree -> host numpy pytree (the commit snapshot)."""
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


class JaxState(State):
    """Elastic state over named pytrees / picklable values.

    ``checkpoint_dir`` makes every ``commit()`` also durable on disk via
    the orbax engine (horovod_tpu.checkpoint) — surviving full-job
    restarts, not just in-memory rollback. ``resume()`` reloads the
    newest on-disk commit. Reference analog: the reference's elastic
    State is memory-only (SURVEY.md §5.4); the disk layer is the
    TPU-idiomatic extension.
    """

    def __init__(self, checkpoint_dir=None, **kwargs):
        super().__init__()
        self._keys = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._ckpt_mgr = None
        self._commit_step = 0
        if checkpoint_dir is not None:
            from horovod_tpu.checkpoint import CheckpointManager

            self._ckpt_mgr = CheckpointManager(checkpoint_dir)
            # Continue numbering past any previous run's commits — orbax
            # silently skips steps that already exist on disk, so
            # restarting at 0 would drop every durable commit.
            self._commit_step = self._ckpt_mgr.latest_step() or 0
        self.save()

    def commit(self):
        self.save()
        if self._ckpt_mgr is not None:
            from horovod_tpu.checkpoint import encode_pytree

            self._commit_step += 1
            # encode: non-array values (run names, dicts of config, ...)
            # are legal elastic state but not orbax leaves.
            self._ckpt_mgr.save(self._commit_step,
                                encode_pytree(self._saved))
        self.check_host_updates()

    def resume(self):
        """Load the newest on-disk commit into this state (cold restart).

        Returns the restored step number, or None when the directory has
        no checkpoint yet."""
        if self._ckpt_mgr is None:
            raise ValueError("JaxState was created without checkpoint_dir")
        step = self._ckpt_mgr.latest_step()
        if step is None:
            return None
        from horovod_tpu.checkpoint import decode_pytree

        self._saved = decode_pytree(self._ckpt_mgr.restore(step))
        self._commit_step = step
        self.restore()
        return step

    def save(self):
        self._saved = {k: _to_host(getattr(self, k)) for k in self._keys}

    def restore(self):
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        _elastic._sync_state(self, "elastic.jax_state")
