"""Elastic training for the JAX frontend.

Reference analog: ``horovod/torch/elastic/state.py`` (TorchState) adapted
to pytrees — the reference has no JAX frontend (SURVEY.md §2.3); the
commit/restore/sync contract is identical: ``commit()`` snapshots to host
memory, ``restore()`` rolls back after a failed collective, ``sync()``
broadcasts rank 0's state after a re-rendezvous.

Usage::

    state = hvd.elastic.JaxState(params=params, opt_state=opt_state, step=0)

    @hvd.elastic.run
    def train(state):
        while state.step < total_steps:
            ... state.params, state.opt_state = update(...)
            if state.step % 10 == 0:
                state.commit()
            state.step += 1
"""

import copy

import jax
import numpy as np

from horovod_tpu.common import elastic as _elastic
from horovod_tpu.common.elastic import State, _broadcast_object

run = _elastic.run_fn
init = _elastic.init
reset = _elastic.reset
ObjectState = _elastic.ObjectState


def _to_host(tree):
    """Device pytree -> host numpy pytree (the commit snapshot)."""
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


class JaxState(State):
    """Elastic state over named pytrees / picklable values."""

    def __init__(self, **kwargs):
        super().__init__()
        self._keys = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self.save()

    def save(self):
        self._saved = {k: _to_host(getattr(self, k)) for k in self._keys}

    def restore(self):
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        from horovod_tpu.common.basics import HorovodBasics

        if HorovodBasics().size() == 1:
            return
        self.save()
        self._saved = _broadcast_object(self._saved, name="elastic.jax_state")
        self.restore()
