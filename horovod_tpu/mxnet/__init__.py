"""MXNet frontend.

Reference analog: ``horovod/mxnet/__init__.py`` + ``mpi_ops.py`` —
``DistributedOptimizer`` (allreduce inside ``update``), gluon
``DistributedTrainer`` (allreduce in ``_allreduce_grads``), and
``broadcast_parameters``. Collectives ride the shared eager core
(``horovod_tpu.common.eager_ops``) via NDArray's numpy bridge, so the
negotiation / fusion / response-cache machinery is identical across
frontends.

MXNet itself is optional: importing this module without mxnet installed
raises the same "extension not available" ImportError shape the reference
uses (horovod/mxnet raises on missing extension at import).
"""

try:
    import mxnet as mx
except ImportError as e:  # pragma: no cover - exercised only without mxnet
    raise ImportError(
        "horovod_tpu.mxnet requires the 'mxnet' package, which is not "
        "installed in this environment. The jax/torch/tensorflow frontends "
        "carry the same API.") from e

from horovod_tpu.mxnet.mpi_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allreduce,
    allreduce_,
    alltoall,
    barrier,
    broadcast,
    broadcast_,
    cross_rank,
    cross_size,
    grouped_allreduce,
    init,
    is_initialized,
    join,
    local_rank,
    local_size,
    rank,
    reducescatter,
    shutdown,
    size,
    start_timeline,
    stop_timeline,
)


def _ps_size(process_set):
    """Size of the process set — the world's for id 0, the subgroup's for
    a ProcessSet object OR a plain integer id (both are accepted wherever
    an id is expected, so both must scale gradients correctly)."""
    if hasattr(process_set, "size"):
        return process_set.size()
    ps_id = int(process_set)
    if ps_id == 0:
        return size()
    from horovod_tpu.common.basics import HorovodBasics

    n = HorovodBasics().lib.hvdtpu_process_set_size(ps_id)
    if n < 0:
        raise ValueError(f"unknown process set id {ps_id}")
    return n


def broadcast_parameters(params, root_rank=0, prefix=""):
    """Broadcast a gluon ``ParameterDict`` / plain dict of NDArrays from
    ``root_rank`` (reference: horovod/mxnet broadcast_parameters)."""
    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        raise ValueError("invalid params of type: %s" % type(params))
    for name, p in items:
        try:
            tensor = p.data() if hasattr(p, "data") else p
        except mx.gluon.parameter.DeferredInitializationError:
            continue
        broadcast_(tensor, root_rank, name=f"{prefix}parameter.{name}")
    if items:
        mx.nd.waitall()


class DistributedOptimizer(mx.optimizer.Optimizer):
    """Wrap an mxnet Optimizer: allreduce (average) each gradient before
    the wrapped update (reference: horovod/mxnet DistributedOptimizer)."""

    def __init__(self, optimizer, gradient_predivide_factor=1.0,
                 num_groups=0, process_set_id=0):
        self._optimizer = optimizer
        self._gradient_predivide_factor = gradient_predivide_factor
        self._num_groups = num_groups
        self._process_set_id = process_set_id

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _do_allreduce(self, index, grad):
        if _ps_size(self._process_set_id) == 1:
            return
        # Predivide splits the averaging around the wire to control fp16
        # range: Sum with prescale 1/f and postscale f/size nets to an
        # exact average for any f (reference passes the same pair).
        f = self._gradient_predivide_factor
        pre, post = 1.0 / f, f / _ps_size(self._process_set_id)
        if isinstance(index, (tuple, list)):
            if self._num_groups > 0:
                names = [f"gradient.{i}" for i in index]
                grouped_allreduce(grad, names=names, op=Sum,
                                  prescale_factor=pre, postscale_factor=post,
                                  process_set_id=self._process_set_id,
                                  inplace=True)
            else:
                for i, g in zip(index, grad):
                    allreduce_(g, name=f"gradient.{i}", op=Sum,
                               prescale_factor=pre, postscale_factor=post,
                               process_set_id=self._process_set_id)
        else:
            allreduce_(grad, name=f"gradient.{index}", op=Sum,
                       prescale_factor=pre, postscale_factor=post,
                       process_set_id=self._process_set_id)

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


class DistributedTrainer(mx.gluon.Trainer):
    """Gluon Trainer whose gradient aggregation is the shared eager
    allreduce (reference: horovod/mxnet DistributedTrainer: overrides
    ``_allreduce_grads``; scales lr by 1/size so the wrapped optimizer's
    rescale_grad stays correct under averaging)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 gradient_predivide_factor=1.0, process_set_id=0):
        if isinstance(optimizer, DistributedOptimizer):
            optimizer = optimizer._optimizer
        super().__init__(params, optimizer, optimizer_params, kvstore=None)
        self._hvd_process_set_id = process_set_id
        self._gradient_predivide_factor = gradient_predivide_factor
        # Trainer applies rescale_grad itself: fold the 1/size of the
        # average there, and run the wire collective as a pre/post-scaled
        # Sum (net scale 1) so any predivide factor cancels exactly.
        self._scale /= _ps_size(process_set_id)

    def _allreduce_grads(self):
        if _ps_size(self._hvd_process_set_id) == 1:
            return
        f = self._gradient_predivide_factor
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                for grad in param.list_grad():
                    allreduce_(grad, name=f"gradient.{i}.{param.name}",
                               op=Sum, prescale_factor=1.0 / f,
                               postscale_factor=f,
                               process_set_id=self._hvd_process_set_id)

# Capability surface (reference analog: hvd.mpi_built()/gloo_built()/...).
from horovod_tpu.mxnet.mpi_ops import (  # noqa: F401,E402
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    xla_built,
    xla_enabled,
)
