"""MXNet eager collective ops over the shared core.

Reference analog: ``horovod/mxnet/mpi_ops.py`` (+ its C extension
``mpi_ops.cc``). NDArrays bridge through numpy: enqueue copies out,
completion writes back in-place — same contract as the reference's
in-place ``allreduce_`` on NDArray.
"""



import mxnet as mx
import numpy as np

from horovod_tpu.common import eager_ops
from horovod_tpu.common.eager_ops import ReduceOp

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
Adasum = ReduceOp.ADASUM

_basics = eager_ops._basics

from horovod_tpu.common import elastic as _elastic_init_mod  # noqa: E402

init = _elastic_init_mod.init
shutdown = _basics.shutdown
is_initialized = _basics.is_initialized
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
cross_rank = _basics.cross_rank
cross_size = _basics.cross_size

for _cap in _basics.CAPABILITY_NAMES:
    globals()[_cap] = getattr(_basics, _cap)
start_timeline = _basics.start_timeline
stop_timeline = _basics.stop_timeline
join = eager_ops.join
barrier = eager_ops.barrier

from horovod_tpu.common.auto_name import make_auto_namer

_auto_name = make_auto_namer()


def _to_np(tensor):
    return tensor.asnumpy()


def _write_back(tensor, result):
    tensor[:] = mx.nd.array(result, ctx=tensor.context, dtype=result.dtype)


def allreduce(tensor, name=None, op=Average, prescale_factor=1.0,
              postscale_factor=1.0, process_set_id=0):
    h = eager_ops.allreduce_async(
        _to_np(tensor), name or _auto_name("allreduce"), op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set_id=process_set_id)
    out = h.synchronize()
    return mx.nd.array(out, ctx=tensor.context, dtype=out.dtype)


def allreduce_(tensor, name=None, op=Average, prescale_factor=1.0,
               postscale_factor=1.0, process_set_id=0):
    h = eager_ops.allreduce_async(
        _to_np(tensor), name or _auto_name("allreduce"), op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set_id=process_set_id)
    _write_back(tensor, h.synchronize())
    return tensor


def grouped_allreduce(tensors, names=None, op=Average, prescale_factor=1.0,
                      postscale_factor=1.0, process_set_id=0, inplace=False):
    if names is None:
        base = _auto_name("grouped_allreduce")
        names = [f"{base}.{i}" for i in range(len(tensors))]
    handles = eager_ops.grouped_allreduce_async(
        [_to_np(t) for t in tensors], names, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set_id=process_set_id)
    outs = [h.synchronize() for h in handles]
    if inplace:
        for t, o in zip(tensors, outs):
            _write_back(t, o)
        return tensors
    return [mx.nd.array(o, ctx=t.context, dtype=o.dtype)
            for t, o in zip(tensors, outs)]


def allgather(tensor, name=None, process_set_id=0):
    h = eager_ops.allgather_async(
        _to_np(tensor), name or _auto_name("allgather"),
        process_set_id=process_set_id)
    out = h.synchronize()
    return mx.nd.array(out, ctx=tensor.context, dtype=out.dtype)


def broadcast(tensor, root_rank, name=None, process_set_id=0):
    h = eager_ops.broadcast_async(
        _to_np(tensor), root_rank, name or _auto_name("broadcast"),
        process_set_id=process_set_id)
    out = h.synchronize()
    return mx.nd.array(out, ctx=tensor.context, dtype=out.dtype)


def broadcast_(tensor, root_rank, name=None, process_set_id=0):
    h = eager_ops.broadcast_async(
        _to_np(tensor), root_rank, name or _auto_name("broadcast"),
        process_set_id=process_set_id)
    _write_back(tensor, h.synchronize())
    return tensor


def alltoall(tensor, splits=None, name=None, process_set_id=0):
    arr = _to_np(tensor)
    if splits is None:
        n = size(process_set_id)
        if arr.shape[0] % n != 0:
            raise ValueError(
                "alltoall without splits needs dim0 divisible by size")
        splits_np = np.full(n, arr.shape[0] // n, np.int64)
    else:
        splits_np = np.asarray(
            splits.asnumpy() if isinstance(splits, mx.nd.NDArray) else splits,
            np.int64)
    h = eager_ops.alltoall_async(arr, splits_np,
                                 name or _auto_name("alltoall"),
                                 process_set_id=process_set_id)
    out = h.synchronize()
    return mx.nd.array(out, ctx=tensor.context, dtype=out.dtype)


def reducescatter(tensor, name=None, op=Average, process_set_id=0):
    h = eager_ops.reducescatter_async(
        _to_np(tensor), name or _auto_name("reducescatter"), op=op,
        process_set_id=process_set_id)
    out = h.synchronize()
    return mx.nd.array(out, ctx=tensor.context, dtype=out.dtype)
