from horovod_tpu.data.data_loader_base import (  # noqa: F401
    AsyncDataLoaderMixin,
    BaseDataLoader,
)
