"""Data-loader base + async prefetch mixin.

Reference analog: ``horovod/data/data_loader_base.py`` (BaseDataLoader,
AsyncDataLoaderMixin) — the helper the Spark/Ray estimator paths use to
overlap host-side input processing with device compute. On TPU the overlap
matters more, not less: the single host thread feeding an accelerator must
never stall the device, so the async mixin keeps a bounded queue of batches
ready ahead of the step loop (the pure-Python analog of double-buffered
infeed).
"""

import queue
import threading


class BaseDataLoader:
    """Iterable over training batches.

    Subclasses implement :meth:`_iterate`; users iterate the loader itself.
    """

    def __len__(self):
        raise NotImplementedError()

    def _iterate(self):
        """Yield batches for one epoch."""
        raise NotImplementedError()

    def __iter__(self):
        return iter(self._iterate())


class AsyncDataLoaderMixin:
    """Mix in BEFORE a BaseDataLoader subclass to prefetch on a thread.

    ``class AsyncDataLoader(AsyncDataLoaderMixin, MyLoader): ...``

    The producer thread runs ``super()._iterate()`` and feeds a bounded
    queue; the consumer (training loop) pops from it. ``async_loading=False``
    degrades to synchronous iteration. Call :meth:`close_async_loader` when
    finished (elastic reset does this between generations).
    """

    def __init__(self, async_loading=True, async_depth=2, *args, **kwargs):
        self.async_loading = async_loading
        self.async_depth = async_depth
        self._queue = None
        self._thread = None
        self._shutdown = None
        super().__init__(*args, **kwargs)

    def close_async_loader(self):
        """Stop the producer thread and drain the queue."""
        if self._thread is None:
            return
        self._shutdown.set()
        # Unblock a producer waiting on a full queue.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10)
        # A producer stuck >10s inside user I/O is left to die as a daemon;
        # it holds only this epoch's queue/event (captured below), so it can
        # never leak stale batches into a later epoch.
        self._thread = None
        self._queue = None
        self._shutdown = None

    def _produce(self, q, shutdown):
        # q/shutdown are THIS epoch's objects: a zombie from a timed-out
        # close cannot observe the next epoch's state.
        try:
            interrupted = False
            for batch in super()._iterate():
                if shutdown.is_set():
                    interrupted = True
                    break
                q.put((batch, None))
            if not interrupted:
                q.put((None, StopIteration()))
            else:
                # Best-effort sentinel after an early shutdown: a consumer
                # resumed post-close still terminates via its timed get
                # even if the queue was full here.
                try:
                    q.put_nowait((None, StopIteration()))
                except queue.Full:
                    pass
        except Exception as e:  # noqa: BLE001 — surface in the consumer
            q.put((None, e))

    def _iterate(self):
        if not self.async_loading:
            yield from super()._iterate()
            return
        self.close_async_loader()  # end any previous epoch first
        shutdown = threading.Event()
        q = queue.Queue(maxsize=self.async_depth)
        thread = threading.Thread(target=self._produce, args=(q, shutdown),
                                  daemon=True)
        self._shutdown, self._queue, self._thread = shutdown, q, thread
        thread.start()
        try:
            while True:
                try:
                    batch, err = q.get(timeout=0.1)
                except queue.Empty:
                    # Timed get (not a bare blocking get) so a consumer
                    # resumed after close_async_loader() terminates even if
                    # the producer's sentinel was drained by the close.
                    if shutdown.is_set():
                        return
                    continue
                if err is not None:
                    if isinstance(err, StopIteration):
                        return
                    raise err
                yield batch
        finally:
            # Close THIS epoch via locals: a late-GC'd abandoned generator
            # must not tear down a newer epoch's producer.
            shutdown.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            thread.join(timeout=10)
            if self._thread is thread:
                self._thread = self._queue = self._shutdown = None
