"""Checkpoint engine: async, sharded, TPU-idiomatic (orbax).

Reference analog: the reference has NO core checkpoint engine
(SURVEY.md §5.4) — it delegates to the frameworks: elastic ``State``
commits to host memory, Keras callbacks save on rank 0, Spark
estimators write to the ``Store``. This module is the TPU-idiomatic
engine those layers compose with: orbax handles sharded jax pytrees
(on multi-host meshes every process writes exactly its own shards) and
async save (training continues while the previous step flushes).

One-shot::

    from horovod_tpu import checkpoint as ckpt
    ckpt.save(path, {"params": params, "opt": opt_state})
    state = ckpt.restore(path, target=abstract_state)

Step-managed::

    mgr = ckpt.CheckpointManager(dir, max_to_keep=3)
    mgr.save(step, state)          # async; returns immediately
    state = mgr.restore(target=abstract_state)   # latest step
    mgr.wait(); mgr.close()

Rank policy: with a single jax process but multiple Horovod ranks
(host-ring data parallelism), only rank 0 writes — replicas hold
identical state, and concurrent writers to one directory would race.
With ``jax.distributed`` initialized (TPU pods / the xla_ici plane),
every process participates — orbax coordinates the multi-host write.
"""

import os

import jax

from horovod_tpu.common.basics import HorovodBasics

_basics = HorovodBasics()


def _i_write():
    """Whether this rank takes part in the write (see module docstring)."""
    if jax.process_count() > 1:
        return True
    if not _basics.is_initialized():
        return True  # standalone use outside a Horovod job
    return _basics.rank() == 0


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


_PICKLE_KEY = "__hvd_pickle__"


def encode_pytree(tree):
    """Replace leaves orbax can't store (strings, arbitrary objects)
    with pickled uint8 buffers, marked for :func:`decode_pytree`."""
    import pickle

    import numpy as np

    def enc(x):
        try:
            if np.asarray(x).dtype.kind in "biufc?":
                return x
        except Exception:  # noqa: BLE001 — not arrayable at all
            pass
        return {_PICKLE_KEY: np.frombuffer(pickle.dumps(x),
                                           np.uint8).copy()}

    return jax.tree.map(enc, tree)


def decode_pytree(tree):
    """Inverse of :func:`encode_pytree`."""
    import pickle

    import numpy as np

    def is_marker(x):
        return isinstance(x, dict) and set(x) == {_PICKLE_KEY}

    def dec(x):
        if is_marker(x):
            return pickle.loads(np.asarray(x[_PICKLE_KEY]).tobytes())
        return x

    return jax.tree.map(dec, tree, is_leaf=is_marker)


def _sanitize_scalars(state):
    """Orbax's StandardCheckpointHandler restricts leaves to
    ``(int, float, np.ndarray, jax.Array)`` on recent versions (0.7.x
    validates on save); numpy SCALARS (``np.int64(7)`` — the natural
    type of a step counter) fail that check. Promote them to 0-d
    ndarrays, which round-trip equivalently (``int(x)``/``float(x)``
    and arithmetic behave the same on restore)."""
    import numpy as np

    return jax.tree.map(
        lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
        state)


def save(path, state, force=True, sync=False):
    """Synchronous one-shot save of a pytree (jax arrays, numpy, scalars).

    ``force`` overwrites an existing checkpoint at ``path``. On the
    host-ring (single jax process, many Horovod ranks) only rank 0
    writes; non-writer ranks return IMMEDIATELY, so a rank that wants to
    restore right after must synchronize first — either pass
    ``sync=True`` (runs a Horovod barrier; then EVERY rank must call
    save, or the job hangs) or barrier explicitly.
    """
    if _i_write():
        ocp = _ocp()
        with ocp.StandardCheckpointer() as cp:
            cp.save(os.path.abspath(os.fspath(path)),
                    _sanitize_scalars(state), force=force)
    if sync and _basics.is_initialized() and _basics.size() > 1:
        from horovod_tpu.common import eager_ops

        eager_ops.barrier()


def restore(path, target=None):
    """Restore a pytree saved by :func:`save`.

    ``target`` (optional) is a pytree of like-structured arrays or
    ``jax.ShapeDtypeStruct`` with shardings — pass it to restore
    directly into a sharded layout on a mesh; without it, values come
    back as host arrays in the saved structure.
    """
    ocp = _ocp()
    with ocp.StandardCheckpointer() as cp:
        return cp.restore(os.path.abspath(os.fspath(path)), target)


class CheckpointManager:
    """Step-numbered checkpoints with retention and async save.

    Reference analog: the Keras ``ModelCheckpoint``-on-rank-0 pattern
    and Spark's Store, unified on one engine.
    """

    def __init__(self, directory, max_to_keep=3, async_save=True):
        self._dir = os.path.abspath(os.fspath(directory))
        self._mgr = None
        self._options = _ocp().CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save)
        self._ensure_role()

    def _ensure_role(self):
        """(Re-)evaluate whether this rank writes. Elastic re-rendezvous
        reassigns Horovod ranks, so writer status cannot be frozen at
        construction: a departed rank 0 must hand the manager to the new
        rank 0, and a demoted one must stop writing."""
        writer = _i_write()
        if writer and self._mgr is None:
            self._mgr = _ocp().CheckpointManager(self._dir,
                                                 options=self._options)
        elif not writer and self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
            self._mgr = None
        return self._mgr

    def save(self, step, state, wait=False):
        """Queue an async save of ``state`` under ``step``. ``wait``
        blocks until it is durable (otherwise the next save or
        :meth:`wait` joins it). Returns False on non-writer ranks and
        when orbax skips the step (already on disk)."""
        if self._ensure_role() is None:
            return False
        ocp = _ocp()
        saved = self._mgr.save(
            int(step), args=ocp.args.StandardSave(_sanitize_scalars(state)))
        if wait:
            self._mgr.wait_until_finished()
        return saved

    def latest_step(self):
        if self._mgr is None:
            # Non-writer ranks can still read the directory.
            ocp = _ocp()
            with ocp.CheckpointManager(self._dir) as mgr:
                return mgr.latest_step()
        return self._mgr.latest_step()

    def restore(self, step=None, target=None):
        """Restore ``step`` (default: latest). See :func:`restore` for
        ``target``. Every rank may call this."""
        ocp = _ocp()
        mgr = self._mgr
        own = False
        if mgr is None:
            mgr = ocp.CheckpointManager(self._dir)
            own = True
        try:
            if step is None:
                step = mgr.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self._dir}")
            # args ALWAYS passed (StandardRestore(None) = saved
            # structure): a bare mgr.restore(step) only works when the
            # SAME manager object did the save — a fresh manager (the
            # resume-after-restart path) has no handler registered for
            # the item and orbax >= 0.7 raises KeyError asking for a
            # CheckpointArgs subclass.
            return mgr.restore(
                int(step), args=ocp.args.StandardRestore(target))
        finally:
            if own:
                mgr.close()

    def wait(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def close(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
            self._mgr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
