"""Small MLP classifier — the MNIST end-to-end-slice model.

Reference analog: examples/pytorch_mnist.py's Net (the reference's
minimum end-to-end demo); functional jax instead of nn.Module.
"""

import jax
import jax.numpy as jnp


def mlp_init(key, sizes=(784, 128, 64, 10)):
    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, fan_in, fan_out in zip(keys, sizes[:-1], sizes[1:]):
        params.append({
            "w": jax.random.normal(k, (fan_in, fan_out), jnp.float32)
            * (fan_in ** -0.5),
            "b": jnp.zeros(fan_out),
        })
    return params


def mlp_forward(params, x):
    for layer in params[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    last = params[-1]
    return x @ last["w"] + last["b"]
