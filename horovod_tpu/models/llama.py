"""Llama-family decoder-only transformer, TPU-first.

Design choices (vs a torch translation):
- functional: params are a plain pytree; init/forward are pure functions
  compatible with jit/grad/shard_map.
- scan-over-layers: per-layer params are stacked on a leading axis and the
  decoder body is one ``lax.scan`` — O(1) XLA program size in depth, the
  standard TPU idiom (compile time does not grow with n_layers).
- remat: each scanned layer is wrapped in ``jax.checkpoint`` so activations
  are recomputed in backward — HBM for FLOPs, the right TPU trade.
- bfloat16 compute; params stored in ``param_dtype`` (float32 default
  for stability, bfloat16 for the pure-bf16 large-model recipe — the
  HBM ceiling on a single chip); logits-softmax always float32.
- attention dispatches to exact ring attention when the mesh has a
  non-trivial ``seq`` axis (long-context sequence parallelism), else to
  single-device flash-style blockwise attention.
- sharding by PartitionSpec rules (megatron TP + FSDP), applied by the
  caller via ``llama_partition_rules``; XLA/GSPMD inserts the collectives.
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.ring_attention import ring_self_attention

# Extra residual names the "moe" remat mode saves beyond "attn+moe".
_MOE_EXTRA_SAVE = ("moe_x_sorted", "moe_gate_act", "moe_up_act")


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    # Rematerialization: True/"full" recomputes the whole layer in
    # backward (min HBM, ~1/3 extra FLOPs); "attn" saves only the flash
    # kernel's residuals; "attn+gate" also saves the pre-silu FFN gate
    # (skips one matmul re-run per layer — best measured MFU at bench
    # shapes); "attn+ffn" saves both up-projections (more HBM); "dots"
    # saves every matmul output and recomputes only elementwise work;
    # False/"none" saves everything.
    remat: "bool | str" = True
    # Sparse mixture-of-experts (mixtral-style): n_experts == 0 keeps the
    # dense FFN; otherwise every layer's FFN becomes top-k-routed experts
    # sharded over the mesh's "expert" axis.
    n_experts: int = 0
    n_experts_per_token: int = 2
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Expert dispatch implementation: "grouped" = dropless sorted
    # grouped-GEMM (megablox; no capacity padding, no one-hot dispatch
    # einsums, no dropped tokens — fastest on a single program),
    # "gshard" = capacity-factor one-hot einsum dispatch (the [G,E,C,D]
    # buffers give GSPMD its expert-parallel all-to-all seam), "auto" =
    # grouped when no mesh is active, gshard under a mesh.
    moe_impl: str = "auto"
    # GPipe microbatch count when the mesh has a non-trivial "pipe" axis
    # (0 = one microbatch per stage). Batch must divide by it.
    pipeline_microbatches: int = 0
    # Pipeline schedule for TRAINING: "gpipe" (all forwards, then AD's
    # reversed backward — per-stage activation stash grows with M),
    # "1f1b" (lockstep forward/backward slots, loss fused into the last
    # stage, stash bounded by ~2S microbatch inputs — see
    # parallel.pipeline.one_f_one_b), or "interleaved_1f1b" (each
    # device holds pipeline_virtual_stages NON-contiguous layer chunks;
    # single-subtick slots cut the bubble to 2(S-1)/(2MV + 2(S-1)),
    # ~V-fold below 1f1b — parallel.pipeline.interleaved_one_f_one_b).
    # Forward-only calls (llama_forward) always use gpipe: the fused
    # schedules never materialize logits. Value-only llama_loss calls
    # (eval loops, loss logging without grad) also run the gpipe
    # forward + loss head under both 1F1B variants — their combined
    # forward/backward computes every gradient just to discard them
    # (~3x the needed work), so only jax.grad/value_and_grad engages
    # them.
    pipeline_schedule: str = "gpipe"
    # Virtual chunks per device for "interleaved_1f1b" (Megatron's
    # virtual pipeline size). n_layers must divide by
    # pipe_size * pipeline_virtual_stages; 1 = the true non-interleaved
    # 1F1B through the same single-subtick engine.
    pipeline_virtual_stages: int = 1
    # Sequence-parallel strategy when the mesh's "seq" axis is
    # non-trivial: "ring" (K/V rotate via ppermute — any head count) or
    # "ulysses" (all-to-all head/sequence reshard — needs
    # n_heads % seq_size == 0, cheaper at short per-device sequences).
    seq_parallel: str = "ring"
    # Unroll factor for the scan-over-layers (1 = rolled, n_layers =
    # fully unrolled). Unrolling turns the stacked-weight dynamic
    # slices into static ones — on TPU that halves the per-layer weight
    # copies feeding grouped-GEMM custom-calls (measured -5% MoE step
    # time at bench shape) at the price of compile time and program
    # size. Leave 1 for multi-chip pipeline meshes.
    scan_unroll: int = 1
    # Pallas flash-attention block size (both the q and k grid blocks;
    # 0 = the kernel default, 1024 — the measured optimum of
    # {256,512,1024,2048}² at t2048, docs/benchmarks.md r4). Exposed so
    # bench.py --sweep can re-sweep the attention block shapes when the
    # geometry moves; ring/ulysses SP paths keep their own defaults.
    flash_block: int = 0
    # Parameter STORAGE dtype ("float32" default). "bfloat16" halves
    # parameter/gradient/optimizer-state HBM (pure-bf16 training, the
    # usual large-model recipe on TPU) — on one 16G chip it is what
    # lets >1B-param configs fit; use fp32 when running few-hundred-M
    # models where master-precision weights are free.
    param_dtype: str = "float32"

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def llama3_8b():
        return LlamaConfig(vocab_size=128256, d_model=4096, n_layers=32,
                           n_heads=32, n_kv_heads=8, d_ff=14336)

    @staticmethod
    def mixtral_8x7b():
        return LlamaConfig(vocab_size=32000, d_model=4096, n_layers=32,
                           n_heads=32, n_kv_heads=8, d_ff=14336,
                           n_experts=8, n_experts_per_token=2)

    @staticmethod
    def tiny(**kw):
        """Test/dryrun config: full architecture, toy sizes."""
        defaults = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, d_ff=128, rope_theta=10000.0)
        defaults.update(kw)
        return LlamaConfig(**defaults)

    @staticmethod
    def tiny_moe(**kw):
        """Tiny sparse-MoE variant (expert-parallel test/dryrun config)."""
        kw.setdefault("n_experts", 4)
        return LlamaConfig.tiny(**kw)


def llama_init(config, key):
    """Initialize the parameter pytree (stored in config.param_dtype;
    float32 by default — "master weights" — or bfloat16 for the
    pure-bf16 large-model recipe).

    Per-layer tensors are stacked on a leading n_layers axis for scan.
    """
    c = config
    hd = c.head_dim
    k = iter(jax.random.split(key, 16))
    pd = jnp.dtype(c.param_dtype)

    def dense(key, shape, fan_in):
        # Cast per-leaf at creation: a post-hoc whole-tree cast would
        # transiently hold fp32 AND target trees (~1.5x init peak, which
        # matters for >1B params on a 16G chip).
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(pd)

    L = c.n_layers
    layers = {
        "attn_norm": jnp.ones((L, c.d_model), pd),
        "wq": dense(next(k), (L, c.d_model, c.n_heads * hd), c.d_model),
        "wk": dense(next(k), (L, c.d_model, c.n_kv_heads * hd),
                    c.d_model),
        "wv": dense(next(k), (L, c.d_model, c.n_kv_heads * hd),
                    c.d_model),
        "wo": dense(next(k), (L, c.n_heads * hd, c.d_model),
                    c.n_heads * hd),
        "mlp_norm": jnp.ones((L, c.d_model), pd),
    }
    if c.n_experts > 0:
        E = c.n_experts
        layers.update({
            "router": dense(next(k), (L, c.d_model, E), c.d_model),
            "moe_gate": dense(next(k), (L, E, c.d_model, c.d_ff),
                              c.d_model),
            "moe_up": dense(next(k), (L, E, c.d_model, c.d_ff), c.d_model),
            "moe_down": dense(next(k), (L, E, c.d_ff, c.d_model), c.d_ff),
        })
    else:
        layers.update({
            "w_gate": dense(next(k), (L, c.d_model, c.d_ff), c.d_model),
            "w_up": dense(next(k), (L, c.d_model, c.d_ff), c.d_model),
            "w_down": dense(next(k), (L, c.d_ff, c.d_model), c.d_ff),
        })
    params = {
        "embed": (jax.random.normal(next(k), (c.vocab_size, c.d_model),
                                    jnp.float32) * 0.02).astype(pd),
        "layers": layers,
        "final_norm": jnp.ones(c.d_model, pd),
        "lm_head": dense(next(k), (c.d_model, c.vocab_size), c.d_model),
    }
    return params


def llama_partition_rules(pipeline=False):
    """Megatron TP + FSDP sharding rules for the param pytree.

    Layer-stacked tensors have a leading layer axis — unsharded by
    default, split over the "pipe" mesh axis when ``pipeline`` is set
    (contiguous layer blocks = GPipe stages; see parallel.pipeline). The
    ``tensor`` axis splits heads / ffn; ``fsdp`` shards the other matmul
    dimension ZeRO-3 style. Pass to parallel.shard_params.
    """
    lead = "pipe" if pipeline else None
    return [
        (r"embed", P(("tensor", "fsdp"), None)),
        (r"layers/.*norm", P(lead, None)),
        (r"layers/w[qkv]$", P(lead, "fsdp", "tensor")),
        (r"layers/wo", P(lead, "tensor", "fsdp")),
        (r"layers/w_(gate|up)", P(lead, "fsdp", "tensor")),
        (r"layers/w_down", P(lead, "tensor", "fsdp")),
        # MoE: experts shard over the "expert" mesh axis (EP); within an
        # expert the FFN shards like the dense MLP. The router is tiny and
        # stays replicated.
        (r"layers/router", P(lead, None, None)),
        (r"layers/moe_(gate|up)", P(lead, "expert", "fsdp", "tensor")),
        (r"layers/moe_down", P(lead, "expert", "tensor", "fsdp")),
        (r"final_norm", P(None)),
        (r"lm_head", P("fsdp", "tensor")),
    ]


def _rmsnorm(x, scale, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale


def _rope(x, positions, theta):
    """Rotary embedding; positions are GLOBAL indices [B, T] so sequence
    sharding stays correct."""
    b, t, h, d = x.shape
    freqs = theta ** (-jnp.arange(0, d // 2, dtype=jnp.float32) / (d // 2))
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # [B,T,d/2]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _attention(q, k, v, mesh, seq_axis, seq_parallel="ring",
               flash_block=0):
    # remat="attn" naming: the SP paths name their OUTPUT ("attn_out");
    # the flash path names its custom-VJP residuals internally
    # (flash_o/flash_lse) instead — naming the transposed output TOO
    # would save a ~671 MB duplicate of flash_o at bench shapes (the
    # transpose is a distinct buffer) for no backward work saved.
    if mesh is not None and seq_axis and mesh.shape.get(seq_axis, 1) > 1:
        if seq_parallel == "ulysses":
            from horovod_tpu.parallel.ulysses import ulysses_self_attention

            return checkpoint_name(
                ulysses_self_attention(q, k, v, mesh, causal=True,
                                       batch_axis=("data", "fsdp"),
                                       seq_axis=seq_axis), "attn_out")
        if seq_parallel not in ("ring", None):
            raise ValueError(f"unknown seq_parallel {seq_parallel!r}: "
                             "expected 'ring' or 'ulysses'")
        return checkpoint_name(
            ring_self_attention(q, k, v, mesh, causal=True,
                                batch_axis=("data", "fsdp"),
                                seq_axis=seq_axis), "attn_out")
    # Pallas flash kernel on TPU (no T^2 score materialization, so the
    # layer no longer needs full remat for memory). flash_attention
    # owns the remat naming for both of its paths: the pallas kernels
    # name their VJP residuals (flash_o/flash_lse), the off-TPU
    # fallback names its output attn_out.
    from horovod_tpu.ops import flash_attention

    if flash_block:
        return flash_attention(q, k, v, causal=True,
                               block_q=flash_block, block_k=flash_block)
    return flash_attention(q, k, v, causal=True)


def _activation_spec(mesh):
    """[B, T, D] activations: batch over data+fsdp, seq over seq axis."""
    return P(("data", "fsdp"), "seq", None)


def moe_route(h, router_w, n_experts_per_token):
    """The ONE router: f32 logits matmul, softmax, top-K, epsilon-
    guarded gate normalization, and the Switch load-balancing aux loss
    (E * <fraction top-1 routed to e> . <mean prob of e>, minimized =1
    at uniform routing). Shared by the GShard dispatch below, the
    dropless grouped dispatch (ops/grouped_moe.py), and cached decode
    (models/generate.py) so the three can never drift.

    ``h`` is [..., D] with any leading shape; returns
    (gate_vals [..., K] f32-normalized, gate_idx [..., K] int32, aux).
    """
    E = router_w.shape[-1]
    logits = h.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                # [..., E]
    gate_vals, gate_idx = lax.top_k(probs, n_experts_per_token)
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)
    lead = tuple(range(probs.ndim - 1))
    top1 = jax.nn.one_hot(gate_idx[..., 0], E, dtype=jnp.float32)
    aux = E * jnp.sum(top1.mean(lead) * probs.mean(lead))
    return gate_vals, gate_idx, aux


def _moe_ffn(h, lp, c, mesh):
    """Top-k routed expert FFN, GShard-style grouped einsum dispatch.

    Static shapes throughout (XLA requirement): each batch row is a
    dispatch GROUP (GShard's group axis — without it the one-hot
    dispatch tensors are O(S²) in the token count); within a group,
    tokens scatter into per-expert buffers of fixed capacity C via
    one-hot tensors, and over-capacity tokens fall through on the
    residual (combine weight zero). Groups ride the batch sharding
    (data/fsdp); the [G, E, C, D] expert buffers get an "expert" axis
    constraint so GSPMD inserts the token all-to-alls — the TPU analog
    of expert-parallel dispatch. Reference analog: none (Horovod has no
    MoE); design follows the GShard/Switch public formulation.
    Returns (out [B,T,D], aux loss).
    """
    B, T, D = h.shape
    E, K = c.n_experts, c.n_experts_per_token
    C = max(int(T * K * c.capacity_factor / E), 1)

    gate_vals, gate_idx, aux = moe_route(h, lp["router"], K)  # [B,T,K]

    # Position of each (token, slot) in its expert's per-group capacity
    # buffer, filling slot 0 for every token before slot 1 (priority to
    # the top-1 expert, as in GShard).
    dt = c.compute_dtype
    dispatch = jnp.zeros((B, T, E, C), dt)
    combine = jnp.zeros((B, T, E, C), dt)
    counts = jnp.zeros((B, E), jnp.int32)
    for slot in range(K):
        oh = jax.nn.one_hot(gate_idx[..., slot], E,
                            dtype=jnp.int32)                    # [B,T,E]
        pos = jnp.cumsum(oh, axis=1) - 1 + counts[:, None, :]   # [B,T,E]
        keep = (pos < C) & (oh > 0)
        pos_oh = jax.nn.one_hot(pos, C, dtype=dt) \
            * keep[..., None].astype(dt)                        # [B,T,E,C]
        dispatch = dispatch + pos_oh
        combine = combine + pos_oh * gate_vals[..., slot].astype(
            dt)[..., None, None]
        counts = counts + oh.sum(1)

    def constrain_e(z):
        if mesh is None:
            return z
        return lax.with_sharding_constraint(
            z, jax.sharding.NamedSharding(
                mesh, P(("data", "fsdp"), "expert", None, None)))

    # Named for remat="attn+gate" (the FFN-residual mode): the one-hot
    # cumsum routing chain above is bandwidth-bound vector work over
    # [B,T,E,C] tensors — saving its two products keeps backward from
    # re-running it (the MoE analog of the dense mode's saved gate).
    dispatch = checkpoint_name(dispatch, "moe_dispatch")
    combine = checkpoint_name(combine, "moe_combine")

    xe = constrain_e(jnp.einsum("btec,btd->becd", dispatch,
                                h.astype(dt)))                # [B,E,C,D]
    gate = jax.nn.silu(jnp.einsum("becd,edf->becf", xe,
                                  lp["moe_gate"].astype(dt)))
    up = jnp.einsum("becd,edf->becf", xe, lp["moe_up"].astype(dt))
    ye = constrain_e(jnp.einsum("becf,efd->becd", gate * up,
                                lp["moe_down"].astype(dt)))
    y = jnp.einsum("btec,becd->btd", combine, ye)             # [B,T,D]
    return y, aux


def _ffn(h, lp, c, mesh=None):
    """One layer's FFN on normalized activations: dense siglu MLP, or
    top-k expert routing for MoE configs. Returns (y, aux_loss).
    Shared by llama_forward and the cached decode path (generate.py) so
    the two can never diverge."""
    dt = c.compute_dtype
    if c.n_experts > 0:
        if c.moe_impl == "grouped" or (c.moe_impl == "auto"
                                       and mesh is None):
            from horovod_tpu.ops.grouped_moe import grouped_moe_ffn

            return grouped_moe_ffn(h, lp, c)
        if c.moe_impl not in ("auto", "gshard"):
            raise ValueError(f"unknown moe_impl {c.moe_impl!r}: "
                             "expected 'auto', 'grouped', or 'gshard'")
        return _moe_ffn(h, lp, c, mesh)
    # Named for remat="attn+ffn": saving the two up-projections (the
    # bulk of a layer's recomputed matmul FLOPs) lets backward rebuild
    # silu(gate)*up elementwise instead of re-running both matmuls.
    # The PRE-silu value is what must be saved — silu's own vjp needs
    # its primal input, so saving post-silu would still re-run the
    # matmul to regenerate it.
    gate_pre = checkpoint_name(h @ lp["w_gate"].astype(dt), "ffn_gate")
    up = checkpoint_name(h @ lp["w_up"].astype(dt), "ffn_up")
    return ((jax.nn.silu(gate_pre) * up) @ lp["w_down"].astype(dt),
            jnp.zeros((), jnp.float32))


def llama_forward(params, tokens, config, mesh=None, seq_axis="seq",
                  return_aux=False):
    """tokens [B, T] int32 -> logits [B, T, vocab] (float32).

    Under jit with a mesh, activations get sharding constraints so GSPMD
    lays out batch over data/fsdp and sequence over seq; the attention op
    switches to ring attention when seq parallelism is active. With
    ``return_aux`` the MoE load-balancing loss (mean over layers; 0 for
    dense configs) is returned alongside the logits.
    """
    c = config
    dt = c.compute_dtype
    b, t = tokens.shape

    def constrain(x):
        return _constrain(x, mesh)

    # Layout contract for the vocab lookup: tokens are pinned to the
    # activation layout (batch over data/fsdp, seq over seq) so the SPMD
    # partitioner picks INDEX-passthrough for the gather — each device
    # all-gathers the (small) table shard and gathers its own token
    # block, and the output is born in the activation layout. Without the
    # pin it picks operand-passthrough (output sharded over the table's d
    # axis) and then "involuntary full rematerialization" to reshard
    # [B,T,D] into the batch/seq layout.
    if mesh is not None:
        tokens = lax.with_sharding_constraint(
            tokens, jax.sharding.NamedSharding(mesh, P(("data", "fsdp"),
                                                       "seq")))
    x = params["embed"].astype(dt)[tokens]
    x = constrain(x)

    body = _build_layer_body(c, mesh, seq_axis)

    n_stages = mesh.shape.get("pipe", 1) if mesh is not None else 1
    if n_stages > 1:
        # GPipe over the "pipe" axis: each stage scans its contiguous
        # layer block; microbatches rotate stage-to-stage via ppermute
        # (parallel.pipeline.gpipe). llama_forward always uses gpipe —
        # it must produce LOGITS, which the 1F1B schedule (loss fused
        # into the last stage; see llama_loss) never materializes.
        from horovod_tpu.parallel.pipeline import gpipe

        M = _validate_pipeline(c, b, mesh, seq_axis, n_stages)
        xs = x.reshape(M, b // M, t, x.shape[-1])
        ys, aux_total = gpipe(_stage_scan(body), params["layers"], xs,
                              mesh)
        x = ys.reshape(b, t, x.shape[-1])
        aux = aux_total / (c.n_layers * M)
    else:
        x, aux_per_layer = lax.scan(body, x, params["layers"],
                                    unroll=c.scan_unroll)
        aux = jnp.mean(aux_per_layer)

    x = _rmsnorm(x, params["final_norm"].astype(dt), c.norm_eps)
    # bf16 operands, f32 accumulation: full MXU rate without giving up
    # the f32 logits downstream softmax stability needs.
    logits = jnp.matmul(x, params["lm_head"].astype(dt),
                        preferred_element_type=jnp.float32)
    if return_aux:
        return logits, aux
    return logits


def _constrain(x, mesh):
    if mesh is None:
        return x
    return lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, _activation_spec(mesh)))


def _stage_scan(body):
    """One pipeline stage = a scan of ``body`` over its layer block
    (shared by the gpipe and 1f1b paths)."""
    def stage_fn(lp_stage, x_mb):
        x_out, aux_layers = lax.scan(body, x_mb, lp_stage)
        return x_out, jnp.sum(aux_layers)
    return stage_fn


def _validate_pipeline(c, b, mesh, seq_axis, n_stages):
    """Shared gpipe/1f1b precondition checks; returns the microbatch
    count M. seq parallelism is mutually exclusive with pipelining in
    this layout (ring attention's own shard_map cannot nest inside the
    pipeline's)."""
    M = c.pipeline_microbatches or n_stages
    if seq_axis and mesh.shape.get(seq_axis, 1) > 1:
        raise ValueError("pipeline (pipe>1) and sequence parallelism "
                         "(seq>1) cannot combine: ring attention's "
                         "shard_map cannot nest inside the pipeline's")
    if M <= 0 or b % M:
        raise ValueError(f"batch {b} must divide into "
                         f"{M} pipeline microbatches")
    V = c.pipeline_virtual_stages
    if V < 1:
        raise ValueError(f"pipeline_virtual_stages must be >= 1, got {V}")
    if V > 1 and c.pipeline_schedule != "interleaved_1f1b":
        raise ValueError(
            f"pipeline_virtual_stages={V} requires "
            f"pipeline_schedule='interleaved_1f1b' "
            f"(got {c.pipeline_schedule!r})")
    chunks = n_stages * (V if c.pipeline_schedule == "interleaved_1f1b"
                         else 1)
    if c.n_layers % chunks:
        raise ValueError(f"n_layers {c.n_layers} must divide into "
                         f"{chunks} pipeline stage chunks "
                         f"({n_stages} stages x {V} virtual)")
    return M


def _build_layer_body(c, mesh, seq_axis, constrain_acts=True):
    """One decoder layer as a scan body, wrapped in the configured
    remat policy — shared by llama_forward (single-device and gpipe)
    and the 1F1B training path. ``constrain_acts=False`` drops the
    per-activation sharding constraints (the 1F1B path differentiates
    INSIDE the pipe-manual shard_map, and XLA CPU aborts transposing
    with_sharding_constraint on auto axes there; GSPMD still lays out
    activations by propagation from the sharded params)."""
    dt = c.compute_dtype

    def constrain(x):
        return _constrain(x, mesh) if constrain_acts else x

    def layer(x, lp):
        # Shapes from x, not the enclosing scope: under pipelining the
        # layer sees microbatches smaller than the full batch.
        bb, tt = x.shape[0], x.shape[1]
        positions = jnp.broadcast_to(jnp.arange(tt), (bb, tt))
        h = _rmsnorm(x, lp["attn_norm"].astype(dt), c.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(bb, tt, c.n_heads, c.head_dim)
        kk = (h @ lp["wk"].astype(dt)).reshape(bb, tt, c.n_kv_heads,
                                               c.head_dim)
        vv = (h @ lp["wv"].astype(dt)).reshape(bb, tt, c.n_kv_heads,
                                               c.head_dim)
        # Named for remat="attn+gate+qkv": saving the POST-rope q/k and
        # v ([B,T,H(kv),D] bf16 — ~67 MB/layer at bench shapes) lets
        # backward skip the wq/wk/wv matmul + rope re-runs entirely
        # (attn_out/flash_o already cover wo's operands).
        q = checkpoint_name(_rope(q, positions, c.rope_theta), "rope_q")
        kk = checkpoint_name(_rope(kk, positions, c.rope_theta),
                             "rope_k")
        vv = checkpoint_name(vv, "attn_v")
        # remat="attn" save-names applied inside _attention (per path).
        attn = _attention(q, kk, vv, mesh, seq_axis, c.seq_parallel,
                          c.flash_block)
        x = x + constrain(attn.reshape(bb, tt, -1) @ lp["wo"].astype(dt))

        h = _rmsnorm(x, lp["mlp_norm"].astype(dt), c.norm_eps)
        ff, aux = _ffn(h, lp, c, mesh)
        x = x + constrain(ff)
        return x, aux

    body = layer
    if c.remat == "dots":
        body = jax.checkpoint(
            layer, policy=jax.checkpoint_policies.dots_saveable)
    elif c.remat == "attn":
        # Full remat except the attention output and the flash kernel's
        # residuals (o + logsumexp — one [B,T,H*D] bf16 and one
        # [B,H,T,1] f32 per layer): saving flash_lse is what actually
        # stops backward from re-running the flash forward — the
        # custom-vjp residuals are distinct from the outer attn_out
        # var, so naming only attn_out still recomputed the kernel
        # (profiled r3: ~12% of the step).
        body = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out", "flash_o", "flash_lse"))
    elif c.remat in ("attn+moe", "moe") and not (
            c.n_experts > 0
            and (c.moe_impl == "grouped"
                 or (c.moe_impl == "auto" and mesh is None))):
        # These modes save residuals only grouped_moe_ffn emits; under
        # GShard dispatch (mesh present or moe_impl="gshard") or a
        # dense config they would silently degrade to plain "attn".
        raise ValueError(
            f"remat={c.remat!r} requires the grouped MoE dispatch "
            "(n_experts > 0 and moe_impl='grouped', or 'auto' with no "
            "mesh); use remat='attn' or 'attn+gate' here")
    elif c.remat == "attn+moe":
        # "attn" plus the grouped-MoE y_slots residual ([S*K, D] bf16
        # per layer): the router's combine-weight gradient consumes
        # y_slots, so without it the backward remat must re-run the
        # down-projection grouped GEMM per layer.
        body = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out", "flash_o", "flash_lse", "moe_y_slots"))
    elif c.remat == "moe":
        # Save the whole grouped-expert chain (x_sorted, pre-silu gate,
        # up, y_slots — ~[S*K, 2F+2D] bf16 per layer): backward re-runs
        # NO grouped matmul. The HBM price usually needs microbatched
        # steps (gradient accumulation) at bench sizes; see
        # benchmarks/moe_bench.py.
        body = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out", "flash_o", "flash_lse", "moe_y_slots",
                *_MOE_EXTRA_SAVE))
    elif c.remat == "attn+gate+qkv":
        # "attn+gate" plus the post-rope q/k/v: backward re-runs only
        # the rmsnorms and elementwise chains — no qkv matmuls, no
        # rope, no FFN gate matmul. The extra ~[B,T,2D] bf16 per layer
        # is the cheapest matmul-recompute elimination left after
        # attn+gate — FOR SHAPES WITH HBM HEADROOM: at the 16G-chip
        # flagship bench shape it exceeds HBM (r5: the AOT compile
        # helper crashes rather than reporting a clean OOM), so the
        # mode is pinned by the CPU remat-equivalence test but has no
        # on-chip flagship measurement.
        body = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out", "flash_o", "flash_lse", "ffn_gate",
                "moe_dispatch", "moe_combine", "rope_q", "rope_k",
                "attn_v"))
    elif c.remat in ("attn+ffn", "attn+gate"):
        # "attn" plus FFN up-projection residuals (pre-silu gate, and
        # for "attn+ffn" also up — [B,T,d_ff] each per layer): trades
        # d·d_ff matmul re-runs per layer for HBM — the largest
        # recompute term after attention. Measured on one v5e chip the
        # HBM price exceeds the win (the batch must shrink to fit, see
        # docs/benchmarks.md r4 notes); the modes exist for multi-chip
        # FSDP runs where per-chip activation memory is the constraint
        # that actually relaxes.
        names = ["attn_out", "flash_o", "flash_lse", "ffn_gate",
                 "moe_dispatch", "moe_combine"]
        if c.remat == "attn+ffn":
            names.append("ffn_up")
        body = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.save_only_these_names(*names))
    elif c.remat in (False, "none"):
        pass
    elif c.remat in (True, "full"):
        body = jax.checkpoint(layer)
    else:
        raise ValueError(f"unknown remat mode {c.remat!r}: expected "
                         "True/'full', 'dots', 'attn', 'attn+gate', "
                         "'attn+gate+qkv', 'attn+ffn', 'attn+moe', "
                         "'moe', or False/'none'")

    return body


def llama_loss(params, batch, config, mesh=None, seq_axis="seq"):
    """Causal LM loss (+ weighted MoE aux loss for expert configs).
    batch = {"tokens": [B,T], "targets": [B,T], "mask": [B,T] or absent}.

    With an active "pipe" mesh axis and ``pipeline_schedule="1f1b"``
    the loss runs through the interleaved 1F1B schedule (loss fused
    into the last stage, O(S) activation stash — see
    parallel.pipeline.one_f_one_b) instead of gpipe + a global logits
    pass; values and gradients are pinned equal by
    tests/single/test_pipeline_1f1b.py.
    """
    n_stages = mesh.shape.get("pipe", 1) if mesh is not None else 1
    if n_stages > 1 and config.pipeline_schedule in ("1f1b",
                                                     "interleaved_1f1b"):
        return _llama_loss_1f1b(params, batch, config, mesh, seq_axis,
                                n_stages)
    if config.pipeline_schedule not in ("gpipe", "1f1b",
                                        "interleaved_1f1b"):
        raise ValueError(
            f"unknown pipeline_schedule {config.pipeline_schedule!r}: "
            "expected 'gpipe', '1f1b', or 'interleaved_1f1b'")
    logits, aux = llama_forward(params, batch["tokens"], config, mesh,
                                seq_axis, return_aux=True)
    nll = _token_nll(logits, batch["targets"])
    mask = batch.get("mask")
    if mask is None:
        loss = jnp.mean(nll)
    else:
        mask = mask.astype(jnp.float32)
        loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if config.n_experts > 0:
        loss = loss + config.moe_aux_weight * aux
    return loss


def _token_nll(logits, targets):
    """Per-token negative log-likelihood in logsumexp form: no second
    [B,T,vocab] f32 array for log_softmax — at bench shapes that array
    alone is GBs of HBM. The ONE cross-entropy used by llama_loss and
    the 1F1B last-stage loss head."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None],
                                 axis=-1)[..., 0]
    return lse - picked


def llama_pipeline_programs(config, mesh=None, seq_axis="seq", *,
                            microbatches=1, denom=1.0):
    """Build ``(stage_fn, loss_fn, aux_cotangent)`` — the exact per-
    stage program and last-stage loss head the 1F1B pipeline engines
    run (also the gpipe stage body via the same ``_stage_scan``).

    This is the program-builder hook hvdlint traces: combined with
    ``parallel.pipeline.build_pipeline_inner`` it reconstructs the real
    per-device pipeline program for static analysis (C5 schedule
    conformance — see ``horovod_tpu/analysis/``) without needing a
    mesh, devices, or shard_map. ``denom`` is the global mask-token
    denominator folded into each microbatch's loss numerator (a traced
    value inside the real step; any static float for lint purposes).
    Used by :func:`_llama_loss_1f1b` itself so the two can never drift.
    """
    c = config
    dt = c.compute_dtype
    stage_fn = _stage_scan(
        _build_layer_body(c, mesh, seq_axis, constrain_acts=False))

    def loss_fn(hp, y_mb, la):
        final_norm, lm_head = hp
        tgt, m = la
        h = _rmsnorm(y_mb, final_norm.astype(dt), c.norm_eps)
        logits = jnp.matmul(h, lm_head.astype(dt),
                            preferred_element_type=jnp.float32)
        return jnp.sum(_token_nll(logits, tgt) * m) / denom

    aux_ct = (c.moe_aux_weight / (c.n_layers * microbatches)
              if c.n_experts > 0 else 0.0)
    return stage_fn, loss_fn, aux_ct


def _llama_loss_1f1b(params, batch, c, mesh, seq_axis, n_stages):
    """Training loss through a fused-backward pipeline schedule —
    lockstep "1f1b" or the virtual-stage "interleaved_1f1b".

    The schedule computes loss AND gradients in one combined scan
    (parallel.pipeline.one_f_one_b / interleaved_one_f_one_b); a
    ``custom_vjp`` hands those gradients to the outer
    ``jax.value_and_grad`` so callers keep the ordinary llama_loss
    contract. The MoE aux objective is folded into the schedule's
    backward via its constant per-contribution cotangent
    (moe_aux_weight / (n_layers * M)) — identical math to the gpipe
    path's ``loss + w * mean(aux)``. For the interleaved schedule the
    stacked layer axis is split into ``n_stages * V`` chunks and
    device ``s`` holds the non-contiguous chunks ``v*S + s`` (the
    engine permutes/unpermutes internally, so params and grads stay in
    canonical layer order here).
    """
    from horovod_tpu.parallel.pipeline import (
        interleaved_one_f_one_b,
        one_f_one_b,
    )

    dt = c.compute_dtype
    b, t = batch["tokens"].shape
    M = _validate_pipeline(c, b, mesh, seq_axis, n_stages)

    tokens = batch["tokens"]
    if mesh is not None:
        tokens = lax.with_sharding_constraint(
            tokens, jax.sharding.NamedSharding(
                mesh, P(("data", "fsdp"), "seq")))

    mask = batch.get("mask")
    if mask is None:
        mask = jnp.ones((b, t), jnp.float32)
    mask = mask.astype(jnp.float32)
    # The mask denominator is global across microbatches, so it is
    # computed OUTSIDE the schedule and folded into each microbatch's
    # loss numerator (mask is data, not a differentiated value).
    denom = jnp.maximum(jnp.sum(mask), 1.0)

    stage_fn, loss_fn, aux_ct = llama_pipeline_programs(
        c, mesh, seq_axis, microbatches=M, denom=denom)

    def schedule_fwd(sp, hp, xs, largs):
        if c.pipeline_schedule == "interleaved_1f1b":
            loss, aux, d_sp, d_hp, d_xs = interleaved_one_f_one_b(
                stage_fn, loss_fn, sp, hp, xs, largs, mesh,
                num_virtual=c.pipeline_virtual_stages,
                aux_cotangent=aux_ct)
        else:
            loss, aux, d_sp, d_hp, d_xs = one_f_one_b(
                stage_fn, loss_fn, sp, hp, xs, largs, mesh,
                aux_cotangent=aux_ct)
        return loss + aux_ct * aux, (d_sp, d_hp, d_xs, largs)

    def schedule_primal(sp, hp, xs, largs):
        # VALUE-ONLY path (eval loops, loss logging): the gpipe forward
        # plus the shared loss head. one_f_one_b computes every
        # gradient to produce its value, so routing no-grad calls
        # through it costs ~3x the needed work (ADVICE r5); under
        # differentiation custom_vjp uses schedule_fwd instead. Same
        # stage_fn, same loss_fn, same aux folding — equality of the
        # two values is the gpipe-vs-1f1b loss identity
        # tests/single/test_pipeline_1f1b.py pins.
        from horovod_tpu.parallel.pipeline import gpipe

        ys, aux_total = gpipe(stage_fn, sp, xs, mesh)
        losses = jax.vmap(loss_fn, in_axes=(None, 0, 0))(hp, ys, largs)
        return jnp.sum(losses) + aux_ct * aux_total

    schedule = jax.custom_vjp(schedule_primal)

    def schedule_bwd(res, dl):
        import numpy as _np

        d_sp, d_hp, d_xs, largs = res
        scale = lambda g: jax.tree.map(  # noqa: E731
            lambda x: (x * dl).astype(x.dtype), g)
        d_largs = jax.tree.map(
            lambda x: (jnp.zeros_like(x)
                       if jnp.issubdtype(x.dtype, jnp.inexact)
                       else _np.zeros(x.shape, jax.dtypes.float0)),
            largs)
        return scale(d_sp), scale(d_hp), scale(d_xs), d_largs

    schedule.defvjp(schedule_fwd, schedule_bwd)

    x = _constrain(params["embed"].astype(dt)[tokens], mesh)
    xs = x.reshape(M, b // M, t, x.shape[-1])
    largs = (batch["targets"].reshape(M, b // M, t),
             mask.reshape(M, b // M, t))
    return schedule(params["layers"],
                    (params["final_norm"], params["lm_head"]), xs,
                    largs)
