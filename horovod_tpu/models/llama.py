"""Llama-family decoder-only transformer, TPU-first.

Design choices (vs a torch translation):
- functional: params are a plain pytree; init/forward are pure functions
  compatible with jit/grad/shard_map.
- scan-over-layers: per-layer params are stacked on a leading axis and the
  decoder body is one ``lax.scan`` — O(1) XLA program size in depth, the
  standard TPU idiom (compile time does not grow with n_layers).
- remat: each scanned layer is wrapped in ``jax.checkpoint`` so activations
  are recomputed in backward — HBM for FLOPs, the right TPU trade.
- bfloat16 compute, float32 params/logits-softmax for stability.
- attention dispatches to exact ring attention when the mesh has a
  non-trivial ``seq`` axis (long-context sequence parallelism), else to
  single-device flash-style blockwise attention.
- sharding by PartitionSpec rules (megatron TP + FSDP), applied by the
  caller via ``llama_partition_rules``; XLA/GSPMD inserts the collectives.
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.ring_attention import ring_self_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = True

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def llama3_8b():
        return LlamaConfig(vocab_size=128256, d_model=4096, n_layers=32,
                           n_heads=32, n_kv_heads=8, d_ff=14336)

    @staticmethod
    def tiny(**kw):
        """Test/dryrun config: full architecture, toy sizes."""
        defaults = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, d_ff=128, rope_theta=10000.0)
        defaults.update(kw)
        return LlamaConfig(**defaults)


def llama_init(config, key):
    """Initialize the parameter pytree (float32 master weights).

    Per-layer tensors are stacked on a leading n_layers axis for scan.
    """
    c = config
    hd = c.head_dim
    k = iter(jax.random.split(key, 16))

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5))

    L = c.n_layers
    params = {
        "embed": jax.random.normal(next(k), (c.vocab_size, c.d_model),
                                   jnp.float32) * 0.02,
        "layers": {
            "attn_norm": jnp.ones((L, c.d_model)),
            "wq": dense(next(k), (L, c.d_model, c.n_heads * hd), c.d_model),
            "wk": dense(next(k), (L, c.d_model, c.n_kv_heads * hd),
                        c.d_model),
            "wv": dense(next(k), (L, c.d_model, c.n_kv_heads * hd),
                        c.d_model),
            "wo": dense(next(k), (L, c.n_heads * hd, c.d_model),
                        c.n_heads * hd),
            "mlp_norm": jnp.ones((L, c.d_model)),
            "w_gate": dense(next(k), (L, c.d_model, c.d_ff), c.d_model),
            "w_up": dense(next(k), (L, c.d_model, c.d_ff), c.d_model),
            "w_down": dense(next(k), (L, c.d_ff, c.d_model), c.d_ff),
        },
        "final_norm": jnp.ones(c.d_model),
        "lm_head": dense(next(k), (c.d_model, c.vocab_size), c.d_model),
    }
    return params


def llama_partition_rules():
    """Megatron TP + FSDP sharding rules for the param pytree.

    Layer-stacked tensors have a leading (unsharded) layer axis. The
    ``tensor`` axis splits heads / ffn; ``fsdp`` shards the other matmul
    dimension ZeRO-3 style. Pass to parallel.shard_params.
    """
    return [
        (r"embed", P("tensor", "fsdp")),
        (r"layers/.*norm", P(None, None)),
        (r"layers/w[qkv]$", P(None, "fsdp", "tensor")),
        (r"layers/wo", P(None, "tensor", "fsdp")),
        (r"layers/w_(gate|up)", P(None, "fsdp", "tensor")),
        (r"layers/w_down", P(None, "tensor", "fsdp")),
        (r"final_norm", P(None)),
        (r"lm_head", P("fsdp", "tensor")),
    ]


def _rmsnorm(x, scale, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale


def _rope(x, positions, theta):
    """Rotary embedding; positions are GLOBAL indices [B, T] so sequence
    sharding stays correct."""
    b, t, h, d = x.shape
    freqs = theta ** (-jnp.arange(0, d // 2, dtype=jnp.float32) / (d // 2))
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # [B,T,d/2]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _attention(q, k, v, mesh, seq_axis):
    if mesh is not None and seq_axis and mesh.shape.get(seq_axis, 1) > 1:
        return ring_self_attention(q, k, v, mesh, causal=True,
                                   batch_axis=("data", "fsdp"),
                                   seq_axis=seq_axis)
    # Pallas flash kernel on TPU (no T^2 score materialization, so the
    # layer no longer needs full remat for memory); flash_attention
    # itself falls back to blockwise_attention off-TPU.
    from horovod_tpu.ops import flash_attention

    return flash_attention(q, k, v, causal=True)


def _activation_spec(mesh):
    """[B, T, D] activations: batch over data+fsdp, seq over seq axis."""
    return P(("data", "fsdp"), "seq", None)


def llama_forward(params, tokens, config, mesh=None, seq_axis="seq"):
    """tokens [B, T] int32 -> logits [B, T, vocab] (float32).

    Under jit with a mesh, activations get sharding constraints so GSPMD
    lays out batch over data/fsdp and sequence over seq; the attention op
    switches to ring attention when seq parallelism is active.
    """
    c = config
    dt = c.compute_dtype
    b, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t), (b, t))

    def constrain(x):
        if mesh is None:
            return x
        return lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, _activation_spec(mesh)))

    x = params["embed"].astype(dt)[tokens]
    x = constrain(x)

    def layer(x, lp):
        h = _rmsnorm(x, lp["attn_norm"].astype(dt), c.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(b, t, c.n_heads, c.head_dim)
        kk = (h @ lp["wk"].astype(dt)).reshape(b, t, c.n_kv_heads,
                                               c.head_dim)
        vv = (h @ lp["wv"].astype(dt)).reshape(b, t, c.n_kv_heads,
                                               c.head_dim)
        q = _rope(q, positions, c.rope_theta)
        kk = _rope(kk, positions, c.rope_theta)
        attn = _attention(q, kk, vv, mesh, seq_axis)
        x = x + constrain(attn.reshape(b, t, -1) @ lp["wo"].astype(dt))

        h = _rmsnorm(x, lp["mlp_norm"].astype(dt), c.norm_eps)
        gate = jax.nn.silu(h @ lp["w_gate"].astype(dt))
        up = h @ lp["w_up"].astype(dt)
        x = x + constrain((gate * up) @ lp["w_down"].astype(dt))
        return x, None

    body = layer
    if c.remat:
        body = jax.checkpoint(layer)
    x, _ = lax.scan(body, x, params["layers"])

    x = _rmsnorm(x, params["final_norm"].astype(dt), c.norm_eps)
    logits = x.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)
    return logits


def llama_loss(params, batch, config, mesh=None, seq_axis="seq"):
    """Causal LM loss. batch = {"tokens": [B,T], "targets": [B,T],
    "mask": [B,T] or absent}."""
    logits = llama_forward(params, batch["tokens"], config, mesh, seq_axis)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tgt = batch["targets"]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    mask = batch.get("mask")
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
