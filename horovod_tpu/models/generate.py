"""KV-cached autoregressive decoding for the llama family.

Net-new vs the reference (Horovod ships no inference path); TPU-first:
one jitted program — prefill fills the cache with a single full-sequence
pass, then ``lax.scan`` decodes token-by-token against a static-shaped
cache (no dynamic shapes, no per-step retrace). The per-step attention
is GQA-native (``_decode_attention``): the fused kernel reads the cache
at its stored kv-head width, and slots past the current position mask
themselves by global index.

Dense and MoE configs (per-token top-k routing is sequence-independent,
so cached decode routes each new token exactly as a full forward would).
With the default ``moe_impl="auto"`` the single-chip prefill resolves
to the DROPLESS grouped dispatch (ops/grouped_moe.py), which matches
the top-k decode path exactly — no capacity drops anywhere. A
checkpoint trained under an expert-parallel mesh (auto -> GShard,
capacity drops) should set ``moe_impl="gshard"`` for bit-parity with
its training-time prefill semantics; its decode steps still use the
drop-free top-k path (a single token never overflows capacity).
Single-device or data-parallel batch — the sequence axis is not
sharded at decode.

Numerics (changed round 5): decode attention — both the fused pallas
kernel and the einsum fallback — casts the softmaxed attention
probabilities to bf16 before the PV contraction and accumulates in
f32, matching the training flash kernel's recipe exactly. Round 4
kept the probabilities f32 through PV; rounding them to bf16 can flip
the greedy argmax when two next-token logits sit within rounding
distance, so greedy output may differ from round-4 behavior at such
near-ties. The two decode paths stay mutually consistent, and
train/decode now share one numerics contract (see docs/benchmarks.md,
"Decode numerics").
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.models.llama import _ffn as _llama_ffn
from horovod_tpu.models.llama import _rmsnorm, _rope, moe_route


def _ffn(h, lp, c):
    """llama.py's shared FFN, aux loss dropped (decode does not train).
    Serves prefill, dense decode, and MoE decode at large batch;
    small-batch MoE decode uses _moe_ffn_topk. Dispatch follows
    ``c.moe_impl`` exactly as llama_forward with no mesh does (see the
    module docstring for the gshard-trained-checkpoint caveat)."""
    y, _aux = _llama_ffn(h, lp, c, None)
    return y


def _moe_ffn_topk(h, lp, c):
    """Decode-step MoE FFN: gather only the K routed experts' weights
    per token and run a [K]-grouped matmul — FLOPs and weight-HBM reads
    scale with top-k, not the expert count E (the capacity dispatch in
    llama._moe_ffn streams all E experts, which is right for training
    but E/K-times wasteful for a single decoded token). Routing (same
    router, same gate normalization) matches llama._moe_ffn; a single
    token can never overflow per-expert capacity, so no drop divergence.

    The gathers materialize one [K,D,F]-sized weight copy per token, so
    this path only wins while B*T*K < E — _decode_ffn falls back to the
    streaming dispatch beyond that (where it reads fewer weight bytes
    anyway).
    """
    dt = c.compute_dtype
    K = c.n_experts_per_token
    gate_vals, gate_idx, _aux = moe_route(h, lp["router"], K)  # [B,T,K]
    wg = lp["moe_gate"].astype(dt)[gate_idx]                # [B,T,K,D,F]
    wu = lp["moe_up"].astype(dt)[gate_idx]
    wd = lp["moe_down"].astype(dt)[gate_idx]                # [B,T,K,F,D]
    hk = h.astype(dt)
    gate = jax.nn.silu(jnp.einsum("btd,btkdf->btkf", hk, wg))
    up = jnp.einsum("btd,btkdf->btkf", hk, wu)
    y = jnp.einsum("btkf,btkfd->btkd", gate * up, wd)
    return jnp.einsum("btk,btkd->btd", gate_vals.astype(dt), y)


def _decode_ffn(h, lp, c):
    """FFN for the one-token decode step: dense as-is; MoE via the
    top-k gather while it touches fewer weights than streaming all E
    experts (shapes are static, so this is a trace-time choice)."""
    if c.n_experts > 0:
        b, t, _ = h.shape
        if b * t * c.n_experts_per_token < c.n_experts:
            return _moe_ffn_topk(h, lp, c)
    return _ffn(h, lp, c)


def _layer_kv(h, lp, c, positions):
    """Project h -> rope'd (k, v) for one layer. h [B,T,D] normalized."""
    dt = c.compute_dtype
    b, t = h.shape[0], h.shape[1]
    k = (h @ lp["wk"].astype(dt)).reshape(b, t, c.n_kv_heads, c.head_dim)
    v = (h @ lp["wv"].astype(dt)).reshape(b, t, c.n_kv_heads, c.head_dim)
    return _rope(k, positions, c.rope_theta), v


def _decode_attention(q, cache_k, cache_v, pos):
    """One-token attention against the cache, GQA-native: the fused
    pallas kernel on TPU (scores + masked softmax + PV folded into the
    one pass that streams the cache — ops/decode_attention.py), the
    same-recipe einsum chain elsewhere. Either way kv-heads are indexed
    directly: repeating the cache to H query heads would stream an
    n_rep× expanded copy through HBM per layer per step, and decode is
    pure bandwidth (at batch 64 that repeat alone tripled step time).
    """
    from horovod_tpu.ops.decode_attention import decode_attention

    return decode_attention(q, cache_k, cache_v, pos)


def _attend_step(x, lp, c, cache_k, cache_v, li, pos):
    """One decode-position layer step against the STACKED caches.

    x [B,D]; cache_k/v [L,B,Hkv,max_len,hd] with positions < pos
    valid; this step's k/v are written at (li, :, pos) before
    attending. The caches stay scan CARRIES and are updated by
    layer-indexed dynamic_update_slice — passing them as scanned
    xs/stacked ys instead forces XLA to rebuild the whole stacked
    buffer every token (measured: a 2x176 MB copy per decode step at
    flagship b64, ~25% of the step's bandwidth budget).
    Returns (x_out, cache_k, cache_v).
    """
    dt = c.compute_dtype
    b = x.shape[0]
    # x is 2-D [B, D] through the layer: the [B, 1, D] singleton-dim
    # form makes XLA pick {2,0,1}-style layouts for the residual/norm
    # chains and pay a layout cast per op (~2 ms/step across 14 layers
    # at flagship b64). The sequence dim reappears only at the
    # attention/FFN boundaries that need it.
    positions = jnp.broadcast_to(pos, (b, 1))
    h = _rmsnorm(x, lp["attn_norm"].astype(dt), c.norm_eps)
    q = (h @ lp["wq"].astype(dt)).reshape(b, 1, c.n_heads, c.head_dim)
    q = _rope(q, positions, c.rope_theta)
    k_new, v_new = _layer_kv(h[:, None, :], lp, c, positions)
    # Caches live heads-major [L, B, Hkv, S, D] (the attention-kernel
    # layout); the new token's [B, 1, Hkv, D] projects to [B, Hkv, 1, D].
    cache_k = lax.dynamic_update_slice(
        cache_k, k_new.transpose(0, 2, 1, 3)[None], (li, 0, 0, pos, 0))
    cache_v = lax.dynamic_update_slice(
        cache_v, v_new.transpose(0, 2, 1, 3)[None], (li, 0, 0, pos, 0))
    ck = lax.dynamic_index_in_dim(cache_k, li, 0, keepdims=False)
    cv = lax.dynamic_index_in_dim(cache_v, li, 0, keepdims=False)
    attn = _decode_attention(q, ck, cv, pos)
    x = x + attn.reshape(b, -1) @ lp["wo"].astype(dt)
    h = _rmsnorm(x, lp["mlp_norm"].astype(dt), c.norm_eps)
    x = x + _decode_ffn(h[:, None, :], lp, c)[:, 0, :]
    return x, cache_k, cache_v


def _prefill(params, prompt, c, pad_to):
    """One full-sequence pass capturing each layer's K/V.

    Returns (x [B, T, D] final hidden states, cache_k, cache_v
    [L, B, Hkv, T+pad_to, hd] heads-major). The shared front half of
    :func:`llama_generate` (pad_to=max_new_tokens, decode scans in
    place) and :func:`llama_prefill` (the serving lane, pad_to=0 — the
    paged KV pool owns the growth instead of padding)."""
    dt = c.compute_dtype
    b, t0 = prompt.shape
    x = params["embed"].astype(dt)[prompt]
    positions = jnp.broadcast_to(jnp.arange(t0), (b, t0))

    def prefill_layer(x, lp):
        h = _rmsnorm(x, lp["attn_norm"].astype(dt), c.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(b, t0, c.n_heads, c.head_dim)
        q = _rope(q, positions, c.rope_theta)
        k, v = _layer_kv(h, lp, c, positions)
        # Flash kernel (not blockwise): a long prompt must not
        # materialize the [B,H,T,T] score tensor.
        from horovod_tpu.ops import flash_attention

        attn = flash_attention(q, k, v, causal=True)
        x = x + attn.reshape(b, t0, -1) @ lp["wo"].astype(dt)
        h = _rmsnorm(x, lp["mlp_norm"].astype(dt), c.norm_eps)
        x = x + _ffn(h, lp, c)
        # Cache padded to max_len so decode's dynamic_update_slice fits.
        # Heads-major cache layout [B, Hkv, max_len, hd] (the decode
        # attention kernel's layout); one transpose per layer at
        # prefill, never again.
        pad = jnp.zeros((b, c.n_kv_heads, pad_to, c.head_dim), dt)
        return x, (jnp.concatenate([k.transpose(0, 2, 1, 3), pad], 2),
                   jnp.concatenate([v.transpose(0, 2, 1, 3), pad], 2))

    x, (cache_k, cache_v) = lax.scan(prefill_layer, x, params["layers"])
    return x, cache_k, cache_v


def _lm_logits(params, x_last, c):
    """Final-norm + lm_head in f32 (x_last [..., D])."""
    dt = c.compute_dtype
    h = _rmsnorm(x_last, params["final_norm"].astype(dt), c.norm_eps)
    return (h @ params["lm_head"].astype(dt)).astype(jnp.float32)


@partial(jax.jit, static_argnames=("config", "pad_to"))
def llama_prefill(params, prompt, config, pad_to=0):
    """Serving-lane prefill: one compiled pass -> the greedy first
    token plus this prompt's per-layer K/V for a paged cache.

    prompt [B, T] int32 -> (first [B] int32, cache_k, cache_v
    [L, B, Hkv, T+pad_to, hd]). Unlike :func:`llama_generate` the
    caches come back UNPADDED by default — the continuous-batching
    engine writes them into fixed-size pool blocks (per-sequence block
    tables), so sequence growth never re-allocates a monolithic
    buffer. Greedy only: the serving lane's elastic re-queue guarantee
    is token-identity, which sampling would break."""
    x, cache_k, cache_v = _prefill(params, prompt, config, pad_to)
    logits = _lm_logits(params, x[:, -1:, :], config)[:, 0, :]
    return (jnp.argmax(logits, axis=-1).astype(prompt.dtype),
            cache_k, cache_v)


@partial(jax.jit, static_argnames=("config",))
def llama_decode_step(params, tokens, cache_k, cache_v, lengths, config,
                      k_scale=None, v_scale=None):
    """One continuous-batching decode step over a RAGGED batch.

    Each batch row b holds its own sequence at position ``lengths[b]``
    (valid cached slots < lengths[b]; pool-gathered caches are padded
    to one static S — the mask, not the shape, carries raggedness, so
    one compiled program serves every batch composition). tokens [B]
    int32 (each row's last emitted token); cache_k/v
    [L, B, Hkv, S, hd] — f32/bf16, or int8 with per-slot dequant
    scales ``k_scale``/``v_scale`` [L, B, Hkv, S] (the paged pool's
    per-block scales expanded; dequant is f32-accumulate inside
    ``decode_attention_ragged``).

    Returns (next [B] int32 greedy tokens, k_new, v_new
    [L, B, Hkv, hd] — this step's projections, which the CALLER writes
    into the paged cache; the step never updates the cache in place,
    so the gathered view can stay a cheap scan input instead of a
    carried copy).
    """
    from horovod_tpu.ops.decode_attention import decode_attention_ragged

    c = config
    dt = c.compute_dtype
    b = tokens.shape[0]
    x = params["embed"].astype(dt)[tokens]          # [B, D]
    positions = jnp.asarray(lengths, jnp.int32)[:, None]  # [B, 1]

    def layer(x, xs):
        lp, ck, cv, ks, vs = xs
        h = _rmsnorm(x, lp["attn_norm"].astype(dt), c.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(b, 1, c.n_heads,
                                              c.head_dim)
        q = _rope(q, positions, c.rope_theta)
        k_new, v_new = _layer_kv(h[:, None, :], lp, c, positions)
        attn = decode_attention_ragged(
            q, ck, cv, lengths,
            k_new.transpose(0, 2, 1, 3), v_new.transpose(0, 2, 1, 3),
            k_scale=ks, v_scale=vs)
        x = x + attn.reshape(b, -1) @ lp["wo"].astype(dt)
        h = _rmsnorm(x, lp["mlp_norm"].astype(dt), c.norm_eps)
        x = x + _decode_ffn(h[:, None, :], lp, c)[:, 0, :]
        return x, (k_new[:, 0, :, :], v_new[:, 0, :, :])

    # Caches (and scales) are read-only here, so they ride as scanned
    # xs — no carried copy (the _attend_step rebuild hazard only bites
    # when the scan must WRITE the stacked buffer). Absent scales scan
    # as zero-width placeholders so both modes share one layer body.
    if k_scale is None:
        empty = jnp.zeros((c.n_layers, 0), jnp.float32)

        def layer_noscale(x, xs):
            lp, ck, cv, _, _ = xs
            return layer(x, (lp, ck, cv, None, None))

        x, (k_new, v_new) = lax.scan(
            layer_noscale, x,
            (params["layers"], cache_k, cache_v, empty, empty))
    else:
        x, (k_new, v_new) = lax.scan(
            layer, x,
            (params["layers"], cache_k, cache_v, k_scale, v_scale))
    logits = _lm_logits(params, x, c)               # [B, V]
    nxt = jnp.argmax(logits, axis=-1).astype(tokens.dtype)
    return nxt, k_new, v_new


@partial(jax.jit,
         static_argnames=("config", "max_new_tokens", "temperature"))
def llama_generate(params, prompt, config, max_new_tokens,
                   temperature=0.0, key=None):
    """Greedy (temperature=0) or sampled decoding.

    prompt [B, T] int32 -> [B, T + max_new_tokens] (prompt + generated).
    The whole prefill+decode is ONE compiled program; recompiles when
    (config, prompt length, max_new_tokens, temperature) change —
    temperature is static because it selects greedy vs sampled tracing.
    """
    c = config
    dt = c.compute_dtype
    b, t0 = prompt.shape
    if key is None:
        key = jax.random.PRNGKey(0)
    keys = jax.random.split(key, max_new_tokens)  # [0]=first, rest=steps

    # ---- prefill: one full pass, capturing each layer's K/V ----------
    x, cache_k, cache_v = _prefill(params, prompt, c, max_new_tokens)
    # cache_k/v: [L, B, Hkv, max_len, hd]

    def logits_of(x_last):
        return _lm_logits(params, x_last, c)

    def pick(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        return jax.random.categorical(
            k, logits / temperature, axis=-1).astype(prompt.dtype)

    first = pick(logits_of(x[:, -1:, :])[:, 0, :], keys[0])  # [B]

    # ---- decode: scan max_new_tokens-1 steps (each feeds the previous
    # token and emits the NEXT one; 'first' is prepended at the end) ---
    def step(carry, step_key):
        token, pos, cache_k, cache_v = carry
        x = params["embed"].astype(dt)[token]       # [B, D] (2-D!)

        def layer(lcarry, lp):
            x, ck, cv, li = lcarry
            x, ck, cv = _attend_step(x, lp, c, ck, cv, li, pos)
            return (x, ck, cv, li + 1), None

        (x, cache_k, cache_v, _), _ = lax.scan(
            layer, (x, cache_k, cache_v, jnp.int32(0)),
            params["layers"])
        nxt = pick(logits_of(x), step_key)
        return (nxt, pos + 1, cache_k, cache_v), nxt

    (_, _, _, _), toks = lax.scan(
        step, (first, jnp.int32(t0), cache_k, cache_v), keys[1:])
    # toks [max_new_tokens-1, B]: tokens generated after 'first'.
    return jnp.concatenate(
        [prompt, first[:, None], jnp.transpose(toks, (1, 0))], axis=1)
