"""BERT-family encoder, TPU-first.

Reference analog: the reference's transformer benchmark workload (its
examples tree trains BERT via the framework frontends). Same design
stance as ``llama.py``: functional params pytree, scan-over-layers with
remat, bf16 compute / f32 master weights, megatron TP + FSDP partition
rules. Bidirectional (non-causal) attention with an additive padding
mask; learned position embeddings; MLM head tied to the token embedding.
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_position: int = 512
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    norm_eps: float = 1e-12
    dtype: str = "bfloat16"
    remat: bool = True

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def large():
        return BertConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)

    @staticmethod
    def tiny(**kw):
        defaults = dict(vocab_size=256, max_position=128, d_model=64,
                        n_layers=2, n_heads=4, d_ff=128)
        defaults.update(kw)
        return BertConfig(**defaults)


def bert_init(config, key):
    c = config
    k = iter(jax.random.split(key, 16))

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5))

    L = c.n_layers
    return {
        "embed": jax.random.normal(next(k), (c.vocab_size, c.d_model),
                                   jnp.float32) * 0.02,
        "pos_embed": jax.random.normal(next(k), (c.max_position, c.d_model),
                                       jnp.float32) * 0.02,
        "embed_norm": {"scale": jnp.ones(c.d_model),
                       "bias": jnp.zeros(c.d_model)},
        "layers": {
            "attn_norm_scale": jnp.ones((L, c.d_model)),
            "attn_norm_bias": jnp.zeros((L, c.d_model)),
            "wq": dense(next(k), (L, c.d_model, c.d_model), c.d_model),
            "wk": dense(next(k), (L, c.d_model, c.d_model), c.d_model),
            "wv": dense(next(k), (L, c.d_model, c.d_model), c.d_model),
            "wo": dense(next(k), (L, c.d_model, c.d_model), c.d_model),
            "mlp_norm_scale": jnp.ones((L, c.d_model)),
            "mlp_norm_bias": jnp.zeros((L, c.d_model)),
            "w_in": dense(next(k), (L, c.d_model, c.d_ff), c.d_model),
            "b_in": jnp.zeros((L, c.d_ff)),
            "w_out": dense(next(k), (L, c.d_ff, c.d_model), c.d_ff),
            "b_out": jnp.zeros((L, c.d_model)),
        },
        "mlm_norm": {"scale": jnp.ones(c.d_model),
                     "bias": jnp.zeros(c.d_model)},
        "mlm_dense": dense(next(k), (c.d_model, c.d_model), c.d_model),
        "mlm_bias": jnp.zeros(c.vocab_size),  # head weights tied to embed
    }


def bert_partition_rules():
    """Megatron TP + FSDP rules (same scheme as llama)."""
    return [
        (r"pos_embed", P(None, "fsdp")),
        (r"^embed$", P("tensor", "fsdp")),
        (r".*norm.*", P()),
        (r"layers/w[qkv]$", P(None, "fsdp", "tensor")),
        (r"layers/wo", P(None, "tensor", "fsdp")),
        (r"layers/w_in", P(None, "fsdp", "tensor")),
        (r"layers/b_in", P(None, "tensor")),
        (r"layers/w_out", P(None, "tensor", "fsdp")),
        (r"layers/b_out", P(None, None)),
        (r"mlm_dense", P("fsdp", "tensor")),
        (r"mlm_bias", P("tensor")),
    ]


def _layernorm(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _encoder_attention(q, k, v, kv_bias):
    """Bidirectional attention. q,k,v [B,T,H,D]; kv_bias [B,T] additive
    per key (large negative on padding). Pallas flash kernel on TPU (no
    T² score materialization); reference-math fallback elsewhere."""
    from horovod_tpu.ops import flash_attention

    return flash_attention(q, k, v, causal=False, kv_bias=kv_bias)


def bert_forward(params, tokens, config, attention_mask=None, mesh=None):
    """tokens [B,T] int32 -> MLM logits [B,T,vocab] (f32).

    ``attention_mask`` [B,T] with 1 = real token, 0 = padding.
    """
    c = config
    dt = c.compute_dtype
    B, T = tokens.shape
    if attention_mask is None:
        attention_mask = jnp.ones((B, T), jnp.int32)
    # Finite bias (not -inf): a fully-padded row (ragged final batch) must
    # softmax to uniform garbage that the loss masks out, not to NaN.
    kv_bias = jnp.where(attention_mask > 0, 0.0, -1e30).astype(jnp.float32)

    h = params["embed"][tokens] + params["pos_embed"][None, :T]
    h = _layernorm(h.astype(dt), params["embed_norm"]["scale"],
                   params["embed_norm"]["bias"], c.norm_eps)

    def layer(h, lp):
        hn = _layernorm(h, lp["attn_norm_scale"], lp["attn_norm_bias"],
                        c.norm_eps)
        q = (hn @ lp["wq"].astype(dt)).reshape(B, T, c.n_heads, c.head_dim)
        k = (hn @ lp["wk"].astype(dt)).reshape(B, T, c.n_heads, c.head_dim)
        v = (hn @ lp["wv"].astype(dt)).reshape(B, T, c.n_heads, c.head_dim)
        attn = _encoder_attention(q, k, v, kv_bias)
        h = h + attn.reshape(B, T, c.d_model) @ lp["wo"].astype(dt)
        hn = _layernorm(h, lp["mlp_norm_scale"], lp["mlp_norm_bias"],
                        c.norm_eps)
        ff = jax.nn.gelu(hn @ lp["w_in"].astype(dt) + lp["b_in"].astype(dt))
        h = h + (ff @ lp["w_out"].astype(dt) + lp["b_out"].astype(dt))
        return h, None

    body = layer
    if c.remat:
        body = jax.checkpoint(layer)
    h, _ = lax.scan(body, h, params["layers"])

    # MLM head: dense + norm, decode against the tied embedding.
    h = jax.nn.gelu(h @ params["mlm_dense"].astype(dt))
    h = _layernorm(h, params["mlm_norm"]["scale"], params["mlm_norm"]["bias"],
                   c.norm_eps)
    logits = h.astype(jnp.float32) @ params["embed"].T + params["mlm_bias"]
    return logits


def bert_mlm_loss(params, batch, config, mesh=None):
    """Masked-LM loss. batch = {"tokens": [B,T] (with [MASK] ids),
    "targets": [B,T] original ids, "mlm_mask": [B,T] 1 where predicted,
    optional "attention_mask": [B,T]}."""
    logits = bert_forward(params, batch["tokens"], config,
                          attention_mask=batch.get("attention_mask"),
                          mesh=mesh)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, batch["targets"][..., None], -1)[..., 0]
    m = batch["mlm_mask"].astype(jnp.float32)
    return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)
