"""ResNet v1.5 family, TPU-first.

Reference analog: the reference's headline benchmark models
(docs/benchmarks.rst: ResNet-50/101 in tf_cnn_benchmarks via
examples/). Functional jax instead of torch nn.Module:

- NHWC layout (TPU's native conv layout — the MXU consumes the channel
  minor dimension directly; torch's NCHW would force transposes).
- params and batchnorm running stats are separate pytrees; forward is
  pure: ``resnet_forward(params, state, x, train=...)`` returns
  ``(logits, new_state)`` — jit/grad/shard_map compose cleanly.
- bf16 compute / f32 params + batchnorm statistics.
- stride-on-3x3 (v1.5), matching the torchvision weights the reference
  benchmarks load.
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

# depths per stage for each family member
_DEPTHS = {
    18: ((2, 2, 2, 2), False),
    34: ((3, 4, 6, 3), False),
    50: ((3, 4, 6, 3), True),
    101: ((3, 4, 23, 3), True),
    152: ((3, 8, 36, 3), True),
}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64
    compute_dtype: str = "bfloat16"
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5

    @property
    def stage_depths(self):
        return _DEPTHS[self.depth][0]

    @property
    def bottleneck(self):
        return _DEPTHS[self.depth][1]


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * (
        (2.0 / fan_in) ** 0.5)


def _bn_init(c):
    return {"scale": jnp.ones(c), "bias": jnp.zeros(c)}


def _bn_state(c):
    return {"mean": jnp.zeros(c), "var": jnp.ones(c)}


def resnet_init(config, key):
    """Returns (params, state): state holds batchnorm running stats."""
    c = config
    keys = iter(jax.random.split(key, 4 + sum(c.stage_depths) * 4))
    params = {"stem": {"conv": _conv_init(next(keys), 7, 7, 3, c.width),
                       "bn": _bn_init(c.width)}}
    state = {"stem": {"bn": _bn_state(c.width)}}
    cin = c.width
    expansion = 4 if c.bottleneck else 1
    for s, depth in enumerate(c.stage_depths):
        cmid = c.width * (2 ** s)
        cout = cmid * expansion
        blocks_p, blocks_s = [], []
        for b in range(depth):
            stride = 2 if (s > 0 and b == 0) else 1
            bp, bs = {}, {}
            if c.bottleneck:
                bp["conv1"] = _conv_init(next(keys), 1, 1, cin, cmid)
                bp["conv2"] = _conv_init(next(keys), 3, 3, cmid, cmid)
                bp["conv3"] = _conv_init(next(keys), 1, 1, cmid, cout)
                for i, ch in (("1", cmid), ("2", cmid), ("3", cout)):
                    bp[f"bn{i}"] = _bn_init(ch)
                    bs[f"bn{i}"] = _bn_state(ch)
                # zero-init the last BN scale (standard trick: the block
                # starts as identity, stabilizing early large-batch training)
                bp["bn3"]["scale"] = jnp.zeros(cout)
            else:
                bp["conv1"] = _conv_init(next(keys), 3, 3, cin, cmid)
                bp["conv2"] = _conv_init(next(keys), 3, 3, cmid, cout)
                for i, ch in (("1", cmid), ("2", cout)):
                    bp[f"bn{i}"] = _bn_init(ch)
                    bs[f"bn{i}"] = _bn_state(ch)
                bp["bn2"]["scale"] = jnp.zeros(cout)
            if cin != cout or stride != 1:
                bp["proj"] = _conv_init(next(keys), 1, 1, cin, cout)
                bp["proj_bn"] = _bn_init(cout)
                bs["proj_bn"] = _bn_state(cout)
            blocks_p.append(bp)
            blocks_s.append(bs)
            cin = cout
        params[f"stage{s}"] = blocks_p
        state[f"stage{s}"] = blocks_s
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, c.num_classes),
                               jnp.float32) * (cin ** -0.5),
        "b": jnp.zeros(c.num_classes)}
    return params, state


def _conv(x, w, stride=1, dtype=jnp.bfloat16):
    return lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _batch_norm(x, p, s, train, momentum, eps):
    """Returns (y, new_running_stats). Stats in f32."""
    xf = x.astype(jnp.float32)
    if train:
        mean = xf.mean(axis=(0, 1, 2))
        var = xf.var(axis=(0, 1, 2))
        new_s = {"mean": momentum * s["mean"] + (1 - momentum) * mean,
                 "var": momentum * s["var"] + (1 - momentum) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    y = (xf - mean) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return y.astype(x.dtype), new_s


def resnet_forward(params, state, x, config, train=True):
    """x [N,H,W,3] float -> (logits [N,classes] f32, new_state)."""
    c = config
    dt = jnp.dtype(c.compute_dtype)
    bn = partial(_batch_norm, train=train, momentum=c.bn_momentum,
                 eps=c.bn_eps)
    new_state = {"stem": {}}
    h = _conv(x.astype(dt), params["stem"]["conv"], stride=2, dtype=dt)
    h, new_state["stem"]["bn"] = bn(h, params["stem"]["bn"],
                                    state["stem"]["bn"])
    h = jax.nn.relu(h)
    h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")
    for s in range(len(c.stage_depths)):
        stage_state = []
        for b, bp in enumerate(params[f"stage{s}"]):
            bs = state[f"stage{s}"][b]
            nbs = {}
            stride = 2 if (s > 0 and b == 0) else 1
            shortcut = h
            if "proj" in bp:
                shortcut = _conv(h, bp["proj"], stride=stride, dtype=dt)
                shortcut, nbs["proj_bn"] = bn(shortcut, bp["proj_bn"],
                                              bs["proj_bn"])
            if c.bottleneck:
                y = _conv(h, bp["conv1"], dtype=dt)
                y, nbs["bn1"] = bn(y, bp["bn1"], bs["bn1"])
                y = jax.nn.relu(y)
                y = _conv(y, bp["conv2"], stride=stride, dtype=dt)  # v1.5
                y, nbs["bn2"] = bn(y, bp["bn2"], bs["bn2"])
                y = jax.nn.relu(y)
                y = _conv(y, bp["conv3"], dtype=dt)
                y, nbs["bn3"] = bn(y, bp["bn3"], bs["bn3"])
            else:
                y = _conv(h, bp["conv1"], stride=stride, dtype=dt)
                y, nbs["bn1"] = bn(y, bp["bn1"], bs["bn1"])
                y = jax.nn.relu(y)
                y = _conv(y, bp["conv2"], dtype=dt)
                y, nbs["bn2"] = bn(y, bp["bn2"], bs["bn2"])
            h = jax.nn.relu(y + shortcut)
            stage_state.append(nbs)
        new_state[f"stage{s}"] = stage_state
    pooled = h.astype(jnp.float32).mean(axis=(1, 2))
    logits = pooled @ params["head"]["w"] + params["head"]["b"]
    return logits, new_state


def resnet_loss(params, state, batch, config, train=True):
    """Softmax CE; batch = {"images": [N,H,W,3], "labels": [N]}."""
    logits, new_state = resnet_forward(params, state, batch["images"],
                                       config, train=train)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1)
    return nll.mean(), new_state


def resnet_partition_rules():
    """Data-parallel by default: conv weights replicated, batch over
    data axes. (The reference's benchmark setup — pure DP.)"""
    from jax.sharding import PartitionSpec as P

    return [(r".*", P())]
