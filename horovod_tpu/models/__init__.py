"""horovod_tpu.models — JAX-native model zoo for examples and benchmarks.

The reference ships models only as examples (examples/pytorch_mnist.py,
keras resnet, BERT scripts — SURVEY.md §1 top layer); here they are proper
library code because the flagship transformer doubles as the perf vehicle
for the sharding/ring-attention machinery in ``horovod_tpu.parallel``.
"""

from horovod_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_loss,
    llama_partition_rules,
)
from horovod_tpu.models.generate import (  # noqa: F401
    llama_decode_step,
    llama_generate,
    llama_prefill,
)
from horovod_tpu.models.mlp import mlp_forward, mlp_init  # noqa: F401
from horovod_tpu.models.resnet import (  # noqa: F401
    ResNetConfig,
    resnet_forward,
    resnet_init,
    resnet_loss,
    resnet_partition_rules,
)
from horovod_tpu.models.bert import (  # noqa: F401
    BertConfig,
    bert_forward,
    bert_init,
    bert_mlm_loss,
    bert_partition_rules,
)
