"""Worker placement strategies for the Ray executor.

Reference analog: ``horovod/ray/strategy.py`` — decide how the
``num_workers`` actor slots map onto Ray placement-group bundles:
``pack`` fills hosts (maximizes intra-host locality — on TPU pods this
keeps ranks next to their chips), ``spread`` balances across hosts.
The strategy is pure planning (testable without ray); the executor turns
the plan into an actual placement group.
"""


class ColocationStrategy:
    def __init__(self, num_workers, cpus_per_worker=1, gpus_per_worker=0,
                 resources_per_worker=None):
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker
        self.resources_per_worker = dict(resources_per_worker or {})

    @property
    def placement_strategy(self):
        raise NotImplementedError()

    def bundles(self):
        b = {"CPU": self.cpus_per_worker}
        if self.gpus_per_worker:
            b["GPU"] = self.gpus_per_worker
        b.update(self.resources_per_worker)
        return [dict(b) for _ in range(self.num_workers)]


class PackStrategy(ColocationStrategy):
    placement_strategy = "PACK"


class SpreadStrategy(ColocationStrategy):
    placement_strategy = "SPREAD"
