"""Elastic Horovod on Ray: auto-scaling worker fleet as Ray actors.

Reference analog: ``horovod/ray/elastic_v2.py`` (ElasticRayExecutor +
RayHostDiscovery): the driver discovers the Ray cluster's current
nodes, spawns one worker actor per slot, and the elastic machinery
(rendezvous, epoch cuts, respawn-on-failure, blacklist, scale-up/down)
keeps the fleet matched to the cluster as nodes come and go.

TPU-native redesign: rather than a second elastic driver, the Ray path
reuses ``horovod_tpu.runner.elastic.driver.ElasticDriver`` wholesale —
only the worker LAUNCH is swapped (`_execute_worker`): a Ray actor
pinned to the discovered node runs the user's function instead of an
ssh'd OS process. Discovery, reconcile, rendezvous, survivor-first rank
layout, and blacklisting are the same code paths the launcher-based
elastic tests already prove. The launcher backend is injectable, so the
full add/remove/respawn lifecycle is unit-testable without a Ray
cluster (thread-fake actors — the reference's own elastic test
pattern).
"""

import sys
import threading

from horovod_tpu.runner.elastic.driver import ElasticDriver


def _require_ray():
    try:
        import ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray elastic support requires the 'ray' package, "
            "which is not installed in this environment.") from e
    return ray


class RayHostDiscovery:
    """Discovery over the live Ray cluster: one entry per alive node,
    slots = how many workers its resources can host.

    Reference analog: ``elastic_v2.RayHostDiscovery`` (ray.nodes() →
    {ip: slots} using CPU/GPU totals).
    """

    def __init__(self, cpus_per_worker=1, gpus_per_worker=0,
                 use_gpu=None):
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker
        if use_gpu and not gpus_per_worker:
            self.gpus_per_worker = 1

    def find_available_hosts_and_slots(self):
        ray = _require_ray()
        hosts = {}
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            res = node.get("Resources", {}) or {}
            slots = int(res.get("CPU", 0) // max(self.cpus_per_worker, 1))
            if self.gpus_per_worker:
                slots = min(slots,
                            int(res.get("GPU", 0) // self.gpus_per_worker))
            if slots > 0:
                hosts[node.get("NodeManagerAddress")] = slots
        return hosts


def _ray_actor_launcher(cpus_per_worker=1, gpus_per_worker=0,
                        poll_s=0.25, extra_env_keys=(), verbose=False):
    """Real backend: run the worker fn inside a Ray actor pinned to the
    worker's discovered node. Returns a launcher callable with the
    injectable-backend signature ``(worker, env, fn, events) ->
    (rc, result)``.

    ``extra_env_keys`` names env vars to ship to the actor on top of
    the HOROVOD_* contract — the executor threads the keys of its
    user-supplied ``env_vars`` through here so explicitly requested
    vars reach the workers on the Ray backend too.
    """
    ray = _require_ray()
    extra_env_keys = frozenset(extra_env_keys)

    @ray.remote
    class _ElasticWorker:
        def run(self, env, fn):
            import os

            os.environ.update(env)
            return fn(env)

    def launch(worker, env, fn, events):
        # Ship the HOROVOD_* contract vars plus any explicitly
        # user-requested keys to the actor — the env dict the driver
        # builds starts from the driver node's full os.environ, and
        # overwriting a remote node's JAX_PLATFORMS / TPU_* / PATH with
        # the driver's would silently move workers onto the wrong
        # devices (the ssh backend exports HOROVOD_* only for the same
        # reason).
        env = {k: v for k, v in env.items()
               if k.startswith("HOROVOD_") or k in extra_env_keys}
        actor = _ElasticWorker.options(
            num_cpus=cpus_per_worker, num_gpus=gpus_per_worker,
            # Pin to the discovered node: discovery reports node IPs and
            # ray publishes a node:<ip> custom resource per node.
            resources={f"node:{worker.host}": 0.001},
        ).remote()
        ref = actor.run.remote(env, fn)
        try:
            while True:
                done, _ = ray.wait([ref], timeout=poll_s)
                if done:
                    try:
                        return 0, ray.get(done[0])
                    except Exception as e:  # noqa: BLE001 — actor death
                        # or user-fn failure both mean this slot failed;
                        # surface the cause like the ssh backend does
                        # worker stderr, else real-cluster failures are
                        # undiagnosable.
                        if verbose:
                            print(f"[{worker.worker_id}]: actor failed: "
                                  f"{e!r}", file=sys.stderr)
                        return 1, None
                if any(ev.is_set() for ev in events):
                    return 1, None
        finally:
            ray.kill(actor)

    return launch


class _ElasticRayDriver(ElasticDriver):
    """ElasticDriver with actor-launched workers + per-worker results.
    Everything but the launch backend is inherited unchanged."""

    def __init__(self, discovery, fn, launcher, min_np, **kw):
        super().__init__(discovery, command=[], min_np=min_np, **kw)
        self._fn = fn
        self._launcher = launcher
        self._results = {}
        self._results_lock = threading.Lock()

    def _execute_worker(self, worker, env):
        rc, result = self._launcher(worker, env, self._fn,
                                    [worker.kill_event, self._shutdown])
        if rc == 0 and not worker.driver_killed:
            with self._results_lock:
                self._results[worker.worker_id] = result
        return rc

    def results(self):
        with self._results_lock:
            return dict(self._results)


class ElasticRayExecutor:
    """Reference-shaped elastic executor: construct with discovery +
    fleet bounds, then ``run(fn)`` blocks until the job completes and
    returns the successful workers' results.

    ``launcher`` is the actor backend — default is real Ray actors;
    tests inject thread-fakes (``(worker, env, fn, events) ->
    (rc, result)``).
    """

    def __init__(self, discovery=None, min_np=1, max_np=None,
                 cpus_per_worker=1, gpus_per_worker=0, env_vars=None,
                 override_discovery=None, launcher=None,
                 poll_interval=2.0, start_timeout=60, verbose=False):
        self.discovery = override_discovery or discovery
        if self.discovery is None:
            self.discovery = RayHostDiscovery(
                cpus_per_worker=cpus_per_worker,
                gpus_per_worker=gpus_per_worker)
        self.min_np = min_np
        self.max_np = max_np
        # Stringify: these land in os.environ.update on the actor,
        # which raises on non-str values (users pass ints routinely,
        # e.g. OMP_NUM_THREADS=4).
        self.env_vars = {str(k): str(v)
                         for k, v in (env_vars or {}).items()}
        self._launcher = launcher
        self._cpus = cpus_per_worker
        self._gpus = gpus_per_worker
        self._poll_interval = poll_interval
        self._start_timeout = start_timeout
        self._verbose = verbose
        self.driver = None

    def start(self):
        """No-op kept for reference API parity (`start(); run(fn)`) —
        the fleet cannot spawn before ``run`` supplies the worker fn."""

    def run(self, fn):
        """Run ``fn`` elastically; blocks until the job completes and
        returns the successful workers' results (sorted by worker id).

        The worker-fn contract is the same on every backend: ``fn`` is
        called with the HOROVOD_* env dict (rendezvous address, worker
        id, hostname); real Ray actors additionally apply it to
        ``os.environ`` first, so ``hvd.init()`` works unmodified.
        """
        launcher = self._launcher or _ray_actor_launcher(
            cpus_per_worker=self._cpus, gpus_per_worker=self._gpus,
            extra_env_keys=self.env_vars, verbose=self._verbose)
        self.driver = _ElasticRayDriver(
            self.discovery, fn, launcher, min_np=self.min_np,
            max_np=self.max_np, env=self.env_vars,
            poll_interval=self._poll_interval,
            start_timeout=self._start_timeout, verbose=self._verbose)
        try:
            self.driver.start()
            rc = self.driver.wait_for_completion()
        finally:
            # stop() also runs when start() itself times out waiting
            # for min_np slots — the rendezvous HTTP server was already
            # live from __init__ and must not leak.
            results = self.driver.results()
            self.driver.stop()
        if rc != 0:
            raise RuntimeError(
                f"elastic ray job failed (exit code {rc})")
        return [results[wid] for wid in sorted(results)]
