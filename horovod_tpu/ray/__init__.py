"""Ray integration.

Reference analog: ``horovod/ray/`` (``RayExecutor`` in runner.py +
placement-group colocation in strategy.py): workers are Ray actors, one
per slot, placed by a colocation strategy; the executor wires the
HOROVOD_* env across them and drives ``execute``/``run`` calls.
"""

from horovod_tpu.ray.elastic import (  # noqa: F401
    ElasticRayExecutor,
    RayHostDiscovery,
)
from horovod_tpu.ray.runner import RayExecutor  # noqa: F401
from horovod_tpu.ray.strategy import (  # noqa: F401
    ColocationStrategy,
    PackStrategy,
    SpreadStrategy,
)
