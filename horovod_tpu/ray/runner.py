"""``RayExecutor`` — Horovod workers as Ray actors.

Reference analog: ``horovod/ray/runner.py``: ``start()`` creates a
placement group per the strategy, spawns one worker actor per slot,
assigns ranks grouped by host (local_rank = position within host),
exports the HOROVOD_* env to each actor, and ``run``/``execute`` invoke a
fn on all workers simultaneously, returning per-rank results.
"""

import collections
import socket


def _require_ray():
    try:
        import ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray.RayExecutor requires the 'ray' package, which "
            "is not installed in this environment.") from e
    return ray


def plan_ranks(worker_hosts):
    """Rank layout from a list of (worker_index, hostname): ranks are
    contiguous per host (reference: runner.py host grouping). Pure &
    unit-testable. Returns {worker_index: env_dict}."""
    by_host = collections.OrderedDict()
    for idx, host in worker_hosts:
        by_host.setdefault(host, []).append(idx)
    size = len(worker_hosts)
    cross_size = len(by_host)
    envs = {}
    rank = 0
    for cross_rank, (host, members) in enumerate(by_host.items()):
        for local_rank, idx in enumerate(members):
            envs[idx] = {
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(size),
                "HOROVOD_LOCAL_RANK": str(local_rank),
                "HOROVOD_LOCAL_SIZE": str(len(members)),
                "HOROVOD_CROSS_RANK": str(cross_rank),
                "HOROVOD_CROSS_SIZE": str(cross_size),
            }
            rank += 1
    return envs


class RayExecutor:
    """Reference-shaped executor: start() / run(fn) / execute(fn) /
    shutdown()."""

    def __init__(self, strategy=None, num_workers=None, cpus_per_worker=1,
                 gpus_per_worker=0, env_vars=None, use_current_placement_group
                 =False):
        from horovod_tpu.ray.strategy import PackStrategy

        if strategy is None:
            if num_workers is None:
                raise ValueError("need strategy= or num_workers=")
            strategy = PackStrategy(num_workers,
                                    cpus_per_worker=cpus_per_worker,
                                    gpus_per_worker=gpus_per_worker)
        self.strategy = strategy
        self.env_vars = dict(env_vars or {})
        self._workers = []
        self._pg = None

    def start(self):
        ray = _require_ray()
        from ray.util.placement_group import placement_group

        self._pg = placement_group(
            self.strategy.bundles(),
            strategy=self.strategy.placement_strategy)
        ray.get(self._pg.ready())

        @ray.remote(num_cpus=self.strategy.cpus_per_worker,
                    num_gpus=self.strategy.gpus_per_worker)
        class Worker:
            def __init__(self, index):
                self.index = index

            def hostname(self):
                return socket.gethostname()

            def node_ip(self):
                import ray

                return ray.util.get_node_ip_address()

            def set_env(self, env):
                import os

                os.environ.update(env)

            def execute(self, fn, args, kwargs):
                return fn(*args, **kwargs)

        self._workers = [
            Worker.options(placement_group=self._pg,
                           placement_group_bundle_index=i).remote(i)
            for i in range(self.strategy.num_workers)]

        hosts = ray.get([w.hostname.remote() for w in self._workers])
        envs = plan_ranks(list(enumerate(hosts)))
        # Controller bootstrap: rank 0's listen socket binds inside the
        # rank-0 ACTOR, so the address must be that actor's node IP (not
        # the Ray driver's).
        from horovod_tpu.runner import util

        rank0_worker = next(
            i for i, e in envs.items() if e["HOROVOD_RANK"] == "0")
        addr = ray.get(self._workers[rank0_worker].node_ip.remote())
        port = util.free_port()
        ray.get([
            w.set_env.remote({**envs[i], **self.env_vars,
                              "HOROVOD_CONTROLLER_ADDR": addr,
                              "HOROVOD_CONTROLLER_PORT": str(port)})
            for i, w in enumerate(self._workers)])

    def run(self, fn, args=None, kwargs=None):
        """Run fn on every worker simultaneously; list of results by rank."""
        ray = _require_ray()
        return ray.get([w.execute.remote(fn, tuple(args or ()),
                                         dict(kwargs or {}))
                        for w in self._workers])

    # Reference exposes both names.
    execute = run

    def run_remote(self, fn, args=None, kwargs=None):
        """Async variant: returns ray ObjectRefs (reference parity)."""
        return [w.execute.remote(fn, tuple(args or ()), dict(kwargs or {}))
                for w in self._workers]

    def shutdown(self):
        ray = _require_ray()
        for w in self._workers:
            ray.kill(w)
        self._workers = []
        if self._pg is not None:
            from ray.util.placement_group import remove_placement_group

            remove_placement_group(self._pg)
            self._pg = None
