"""horovod_tpu.serving — the continuous-batching decode service.

The first REQUEST-driven (not step-driven) consumer of the runtime:
a paged KV cache (fixed-size block pool + free-list allocator +
optional int8 block format — kvcache.py), a continuous-batching
scheduler (admit/evict per decode step against a token budget —
scheduler.py), a static-shape decode engine over
``models.generate.llama_decode_step`` (engine.py), and the elastic
serving loop with prefill/decode disaggregation over the CRC-framed
chunked host ring (service.py). Every request's lifecycle is traced
through the core event ring (rid-tagged ``request`` events ->
:mod:`horovod_tpu.telemetry.reqtrace` span ledgers,
``report.py --requests`` tail attribution, the ``/requests`` live
endpoint). ``make serve-smoke`` kills a decode rank mid-trace and pins
that every admitted request still completes, token-identically, on
the survivors — and that the stitched request chains attribute the
latency cliff to ``fault_requeue``, gap-free. docs/serving.md has the
full semantics table.

Reference analog: none — upstream Horovod is a training runtime; this
lane is what ROADMAP item 1 calls the path from "fast kernel" to
"millions of users".
"""

from horovod_tpu.serving.kvcache import (  # noqa: F401
    OutOfBlocks,
    PagedKVCache,
    quantize_blocks,
)
from horovod_tpu.serving.scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    Request,
    Sequence,
    latency_summary,
    poisson_trace,
)
from horovod_tpu.serving.engine import DecodeEngine  # noqa: F401
from horovod_tpu.serving.service import (  # noqa: F401
    ServingLoop,
    serving_signals,
)
