"""The elastic serving loop: prefill/decode disaggregation on the
self-healing runtime.

Topology (derived fresh every round, so elastic resizes just work):
rank 0 is the FRONTEND — it owns the arrival trace, runs prefill, and
scoreboards completions; every other rank is a DECODE rank running a
local :class:`DecodeEngine`. At size 1 the frontend decodes too (the
all-in-one lane — also exactly what a 2-rank world collapses to when
its decode rank dies).

One round = one lockstep beat of the world:

1. frontend admits due arrivals, prefills (``llama_prefill``) and packs
   each prompt's KV into POOL-FORMAT blocks — int8 + per-block scales
   when quantized, so the wire ships the narrow format and the decode
   rank adopts bytes verbatim (``write_raw``): quantize once, at the
   source, per EQuARX;
2. a control allgather (pickled, CRC-framed chunked host ring like
   every eager collective): frontend -> {assignments, cancels,
   shutdown}; decode ranks -> {acks, rejects, completions, stats};
3. a uint8 alltoall ships the KV payloads to their target ranks (the
   splits vector routes; skipped by agreement when nothing was
   assigned this round);
4. decode ranks adopt new sequences and run ``steps_per_round``
   continuous-batching steps; the frontend decodes its own batch when
   it is in the decode set.

ELASTIC CONTRACT (the chaos acceptance): any typed collective failure
(``HorovodPeerFailureError`` — a SIGKILLed decode rank's EOF) is caught
at the round boundary; survivors re-form IN PLACE via
``hvd.elastic.reset()`` (r12/r14 machinery — python state, including
every survivor's pool and running batch, survives), and the frontend
re-queues the dead rank's in-flight requests plus anything assigned but
never acked. Greedy decoding + the engine's static-shape determinism
make the replay token-identical, so a request's output does not depend
on whether its first home died (pinned by
tests/parallel/test_serving_elastic.py and ``make serve-smoke``).
A re-queued rid that a survivor ALSO still holds (assigned, admitted,
ack lost with the round) is cancelled on the survivor via the control
message — first completion wins, nothing double-serves.

Load-balancer integration: the per-rank debug server's ``/healthz``
(r15) carries the serving field set — queue depth, in-flight
sequences, kv blocks free/total, rolling p50/p99 latency, served
count, and eviction amplification — via :func:`serving_signals`
(module-level registry; sentinel defaults when no service is live).
Request-scoped tracing (r19, docs/serving.md "Request lifecycle &
tracing"): every lifecycle transition records a rid-tagged ``request``
event through :mod:`horovod_tpu.telemetry.reqtrace`, which also feeds
the ``/requests`` live in-flight endpoint; offline,
``report.py --requests`` stitches per-rank dumps into gap-free
per-request span chains and decomposes the tail-latency band.
"""

import time
from collections import deque

import numpy as np

from horovod_tpu.serving.engine import DecodeEngine
from horovod_tpu.serving.kvcache import quantize_blocks
from horovod_tpu.serving.scheduler import (
    Request,
    Sequence,
    latency_summary,
)
from horovod_tpu.telemetry import reqtrace

# The live service in this process (serving_signals / /healthz).
_live = None


def serving_signals():
    """The /healthz serving fields — sentinel defaults when no service
    is live (ONE source of truth:
    ``telemetry.autoscale.SERVING_SIGNAL_DEFAULTS``; the field SET is
    pinned in tests/parallel/test_observability.py)."""
    from horovod_tpu.telemetry.autoscale import SERVING_SIGNAL_DEFAULTS

    if _live is not None:
        try:
            return _live.signals()
        except Exception:  # noqa: BLE001 — health must answer anyway
            pass
    return dict(SERVING_SIGNAL_DEFAULTS)


class ServingLoop:
    """Round-based elastic serving over a request trace.

    ``trace`` is a list of :class:`Request` (see ``poisson_trace``);
    arrival times are honored against a wall clock started at
    :meth:`run`. ``round_hook(loop, round_idx)`` runs at the top of
    every round on every rank — the chaos tests' kill injection point.
    """

    def __init__(self, params, config, trace=(), *, block_size=16,
                 n_blocks=256, max_batch=8, max_context=512,
                 token_budget=None, quantized=False, steps_per_round=4,
                 prefill_per_round=4, max_rounds=100000,
                 time_scale=1.0, round_hook=None):
        self.engine = DecodeEngine(
            params, config, block_size=block_size, n_blocks=n_blocks,
            max_batch=max_batch, max_context=max_context,
            token_budget=token_budget, quantized=quantized)
        self.params = params
        self.config = config
        self.trace = sorted(trace, key=lambda r: r.arrival_t)
        self.quantized = bool(quantized)
        self.steps_per_round = int(steps_per_round)
        self.prefill_per_round = int(prefill_per_round)
        self.max_rounds = int(max_rounds)
        self.time_scale = float(time_scale)  # <1 compresses the trace
        self.round_hook = round_hook
        # Every request must fit the engine's static decode shape —
        # reject at construction, not deep inside a decode rank's
        # gather (where it would read as a fault and cascade).
        for req in self.trace:
            if (len(req.prompt) + req.max_new_tokens
                    > self.engine.s_pad):
                raise ValueError(
                    f"request {req.rid}: prompt {len(req.prompt)} + "
                    f"max_new {req.max_new_tokens} exceeds max_context "
                    f"{self.engine.s_pad}")
        # Frontend state.
        self._pending = []            # Requests awaiting assignment
        self._assigned = {}           # rid -> {req, rank, acked}
        self._cancel = []             # rids to cancel on survivors
        self._completed = {}          # rid -> np.ndarray tokens
        self._latency = {}            # rid -> seconds
        # Rolling completion-latency window (newest _LAT_WINDOW): the
        # /healthz serving_p50/p99_ms pressure signal — percentiles
        # over recent completions, not the whole run, so the
        # autoscaler sees CURRENT latency, not history-diluted.
        self._lat_window = deque(maxlen=128)
        self.requests_served = 0
        # rids the elastic path re-queued after a peer fault — the
        # chaos smoke checks the stitched chains' fault_requeue set
        # against exactly this.
        self.requeued_rids = set()
        self._req_by_rid = {r.rid: r for r in self.trace}
        self._arrival_idx = 0
        # Decode-rank OUTBOXES: report payloads stay here until the
        # frontend provably PROCESSED them — receiving the frontend's
        # round-R+1 control is the proof for round R's reports (the
        # frontend only enters R+1 after applying R), so items retire
        # two-stage: sent -> inflight -> retired at the NEXT successful
        # allgather. A fault at any point keeps them for re-send; the
        # frontend's handlers are idempotent (duplicate acks/rejects
        # no-op, first completion wins).
        self._ack_buf = []
        self._reject_buf = []
        self._done_outbox = {}
        self._inflight = {"acks": [], "rejects": [], "done": []}
        self.faults_survived = 0
        self.served_local = 0         # completions this rank decoded
        self.rounds = 0
        # Collective names are serve.<epoch>.<epoch_round>: the
        # counter advances only on a fully-successful round and RESETS
        # on recovery, so survivors that observed a fault at different
        # rounds re-align at (new epoch, 0) instead of negotiating
        # mismatched tensor names forever.
        self._epoch_round = 0
        self._rr = 0                  # round-robin assignment cursor

    # ---- signals -------------------------------------------------------

    def signals(self):
        sig = self.engine.scheduler.signals()
        sig["serving_queue_depth"] += len(self._pending)
        # Rolling latency pressure + served count. Decode ranks have no
        # scoreboard (latency is measured where the request's arrival
        # clock lives — the frontend), so their window is empty and
        # served counts what THIS rank decoded.
        lat = latency_summary(list(self._lat_window))
        sig["serving_p50_ms"] = lat["p50_ms"]
        sig["serving_p99_ms"] = lat["p99_ms"]
        sig["requests_served"] = (self.requests_served
                                  or self.served_local)
        return sig

    # ---- helpers -------------------------------------------------------

    def _basics(self):
        from horovod_tpu.common.basics import HorovodBasics

        return HorovodBasics()

    def _decode_ranks(self, size):
        return list(range(1, size)) if size > 1 else [0]

    def _pack_assignment(self, req):
        """Prefill one request and freeze its wire payload: pool-format
        blocks (quantized at the SOURCE when the pool is int8) plus the
        metadata a decode rank needs to adopt them."""
        reqtrace.record_request("prefill", req.rid,
                                aux=len(req.prompt))
        first, k, v = self.engine.prefill(req)
        bs = self.engine.pool.block_size
        k_q, v_q, k_s, v_s = quantize_blocks(
            k, v, bs, quantized=self.quantized,
            dtype=self.engine.pool.k_pool.dtype)
        payload = [k_q.tobytes(), v_q.tobytes()]
        if self.quantized:
            payload += [k_s.tobytes(), v_s.tobytes()]
        meta = {"rid": req.rid, "prompt": np.asarray(req.prompt,
                                                    np.int32).tolist(),
                "first": int(first), "max_new": int(req.max_new_tokens),
                "n_blocks": int(k_q.shape[0]),
                "nbytes": sum(len(p) for p in payload)}
        # Packed: the payload is (about to be) in flight to its decode
        # rank — kv_ship lasts until that rank's adoption transition
        # (or, if the rank dies holding it, until fault_requeue).
        reqtrace.record_request("kv_ship", req.rid, aux=meta["nbytes"])
        return meta, b"".join(payload)

    def _adopt_assignment(self, meta, payload):
        """Decode-rank side of :meth:`_pack_assignment`: allocate local
        blocks, adopt the shipped bytes, register the sequence. Returns
        True, or False when the local pool is full (NACK)."""
        from horovod_tpu.serving.kvcache import OutOfBlocks

        pool = self.engine.pool
        c = self.config
        n = meta["n_blocks"]
        bs = pool.block_size
        store = pool.k_pool.dtype
        shape = (n, c.n_layers, c.n_kv_heads, bs, c.head_dim)
        k_q = np.frombuffer(payload, store,
                            count=int(np.prod(shape))).reshape(shape)
        off = k_q.nbytes
        v_q = np.frombuffer(payload, store, count=int(np.prod(shape)),
                            offset=off).reshape(shape)
        off += v_q.nbytes
        k_s = v_s = None
        if self.quantized:
            sshape = (n, c.n_layers, c.n_kv_heads)
            k_s = np.frombuffer(payload, np.float32,
                                count=int(np.prod(sshape)),
                                offset=off).reshape(sshape)
            off += k_s.nbytes
            v_s = np.frombuffer(payload, np.float32,
                                count=int(np.prod(sshape)),
                                offset=off).reshape(sshape)
        try:
            blocks = pool.alloc(n)
        except OutOfBlocks:
            return False
        pool.write_raw(blocks, k_q, v_q, k_s, v_s)
        req = Request(rid=meta["rid"],
                      prompt=np.asarray(meta["prompt"], np.int32),
                      max_new_tokens=meta["max_new"])
        seq = Sequence(req=req, blocks=blocks,
                       generated=[meta["first"]])
        if seq.done:  # max_new == 1: the prefill token finished it
            pool.free(blocks)
            seq.blocks = []
            self.engine.scheduler.completed[seq.rid] = seq
            self.engine.scheduler.useful_tokens += len(seq.generated)
            reqtrace.record_request("done", seq.rid,
                                    aux=len(seq.generated))
        else:
            self.engine.adopt_remote(seq)
        return True

    def _admit_arrivals(self, now):
        while (self._arrival_idx < len(self.trace)
               and self.trace[self._arrival_idx].arrival_t
               * self.time_scale <= now):
            req = self.trace[self._arrival_idx]
            reqtrace.record_request("queued", req.rid,
                                    aux=len(req.prompt))
            self._pending.append(req)
            self._arrival_idx += 1

    def _local_admit(self, reqs):
        """Frontend-as-decoder lane (size 1): the same prefill+write
        path a remote adoption takes, through the engine's local
        scheduler — numerics identical to the shipped path because the
        pool write IS the quantizer."""
        for req in reqs:
            self.engine.submit(req)

    # ---- fault recovery ------------------------------------------------

    def _recover(self, old_size, old_rank):
        """Re-form over survivors and re-route orphaned work. Returns
        the (new_rank, new_size) of this process."""
        from horovod_tpu.common import elastic as hvd_elastic

        alive = hvd_elastic.survivors()  # old-rank ids, rank-consistent
        if old_rank != 0 and alive is not None and 0 not in alive:
            # The frontend owns the trace scoreboard (arrivals,
            # assignments, completions) — no survivor can reconstruct
            # it, and a decode rank silently promoting itself to rank 0
            # would replay the whole trace against its own half-decoded
            # state. Fail loudly instead; restarting the service is the
            # recovery (the driverless elastic core has the same
            # rank-0-must-survive constraint, docs/elastic.md).
            raise RuntimeError(
                "frontend (rank 0) died; the serving loop cannot "
                "re-form without its scoreboard — restart the service")
        hvd_elastic.reset()
        b = self._basics()
        self.faults_survived += 1
        # Survivors may have observed the fault at DIFFERENT rounds;
        # every one re-aligns at (new epoch, round 0). Nothing inflight
        # is confirmed anymore — keep it all in the outboxes for
        # re-send (idempotent on the frontend).
        self._epoch_round = 0
        self._inflight = {"acks": [], "rejects": [], "done": []}
        if old_rank == 0:
            if alive is None:
                # Suspicion-only fallback (full re-init): no agreed
                # dead set — conservatively treat every un-acked or
                # remote assignment as orphaned.
                alive = [0]
            dead = [r for r in range(old_size) if r not in alive]
            requeue = []
            for rid, rec in list(self._assigned.items()):
                target = rec["rank"]
                if target in dead or not rec["acked"]:
                    requeue.append(rec["req"])
                    if target not in dead:
                        # May have been admitted with the ack lost in
                        # the dying round: cancel the survivor's copy
                        # so the replay can't double-serve.
                        self._cancel.append(rid)
                    del self._assigned[rid]
                else:
                    # Surviving decode ranks renumber compactly.
                    rec["rank"] = alive.index(target)
            # Oldest arrivals first, ahead of anything still pending.
            requeue.sort(key=lambda r: r.arrival_t)
            for req in requeue:
                # The orphan's extra latency books to fault_requeue
                # from THIS instant until its replacement prefill
                # starts — the span the chaos smoke's tail report
                # attributes the latency cliff to. The dead rank also
                # re-prefills the prompt, so it counts as recompute.
                reqtrace.record_request("fault_requeue", req.rid,
                                        aux=len(req.prompt))
                self.requeued_rids.add(req.rid)
                self.engine.scheduler.recomputed_prefill_tokens += \
                    len(req.prompt)
            self._pending = requeue + self._pending
        return b.rank(), b.size()

    # ---- the loop ------------------------------------------------------

    def run(self):
        """Drive the trace to completion. Rank 0 returns the serving
        report (completions, latency percentiles, sustained tok/s);
        decode ranks return their local engine stats."""
        global _live
        from horovod_tpu.common import elastic as hvd_elastic
        from horovod_tpu.common.exceptions import HorovodInternalError

        b = self._basics()
        _live = self
        t0 = time.monotonic()
        decode_clock = 0.0
        try:
            while True:
                rank, size = b.rank(), b.size()
                if self.round_hook is not None:
                    self.round_hook(self, self.rounds)
                try:
                    done = self._round(b, rank, size,
                                       time.monotonic() - t0)
                except HorovodInternalError:
                    rank, size = self._recover(size, rank)
                    continue
                self.rounds += 1
                self._epoch_round += 1
                if done:
                    break
                if self.rounds > self.max_rounds:
                    raise RuntimeError(
                        f"serving loop: no convergence after "
                        f"{self.max_rounds} rounds")
            decode_clock = time.monotonic() - t0
        finally:
            _live = None
        if b.rank() != 0:
            return {"rank": b.rank(), "steps": self.engine.steps,
                    "served": self.served_local,
                    "evictions": self.engine.scheduler.evictions}
        total_tokens = int(sum(
            len(t) - len(self._rid_req(rid).prompt)
            for rid, t in self._completed.items()))
        lat = latency_summary(list(self._latency.values()))
        return {
            "completed": {int(r): np.asarray(t)
                          for r, t in self._completed.items()},
            "requests": len(self.trace),
            "served": len(self._completed),
            "generated_tokens": total_tokens,
            "wall_s": round(decode_clock, 4),
            "sustained_tok_s": round(total_tokens / decode_clock, 2)
            if decode_clock > 0 else 0.0,
            "faults_survived": self.faults_survived,
            "evictions": self.engine.scheduler.evictions,
            "rounds": self.rounds,
            **lat,
        }

    def _rid_req(self, rid):
        return self._req_by_rid[rid]

    def _score_completion(self, rid, now, remote=False):
        """Frontend scoreboard entry for one completed rid: measured
        latency, the rolling /healthz window, and the chain-terminal
        ``done`` transition (the instant the user-visible answer
        exists — a decode rank's own ``done`` marks local completion;
        this one closes the request's span chain). ``remote`` books
        the generated tokens as useful on the FRONTEND's scheduler
        too — its amplification ratio must describe the service
        (it holds the fault-requeue recompute counter), not divide a
        fleet-wide numerator by a local-only denominator; local
        completions were already counted by ``scheduler.complete``."""
        lat = max(now - self._rid_req(rid).arrival_t * self.time_scale,
                  0.0)
        self._latency[rid] = lat
        self._lat_window.append(lat)
        self.requests_served += 1
        generated = (len(self._completed[rid])
                     - len(self._rid_req(rid).prompt))
        if remote:
            self.engine.scheduler.useful_tokens += generated
        reqtrace.record_request("done", rid, aux=generated)

    def _round(self, b, rank, size, now):
        from horovod_tpu.common import elastic as hvd_elastic

        epoch = b.epoch() if b.is_initialized() else 0
        tag = f"serve.{epoch}.{self._epoch_round}"
        decode_ranks = self._decode_ranks(size)

        # -- frontend: admit + prefill + assign --------------------------
        ctl = {}
        packed = {}                   # target rank -> [(meta, bytes)]
        if rank == 0:
            self._admit_arrivals(now)
            if size == 1:
                self._local_admit(self._pending)
                self._pending = []
            assigns = []
            if size > 1:
                budget = self.prefill_per_round
                while self._pending and budget > 0:
                    req = self._pending.pop(0)
                    target = decode_ranks[self._rr % len(decode_ranks)]
                    self._rr += 1
                    meta, payload = self._pack_assignment(req)
                    meta["target"] = target
                    packed.setdefault(target, []).append(
                        (meta, payload))
                    assigns.append(meta)
                    self._assigned[req.rid] = {
                        "req": req, "rank": target, "acked": False}
                    budget -= 1
            all_done = (self._arrival_idx >= len(self.trace)
                        and not self._pending and not self._assigned
                        and (size > 1 or (
                            not self.engine.scheduler.waiting
                            and not self.engine.scheduler.running)))
            ctl = {"assign": assigns, "cancel": list(self._cancel),
                   "shutdown": bool(all_done)}
        else:
            ctl = {"acks": list(self._ack_buf),
                   "rejects": list(self._reject_buf),
                   "done": self._done_out(),
                   "stats": self.engine.scheduler.signals()}

        # -- collectives (the only wire section => the only fault
        # -- surface; _recover handles a typed failure of either) --------
        if size > 1:
            ctls = hvd_elastic._allgather_object(ctl, name=f"{tag}.ctl")
            front = ctls[0]
            if rank != 0:
                self._retire_inflight(ctl)
        else:
            front = ctl if rank == 0 else {"assign": [], "cancel": [],
                                           "shutdown": True}
            ctls = [ctl]

        # Cancels apply BEFORE payload adoption: they target copies
        # admitted in EARLIER rounds, and a rid that is cancelled and
        # reassigned in one control message must drop the stale copy
        # while keeping this round's fresh adoption. The reversed
        # ordering is the seeded serving.cancel_after_adopt mutant in
        # analysis/model/serving.py — hvdcheck finds the lost-request
        # interleaving in 3 steps.
        if rank in decode_ranks:
            for rid in front.get("cancel", ()):
                self.engine.scheduler.drop(rid)

        if size > 1 and front["assign"]:
            # KV payloads ride one alltoall, by agreement non-empty.
            self._exchange_payloads(b, rank, size, front, packed, tag)

        # -- apply control ----------------------------------------------
        if rank == 0 and size > 1:
            for peer_rank, peer in enumerate(ctls[1:], start=1):
                self._apply_decode_report(peer_rank, peer, now)
        if rank == 0:
            # Retire only the cancels that RODE this round's control
            # (at ANY world size — a size-1 survivor must not re-apply
            # them forever): _apply_decode_report may have appended
            # fresh ones, which must survive to the next round.
            sent = set(ctl["cancel"])
            self._cancel = [c for c in self._cancel if c not in sent]
        if rank in decode_ranks:
            for _ in range(self.steps_per_round):
                self.engine.step()
        if rank == 0 and size == 1:
            # Collect local completions straight off the engine.
            for rid, seq in list(self.engine.scheduler.completed.items()):
                if rid not in self._completed:
                    self._completed[rid] = seq.tokens
                    self._score_completion(rid, now)
        if rank == 0 and not front.get("shutdown"):
            idle = (not self._pending
                    and self._arrival_idx < len(self.trace)
                    and (size > 1
                         or not self.engine.scheduler.running))
            if idle:
                # Idle beat: let the trace clock advance.
                time.sleep(0.002)
        return bool(front.get("shutdown"))

    # -- decode-rank report bookkeeping ---------------------------------

    def _done_out(self):
        """Move fresh completions into the outbox and return the WHOLE
        outbox — items re-send every round until retired. Draining at
        send instead is the seeded serving.retire_on_send mutant in
        analysis/model/serving.py: a fault mid-round then loses the
        completion forever (no-lost-completion invariant)."""
        for rid, seq in list(self.engine.scheduler.completed.items()):
            self._done_outbox[int(rid)] = seq.tokens.tolist()
            del self.engine.scheduler.completed[rid]
            self.served_local += 1
        return dict(self._done_outbox)

    def _retire_inflight(self, sent_ctl):
        """A successful allgather proves the frontend finished the
        PREVIOUS round (it only builds this round's control after
        applying the last one's reports): retire what was inflight,
        and promote this round's payload to inflight."""
        for rid in self._inflight["acks"]:
            if rid in self._ack_buf:
                self._ack_buf.remove(rid)
        for rid in self._inflight["rejects"]:
            if rid in self._reject_buf:
                self._reject_buf.remove(rid)
        for rid in self._inflight["done"]:
            self._done_outbox.pop(rid, None)
        self._inflight = {"acks": list(sent_ctl["acks"]),
                          "rejects": list(sent_ctl["rejects"]),
                          "done": list(sent_ctl["done"])}

    def _apply_decode_report(self, peer_rank, peer, now):
        for rid in peer.get("acks", ()):
            rec = self._assigned.get(rid)
            if rec is not None and rec["rank"] == peer_rank:
                rec["acked"] = True
        for rid in peer.get("rejects", ()):
            rec = self._assigned.pop(rid, None)
            if rec is not None:
                # NACK (decode pool full): back to the head of the
                # line — a fresh queued span until the next prefill.
                reqtrace.record_request(
                    "queued", rid, aux=len(rec["req"].prompt))
                self._pending.insert(0, rec["req"])
        for rid, tokens in peer.get("done", {}).items():
            rid = int(rid)
            if rid in self._completed:
                continue  # duplicate (re-queued then both finished)
            self._completed[rid] = np.asarray(tokens, np.int32)
            self._score_completion(rid, now, remote=True)
            # Duplicate guard: a re-queued copy may still be pending
            # here or re-assigned to another rank — drop/cancel it so
            # nothing double-serves (first completion wins).
            rec = self._assigned.pop(rid, None)
            if rec is not None and rec["rank"] != peer_rank:
                self._cancel.append(rid)
            self._pending = [r for r in self._pending if r.rid != rid]

    def _exchange_payloads(self, b, rank, size, front, packed, tag):
        from horovod_tpu.common import eager_ops

        sizes = np.zeros(size, np.int64)
        chunks = []
        if rank == 0:
            for target in range(size):
                for meta, payload in packed.get(target, ()):
                    sizes[target] += len(payload)
                    chunks.append(payload)
        buf = np.frombuffer(b"".join(chunks), np.uint8) if chunks \
            else np.zeros(0, np.uint8)
        out = eager_ops.alltoall_async(
            buf, sizes.tolist(), f"{tag}.kv").synchronize()
        if rank == 0:
            return
        # Everything received came from rank 0, packed in assignment
        # order for THIS rank.
        mine = [m for m in front["assign"] if m["target"] == rank]
        data = out.tobytes()
        off = 0
        for meta in mine:
            payload = data[off:off + meta["nbytes"]]
            off += meta["nbytes"]
            if self._adopt_assignment(meta, payload):
                self._ack_buf.append(meta["rid"])
            else:
                self._reject_buf.append(meta["rid"])
