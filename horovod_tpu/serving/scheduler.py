"""Continuous-batching request scheduler: admit/evict per decode step.

The serving lane's control brain, deliberately pure bookkeeping (no
model, no wire — testable with a bare :class:`PagedKVCache`): requests
wait in an arrival-ordered queue; each decode step the scheduler ADMITS
from the front while three budgets hold — batch slots, a token budget
(the sum of live context lengths, the knob that bounds per-step
attention work), and pool blocks for prompt+1 — and GROWS running
sequences one block at a time as they cross block boundaries. When the
pool runs dry mid-step, the YOUNGEST running sequence is evicted
(LIFO preemption: the oldest request is closest to completing, evicting
it wastes the most work), its blocks freed and the request re-queued at
the FRONT of the waiting line for a later re-prefill — nothing is ever
dropped. The same re-queue primitive serves the elastic path: a dead
decode rank's sequences re-enter through it (serving/service.py).

Greedy decoding makes eviction and elastic re-queue SAFE: re-prefilling
the same prompt reproduces the identical continuation, so a preempted
or orphaned request completes with token-identical output (pinned by
tests/parallel/test_serving_elastic.py).
"""

from collections import deque
from dataclasses import dataclass, field

import numpy as np

from horovod_tpu.serving.kvcache import OutOfBlocks
from horovod_tpu.telemetry import reqtrace


@dataclass
class Request:
    """One decode request (prompt tokens in, greedy continuation out)."""

    rid: int
    prompt: np.ndarray            # [T] int32
    max_new_tokens: int
    arrival_t: float = 0.0        # seconds on the trace clock


@dataclass
class Sequence:
    """A running request: its block table and generated tail."""

    req: Request
    blocks: list = field(default_factory=list)
    generated: list = field(default_factory=list)  # incl. the prefill
    #                                                first token

    @property
    def rid(self):
        return self.req.rid

    @property
    def length(self):
        """Logical sequence length (prompt + generated so far)."""
        return len(self.req.prompt) + len(self.generated)

    @property
    def cached(self):
        """Cache slots actually HOLDING K/V: the newest generated
        token is the decode step's input — its K/V is computed (and
        written at position ``cached``) by that step, so it is always
        one behind ``length`` while decoding."""
        return len(self.req.prompt) + max(len(self.generated) - 1, 0)

    @property
    def done(self):
        return len(self.generated) >= self.req.max_new_tokens

    @property
    def tokens(self):
        return np.concatenate([
            np.asarray(self.req.prompt, np.int32),
            np.asarray(self.generated, np.int32)])


def poisson_trace(n, rps, seed=0, prompt_len=(4, 24),
                  max_new=(4, 24), vocab_size=256):
    """A deterministic Poisson arrival trace: ``n`` requests with
    exponential inter-arrival gaps at ``rps`` requests/second, ragged
    prompt lengths and generation budgets — the serving bench's (and
    chaos smoke's) offered load."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for rid in range(n):
        t += rng.exponential(1.0 / rps)
        tlen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        out.append(Request(
            rid=rid,
            prompt=rng.integers(0, vocab_size, size=tlen).astype(np.int32),
            max_new_tokens=int(rng.integers(max_new[0], max_new[1] + 1)),
            arrival_t=t))
    return out


class ContinuousBatchingScheduler:
    """Admit/evict against a :class:`PagedKVCache` and a token budget.

    The pool may be shared with other components; the scheduler only
    allocates/frees through it. ``token_budget`` caps the sum of live
    context lengths across running sequences (attention work per step);
    ``max_batch`` caps batch slots (the decode step's static B).
    """

    def __init__(self, pool, max_batch=8, token_budget=4096):
        self.pool = pool
        self.max_batch = int(max_batch)
        self.token_budget = int(token_budget)
        self.waiting = deque()
        self.running = []            # admission order: oldest first
        self.completed = {}          # rid -> Sequence
        self.evictions = 0
        # Eviction amplification (docs/serving.md): prompt tokens that
        # must be prefilled AGAIN because their first pass was thrown
        # away (LIFO eviction or elastic fault re-queue), against the
        # generated tokens that actually reached a completion. The
        # ratio is the pool-thrash signal /healthz and the Prometheus
        # exporter carry.
        self.recomputed_prefill_tokens = 0
        self.useful_tokens = 0

    # ---- signals -------------------------------------------------------

    @property
    def queue_depth(self):
        return len(self.waiting)

    @property
    def inflight(self):
        return len(self.running)

    def _live_tokens(self):
        # +1: each running sequence is about to fill one more slot.
        return sum(s.cached + 1 for s in self.running)

    # ---- admission -----------------------------------------------------

    def submit(self, req):
        reqtrace.record_request("queued", req.rid, aux=len(req.prompt))
        self.waiting.append(req)

    def requeue_front(self, reqs):
        """Put evicted/orphaned requests back at the head of the line
        (they already waited once)."""
        for r in reversed(list(reqs)):
            self.waiting.appendleft(r)

    def admit(self):
        """Admit from the waiting queue while every budget holds.
        Returns the newly admitted :class:`Sequence` list — the caller
        (engine or service) prefills them and writes their KV blocks."""
        admitted = []
        while (self.waiting and len(self.running) < self.max_batch):
            req = self.waiting[0]
            need_tokens = len(req.prompt) + 1
            if self._live_tokens() + need_tokens > self.token_budget:
                break
            try:
                blocks = self.pool.alloc(self.pool.blocks_for(need_tokens))
            except OutOfBlocks:
                break
            self.waiting.popleft()
            seq = Sequence(req=req, blocks=blocks)
            self.running.append(seq)
            admitted.append(seq)
        return admitted

    def adopt(self, seq):
        """Register an externally-built sequence (the disaggregated
        path: prefill happened on another rank, blocks are already
        allocated and written)."""
        self.running.append(seq)

    # ---- per-step growth / eviction ------------------------------------

    def ensure_slot(self, seq):
        """Guarantee ``seq`` has a cache slot for its next token,
        growing its block table across a block boundary; evicts the
        youngest OTHER running sequence until the allocation fits.
        Returns False when ``seq`` itself had to be evicted (pool too
        small even after evicting everyone else)."""
        need = self.pool.blocks_for(seq.cached + 1)
        while need > len(seq.blocks):
            try:
                seq.blocks.extend(self.pool.alloc(need - len(seq.blocks)))
            except OutOfBlocks:
                victim = self._youngest_other(seq)
                if victim is None:
                    self.evict(seq)
                    return False
                self.evict(victim)
        return True

    def _youngest_other(self, seq):
        for s in reversed(self.running):
            if s is not seq:
                return s
        return None

    def evict(self, seq):
        """Free a running sequence's blocks and re-queue its request
        at the front (re-prefill later; greedy decode makes the replay
        token-identical)."""
        self.running.remove(seq)
        self.pool.free(seq.blocks)
        seq.blocks = []
        seq.generated = []
        self.requeue_front([seq.req])
        self.evictions += 1
        # The prompt's prefill pass is now wasted work: it runs again
        # when the request is re-admitted (the generated tail is also
        # re-decoded, but the ledger counts prefill recompute — the
        # quantity the amplification ratio names).
        self.recomputed_prefill_tokens += len(seq.req.prompt)
        reqtrace.record_request("evicted_requeue", seq.rid,
                                aux=len(seq.req.prompt))

    def complete(self, seq):
        self.running.remove(seq)
        self.pool.free(seq.blocks)
        seq.blocks = []
        self.completed[seq.rid] = seq
        self.useful_tokens += len(seq.generated)
        reqtrace.record_request("done", seq.rid,
                                aux=len(seq.generated))

    def drop(self, rid):
        """Cancel a running/waiting request (the elastic duplicate
        guard: another rank already completed it). Returns True when
        something was dropped."""
        for s in list(self.running):
            if s.rid == rid:
                self.running.remove(s)
                self.pool.free(s.blocks)
                s.blocks = []
                # No `done` transition here: the completion that wins
                # lives on another rank, whose event is the chain's
                # terminal — only the live table forgets the rid.
                reqtrace.forget_request(rid)
                return True
        for r in list(self.waiting):
            if r.rid == rid:
                self.waiting.remove(r)
                reqtrace.forget_request(rid)
                return True
        return False

    def signals(self):
        """The /healthz serving field set (docs/serving.md)."""
        out = {"serving_queue_depth": self.queue_depth,
               "inflight_sequences": self.inflight,
               "recomputed_prefill_tokens":
                   self.recomputed_prefill_tokens,
               "useful_tokens": self.useful_tokens,
               "eviction_amplification": round(
                   self.recomputed_prefill_tokens
                   / max(self.useful_tokens, 1), 6)}
        out.update(self.pool.stats())
        return out


def latency_summary(latencies_s):
    """p50/p99 (ms) over per-request completion latencies."""
    if not latencies_s:
        return {"p50_ms": 0.0, "p99_ms": 0.0}
    p50, p99 = np.percentile(np.asarray(latencies_s, np.float64),
                             [50, 99])
    return {"p50_ms": round(float(p50) * 1000.0, 3),
            "p99_ms": round(float(p99) * 1000.0, 3)}
