"""Paged KV cache: a fixed-size block pool with a free-list allocator.

The offline decode path (models/generate.py) pads every sequence's
cache to ``prompt + max_new`` up front — fine for a fixed batch, fatal
for serving: a 2048-slot reservation for a request that stops after 40
tokens strands ~98% of its HBM for its whole lifetime. Here the cache
is a POOL of fixed-size blocks (``block_size`` token slots each, all
layers and kv-heads of those slots together, the vLLM/PagedAttention
layout adapted to this stack's heads-major [L, Hkv, S, D] attention
order); a sequence holds a BLOCK TABLE (ordered block ids) and grows
one block at a time, so stranded memory is bounded by
``block_size - 1`` slots per sequence and freed blocks are instantly
reusable by any other request.

Optional int8 block format (``quantized=True``): blocks store int8
payloads plus one f32 scale per (block, layer, kv-head) —
quantize-narrow on write, f32-accumulate dequant on read, the EQuARX
recipe (arXiv:2506.17615) the bf16 wire codec already validates. Halves
pool HBM *and* the prefill->decode KV wire bytes (serving/service.py
ships blocks in pool format). A later write into a partially-filled
block may grow the block's amax; existing entries are then requantized
under the new scale, which adds at most one extra quantization step of
error (pinned in tests/single/test_serving.py).

Host-resident numpy by design: the pool is control-plane state (the
scheduler allocates/evicts against it, the elastic re-queue path reads
block tables off it, the wire ships it), and the decode step consumes
a GATHERED view — on TPU the gathered batch is device_put once per
step, exactly like the eager lane's host staging. A device-resident
pool with in-place paged writes is the kernel follow-up
(docs/serving.md).
"""

import numpy as np


class OutOfBlocks(RuntimeError):
    """The pool cannot satisfy an allocation (caller evicts or queues)."""


def quantize_blocks(k, v, block_size, quantized=True, dtype=np.float32):
    """Freeze k/v [L, Hkv, T, D] into POOL-FORMAT blocks — the
    prefill->decode wire payload (serving/service.py ships these bytes;
    ``PagedKVCache.write_raw`` adopts them verbatim).

    Returns (k_q, v_q [n, L, Hkv, block_size, D], k_scale, v_scale
    [n, L, Hkv] — None unquantized). The quantization recipe is
    IDENTICAL to a fresh-block :meth:`PagedKVCache.write` (per-block
    amax/127, zero padding), so a shipped prompt and a locally
    re-prefilled one produce the same bytes — the bit-determinism the
    elastic re-queue token-identity pin rests on."""
    n_layers, n_kv_heads, t, head_dim = k.shape
    n = max(1, -(-t // block_size))
    s_pad = n * block_size

    def to_blocks(x):
        out = np.zeros((n_layers, n_kv_heads, s_pad, head_dim),
                       x.dtype)
        out[:, :, :t, :] = x
        # [L, Hkv, n, bs, D] -> [n, L, Hkv, bs, D]
        return out.reshape(n_layers, n_kv_heads, n, block_size,
                           head_dim).transpose(2, 0, 1, 3, 4)

    kb, vb = to_blocks(np.asarray(k)), to_blocks(np.asarray(v))
    if not quantized:
        return kb.astype(dtype), vb.astype(dtype), None, None

    def quant(xb):
        amax = np.abs(xb).max(axis=(-2, -1))          # [n, L, Hkv]
        scale = amax.astype(np.float32) / 127.0
        safe = np.where(scale > 0, scale, 1.0)
        q = np.rint(xb.astype(np.float32) / safe[..., None, None])
        return np.clip(q, -127, 127).astype(np.int8), scale

    k_q, k_s = quant(kb)
    v_q, v_s = quant(vb)
    return k_q, v_q, k_s, v_s


class PagedKVCache:
    """Block pool + allocator for K and V of every layer.

    Block layout: ``k_pool[b]``/``v_pool[b]`` are
    [n_layers, n_kv_heads, block_size, head_dim] — one block covers
    ``block_size`` consecutive token positions of ONE sequence across
    all layers/heads, so a sequence's cache is just its block table
    concatenated along the position axis.
    """

    def __init__(self, n_layers, n_kv_heads, head_dim, block_size=16,
                 n_blocks=256, dtype=np.float32, quantized=False):
        self.n_layers = int(n_layers)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.n_blocks = int(n_blocks)
        self.quantized = bool(quantized)
        shape = (self.n_blocks, self.n_layers, self.n_kv_heads,
                 self.block_size, self.head_dim)
        store = np.int8 if quantized else dtype
        self.k_pool = np.zeros(shape, store)
        self.v_pool = np.zeros(shape, store)
        if quantized:
            sshape = (self.n_blocks, self.n_layers, self.n_kv_heads)
            self.k_scale = np.zeros(sshape, np.float32)
            self.v_scale = np.zeros(sshape, np.float32)
        else:
            self.k_scale = self.v_scale = None
        # LIFO free list: recently-freed blocks are cache-warm.
        self._free = list(range(self.n_blocks - 1, -1, -1))
        self._allocated = set()

    # ---- allocator ----------------------------------------------------

    @property
    def blocks_free(self):
        return len(self._free)

    @property
    def blocks_total(self):
        return self.n_blocks

    def blocks_for(self, n_tokens):
        """Blocks needed to hold ``n_tokens`` positions."""
        return max(1, -(-int(n_tokens) // self.block_size))

    def alloc(self, n):
        """Take ``n`` blocks off the free list (all-or-nothing).

        Raises :class:`OutOfBlocks` when fewer than ``n`` are free —
        the scheduler's cue to evict or hold the request."""
        if n > len(self._free):
            raise OutOfBlocks(
                f"need {n} blocks, {len(self._free)} free "
                f"of {self.n_blocks}")
        out = [self._free.pop() for _ in range(n)]
        self._allocated.update(out)
        if self.quantized:
            # A handed-out block must be SCALE-fresh: `_write_block_q`
            # merges against the block's current scale, and a reused
            # block still carrying its previous owner's (possibly much
            # larger) scale would quantize the new owner's first write
            # under it — different bytes than `quantize_blocks`, i.e.
            # the local-write==wire equivalence the elastic replay
            # token-identity pin rests on breaks, and it breaks
            # TIMING-DEPENDENTLY (which block the LIFO list hands back
            # depends on eviction churn). Stale payload beyond a
            # sequence's `cached` slots is fine — the lengths mask
            # hides it — but scales feed every future write.
            idx = np.asarray(out, np.int64)
            self.k_scale[idx] = 0.0
            self.v_scale[idx] = 0.0
        return out

    def free(self, blocks):
        """Return blocks to the pool (idempotence is a bug: freeing a
        block twice means two sequences think they own it)."""
        for blk in blocks:
            if blk not in self._allocated:
                raise ValueError(f"double free of block {blk}")
            self._allocated.discard(blk)
            self._free.append(blk)

    # ---- block I/O ----------------------------------------------------

    def write(self, blocks, pos, k, v):
        """Write ``k``/``v`` [L, Hkv, T, D] at sequence positions
        ``pos .. pos+T-1`` into the block table ``blocks``.

        Prefill writes whole prompts (T = prompt length); decode writes
        T=1 at the tail. Quantized writes are BLOCK-granular: one scale
        update + one requantize per touched block per call (not per
        slot), so a full-prompt write pays the single-shot quantization
        error and only tail-block growth across calls compounds (error
        note in the module docstring)."""
        t = k.shape[2]
        i = 0
        while i < t:
            p = pos + i
            blk = blocks[p // self.block_size]
            off = p % self.block_size
            # All incoming slots landing in this block, in one strip.
            run = min(t - i, self.block_size - off)
            ks = k[:, :, i:i + run, :]
            vs = v[:, :, i:i + run, :]
            if self.quantized:
                self._write_block_q(blk, off, ks, vs)
            else:
                self.k_pool[blk, :, :, off:off + run, :] = ks
                self.v_pool[blk, :, :, off:off + run, :] = vs
            i += run

    def _write_block_q(self, blk, off, k_strip, v_strip):
        """Quantized write of a strip [L, Hkv, run, D] at slot ``off``;
        rescale-and-requantize existing entries when the strip's amax
        grows the block scale."""
        run = k_strip.shape[2]
        for pool, scales, strip in ((self.k_pool, self.k_scale, k_strip),
                                    (self.v_pool, self.v_scale, v_strip)):
            amax = np.abs(strip).max(axis=(-2, -1))    # [L, Hkv]
            new_scale = amax.astype(np.float32) / 127.0
            old = scales[blk]
            grow = new_scale > old
            if grow.any():
                merged = np.where(grow, new_scale, old)
                # Requantize existing entries under the merged scale
                # (dead scale rows scale by 0 — nothing stored there).
                safe = np.where(merged > 0, merged, 1.0)
                ratio = np.where(old > 0, old, 0.0) / safe
                pool[blk] = np.rint(
                    pool[blk].astype(np.float32)
                    * ratio[:, :, None, None]).astype(np.int8)
                scales[blk] = merged
            s = scales[blk]                            # [L, Hkv]
            safe = np.where(s > 0, s, 1.0)
            q = np.rint(strip.astype(np.float32) / safe[:, :, None, None])
            pool[blk, :, :, off:off + run, :] = np.clip(
                q, -127, 127).astype(np.int8)

    def write_raw(self, blocks, k_q, v_q, k_scale, v_scale):
        """Adopt pool-format payloads wholesale (the prefill->decode
        wire path): ``k_q``/``v_q`` [n, L, Hkv, bs, D] in the pool's
        storage dtype, scales [n, L, Hkv] (quantized pools only)."""
        for i, blk in enumerate(blocks):
            self.k_pool[blk] = k_q[i]
            self.v_pool[blk] = v_q[i]
            if self.quantized:
                self.k_scale[blk] = k_scale[i]
                self.v_scale[blk] = v_scale[i]

    def read_raw(self, blocks):
        """Pool-format payloads for ``blocks`` (the wire's send side).
        Returns (k_q, v_q, k_scale, v_scale); scales are None for
        unquantized pools."""
        idx = np.asarray(blocks, np.int64)
        k_q, v_q = self.k_pool[idx], self.v_pool[idx]
        if self.quantized:
            return k_q, v_q, self.k_scale[idx], self.v_scale[idx]
        return k_q, v_q, None, None

    def gather(self, blocks, pad_blocks=0):
        """Concatenate a block table into the attention view.

        Returns (k, v, k_scale, v_scale): k/v
        [L, Hkv, (len(blocks)+pad_blocks)*block_size, D] in the pool's
        storage dtype; scales are per-SLOT vectors [L, Hkv, S] (the
        per-block scale repeated over its slots) for quantized pools,
        None otherwise — exactly what
        ``decode_attention_ragged(k_scale=...)``'s f32-accumulate
        dequant consumes. ``pad_blocks`` zero-pads to a static shape so
        one compiled step serves every table length."""
        idx = np.asarray(blocks, np.int64)
        n = len(blocks) + int(pad_blocks)
        s_pad = n * self.block_size
        shape = (self.n_layers, self.n_kv_heads, s_pad, self.head_dim)
        k = np.zeros(shape, self.k_pool.dtype)
        v = np.zeros(shape, self.v_pool.dtype)
        valid = len(blocks) * self.block_size
        if len(blocks):
            # [n, L, Hkv, bs, D] -> [L, Hkv, n*bs, D]
            k[:, :, :valid, :] = self.k_pool[idx].transpose(
                1, 2, 0, 3, 4).reshape(self.n_layers, self.n_kv_heads,
                                       valid, self.head_dim)
            v[:, :, :valid, :] = self.v_pool[idx].transpose(
                1, 2, 0, 3, 4).reshape(self.n_layers, self.n_kv_heads,
                                       valid, self.head_dim)
        if not self.quantized:
            return k, v, None, None
        ks = np.zeros((self.n_layers, self.n_kv_heads, s_pad), np.float32)
        vs = np.zeros_like(ks)
        if len(blocks):
            ks[:, :, :valid] = np.repeat(
                self.k_scale[idx].transpose(1, 2, 0), self.block_size,
                axis=-1)
            vs[:, :, :valid] = np.repeat(
                self.v_scale[idx].transpose(1, 2, 0), self.block_size,
                axis=-1)
        return k, v, ks, vs

    def stats(self):
        """The /healthz serving fields (docs/serving.md)."""
        return {"kv_blocks_free": self.blocks_free,
                "kv_blocks_total": self.blocks_total}
