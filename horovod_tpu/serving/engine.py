"""Continuous-batching decode engine over the paged KV cache.

Glues the three layers below it into one `step()`:

- the scheduler admits/evicts (scheduler.py),
- admitted prompts prefill through ``llama_prefill`` (one compiled
  pass -> first token + per-layer K/V, written into pool blocks),
- running sequences decode through ``llama_decode_step`` — ONE jitted
  program with STATIC shapes: the batch is padded to ``max_batch`` rows
  and every gathered cache to ``max_context`` rounded up to whole
  blocks, raggedness carried by the ``lengths`` mask. Static shapes buy
  two things: no retrace as the batch composition churns (admissions /
  completions / evictions every step), and bit-deterministic numerics
  regardless of WHICH requests happen to share a step — the property
  the elastic re-queue guarantee (token-identical replay on survivors)
  and eviction-replay both lean on.

Padding rows decode a dummy token at length 0 (self-attention over one
position — numerically inert, output discarded); their cost is bounded
by max_batch, the knob the operator already sized for peak.
"""

import numpy as np

from horovod_tpu.serving.kvcache import PagedKVCache
from horovod_tpu.serving.scheduler import ContinuousBatchingScheduler
from horovod_tpu.telemetry import reqtrace


class DecodeEngine:
    """Single-rank continuous-batching decode over a paged pool."""

    def __init__(self, params, config, *, block_size=16, n_blocks=256,
                 max_batch=8, max_context=512, token_budget=None,
                 quantized=False):
        import jax.numpy as jnp

        self.params = params
        self.config = config
        self._jnp = jnp
        self.max_batch = int(max_batch)
        # Static gathered-cache length: whole blocks covering
        # max_context (+1 growth slot so a sequence at exactly
        # max_context-1 still fits its next token).
        # compute_dtype is a numpy-compatible dtype object (ml_dtypes
        # covers bfloat16), so the pool can store it directly.
        self.pool = PagedKVCache(
            config.n_layers, config.n_kv_heads, config.head_dim,
            block_size=block_size, n_blocks=n_blocks,
            dtype=config.compute_dtype, quantized=quantized)
        self.blocks_per_seq = self.pool.blocks_for(int(max_context))
        self.s_pad = self.blocks_per_seq * self.pool.block_size
        self.scheduler = ContinuousBatchingScheduler(
            self.pool, max_batch=max_batch,
            token_budget=int(token_budget) if token_budget
            else self.s_pad * max_batch)
        self.steps = 0
        self.tokens_out = 0

    # ---- admission ----------------------------------------------------

    def submit(self, req):
        """Queue a request for local prefill+decode (the all-in-one
        lane; the disaggregated service prefills remotely and calls
        :meth:`adopt_remote` instead)."""
        if len(req.prompt) + req.max_new_tokens > self.s_pad:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + "
                f"max_new {req.max_new_tokens} exceeds max_context "
                f"{self.s_pad}")
        self.scheduler.submit(req)

    def prefill(self, req):
        """Run the compiled prefill for one request; returns
        (first_token, k, v [L, Hkv, T, D] numpy)."""
        from horovod_tpu.models.generate import llama_prefill

        prompt = self._jnp.asarray(
            np.asarray(req.prompt, np.int32)[None, :])
        first, ck, cv = llama_prefill(self.params, prompt, self.config)
        # [L, 1, Hkv, T, D] -> [L, Hkv, T, D]
        return (int(np.asarray(first)[0]), np.asarray(ck[:, 0]),
                np.asarray(cv[:, 0]))

    def _admit_local(self):
        for seq in self.scheduler.admit():
            reqtrace.record_request("prefill", seq.rid,
                                    aux=len(seq.req.prompt))
            first, k, v = self.prefill(seq.req)
            self.pool.write(seq.blocks, 0, k, v)
            seq.generated.append(first)
            self.tokens_out += 1
            if seq.done:  # max_new_tokens == 1: prefill finished it
                self.scheduler.complete(seq)
            else:
                reqtrace.record_request("decode_wait", seq.rid)

    def adopt_remote(self, seq):
        """Register a sequence whose blocks were shipped in (service
        lane). The caller allocated+wrote the blocks already."""
        reqtrace.record_request("decode_wait", seq.rid,
                                aux=len(seq.blocks))
        self.scheduler.adopt(seq)

    # ---- the decode step ----------------------------------------------

    def step(self):
        """One continuous-batching step: admit, then one token for
        every running sequence. Returns [(rid, token, done), ...]."""
        self._admit_local()
        # ensure_slot may EVICT other running sequences (pool
        # pressure), so iterate a snapshot and re-validate membership
        # afterwards — a sequence granted a slot early can still be
        # evicted by a later sibling's growth.
        snapshot = list(self.scheduler.running)
        for seq in snapshot:
            if seq in self.scheduler.running:
                self.scheduler.ensure_slot(seq)
        live = [s for s in snapshot if s in self.scheduler.running]
        if not live:
            return []
        live = live[:self.max_batch]
        # Request tracing: this batch's rows are DECODING for the span
        # of the jitted step; survivors fall back to decode_wait after
        # it. One transition pair per row per step is the ledger's
        # resolution (tail_report aggregates the alternation), cheap
        # enough that `bench.py --serving` pins the whole tracing cost
        # under 2% of sustained tok/s.
        for seq in live:
            reqtrace.record_request("decode_active", seq.rid,
                                    aux=seq.cached)
        out = self._decode_batch(live)
        events = []
        for seq, tok in zip(live, out):
            # Write the new token's K/V before appending: position
            # `length` is the slot ensure_slot just guaranteed.
            seq.generated.append(tok)
            self.tokens_out += 1
            events.append((seq.rid, tok, seq.done))
            if seq.done:
                self.scheduler.complete(seq)
            else:
                reqtrace.record_request("decode_wait", seq.rid)
        self.steps += 1
        return events

    def _decode_batch(self, live):
        from horovod_tpu.models.generate import llama_decode_step

        jnp = self._jnp
        c = self.config
        b_pad = self.max_batch
        s_pad = self.s_pad
        dt = c.compute_dtype
        quant = self.pool.quantized
        store = np.int8 if quant else dt
        tokens = np.zeros(b_pad, np.int32)
        lengths = np.zeros(b_pad, np.int32)
        ck = np.zeros((c.n_layers, b_pad, c.n_kv_heads, s_pad,
                       c.head_dim), store)
        cv = np.zeros_like(ck)
        ks = vs = None
        if quant:
            ks = np.zeros((c.n_layers, b_pad, c.n_kv_heads, s_pad),
                          np.float32)
            vs = np.zeros_like(ks)
        for i, seq in enumerate(live):
            tokens[i] = seq.generated[-1]
            lengths[i] = seq.cached
            k, v, k_s, v_s = self.pool.gather(
                seq.blocks, pad_blocks=self.blocks_per_seq
                - len(seq.blocks))
            ck[:, i], cv[:, i] = k, v
            if quant:
                ks[:, i], vs[:, i] = k_s, v_s
        nxt, k_new, v_new = llama_decode_step(
            self.params, jnp.asarray(tokens), jnp.asarray(ck),
            jnp.asarray(cv), jnp.asarray(lengths), c,
            k_scale=jnp.asarray(ks) if quant else None,
            v_scale=jnp.asarray(vs) if quant else None)
        nxt = np.asarray(nxt)
        k_new = np.asarray(k_new, np.float32 if quant else dt)
        v_new = np.asarray(v_new, np.float32 if quant else dt)
        for i, seq in enumerate(live):
            # [L, Hkv, D] -> [L, Hkv, 1, D]: the input token's K/V
            # lands at the slot ensure_slot just guaranteed.
            self.pool.write(seq.blocks, seq.cached,
                            k_new[:, i][:, :, None, :],
                            v_new[:, i][:, :, None, :])
        return [int(t) for t in nxt[:len(live)]]

    # ---- drive to completion (bench / offline lane) --------------------

    def run_until_idle(self, max_steps=100000):
        """Decode until nothing is waiting or running. Returns the
        completed {rid: tokens} map."""
        steps = 0
        while self.scheduler.waiting or self.scheduler.running:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError("run_until_idle: no convergence "
                                   f"after {max_steps} steps")
        return {rid: s.tokens for rid, s in
                self.scheduler.completed.items()}
