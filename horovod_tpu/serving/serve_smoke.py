"""Two-rank serving chaos smoke: ``make serve-smoke``.

The acceptance drill for the serving lane, one command, no
accelerator: a 2-rank prefill/decode world (rank 0 frontend+prefill,
rank 1 decode) serves a Poisson arrival trace with int8 paged KV
shipped over the CRC-framed host ring — then rank 1 is SIGKILLed
mid-trace, with admitted sequences in flight. Asserts:

1. rank 0 takes the typed peer failure at the round boundary, re-forms
   a 1-rank world in place (r12/r14 elastic), re-queues the dead
   rank's in-flight requests, and EVERY trace request completes on the
   survivor;
2. greedy output is TOKEN-IDENTICAL to ``llama_generate`` for every
   request — a request's answer does not depend on whether its first
   home died (the static-shape engine + source-side quantization
   determinism, docs/serving.md);
3. the victim really died by SIGKILL (exit code pins the chaos, not a
   clean shutdown);
4. request-scoped tracing EXPLAINS the latency cliff
   (docs/serving.md "Request lifecycle & tracing"): the survivor's
   event dump stitches into one gap-free span chain per completed rid
   (per-phase sums reconcile to the chain's wall time EXACTLY — the
   r17 standard), the victim's orphaned requests carry a
   ``fault_requeue`` span (and only they do), and
   ``report.py --requests`` renders the tail attribution.
"""

import json
import os
import signal
import subprocess
import sys
import time

N_REQUESTS = 12
ARRIVAL_RPS = 60.0
KILL_ROUND = 6
TRACE_SEED = 5


def _trace(cfg):
    from horovod_tpu.serving.scheduler import poisson_trace

    return poisson_trace(N_REQUESTS, ARRIVAL_RPS, seed=TRACE_SEED,
                         prompt_len=(4, 12), max_new=(3, 8),
                         vocab_size=cfg.vocab_size)


def worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from horovod_tpu.common import elastic as hvd_elastic
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.models import (
        LlamaConfig,
        llama_generate,
        llama_init,
    )
    from horovod_tpu.serving.service import ServingLoop

    rank = int(os.environ["HOROVOD_RANK"])
    b = HorovodBasics()
    hvd_elastic.init()
    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    trace = _trace(cfg)

    def hook(loop, round_idx):
        if rank == 1 and round_idx == KILL_ROUND:
            # Die holding in-flight sequences: the survivor must
            # re-queue and finish them.
            os.kill(os.getpid(), signal.SIGKILL)

    loop = ServingLoop(params, cfg, trace, block_size=8, n_blocks=64,
                       max_batch=4, max_context=32, quantized=True,
                       steps_per_round=2, prefill_per_round=2,
                       round_hook=hook)
    report = loop.run()
    if b.rank() == 0:
        assert report["faults_survived"] >= 1, report
        assert report["served"] == len(trace), (
            report["served"], len(trace))
        for req in trace:
            ref = np.asarray(llama_generate(
                params, jax.numpy.asarray(req.prompt[None, :]), cfg,
                req.max_new_tokens))[0]
            got = report["completed"][req.rid]
            assert np.array_equal(got, ref), (
                f"rid {req.rid}: served tokens diverge from "
                f"llama_generate\n got {got}\n ref {ref}")
        summary = {k: report[k] for k in
                   ("requests", "served", "generated_tokens",
                    "faults_survived", "evictions", "rounds",
                    "sustained_tok_s", "p50_ms", "p99_ms")}
        print("SERVE_SMOKE_OK " + json.dumps(summary), flush=True)
        _verify_request_chains(b, loop, report)
    b.shutdown()
    return 0


def _verify_request_chains(b, loop, report):
    """Acceptance 4: dump the survivor's event ring, stitch the
    per-request span chains, and assert the chaos is EXPLAINED — every
    completed rid's chain is gap-free with per-phase sums reconciling
    to its wall time exactly, and `fault_requeue` spans appear on
    precisely the requests the fault orphaned."""
    from horovod_tpu.telemetry import critpath, reqtrace

    dump_dir = os.environ.get("SERVE_SMOKE_DUMPS")
    if not dump_dir:
        return
    path = os.path.join(dump_dir, f"blackbox-rank{b.rank()}.jsonl")
    critpath.write_event_dump(path, b.rank(), b.size(),
                              b.events_drain(),
                              epoch=int(b.lib.hvdtpu_epoch()))
    chains = reqtrace.stitch(dump_dir)
    for rid in report["completed"]:
        chain = chains.get(int(rid))
        assert chain is not None, f"rid {rid}: no stitched chain"
        assert chain["complete"], f"rid {rid}: no terminal done"
        defects = reqtrace.chain_gaps(chain)
        assert not defects, f"rid {rid}: chain defects {defects}"
        # The exact-reconciliation pin, recomputed independently of
        # the stitcher's construction.
        assert sum(chain["phase_us"].values()) == chain["wall_us"], rid
    fault_rids = {rid for rid, c in chains.items()
                  if c["phase_us"].get("fault_requeue", 0) > 0}
    assert fault_rids == loop.requeued_rids, (
        "fault_requeue attribution does not match the re-queued set",
        sorted(fault_rids), sorted(loop.requeued_rids))
    assert fault_rids, "chaos fired but no request carries a " \
                       "fault_requeue span"
    print("REQTRACE_OK " + json.dumps({
        "chains": len(chains),
        "complete": sum(c["complete"] for c in chains.values()),
        "fault_requeued": sorted(fault_rids),
    }), flush=True)


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main():
    if "--worker" in sys.argv:
        return worker()

    import tempfile

    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    dump_dir = tempfile.mkdtemp(prefix="serve_smoke_reqtrace_")
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(r), "HOROVOD_SIZE": "2",
            "HOROVOD_LOCAL_RANK": str(r), "HOROVOD_LOCAL_SIZE": "2",
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
            "HOROVOD_WIRE_TIMEOUT_MS": "2000",
            "HOROVOD_EVENTS": "1",
            "SERVE_SMOKE_DUMPS": dump_dir,
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.serving.serve_smoke",
             "--worker"],
            stdout=subprocess.PIPE if r == 0 else subprocess.DEVNULL,
            stderr=None, text=True, env=env, cwd=repo))
    t0 = time.monotonic()
    out, _ = procs[0].communicate(timeout=600)
    procs[1].wait(timeout=30)
    ok_lines = [ln for ln in out.splitlines()
                if ln.startswith("SERVE_SMOKE_OK")]
    assert procs[0].returncode == 0, f"rank 0 failed:\n{out}"
    assert ok_lines, f"no SERVE_SMOKE_OK line:\n{out}"
    assert procs[1].returncode == -signal.SIGKILL, (
        "victim exited cleanly — the chaos never fired: "
        f"{procs[1].returncode}")
    summary = json.loads(ok_lines[0].split(" ", 1)[1])
    assert summary["faults_survived"] >= 1, summary
    assert summary["served"] == summary["requests"] == N_REQUESTS
    trace_lines = [ln for ln in out.splitlines()
                   if ln.startswith("REQTRACE_OK")]
    assert trace_lines, f"no REQTRACE_OK line:\n{out}"
    reqtrace_summary = json.loads(trace_lines[0].split(" ", 1)[1])
    assert reqtrace_summary["complete"] == N_REQUESTS, reqtrace_summary
    assert reqtrace_summary["fault_requeued"], reqtrace_summary
    # The operator-facing renderer over the same dumps: the tail band
    # must attribute through the CLI too (report.py --requests).
    from horovod_tpu.telemetry.report import main as report_main

    rc = report_main(["--requests", dump_dir])
    assert rc == 0, "report.py --requests failed over smoke dumps"
    print(f"serve-smoke OK in {time.monotonic() - t0:.1f}s: "
          f"{summary['served']}/{summary['requests']} requests "
          f"token-identical across a SIGKILLed decode rank "
          f"({summary['generated_tokens']} tokens, "
          f"p99 {summary['p99_ms']:.0f} ms, "
          f"{summary['faults_survived']} fault(s) survived; "
          f"{reqtrace_summary['complete']} gap-free request chains, "
          f"fault_requeue on {reqtrace_summary['fault_requeued']})")
    # Dumps are forensic evidence on a FAILED run (every assertion
    # above raises before this line, leaving them in place); a green
    # run cleans up after itself instead of leaking a /tmp dir per CI
    # invocation.
    import shutil

    shutil.rmtree(dump_dir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
