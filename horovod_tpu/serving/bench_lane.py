"""Serving bench lane: sustained tok/s + p50/p99 under a Poisson trace.

``python -m horovod_tpu.serving.bench_lane`` runs the all-in-one
continuous-batching engine (single rank, no wire — the scheduler/paged
-cache/decode-step stack is what's being measured) against a seeded
Poisson arrival trace on a tiny llama config, once per KV block format
(f32 and int8), and prints one schema-stamped JSON row per format —
the ``serving_latency`` family ``bench.py`` emits and
``perfwatch``/``bench.py --diff`` watch (p50/p99 up and
sustained_tok_s down are the bad directions; registered in
telemetry/perfwatch.py).

Substrate-independent (CPU jax) like ``ring_busbw``: the driver's
bench capture gets serving rows on any box. bench.py runs this module
as a SUBPROCESS so the flagship lane's virgin-device-heap requirement
is untouched.
"""

import json
import sys
import time


def serving_rows(n_requests=24, rps=200.0, seed=7):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np  # noqa: F401  (trace helpers return numpy)

    from horovod_tpu.models import LlamaConfig, llama_init
    from horovod_tpu.serving.scheduler import (
        latency_summary,
        poisson_trace,
    )
    from horovod_tpu.serving.engine import DecodeEngine

    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    rows = []
    for name, quantized in (("f32", False), ("int8", True)):
        trace = poisson_trace(n_requests, rps, seed=seed,
                              prompt_len=(4, 24), max_new=(4, 24),
                              vocab_size=cfg.vocab_size)
        eng = DecodeEngine(params, cfg, block_size=8, n_blocks=128,
                           max_batch=8, max_context=64,
                           quantized=quantized)
        # Warm EVERY compiled program off the clock: the prefill
        # recompiles per distinct prompt length (static T) and is
        # shared across formats, so an unwarmed first format would eat
        # all the compiles and skew the f32-vs-int8 comparison.
        seen = set()
        for req in trace:
            if len(req.prompt) not in seen:
                seen.add(len(req.prompt))
                eng.prefill(req)
        eng.submit(trace[0])
        eng.run_until_idle()     # decode program for this format
        eng.scheduler.completed.clear()
        t0 = time.monotonic()
        done_at = {}
        for req in trace:
            # Offered-load replay: submit when the trace clock says so.
            now = time.monotonic() - t0
            if req.arrival_t > now:
                time.sleep(req.arrival_t - now)
            eng.submit(req)
            eng.step()
            for rid in list(eng.scheduler.completed):
                done_at.setdefault(rid, time.monotonic() - t0)
        while eng.scheduler.waiting or eng.scheduler.running:
            eng.step()
            for rid in list(eng.scheduler.completed):
                done_at.setdefault(rid, time.monotonic() - t0)
        wall = time.monotonic() - t0
        lat = latency_summary([
            done_at[r.rid] - r.arrival_t for r in trace])
        gen = sum(len(s.tokens) - len(s.req.prompt)
                  for s in eng.scheduler.completed.values())
        rows.append({
            "metric": "serving_latency",
            "config": name,
            "ranks": 1,
            "arrival_rps": rps,
            "block_size": eng.pool.block_size,
            "requests": n_requests,
            "served": len(eng.scheduler.completed),
            "sustained_tok_s": round(gen / wall, 2),
            "p50_ms": lat["p50_ms"],
            "p99_ms": lat["p99_ms"],
            "evictions": eng.scheduler.evictions,
            "unit": "continuous-batching decode, Poisson trace "
                    f"({rps:.0f} rps offered, tiny llama, CPU, "
                    f"paged KV {name}); sustained tok/s + request "
                    "latency percentiles",
        })
    return rows


def main():
    for row in serving_rows():
        print("SERVING_ROW " + json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
