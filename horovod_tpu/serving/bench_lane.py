"""Serving bench lane: sustained tok/s + p50/p99 under a Poisson trace.

``python -m horovod_tpu.serving.bench_lane`` runs the all-in-one
continuous-batching engine (single rank, no wire — the scheduler/paged
-cache/decode-step stack is what's being measured) against a seeded
Poisson arrival trace on a tiny llama config, once per KV block format
(f32 and int8), and prints one schema-stamped JSON row per format —
the ``serving_latency`` family ``bench.py`` emits and
``perfwatch``/``bench.py --diff`` watch (p50/p99 up and
sustained_tok_s down are the bad directions; registered in
telemetry/perfwatch.py).

It also emits the ``serving_trace_overhead`` row: the same engine
driven CLOSED-LOOP (all requests submitted up front, no arrival
sleeps — the decode-bound regime where per-step tracing would show)
with request tracing on vs off, best-of-N per mode. The acceptance
bar mirrors the r15 events-overhead criterion: < 2% sustained tok/s
regression with tracing on (``overhead_pct`` is perfwatch-watched, up
= bad).

Substrate-independent (CPU jax) like ``ring_busbw``: the driver's
bench capture gets serving rows on any box. bench.py runs this module
as a SUBPROCESS so the flagship lane's virgin-device-heap requirement
is untouched.
"""

import json
import sys
import time


def serving_rows(n_requests=24, rps=200.0, seed=7):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np  # noqa: F401  (trace helpers return numpy)

    from horovod_tpu.models import LlamaConfig, llama_init
    from horovod_tpu.serving.scheduler import (
        latency_summary,
        poisson_trace,
    )
    from horovod_tpu.serving.engine import DecodeEngine

    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    rows = []
    for name, quantized in (("f32", False), ("int8", True)):
        trace = poisson_trace(n_requests, rps, seed=seed,
                              prompt_len=(4, 24), max_new=(4, 24),
                              vocab_size=cfg.vocab_size)
        eng = DecodeEngine(params, cfg, block_size=8, n_blocks=128,
                           max_batch=8, max_context=64,
                           quantized=quantized)
        # Warm EVERY compiled program off the clock: the prefill
        # recompiles per distinct prompt length (static T) and is
        # shared across formats, so an unwarmed first format would eat
        # all the compiles and skew the f32-vs-int8 comparison.
        seen = set()
        for req in trace:
            if len(req.prompt) not in seen:
                seen.add(len(req.prompt))
                eng.prefill(req)
        eng.submit(trace[0])
        eng.run_until_idle()     # decode program for this format
        eng.scheduler.completed.clear()
        t0 = time.monotonic()
        done_at = {}
        for req in trace:
            # Offered-load replay: submit when the trace clock says so.
            now = time.monotonic() - t0
            if req.arrival_t > now:
                time.sleep(req.arrival_t - now)
            eng.submit(req)
            eng.step()
            for rid in list(eng.scheduler.completed):
                done_at.setdefault(rid, time.monotonic() - t0)
        while eng.scheduler.waiting or eng.scheduler.running:
            eng.step()
            for rid in list(eng.scheduler.completed):
                done_at.setdefault(rid, time.monotonic() - t0)
        wall = time.monotonic() - t0
        lat = latency_summary([
            done_at[r.rid] - r.arrival_t for r in trace])
        gen = sum(len(s.tokens) - len(s.req.prompt)
                  for s in eng.scheduler.completed.values())
        rows.append({
            "metric": "serving_latency",
            "config": name,
            "ranks": 1,
            "arrival_rps": rps,
            "block_size": eng.pool.block_size,
            "requests": n_requests,
            "served": len(eng.scheduler.completed),
            "sustained_tok_s": round(gen / wall, 2),
            "p50_ms": lat["p50_ms"],
            "p99_ms": lat["p99_ms"],
            "evictions": eng.scheduler.evictions,
            "unit": "continuous-batching decode, Poisson trace "
                    f"({rps:.0f} rps offered, tiny llama, CPU, "
                    f"paged KV {name}); sustained tok/s + request "
                    "latency percentiles",
        })
    return rows


def trace_overhead_row(n_requests=16, seed=11, repeats=2):
    """Request-tracing overhead on sustained tok/s: the closed-loop
    decode lane (submit everything, drain the engine) measured with
    the kRequest event stream on vs off. Closed-loop on purpose — the
    Poisson replay's arrival sleeps would hide any per-step cost."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from horovod_tpu.models import LlamaConfig, llama_init
    from horovod_tpu.serving.engine import DecodeEngine
    from horovod_tpu.serving.scheduler import poisson_trace
    from horovod_tpu.telemetry import reqtrace

    cfg = LlamaConfig.tiny(dtype="float32", n_layers=2)
    params = llama_init(cfg, jax.random.PRNGKey(0))
    trace = poisson_trace(n_requests, 1000.0, seed=seed,
                          prompt_len=(4, 24), max_new=(4, 24),
                          vocab_size=cfg.vocab_size)

    def run_once():
        eng = DecodeEngine(params, cfg, block_size=8, n_blocks=128,
                           max_batch=8, max_context=64)
        for req in trace:
            eng.submit(req)
        t0 = time.monotonic()
        done = eng.run_until_idle()
        wall = time.monotonic() - t0
        gen = sum(len(t) - len(r.prompt)
                  for r, t in ((req, done[req.rid]) for req in trace))
        return gen / wall

    # Warm every compiled program off the clock (prefill recompiles per
    # prompt length; one full pass covers decode too).
    run_once()
    best = {}
    prior = reqtrace.tracing_enabled()  # restore, don't force-enable:
    # an operator who started with HOROVOD_EVENTS=0 keeps the ring off
    for _ in range(repeats):
        for name, on in (("on", True), ("off", False)):
            reqtrace.set_tracing(on)
            try:
                tok_s = run_once()
            finally:
                reqtrace.set_tracing(prior)
            if name not in best or tok_s > best[name]:
                best[name] = tok_s
    overhead = (best["off"] - best["on"]) / best["off"] * 100.0
    return {
        "metric": "serving_trace_overhead",
        "config": "f32",
        "ranks": 1,
        "requests": n_requests,
        "block_size": 8,
        "tok_s_tracing_on": round(best["on"], 2),
        "tok_s_tracing_off": round(best["off"], 2),
        "overhead_pct": round(overhead, 3),
        "criterion": "overhead_pct < 2 (closed-loop decode, "
                     f"best-of-{repeats}; r15 events bar)",
        "pass": overhead < 2.0,
        "unit": "request-tracing cost on sustained tok/s "
                "(kRequest events on vs off, same engine/trace)",
    }


def main():
    for row in serving_rows():
        print("SERVING_ROW " + json.dumps(row), flush=True)
    print("SERVING_ROW " + json.dumps(trace_overhead_row()), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
