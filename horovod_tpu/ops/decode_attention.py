"""Fused single-position decode attention as a pallas TPU kernel.

The XLA path (models/generate.py's einsum chain) materializes the f32
score tensor [B, G, R, S], the softmax statistics, and the f32->bf16
probability cast as separate HBM round-trips — ~0.07 ms/layer of pure
bandwidth overhead on top of the KV-cache stream at flagship batch 64.
This kernel folds scores + masked softmax + the value contraction into
the one pass that streams the cache: grid (batch, kv-head group), each
program loads its [S, D] K/V slices into VMEM (decode caches are
short — S = prompt + max_new), computes the R grouped query rows
against them, and writes [R, D] back. GQA-native like the rest of the
stack: K/V are read at their stored head count.

Same numeric recipe as the XLA path and the training flash kernel:
f32 scores and softmax, bf16 probabilities into a f32-accumulated PV.
Falls back to the einsum path off-TPU; interpret mode gives the kernel
CPU test coverage (tests/single/test_decode_attention.py).

Reference analog: none (Horovod ships no inference path).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30

# Tests set this to run the kernel in interpret mode on CPU.
_INTERPRET = False


def _kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *, scale):
    # q [R, D]; k/v [S, D] — one (batch, kv-head) slice, fully resident
    # in VMEM (decode S is prompt+max_new, ~hundreds). pos is an SMEM
    # scalar: cache slots <= pos are valid.
    q = q_ref[:, :]
    k = k_ref[:, :]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    valid = lax.broadcasted_iota(jnp.int32, s.shape, 1) <= pos_ref[0]
    s = jnp.where(valid, s, _NEG)
    m = s.max(axis=1, keepdims=True)
    p = jnp.exp(s - m)
    l = p.sum(axis=1, keepdims=True)
    p = (p / l).astype(v_ref.dtype)
    o_ref[:, :] = jax.lax.dot_general(
        p, v_ref[:, :], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def decode_attention(q, cache_k, cache_v, pos):
    """One-token attention against the cache, GQA-native.

    q [B, 1, H, D]; cache_k/v [B, Hkv, S, D] (kernel layout — heads
    major, like the flash kernels, so the pallas block's trailing dims
    are the contiguous [S, D] slice); slots <= pos valid.
    Returns [B, 1, H, D] in q's dtype.
    """
    b, _, hq, d = q.shape
    hkv, s_len = cache_k.shape[1], cache_k.shape[2]
    n_rep = hq // hkv

    # Each grid program holds its whole [S, D] K and V slices plus the
    # f32 score rows in VMEM; past ~long-context cache lengths that
    # exceeds the ~16 MB budget and the kernel cannot lower — fall back
    # to the same-recipe einsum chain (slower per step, any S). Shapes
    # are static, so this is a trace-time choice.
    vmem_bytes = (2 * s_len * d * cache_k.dtype.itemsize  # K + V
                  + n_rep * s_len * 4                     # f32 scores
                  + 2 * n_rep * d * 4)                    # q + out
    if vmem_bytes > 12 * (1 << 20):
        return _decode_attention_xla(q, cache_k, cache_v, pos)

    if not _INTERPRET and jax.devices()[0].platform not in ("tpu", "axon"):
        return _decode_attention_xla(q, cache_k, cache_v, pos)

    qg = q.reshape(b, hkv, n_rep, d)
    pos_arr = jnp.asarray(pos, jnp.int32).reshape(1)
    kernel = functools.partial(_kernel, scale=d ** -0.5)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hkv),
            in_specs=[
                pl.BlockSpec((None, None, n_rep, d),
                             lambda bi, gi, *a: (bi, gi, 0, 0)),
                pl.BlockSpec((None, None, s_len, d),
                             lambda bi, gi, *a: (bi, gi, 0, 0)),
                pl.BlockSpec((None, None, s_len, d),
                             lambda bi, gi, *a: (bi, gi, 0, 0)),
            ],
            out_specs=pl.BlockSpec((None, None, n_rep, d),
                                   lambda bi, gi, *a: (bi, gi, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, n_rep, d), q.dtype),
        interpret=_INTERPRET,
    )(pos_arr, qg, cache_k, cache_v)
    return out.reshape(b, 1, hq, d)


def decode_attention_ragged(q, cache_k, cache_v, lengths, k_new, v_new,
                            k_scale=None, v_scale=None):
    """One-token attention for a CONTINUOUS-BATCHING step: every row of
    the batch sits at its OWN position (``lengths[b]`` — the count of
    valid cached slots), and the new token's k/v ride alongside instead
    of being written into the cache first (the serving engine owns the
    paged write; see horovod_tpu/serving/kvcache.py).

    q [B, 1, H, D]; cache_k/v [B, Hkv, S, D] gathered from the block
    pool (slots < lengths[b] valid); k_new/v_new [B, Hkv, 1, D] — this
    step's projections, attended as position lengths[b]. Masked cache
    slots softmax to exactly 0.0 (exp underflow at -1e30), so the
    result equals attention over the first lengths[b]+1 positions.

    int8 paged read path (``k_scale``/``v_scale`` [B, Hkv, S]): the
    cache arrives int8 with per-block scales expanded per slot, and the
    dequant happens HERE — widen to f32, scale, and accumulate in f32
    (``preferred_element_type``), the quantize-narrow/accumulate-wide
    recipe of the bf16 wire codec and EQuARX (arXiv:2506.17615).
    Numeric recipe otherwise matches :func:`decode_attention`: f32
    scores/softmax, probabilities cast to the value dtype before a
    f32-accumulated PV.
    """
    b, _, hq, d = q.shape
    hkv, s_len = cache_k.shape[1], cache_k.shape[2]
    n_rep = hq // hkv
    qg = q.reshape(b, hkv, n_rep, d)
    if k_scale is not None:
        kc = cache_k.astype(jnp.float32) * k_scale[..., None]
        vc = cache_v.astype(jnp.float32) * v_scale[..., None]
    else:
        kc, vc = cache_k, cache_v
    s = jnp.einsum("bgrd,bgsd->bgrs", qg, kc,
                   preferred_element_type=jnp.float32) * (d ** -0.5)
    valid = (jnp.arange(s_len)[None, :]
             < jnp.asarray(lengths, jnp.int32)[:, None])
    s = jnp.where(valid[:, None, None, :], s, _NEG)
    s_self = jnp.einsum("bgrd,bgsd->bgrs", qg, k_new.astype(kc.dtype),
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    s = jnp.concatenate([s, s_self], axis=-1)
    p = jax.nn.softmax(s, axis=-1)
    # bf16-probabilities recipe: cast to the (dequantized) value dtype.
    p = p.astype(vc.dtype)
    out = (jnp.einsum("bgrs,bgsd->bgrd", p[..., :s_len], vc,
                      preferred_element_type=jnp.float32)
           + jnp.einsum("bgrs,bgsd->bgrd", p[..., s_len:],
                        v_new.astype(vc.dtype),
                        preferred_element_type=jnp.float32))
    return out.reshape(b, 1, hq, d).astype(q.dtype)


def _decode_attention_xla(q, cache_k, cache_v, pos):
    """Reference-math einsum chain (off-TPU fallback; same numerics).
    cache_k/v in the [B, Hkv, S, D] kernel layout."""
    b, _, hq, d = q.shape
    hkv, s_len = cache_k.shape[1], cache_k.shape[2]
    n_rep = hq // hkv
    qg = q.reshape(b, hkv, n_rep, d)
    s = jnp.einsum("bgrd,bgsd->bgrs", qg, cache_k,
                   preferred_element_type=jnp.float32) * (d ** -0.5)
    valid = jnp.arange(s_len) <= pos
    s = jnp.where(valid[None, None, None, :], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bgrs,bgsd->bgrd", p.astype(cache_v.dtype),
                     cache_v, preferred_element_type=jnp.float32)
    return out.reshape(b, 1, hq, d).astype(q.dtype)
