"""Dropless sorted grouped-GEMM mixture-of-experts FFN (TPU-first).

Why: the GShard dispatch path (``models/llama.py:_moe_ffn``) pays two
structural taxes on a single chip:

1. the one-hot dispatch/combine einsums ``btec,btd->becd`` /
   ``btec,becd->btd`` are real matmuls — at bench shape (B4 T2048 E4
   C1280 D2048) they cost ~2x86 GFLOP/layer against ~1030 GFLOP for the
   expert FFN itself (a ~17% pure-overhead FLOP tax), and
2. capacity-factor padding makes the expert GEMMs compute E*C =
   T*K*capacity_factor token-slots instead of the T*K that carry
   tokens (+25% at cf=1.25) — waste that active-param MFU accounting
   charges straight to the implementation.

This path removes both: flatten the (token, k) slots, ``argsort`` them
by routed expert (16K int32 keys — microseconds), gather the activation
rows once, and run the three expert projections as ragged grouped
matmuls (``jax.experimental.pallas.ops.tpu.megablox.gmm`` — measured at
dense-matmul throughput on v5e). Every token-slot is computed — no
capacity, no dropped tokens (dropless), no padding FLOPs. The
un-permutation is a custom-VJP gather whose backward is the inverse
gather, so no XLA scatter ever appears on the hot path.

Sharding: this path is for programs where the experts are NOT sharded
over an ``expert`` mesh axis (single chip, or EP-free meshes) — the
sort is a per-program global op. Expert-parallel meshes keep the GShard
grouped-einsum path, whose [G, E, C, D] buffers give GSPMD the clean
all-to-all seam (``LlamaConfig.moe_impl`` documents the dispatch).

Reference analog: none (Horovod has no model layer); the design follows
the public dropless-MoE formulation (MegaBlocks) re-founded on TPU
primitives.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

# Megablox tile sizes (m, k, n), clamped to the problem dims. Swept on
# a v5e chip at bench shape (m=16K, D=2048, F=4096): large k/n tiles
# beat the (128,128,128) default by ~2x; m=512 keeps the ragged group
# boundaries cheap. The three directions get INDEPENDENT tilings —
# megablox's stock custom_vjp reuses the forward tiling for dlhs and
# tgmm, so one direction's compiler ceiling caps all three. (On this
# box every tile > 1024 in any direction crashes the AOT compile
# helper, so all three sit at the shared optimum; the seam is for
# standard libtpu stacks. Gradient parity with the stock VJP is pinned
# on-chip — see docs/benchmarks.md.)
_TILING = (512, 1024, 1024)          # forward gmm
_TILING_DLHS = (512, 1024, 1024)     # backward dlhs gmm (transposed rhs)
_TILING_TGMM = (512, 1024, 1024)     # backward dW tgmm


def _on_tpu():
    return jax.devices()[0].platform in ("tpu", "axon")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _unpermute(x, perm, _n):
    """``x[perm]`` where ``perm`` is a PERMUTATION (bijective): the VJP
    is the gather by the inverse permutation — never an XLA scatter.
    ``perm`` rides as a regular traced operand; its cotangent is the
    symbolic zero for ints. ``_n`` is unused padding to keep the vjp
    signature stable (nondiff static)."""
    return jnp.take(x, perm, axis=0)


def _unpermute_fwd(x, perm, _n):
    return jnp.take(x, perm, axis=0), perm


def _unpermute_bwd(_n, perm, g):
    # inverse gather: out[perm[i]] = g[i]  <=>  out = g[argsort(perm)]
    return jnp.take(g, jnp.argsort(perm), axis=0), None


_unpermute.defvjp(_unpermute_fwd, _unpermute_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _dispatch_gather(h, slot_token, sorted_order, K):
    """Rows of ``h`` [S, D] replicated K ways and permuted into expert
    order in ONE gather: out[i] = h[slot_token[i]] ([S*K, D]).

    ``slot_token = sorted_order // K`` (token of each sorted slot). The
    VJP avoids a duplicate-index scatter: un-permute the cotangent back
    to (token, k) slot order with the inverse permutation, then sum the
    K slots of each token — a reshape + reduce.
    """
    return jnp.take(h, slot_token, axis=0)


def _dispatch_gather_fwd(h, slot_token, sorted_order, K):
    return jnp.take(h, slot_token, axis=0), sorted_order


def _dispatch_gather_bwd(K, sorted_order, g):
    flat = jnp.take(g, jnp.argsort(sorted_order), axis=0)  # slot order
    dh = flat.reshape(-1, K, g.shape[-1]).sum(axis=1)
    return dh, None, None


_dispatch_gather.defvjp(_dispatch_gather_fwd, _dispatch_gather_bwd)


def _clamp(tiling, m, k, n):
    tm, tk, tn = tiling
    return (min(tm, m), min(tk, k), min(tn, n))


def _bwd_tilings(m, k, n):
    """Per-direction backward tilings clamped against EACH matmul's own
    (rows, contraction, out) dims — NOT the forward's (m, k, n).

    - dlhs runs ``gmm(grad [m,n], rhs [E,k,n], transpose_rhs=True)``:
      gmm reads its problem dims as (m, lhs.shape[1], rhs.shape[1]) =
      (m, n, k) — contraction over n, output k;
    - tgmm runs ``tgmm(lhs^T [k,m], grad [m,n])``: its (m, k, n) are
      (lhs.shape[1], lhs.shape[0], rhs.shape[1]) = (m, k, n), which
      COINCIDES with the forward dims (the contraction is over m, which
      tm tiles).

    Clamping dlhs against the forward dims handed it a tile larger than
    its real contraction/output whenever k and n straddle the 1024 tile
    boundary (d_model < 1024 <= d_ff — the gate/up projections'
    backward; ADVICE r5). Shapes pinned by
    tests/single/test_grouped_moe.py::test_bwd_tilings_clamp_per_direction.
    """
    return (_clamp(_TILING_DLHS, m, n, k),   # dlhs: (m, n, k)
            _clamp(_TILING_TGMM, m, k, n))   # tgmm: (m, k, n)


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _gmm_tpu(lhs, rhs, group_sizes):
    from jax.experimental.pallas.ops.tpu.megablox.gmm import gmm

    m, k = lhs.shape
    n = rhs.shape[-1]
    return gmm(lhs, rhs, group_sizes,
               preferred_element_type=lhs.dtype,
               tiling=_clamp(_TILING, m, k, n))


def _gmm_tpu_fwd(lhs, rhs, group_sizes):
    return _gmm_tpu(lhs, rhs, group_sizes), (lhs, rhs, group_sizes)


def _gmm_tpu_bwd(res, grad):
    # Same decomposition as megablox's stock VJP (ops.py), but each
    # direction gets its own tiling: dlhs = grad @ rhs^T via gmm with
    # transpose_rhs, dW via the transposed-lhs tgmm kernel.
    from jax.experimental.pallas.ops.tpu.megablox.gmm import gmm, tgmm

    lhs, rhs, group_sizes = res
    m, k = lhs.shape
    n = rhs.shape[-1]
    dlhs_tiling, tgmm_tiling = _bwd_tilings(m, k, n)
    dlhs = gmm(grad, rhs, group_sizes, lhs.dtype,
               dlhs_tiling, transpose_rhs=True)
    drhs = tgmm(lhs.swapaxes(0, 1), grad, group_sizes, rhs.dtype,
                tgmm_tiling)
    return dlhs, drhs, None


_gmm_tpu.defvjp(_gmm_tpu_fwd, _gmm_tpu_bwd)


def _grouped_mm(lhs, rhs, group_sizes):
    """Ragged grouped matmul: rows of ``lhs`` [M, K] are grouped
    contiguously per ``group_sizes`` [E]; ``rhs`` [E, K, N]. On TPU this
    is the megablox pallas kernel (dense-matmul throughput, f32
    accumulation) under our per-direction-tiling custom VJP. Off-TPU
    tests use an exact one-hot einsum (tiny shapes only)."""
    if _on_tpu():
        return _gmm_tpu(lhs, rhs, group_sizes)
    # Exact fallback: expert id per row from the group layout, then a
    # one-hot contraction (f32-exact; O(M*E*K*N) — test shapes only).
    eid = jnp.sum(jnp.arange(lhs.shape[0])[:, None]
                  >= jnp.cumsum(group_sizes)[None, :], axis=1)
    sel = jax.nn.one_hot(eid, rhs.shape[0], dtype=lhs.dtype)
    return jnp.einsum("se,sk,ekn->sn", sel, lhs, rhs)


def grouped_moe_ffn(h, lp, c):
    """Dropless top-K routed expert FFN over ``h`` [B, T, D] with the
    layer params ``lp`` (router [D, E], moe_gate/moe_up [E, D, F],
    moe_down [E, F, D]). Returns (out [B, T, D], aux loss) — the same
    contract, router math, gate normalization, and Switch aux loss as
    the GShard path (``models/llama.py:_moe_ffn``), with no capacity
    dropping (every token-slot is computed).
    """
    B, T, D = h.shape
    E, K = c.n_experts, c.n_experts_per_token
    S = B * T
    dt = c.compute_dtype
    hf = h.reshape(S, D)

    # Shared router (llama.moe_route): identical math and aux value to
    # the GShard path's (means over flat S == means over (B, T)).
    from horovod_tpu.models.llama import moe_route

    gate_vals, gate_idx, aux = moe_route(hf, lp["router"], K)  # [S, K]

    # Sort the S*K (token, k) slots by routed expert. Indices are data
    # (not differentiated); stop_gradient keeps the int chain out of
    # the autodiff graph entirely.
    e_flat = lax.stop_gradient(gate_idx.reshape(S * K))
    order = jnp.argsort(e_flat)                    # sorted slot -> slot
    group_sizes = jnp.bincount(e_flat, length=E).astype(jnp.int32)

    # Residual names for the "moe" remat mode (save the expert-GEMM
    # chain so backward re-runs NO grouped matmul): x_sorted is the
    # tgmm lhs for dW_gate/dW_up; the PRE-silu gate is what silu's vjp
    # needs; up pairs with it for the product rule.
    x_sorted = checkpoint_name(
        _dispatch_gather(hf.astype(dt), order // K, order, K),
        "moe_x_sorted")

    gate_pre = checkpoint_name(
        _grouped_mm(x_sorted, lp["moe_gate"].astype(dt), group_sizes),
        "moe_gate_act")
    up = checkpoint_name(
        _grouped_mm(x_sorted, lp["moe_up"].astype(dt), group_sizes),
        "moe_up_act")
    y_sorted = _grouped_mm(jax.nn.silu(gate_pre) * up,
                           lp["moe_down"].astype(dt),
                           group_sizes)            # [S*K, D]

    # Un-permute to slot order (inverse-gather VJP) and combine with
    # the normalized gate weights. Named for the "attn+moe" remat mode:
    # the router's combine-weight gradient needs y_slots (d gate_vals =
    # <dy, y_slots>), which is what forces the backward remat to re-run
    # the down-projection gmm — saving it trades [S*K, D] bf16 per
    # layer for that re-run.
    y_slots = checkpoint_name(
        _unpermute(y_sorted, jnp.argsort(order), S * K), "moe_y_slots")
    y = (y_slots.reshape(S, K, D)
         * gate_vals.astype(dt)[..., None]).sum(axis=1)
    return y.reshape(B, T, D), aux
