"""horovod_tpu.ops — pallas TPU kernels for the hot ops.

Reference analog: the reference's CUDA kernels
(``horovod/common/ops/cuda_kernels.cu`` — batched memcpy/scale); on TPU
the equivalent hand-written layer is pallas kernels for ops XLA doesn't
schedule optimally by itself. Flash attention is the flagship: it
removes the T² score materialization that otherwise forces full remat.
"""

from horovod_tpu.ops.flash_attention import flash_attention  # noqa: F401
