"""Flash attention as a pallas TPU kernel (forward + custom-VJP backward).

Why: plain attention materializes the [B,H,T,T] score matrix; at the
bench shape (B8 H16 T2048 f32) that is 2 GB per layer — XLA must either
spill to HBM or the model must full-remat (33% extra FLOPs). Blockwise
online-softmax attention keeps everything in VMEM; the residuals are
just the output and the per-row logsumexp.

Kernel design (v5e-friendly):
- layout [B, H, T, D]; 4-D grid over (batch, head, outer-block,
  inner-block) with the INNER loop as the last grid dimension, so
  every operand is streamed block-by-block: VMEM residency is
  O(block_q·block_k + (block_q+block_k)·D) — independent of sequence
  length. (The round-3 kernels kept whole-(b,h) K/V or Q/dO slices
  resident, which capped the single-chip backward at T≈4096 with a
  scoped-VMEM compile error.)
- online-softmax / gradient accumulators are f32 VMEM scratch that
  persists across the inner grid steps; outputs are written on the
  last inner step. bf16 matmul inputs (MXU native),
  `preferred_element_type=f32`.
- causal masking by global position iota; whole causally-irrelevant
  blocks are skipped with `pl.when` (the block's DMA still streams,
  but it costs bandwidth only — no MXU work).
- backward = two kernels (dkv over kv-blocks with q streamed, dq over
  q-blocks with kv streamed), the standard flash decomposition with
  the saved logsumexp.

Falls back to the XLA blockwise implementation off-TPU (pallas interpret
mode is too slow for real runs; CPU tests exercise the same math via
``horovod_tpu.parallel.blockwise_attention``).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30

# Tests set this to run the kernels in pallas interpret mode on CPU —
# the only way the TPU code paths (incl. the bias branches) get CI
# coverage without a chip.
_INTERPRET = False


def _fwd_kernel(*refs, scale, causal, has_bias, has_offsets):
    # refs = ([offs_ref,] q_ref, k_ref, v_ref, [bias_ref,] o_ref,
    # lse_ref, acc_ref, m_ref, l_ref). grid = (b, h, iq, jj): q/o/lse
    # blocks are keyed by iq (constant across the inner jj steps), k/v
    # stream per jj; the online-softmax state lives in f32 VMEM scratch
    # persisted across jj and the output is written on the last step.
    # bias is a per-key additive f32 row [1, Tk] (padding masks).
    # offs_ref is an SMEM int32 [2] = (q_offset, kv_offset): GLOBAL
    # positions for causal masking when the call sees only a chunk of
    # the sequence (ring attention steps) — dynamic, so one compiled
    # kernel serves every ring step.
    if has_offsets:
        offs_ref, q_ref, k_ref, v_ref, *rest = refs
    else:
        (q_ref, k_ref, v_ref), rest = refs[:3], list(refs[3:])
        offs_ref = None
    if has_bias:
        bias_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
        bias_ref = None
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    iq = pl.program_id(2)
    jj = pl.program_id(3)
    n_jj = pl.num_programs(3)

    @pl.when(jj == 0)
    def _init():
        acc_ref[:, :] = jnp.zeros((bq, d), jnp.float32)
        m_ref[:, :] = jnp.full((bq, 1), _NEG, jnp.float32)
        l_ref[:, :] = jnp.zeros((bq, 1), jnp.float32)

    q_base = offs_ref[0] if has_offsets else 0
    kv_base = offs_ref[1] if has_offsets else 0
    # Whole-block causal skip: the block's first GLOBAL kv position must
    # not be past this q block's last GLOBAL row (with offsets the bases
    # are scalar-prefetched SMEM values, so the predicate is dynamic —
    # a causal ring's fully-future chunks cost zero matmuls).
    relevant = True
    if causal:
        relevant = kv_base + jj * bk <= q_base + (iq + 1) * bq - 1

    @pl.when(relevant)
    def _update():
        q = q_ref[:, :]
        k_blk = k_ref[:, :]
        v_blk = v_ref[:, :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_base + iq * bq + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            kv_pos = kv_base + jj * bk + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= kv_pos, s, _NEG)
        if has_bias:
            s = s + bias_ref[:, :]
        m = m_ref[:, :]
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_ref[:, :] = l_ref[:, :] * corr + p.sum(axis=1, keepdims=True)
        acc_ref[:, :] = acc_ref[:, :] * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, :] = m_new

    @pl.when(jj == n_jj - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :], 1e-30)
        # A q row with ZERO valid keys (possible in ring/offset chunks
        # whose kv chunk is entirely future) keeps m == _NEG, so
        # p = exp(s - m) = 1 uniformly and acc/l would be mean-of-V.
        # Zero those rows: their lse stays ~_NEG, so ring logsumexp
        # merging weights them out anyway, but the standalone chunk
        # output must be correct in its own right.
        valid = m_ref[:, :] > _NEG / 2
        o_ref[:, :] = jnp.where(
            valid, acc_ref[:, :] / l, 0.0).astype(o_ref.dtype)
        lse_ref[:, :] = m_ref[:, :] + jnp.log(l)


def _bwd_dkv_kernel(*refs, scale, causal, has_bias, has_offsets):
    # grid = (b, h, jk, iq): k/v/dk/dv blocks are keyed by jk (constant
    # across the inner iq steps), q/do/lse/delta stream per iq; dk/dv
    # accumulate in f32 VMEM scratch and are written on the last step.
    if has_offsets:
        offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, \
            *rest = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest = refs
        offs_ref = None
    if has_bias:
        bias_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
        bias_ref = None
    bk, d = k_ref.shape
    bq = q_ref.shape[0]
    jk = pl.program_id(2)
    iq = pl.program_id(3)
    n_iq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:, :] = jnp.zeros((bk, d), jnp.float32)
        dv_acc[:, :] = jnp.zeros((bk, d), jnp.float32)

    q_base = offs_ref[0] if has_offsets else 0
    kv_base = offs_ref[1] if has_offsets else 0
    relevant = True
    if causal:
        # This q block contributes iff its last GLOBAL row reaches the
        # kv block's first GLOBAL position.
        relevant = q_base + (iq + 1) * bq - 1 >= kv_base + jk * bk

    @pl.when(relevant)
    def _update():
        k = k_ref[:, :]
        v = v_ref[:, :]
        qi = q_ref[:, :]
        doi = do_ref[:, :]
        lse = lse_ref[:, :]
        delta = delta_ref[:, :]
        s = jax.lax.dot_general(
            qi, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_base + iq * bq + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            kv_pos = kv_base + jk * bk + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= kv_pos, s, _NEG)
        if has_bias:
            s = s + bias_ref[:, :]
        # For a q row with ZERO valid keys lse is itself ~_NEG, so
        # exp(s - lse) rounds to 1 per masked key — guard on s directly
        # (valid rows are unaffected: their masked keys underflow to 0).
        p = jnp.where(s > _NEG / 2, jnp.exp(s - lse), 0.0)  # [bq, bk]
        dv_acc[:, :] = dv_acc[:, :] + jax.lax.dot_general(
            p.astype(doi.dtype), doi, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            doi, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:, :] = dk_acc[:, :] + jax.lax.dot_general(
            ds.astype(qi.dtype), qi, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == n_iq - 1)
    def _finish():
        dk_ref[:, :] = dk_acc[:, :].astype(dk_ref.dtype)
        dv_ref[:, :] = dv_acc[:, :].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, scale, causal, has_bias, has_offsets):
    # grid = (b, h, iq, jj): q/do/lse/delta/dq blocks are keyed by iq
    # (constant across the inner jj steps), k/v stream per jj; dq
    # accumulates in f32 VMEM scratch, written on the last step.
    if has_offsets:
        offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, \
            *rest = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest = refs
        offs_ref = None
    if has_bias:
        bias_ref, dq_ref, dq_acc = rest
    else:
        dq_ref, dq_acc = rest
        bias_ref = None
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    iq = pl.program_id(2)
    jj = pl.program_id(3)
    n_jj = pl.num_programs(3)

    @pl.when(jj == 0)
    def _init():
        dq_acc[:, :] = jnp.zeros((bq, d), jnp.float32)

    q_base = offs_ref[0] if has_offsets else 0
    kv_base = offs_ref[1] if has_offsets else 0
    relevant = True
    if causal:
        relevant = kv_base + jj * bk <= q_base + (iq + 1) * bq - 1

    @pl.when(relevant)
    def _update():
        q = q_ref[:, :]
        do = do_ref[:, :]
        lse = lse_ref[:, :]
        delta = delta_ref[:, :]
        k_blk = k_ref[:, :]
        v_blk = v_ref[:, :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_base + iq * bq + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            kv_pos = kv_base + jj * bk + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= kv_pos, s, _NEG)
        if has_bias:
            s = s + bias_ref[:, :]
        # Same zero-valid-key guard as the dkv kernel (see there).
        p = jnp.where(s > _NEG / 2, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:, :] = dq_acc[:, :] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jj == n_jj - 1)
    def _finish():
        dq_ref[:, :] = dq_acc[:, :].astype(dq_ref.dtype)


def _pallas_dispatch(kernel, grid, in_specs, out_specs, out_shape, args,
                     offsets, scratch_shapes):
    """Shared fwd/bwd dispatch: plain grid, or scalar-prefetch grid
    spec when dynamic offsets ride along (the SMEM scalars arrive
    before the kernel body and every index map). ``scratch_shapes``
    are the f32 VMEM accumulators that persist across the inner grid
    dimension."""
    if offsets is not None:
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=grid, in_specs=in_specs,
                out_specs=out_specs, scratch_shapes=scratch_shapes),
            out_shape=out_shape, interpret=_INTERPRET,
        )(offsets, *args)
    return pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=_INTERPRET,
        scratch_shapes=scratch_shapes)(*args)


def _pick_block(t, want):
    """Largest divisor of t that is <= want (t is a power-of-two seq in
    practice; degrade gracefully otherwise)."""
    b = min(want, t)
    while t % b:
        b -= 1
    return b


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    o, _ = _flash_fwd_impl(q, k, v, None, causal, block_q, block_k)
    return o


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_biased(q, k, v, bias, causal, block_q, block_k):
    o, _ = _flash_fwd_impl(q, k, v, bias, causal, block_q, block_k)
    return o


def _flash_fwd_impl(q, k, v, bias, causal, block_q, block_k,
                    offsets=None):
    b, h, t, d = q.shape
    tk = k.shape[2]
    # GQA-native: k/v arrive UNREPEATED ([B, Hkv, T, D]); each query
    # head's block specs index kv-head hi // n_rep, so the n_rep-fold
    # expansion never materializes in HBM (the repeat would cost a copy
    # per call and double the saved k/v residuals).
    n_rep = h // k.shape[1]
    scale = d ** -0.5
    grid = (b, h, t // block_q, tk // block_k)
    has_bias = bias is not None
    has_offsets = offsets is not None
    kernel = functools.partial(_fwd_kernel, scale=scale,
                               causal=causal, has_bias=has_bias,
                               has_offsets=has_offsets)
    # With scalar prefetch the index maps receive the scalar ref as a
    # trailing arg; *a soaks it up either way.
    in_specs = [
        pl.BlockSpec((None, None, block_q, d),
                     lambda bi, hi, qi, ji, *a: (bi, hi, qi, 0)),
        pl.BlockSpec((None, None, block_k, d),
                     lambda bi, hi, qi, ji, *a: (bi, hi // n_rep, ji, 0)),
        pl.BlockSpec((None, None, block_k, d),
                     lambda bi, hi, qi, ji, *a: (bi, hi // n_rep, ji, 0)),
    ]
    args = [q, k, v]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((None, 1, block_k),
                         lambda bi, hi, qi, ji, *a: (bi, 0, ji)))
        args.append(bias)
    out_specs = [
        pl.BlockSpec((None, None, block_q, d),
                     lambda bi, hi, qi, ji, *a: (bi, hi, qi, 0)),
        pl.BlockSpec((None, None, block_q, 1),
                     lambda bi, hi, qi, ji, *a: (bi, hi, qi, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
    ]
    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),   # acc
        pltpu.VMEM((block_q, 1), jnp.float32),   # m
        pltpu.VMEM((block_q, 1), jnp.float32),   # l
    ]
    return _pallas_dispatch(kernel, grid, in_specs, out_specs, out_shape,
                            args, offsets, scratch)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    o, lse = _flash_fwd_impl(q, k, v, None, causal, block_q, block_k)
    # Residuals named for remat policies: an outer checkpoint_name on
    # the returned o covers only the PRIMAL output — the residual o/lse
    # here are distinct jaxpr vars, and leaving them unnamed makes
    # jax.checkpoint re-run this whole kernel in the backward pass just
    # to regenerate lse (a [B,H,T,1] f32 — ~1 MB/layer at bench shapes,
    # vs a full flash forward to recompute). Profiled round 3: the
    # rerun cost ~12% of the train step.
    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_biased_fwd(q, k, v, bias, causal, block_q, block_k):
    o, lse = _flash_fwd_impl(q, k, v, bias, causal, block_q, block_k)
    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, bias, o, lse)


def _flash_bwd_impl(q, k, v, bias, o, lse, do, causal, block_q, block_k,
                    offsets=None, dlse=None):
    b, h, t, d = q.shape
    hkv = k.shape[1]
    tk = k.shape[2]
    n_rep = h // hkv
    scale = d ** -0.5
    has_bias = bias is not None
    has_offsets = offsets is not None
    delta = (do.astype(jnp.float32)
             * o.astype(jnp.float32)).sum(-1, keepdims=True)
    if dlse is not None:
        # An incoming lse cotangent folds into delta: ds = p*(dp - delta)
        # becomes p*(dp - delta + dlse), i.e. delta -= dlse.
        delta = delta - dlse.astype(jnp.float32)

    def call(kernel, grid, in_specs, out_specs, out_shape, args,
             scratch):
        return _pallas_dispatch(kernel, grid, in_specs, out_specs,
                                out_shape, args, offsets, scratch)

    # dkv: grid (b, h, jk, iq) — q/do/lse/delta stream over the inner
    # iq dimension, k/v and the dk/dv accumulators stay pinned per jk.
    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale,
                                   causal=causal, has_bias=has_bias,
                                   has_offsets=has_offsets)
    in_specs = [
        pl.BlockSpec((None, None, block_q, d),
                     lambda bi, hi, jk, iq, *a: (bi, hi, iq, 0)),
        pl.BlockSpec((None, None, block_k, d),
                     lambda bi, hi, jk, iq, *a: (bi, hi // n_rep, jk, 0)),
        pl.BlockSpec((None, None, block_k, d),
                     lambda bi, hi, jk, iq, *a: (bi, hi // n_rep, jk, 0)),
        pl.BlockSpec((None, None, block_q, d),
                     lambda bi, hi, jk, iq, *a: (bi, hi, iq, 0)),
        pl.BlockSpec((None, None, block_q, 1),
                     lambda bi, hi, jk, iq, *a: (bi, hi, iq, 0)),
        pl.BlockSpec((None, None, block_q, 1),
                     lambda bi, hi, jk, iq, *a: (bi, hi, iq, 0)),
    ]
    args = [q, k, v, do, lse, delta]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((None, 1, block_k),
                         lambda bi, hi, jk, iq, *a: (bi, 0, jk)))
        args.append(bias)
    # dk/dv come out PER QUERY HEAD ([B, H, Tk, D]); the sum over each
    # kv-head's n_rep sharing query heads happens outside the kernel
    # (one cheap XLA reduction — keeps the kernel free of cross-kv-head
    # accumulation state).
    dk, dv = call(
        dkv_kernel, (b, h, tk // block_k, t // block_q), in_specs,
        [
            pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, jk, iq, *a: (bi, hi, jk, 0)),
            pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, jk, iq, *a: (bi, hi, jk, 0)),
        ],
        [
            jax.ShapeDtypeStruct((b, h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, tk, d), v.dtype),
        ],
        args,
        [pltpu.VMEM((block_k, d), jnp.float32),
         pltpu.VMEM((block_k, d), jnp.float32)])
    if n_rep > 1:
        dk = dk.astype(jnp.float32).reshape(b, hkv, n_rep, tk, d) \
            .sum(axis=2).astype(k.dtype)
        dv = dv.astype(jnp.float32).reshape(b, hkv, n_rep, tk, d) \
            .sum(axis=2).astype(v.dtype)

    # dq: grid (b, h, iq, jj) — k/v stream over the inner jj dimension,
    # q/do/lse/delta and the dq accumulator stay pinned per iq.
    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale,
                                  causal=causal, has_bias=has_bias,
                                  has_offsets=has_offsets)
    in_specs = [
        pl.BlockSpec((None, None, block_q, d),
                     lambda bi, hi, qi, ji, *a: (bi, hi, qi, 0)),
        pl.BlockSpec((None, None, block_k, d),
                     lambda bi, hi, qi, ji, *a: (bi, hi // n_rep, ji, 0)),
        pl.BlockSpec((None, None, block_k, d),
                     lambda bi, hi, qi, ji, *a: (bi, hi // n_rep, ji, 0)),
        pl.BlockSpec((None, None, block_q, d),
                     lambda bi, hi, qi, ji, *a: (bi, hi, qi, 0)),
        pl.BlockSpec((None, None, block_q, 1),
                     lambda bi, hi, qi, ji, *a: (bi, hi, qi, 0)),
        pl.BlockSpec((None, None, block_q, 1),
                     lambda bi, hi, qi, ji, *a: (bi, hi, qi, 0)),
    ]
    args = [q, k, v, do, lse, delta]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((None, 1, block_k),
                         lambda bi, hi, qi, ji, *a: (bi, 0, ji)))
        args.append(bias)
    dq = call(
        dq_kernel, (b, h, t // block_q, tk // block_k), in_specs,
        pl.BlockSpec((None, None, block_q, d),
                     lambda bi, hi, qi, ji, *a: (bi, hi, qi, 0)),
        jax.ShapeDtypeStruct(q.shape, q.dtype), args,
        [pltpu.VMEM((block_q, d), jnp.float32)])
    return dq, dk, dv


def _flash_bwd(causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    return _flash_bwd_impl(q, k, v, None, o, lse, do, causal, block_q,
                           block_k)


def _flash_biased_bwd(causal, block_q, block_k, res, do):
    q, k, v, bias, o, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, bias, o, lse, do, causal,
                                 block_q, block_k)
    # The bias is a padding mask (piecewise-constant); its cotangent is
    # never consumed, so report zeros rather than paying a reduction.
    return dq, dk, dv, jnp.zeros_like(bias)


_flash.defvjp(_flash_fwd, _flash_bwd)
_flash_biased.defvjp(_flash_biased_fwd, _flash_biased_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_offsets(q, k, v, offsets, causal, block_q, block_k):
    """Flash attention over a K/V CHUNK with dynamic global-position
    offsets (SMEM scalars — one compiled kernel serves every ring
    step). Returns (o, lse): the normalized chunk output plus its
    logsumexp, exactly what ring attention's online-softmax merge
    needs. q [B,H,Tq,D]; k,v [B,Hkv,Tk,D]; offsets int32 [2] =
    (global q start, global kv start)."""
    return _flash_fwd_impl(q, k, v, None, causal, block_q, block_k,
                           offsets=offsets)


def _flash_offsets_fwd(q, k, v, offsets, causal, block_q, block_k):
    o, lse = _flash_fwd_impl(q, k, v, None, causal, block_q, block_k,
                             offsets=offsets)
    # Same residual naming as _flash_fwd: without it, remat="attn"
    # re-runs every ring step's forward kernel in backward just to
    # regenerate these (n ring steps per layer).
    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return (o, lse), (q, k, v, offsets, o, lse)


def _flash_offsets_bwd(causal, block_q, block_k, res, cts):
    q, k, v, offsets, o, lse = res
    do, dlse = cts
    dq, dk, dv = _flash_bwd_impl(q, k, v, None, o, lse, do, causal,
                                 block_q, block_k, offsets=offsets,
                                 dlse=dlse)
    import numpy as _np

    d_offs = _np.zeros(offsets.shape, jax.dtypes.float0)
    return dq, dk, dv, d_offs


_flash_offsets.defvjp(_flash_offsets_fwd, _flash_offsets_bwd)


def flash_attention_chunk(q, k, v, q_offset, kv_offset, causal=True,
                          block_q=1024, block_k=1024):
    """One ring-attention step on the pallas kernels: attention of the
    local queries against ONE K/V chunk, with global positions for the
    causal mask. Layout [B, H(q)/Hkv(kv), T, D] (kernel layout — ring
    loops keep tensors there to avoid per-step transposes). Returns
    ``(o, lse)`` ready for logsumexp merging; differentiable (the lse
    cotangent folds into the backward's delta).
    """
    bq = _pick_block(q.shape[2], block_q)
    bk = _pick_block(k.shape[2], block_k)
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(kv_offset, jnp.int32)])
    return _flash_offsets(q, k, v, offsets, causal, bq, bk)


def _masked_attention_xla(q, k, v, kv_bias, causal):
    """Reference-math fallback with a per-key additive bias (CPU tests;
    shapes there are tiny, so materializing scores is fine)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / (d ** 0.5)
    s = s + kv_bias[:, None, None, :].astype(jnp.float32)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def flash_attention(q, k, v, causal=True, kv_bias=None, block_q=1024,
                    block_k=1024):
    """Flash attention. q,k,v: [B, T, H, D] (framework layout; kv heads
    may be fewer — GQA is handled natively: the kernels index kv-head
    ``query_head // n_rep``, so the expansion never materializes in
    HBM). Returns [B, T, H, D].

    ``kv_bias`` is an optional [B, Tk] f32 additive per-key bias —
    padding masks pass 0 for real keys and a large negative for padding
    (BERT-style bidirectional attention over ragged batches). It is
    treated as a CONSTANT (stop_gradient on every path): masks have no
    useful gradient, and the TPU kernel does not compute one.

    TPU: pallas kernel. Elsewhere: falls back to the XLA blockwise
    implementation (same math, used by CPU tests).
    """
    from horovod_tpu.parallel.ring_attention import _repeat_kv

    if kv_bias is not None:
        kv_bias = lax.stop_gradient(kv_bias)
    n_rep = q.shape[2] // k.shape[2]
    # _INTERPRET forces the pallas path off-TPU so tests cover the real
    # kernel code (interpret mode) instead of the fallback.
    if not _INTERPRET and jax.devices()[0].platform not in ("tpu", "axon"):
        # The fallback paths name their output for remat="attn" here —
        # keeping the naming NEXT TO the platform predicate means a
        # future fallback reason can't silently lose the saved
        # activation (the pallas path instead names its VJP residuals,
        # flash_o/flash_lse, in _flash_fwd).
        if kv_bias is not None:
            return checkpoint_name(
                _masked_attention_xla(q, _repeat_kv(k, n_rep),
                                      _repeat_kv(v, n_rep), kv_bias,
                                      causal), "attn_out")
        from horovod_tpu.parallel.ring_attention import blockwise_attention

        return checkpoint_name(blockwise_attention(q, k, v, causal=causal),
                               "attn_out")

    # [B,T,H,D] -> [B,H,T,D]; k/v stay at Hkv heads — the kernels index
    # kv-head = query-head // n_rep, so GQA expansion never hits HBM.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    t = qt.shape[2]
    bq = _pick_block(t, block_q)
    bk = _pick_block(t, block_k)
    if kv_bias is not None:
        bias = kv_bias.astype(jnp.float32)[:, None, :]  # [B, 1, Tk]
        o = _flash_biased(qt, kt, vt, bias, causal, bq, bk)
    else:
        o = _flash(qt, kt, vt, causal, bq, bk)
    return o.transpose(0, 2, 1, 3)
