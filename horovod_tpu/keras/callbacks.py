"""Keras callbacks.

Reference analog: ``horovod/_keras/callbacks.py`` — the canonical
broadcast / metric-average / LR-warmup callbacks every Horovod Keras
script uses.
"""

import tensorflow as tf

import horovod_tpu.tensorflow as hvd


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast all model/optimizer variables from root at train start."""

    def __init__(self, root_rank=0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_begin(self, batch, logs=None):
        if self.broadcast_done:
            return
        hvd.broadcast_variables(self.model.variables,
                                root_rank=self.root_rank, prefix="model")
        if getattr(self.model, "optimizer", None) is not None:
            hvd.broadcast_variables(self.model.optimizer.variables,
                                    root_rank=self.root_rank, prefix="opt")
        self.broadcast_done = True


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch metrics over ranks (so rank-0 logs/checkpoint
    decisions see global values)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            for k in sorted(logs.keys()):
                try:
                    val = float(logs[k])
                except (TypeError, ValueError):
                    continue
                import numpy as np

                logs[k] = float(
                    hvd.allreduce(np.array(val, np.float64),
                                  name=f"metric.{k}").numpy())


class LearningRateWarmupCallback(tf.keras.callbacks.Callback):
    """Linear LR warmup over the first epochs: scale from initial_lr/size
    * 1 up to initial_lr * multiplier (reference: the facebook 1-hour
    ImageNet recipe baked into horovod's callbacks)."""

    def __init__(self, initial_lr, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0):
        super().__init__()
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self.current_epoch = 0

    def _set_lr(self, lr):
        opt = self.model.optimizer
        if hasattr(opt, "learning_rate"):
            opt.learning_rate = lr

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_batch_begin(self, batch, logs=None):
        if self.current_epoch >= self.warmup_epochs:
            return
        if self.steps_per_epoch:
            progress = ((self.current_epoch * self.steps_per_epoch + batch)
                        / (self.warmup_epochs * self.steps_per_epoch))
        else:
            progress = self.current_epoch / max(self.warmup_epochs, 1)
        lr = self.initial_lr * (1.0 / hvd.size()
                                + progress * (1 - 1.0 / hvd.size()))
        self._set_lr(lr)

    def on_epoch_end(self, epoch, logs=None):
        if epoch == self.warmup_epochs - 1:
            self._set_lr(self.initial_lr)


class LearningRateScheduleCallback(tf.keras.callbacks.Callback):
    """Piecewise LR multiplier schedule (reference:
    LearningRateScheduleCallback)."""

    def __init__(self, initial_lr, multiplier, start_epoch=0, end_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.multiplier = (multiplier if callable(multiplier)
                           else lambda epoch: multiplier)
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_epoch_begin(self, epoch, logs=None):
        if epoch < self.start_epoch:
            return
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return
        opt = self.model.optimizer
        if hasattr(opt, "learning_rate"):
            opt.learning_rate = self.initial_lr * self.multiplier(epoch)
