"""Elastic support for the Keras frontend: state + fit() callbacks.

Reference analog: ``horovod/_keras/elastic.py`` +
``horovod/tensorflow/keras/elastic.py`` (``KerasState``,
``CommitStateCallback``, ``UpdateBatchStateCallback``,
``UpdateEpochStateCallback``) — keep an elastic ``State`` current while
``model.fit`` runs, so recovery resumes at the right epoch/batch.
"""

import tensorflow as tf

from horovod_tpu.common import elastic as _elastic
from horovod_tpu.tensorflow.elastic import (  # noqa: F401
    ObjectState,
    State,
    TensorFlowKerasState,
    TensorFlowState,
)

run = _elastic.run_fn
init = _elastic.init
reset = _elastic.reset


class KerasState(TensorFlowKerasState):
    """Elastic state for a compiled keras model (reference:
    hvd.elastic.KerasState — identical to TensorFlowKerasState with the
    optimizer taken from the model)."""

    def __init__(self, model, **kwargs):
        super().__init__(model, optimizer=None, **kwargs)


class CommitStateCallback(tf.keras.callbacks.Callback):
    """``state.commit()`` every ``batches_per_commit`` batches and at
    every epoch end (reference: hvd.elastic.CommitStateCallback).

    List this AFTER Update{Batch,Epoch}StateCallback: keras runs
    callbacks in list order, so the commit must fire after the state's
    position was advanced — otherwise the epoch-end snapshot records the
    previous epoch and recovery re-runs one epoch."""

    def __init__(self, state, batches_per_commit=1):
        super().__init__()
        self._state = state
        self._batches_per_commit = batches_per_commit

    def on_train_batch_end(self, batch, logs=None):
        if (batch + 1) % self._batches_per_commit == 0:
            self._state.commit()

    def on_epoch_end(self, epoch, logs=None):
        self._state.commit()


class UpdateBatchStateCallback(tf.keras.callbacks.Callback):
    """Track ``state.batch`` and shorten the first restored epoch.

    Reference analog: hvd.elastic.UpdateBatchStateCallback. On resume
    (``fit(initial_epoch=state.epoch)`` re-entering the epoch a failure
    interrupted), the committed batch count becomes an offset: callback
    ``params['steps']`` is reduced by it (honored by keras-2-style loops;
    keras 3 treats params as informational, so there the offset is kept
    in ``state.batch`` for the input pipeline to skip) and subsequent
    ``state.batch`` values continue from the offset, so commits made
    after recovery record absolute progress within the epoch. Resets to
    0 at epoch end."""

    def __init__(self, state):
        super().__init__()
        self._state = state
        self._offset = 0
        self._orig_steps = None
        self._resume_target = None
        self._stopped_epoch_early = False
        if not hasattr(state, "batch"):
            state.batch = 0

    def on_epoch_begin(self, epoch, logs=None):
        self._offset = 0
        self._resume_target = None
        if epoch == getattr(self._state, "epoch", 0) \
                and getattr(self._state, "batch", 0) > 0:
            self._offset = self._state.batch
            steps = (self.params or {}).get("steps")
            if steps:
                self._orig_steps = steps
                shortened = max(steps - self._offset, 1)
                self.params["steps"] = shortened
                # keras 3 treats params["steps"] as informational and
                # runs the full epoch anyway; _resume_target enforces
                # the shortened epoch via an early stop (below).
                self._resume_target = shortened

    def on_train_batch_end(self, batch, logs=None):
        self._state.batch = self._offset + batch + 1
        if (self._resume_target is not None
                and batch + 1 >= self._resume_target
                and not getattr(self.model, "stop_training", False)):
            # End the resumed epoch after the remaining step count.
            # keras 3's trainer breaks the batch loop on stop_training,
            # runs on_epoch_end, and only THEN checks stop_training to
            # leave the epoch loop — clearing the flag in our
            # on_epoch_end therefore ends just this epoch, not training.
            self._stopped_epoch_early = True
            self.model.stop_training = True

    def on_epoch_end(self, epoch, logs=None):
        self._state.batch = 0
        if self._stopped_epoch_early:
            # Ours, not a user callback's (we checked stop_training was
            # False before setting it): clear so later epochs still run.
            # ORDERING CONTRACT: list the hvd.elastic callbacks BEFORE
            # user callbacks (as every example does) — a user callback
            # that sets stop_training in its own on_epoch_end then runs
            # after this clear and its stop request is preserved.
            self._stopped_epoch_early = False
            self.model.stop_training = False
        self._resume_target = None
        if self._orig_steps is not None:
            # params is shared by the whole CallbackList; un-shrink it so
            # epochs after the resumed one see the true step count.
            self.params["steps"] = self._orig_steps
            self._orig_steps = None


class UpdateEpochStateCallback(tf.keras.callbacks.Callback):
    """Track ``state.epoch`` so recovery re-enters ``fit`` with
    ``initial_epoch=state.epoch`` (reference:
    hvd.elastic.UpdateEpochStateCallback)."""

    def __init__(self, state):
        super().__init__()
        self._state = state
        if not hasattr(state, "epoch"):
            state.epoch = 0

    def on_epoch_end(self, epoch, logs=None):
        self._state.epoch = epoch + 1
