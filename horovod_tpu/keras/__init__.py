"""horovod_tpu.keras — the Keras frontend
(``import horovod_tpu.keras as hvd``).

Reference analog: ``horovod/keras/__init__.py`` + ``horovod/_keras/`` —
``DistributedOptimizer`` that averages gradients before apply, plus the
canonical callbacks (broadcast, metric averaging, LR warmup/schedule).
"""

import tensorflow as tf

from horovod_tpu.tensorflow import (  # noqa: F401
    Adasum,
    Average,
    Compression,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allreduce,
    alltoall,
    barrier,
    join,
    broadcast,
    broadcast_variables,
    cross_rank,
    cross_size,
    grouped_allreduce,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    reducescatter,
    shutdown,
    size,
)
from horovod_tpu.keras import callbacks  # noqa: F401


class DistributedOptimizer:
    """Wrap a keras optimizer: gradients are allreduce-averaged across
    ranks before ``apply_gradients``.

    Reference analog: hvd.DistributedOptimizer
    (horovod/_keras/__init__.py create_distributed_optimizer). Wrapping
    is by composition + delegation so it works across keras optimizer API
    generations.
    """

    def __init__(self, optimizer, compression=Compression.none, op=Average,
                 backward_passes_per_step=1):
        if backward_passes_per_step != 1:
            raise NotImplementedError(
                "backward_passes_per_step > 1 for keras lands with the "
                "gradient-aggregation helper")
        self._opt = optimizer
        self._compression = compression
        self._op = op

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def _allreduce(self, grads):
        from horovod_tpu.tensorflow import mpi_ops

        compressed, ctxs = [], []
        for g in grads:
            if isinstance(g, tf.IndexedSlices):
                g = tf.convert_to_tensor(g)
            c, ctx = self._compression.compress(g)
            compressed.append(c)
            ctxs.append(ctx)
        reduced = mpi_ops.grouped_allreduce(
            compressed, names=[f"keras.grad.{i}"
                               for i in range(len(compressed))],
            op=self._op)
        return [self._compression.decompress(r, ctx)
                for r, ctx in zip(reduced, ctxs)]

    def apply_gradients(self, grads_and_vars, **kwargs):
        grads_and_vars = list(grads_and_vars)
        grads = self._allreduce([g for g, _ in grads_and_vars])
        return self._opt.apply_gradients(
            zip(grads, [v for _, v in grads_and_vars]), **kwargs)

    # keras 3 calls optimizer.apply(grads, vars)
    def apply(self, grads, variables=None, **kwargs):
        grads = self._allreduce(list(grads))
        if variables is None:
            return self._opt.apply(grads, **kwargs)
        return self._opt.apply(grads, variables, **kwargs)
