"""horovod_tpu.keras — the Keras frontend
(``import horovod_tpu.keras as hvd``).

Reference analog: ``horovod/keras/__init__.py`` + ``horovod/_keras/`` —
``DistributedOptimizer`` that averages gradients before apply, plus the
canonical callbacks (broadcast, metric averaging, LR warmup/schedule).
"""

import tensorflow as tf

from horovod_tpu.tensorflow import (  # noqa: F401
    Adasum,
    Average,
    Compression,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allreduce,
    alltoall,
    barrier,
    join,
    broadcast,
    broadcast_variables,
    cross_rank,
    cross_size,
    grouped_allreduce,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    reducescatter,
    shutdown,
    size,
)
from horovod_tpu.keras import callbacks  # noqa: F401


class _DistributedOptimizer:
    """Method bodies grafted onto a dynamic subclass of the wrapped
    optimizer's own class — so ``model.compile(optimizer=...)`` sees a
    genuine keras optimizer (reference: horovod/_keras/__init__.py
    create_distributed_optimizer's ``cls = type(...)`` trick)."""

    def _hvd_allreduce(self, grads):
        from horovod_tpu.tensorflow import mpi_ops

        compressed, ctxs = [], []
        for g in grads:
            if isinstance(g, tf.IndexedSlices):
                g = tf.convert_to_tensor(g)
            c, ctx = self._hvd_compression.compress(g)
            compressed.append(c)
            ctxs.append(ctx)
        reduced = mpi_ops.grouped_allreduce(
            compressed, names=[f"keras.grad.{i}"
                               for i in range(len(compressed))],
            op=self._hvd_op)
        return [self._hvd_compression.decompress(r, ctx)
                for r, ctx in zip(reduced, ctxs)]

    # Exactly ONE of these is grafted onto the subclass (see
    # DistributedOptimizer below): keras 3's BaseOptimizer.apply_gradients
    # delegates to self.apply(), so overriding both would allreduce twice
    # (harmlessly-looking with Average, wrong by a factor of size with Sum).

    def apply_gradients(self, grads_and_vars, **kwargs):
        grads_and_vars = list(grads_and_vars)
        grads = self._hvd_allreduce([g for g, _ in grads_and_vars])
        return super(self.__class__, self).apply_gradients(
            zip(grads, [v for _, v in grads_and_vars]), **kwargs)

    def apply(self, grads, variables=None, **kwargs):
        grads = self._hvd_allreduce(list(grads))
        if variables is None:
            return super(self.__class__, self).apply(grads, **kwargs)
        return super(self.__class__, self).apply(grads, variables, **kwargs)


def DistributedOptimizer(optimizer, compression=Compression.none, op=Average,
                         backward_passes_per_step=1):
    """Wrap a keras optimizer: gradients are allreduce-averaged across
    ranks before apply.

    Returns an instance of a dynamically-created subclass of
    ``type(optimizer)``, rebuilt from its config — so it passes keras's
    optimizer checks everywhere (compile, serialization), exactly like the
    reference's create_distributed_optimizer.
    """
    if backward_passes_per_step != 1:
        raise NotImplementedError(
            "backward_passes_per_step > 1 for keras lands with the "
            "gradient-aggregation helper")
    members = {"_hvd_allreduce": _DistributedOptimizer._hvd_allreduce}
    if hasattr(optimizer, "apply"):
        # keras 3: apply() is the single grad-application chokepoint
        # (apply_gradients delegates to it) — override only it.
        members["apply"] = _DistributedOptimizer.apply
    else:
        # keras 2 family: apply_gradients is the chokepoint.
        members["apply_gradients"] = _DistributedOptimizer.apply_gradients
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,), members)
    dist = cls.from_config(optimizer.get_config())
    dist._hvd_compression = compression
    dist._hvd_op = op
    return dist

# Capability surface (reference analog: hvd.mpi_built()/gloo_built()/...).
from horovod_tpu.tensorflow import (  # noqa: F401,E402
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    xla_built,
    xla_enabled,
)
