"""horovod_tpu.keras — the Keras frontend
(``import horovod_tpu.keras as hvd``).

Reference analog: ``horovod/keras/__init__.py`` + ``horovod/_keras/`` —
``DistributedOptimizer`` that averages gradients before apply, plus the
canonical callbacks (broadcast, metric averaging, LR warmup/schedule).
"""

import tensorflow as tf

from horovod_tpu.tensorflow import (  # noqa: F401
    Adasum,
    Average,
    Compression,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allgather_object,
    allreduce,
    alltoall,
    broadcast_object,
    broadcast_object_fn,
    barrier,
    join,
    broadcast,
    broadcast_variables,
    cross_rank,
    cross_size,
    grouped_allreduce,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    reducescatter,
    shutdown,
    size,
)
from horovod_tpu.keras import callbacks  # noqa: F401
from horovod_tpu.keras import elastic  # noqa: F401


class _DistributedOptimizer:
    """Method bodies grafted onto a dynamic subclass of the wrapped
    optimizer's own class — so ``model.compile(optimizer=...)`` sees a
    genuine keras optimizer (reference: horovod/_keras/__init__.py
    create_distributed_optimizer's ``cls = type(...)`` trick)."""

    def _hvd_allreduce(self, grads, variables=None):
        from horovod_tpu.tensorflow import _allreduce_grads_list

        if variables is not None and len(variables) == len(grads):
            # Variable-derived for cross-rank stability, positional
            # suffix for uniqueness (two models may both own a
            # 'dense/kernel'; duplicate names fail group enqueue).
            names = [
                f"keras.grad.{getattr(v, 'path', None) or getattr(v, 'name', '')}.{i}"
                for i, v in enumerate(variables)]
        else:
            names = [f"keras.grad.{i}" for i in range(len(grads))]
        return _allreduce_grads_list(grads, self._hvd_compression,
                                     self._hvd_op, names)

    # Local gradient aggregation (backward_passes_per_step > 1).
    # Reference analog: horovod/tensorflow/gradient_aggregation*.py
    # LocalGradientAggregationHelper — accumulate N local backward passes,
    # allreduce + apply only on the Nth, skip apply otherwise. tf.cond so
    # the same code traces under tf.function.

    def _hvd_agg_step(self, grads, variables, apply_fn):
        grads = [tf.convert_to_tensor(g) if isinstance(g, tf.IndexedSlices)
                 else g for g in grads]
        if self._hvd_agg_acc is None:
            # init_scope lifts creation out of any tf.function trace; the
            # initializers use only static shapes/dtypes, never in-graph
            # gradient tensors.
            with tf.init_scope():
                self._hvd_agg_acc = [
                    tf.Variable(tf.zeros(g.shape, g.dtype),
                                trainable=False) for g in grads]
                self._hvd_agg_counter = tf.Variable(0, dtype=tf.int64,
                                                    trainable=False)
        # Build the base optimizer's slot/iteration variables BEFORE the
        # cond: keras cannot create variables inside a tf.cond branch
        # when the boundary's first apply happens under tf.function.
        if variables is not None and getattr(self, "built", True) is False:
            self.build(variables)
        for a, g in zip(self._hvd_agg_acc, grads):
            a.assign_add(tf.cast(g, a.dtype))
        self._hvd_agg_counter.assign_add(1)
        n = self._hvd_backward_passes

        def boundary():
            avg = [tf.identity(a) / tf.cast(n, a.dtype)
                   for a in self._hvd_agg_acc]
            apply_fn(self._hvd_allreduce(avg, variables))
            for a in self._hvd_agg_acc:
                a.assign(tf.zeros_like(a))
            self._hvd_agg_counter.assign(0)
            return tf.constant(True)

        def skip():
            # Reference parity (LocalGradientAggregationHelper):
            # iterations counts every backward pass, including skipped
            # applies — LR schedules keyed on it must not run N× slow.
            it = getattr(self, "iterations", None)
            if it is not None:
                it.assign_add(1)
            return tf.constant(False)

        return tf.cond(tf.equal(self._hvd_agg_counter, n), boundary, skip)

    # Exactly ONE of these is grafted onto the subclass (see
    # DistributedOptimizer below): keras 3's BaseOptimizer.apply_gradients
    # delegates to self.apply(), so overriding both would allreduce twice
    # (harmlessly-looking with Average, wrong by a factor of size with Sum).

    def apply_gradients(self, grads_and_vars, **kwargs):
        grads_and_vars = list(grads_and_vars)
        grads = [g for g, _ in grads_and_vars]
        hvd_vars = [v for _, v in grads_and_vars]
        if self._hvd_backward_passes > 1:
            def apply_fn(reduced):
                super(self.__class__, self).apply_gradients(
                    zip(reduced, hvd_vars), **kwargs)

            return self._hvd_agg_step(grads, hvd_vars, apply_fn)
        grads = self._hvd_allreduce(grads, hvd_vars)
        return super(self.__class__, self).apply_gradients(
            zip(grads, hvd_vars), **kwargs)

    def apply(self, grads, variables=None, **kwargs):
        if self._hvd_backward_passes > 1:
            def apply_fn(reduced):
                if variables is None:
                    super(self.__class__, self).apply(reduced, **kwargs)
                else:
                    super(self.__class__, self).apply(reduced, variables,
                                                      **kwargs)

            return self._hvd_agg_step(list(grads), variables, apply_fn)
        grads = self._hvd_allreduce(list(grads), variables)
        if variables is None:
            return super(self.__class__, self).apply(grads, **kwargs)
        return super(self.__class__, self).apply(grads, variables, **kwargs)


def DistributedOptimizer(optimizer, compression=Compression.none, op=Average,
                         backward_passes_per_step=1):
    """Wrap a keras optimizer: gradients are allreduce-averaged across
    ranks before apply.

    Returns an instance of a dynamically-created subclass of
    ``type(optimizer)``, rebuilt from its config — so it passes keras's
    optimizer checks everywhere (compile, serialization), exactly like the
    reference's create_distributed_optimizer.
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    from horovod_tpu.tensorflow import _is_v1_optimizer

    if _is_v1_optimizer(optimizer):
        # Legacy graph-mode optimizer handed to the keras entry point:
        # route to the TF-level wrapper (same dispatch as
        # hvd.tensorflow.DistributedOptimizer).
        from horovod_tpu import tensorflow as _hvd_tf

        return _hvd_tf.DistributedOptimizer(
            optimizer, compression=compression, op=op,
            backward_passes_per_step=backward_passes_per_step)
    members = {"_hvd_allreduce": _DistributedOptimizer._hvd_allreduce,
               "_hvd_agg_step": _DistributedOptimizer._hvd_agg_step}
    if hasattr(optimizer, "apply"):
        # keras 3: apply() is the single grad-application chokepoint
        # (apply_gradients delegates to it) — override only it.
        members["apply"] = _DistributedOptimizer.apply
    else:
        # keras 2 family: apply_gradients is the chokepoint.
        members["apply_gradients"] = _DistributedOptimizer.apply_gradients
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,), members)
    dist = cls.from_config(optimizer.get_config())
    dist._hvd_compression = compression
    dist._hvd_op = op
    dist._hvd_backward_passes = backward_passes_per_step
    dist._hvd_agg_acc = None
    dist._hvd_agg_counter = None
    return dist

# Capability surface (reference analog: hvd.mpi_built()/gloo_built()/...).
from horovod_tpu.tensorflow import (  # noqa: F401,E402
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    xla_built,
    xla_enabled,
)
