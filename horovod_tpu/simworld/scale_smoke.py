"""``make scale-smoke`` — the large-world CI lane (docs/scale.md).

One 64-rank simulated world, flat star AND tree gather:

1. a negotiation + allreduce round completes in both modes and the
   per-phase control-plane latency rows come out (the scaling-curve
   plumbing, end to end);
2. an injected kill at round 1 surfaces a typed peer failure on the
   survivors with the dead rank named;
3. a 64-rank post-mortem — one black-box dump per survivor in the
   exact ``DumpBlackBox`` schema — merges through the STREAMING path
   and names the killed rank as root cause.

Exit 0 = all three behaviors hold. ~15 s on a laptop.
"""

import json
import shutil
import sys
import tempfile
import time

from horovod_tpu.simworld import run_world, write_sim_dumps
from horovod_tpu.telemetry.postmortem import (
    format_post_mortem,
    merge_post_mortem_streaming,
)

RANKS = 64
TREE_FANOUT = 8
KILL_RANK = 37


def main():
    failures = []

    # (1) negotiation + allreduce, both gather modes, phase rows out.
    for config, fanout in (("flat", 0), (f"tree{TREE_FANOUT}",
                                         TREE_FANOUT)):
        t0 = time.monotonic()
        rep = run_world(RANKS, tree_fanout=fanout, elems=256, rounds=2)
        row = {
            "metric": "scale_smoke", "config": config, "ranks": RANKS,
            "standup_us": rep["standup_us"],
            "round_mean_us": rep["round_us"]["mean"],
            "phases": {k: {"p50_us": v["p50_us"], "count": v["count"]}
                       for k, v in rep["phases"].items()},
            "allreduce_ok": rep["allreduce_ok"],
            "wall_s": round(time.monotonic() - t0, 2),
        }
        print("SCALE_SMOKE " + json.dumps(row), flush=True)
        if not rep["allreduce_ok"]:
            failures.append(f"{config}: allreduce mismatch")
        for phase in ("gather", "broadcast"):
            if not rep["phases"].get(phase, {}).get("count"):
                failures.append(f"{config}: no {phase} phase rows")

    # (2) injected kill: every survivor gets a typed fault naming the
    # dead rank (certain EOF attribution, no timeouts needed).
    rep = run_world(RANKS, tree_fanout=TREE_FANOUT, elems=256, rounds=3,
                    kill_rank=KILL_RANK, kill_round=1)
    fault = rep.get("fault", {})
    print("SCALE_SMOKE " + json.dumps(
        {"metric": "scale_smoke_kill", "ranks": RANKS, **fault}),
        flush=True)
    if fault.get("typed_faults", 0) < RANKS - 1:
        failures.append(f"kill: only {fault.get('typed_faults')} of "
                        f"{RANKS - 1} survivors saw a typed fault")
    if fault.get("named_rank") != KILL_RANK:
        failures.append(f"kill: named rank {fault.get('named_rank')}, "
                        f"injected {KILL_RANK}")

    # (3) fleet post-mortem: streaming merge over the survivors' dumps
    # names the killed rank as root cause.
    dump_dir = tempfile.mkdtemp(prefix="hvdtpu_scale_smoke_")
    try:
        write_sim_dumps(dump_dir, RANKS, KILL_RANK,
                        events_per_rank=256)
        t0 = time.monotonic()
        analysis = merge_post_mortem_streaming(dump_dir)
        merge_s = time.monotonic() - t0
        print("SCALE_SMOKE " + json.dumps({
            "metric": "scale_smoke_postmortem", "dumps": RANKS - 1,
            "merge_s": round(merge_s, 2),
            "root_cause_ranks": analysis["root_cause_ranks"],
            "timeline_total": analysis["timeline_total"],
        }), flush=True)
        if analysis["root_cause_ranks"] != [KILL_RANK]:
            failures.append("post-mortem root cause "
                            f"{analysis['root_cause_ranks']}, expected "
                            f"[{KILL_RANK}]")
            print(format_post_mortem(analysis, tail=10))
    finally:
        shutil.rmtree(dump_dir, ignore_errors=True)

    if failures:
        print("scale-smoke FAILED:\n  " + "\n  ".join(failures),
              file=sys.stderr)
        return 1
    print(f"scale-smoke OK ({RANKS}-rank world: negotiation+allreduce "
          "in both gather modes, typed kill attribution, streaming "
          "post-mortem root cause)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
