"""Simulated-world drivers: scaling rows and synthetic black-box dumps.

See the package docstring and docs/scale.md for the methodology; the
native entry is ``hvdtpu_simworld_run`` (csrc/simworld.cc).
"""

import json
import os
import time

from horovod_tpu.common.basics import HorovodBasics

_basics = HorovodBasics()

# The bench ladder (docs/scale.md): small points anchor the curve's
# intercept, 256 is the north-star world size the r12-r15 machinery
# claims to serve.
DEFAULT_WORLD_SIZES = (8, 32, 64, 128, 256)
DEFAULT_TREE_FANOUT = 8


def run_world(ranks, tree_fanout=0, elems=1024, rounds=3, kill_rank=-1,
              kill_round=-1):
    """One simulated world; returns the native JSON report as a dict
    (raises on any non-injected failure). ``tree_fanout=0`` is the
    flat-star baseline, ``>= 2`` the tree gather."""
    return _basics.simworld_run(ranks, tree_fanout=tree_fanout,
                                elems=elems, rounds=rounds,
                                kill_rank=kill_rank,
                                kill_round=kill_round)


def _phase_stats(report, phase):
    h = report.get("phases", {}).get(phase)
    if not h or not h.get("count"):
        return {}
    return {
        "mean_us": h["sum_us"] // h["count"],
        "p50_us": h["p50_us"],
        "p90_us": h["p90_us"],
        "count": h["count"],
    }


def scaling_profile(world_sizes=DEFAULT_WORLD_SIZES,
                    tree_fanout=DEFAULT_TREE_FANOUT, elems=256,
                    rounds=6):
    """The ``control_plane_scaling`` bench rows: for every world size,
    one flat-star row and one tree row — BOTH curves, so the sub-linear
    claim for the tree gather is checkable against its own baseline in
    the same run (`bench.py --scale`). Per row: world standup, mean
    negotiation+allreduce round, and the gather/broadcast phase stats
    the curves are drawn from."""
    rows = []
    for ranks in world_sizes:
        for fanout in (0, tree_fanout):
            if fanout and ranks <= fanout + 1:
                continue  # tree degenerates to the star
            t0 = time.monotonic()
            rep = run_world(ranks, tree_fanout=fanout, elems=elems,
                            rounds=rounds)
            rows.append({
                "metric": "control_plane_scaling",
                "config": "flat" if fanout == 0 else f"tree{fanout}",
                "ranks": ranks,
                "rounds": rounds,
                "elems": elems,
                "standup_us": rep.get("standup_us"),
                "round_mean_us": rep.get("round_us", {}).get("mean"),
                "gather": _phase_stats(rep, "gather"),
                "broadcast": _phase_stats(rep, "broadcast"),
                "allreduce_ok": rep.get("allreduce_ok"),
                "wall_s": round(time.monotonic() - t0, 3),
            })
    return rows


# ---- synthetic per-rank black-box dumps -------------------------------
#
# The in-process world shares ONE event ring and ONE process, so real
# per-rank dump FILES cannot come out of it. For the merge-at-scale
# lane we synthesize the fleet's dumps in the exact DumpBlackBox schema
# (csrc/operations.cc): per surviving rank a header (clock anchors +
# fault record) and an event tail whose content mirrors what that rank
# would have recorded — survivors show progress then a fault; the
# coordinator's dump names the dead rank with certainty (probe-sweep
# attribution), everyone else suspects a neighbor (timeout), which is
# exactly the proof-vs-suspicion geometry merge_post_mortem untangles.


def write_sim_step_dumps(out_dir, ranks, steps, slow_rank, step_ms=120,
                         wire_ms=15, slow_ms=60, epoch=0, skew_us=900,
                         waits=False, serving=False, breach=None):
    """Synthesize per-rank STEP-ANATOMY dumps for the critical-path
    merge at fleet scale (the step-window twin of
    :func:`write_sim_dumps`): every rank records the same
    ``step_begin``/``step_end`` windows (one id sequence — the SPMD
    mark contract), but ``slow_rank`` spends ``slow_ms`` extra in
    unrecorded compute each step while everyone else's wire span
    stretches to absorb the wait — exactly the signature a real
    straggler leaves, so ``critpath.critical_path`` must name
    ``slow_rank`` with phase ``compute`` on EVERY step
    (tests/single/test_critpath.py pins this at 64 ranks; r16 gotcha 1
    applies — the in-process simworld cannot emit real per-rank files).

    The r23 fleet lane (docs/fleet.md) rides on three opt-in knobs,
    defaulted off so the critpath geometry above is untouched:

    - ``waits=True`` pairs each wire span with a ``wait`` block ending
      at the same instant but HALF the duration — exposed wire on the
      fused lane is ``spans ∩ waits``, so the rank-seconds ledger must
      book exactly half of each span as ``exposed_wire``;
    - ``serving=True`` runs one request per step through
      queued -> prefill -> decode_active -> done at fixed fractions of
      the window (10%/30%/80%), exercising the serving buckets;
    - ``breach={"objective": ..., "rank": ..., "value": ...,
      "phase": ...}`` records one ``slo_breach`` event on rank 0 (ids
      per the pinned tables — the live observatory's footprint).

    Returns the list of dump paths."""
    os.makedirs(out_dir, exist_ok=True)
    base_unix = int(time.time() * 1e6)
    total_us = (step_ms + slow_ms) * 1000
    wire_us = wire_ms * 1000
    paths = []
    for rank in range(ranks):
        path = os.path.join(out_dir, f"blackbox-rank{rank}.jsonl")
        steady0 = 5_000_000 + rank * 333_007
        unix0 = base_unix + skew_us * rank  # simulated NTP skew
        header = {
            "kind": "blackbox_header", "rank": rank, "size": ranks,
            "epoch": epoch, "unix_us": unix0, "steady_us": steady0,
            "fault": {},
        }
        lines = [json.dumps(header)]
        seq = 0

        def emit(ts, typ, **fields):
            nonlocal seq
            row = {"seq": seq, "ts_us": ts, "type": typ}
            row.update(fields)
            lines.append(json.dumps(row))
            seq += 1

        for k in range(1, steps + 1):
            begin = steady0 + (k - 1) * total_us
            end = begin + total_us
            emit(begin, "step_begin", step=k)
            if serving:
                # One request per step, rid = step: enters queued early,
                # prefills, decodes, and completes inside the window.
                rid = k
                emit(begin + total_us // 10, "request", phase=0,
                     rid=rid, aux=0, phase_name="queued")
                emit(begin + (3 * total_us) // 10, "request", phase=1,
                     rid=rid, aux=0, phase_name="prefill")
                emit(begin + (8 * total_us) // 10, "request", phase=4,
                     rid=rid, aux=0, phase_name="decode_active")
                emit(end - 500, "request", phase=7, rid=rid, aux=0,
                     phase_name="done")
            # The slow rank computes for most of the window and runs a
            # short span at the end; everyone else finishes local work
            # quickly and their span blocks until the slow rank's data
            # arrives (span stamped at its END with dur_us).
            dur = wire_us if rank == slow_rank else \
                total_us - wire_us - 2000
            emit(end - 1000, "wire_span", plane=0, dur_us=dur,
                 tx_bytes=1 << 20, rx_bytes=1 << 20)
            if waits:
                # Fused-lane evidence: the API thread only BLOCKED for
                # the back half of the span.
                emit(end - 1000, "wait", dur_us=dur // 2)
            emit(end, "step_end", step=k, dur_us=total_us)
        if breach is not None and rank == 0:
            emit(steady0 + steps * total_us, "slo_breach",
                 objective=int(breach.get("objective", 0)),
                 breach_rank=int(breach.get("rank", 0)),
                 value=int(breach.get("value", 0)),
                 phase=int(breach.get("phase", 0)),
                 objective_name=breach.get("objective_name", ""),
                 phase_name=breach.get("phase_name", ""))
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        paths.append(path)
    return paths


def write_sim_dumps(out_dir, ranks, fault_rank, events_per_rank=64,
                    epoch=0, skew_us=1500):
    """Write ``ranks - 1`` survivor dumps (the dead rank writes none —
    that absence IS the root-cause evidence) under ``out_dir``;
    returns the list of paths."""
    os.makedirs(out_dir, exist_ok=True)
    base_unix = int(time.time() * 1e6)
    paths = []
    for rank in range(ranks):
        if rank == fault_rank:
            continue
        path = os.path.join(out_dir, f"blackbox-rank{rank}.jsonl")
        # Per-rank steady clocks start at unrelated offsets; the header
        # anchor pair is what lets the merge align them.
        steady0 = 10_000_000 + rank * 777_001
        certain = rank == 0  # coordinator: probe-sweep proof
        named = fault_rank if certain else (rank + 1) % ranks
        fault = {
            "kind": "peer",
            "certain": certain,
            "ranks": [named],
            "detect_ms": 12,
            "reason": f"simworld: peer failure (rank {named})",
        }
        header = {
            "kind": "blackbox_header", "rank": rank, "size": ranks,
            "epoch": epoch, "unix_us": base_unix + skew_us * rank,
            "steady_us": steady0 + events_per_rank * 1000,
            "fault": fault,
        }
        lines = [json.dumps(header)]
        for i in range(events_per_rank):
            ts = steady0 + i * 1000
            if i == events_per_rank - 1:
                ev = {"seq": i, "ts_us": ts, "type": "fault", "kind": 0,
                      "certain": 1 if certain else 0, "epoch": epoch,
                      "fault_rank": named}
            elif i == events_per_rank - 2:
                ev = {"seq": i, "ts_us": ts, "type": "retry_window",
                      "attempt": 1, "window_ms": 250}
            else:
                # The dead rank's neighbors stop seeing progress first:
                # their last wire span lands earlier on the merged axis.
                near_dead = abs(rank - fault_rank) <= 1
                cut = events_per_rank - (8 if near_dead else 4)
                typ = "wire_span" if i < cut else "negotiate_begin"
                ev = {"seq": i, "ts_us": ts, "type": typ}
                if typ == "wire_span":
                    ev.update({"plane": 0, "dur_us": 800,
                               "tx_bytes": 4096, "rx_bytes": 4096})
            lines.append(json.dumps(ev))
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        paths.append(path)
    return paths
