"""Simulated large-world harness (docs/scale.md).

``csrc/simworld.cc`` stands up a 64-256-rank world as thread-per-rank
controllers over socketpairs in ONE process — the real negotiation
protocol (flat star or the ``HOROVOD_CONTROL_TREE`` tree gather) and
the real ring allreduce, with only the transport hops loopback. This
package is the Python face:

- :func:`run_world` — one world, one JSON report (standup, per-round
  latency, the per-phase control-plane profile);
- :func:`scaling_profile` — the ``control_plane_scaling`` bench rows:
  flat-vs-tree latency curves at 8/32/64/128/256 ranks, the
  characterization the tree gather was built from (arXiv:1810.11112's
  profile-first discipline);
- :func:`write_sim_dumps` — synthetic per-rank black-box dumps in the
  exact ``DumpBlackBox`` schema, sized to exercise the streaming
  post-mortem merge at hundreds of ranks (the in-process world shares
  one event ring, so per-rank dump FILES are simulated while the fault
  content mirrors what each real rank would record);
- ``python -m horovod_tpu.simworld.scale_smoke`` — the 64-rank CI lane
  (``make scale-smoke``).
"""

from horovod_tpu.simworld.harness import (  # noqa: F401
    run_world,
    scaling_profile,
    write_sim_dumps,
)
