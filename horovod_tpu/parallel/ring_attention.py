"""Ring attention: exact long-context attention over a sharded sequence.

Net-new vs the reference (Horovod has no sequence parallelism —
SURVEY.md §5.7). The sequence axis of Q/K/V is sharded across the ``seq``
mesh axis; each step every device computes flash-style blockwise attention
against the K/V shard it currently holds, then rotates K/V one hop around
the ICI ring (``ppermute``). After ``seq_size`` steps every query has seen
every key exactly once; the online-softmax accumulators make the result
exact, not approximate. Communication per step is one neighbor exchange
that XLA overlaps with the attention matmuls.

Causal masking uses global positions, so fully-masked (future) blocks
contribute nothing and early-exit naturally via zeroed partial sums.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

_NEG_BIG = -1e30  # finite stand-in for -inf: keeps exp() NaN-free


def _axis_size(axis_name):
    # lax.axis_size only exists in newer jax; psum(1) is the portable
    # spelling (constant-folded to the bound axis size at trace time).
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def _repeat_kv(x, n_rep):
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] for grouped-query attention."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)) \
        .reshape(b, s, h * n_rep, d)


def _attn_block(q, k, v, q_pos, kv_pos, causal, scale):
    """One flash-attention block: returns unnormalized (o, m, l) stats.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]; positions are global indices.
    o is f32 [B, Tq, H, D]; m (running max) and l (sum of exp) are
    f32 [B, H, Tq].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        visible = kv_pos[None, None, None, :] <= q_pos[None, None, :, None]
        s = jnp.where(visible, s, _NEG_BIG)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    if causal:
        p = jnp.where(visible, p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o, m, l


def _combine(o, m, l, o_blk, m_blk, l_blk):
    """Merge a new block into running online-softmax accumulators."""
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(m_blk - m_new)
    l_new = alpha * l + beta * l_blk
    # [B, H, Tq] -> [B, Tq, H, 1] to scale o.
    def bcast(x):
        return jnp.transpose(x, (0, 2, 1))[..., None]
    o_new = bcast(alpha) * o + bcast(beta) * o_blk
    return o_new, m_new, l_new


def blockwise_attention(q, k, v, causal=True, q_offset=0, kv_offset=0):
    """Plain (single-device) attention with global-position causal mask.

    q: [B, Tq, H, D]; k, v: [B, Tk, Hkv, D]. The offsets give the global
    index of the first q/kv position (used by ring steps and by decode).
    """
    n_rep = q.shape[2] // k.shape[2]
    k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    q_pos = q_offset + jnp.arange(q.shape[1])
    kv_pos = kv_offset + jnp.arange(k.shape[1])
    o, m, l = _attn_block(q, k, v, q_pos, kv_pos, causal, scale)
    l = jnp.maximum(l, 1e-30)
    out = o / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def _ring_attention_flash(q, k, v, axis_name, causal):
    """Ring attention with the pallas flash kernels doing each step.

    Every chunk step is one fused kernel call (dynamic global-position
    offsets ride in SMEM, so ONE compiled kernel serves all steps);
    partial results merge by logsumexp, the exact online-softmax
    combination. K/V rotate UNREPEATED (GQA: n_rep× less ICI traffic
    than repeating before the ring). Differentiable end-to-end — the
    kernel's custom VJP folds the lse cotangent into its backward.
    """
    from horovod_tpu.ops.flash_attention import flash_attention_chunk

    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    # Kernel layout [B, H, T, D]; stay there across steps (one
    # transpose in, one out — not per step).
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    o = jnp.zeros((b, h, tq, d), jnp.float32)
    lse = jnp.full((b, h, tq, 1), -jnp.inf, jnp.float32)
    for step in range(n):
        src = (idx - step) % n  # whose shard we currently hold
        o_blk, lse_blk = flash_attention_chunk(
            qt, kt, vt, idx * tq, src * tk, causal=causal)
        new_lse = jnp.logaddexp(lse, lse_blk)
        o = (jnp.exp(lse - new_lse) * o
             + jnp.exp(lse_blk - new_lse) * o_blk.astype(jnp.float32))
        lse = new_lse
        if step != n - 1:
            perm = [(i, (i + 1) % n) for i in range(n)]
            kt = lax.ppermute(kt, axis_name, perm)
            vt = lax.ppermute(vt, axis_name, perm)
    return o.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(q, k, v, axis_name, causal=True, use_flash=None):
    """Exact attention with sequence sharded over mesh axis ``axis_name``.

    Must run inside shard_map (or pmap) with the sequence dimension of
    q/k/v sharded contiguously across the axis. Shapes are the LOCAL
    shards: q [B, Tq, H, D]; k, v [B, Tk, Hkv, D].

    ``use_flash`` (default: auto — True on TPU) runs every ring step
    through the pallas flash kernels instead of the XLA blockwise math
    (~2× at model shapes, and K/V rotate unrepeated under GQA).
    """
    if use_flash is None:
        use_flash = jax.devices()[0].platform in ("tpu", "axon")
    if use_flash:
        return _ring_attention_flash(q, k, v, axis_name, causal)
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_rep = q.shape[2] // k.shape[2]
    k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    b, tq, h, d = q.shape
    tk = k.shape[1]
    q_pos = idx * tq + jnp.arange(tq)

    o = jnp.zeros((b, tq, h, d), jnp.float32)
    m = jnp.full((b, h, tq), _NEG_BIG, jnp.float32)
    l = jnp.zeros((b, h, tq), jnp.float32)

    # Static python loop: n is the (compile-time) mesh axis size. Each
    # iteration's ppermute is independent of the block matmul before it,
    # so XLA overlaps communication with compute.
    for step in range(n):
        src = (idx - step) % n  # whose shard we currently hold
        kv_pos = src * tk + jnp.arange(tk)
        o_blk, m_blk, l_blk = _attn_block(q, k, v, q_pos, kv_pos, causal,
                                          scale)
        o, m, l = _combine(o, m, l, o_blk, m_blk, l_blk)
        if step != n - 1:
            perm = [(i, (i + 1) % n) for i in range(n)]
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)

    l = jnp.maximum(l, 1e-30)
    out = o / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, mesh, causal=True, batch_axis="data",
                        seq_axis="seq"):
    """User-facing wrapper: shard q/k/v over (batch, seq) and run
    ring_attention under shard_map on the given mesh."""
    spec = P(batch_axis, seq_axis, None, None)

    @jax.shard_map(mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                   check_vma=False)
    def _run(ql, kl, vl):
        return ring_attention(ql, kl, vl, seq_axis, causal=causal)

    return _run(q, k, v)
