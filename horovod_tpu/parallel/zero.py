"""ZeRO-1 sharded optimizer for the split train step (docs/zero.md).

The r06 split step materializes FULLY REPLICATED optimizer state and
treats the gradient reduction as a bulk allreduce phase. This module
restructures the optimizer-apply program into the ZeRO-1 shape
(Rajbhandari et al., arXiv:1910.02054; the fused-collective overlap
follows arXiv:2305.06942):

- the gradient buckets are **reduce-scattered** over the ``zero`` axis,
  so rank r receives only its 1/N shard of each bucket;
- the single-pass fused adam (``parallel.precision``) runs on 1/N
  optimizer state — per-rank mu/nu (and fp32 master, for the
  master-weights variant) drop N-fold;
- the updated parameter shards are **allgathered** back to the full
  replicated tree the next forward consumes.

Wire cost per rank: (N-1)/N x grads down + (N-1)/N x params up — the
same total as the allreduce it replaces at equal dtypes, but the two
phases carry DIFFERENT payloads: the reduce-scatter rides the core's
bf16 wire compression (``HOROVOD_WIRE_COMPRESSION``, extended to
reduce-scatter in this round — csrc/ring_ops.cc), and the allgather
ships params at their (usually narrow) storage/compute width, which is
where the ~2x wire saving comes from on fp32-gradient runs.

Shard-boundary contract: buckets are padded to a multiple of the shard
count, so shard boundaries ALWAYS align with bucket boundaries; rank r
owns flat segment ``[r*s, (r+1)*s)`` of every bucket — the
reduce-scatter rotation that makes this true inside the ring engine
(rot=-1: rank r ends owning its own segment) is pinned by
:func:`ring_owned_segment`, the Python twin of
``csrc/ring_ops.h RingOwnedSegment``.

Two lanes share this module's layout math:

- the **jitted lane**: ``make_split_train_step(..., zero=ZeroConfig())``
  wires :func:`make_zero_apply` in as the apply program — a manual-
  over-axis SPMD program (``jax.shard_map`` where available, the
  pipeline package's ``vmap(axis_name=...)`` emulation on jax 0.4.x
  boxes) whose per-bucket reduce-scatter/allgather pairs are exactly
  what the latency-hiding scheduler overlaps with compute on TPU, and
  what hvdlint's C6 pairing check verifies statically;
- the **eager lane**: ``hvd.DistributedFusedAdam(zero=True)``
  (horovod_tpu/jax/optimizer.py) issues one ``reducescatter_async``
  per bucket and pipelines shard-update + ``allgather_async`` per
  bucket as reductions complete, hiding wire time under update compute.
"""

import dataclasses
import functools
import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.precision import _adam_leaf, _bias_corrections

#: default fused-bucket size (unpadded payload bytes); matches the
#: core's fusion-threshold order of magnitude so one eager bucket fills
#: one fusion buffer.
DEFAULT_BUCKET_BYTES = 32 * 1024 * 1024


def ring_owned_segment(rank, size, rot=0):
    """Which ring segment ``rank`` holds fully reduced after the N-1
    reduce steps at rotation ``rot`` — the Python twin of
    ``csrc/ring_ops.h RingOwnedSegment`` (pinned against the C ABI by
    ``tests/single/test_zero.py``).

    ``rot=0`` is the allreduce rotation: rank r owns segment
    ``(r+1) % size`` (the r10 trap — the compressed allgather finalizes
    THAT segment). ``rot=-1`` is the reduce-scatter rotation: rank r
    owns its own segment r, which is why this module's shard-boundary
    math can use plain ``rank``-indexed slices everywhere.
    """
    if size <= 0 or not 0 <= rank < size:
        raise ValueError(f"rank {rank} not in [0, {size})")
    return (rank + 1 + rot) % size


@dataclasses.dataclass(frozen=True)
class ZeroConfig:
    """How to shard the optimizer.

    ``axis`` — mesh-axis name the shards live on (default ``"data"``:
    pure data-parallel replicas are exactly the ranks whose optimizer
    copies are redundant). ``size`` — shard count; defaults to
    ``mesh.shape[axis]`` when ``mesh`` is given. ``mesh`` — used by the
    real ``jax.shard_map`` path; on jax 0.4.x boxes the apply runs
    under the vmap(axis_name) emulation and only ``size`` matters.
    ``bucket_bytes`` — fused-bucket granularity (shard boundaries align
    with bucket boundaries by construction).

    ``inter_axis``/``inter_size`` — optional CROSS-PLANE split of the
    RS/AG pair (docs/redistribute.md): the reduce-scatter and allgather
    ride ``axis`` (the intra-slice/ICI fabric) while only the 1/size
    gradient shard crosses ``inter_axis`` (the DCN fabric) as a psum —
    the hierarchical decomposition applied to ZeRO-1's collective mix.
    """

    axis: str = "data"
    size: int = None
    mesh: Any = None
    bucket_bytes: int = DEFAULT_BUCKET_BYTES
    inter_axis: str = None
    inter_size: int = 1

    def resolved_size(self):
        if self.size is not None:
            return int(self.size)
        if self.mesh is not None:
            return int(self.mesh.shape[self.axis])
        raise ValueError("ZeroConfig needs size= or mesh=")


# ---- bucket layout ---------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Bucket:
    indices: tuple   # leaf positions (into the flattened tree)
    sizes: tuple     # flat element count per leaf
    offsets: tuple   # leaf offsets within the unpadded concat
    dtype: Any
    nelems: int      # unpadded total elements
    padded: int      # padded to a multiple of n_shards

    def shard_elems(self, n_shards):
        return self.padded // n_shards


@dataclasses.dataclass(frozen=True)
class BucketLayout:
    """Partition of a flat leaf list into dtype-homogeneous fused
    buckets, each padded to a multiple of ``n_shards`` so every shard
    boundary is a bucket-internal offset (never mid-leaf arithmetic on
    the wire: the collective sees whole padded buckets)."""

    buckets: tuple
    n_shards: int
    shapes: tuple    # per-leaf shapes (for unpack)
    dtypes: tuple    # per-leaf dtypes

    @property
    def padded_elems(self):
        return sum(b.padded for b in self.buckets)

    def pack(self, leaves):
        """leaves -> list of flat padded 1-D arrays, one per bucket.

        Deliberately built from ``dynamic_update_slice`` writes into a
        zeros bucket instead of ``jnp.concatenate``: on the jax-0.4.x
        CPU substrate, GSPMD miscompiles a jitted concatenate whose
        operand is a reshape of an axis-sharded array (the PHYSICAL
        per-device layout leaks into the result — elements come back
        strided; two-line repro in tests/single/test_zero.py::
        test_pack_of_sharded_leaves_is_layout_exact). The update-slice
        chain lowers to plain copies and is exact under every sharding;
        XLA fuses it to the same memcpys the concat would have been.
        """
        out = []
        for b in self.buckets:
            if len(b.indices) == 1 and b.padded == b.nelems:
                out.append(leaves[b.indices[0]].reshape(-1))
                continue
            flat = jnp.zeros((b.padded,), b.dtype)
            for i, off in zip(b.indices, b.offsets):
                flat = lax.dynamic_update_slice(
                    flat, leaves[i].reshape(-1).astype(b.dtype), (off,))
            out.append(flat)
        return out

    def unpack(self, flat_buckets):
        """Inverse of :meth:`pack` (padding dropped)."""
        leaves = [None] * len(self.shapes)
        for b, flat in zip(self.buckets, flat_buckets):
            for i, size, off in zip(b.indices, b.sizes, b.offsets):
                leaves[i] = flat[off:off + size].reshape(self.shapes[i])
        return leaves

    def pack_shard(self, leaves, bucket_index, rank):
        """Rank ``rank``'s shard of bucket ``bucket_index`` WITHOUT
        materializing the full packed bucket: only the leaf slices that
        overlap ``[rank*s, (rank+1)*s)`` are copied — 1/N of
        :meth:`pack`'s work, which is what the eager per-step param
        slice wants (the other N-1 shards of the params would be packed
        only to be thrown away). All offsets are static, so this is
        plain slicing; identical values to
        ``pack(leaves)[bucket_index][rank*s:(rank+1)*s]`` (pinned by
        tests/single/test_zero.py)."""
        b = self.buckets[bucket_index]
        s = b.shard_elems(self.n_shards)
        lo, hi = rank * s, (rank + 1) * s
        shard = jnp.zeros((s,), b.dtype)
        for i, size, off in zip(b.indices, b.sizes, b.offsets):
            a, z = max(off, lo), min(off + size, hi)
            if a >= z:
                continue
            piece = leaves[i].reshape(-1)[a - off:z - off].astype(b.dtype)
            shard = lax.dynamic_update_slice(shard, piece, (a - lo,))
        return shard


def zero_bucket_layout(leaves, n_shards, bucket_bytes=DEFAULT_BUCKET_BYTES):
    """Build the fused-bucket partition of ``leaves`` (arrays or
    ShapeDtypeStructs): group by dtype in tree order, close a bucket
    when it reaches ``bucket_bytes`` (a single over-sized leaf still
    gets exactly one bucket), pad each bucket to a multiple of
    ``n_shards``."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    by_dtype = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    buckets = []
    for dtype, idxs in by_dtype.items():
        cur, cur_bytes = [], 0
        itemsize = dtype.itemsize
        for i in idxs:
            n = int(math.prod(leaves[i].shape)) if leaves[i].shape else 1
            if cur and cur_bytes + n * itemsize > bucket_bytes:
                buckets.append((dtype, cur))
                cur, cur_bytes = [], 0
            cur.append((i, n))
            cur_bytes += n * itemsize
        if cur:
            buckets.append((dtype, cur))
    built = []
    for dtype, members in buckets:
        sizes = tuple(n for _, n in members)
        offsets, off = [], 0
        for n in sizes:
            offsets.append(off)
            off += n
        padded = -(-off // n_shards) * n_shards
        built.append(Bucket(indices=tuple(i for i, _ in members),
                            sizes=sizes, offsets=tuple(offsets),
                            dtype=dtype, nelems=off, padded=max(padded,
                                                                n_shards)))
    return BucketLayout(buckets=tuple(built), n_shards=n_shards,
                        shapes=tuple(tuple(l.shape) for l in leaves),
                        dtypes=tuple(jnp.dtype(l.dtype) for l in leaves))


def optimizer_state_bytes(state):
    """Total bytes of an optimizer-state pytree (the 1/N pin in tests
    and the ``zero_sweep`` per-rank accounting)."""
    return sum(l.size * l.dtype.itemsize
               for l in jax.tree.leaves(state)
               if hasattr(l, "dtype"))


# ---- sharded optimizer state ----------------------------------------

class ZeroAdamState(NamedTuple):
    """Sharded fused-adam state with EVERY leaf's leading dim divisible
    by the shard count, so the whole state splits uniformly over the
    zero axis: ``count`` is the step counter tiled to ``(n_shards,)``
    (each rank's block is its ``(1,)`` copy), ``mu``/``nu`` are tuples
    of flat padded bucket arrays — per rank, 1/N of the replicated
    ``FusedAdamState``."""

    count: Any
    mu: Any
    nu: Any


class ZeroMasterAdamState(NamedTuple):
    """Sharded fused-master-adam state: the fp32 ``master`` shards live
    in the state (ZeRO-1 over the master-weights recipe); ``mu``/``nu``
    are f32, all 1/N per rank."""

    count: Any
    master: Any
    mu: Any
    nu: Any


def _optimizer_hyper(optimizer):
    hyper = getattr(optimizer, "hyper", None)
    if not hyper or hyper.get("kind") not in ("adam", "master_adam"):
        raise ValueError(
            "zero= needs a fused optimizer carrying its hyperparameters "
            "(parallel.precision.fused_adam / fused_master_adam); got "
            f"{optimizer!r}. optax transformations have no single-pass "
            "shard apply — wrap the update in fused form first.")
    return hyper


# ---- the SPMD apply program -----------------------------------------

def _zero_spmd(inner, axis, size, mesh, split_in, split_out,
               inter_axis=None, inter_size=1):
    """Run ``inner`` manual over the zero axis: ``jax.shard_map`` when
    this jax has it AND a mesh was provided, else the same
    ``vmap(axis_name=...)`` emulation the pipeline schedules use on
    jax 0.4.x boxes (identical collective semantics; GSPMD lays the
    emulated program out freely). ``split_in``/``split_out`` are
    per-argument booleans: True = leading dim splits over ``axis``
    (every leaf of that argument), False = replicated.

    ``inter_axis`` (the cross-plane ZeRO split) binds a second named
    axis the inner program psums its gradient shards over. Data stays
    replicated across it (each inter member holds the same accumulated
    grads under the emulation; the real multi-slice run feeds per-slice
    grads), so the emulation maps a dummy over the axis and every
    member computes the identical result — index 0 is returned."""
    if mesh is not None and hasattr(jax, "shard_map"):
        from jax.sharding import PartitionSpec as P

        names = {axis} if inter_axis is None else {axis, inter_axis}
        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=tuple(P(axis) if s else P() for s in split_in),
            out_specs=tuple(P(axis) if s else P() for s in split_out),
            axis_names=names, check_vma=False)

    def emulated(*args):
        split = lambda a: jax.tree.map(  # noqa: E731
            lambda x: x.reshape((size, x.shape[0] // size) + x.shape[1:]),
            a)
        args = tuple(split(a) if s else a
                     for a, s in zip(args, split_in))
        outs = jax.vmap(inner,
                        in_axes=tuple(0 if s else None for s in split_in),
                        out_axes=0, axis_name=axis)(*args)
        merge = lambda o: jax.tree.map(  # noqa: E731
            lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]),
            o)
        first = lambda o: jax.tree.map(lambda x: x[0], o)  # noqa: E731
        return tuple(merge(o) if s else first(o)
                     for o, s in zip(outs, split_out))

    if inter_axis is None:
        return emulated

    def emulated_hier(*args):
        # Bind the inter axis via a dummy mapped operand (vmap needs at
        # least one); all real args replicate across it. Every member's
        # result is identical post-psum, so member 0 stands for all.
        dummy = jnp.zeros((inter_size,), jnp.float32)
        outs = jax.vmap(lambda _d, *a: emulated(*a),
                        in_axes=(0,) + (None,) * len(args),
                        out_axes=0, axis_name=inter_axis)(dummy, *args)
        return jax.tree.map(lambda x: x[0], outs)

    return emulated_hier


def build_zero_apply_inner(hyper, layout, axis, size, inter_axis=None,
                           inter_size=1):
    """The per-rank apply program (manual over ``axis``):

    for every bucket, ``psum_scatter`` the full gradient bucket (rank r
    receives the mean-gradient shard it owns), run the single-pass adam
    leaf kernel on the 1/N (params, mu, nu[, master]) shards, and
    ``all_gather`` the updated param shards back into the replicated
    flat bucket. Registered standalone with hvdlint (traced via
    ``jax.make_jaxpr(axis_env=[(axis, size)])`` — no mesh or shard_map
    needed), where check C6 verifies every reduce-scatter pairs with an
    allgather on the same axis.

    With ``inter_axis`` the RS/AG pair SPLITS ACROSS PLANES
    (docs/redistribute.md): the scatter and gather stay on ``axis``
    (ICI), and the 1/size gradient shard additionally psums over
    ``inter_axis`` (DCN) between them — the hierarchical allreduce
    shape with the optimizer update fused at the 1/N point, so only
    1/size of the gradient bytes ever cross the expensive fabric.
    """
    lr, b1 = hyper["learning_rate"], hyper["b1"]
    b2, eps = hyper["b2"], hyper["eps"]
    master = hyper["kind"] == "master_adam"
    compute_dtype = hyper.get("compute_dtype")
    inv_size = 1.0 / (size * max(int(inter_size), 1))

    def inner(grads_flat, params_flat, opt):
        r = lax.axis_index(axis)
        count = opt.count + 1  # per-rank (1,) block of the tiled counter
        bc1, bc2 = _bias_corrections(count[0], b1, b2)
        new_params, new_mu, new_nu, new_master = [], [], [], []
        for i, b in enumerate(layout.buckets):
            s = b.shard_elems(size)
            # Reduce-scatter: rank r owns flat segment [r*s, (r+1)*s) of
            # every bucket (the rot=-1 ownership — ring_owned_segment).
            # Runs at the gradient's native width (the wire stays
            # narrow; the adam kernel upcasts the SHARD to f32), and the
            # mean over the axis folds on the shard — one s-element
            # multiply instead of a padded-bucket one.
            g_shard = lax.psum_scatter(
                grads_flat[i], axis, scatter_dimension=0, tiled=True)
            if inter_axis is not None:
                # Cross-plane hop: only the 1/size shard crosses the
                # inter (DCN) axis — the hierarchical decomposition.
                g_shard = lax.psum(g_shard, inter_axis)
            g_shard = g_shard * inv_size
            if master:
                p_shard = opt.master[i]
            else:
                p_shard = lax.dynamic_slice(params_flat[i], (r * s,), (s,))
            p2, mu2, nu2 = _adam_leaf(
                p_shard, g_shard, opt.mu[i], opt.nu[i], lr, b1, b2, eps,
                bc1, bc2, p_shard.dtype)
            if master:
                new_master.append(p2)
                out_shard = p2.astype(compute_dtype)
            else:
                out_shard = p2
            # Allgather the updated shards: rank-order concatenation is
            # exactly the packed bucket layout.
            new_params.append(lax.all_gather(out_shard, axis, axis=0,
                                             tiled=True))
            new_mu.append(mu2)
            new_nu.append(nu2)
        if master:
            new_opt = ZeroMasterAdamState(count=count,
                                          master=tuple(new_master),
                                          mu=tuple(new_mu),
                                          nu=tuple(new_nu))
        else:
            new_opt = ZeroAdamState(count=count, mu=tuple(new_mu),
                                    nu=tuple(new_nu))
        return tuple(new_params), new_opt

    return inner


def zero_state_init(hyper, layout, params, size):
    """Build the ZeRO-1 carry ``(params, opt)`` for a bucket layout:
    optimizer state laid out so every leaf's leading dim splits
    ``size``-fold over the zero axis (``ZeroAdamState`` /
    ``ZeroMasterAdamState`` docstrings). Shared by the unfused apply
    (:func:`make_zero_apply`) and the fused one-program step
    (``parallel.fusion.make_fused_zero_programs``) — the SAME carry, so
    the ``HOROVOD_JIT_FUSION`` knob can flip without converting
    state."""
    master = hyper["kind"] == "master_adam"
    flat = layout.pack(jax.tree.leaves(params))
    count = jnp.zeros((size,), jnp.int32)
    if master:
        m_dtype = hyper.get("master_dtype", jnp.float32)
        master_flat = tuple(jnp.array(f, m_dtype) for f in flat)
        opt = ZeroMasterAdamState(
            count=count, master=master_flat,
            mu=tuple(jnp.zeros_like(m) for m in master_flat),
            nu=tuple(jnp.zeros_like(m) for m in master_flat))
        params = jax.tree.map(
            lambda p: p.astype(hyper["compute_dtype"]), params)
    else:
        opt = ZeroAdamState(
            count=count,
            mu=tuple(jnp.zeros_like(f) for f in flat),
            nu=tuple(jnp.zeros_like(f) for f in flat))
    return params, opt


def make_zero_apply(optimizer, zero, jit_kwargs=None):
    """Build the ZeRO apply for ``make_split_train_step``.

    Returns ``(apply_fn, init)``: ``init(params) -> (params, opt)``
    carry (optimizer state sharded N-fold over ``zero.axis``) and
    ``apply_fn(grads, params, opt) -> (params, opt)`` — drop-in for the
    replicated apply program, same donation contract (params/opt
    donate 1:1 into their updated versions; grads do not).
    """
    hyper = _optimizer_hyper(optimizer)
    size = zero.resolved_size()
    jk = dict(jit_kwargs or {})
    cache = {}  # treedef -> (layout, jitted apply)

    def _programs(params):
        leaves, treedef = jax.tree.flatten(params)
        key = treedef
        if key in cache:
            return cache[key]
        layout = zero_bucket_layout(leaves, size, zero.bucket_bytes)
        inner = build_zero_apply_inner(
            hyper, layout, zero.axis, size,
            inter_axis=zero.inter_axis,
            inter_size=zero.inter_size)
        spmd = _zero_spmd(inner, zero.axis, size, zero.mesh,
                          split_in=(False, False, True),
                          split_out=(False, True),
                          inter_axis=zero.inter_axis,
                          inter_size=zero.inter_size)

        @functools.partial(jax.jit, donate_argnums=(1, 2), **jk)
        def jitted_apply(grads, params, opt):
            g_flat = layout.pack(treedef.flatten_up_to(grads))
            p_flat = layout.pack(treedef.flatten_up_to(params))
            new_flat, opt = spmd(tuple(g_flat), tuple(p_flat), opt)
            return (jax.tree.unflatten(treedef,
                                       layout.unpack(list(new_flat))),
                    opt)

        cache[key] = (layout, treedef, jitted_apply)
        return cache[key]

    def init(params):
        layout, _, _ = _programs(params)
        return zero_state_init(hyper, layout, params, size)

    def apply_fn(grads, params, opt):
        _, _, fn = _programs(params)
        return fn(grads, params, opt)

    return apply_fn, init
