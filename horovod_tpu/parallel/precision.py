"""Mixed-precision training: fp32 master weights with bf16 compute,
plus the single-pass FUSED optimizer-apply kernels.

Reference analog: none in the core reference — upstream Horovod trains
in the framework's fp32 and only compresses the wire
(``horovod/torch/compression.py`` ``Compression.fp16``). On a 16G-HBM
TPU chip, pure-bf16 parameter+optimizer storage is the recipe that fits
>1B params but leaves adam's second moment in bf16 (a long-horizon
convergence hazard); this module provides the standard middle point:

- **master**: fp32 copy of every parameter, owned by the train state;
- **compute**: bf16 (or any ``compute_dtype``) cast of the master used
  by forward/backward each step — XLA fuses the cast into consumers;
- **optimizer**: any optax transformation, running in fp32 on the
  master (moments therefore fp32).

HBM cost per parameter: 4 (master) + inner-state (8 for adam) + the
transient compute cast, vs 2+4 for pure-bf16 adam — the numerically
safe recipe for sub-~1B models on one chip, and for any size when
sharded (fsdp divides all of it).

Usage::

    mw = master_weights(optax.adam(3e-4))
    state = mw.init(params)             # params any dtype; master = fp32
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        params = mw.compute_params(state)          # bf16 view
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        return loss, mw.apply(state, grads)

Fused formulation (round 6): the optimizer update is the largest pure
HBM-bandwidth tax left on the flagship step (the r5 MoE xplane puts the
adam traffic on 1.49B carried params at ~25 ms/step — 7 passes over
param-sized arrays). ``fused_adam`` expresses the whole update — moment
updates, bias correction, parameter write — as ONE elementwise
expression per leaf so XLA emits a single fused loop touching each
param-sized array exactly once (4 reads, 3 writes — the adam minimum),
instead of optax's chain of per-transformation trees (each a
potentially materialized intermediate). ``fused_master_adam``
additionally folds the master->compute cast into the same pass, so the
split formulation's second read of the master tree
(``apply`` then ``compute_params``) disappears. Both are
drop-in ``FusedOptimizer`` objects for
``parallel.train_step.make_split_train_step``; numerical equivalence
to ``optax.adam`` / ``master_weights(optax.adam(...))`` at f32 is
pinned by ``tests/single/test_llama.py`` (for bf16 params the fused
kernels keep the update math in f32 where optax rounds per transform —
see ``fused_adam``).
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class MasterWeightsState(NamedTuple):
    master: Any   # fp32 parameter pytree
    inner: Any    # wrapped optimizer state (over the master tree)


class MasterWeights(NamedTuple):
    init: Any
    compute_params: Any
    apply: Any


def master_weights(tx, compute_dtype=jnp.bfloat16,
                   master_dtype=jnp.float32):
    """Wrap an optax optimizer with an fp32 master-parameter loop."""

    def init(params):
        # fp32 inputs alias through jnp.asarray (no copy, no precision
        # loss); init from fp32 params when possible.
        master = jax.tree.map(
            lambda p: jnp.asarray(p, master_dtype), params)
        return MasterWeightsState(master=master, inner=tx.init(master))

    def compute_params(state):
        return jax.tree.map(
            lambda p: p.astype(compute_dtype), state.master)

    def apply(state, grads):
        import optax  # deferred: parallel/ stays importable without optax

        grads = jax.tree.map(
            lambda g: g.astype(master_dtype), grads)
        updates, inner = tx.update(grads, state.inner, state.master)
        master = optax.apply_updates(state.master, updates)
        return MasterWeightsState(master=master, inner=inner)

    return MasterWeights(init=init, compute_params=compute_params,
                         apply=apply)


# ---- fused single-pass optimizer apply -------------------------------

class FusedAdamState(NamedTuple):
    count: Any    # int32 scalar step counter
    mu: Any       # first-moment pytree
    nu: Any       # second-moment pytree


class FusedOptimizer(NamedTuple):
    """The optimizer protocol ``make_split_train_step`` recognizes as
    fused: ``apply(params, grads, state) -> (new_params, new_state)``
    produces the updated parameters DIRECTLY (no intermediate updates
    tree, no separate ``optax.apply_updates`` pass). ``hyper`` carries
    the constructor's hyperparameters so shard-level re-expressions of
    the same update (``parallel.zero``, the ZeRO-1 apply) can rebuild
    the identical single-pass kernel on 1/N state."""
    init: Any
    apply: Any
    hyper: Any = None


def _adam_leaf(p, g, mu, nu, lr, b1, b2, eps, bc1, bc2, out_dtype):
    """One parameter leaf's full adam step in f32, emitted as a single
    elementwise expression so XLA fuses it into one pass."""
    gf = g.astype(jnp.float32)
    mu2 = b1 * mu.astype(jnp.float32) + (1.0 - b1) * gf
    nu2 = b2 * nu.astype(jnp.float32) + (1.0 - b2) * jnp.square(gf)
    update = lr * (mu2 / bc1) / (jnp.sqrt(nu2 / bc2) + eps)
    p2 = (p.astype(jnp.float32) - update).astype(out_dtype)
    return p2, mu2.astype(mu.dtype), nu2.astype(nu.dtype)


def _bias_corrections(count, b1, b2):
    cf = count.astype(jnp.float32)
    return 1.0 - b1 ** cf, 1.0 - b2 ** cf


def fused_adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8):
    """Single-pass adam: moments in the parameter dtype (matching
    ``optax.adam``'s default ``mu_dtype``), all math in f32. For f32
    params this is numerically equivalent to ``optax.adam`` (pinned by
    ``tests/single/test_llama.py::test_fused_adam_matches_optax``).
    For bf16 params (the pure-bf16 flagship) the two deliberately
    differ: optax's chained transforms do moment arithmetic in the
    bf16 gradient dtype, while this kernel computes every step in f32
    and only rounds the STORED moments/params to bf16 — the same
    optimizer to bf16 resolution, with strictly less rounding inside
    the update math."""

    def init(params):
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params))

    def apply(params, grads, state):
        count = state.count + 1
        bc1, bc2 = _bias_corrections(count, b1, b2)
        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_mu = treedef.flatten_up_to(state.mu)
        flat_nu = treedef.flatten_up_to(state.nu)
        out = [_adam_leaf(p, g, mu, nu, learning_rate, b1, b2, eps,
                          bc1, bc2, p.dtype)
               for p, g, mu, nu in zip(flat_p, flat_g, flat_mu, flat_nu)]
        unflat = lambda i: jax.tree.unflatten(  # noqa: E731
            treedef, [o[i] for o in out])
        return unflat(0), FusedAdamState(count=count, mu=unflat(1),
                                         nu=unflat(2))

    return FusedOptimizer(init=init, apply=apply,
                          hyper={"kind": "adam",
                                 "learning_rate": learning_rate,
                                 "b1": b1, "b2": b2, "eps": eps})


class FusedMasterState(NamedTuple):
    master: Any   # master-dtype (fp32) parameter pytree
    count: Any
    mu: Any       # f32 moments (the numerically safe recipe)
    nu: Any


class FusedMasterOptimizer(NamedTuple):
    """FusedOptimizer protocol plus the initial-cast helper (the step
    carry holds COMPUTE-dtype params; build it as
    ``(opt.compute_params(state), state)`` after ``init``). ``hyper``
    as in :class:`FusedOptimizer`."""
    init: Any
    apply: Any
    compute_params: Any
    hyper: Any = None


def fused_master_adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
                      compute_dtype=jnp.bfloat16,
                      master_dtype=jnp.float32):
    """Adam + master-weight cast in a SINGLE pass over params.

    The split formulation (``master_weights(optax.adam(...))``) touches
    every master-sized array twice per step: once in ``apply`` (update)
    and once in ``compute_params`` (the bf16 cast the next forward
    consumes). Here ``apply(params, grads, state)`` emits the new
    master AND its compute-dtype cast from the same fused loop — one
    read of the master tree per step instead of two. The ``params``
    argument is the previous step's compute cast; its buffers are
    donated back as the new cast's storage (it does not enter the
    math). Returns ``(new_compute_params, new_state)`` — the
    ``FusedOptimizer`` protocol, so it drops into
    ``make_split_train_step`` unchanged.
    """

    def init(params):
        # jnp.array (copy), NOT jnp.asarray: for params already in
        # master_dtype asarray returns the SAME buffer, and the apply
        # jits donate the state — an aliased master would invalidate
        # the caller's params tree after the first step.
        master = jax.tree.map(lambda p: jnp.array(p, master_dtype),
                              params)
        zeros = lambda t: jax.tree.map(jnp.zeros_like, t)  # noqa: E731
        return FusedMasterState(master=master,
                                count=jnp.zeros((), jnp.int32),
                                mu=zeros(master), nu=zeros(master))

    def compute_params(state):
        return jax.tree.map(lambda p: p.astype(compute_dtype),
                            state.master)

    def apply(params, grads, state):
        del params  # donated storage only; math reads the master
        count = state.count + 1
        bc1, bc2 = _bias_corrections(count, b1, b2)
        flat_m, treedef = jax.tree.flatten(state.master)
        flat_g = treedef.flatten_up_to(grads)
        flat_mu = treedef.flatten_up_to(state.mu)
        flat_nu = treedef.flatten_up_to(state.nu)
        out = []
        for m, g, mu, nu in zip(flat_m, flat_g, flat_mu, flat_nu):
            m2, mu2, nu2 = _adam_leaf(m, g, mu, nu, learning_rate, b1,
                                      b2, eps, bc1, bc2, m.dtype)
            out.append((m2.astype(compute_dtype), m2, mu2, nu2))
        unflat = lambda i: jax.tree.unflatten(  # noqa: E731
            treedef, [o[i] for o in out])
        state = FusedMasterState(master=unflat(1), count=count,
                                 mu=unflat(2), nu=unflat(3))
        return unflat(0), state

    return FusedMasterOptimizer(init=init, apply=apply,
                                compute_params=compute_params,
                                hyper={"kind": "master_adam",
                                       "learning_rate": learning_rate,
                                       "b1": b1, "b2": b2, "eps": eps,
                                       "compute_dtype": compute_dtype,
                                       "master_dtype": master_dtype})
