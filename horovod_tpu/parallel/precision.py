"""Mixed-precision training: fp32 master weights with bf16 compute.

Reference analog: none in the core reference — upstream Horovod trains
in the framework's fp32 and only compresses the wire
(``horovod/torch/compression.py`` ``Compression.fp16``). On a 16G-HBM
TPU chip, pure-bf16 parameter+optimizer storage is the recipe that fits
>1B params but leaves adam's second moment in bf16 (a long-horizon
convergence hazard); this module provides the standard middle point:

- **master**: fp32 copy of every parameter, owned by the train state;
- **compute**: bf16 (or any ``compute_dtype``) cast of the master used
  by forward/backward each step — XLA fuses the cast into consumers;
- **optimizer**: any optax transformation, running in fp32 on the
  master (moments therefore fp32).

HBM cost per parameter: 4 (master) + inner-state (8 for adam) + the
transient compute cast, vs 2+4 for pure-bf16 adam — the numerically
safe recipe for sub-~1B models on one chip, and for any size when
sharded (fsdp divides all of it).

Usage::

    mw = master_weights(optax.adam(3e-4))
    state = mw.init(params)             # params any dtype; master = fp32
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        params = mw.compute_params(state)          # bf16 view
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        return loss, mw.apply(state, grads)
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class MasterWeightsState(NamedTuple):
    master: Any   # fp32 parameter pytree
    inner: Any    # wrapped optimizer state (over the master tree)


class MasterWeights(NamedTuple):
    init: Any
    compute_params: Any
    apply: Any


def master_weights(tx, compute_dtype=jnp.bfloat16,
                   master_dtype=jnp.float32):
    """Wrap an optax optimizer with an fp32 master-parameter loop."""

    def init(params):
        # fp32 inputs alias through jnp.asarray (no copy, no precision
        # loss); init from fp32 params when possible.
        master = jax.tree.map(
            lambda p: jnp.asarray(p, master_dtype), params)
        return MasterWeightsState(master=master, inner=tx.init(master))

    def compute_params(state):
        return jax.tree.map(
            lambda p: p.astype(compute_dtype), state.master)

    def apply(state, grads):
        import optax  # deferred: parallel/ stays importable without optax

        grads = jax.tree.map(
            lambda g: g.astype(master_dtype), grads)
        updates, inner = tx.update(grads, state.inner, state.master)
        master = optax.apply_updates(state.master, updates)
        return MasterWeightsState(master=master, inner=inner)

    return MasterWeights(init=init, compute_params=compute_params,
                         apply=apply)
