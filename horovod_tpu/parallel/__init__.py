"""horovod_tpu.parallel — the TPU-native in-graph SPMD layer.

Net-new relative to the reference (SURVEY.md §5.7: Horovod is pure data
parallelism; TP/SP/ring-attention are absent upstream). This package is the
"xla_ici" data plane of the rebuild: instead of enqueueing host-side
collectives, training steps are jit-compiled over a ``jax.sharding.Mesh``
and XLA inserts psum/all-gather/ppermute collectives that ride the TPU ICI.

Axis conventions (the mesh dimension names the rest of the framework uses):

- ``data``   — pure data parallelism (gradient psum; Horovod's DP)
- ``fsdp``   — data parallelism with sharded params/optimizer (ZeRO-3)
- ``tensor`` — megatron-style tensor parallelism inside matmuls
- ``seq``    — sequence/context parallelism (ring attention)
- ``pipe``   — pipeline stages
- ``expert`` — MoE expert parallelism
"""

from horovod_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    create_mesh,
    local_mesh,
)
from horovod_tpu.parallel.ops import (  # noqa: F401
    all_gather,
    all_to_all,
    hier_allreduce,
    pbroadcast,
    pmean,
    ppermute_ring,
    predicted_hier_collectives,
    psum,
    reduce_scatter,
)
from horovod_tpu.parallel.reshard import (  # noqa: F401
    Layout,
    ReshardPlan,
    even_row_layout,
    execute_plan,
    layout_from_sharding,
    plan_redistribute,
    redistribute,
    simulate_plan,
)
from horovod_tpu.parallel.pipeline import (  # noqa: F401
    build_interleaved_schedule,
    build_pipeline_inner,
    gpipe,
    interleaved_one_f_one_b,
    one_f_one_b,
    predicted_collectives,
)
from horovod_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_self_attention,
)
from horovod_tpu.parallel.ring_attention import (  # noqa: F401
    blockwise_attention,
    ring_attention,
    ring_self_attention,
)
from horovod_tpu.parallel.sharding import (  # noqa: F401
    named_sharding,
    shard_params,
    with_constraint,
)
from horovod_tpu.parallel.precision import (  # noqa: F401
    FusedAdamState,
    FusedMasterState,
    FusedOptimizer,
    MasterWeightsState,
    fused_adam,
    fused_master_adam,
    master_weights,
)
from horovod_tpu.parallel.train_step import (  # noqa: F401
    TrainStep,
    make_split_train_step,
)
from horovod_tpu.parallel.zero import (  # noqa: F401
    ZeroAdamState,
    ZeroConfig,
    ZeroMasterAdamState,
    optimizer_state_bytes,
    ring_owned_segment,
    zero_bucket_layout,
)
