"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

Net-new vs the reference (Horovod has no sequence parallelism —
SURVEY.md §5.7), complementing ring attention: instead of rotating K/V
around the ring, two ``all_to_all`` collectives re-shard
sequence-parallel Q/K/V from (tokens split, all heads) to (all tokens,
heads split), run ordinary full-sequence attention locally per head
group, and shard back. Communication is 2 all-to-alls of Q/K/V/O
instead of ``P`` neighbor exchanges of K/V — cheaper than the ring when
the per-device sequence is short relative to the head count, and it
reuses the single-device flash/blockwise kernel unchanged.

Trade-off vs ring attention: the mesh axis size must divide the head
count (grouped-query K/V heads are replicated up to lcm(Hkv, P) when
the axis does not divide Hkv), and peak activation memory holds the
full sequence for H/P heads.
"""

import math

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.ring_attention import (
    _repeat_kv,
    blockwise_attention,
)


def ulysses_attention(q, k, v, axis_name, causal=True, use_flash=None):
    """Exact attention with sequence sharded over mesh axis ``axis_name``.

    Must run inside shard_map with the sequence dimension sharded
    contiguously across the axis. Local shards: q [B, T/P, H, D];
    k, v [B, T/P, Hkv, D]. Requires H % P == 0; when P does not divide
    Hkv, K/V heads are replicated up to lcm(Hkv, P) first.

    ``use_flash`` (default: auto — True on TPU) runs the post-all-to-all
    local attention through the pallas flash kernels (which handle the
    remaining GQA grouping natively) instead of the XLA blockwise math.
    """
    if use_flash is None:
        use_flash = jax.devices()[0].platform in ("tpu", "axon")
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(
            f"ulysses_attention needs n_heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring_attention otherwise")
    if k.shape[2] % n != 0:
        # GQA head count not divisible by the axis: replicate K/V only up
        # to lcm(Hkv, P). Both Hkv and P divide H, so the lcm does too,
        # and the local attention re-expands the remaining grouping —
        # moving H/lcm× less K/V than replicating to H.
        target = k.shape[2] * n // math.gcd(k.shape[2], n)
        k = _repeat_kv(k, target // k.shape[2])
        v = _repeat_kv(v, target // v.shape[2])

    def to_heads(x):  # [B, T/P, H', D] -> [B, T, H'/P, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = to_heads(q), to_heads(k), to_heads(v)
    if use_flash:
        from horovod_tpu.ops import flash_attention

        out = flash_attention(qg, kg, vg, causal=causal)
    else:
        out = blockwise_attention(qg, kg, vg, causal=causal)
    # [B, T, H/P, D] -> [B, T/P, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_self_attention(q, k, v, mesh, causal=True, batch_axis="data",
                           seq_axis="seq"):
    """User-facing wrapper: shard q/k/v over (batch, seq) and run
    ulysses_attention under shard_map on the given mesh."""
    spec = P(batch_axis, seq_axis, None, None)

    @jax.shard_map(mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                   check_vma=False)
    def _run(ql, kl, vl):
        return ulysses_attention(ql, kl, vl, seq_axis, causal=causal)

    return _run(q, k, v)
