"""Sharding-rule helpers: map pytrees of params onto the mesh.

The pjit recipe: params carry ``PartitionSpec``s chosen by rule (regex or
per-path), inputs shard on the data/seq axes, ``with_sharding_constraint``
pins activation layouts where XLA needs a hint. Reference analog: none —
Horovod shards nothing (pure DP); this is the net-new TP/FSDP machinery.
"""

import re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def named_sharding(mesh, *spec):
    return NamedSharding(mesh, P(*spec))


def with_constraint(x, mesh, *spec):
    """Pin an intermediate's sharding inside jit."""
    return jax.lax.with_sharding_constraint(x, named_sharding(mesh, *spec))


def shard_params(params, mesh, rules, default=P()):
    """Assign NamedShardings to a param pytree by path-regex rules.

    ``rules`` is an ordered list of ``(pattern, PartitionSpec)``; the first
    pattern matching the '/'-joined tree path wins. Returns a pytree of
    NamedShardings (pass to jax.device_put or as jit out_shardings).
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(path):
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        for pat, spec in compiled:
            if pat.search(name):
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, default)

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: spec_for(path), params)


def apply_sharding(params, shardings):
    """device_put the pytree onto its shardings (host->HBM, sharded)."""
    return jax.device_put(params, shardings)
