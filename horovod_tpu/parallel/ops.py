"""In-graph collectives over named mesh axes.

These are the XLA-native analogs of the core runtime's eager collectives
(reference: horovod/common/ops/nccl_operations.cc): inside a jit-compiled
program, ``psum``/``all_gather``/``ppermute`` lower to ICI collectives
fused and scheduled by XLA — no background thread, no fusion buffer; the
compiler owns both.

Use under ``jax.shard_map`` (or inside ``jax.jit`` with sharding
constraints, where XLA inserts them implicitly).
"""

import jax.numpy as jnp
from jax import lax


def psum(x, axis_name):
    """Sum across a mesh axis. Horovod analog: hvd.allreduce(op=Sum)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    """Mean across a mesh axis. Horovod analog: hvd.allreduce(op=Average)."""
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    """Gather shards along `axis`. Horovod analog: hvd.allgather."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    """Sum then scatter along `axis`. Horovod analog: hvd.reducescatter."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, axis_name, split_axis, concat_axis):
    """Transpose shard ownership. Horovod analog: hvd.alltoall."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def hier_allreduce(x, intra_axis, inter_axis, axis=0):
    """Cross-plane composed allreduce (docs/redistribute.md): the
    in-graph twin of the core's hierarchical host path. Reduce-scatter
    over ``intra_axis`` (the ICI-priced fabric), psum the 1/L shard
    over ``inter_axis`` (the DCN-priced fabric — only 1/L of the bytes
    cross it), allgather back over ``intra_axis``. Equal to
    ``psum(x, (intra_axis, inter_axis))`` up to f32 association order;
    bandwidth-optimal on both fabrics at once.
    """
    shard = lax.psum_scatter(x, intra_axis, scatter_dimension=axis,
                             tiled=True)
    shard = lax.psum(shard, inter_axis)
    return lax.all_gather(shard, intra_axis, axis=axis, tiled=True)


def predicted_hier_collectives(intra_axis, inter_axis):
    """The host-side collective prediction for :func:`hier_allreduce`
    — fed to hvdlint's C5 schedule-conformance check, so the composed-
    plane program and this table can never silently diverge."""
    return [("psum_scatter", (intra_axis,)),
            ("psum", (inter_axis,)),
            ("all_gather", (intra_axis,))]


def predicted_zero_collectives(n_buckets, axis, inter_axis=None):
    """The host-side collective prediction for the ZeRO-1 shard apply
    (``parallel.zero.build_zero_apply_inner``): per bucket, a
    reduce-scatter over the zero ``axis``, the optional 1/N cross-plane
    psum over ``inter_axis``, and the allgather of the updated shard
    back over ``axis``. Fed to hvdlint's C5 so the bucketed schedule
    and the traced program can never silently diverge — and, because
    the fused jit-lane step reorders exactly these collectives
    (``parallel.fusion.interleave_collectives`` preserves the per-axis
    relative order C6 counts but not this bucket-serial sequence), it
    documents the UNFUSED contract the ``HOROVOD_JIT_FUSION=0`` escape
    hatch restores."""
    out = []
    for _ in range(int(n_buckets)):
        out.append(("psum_scatter", (axis,)))
        if inter_axis is not None:
            out.append(("psum", (inter_axis,)))
        out.append(("all_gather", (axis,)))
    return out


def pbroadcast(x, axis_name, root=0):
    """Broadcast root's shard to all members of the axis.

    Horovod analog: hvd.broadcast. Lowered as a masked psum (select +
    psum), which XLA turns into an efficient one-to-all on ICI.
    """
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def ppermute_ring(x, axis_name, shift=1):
    """Rotate shards around the axis ring (device i -> i+shift).

    The building block of ring attention and pipelined collectives;
    lowers to neighbor exchanges on the ICI torus.
    """
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.axis_size(axis_name)
