"""Jit-lane compute/collective fusion (docs/fusion.md).

The split train step runs gradient compute and the ZeRO-1 collective
phase as SEPARATE programs: every per-bucket reduce-scatter sits after
the last backward flop, so the wire is fully exposed — the jit lane's
``overlap_efficiency`` reads ~0 while the eager lane (r11) already
hides RS/AG under compute. "Fused Computation-Collective Operations"
(arXiv:2305.06942) is the fix this module implements for the jitted
lane: emit each bucket's reduce-scatter -> (cross-plane psum) ->
shard-adam -> all-gather chain at its earliest dataflow-ready point,
interleaved with the REMAINING backward computation, so the
latency-hiding scheduler (XLA on TPU; the async host ring on the CPU
substrate) overlaps wire with flops.

Three layers, bottom up:

1. **Jaxpr scheduling** — :func:`interleave_collectives` reorders a
   traced program's equations: collective chains (each collective, its
   transitive consumers, and the pure data-movement producers that
   exist only to feed it — the bucket pack chains) float to the
   earliest point their inputs are ready, while every other equation
   keeps its original order. The result is topologically valid by
   construction and bit-identical math in a different schedule; hvdlint
   C7 (``analysis/checks.py``) verifies the interleaving statically.

2. **Program segmentation** — :func:`segment_closed_jaxpr` splits a
   traced gradient program into runnable sub-programs at bucket-
   readiness boundaries (:func:`grad_bucket_cuts`), so a host-side
   step loop can issue eager per-bucket collectives BETWEEN compute
   segments — the eager-lane overlap recipe applied to a jitted
   backward (``hvd.make_fused_train_step``).

3. **The fused ZeRO-1 step** — :func:`make_fused_zero_programs` builds
   the one-program grad+apply step for
   ``make_split_train_step(zero=..., fusion on)``: value_and_grad +
   bucket pack + the :func:`~horovod_tpu.parallel.zero.
   build_zero_apply_inner` collective pipeline traced as ONE jaxpr
   (``axis_env`` — collectives stay visible), reordered by (1), and
   executed through ``_zero_spmd`` exactly like the unfused apply. On
   multi-slice layouts the cross-plane psum rides inside each bucket's
   chain, so the expensive hop is scheduled under intra-slice compute.

``HOROVOD_JIT_FUSION=0`` (env, or ``hvd.init(jit_fusion=False)``)
restores the unfused two-program split step; the knob changes the
SCHEDULE, never the math — pinned bit-identical by
``tests/parallel/test_fusion.py``.
"""

import dataclasses
import functools
import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

try:  # jax >= 0.5 moves the jaxpr types
    from jax.extend import core as _jcore

    _jcore.Jaxpr  # noqa: B018 — probe the attribute
except (ImportError, AttributeError):  # the 0.4.x boxes
    from jax import core as _jcore


# ---- the fusion knob -------------------------------------------------

_ENV = "HOROVOD_JIT_FUSION"
_override = None  # tri-state: None = follow the env


def set_jit_fusion(enabled):
    """Programmatic override of ``HOROVOD_JIT_FUSION`` (the
    ``hvd.init(jit_fusion=...)`` kwarg lands here). ``None`` restores
    env-driven behavior."""
    global _override
    _override = None if enabled is None else bool(enabled)


def jit_fusion_enabled():
    """Whether jit-lane compute/collective fusion is on (default: yes).

    ``HOROVOD_JIT_FUSION=0`` is the escape hatch back to the unfused
    split step — schedule-identical to the pre-fusion lane, for
    bisection when a substrate miscompiles the interleaved program."""
    if _override is not None:
        return _override
    return os.environ.get(_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no")


# ---- jaxpr scheduling ------------------------------------------------

#: named-axis collective primitives (the same family
#: ``analysis.extract.COLLECTIVE_PRIMS`` walks).
COLLECTIVE_PRIM_NAMES = frozenset({
    "psum", "pmax", "pmin", "psum_scatter", "reduce_scatter",
    "all_gather", "all_to_all", "ppermute", "pbroadcast", "pgather",
})

#: pure data-movement primitives: zero flops, so hoisting them along
#: with the collective they feed (bucket pack chains are
#: zeros + dynamic_update_slice + reshape/astype) never reorders any
#: arithmetic relative to other arithmetic.
_MOVEMENT_PRIMS = frozenset({
    "reshape", "broadcast_in_dim", "convert_element_type", "squeeze",
    "expand_dims", "transpose", "slice", "dynamic_slice",
    "dynamic_update_slice", "concatenate", "pad", "copy", "rev",
})


def _graph(eqns):
    """(deps, consumers) adjacency over equation indices."""
    producer = {}
    for i, e in enumerate(eqns):
        for v in e.outvars:
            producer[v] = i
    deps = [set() for _ in eqns]
    consumers = [[] for _ in eqns]
    for i, e in enumerate(eqns):
        for v in e.invars:
            if hasattr(v, "count") and v in producer:
                j = producer[v]
                if j != i and j not in deps[i]:
                    deps[i].add(j)
                    consumers[j].append(i)
    return deps, consumers


def collective_chains(eqns):
    """Indices of the equations that belong to a collective chain: each
    collective itself, its transitive consumers (shard update, gather,
    unpack — everything downstream of the first collective is chain
    work), and its pure data-movement ancestors (the pack copies whose
    only job is assembling the collective's operand)."""
    deps, consumers = _graph(eqns)
    colls = [i for i, e in enumerate(eqns)
             if e.primitive.name in COLLECTIVE_PRIM_NAMES]
    marked = set(colls)
    stack = list(colls)
    while stack:  # forward cone: every consumer of chain output
        for j in consumers[stack.pop()]:
            if j not in marked:
                marked.add(j)
                stack.append(j)
    def _hoistable(e):
        # Pure data movement, or negligible scalar math (the adam
        # bias-correction / axis_index offset feeders): moving these
        # never reorders real arithmetic relative to real arithmetic.
        if e.primitive.name in _MOVEMENT_PRIMS:
            return True
        sizes = [v.aval.size for v in e.outvars
                 if hasattr(getattr(v, "aval", None), "size")]
        return bool(sizes) and max(sizes) <= 64

    stack = list(marked)
    seen = set(marked)
    while stack:  # backward cone: the pack/slice/scalar feeder chains
        for j in deps[stack.pop()]:  # that exist only to feed the chain
            if j in seen:
                continue
            seen.add(j)
            if _hoistable(eqns[j]):
                marked.add(j)
                stack.append(j)
    return marked


def interleave_collectives(closed):
    """Reschedule a ``ClosedJaxpr``: collective chains move to their
    earliest dataflow-ready points; every other equation keeps its
    original relative order. Math is untouched — same equations, same
    dataflow, different emission order — so XLA sees each
    reduce-scatter BEFORE the remaining backward flops and can overlap
    the wire under them. Returns ``closed`` unchanged when there is
    nothing to move (no collectives, or already interleaved)."""
    jaxpr = closed.jaxpr
    eqns = list(jaxpr.eqns)
    marked = collective_chains(eqns)
    if not marked:
        return closed
    deps, _ = _graph(eqns)
    emitted = [False] * len(eqns)
    order = []
    pending = sorted(marked)

    def flush():
        progressed = True
        while progressed:
            progressed = False
            still = []
            for i in pending:
                if all(emitted[j] for j in deps[i]):
                    emitted[i] = True
                    order.append(i)
                    progressed = True
                else:
                    still.append(i)
            pending[:] = still

    for i in range(len(eqns)):
        if i in marked:
            continue
        flush()  # everything ready goes BEFORE the next compute eqn
        emitted[i] = True
        order.append(i)
    flush()
    assert not pending and len(order) == len(eqns), "cyclic jaxpr?"
    if order == list(range(len(eqns))):
        return closed
    reordered = _jcore.Jaxpr(jaxpr.constvars, jaxpr.invars,
                             jaxpr.outvars, [eqns[i] for i in order],
                             jaxpr.effects)
    return _jcore.ClosedJaxpr(reordered, closed.consts)


# ---- program segmentation (the host-lane overlap vehicle) ------------

@dataclasses.dataclass(frozen=True)
class Segment:
    fn: Any          # jitted callable over ``in_vars`` values
    in_vars: tuple   # jaxpr Vars consumed (from env)
    out_vars: tuple  # jaxpr Vars produced (into env)


@dataclasses.dataclass(frozen=True)
class SegmentedProgram:
    """A traced program split into sequentially runnable jits.

    ``run`` threads an environment of jaxpr-var -> value through the
    segments; ``on_boundary(k, env)`` fires after segment ``k`` is
    DISPATCHED (jax async dispatch — its outputs are futures), which is
    exactly where the host step loop issues the eager collectives for
    the gradient buckets that segment completed: the remaining
    segments keep computing while the wire drains the finished buckets.
    """

    segments: tuple
    invars: tuple
    outvars: tuple
    const_env: Any   # dict of constvar -> value

    def run(self, *args, on_boundary=None):
        env = dict(self.const_env)
        env.update(zip(self.invars, args))
        for k, seg in enumerate(self.segments):
            outs = seg.fn(*(env[v] for v in seg.in_vars))
            env.update(zip(seg.out_vars, outs))
            if on_boundary is not None:
                on_boundary(k, env)
        return [v.val if isinstance(v, _jcore.Literal) else env[v]
                for v in self.outvars], env

    def read_output(self, env, position):
        v = self.outvars[position]
        return v.val if isinstance(v, _jcore.Literal) else env[v]


def segment_closed_jaxpr(closed, cuts, jit_kwargs=None):
    """Split ``closed`` at equation indices ``cuts`` (ascending,
    exclusive prefix lengths) into a :class:`SegmentedProgram`. Each
    segment is its own jit over exactly the live values crossing its
    boundaries; running the segments back-to-back replays the original
    program's math (pinned by tests/single/test_fusion_pass.py)."""
    jaxpr = closed.jaxpr
    eqns = list(jaxpr.eqns)
    cuts = [c for c in sorted(set(cuts)) if 0 < c < len(eqns)]
    bounds = [0, *cuts, len(eqns)]
    ranges = list(zip(bounds[:-1], bounds[1:]))
    jk = dict(jit_kwargs or {})

    seg_use, seg_def = [], []
    for a, b in ranges:
        use, use_set, defs = [], set(), set()
        for e in eqns[a:b]:
            for v in e.invars:
                if (hasattr(v, "count") and v not in defs
                        and v not in use_set):
                    use.append(v)
                    use_set.add(v)
            for v in e.outvars:
                defs.add(v)
        seg_use.append(use)
        seg_def.append(defs)

    # used_later[k]: vars needed strictly after segment k (or outputs).
    acc = {v for v in jaxpr.outvars if hasattr(v, "count")}
    used_later = [None] * len(ranges)
    for k in reversed(range(len(ranges))):
        used_later[k] = set(acc)
        acc |= set(seg_use[k])

    segments = []
    for k, (a, b) in enumerate(ranges):
        out_vars = []
        seen = set()
        for e in eqns[a:b]:
            for v in e.outvars:
                if v in used_later[k] and v not in seen:
                    out_vars.append(v)
                    seen.add(v)
        effects = set()
        for e in eqns[a:b]:
            effects |= set(getattr(e, "effects", ()))
        sub = _jcore.Jaxpr((), tuple(seg_use[k]), tuple(out_vars),
                           eqns[a:b], frozenset(effects))
        fn = jax.jit(_jcore.jaxpr_as_fun(_jcore.ClosedJaxpr(sub, ())),
                     **jk)
        segments.append(Segment(fn=fn, in_vars=tuple(seg_use[k]),
                                out_vars=tuple(out_vars)))
    return SegmentedProgram(
        segments=tuple(segments), invars=tuple(jaxpr.invars),
        outvars=tuple(jaxpr.outvars),
        const_env=dict(zip(jaxpr.constvars, closed.consts)))


def grad_bucket_cuts(closed, layout, grad_out_start=1):
    """Bucket-readiness cut points for a traced gradient program whose
    outputs are ``(loss, *grad_leaves)`` (``grad_out_start`` skips the
    loss). Returns ``(cuts, ready)``: ``cuts`` are the equation indices
    where at least one bucket's gradient leaves are all produced
    (feed :func:`segment_closed_jaxpr`), ``ready[b]`` the cut each
    bucket completes at — ``sorted(range(n), key=ready.__getitem__)``
    is the wire issue order."""
    eqns = closed.jaxpr.eqns
    producer = {}
    for i, e in enumerate(eqns):
        for v in e.outvars:
            producer[v] = i
    ready = []
    for b in layout.buckets:
        r = 0
        for li in b.indices:
            v = closed.jaxpr.outvars[grad_out_start + li]
            if hasattr(v, "count") and v in producer:
                r = max(r, producer[v] + 1)
        ready.append(r)
    cuts = sorted({r for r in ready if 0 < r < len(eqns)})
    return cuts, ready


# ---- the fused (one-program) ZeRO-1 step -----------------------------

class FusedZeroPrograms(NamedTuple):
    init: Any        # init(params) -> (params, opt) ZeRO-1 carry
    call: Any        # call(params, batch, opt) -> (loss, params, opt)
    call_final: Any  # call_final(params, loss_acc, acc, batch, opt)
    get: Any         # get(params, batch, opt, accumulate) -> the jit


def fused_zero_inner(loss_fn, params, batch, opt, hyper, layout,
                     treedef, axis, size, *, inter_axis=None,
                     inter_size=1, accumulate=False, loss_scale=1.0):
    """Build the flat per-rank fused grad+apply program and its example
    arguments: ``(inner, example_args, donate_argnums, axis_env)``.

    ``inner`` takes/returns FLAT leaves (so ``jax.make_jaxpr`` /
    ``_zero_spmd`` / ``jaxpr_as_fun`` compose without pytree plumbing):

        inputs  = (*params, [loss_acc, *acc,] *batch, *opt)
        outputs = (loss, *new_params, *new_opt)

    Body: ``value_and_grad(loss_fn)`` (+ the microbatch accumulator
    fold when ``accumulate``), bucket pack, then
    :func:`~horovod_tpu.parallel.zero.build_zero_apply_inner`'s
    per-bucket reduce-scatter -> (cross-plane psum) -> shard-adam ->
    all-gather pipeline, unpack. Traced with ``axis_env`` the
    collectives stay visible in the jaxpr — initially bunched after the
    backward, which is what :func:`interleave_collectives` then fixes.
    """
    from horovod_tpu.parallel.zero import build_zero_apply_inner

    p_leaves = treedef.flatten_up_to(params)
    b_leaves, btree = jax.tree.flatten(batch)
    opt_leaves, opt_tree = jax.tree.flatten(opt)
    n_p, n_b = len(p_leaves), len(b_leaves)
    apply_inner = build_zero_apply_inner(hyper, layout, axis, size,
                                         inter_axis=inter_axis,
                                         inter_size=inter_size)

    def scaled_loss(p, d):
        return (loss_fn(p, d) / loss_scale if loss_scale != 1.0
                else loss_fn(p, d))

    def inner(*flat):
        pos = 0
        p = jax.tree.unflatten(treedef, flat[pos:pos + n_p])
        pos += n_p
        if accumulate:
            loss_acc = flat[pos]
            acc = jax.tree.unflatten(treedef, flat[pos + 1:pos + 1 + n_p])
            pos += 1 + n_p
        d = jax.tree.unflatten(btree, flat[pos:pos + n_b])
        pos += n_b
        opt_state = jax.tree.unflatten(opt_tree, flat[pos:])
        loss, grads = jax.value_and_grad(scaled_loss)(p, d)
        if accumulate:
            loss = loss_acc + loss
            grads = jax.tree.map(jnp.add, acc, grads)
        g_flat = layout.pack(treedef.flatten_up_to(grads))
        p_flat = layout.pack(treedef.flatten_up_to(p))
        new_flat, new_opt = apply_inner(tuple(g_flat), tuple(p_flat),
                                        opt_state)
        new_leaves = layout.unpack(list(new_flat))
        return (loss, *new_leaves, *jax.tree.leaves(new_opt))

    example = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in p_leaves]
    if accumulate:
        example.append(jax.ShapeDtypeStruct((), jnp.float32))
        example.extend(jax.ShapeDtypeStruct(l.shape, l.dtype)
                       for l in p_leaves)
    example.extend(jax.ShapeDtypeStruct(l.shape, l.dtype)
                   for l in b_leaves)
    # The inner is a PER-MEMBER program (``_zero_spmd`` splits the opt
    # leaves over the axis before mapping): trace it with the 1/size
    # member shapes, exactly what each rank holds.
    example.extend(
        jax.ShapeDtypeStruct((l.shape[0] // size,) + tuple(l.shape[1:]),
                             l.dtype) for l in opt_leaves)
    # Donate params + opt leaves 1:1 into the new params/opt outputs;
    # batch (and the accumulator — the r6 lesson: grads never find an
    # output to alias once params claim theirs) stay un-donated.
    donate = tuple(range(n_p)) + tuple(
        range(len(example) - len(opt_leaves), len(example)))
    env = [(axis, size)]
    if inter_axis is not None:
        env.append((inter_axis, int(inter_size)))
    return inner, tuple(example), donate, env


def make_fused_zero_programs(loss_fn, optimizer, zero, *,
                             microbatches=1, jit_kwargs=None):
    """The jit-lane fused step programs for ``make_split_train_step``.

    Returns ``(init, call, call_final)``:

    - ``init(params) -> (params, opt)`` — identical carry to the
      unfused :func:`~horovod_tpu.parallel.zero.make_zero_apply` (the
      fusion knob can flip mid-run without converting state);
    - ``call(params, batch, opt) -> (loss, params, opt)`` — the fused
      single-microbatch step (grad + ZeRO apply, ONE program);
    - ``call_final(params, loss_acc, acc, batch, opt)`` — the fused
      LAST microbatch of an accumulation loop: earlier microbatches
      still run the plain grad programs (their collectives don't exist
      yet), only the step that owns the collective phase fuses.

    Each program is traced flat, rescheduled by
    :func:`interleave_collectives`, and run through ``_zero_spmd`` —
    ``jax.shard_map`` on real meshes, the vmap(axis_name) emulation on
    the jax-0.4.x CPU substrate — with params/opt donated.
    """
    from horovod_tpu.parallel.zero import (
        _optimizer_hyper,
        _zero_spmd,
        zero_bucket_layout,
        zero_state_init,
    )

    hyper = _optimizer_hyper(optimizer)
    size = zero.resolved_size()
    jk = dict(jit_kwargs or {})
    n = int(microbatches)
    cache = {}

    def _programs(params, batch, opt, accumulate):
        p_leaves, treedef = jax.tree.flatten(params)
        key = (treedef, jax.tree.structure(batch), accumulate,
               tuple(tuple(l.shape) for l in jax.tree.leaves(batch)))
        if key in cache:
            return cache[key]
        layout = zero_bucket_layout(p_leaves, size, zero.bucket_bytes)
        inner, example, donate, env = fused_zero_inner(
            loss_fn, params, batch, opt, hyper, layout, treedef,
            zero.axis, size, inter_axis=zero.inter_axis,
            inter_size=zero.inter_size, accumulate=accumulate,
            loss_scale=float(n) if accumulate else 1.0)
        closed = jax.make_jaxpr(inner, axis_env=env)(*example)
        if jit_fusion_enabled():
            closed = interleave_collectives(closed)
        flat_fn = _jcore.jaxpr_as_fun(closed)
        n_p = len(p_leaves)
        n_opt = len(jax.tree.leaves(opt))
        split_in = tuple(i >= len(example) - n_opt
                         for i in range(len(example)))
        split_out = (False,) + (False,) * n_p + (True,) * n_opt
        spmd = _zero_spmd(lambda *a: tuple(flat_fn(*a)), zero.axis,
                          size, zero.mesh, split_in=split_in,
                          split_out=split_out,
                          inter_axis=zero.inter_axis,
                          inter_size=zero.inter_size)
        opt_tree = jax.tree.structure(opt)

        if accumulate:
            @functools.partial(jax.jit, donate_argnums=(0, 4), **jk)
            def call(params, loss_acc, acc, batch, opt):
                flat = (*treedef.flatten_up_to(params), loss_acc,
                        *treedef.flatten_up_to(acc),
                        *jax.tree.leaves(batch), *jax.tree.leaves(opt))
                outs = spmd(*flat)
                return (outs[0],
                        jax.tree.unflatten(treedef, outs[1:1 + n_p]),
                        jax.tree.unflatten(opt_tree, outs[1 + n_p:]))
        else:
            @functools.partial(jax.jit, donate_argnums=(0, 2), **jk)
            def call(params, batch, opt):
                flat = (*treedef.flatten_up_to(params),
                        *jax.tree.leaves(batch), *jax.tree.leaves(opt))
                outs = spmd(*flat)
                return (outs[0],
                        jax.tree.unflatten(treedef, outs[1:1 + n_p]),
                        jax.tree.unflatten(opt_tree, outs[1 + n_p:]))

        cache[key] = call
        return call

    def init(params):
        leaves, _ = jax.tree.flatten(params)
        layout = zero_bucket_layout(leaves, size, zero.bucket_bytes)
        return zero_state_init(hyper, layout, params, size)

    def call(params, batch, opt):
        return _programs(params, batch, opt, False)(params, batch, opt)

    def call_final(params, loss_acc, acc, batch, opt):
        return _programs(params, batch, opt, True)(
            params, loss_acc, acc, batch, opt)

    return FusedZeroPrograms(init=init, call=call,
                             call_final=call_final, get=_programs)
