"""Device-mesh construction helpers.

The scaling-book recipe: pick a mesh whose inner (fastest-varying) axes
carry the highest-bandwidth traffic, annotate shardings, let XLA insert
collectives. On real TPU hardware ``jax.experimental.mesh_utils`` lays the
mesh out along ICI tori; on CPU (tests, the driver's dryrun) any reshape of
``jax.devices()`` works.
"""

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh


# Canonical axis order, outermost (DCN-friendly, low traffic) to innermost
# (ICI-hungry). data/pipe cross slices cheaply; tensor wants the fastest
# links; seq sits between.
AXIS_ORDER = ("pipe", "data", "fsdp", "expert", "seq", "tensor")


@dataclasses.dataclass
class MeshConfig:
    """Sizes for each parallelism axis; -1 on one axis = use all remaining
    devices (like a numpy reshape -1)."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1

    def resolved(self, n_devices):
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} does not cover {n_devices} devices")
        return sizes


def create_mesh(config=None, devices=None, **axis_sizes):
    """Build a Mesh over `devices` (default: all) with named axes.

    ``create_mesh(data=2, tensor=4)`` or ``create_mesh(MeshConfig(...))``.
    Axes of size 1 are kept in the mesh so sharding rules can always name
    them (XLA drops trivial axes at compile time; no cost).
    """
    if config is None:
        config = MeshConfig(**{**{"data": -1}, **axis_sizes}) \
            if axis_sizes else MeshConfig()
    devices = list(jax.devices()) if devices is None else list(devices)
    sizes = config.resolved(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    if _on_tpu(devices):
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def _on_tpu(devices):
    return devices and devices[0].platform == "tpu"


def local_mesh(**axis_sizes):
    """Mesh over this process's addressable devices only."""
    return create_mesh(devices=jax.local_devices(), **axis_sizes)
