"""Split-program training step: grad and optimizer-apply as two jits.

Why two programs instead of one fused train-step jit (measured, r5/r6):

1. the split layout is FASTER at flagship shape — the fused program's
   interleaved adam update schedules worse (573 -> 552 ms/step, r5);
2. it is the formulation that dodges this environment's AOT-compile-
   helper crash on the MoE config: ``remat="moe"`` + microbatch
   gradient accumulation compiled as ONE monolithic jit crashes the
   helper (HTTP 500 — see benchmarks/aot_crash_repro.py), while the
   same math as a small grad program called N times plus a trivial
   apply program compiles each piece separately and never hands the
   helper the monolith;
3. N-way microbatch gradient accumulation falls out naturally: the
   grad program runs once per microbatch into a donated accumulator,
   so per-microbatch activation memory is 1/N of the full batch — the
   enabler for expensive remat save-sets (``remat="moe"``) at bench
   sizes.

The two programs are connected by DONATED gradient buffers: the first
microbatch's gradient outputs become the accumulator and each
accumulation step donates it forward, so exactly one params-sized
gradient tree is live per step. The apply program donates only params
and optimizer state — its outputs are exactly one params tree plus one
state tree, which those donate into 1:1, so a donated gradient tree
could never alias an output and only produced XLA's "donated buffers
were not usable" warning (see apply_fn below).

Semantics: the per-microbatch loss is scaled by 1/N inside the grad
program, so the accumulated gradients equal the full-batch mean-loss
gradients and the accumulated loss equals the full-batch mean loss —
bit-for-bit-ish equivalence with the monolithic jit is pinned by
``tests/single/test_llama.py`` and the driver's ``dryrun_multichip``
split-step pass. That identity requires the loss to be a per-example
MEAN (linear in the batch axis). Batch-NONLINEAR terms become the
mean of per-microbatch values instead of the full-batch value:

- the MoE Switch aux loss (batch routing statistics) — the same
  semantics the pipeline microbatch path already has (see
  ``test_pipeline_with_moe``);
- a MASKED mean ``sum(nll*mask)/sum(mask)`` whose token counts differ
  across microbatches: each microbatch's masked mean gets weight 1/N
  regardless of how many real tokens it holds. For exact equivalence
  on padded batches, fold a GLOBAL denominator into ``loss_fn``
  (compute ``sum(mask)`` over the full batch outside the step and
  have ``loss_fn`` return ``sum(nll*mask)/global_denom``) — exactly
  what the 1F1B schedule does with its loss numerator
  (``models/llama.py``, "mask denominator is global across
  microbatches").

Reference analog: ``backward_passes_per_step`` local gradient
aggregation (``horovod/tensorflow/gradient_aggregation.py``), re-founded
as a program-structure choice instead of an optimizer wrapper.
"""

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class TrainStep(NamedTuple):
    init: Any   # init(params) -> carry
    step: Any   # step(carry, batch) -> (loss, carry)


def _split_microbatches(batch, n):
    """Split every leaf of ``batch`` into ``n`` equal chunks along the
    leading (batch) axis. Runs OUTSIDE jit — each chunk is then a
    separate call to the grad program."""
    leaves = jax.tree.leaves(batch)
    if not leaves:
        raise ValueError("empty batch")
    b = leaves[0].shape[0]
    if b % n:
        raise ValueError(f"batch size {b} must divide into "
                         f"{n} microbatches")
    mb = b // n
    return [jax.tree.map(lambda x: x[i * mb:(i + 1) * mb], batch)
            for i in range(n)]


def _register_split_flops(timer, programs):
    """Fill ``timer.flops_per_step`` from compiled cost analysis:
    ``programs`` is ``[(jitted_fn, abstract_args, calls_per_step)]``.
    Uses the AOT lower/compile path with the SAME abstract signatures
    the step dispatches, so the executables land in (or come from) the
    jit cache that first step populates."""
    for fn, args, calls in programs:
        compiled = fn.lower(*args).compile()
        timer.add_flops_from_compiled(compiled, calls=calls)


def _wrap_step_telemetry(inner_step, telemetry, flops_programs):
    """The StepTimer wrapper both step layouts share: first call
    registers per-step FLOPs from compiled cost analysis (best-effort),
    every call brackets the step with ``start_step``/``end_step``. Lives
    entirely OUTSIDE the jitted programs — traced jaxprs are identical
    with and without it."""
    flops_pending = [telemetry.flops_per_step is None]

    def step(carry, batch):
        if flops_pending[0]:
            flops_pending[0] = False
            try:
                _register_split_flops(telemetry,
                                      flops_programs(carry, batch))
            except Exception:  # noqa: BLE001 — cost analysis is
                pass           # best-effort (backend-dependent)
        telemetry.start_step()
        out = inner_step(carry, batch)
        telemetry.end_step(out)
        return out

    return step


def _make_fused_zero_train_step(loss_fn, optimizer, zero, *, n, jk,
                                telemetry):
    """The fused (one-program) ZeRO-1 step layout (docs/fusion.md).

    ``n == 1``: the whole step — value_and_grad + bucket pack + the
    per-bucket RS/adam/AG pipeline — is one jit whose collective chains
    :func:`~horovod_tpu.parallel.fusion.interleave_collectives`
    rescheduled under the backward. ``n > 1``: microbatches ``0..n-2``
    run the plain grad/accumulate programs (no collectives to fuse);
    the LAST microbatch, which owns the collective phase, runs fused
    with the accumulator folded in. The carry is identical to the
    unfused zero layout (``zero_state_init``), so the
    ``HOROVOD_JIT_FUSION`` knob flips without state conversion.
    """
    from horovod_tpu.parallel.fusion import make_fused_zero_programs

    progs = make_fused_zero_programs(loss_fn, optimizer, zero,
                                     microbatches=n, jit_kwargs=jk)

    if n == 1:
        def step(carry, batch):
            params, opt = carry
            loss, params, opt = progs.call(params, batch, opt)
            return loss, (params, opt)
    else:
        def scaled_loss(p, d):
            return loss_fn(p, d) / n

        grad_first = jax.jit(
            lambda p, d: jax.value_and_grad(scaled_loss)(p, d), **jk)

        @functools.partial(jax.jit, donate_argnums=(1, 2), **jk)
        def grad_acc(params, loss_acc, acc, d):
            loss, g = jax.value_and_grad(scaled_loss)(params, d)
            return loss_acc + loss, jax.tree.map(jnp.add, acc, g)

        def step(carry, batch):
            params, opt = carry
            mbs = _split_microbatches(batch, n)
            loss, grads = grad_first(params, mbs[0])
            for mb in mbs[1:-1]:
                loss, grads = grad_acc(params, loss, grads, mb)
            loss, params, opt = progs.call_final(params, loss, grads,
                                                 mbs[-1], opt)
            return loss, (params, opt)

    if telemetry is not None:
        def _flops_programs(carry, batch):
            params, opt = carry
            if n == 1:
                fused = progs.get(params, batch, opt, False)
                return [(fused, (params, batch, opt), 1)]
            mbs = _split_microbatches(batch, n)
            l_abs, g_abs = jax.eval_shape(grad_first, params, mbs[0])
            fused = progs.get(params, mbs[-1], opt, True)
            return [(grad_first, (params, mbs[0]), 1),
                    (grad_acc, (params, l_abs, g_abs, mbs[0]), n - 2),
                    (fused, (params, l_abs, g_abs, mbs[-1], opt), 1)]

        step = _wrap_step_telemetry(step, telemetry, _flops_programs)

    return TrainStep(init=progs.init, step=step)


def make_split_train_step(loss_fn, optimizer, *, microbatches=1,
                          jit_kwargs=None, telemetry=None, zero=None):
    """Build the split-program step for ``loss_fn(params, batch)``.

    ``optimizer`` is either an optax ``GradientTransformation``
    (``init``/``update`` — the SPLIT apply: updates tree +
    ``optax.apply_updates``) or a ``FusedOptimizer`` /
    ``FusedMasterOptimizer`` from ``parallel.precision``
    (``init``/``apply`` — the single-pass FUSED apply). For the master
    variant the carry's params are the COMPUTE-dtype cast (built by
    ``init``); the fp32 master lives inside the optimizer state.

    ``zero`` (optional) is a :class:`horovod_tpu.parallel.zero.
    ZeroConfig`: the apply program is then the ZeRO-1 sharded form —
    gradient buckets reduce-scattered over ``zero.axis`` so each rank
    updates 1/N of the (fused adam / fused master-adam) optimizer
    state, updated parameter shards allgathered back — cutting
    per-rank optimizer memory N-fold at the same step semantics
    (docs/zero.md; parity pinned by tests/single/test_zero.py). The
    grad/accumulation programs are unchanged: ZeRO-1 restructures only
    the optimizer phase.

    ``telemetry`` (optional) is a
    :class:`horovod_tpu.telemetry.StepTimer`: every ``step`` call is
    then timed into it, and — unless the timer already carries
    ``flops_per_step`` — the first call registers per-step FLOPs from
    ``lowered.compile().cost_analysis()`` over the grad program(s)
    x microbatches plus the apply program, so ``timer.mfu()`` works
    with zero extra bookkeeping. The wrapper lives entirely OUTSIDE
    the jitted programs: traced jaxprs (and therefore hvdlint results
    — see ``analysis/programs.py``'s instrumented registration) are
    identical with and without it.

    Returns ``TrainStep(init, step)`` with
    ``init(params) -> carry`` and ``step(carry, batch) -> (loss,
    carry)``; ``jit_kwargs`` (e.g. TPU compiler options) apply to every
    program.
    """
    jk = dict(jit_kwargs or {})
    n = int(microbatches)
    if n < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    fused = hasattr(optimizer, "apply")

    # Grads are NOT donated: the apply program's outputs are exactly
    # one params tree + one optimizer-state tree, and params/opt donate
    # into them 1:1; a donated grads tree can never find an output to
    # alias and only triggers XLA's "Some donated buffers were not
    # usable" warning on every leaf (observed on the fp32-master path,
    # BENCH r5 tail — the r6 fix, pinned by
    # tests/single/test_llama.py::test_apply_jit_emits_no_donation_warning).
    # The buffers are dead the moment apply returns either way.
    zero_init = None
    if zero is not None:
        from horovod_tpu.parallel import fusion as _fusion

        if _fusion.jit_fusion_enabled():
            # Jit-lane compute/collective fusion (docs/fusion.md): the
            # grad program that owns the collective phase and the ZeRO
            # apply become ONE program whose per-bucket reduce-scatter
            # -> shard-adam -> all-gather chains are rescheduled to
            # interleave with the remaining backward compute
            # (hvdlint C7 verifies the ordering statically). Same math,
            # same carry, different schedule — HOROVOD_JIT_FUSION=0
            # restores the unfused two-program layout below.
            return _make_fused_zero_train_step(
                loss_fn, optimizer, zero, n=n, jk=jk,
                telemetry=telemetry)
        from horovod_tpu.parallel.zero import make_zero_apply

        apply_fn, zero_init = make_zero_apply(optimizer, zero,
                                              jit_kwargs=jk)
    elif fused:
        @functools.partial(jax.jit, donate_argnums=(1, 2), **jk)
        def apply_fn(grads, params, opt):
            return optimizer.apply(params, grads, opt)
    else:
        @functools.partial(jax.jit, donate_argnums=(1, 2), **jk)
        def apply_fn(grads, params, opt):
            import optax  # deferred: parallel/ imports without optax

            updates, opt = optimizer.update(grads, opt, params)
            return optax.apply_updates(params, updates), opt

    if n == 1:
        grad_fn = jax.jit(
            lambda p, d: jax.value_and_grad(loss_fn)(p, d), **jk)

        def step(carry, batch):
            params, opt = carry
            loss, grads = grad_fn(params, batch)
            params, opt = apply_fn(grads, params, opt)
            return loss, (params, opt)
    else:
        def scaled_loss(p, d):
            # 1/N inside the grad program: accumulated grads == the
            # full-batch mean-loss grads, accumulated loss == the
            # full-batch mean loss — no extra scaling pass anywhere.
            return loss_fn(p, d) / n

        # TWO grad programs on purpose: the first microbatch runs an
        # accumulator-free jit and its outputs BECOME the accumulator.
        # Folding both into one program by seeding grad_acc with a
        # zeros tree (halving the dominant fwd+bwd compile) was tried
        # in r7 and MISCOMPILES: with a zeros accumulator whose
        # sharding is the params', GSPMD picks a different partitioning
        # for the embedding-gradient scatter-add inside pipeline-
        # schedule programs and produces wrong embed grads on the CPU
        # substrate (loss right, one leaf off by O(grad) — caught by
        # test_interleaved_composes_with_split_train_step). Keep the
        # two-program layout unless that equivalence test passes with
        # the fold on every substrate.
        grad_first = jax.jit(
            lambda p, d: jax.value_and_grad(scaled_loss)(p, d), **jk)

        @functools.partial(jax.jit, donate_argnums=(1, 2), **jk)
        def grad_acc(params, loss_acc, acc, d):
            loss, g = jax.value_and_grad(scaled_loss)(params, d)
            return loss_acc + loss, jax.tree.map(jnp.add, acc, g)

        def step(carry, batch):
            params, opt = carry
            mbs = _split_microbatches(batch, n)
            loss, grads = grad_first(params, mbs[0])
            for mb in mbs[1:]:
                loss, grads = grad_acc(params, loss, grads, mb)
            params, opt = apply_fn(grads, params, opt)
            return loss, (params, opt)

    if telemetry is not None:
        def _flops_programs(carry, batch):
            params, opt = carry
            if n == 1:
                g_abs = jax.eval_shape(grad_fn, params, batch)
                return [(grad_fn, (params, batch), 1),
                        (apply_fn, (g_abs[1], params, opt), 1)]
            mb0 = _split_microbatches(batch, n)[0]
            l_abs, g_abs = jax.eval_shape(grad_first, params, mb0)
            return [(grad_first, (params, mb0), 1),
                    (grad_acc, (params, l_abs, g_abs, mb0), n - 1),
                    (apply_fn, (g_abs, params, opt), 1)]

        step = _wrap_step_telemetry(step, telemetry, _flops_programs)

    def init(params):
        if zero_init is not None:
            # ZeRO-1 carry: replicated params (compute cast for the
            # master variant), optimizer state sharded over zero.axis.
            return zero_init(params)
        opt = optimizer.init(params)
        if hasattr(optimizer, "compute_params"):
            # Master-weights variant: the carry holds the compute cast;
            # the fp32 master (inside ``opt``) owns the precision.
            params = optimizer.compute_params(opt)
        return (params, opt)

    return TrainStep(init=init, step=step)
