"""GPipe-style pipeline parallelism over the mesh's "pipe" axis.

Reference analog: none — Horovod is data-parallel only (SURVEY.md §5.7/
§2.6); this is net-new TPU machinery like ring attention. Design: the
layer stack is split into S contiguous stages (the stacked layer axis
shards over "pipe", so each device holds its stage's weights); inside a
*partial-manual* ``shard_map`` (manual over "pipe" only — tensor/fsdp/
data stay with GSPMD), a ``lax.scan`` runs the classic GPipe schedule:
each step every stage processes one microbatch and ``ppermute`` rotates
activations to the next stage. M microbatches drain in M + S - 1 steps
(the bubble); results collect on the last stage and are shared back with
a masked ``psum``.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def gpipe(stage_fn, stage_params, xs, mesh, axis="pipe"):
    """Run microbatches through the pipeline.

    ``stage_fn(stage_params_block, x_mb) -> (y_mb, aux)`` applies ONE
    stage's slice of the network (aux is a scalar, e.g. an MoE balance
    loss; return 0.0 if unused). ``stage_params`` is a pytree whose
    leaves have a leading stacked-layer axis of length divisible by the
    pipe size — ``shard_map`` splits it into per-stage blocks.
    ``xs`` is ``[M, ...]`` microbatches. Returns ``(ys [M, ...],
    aux_sum)`` where aux_sum totals stage_fn aux over all (stage,
    microbatch) pairs.
    """
    S = mesh.shape[axis]
    M = xs.shape[0]

    # XLA CPU's AllReducePromotion pass crashes on the bf16 allreduces
    # this program generates (the collection psum and AD's cotangent
    # psum for the replicated xs input). CPU is the test substrate, so
    # run the pipeline in f32 there; TPU keeps native bf16.
    on_cpu = all(d.platform == "cpu" for d in mesh.devices.flat)
    cast_dt = None
    if on_cpu and xs.dtype in (jnp.bfloat16, jnp.float16):
        cast_dt = xs.dtype
        xs = xs.astype(jnp.float32)

    def inner(sp, xs_):
        stage = lax.axis_index(axis)

        def step(state, t):
            carry, buf, aux = state
            inj = lax.dynamic_index_in_dim(xs_, jnp.clip(t, 0, M - 1), 0,
                                           keepdims=False)
            inp = jnp.where(stage == 0, inj, carry)
            out, a = stage_fn(sp, inp)
            # Bubble steps (stage s idle before t=s and after t=s+M-1)
            # compute on garbage; mask their aux and never collect them.
            valid = (t - stage >= 0) & (t - stage < M)
            aux = aux + jnp.where(valid, a, 0.0)
            cidx = jnp.clip(t - (S - 1), 0, M - 1)
            collect = (stage == S - 1) & (t >= S - 1)
            cur = lax.dynamic_index_in_dim(buf, cidx, 0, keepdims=False)
            buf = lax.dynamic_update_index_in_dim(
                buf, jnp.where(collect, out, cur), cidx, 0)
            carry = lax.ppermute(out, axis,
                                 [(i, (i + 1) % S) for i in range(S)])
            return (carry, buf, aux), None

        init = (jnp.zeros_like(xs_[0]), jnp.zeros_like(xs_),
                jnp.zeros((), jnp.float32))
        (carry, buf, aux), _ = lax.scan(step, init, jnp.arange(M + S - 1))
        # Results live on the last stage; the loss is computed globally,
        # so share them (and the aux total) across the pipe axis. The
        # psum runs in f32 for sub-f32 activations: XLA CPU's
        # AllReducePromotion pass crashes on bf16 allreduce inside
        # manual shard_map, and on TPU the f32 cast is fused anyway.
        out_dt = buf.dtype
        masked = jnp.where(stage == S - 1, buf, jnp.zeros_like(buf))
        if out_dt in (jnp.bfloat16, jnp.float16):
            buf = lax.psum(masked.astype(jnp.float32), axis).astype(out_dt)
        else:
            buf = lax.psum(masked, axis)
        aux = lax.psum(aux, axis)
        return buf, aux

    ys, aux = jax.shard_map(inner, mesh=mesh, in_specs=(P(axis), P()),
                            out_specs=(P(), P()), axis_names={axis},
                            check_vma=False)(stage_params, xs)
    if cast_dt is not None:
        ys = ys.astype(cast_dt)
    return ys, aux
