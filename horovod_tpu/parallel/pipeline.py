"""GPipe-style pipeline parallelism over the mesh's "pipe" axis.

Reference analog: none — Horovod is data-parallel only (SURVEY.md §5.7/
§2.6); this is net-new TPU machinery like ring attention. Design: the
layer stack is split into S contiguous stages (the stacked layer axis
shards over "pipe", so each device holds its stage's weights); inside a
*partial-manual* ``shard_map`` (manual over "pipe" only — tensor/fsdp/
data stay with GSPMD), a ``lax.scan`` runs the classic GPipe schedule:
each step every stage processes one microbatch and ``ppermute`` rotates
activations to the next stage. M microbatches drain in M + S - 1 steps
(the bubble); results collect on the last stage and are shared back with
a masked ``psum``.
"""

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _pipe_spmd(inner, mesh, axis, split_in, split_out):
    """Run ``inner`` manual over the pipe axis.

    With ``jax.shard_map`` (jax >= 0.6 — the accelerator/driver
    substrate) this is the partial-manual shard_map the docstring above
    describes. Older jax (0.4.x dev boxes) lacks it and its
    ``jax.experimental`` ancestor miscompiles partial-auto meshes on
    CPU ("PartitionId instruction is not supported"), so there the
    schedules run under ``jax.vmap(..., axis_name=axis)`` instead:
    axis-split arguments are reshaped ``[S*k, ...] -> [S, k, ...]`` and
    mapped, which gives identical collective semantics (psum /
    ppermute / axis_index resolve against the vmapped axis) — the whole
    pipeline stack stays testable on such boxes, with GSPMD free to
    lay out the emulated program however it likes.

    ``split_in`` / ``split_out`` are per-argument booleans: True means
    the leading dim splits over ``axis`` (shard_map spec ``P(axis)``),
    False means replicated (``P()``).
    """
    S = mesh.shape[axis]
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=tuple(P(axis) if s else P() for s in split_in),
            out_specs=tuple(P(axis) if s else P() for s in split_out),
            axis_names={axis}, check_vma=False)

    def emulated(*args):
        split = lambda a: jax.tree.map(  # noqa: E731
            lambda x: x.reshape((S, x.shape[0] // S) + x.shape[1:]), a)
        args = tuple(split(a) if s else a
                     for a, s in zip(args, split_in))
        outs = jax.vmap(inner,
                        in_axes=tuple(0 if s else None
                                      for s in split_in),
                        out_axes=0, axis_name=axis)(*args)
        merge = lambda o: jax.tree.map(  # noqa: E731
            lambda x: x.reshape((x.shape[0] * x.shape[1],)
                                + x.shape[2:]), o)
        first = lambda o: jax.tree.map(lambda x: x[0], o)  # noqa: E731
        return tuple(merge(o) if s else first(o)
                     for o, s in zip(outs, split_out))

    return emulated


def _cast_f32_on_cpu(mesh, xs):
    """XLA CPU's AllReducePromotion pass crashes on the bf16 allreduces
    the pipeline schedules generate (collection/cotangent psums inside
    manual collectives). CPU is the test substrate, so run the schedule
    in f32 there — TPU keeps native bf16. Returns ``(xs, dtype to cast
    schedule outputs back to, or None)``; shared by gpipe /
    one_f_one_b / interleaved_one_f_one_b so the workaround cannot
    drift between schedules."""
    on_cpu = all(d.platform == "cpu" for d in mesh.devices.flat)
    if on_cpu and xs.dtype in (jnp.bfloat16, jnp.float16):
        return xs.astype(jnp.float32), xs.dtype
    return xs, None


def _gpipe_inner(stage_fn, S, M, axis):
    """The per-device GPipe program (manual over ``axis``): a
    ``[M + S - 1]``-step scan with one activation ``ppermute`` per step,
    then the result/aux ``psum`` pair. Factored out of :func:`gpipe` so
    hvdlint can trace it standalone (``jax.make_jaxpr`` with
    ``axis_env=[(axis, S)]``) and check it against
    :func:`predicted_collectives` — see ``horovod_tpu/analysis/``."""

    def inner(sp, xs_):
        stage = lax.axis_index(axis)

        def step(state, t):
            carry, buf, aux = state
            inj = lax.dynamic_index_in_dim(xs_, jnp.clip(t, 0, M - 1), 0,
                                           keepdims=False)
            inp = jnp.where(stage == 0, inj, carry)
            out, a = stage_fn(sp, inp)
            # Bubble steps (stage s idle before t=s and after t=s+M-1)
            # compute on garbage; mask their aux and never collect them.
            valid = (t - stage >= 0) & (t - stage < M)
            aux = aux + jnp.where(valid, a, 0.0)
            cidx = jnp.clip(t - (S - 1), 0, M - 1)
            collect = (stage == S - 1) & (t >= S - 1)
            cur = lax.dynamic_index_in_dim(buf, cidx, 0, keepdims=False)
            buf = lax.dynamic_update_index_in_dim(
                buf, jnp.where(collect, out, cur), cidx, 0)
            carry = lax.ppermute(out, axis,
                                 [(i, (i + 1) % S) for i in range(S)])
            return (carry, buf, aux), None

        init = (jnp.zeros_like(xs_[0]), jnp.zeros_like(xs_),
                jnp.zeros((), jnp.float32))
        (carry, buf, aux), _ = lax.scan(step, init, jnp.arange(M + S - 1))
        # Results live on the last stage; the loss is computed globally,
        # so share them (and the aux total) across the pipe axis. The
        # psum runs in f32 for sub-f32 activations: XLA CPU's
        # AllReducePromotion pass crashes on bf16 allreduce inside
        # manual shard_map, and on TPU the f32 cast is fused anyway.
        out_dt = buf.dtype
        masked = jnp.where(stage == S - 1, buf, jnp.zeros_like(buf))
        if out_dt in (jnp.bfloat16, jnp.float16):
            buf = lax.psum(masked.astype(jnp.float32), axis).astype(out_dt)
        else:
            buf = lax.psum(masked, axis)
        aux = lax.psum(aux, axis)
        return buf, aux

    return inner


def gpipe(stage_fn, stage_params, xs, mesh, axis="pipe"):
    """Run microbatches through the pipeline.

    ``stage_fn(stage_params_block, x_mb) -> (y_mb, aux)`` applies ONE
    stage's slice of the network (aux is a scalar, e.g. an MoE balance
    loss; return 0.0 if unused). ``stage_params`` is a pytree whose
    leaves have a leading stacked-layer axis of length divisible by the
    pipe size — ``shard_map`` splits it into per-stage blocks.
    ``xs`` is ``[M, ...]`` microbatches. Returns ``(ys [M, ...],
    aux_sum)`` where aux_sum totals stage_fn aux over all (stage,
    microbatch) pairs.
    """
    S = mesh.shape[axis]
    M = xs.shape[0]
    xs, cast_dt = _cast_f32_on_cpu(mesh, xs)

    inner = _gpipe_inner(stage_fn, S, M, axis)
    ys, aux = _pipe_spmd(inner, mesh, axis, (True, False),
                         (False, False))(stage_params, xs)
    if cast_dt is not None:
        ys = ys.astype(cast_dt)
    return ys, aux


def one_f_one_b(stage_fn, loss_fn, stage_params, head_params, xs,
                loss_args, mesh, axis="pipe", aux_cotangent=0.0):
    """1F1B pipeline schedule: forward AND backward interleaved in one
    lockstep scan, with the loss computed on the last stage per
    microbatch.

    Why not let AD differentiate :func:`gpipe`? Its backward replays
    the forward scan in reverse, so every stage stashes activations for
    ALL M microbatches — O(M) memory. Here each slot runs one forward
    subtick and one backward subtick per stage: stage ``s`` forwards
    microbatch ``m`` at slot ``s + m``, the last stage turns it
    straight into a loss cotangent, and the backward walks back up at
    slot ``2(S-1) - s + m``. A stage therefore holds at most
    ``min(M, 2(S-1-s) + 1) <= 2S - 1`` stashed INPUTS (activations are
    recomputed from the stashed input during the backward subtick —
    per-stage remat, the standard 1F1B trade). Timeline = ``M + 2(S-1)``
    slots; the ``2(S-1)/(M + 2(S-1))`` bubble fraction matches GPipe's
    forward+backward total, so the win is memory, not bubble.

    ``stage_fn(sp_block, x_mb) -> (y_mb, aux_scalar)`` as in gpipe.
    ``loss_fn(head_params, y_mb, loss_args_mb) -> scalar`` is the last
    stage's per-microbatch objective NUMERATOR (any global
    normalization — e.g. a mask-token count — must be folded in by the
    caller, since microbatches cannot see each other's denominators).
    ``loss_args`` is a pytree with leading microbatch axis M (targets,
    masks, ...). ``aux_cotangent`` is the constant d(objective)/d(aux)
    applied to every valid (stage, microbatch) aux contribution — e.g.
    ``moe_aux_weight / (n_layers * M)``.

    Returns ``(loss_sum, aux_sum, d_stage_params, d_head_params,
    d_xs)`` — the gradient of ``loss_sum + aux_cotangent * aux_raw_sum``
    with respect to (stage_params, head_params, xs). Callers wanting
    plain ``value_and_grad`` ergonomics should wrap this in a
    ``custom_vjp`` (see models/llama.py's 1f1b path).

    Reference analog: none (net-new, like gpipe); the schedule is the
    public non-interleaved 1F1B (PipeDream-flush) formulation.
    """
    S = mesh.shape[axis]
    M = xs.shape[0]
    xs, cast_dt = _cast_f32_on_cpu(mesh, xs)

    inner = _one_f_one_b_inner(stage_fn, loss_fn, S, M, axis,
                               aux_cotangent)
    d_sp, d_hp, d_xs, loss, aux = _pipe_spmd(
        inner, mesh, axis, (True, False, False, False),
        (True, False, False, False, False))(
            stage_params, head_params, xs, loss_args)
    if cast_dt is not None:
        d_xs = d_xs.astype(cast_dt)
    return loss, aux, d_sp, d_hp, d_xs


def _one_f_one_b_inner(stage_fn, loss_fn, S, M, axis, aux_cotangent):
    """The per-device lockstep-1F1B program (manual over ``axis``): a
    ``[M + 2(S-1)]``-slot scan with one forward and one backward
    activation ``ppermute`` per slot, then the shared-gradient ``psum``
    tail (head-param leaves, d_xs, loss, aux — stage params stay
    local). Factored out of :func:`one_f_one_b` so hvdlint can trace it
    standalone against :func:`predicted_collectives`."""
    Q = min(M, 2 * S - 1)                       # stash depth per stage
    U = M + 2 * (S - 1)                         # total slots

    def inner(sp, hp, xs_, largs_):
        stage = lax.axis_index(axis)
        is_last = stage == S - 1

        def slot(state, u):
            (fwd_carry, bwd_carry, stash, d_sp, d_hp, d_xs, loss,
             aux) = state

            # ---- forward subtick ----
            m_f = u - stage
            f_valid = (m_f >= 0) & (m_f < M)
            mf_c = jnp.clip(m_f, 0, M - 1)
            inj = lax.dynamic_index_in_dim(xs_, mf_c, 0, keepdims=False)
            x_in = jnp.where(stage == 0, inj, fwd_carry)
            out, a = stage_fn(sp, x_in)
            aux = aux + jnp.where(f_valid, a, 0.0)

            # Last stage: microbatch loss + its cotangents wrt the
            # stage output AND the head params, all from ONE
            # linearization of the loss head (it contains the
            # [mb,T,D]@[D,vocab] logits matmul — the model's largest —
            # so a second grad call would double the head work every
            # slot). Both are consumed by THIS slot's backward subtick
            # (the last stage's backward slot equals its forward slot).
            la = jax.tree.map(
                lambda t: lax.dynamic_index_in_dim(t, mf_c, 0,
                                                   keepdims=False),
                largs_)
            lval, (g_last, d_hp_m) = jax.value_and_grad(
                lambda o, h: loss_fn(h, o, la), argnums=(0, 1))(out, hp)
            lvalid = is_last & f_valid
            loss = loss + jnp.where(lvalid, lval, 0.0)
            d_hp = jax.tree.map(
                lambda acc, gm: acc + jnp.where(lvalid, gm, 0),
                d_hp, d_hp_m)

            pos_f = mf_c % Q
            old = lax.dynamic_index_in_dim(stash, pos_f, 0,
                                           keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_valid, x_in, old), pos_f, 0)
            fwd_carry = lax.ppermute(
                out, axis, [(i, (i + 1) % S) for i in range(S)])

            # ---- backward subtick ----
            m_b = u - (2 * (S - 1) - stage)
            b_valid = (m_b >= 0) & (m_b < M)
            mb_c = jnp.clip(m_b, 0, M - 1)
            x_b = lax.dynamic_index_in_dim(stash, mb_c % Q, 0,
                                           keepdims=False)
            g_in = jnp.where(is_last, g_last, bwd_carry)
            _, pull = jax.vjp(stage_fn, sp, x_b)
            d_sp_m, dx = pull((g_in,
                               jnp.where(b_valid,
                                         jnp.float32(aux_cotangent),
                                         0.0)))
            d_sp = jax.tree.map(
                lambda acc, gm: acc + jnp.where(b_valid, gm, 0),
                d_sp, d_sp_m)
            # Stage 0's dx is the gradient wrt xs[m_b].
            cur = lax.dynamic_index_in_dim(d_xs, mb_c, 0, keepdims=False)
            d_xs = lax.dynamic_update_index_in_dim(
                d_xs, jnp.where((stage == 0) & b_valid, dx, cur), mb_c,
                0)
            bwd_carry = lax.ppermute(
                dx, axis, [(i, (i - 1) % S) for i in range(S)])
            return (fwd_carry, bwd_carry, stash, d_sp, d_hp, d_xs,
                    loss, aux), None

        mb_shape = xs_[0]
        init = (jnp.zeros_like(mb_shape), jnp.zeros_like(mb_shape),
                jnp.zeros((Q,) + mb_shape.shape, mb_shape.dtype),
                jax.tree.map(jnp.zeros_like, sp),
                jax.tree.map(jnp.zeros_like, hp),
                jnp.zeros_like(xs_),
                jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        (_, _, _, d_sp, d_hp, d_xs, loss, aux), _ = lax.scan(
            slot, init, jnp.arange(U))

        def share(x):
            # Sum across the pipe axis; f32 for sub-f32 payloads (the
            # CPU AllReducePromotion crash, as in gpipe).
            if x.dtype in (jnp.bfloat16, jnp.float16):
                return lax.psum(x.astype(jnp.float32),
                                axis).astype(x.dtype)
            return lax.psum(x, axis)

        # d_sp stays stage-local (out_specs P(axis) reassembles the
        # stacked layer axis); everything else is summed — each piece
        # is nonzero on exactly one stage.
        d_hp = jax.tree.map(share, d_hp)
        d_xs = share(d_xs)
        loss = lax.psum(loss, axis)
        aux = lax.psum(aux, axis)
        return d_sp, d_hp, d_xs, loss, aux

    return inner


# ---- interleaved (virtual-stage) 1F1B --------------------------------

class InterleavedSchedule(NamedTuple):
    """Host-built slot tables for the interleaved 1F1B engine.

    Every slot is ONE subtick: each device either forwards one (chunk,
    microbatch), backwards one, or idles — unlike :func:`one_f_one_b`,
    whose lockstep slots always pay a forward AND a backward subtick
    and therefore match GPipe's bubble. All tables are ``[n_slots, S]``
    int32, indexed by the receiving/acting device.
    """
    S: int
    V: int
    M: int
    n_slots: int
    stash_depth: int          # activation-ring slots per chunk (Q)
    ctg_depth: int            # cotangent-ring slots per chunk (Qb)
    kind: np.ndarray          # 0=forward, 1=backward, 2=idle,
    #                           3=forward+loss-head (final global stage)
    chunk: np.ndarray         # acting chunk v (0 when idle)
    mb: np.ndarray            # acting microbatch m (0 when idle)
    stash_idx: np.ndarray     # v*Q  + m%Q   of the acting task
    ctg_idx: np.ndarray       # v*Qb + m%Qb  of the acting task
    rf_valid: np.ndarray      # activation arriving on the fwd carry?
    rf_idx: np.ndarray        # its stash slot (v*Q + m%Q)
    rb_valid: np.ndarray      # cotangent arriving on the bwd carry?
    rb_idx: np.ndarray        # its ctg slot (v*Qb + m%Qb)

    @property
    def bubble_fraction(self):
        """Idle subticks / total subticks over the whole schedule (each
        device runs ``2*M*V`` useful chunk-subticks in ``n_slots``)."""
        return 1.0 - 2.0 * self.M * self.V / self.n_slots


def build_interleaved_schedule(S, V, M):
    """Slot tables for ``S`` devices x ``V`` chunks x ``M`` microbatches.

    Device ``s`` owns the non-contiguous global stages ``v*S + s``; a
    microbatch therefore visits every device ``V`` times. Forwards issue
    in Megatron's chunk-major group order (chunk 0 on microbatches
    ``0..S-1``, then chunk 1 on the same group, ... then the next group
    of S), backwards mirror it; after the Megatron warmup quota
    ``2*(S-1-s) + (V-1)*S`` each device holds its in-flight forward
    count AT the quota (forward when below, backward when at/above) —
    the discrete 1F1B discipline. One list-scheduling pass resolves the
    per-slot readiness (activations/cotangents travel one ring hop per
    slot boundary); the resulting slot count hits ``2*M*V + 2*(S-1)``
    whenever ``S | M`` — bubble ``2(S-1) / (2MV + 2(S-1))``, the ~V-fold
    reduction over the non-interleaved schedule — and degrades
    gracefully (a few extra slots) on ragged ``M % S`` remainders.
    Dependency-safety and stash-ring collision-freedom are asserted at
    build time, not assumed.
    """
    if S < 1 or V < 1 or M < 1:
        raise ValueError(f"need S,V,M >= 1, got S={S} V={V} M={M}")
    total = M * V

    def warm(s):
        return min(2 * (S - 1 - s) + (V - 1) * S, total)

    fwd_q = {s: sorted(((v, m) for v in range(V) for m in range(M)),
                       key=lambda t: (t[1] // S, t[0], t[1] % S))
             for s in range(S)}
    bwd_q = {s: sorted(((v, m) for v in range(V) for m in range(M)),
                       key=lambda t: (t[1] // S, V - 1 - t[0], t[1] % S))
             for s in range(S)}
    f_slot, b_slot = {}, {}

    def f_arrival(s, v, m):
        if s == 0 and v == 0:
            return 0                      # injected from xs
        prod = f_slot.get((s - 1, v, m)) if s > 0 \
            else f_slot.get((S - 1, v - 1, m))
        return None if prod is None else prod + 1

    def b_arrival(s, v, m):
        own = f_slot.get((s, v, m))
        if own is None:
            return None                   # own forward not yet run
        if s == S - 1 and v == V - 1:
            return own + 1                # loss cotangent, made locally
        prod = b_slot.get((0, v + 1, m)) if s == S - 1 \
            else b_slot.get((s + 1, v, m))
        return None if prod is None else max(own + 1, prod + 1)

    actions, done_b, u = [], 0, 0
    limit = 4 * (total + S * V) + 16 * S + 64
    while done_b < S * total:
        if u >= limit:
            raise AssertionError(
                f"interleaved schedule deadlocked at slot {u} "
                f"(S={S} V={V} M={M})")
        row = []
        for s in range(S):
            nf = fwd_q[s][0] if fwd_q[s] else None
            nb = bwd_q[s][0] if bwd_q[s] else None
            fa = f_arrival(s, *nf) if nf else None
            ba = b_arrival(s, *nb) if nb else None
            f_ready = fa is not None and fa <= u
            b_ready = ba is not None and ba <= u
            f_done = total - len(fwd_q[s])
            in_flight = f_done - (total - len(bwd_q[s]))
            if f_done < warm(s):
                choice = "f" if f_ready else None
            elif in_flight < warm(s):
                choice = "f" if f_ready else ("b" if b_ready else None)
            else:
                choice = "b" if b_ready else ("f" if f_ready else None)
            if choice == "f":
                v, m = fwd_q[s].pop(0)
                f_slot[(s, v, m)] = u
                row.append((0, v, m))
            elif choice == "b":
                v, m = bwd_q[s].pop(0)
                b_slot[(s, v, m)] = u
                done_b += 1
                row.append((1, v, m))
            else:
                row.append((2, 0, 0))
        actions.append(row)
        u += 1

    U = len(actions)
    # Activation-stash and cotangent-buffer lifetimes per (device,
    # chunk): an activation is written when it ARRIVES (or at the
    # forward subtick for the injected stage-0/chunk-0 input) and freed
    # by the backward subtick; a cotangent is written one slot after its
    # producer (or at the local forward for the loss head) and freed by
    # the backward. Ring depth = max concurrent lifetimes, then the
    # m -> m % depth mapping is checked collision-free.
    def ring_depth(intervals_by_chunk, what):
        depth = 1
        for ivs in intervals_by_chunk.values():
            for t in range(U):
                depth = max(depth, sum(1 for (a, b, _) in ivs
                                       if a <= t <= b))
        while True:
            ok = True
            for ivs in intervals_by_chunk.values():
                for i, (a, b, m) in enumerate(ivs):
                    for (a2, b2, m2) in ivs[i + 1:]:
                        if m % depth == m2 % depth and a <= b2 and a2 <= b:
                            ok = False
            if ok:
                return depth
            depth += 1
            if depth > M:
                raise AssertionError(f"no collision-free {what} ring "
                                     f"depth <= M (S={S} V={V} M={M})")

    stash_iv, ctg_iv = {}, {}
    for (s, v, m), bs in b_slot.items():
        fs = f_slot[(s, v, m)]
        if s == 0 and v == 0:
            a_w = fs
        else:
            prod = f_slot[(s - 1, v, m)] if s > 0 \
                else f_slot[(S - 1, v - 1, m)]
            a_w = prod + 1
        stash_iv.setdefault((s, v), []).append((a_w, bs, m))
        if s == S - 1 and v == V - 1:
            c_w = fs
        else:
            prod = b_slot[(0, v + 1, m)] if s == S - 1 \
                else b_slot[(s + 1, v, m)]
            c_w = prod + 1
        ctg_iv.setdefault((s, v), []).append((c_w, bs, m))
    Q = ring_depth(stash_iv, "activation")
    Qb = ring_depth(ctg_iv, "cotangent")

    kind = np.full((U, S), 2, np.int32)
    chunk = np.zeros((U, S), np.int32)
    mb = np.zeros((U, S), np.int32)
    stash_idx = np.zeros((U, S), np.int32)
    ctg_idx = np.zeros((U, S), np.int32)
    rf_valid = np.zeros((U, S), np.int32)
    rf_idx = np.zeros((U, S), np.int32)
    rb_valid = np.zeros((U, S), np.int32)
    rb_idx = np.zeros((U, S), np.int32)
    for t, row in enumerate(actions):
        for s, (k, v, m) in enumerate(row):
            last_global = s == S - 1 and v == V - 1
            # kind 3 = forward that ALSO runs the loss head: only the
            # final global stage's forwards, known statically here, so
            # the engine's plain-forward branch never pays the
            # [mb,T,D]@[D,vocab] head matmul (which would otherwise run
            # masked on every fwd subtick — a cost scaling with the
            # very V the schedule adds to shrink the bubble).
            kind[t, s] = 3 if (k == 0 and last_global) else k
            chunk[t, s], mb[t, s] = v, m
            stash_idx[t, s] = v * Q + m % Q
            ctg_idx[t, s] = v * Qb + m % Qb
            if k == 0 and not last_global:
                # forward output travels one ring hop (s -> s+1 mod S,
                # wrapping into the next chunk off the last device)
                sc, vc = ((s + 1, v) if s < S - 1 else (0, v + 1))
                rf_valid[t + 1, sc] = 1
                rf_idx[t + 1, sc] = vc * Q + m % Q
            if k == 1 and not (s == 0 and v == 0):
                sc, vc = ((s - 1, v) if s > 0 else (S - 1, v - 1))
                rb_valid[t + 1, sc] = 1
                rb_idx[t + 1, sc] = vc * Qb + m % Qb
    # The engine's kind-3 branch accumulates loss/d_hp UNMASKED, so a
    # kind-3 entry anywhere but the final global stage would corrupt
    # gradients — make that impossible by construction.
    head_rows, head_cols = np.nonzero(kind == 3)
    assert (head_cols == S - 1).all() and len(head_rows) == M, \
        f"loss-head subticks misplaced (S={S} V={V} M={M})"
    return InterleavedSchedule(
        S=S, V=V, M=M, n_slots=U, stash_depth=Q, ctg_depth=Qb,
        kind=kind, chunk=chunk, mb=mb, stash_idx=stash_idx,
        ctg_idx=ctg_idx, rf_valid=rf_valid, rf_idx=rf_idx,
        rb_valid=rb_valid, rb_idx=rb_idx)


def _chunk_permutation(n_layers, S, V):
    """Row permutation taking the canonical stacked-layer order to the
    device-major interleaved order (device ``s`` holds global stages
    ``v*S + s`` as ``V`` contiguous blocks), and its inverse."""
    if n_layers % (S * V):
        raise ValueError(f"stacked layer axis {n_layers} must divide "
                         f"into {S} stages x {V} virtual chunks")
    lb = n_layers // (S * V)
    perm = np.concatenate([np.arange(lb) + (v * S + s) * lb
                           for s in range(S) for v in range(V)])
    return perm, np.argsort(perm)


def _interleaved_inner(stage_fn, loss_fn, sched, aux_cotangent, axis):
    """Per-device program for the interleaved schedule (the body that
    runs manual over the pipe axis). Factored out of
    :func:`interleaved_one_f_one_b` so tests can execute it under
    ``jax.vmap(..., axis_name=axis)`` — a faithful collective emulation
    on hosts whose jax lacks ``jax.shard_map``.

    ``sp`` leaves carry the device's ``V`` chunk blocks stacked
    (device-major permuted, leading dim ``V * Lb``).
    """
    S, V, M = sched.S, sched.V, sched.M
    Q, Qb = sched.stash_depth, sched.ctg_depth
    tables = tuple(jnp.asarray(t) for t in
                   (sched.kind, sched.chunk, sched.mb, sched.stash_idx,
                    sched.ctg_idx, sched.rf_valid, sched.rf_idx,
                    sched.rb_valid, sched.rb_idx))

    def inner(sp, hp, xs_, largs_):
        stage = lax.axis_index(axis)
        spv = jax.tree.map(
            lambda a: a.reshape((V, a.shape[0] // V) + a.shape[1:]), sp)
        mb_shape = xs_[0]

        def chunk_params(v):
            return jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, v, 0,
                                                   keepdims=False), spv)

        def slot(state, rows):
            (fwd_c, bwd_c, stash, ctg, d_sp, d_hp, d_xs, loss,
             aux) = state
            (kind, v_a, m_a, sidx, cidx, rfv, rfi, rbv,
             rbi) = [jnp.take(r, stage) for r in rows]

            # Deliver what the carries brought at the slot boundary
            # into the per-chunk rings (garbage hops are masked out).
            cur = lax.dynamic_index_in_dim(stash, rfi, 0, keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(rfv > 0, fwd_c, cur), rfi, 0)
            curb = lax.dynamic_index_in_dim(ctg, rbi, 0, keepdims=False)
            ctg = lax.dynamic_update_index_in_dim(
                ctg, jnp.where(rbv > 0, bwd_c, curb), rbi, 0)

            zero_mb = jnp.zeros_like(mb_shape)

            def make_fwd(with_head):
                # Two forward branches, selected by the HOST tables
                # (kind 3 = the final global stage's forwards): only
                # those pay the loss head — one linearization yields
                # the microbatch loss, its cotangent wrt the chunk
                # output, AND the head-param grads (see one_f_one_b) —
                # while every other forward subtick skips the
                # [mb,T,D]@[D,vocab] head matmul entirely.
                def do_fwd(st):
                    stash, ctg, d_sp, d_hp, d_xs, loss, aux = st
                    stored = lax.dynamic_index_in_dim(stash, sidx, 0,
                                                      keepdims=False)
                    inj = lax.dynamic_index_in_dim(xs_, m_a, 0,
                                                   keepdims=False)
                    x_in = jnp.where((stage == 0) & (v_a == 0), inj,
                                     stored)
                    # Re-stored even when it just arrived: the injected
                    # stage-0/chunk-0 input must land in the ring for
                    # the backward subtick's recompute.
                    stash = lax.dynamic_update_index_in_dim(
                        stash, x_in, sidx, 0)
                    out, a = stage_fn(chunk_params(v_a), x_in)
                    aux = aux + a
                    if with_head:
                        la = jax.tree.map(
                            lambda t: lax.dynamic_index_in_dim(
                                t, m_a, 0, keepdims=False), largs_)
                        lval, (g_last, d_hp_m) = jax.value_and_grad(
                            lambda o, h: loss_fn(h, o, la),
                            argnums=(0, 1))(out, hp)
                        loss = loss + lval
                        d_hp = jax.tree.map(jnp.add, d_hp, d_hp_m)
                        ctg = lax.dynamic_update_index_in_dim(
                            ctg, g_last, cidx, 0)
                    return (stash, ctg, d_sp, d_hp, d_xs, loss, aux,
                            out, zero_mb)
                return do_fwd

            def do_bwd(st):
                stash, ctg, d_sp, d_hp, d_xs, loss, aux = st
                x_b = lax.dynamic_index_in_dim(stash, sidx, 0,
                                               keepdims=False)
                g_in = lax.dynamic_index_in_dim(ctg, cidx, 0,
                                                keepdims=False)
                _, pull = jax.vjp(stage_fn, chunk_params(v_a), x_b)
                d_sp_v, dx = pull((g_in, jnp.float32(aux_cotangent)))
                d_sp = jax.tree.map(
                    lambda acc, g: lax.dynamic_update_index_in_dim(
                        acc,
                        lax.dynamic_index_in_dim(acc, v_a, 0,
                                                 keepdims=False) + g,
                        v_a, 0),
                    d_sp, d_sp_v)
                # Stage 0 / chunk 0's dx is the gradient wrt xs[m].
                cur = lax.dynamic_index_in_dim(d_xs, m_a, 0,
                                               keepdims=False)
                d_xs = lax.dynamic_update_index_in_dim(
                    d_xs, jnp.where((stage == 0) & (v_a == 0), dx, cur),
                    m_a, 0)
                return (stash, ctg, d_sp, d_hp, d_xs, loss, aux,
                        zero_mb, dx)

            def do_idle(st):
                return st + (zero_mb, zero_mb)

            (stash, ctg, d_sp, d_hp, d_xs, loss, aux, f_pay,
             b_pay) = lax.switch(
                kind, [make_fwd(False), do_bwd, do_idle,
                       make_fwd(True)],
                (stash, ctg, d_sp, d_hp, d_xs, loss, aux))

            fwd_c = lax.ppermute(f_pay, axis,
                                 [(i, (i + 1) % S) for i in range(S)])
            bwd_c = lax.ppermute(b_pay, axis,
                                 [(i, (i - 1) % S) for i in range(S)])
            return (fwd_c, bwd_c, stash, ctg, d_sp, d_hp, d_xs, loss,
                    aux), None

        init = (jnp.zeros_like(mb_shape), jnp.zeros_like(mb_shape),
                jnp.zeros((V * Q,) + mb_shape.shape, mb_shape.dtype),
                jnp.zeros((V * Qb,) + mb_shape.shape, mb_shape.dtype),
                jax.tree.map(jnp.zeros_like, spv),
                jax.tree.map(jnp.zeros_like, hp),
                jnp.zeros_like(xs_),
                jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        (_, _, _, _, d_sp, d_hp, d_xs, loss, aux), _ = lax.scan(
            slot, init, tables)

        def share(x):
            # f32 psum for sub-f32 payloads: XLA CPU's
            # AllReducePromotion pass crashes on bf16 allreduce inside
            # manual shard_map (as in gpipe/one_f_one_b).
            if x.dtype in (jnp.bfloat16, jnp.float16):
                return lax.psum(x.astype(jnp.float32),
                                axis).astype(x.dtype)
            return lax.psum(x, axis)

        d_sp = jax.tree.map(
            lambda a: a.reshape((a.shape[0] * a.shape[1],)
                                + a.shape[2:]), d_sp)
        d_hp = jax.tree.map(share, d_hp)
        d_xs = share(d_xs)
        loss = lax.psum(loss, axis)
        aux = lax.psum(aux, axis)
        return d_sp, d_hp, d_xs, loss, aux

    return inner


def interleaved_one_f_one_b(stage_fn, loss_fn, stage_params, head_params,
                            xs, loss_args, mesh, axis="pipe",
                            num_virtual=1, aux_cotangent=0.0):
    """Interleaved (virtual-stage) 1F1B: each device holds ``V``
    NON-contiguous model chunks (global stage ``v*S + s`` on device
    ``s``), microbatches round-robin through the ``S*V`` virtual stages,
    and every slot is a single chunk subtick — forward OR backward —
    chosen per device by the host-built :func:`build_interleaved_schedule`
    tables. Warmup fills the ``S*V``-deep virtual pipeline at full
    forward rate, the steady phase alternates 1F1B per device, and
    cooldown drains backwards, so the bubble drops to
    ``2(S-1) / (2MV + 2(S-1))`` — ~V-fold below :func:`one_f_one_b`'s
    lockstep ``2(S-1)/(M + 2(S-1))`` — at the price of ``V`` ppermute
    ring hops per microbatch instead of one, which the steady phase
    hides behind real chunk compute.

    Same contract as :func:`one_f_one_b` (``stage_fn`` now receives a
    CHUNK block — ``n_layers/(S*V)`` stacked layers; ``loss_fn`` is the
    per-microbatch objective numerator); returns ``(loss_sum, aux_sum,
    d_stage_params, d_head_params, d_xs)`` with ``d_stage_params`` in
    the CANONICAL stacked-layer order (the device-major permutation is
    applied and inverted internally — NOTE: under contiguous-block pipe
    partition rules that is a params-sized reshard in and a grads-sized
    reshard out per step; a production multi-chip deployment should
    store the stacked weights pre-permuted device-major and shard THAT
    over the pipe axis instead, see docs/benchmarks.md round 6).
    ``num_virtual=1`` degenerates to
    the TRUE non-interleaved 1F1B (single-subtick slots — bubble
    ``2(S-1)/(2M + 2(S-1))``, already below the lockstep variant).

    Reference analog: none (net-new); the schedule is the public
    interleaved 1F1B formulation (Megatron-LM's virtual pipeline).
    """
    S = mesh.shape[axis]
    V = int(num_virtual)
    M = xs.shape[0]
    sched = build_interleaved_schedule(S, V, M)
    xs, cast_dt = _cast_f32_on_cpu(mesh, xs)

    leaves = jax.tree.leaves(stage_params)
    if not leaves:
        raise ValueError("empty stage_params")
    perm, inv = _chunk_permutation(leaves[0].shape[0], S, V)
    sp_perm = jax.tree.map(lambda a: a[perm], stage_params)

    inner = _interleaved_inner(stage_fn, loss_fn, sched, aux_cotangent,
                               axis)
    d_sp, d_hp, d_xs, loss, aux = _pipe_spmd(
        inner, mesh, axis, (True, False, False, False),
        (True, False, False, False, False))(
            sp_perm, head_params, xs, loss_args)
    d_sp = jax.tree.map(lambda a: a[inv], d_sp)
    if cast_dt is not None:
        d_xs = d_xs.astype(cast_dt)
    return loss, aux, d_sp, d_hp, d_xs


# ---- static-analysis hooks (hvdlint) ---------------------------------

SCHEDULES = ("gpipe", "1f1b", "interleaved_1f1b")


def build_pipeline_inner(schedule, stage_fn, loss_fn=None, *, S, M,
                         num_virtual=1, axis="pipe", aux_cotangent=0.0):
    """Build a schedule's per-device manual program WITHOUT running it.

    This is the program-builder hook ``horovod_tpu.analysis`` (hvdlint)
    traces: the returned ``inner`` is exactly what the engines hand to
    ``_pipe_spmd``, so linting it covers the real collective sequence —
    and because it is traced with ``jax.make_jaxpr(axis_env=[(axis,
    S)])`` rather than ``shard_map``, the check runs identically on
    jax 0.4.x boxes (where the engines execute under vmap emulation).

    ``schedule="gpipe"`` returns ``inner(sp, xs)``; the 1F1B variants
    return ``inner(sp, hp, xs, largs)`` and require ``loss_fn``.
    """
    if schedule == "gpipe":
        return _gpipe_inner(stage_fn, S, M, axis)
    if loss_fn is None:
        raise ValueError(f"schedule {schedule!r} requires loss_fn")
    if schedule == "1f1b":
        return _one_f_one_b_inner(stage_fn, loss_fn, S, M, axis,
                                  aux_cotangent)
    if schedule == "interleaved_1f1b":
        sched = build_interleaved_schedule(S, int(num_virtual), M)
        return _interleaved_inner(stage_fn, loss_fn, sched,
                                  aux_cotangent, axis)
    raise ValueError(f"unknown schedule {schedule!r}: expected one of "
                     f"{SCHEDULES}")


def predicted_collectives(schedule, *, S, M, num_virtual=1, axis="pipe",
                          n_head_leaves=2):
    """The ordered collective sequence a schedule's inner program MUST
    emit, predicted from the host-side schedule structure — the ground
    truth for hvdlint's C5 schedule-conformance check.

    - gpipe: one activation ``ppermute`` per scan step (``M + S - 1``
      steps), then the result and aux ``psum`` pair;
    - 1f1b: one forward and one backward ``ppermute`` per lockstep slot
      (``M + 2(S-1)`` slots), then the shared-gradient ``psum`` tail;
    - interleaved_1f1b: two ``ppermute`` ring hops per slot, with the
      slot count taken from :func:`build_interleaved_schedule` — the
      SAME table the engine executes, so any engine/table drift is a
      C5 error before launch.

    ``n_head_leaves`` is the leaf count of the loss-head param tree
    (llama: final_norm + lm_head = 2); the psum tail is those leaves
    plus d_xs, loss, and aux. Returns ``[(prim_name, (axis,)), ...]``.
    """
    pp, ps = ("ppermute", (axis,)), ("psum", (axis,))
    if schedule == "gpipe":
        return [pp] * (M + S - 1) + [ps] * 2
    tail = [ps] * (n_head_leaves + 3)
    if schedule == "1f1b":
        return [pp] * (2 * (M + 2 * (S - 1))) + tail
    if schedule == "interleaved_1f1b":
        sched = build_interleaved_schedule(S, int(num_virtual), M)
        return [pp] * (2 * sched.n_slots) + tail
    raise ValueError(f"unknown schedule {schedule!r}: expected one of "
                     f"{SCHEDULES}")
