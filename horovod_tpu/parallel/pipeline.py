"""GPipe-style pipeline parallelism over the mesh's "pipe" axis.

Reference analog: none — Horovod is data-parallel only (SURVEY.md §5.7/
§2.6); this is net-new TPU machinery like ring attention. Design: the
layer stack is split into S contiguous stages (the stacked layer axis
shards over "pipe", so each device holds its stage's weights); inside a
*partial-manual* ``shard_map`` (manual over "pipe" only — tensor/fsdp/
data stay with GSPMD), a ``lax.scan`` runs the classic GPipe schedule:
each step every stage processes one microbatch and ``ppermute`` rotates
activations to the next stage. M microbatches drain in M + S - 1 steps
(the bubble); results collect on the last stage and are shared back with
a masked ``psum``.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def gpipe(stage_fn, stage_params, xs, mesh, axis="pipe"):
    """Run microbatches through the pipeline.

    ``stage_fn(stage_params_block, x_mb) -> (y_mb, aux)`` applies ONE
    stage's slice of the network (aux is a scalar, e.g. an MoE balance
    loss; return 0.0 if unused). ``stage_params`` is a pytree whose
    leaves have a leading stacked-layer axis of length divisible by the
    pipe size — ``shard_map`` splits it into per-stage blocks.
    ``xs`` is ``[M, ...]`` microbatches. Returns ``(ys [M, ...],
    aux_sum)`` where aux_sum totals stage_fn aux over all (stage,
    microbatch) pairs.
    """
    S = mesh.shape[axis]
    M = xs.shape[0]

    # XLA CPU's AllReducePromotion pass crashes on the bf16 allreduces
    # this program generates (the collection psum and AD's cotangent
    # psum for the replicated xs input). CPU is the test substrate, so
    # run the pipeline in f32 there; TPU keeps native bf16.
    on_cpu = all(d.platform == "cpu" for d in mesh.devices.flat)
    cast_dt = None
    if on_cpu and xs.dtype in (jnp.bfloat16, jnp.float16):
        cast_dt = xs.dtype
        xs = xs.astype(jnp.float32)

    def inner(sp, xs_):
        stage = lax.axis_index(axis)

        def step(state, t):
            carry, buf, aux = state
            inj = lax.dynamic_index_in_dim(xs_, jnp.clip(t, 0, M - 1), 0,
                                           keepdims=False)
            inp = jnp.where(stage == 0, inj, carry)
            out, a = stage_fn(sp, inp)
            # Bubble steps (stage s idle before t=s and after t=s+M-1)
            # compute on garbage; mask their aux and never collect them.
            valid = (t - stage >= 0) & (t - stage < M)
            aux = aux + jnp.where(valid, a, 0.0)
            cidx = jnp.clip(t - (S - 1), 0, M - 1)
            collect = (stage == S - 1) & (t >= S - 1)
            cur = lax.dynamic_index_in_dim(buf, cidx, 0, keepdims=False)
            buf = lax.dynamic_update_index_in_dim(
                buf, jnp.where(collect, out, cur), cidx, 0)
            carry = lax.ppermute(out, axis,
                                 [(i, (i + 1) % S) for i in range(S)])
            return (carry, buf, aux), None

        init = (jnp.zeros_like(xs_[0]), jnp.zeros_like(xs_),
                jnp.zeros((), jnp.float32))
        (carry, buf, aux), _ = lax.scan(step, init, jnp.arange(M + S - 1))
        # Results live on the last stage; the loss is computed globally,
        # so share them (and the aux total) across the pipe axis. The
        # psum runs in f32 for sub-f32 activations: XLA CPU's
        # AllReducePromotion pass crashes on bf16 allreduce inside
        # manual shard_map, and on TPU the f32 cast is fused anyway.
        out_dt = buf.dtype
        masked = jnp.where(stage == S - 1, buf, jnp.zeros_like(buf))
        if out_dt in (jnp.bfloat16, jnp.float16):
            buf = lax.psum(masked.astype(jnp.float32), axis).astype(out_dt)
        else:
            buf = lax.psum(masked, axis)
        aux = lax.psum(aux, axis)
        return buf, aux

    ys, aux = jax.shard_map(inner, mesh=mesh, in_specs=(P(axis), P()),
                            out_specs=(P(), P()), axis_names={axis},
                            check_vma=False)(stage_params, xs)
    if cast_dt is not None:
        ys = ys.astype(cast_dt)
    return ys, aux


def one_f_one_b(stage_fn, loss_fn, stage_params, head_params, xs,
                loss_args, mesh, axis="pipe", aux_cotangent=0.0):
    """1F1B pipeline schedule: forward AND backward interleaved in one
    lockstep scan, with the loss computed on the last stage per
    microbatch.

    Why not let AD differentiate :func:`gpipe`? Its backward replays
    the forward scan in reverse, so every stage stashes activations for
    ALL M microbatches — O(M) memory. Here each slot runs one forward
    subtick and one backward subtick per stage: stage ``s`` forwards
    microbatch ``m`` at slot ``s + m``, the last stage turns it
    straight into a loss cotangent, and the backward walks back up at
    slot ``2(S-1) - s + m``. A stage therefore holds at most
    ``min(M, 2(S-1-s) + 1) <= 2S - 1`` stashed INPUTS (activations are
    recomputed from the stashed input during the backward subtick —
    per-stage remat, the standard 1F1B trade). Timeline = ``M + 2(S-1)``
    slots; the ``2(S-1)/(M + 2(S-1))`` bubble fraction matches GPipe's
    forward+backward total, so the win is memory, not bubble.

    ``stage_fn(sp_block, x_mb) -> (y_mb, aux_scalar)`` as in gpipe.
    ``loss_fn(head_params, y_mb, loss_args_mb) -> scalar`` is the last
    stage's per-microbatch objective NUMERATOR (any global
    normalization — e.g. a mask-token count — must be folded in by the
    caller, since microbatches cannot see each other's denominators).
    ``loss_args`` is a pytree with leading microbatch axis M (targets,
    masks, ...). ``aux_cotangent`` is the constant d(objective)/d(aux)
    applied to every valid (stage, microbatch) aux contribution — e.g.
    ``moe_aux_weight / (n_layers * M)``.

    Returns ``(loss_sum, aux_sum, d_stage_params, d_head_params,
    d_xs)`` — the gradient of ``loss_sum + aux_cotangent * aux_raw_sum``
    with respect to (stage_params, head_params, xs). Callers wanting
    plain ``value_and_grad`` ergonomics should wrap this in a
    ``custom_vjp`` (see models/llama.py's 1f1b path).

    Reference analog: none (net-new, like gpipe); the schedule is the
    public non-interleaved 1F1B (PipeDream-flush) formulation.
    """
    S = mesh.shape[axis]
    M = xs.shape[0]
    Q = min(M, 2 * S - 1)                       # stash depth per stage
    U = M + 2 * (S - 1)                         # total slots

    on_cpu = all(d.platform == "cpu" for d in mesh.devices.flat)
    cast_dt = None
    if on_cpu and xs.dtype in (jnp.bfloat16, jnp.float16):
        cast_dt = xs.dtype
        xs = xs.astype(jnp.float32)

    def inner(sp, hp, xs_, largs_):
        stage = lax.axis_index(axis)
        is_last = stage == S - 1

        def slot(state, u):
            (fwd_carry, bwd_carry, stash, d_sp, d_hp, d_xs, loss,
             aux) = state

            # ---- forward subtick ----
            m_f = u - stage
            f_valid = (m_f >= 0) & (m_f < M)
            mf_c = jnp.clip(m_f, 0, M - 1)
            inj = lax.dynamic_index_in_dim(xs_, mf_c, 0, keepdims=False)
            x_in = jnp.where(stage == 0, inj, fwd_carry)
            out, a = stage_fn(sp, x_in)
            aux = aux + jnp.where(f_valid, a, 0.0)

            # Last stage: microbatch loss + its cotangents wrt the
            # stage output AND the head params, all from ONE
            # linearization of the loss head (it contains the
            # [mb,T,D]@[D,vocab] logits matmul — the model's largest —
            # so a second grad call would double the head work every
            # slot). Both are consumed by THIS slot's backward subtick
            # (the last stage's backward slot equals its forward slot).
            la = jax.tree.map(
                lambda t: lax.dynamic_index_in_dim(t, mf_c, 0,
                                                   keepdims=False),
                largs_)
            lval, (g_last, d_hp_m) = jax.value_and_grad(
                lambda o, h: loss_fn(h, o, la), argnums=(0, 1))(out, hp)
            lvalid = is_last & f_valid
            loss = loss + jnp.where(lvalid, lval, 0.0)
            d_hp = jax.tree.map(
                lambda acc, gm: acc + jnp.where(lvalid, gm, 0),
                d_hp, d_hp_m)

            pos_f = mf_c % Q
            old = lax.dynamic_index_in_dim(stash, pos_f, 0,
                                           keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_valid, x_in, old), pos_f, 0)
            fwd_carry = lax.ppermute(
                out, axis, [(i, (i + 1) % S) for i in range(S)])

            # ---- backward subtick ----
            m_b = u - (2 * (S - 1) - stage)
            b_valid = (m_b >= 0) & (m_b < M)
            mb_c = jnp.clip(m_b, 0, M - 1)
            x_b = lax.dynamic_index_in_dim(stash, mb_c % Q, 0,
                                           keepdims=False)
            g_in = jnp.where(is_last, g_last, bwd_carry)
            _, pull = jax.vjp(stage_fn, sp, x_b)
            d_sp_m, dx = pull((g_in,
                               jnp.where(b_valid,
                                         jnp.float32(aux_cotangent),
                                         0.0)))
            d_sp = jax.tree.map(
                lambda acc, gm: acc + jnp.where(b_valid, gm, 0),
                d_sp, d_sp_m)
            # Stage 0's dx is the gradient wrt xs[m_b].
            cur = lax.dynamic_index_in_dim(d_xs, mb_c, 0, keepdims=False)
            d_xs = lax.dynamic_update_index_in_dim(
                d_xs, jnp.where((stage == 0) & b_valid, dx, cur), mb_c,
                0)
            bwd_carry = lax.ppermute(
                dx, axis, [(i, (i - 1) % S) for i in range(S)])
            return (fwd_carry, bwd_carry, stash, d_sp, d_hp, d_xs,
                    loss, aux), None

        mb_shape = xs_[0]
        init = (jnp.zeros_like(mb_shape), jnp.zeros_like(mb_shape),
                jnp.zeros((Q,) + mb_shape.shape, mb_shape.dtype),
                jax.tree.map(jnp.zeros_like, sp),
                jax.tree.map(jnp.zeros_like, hp),
                jnp.zeros_like(xs_),
                jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        (_, _, _, d_sp, d_hp, d_xs, loss, aux), _ = lax.scan(
            slot, init, jnp.arange(U))

        def share(x):
            # Sum across the pipe axis; f32 for sub-f32 payloads (the
            # CPU AllReducePromotion crash, as in gpipe).
            if x.dtype in (jnp.bfloat16, jnp.float16):
                return lax.psum(x.astype(jnp.float32),
                                axis).astype(x.dtype)
            return lax.psum(x, axis)

        # d_sp stays stage-local (out_specs P(axis) reassembles the
        # stacked layer axis); everything else is summed — each piece
        # is nonzero on exactly one stage.
        d_hp = jax.tree.map(share, d_hp)
        d_xs = share(d_xs)
        loss = lax.psum(loss, axis)
        aux = lax.psum(aux, axis)
        return d_sp, d_hp, d_xs, loss, aux

    d_sp, d_hp, d_xs, loss, aux = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(P(axis), P(), P(), P()),
        out_specs=(P(axis), P(), P(), P(), P()),
        axis_names={axis}, check_vma=False)(
            stage_params, head_params, xs, loss_args)
    if cast_dt is not None:
        d_xs = d_xs.astype(cast_dt)
    return loss, aux, d_sp, d_hp, d_xs
