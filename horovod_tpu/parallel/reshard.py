"""``redistribute`` — minimal-collective array resharding (docs/redistribute.md).

The planner of "Memory-efficient array redistribution through portable
collective communication" (arXiv:2112.01075), specialized to the row
partitions this runtime actually ships: an array is REPLICATED (every
rank holds all of it), SHARDED (rank r owns a contiguous, ordered row
range of axis 0), or PARTIAL (every rank holds an unreduced addend —
the state a gradient is in before its reduction). Between any two such
layouts there is a *minimal* collective sequence, and emitting exactly
it — never a gather-everything-then-slice detour — is what makes
checkpoint resharding (train on N, serve on M) and elastic
re-formation (docs/elastic.md) affordable:

========== =============== =============================================
src        dst             plan
========== =============== =============================================
X          X (same rows)   [] — zero-copy
replicated sharded         slice (no wire)
sharded    replicated      allgatherv
sharded    sharded         alltoallv (intersection rows to new owners)
partial    replicated      allreduce
partial    sharded (even)  reducescatter
partial    sharded (other) reducescatter + alltoallv
========== =============== =============================================

Every step carries its exact per-rank wire-byte prediction, derived
from the SAME ring segment-rotation helpers the C++ engine executes
(``ring_owned_segment`` twins, csrc/ring_ops.h) — so the plan
reconciles bit-exactly with the core's wire counters
(``make reshard-smoke`` pins measured-vs-predicted < 1%).

Three executors share one plan:

- :func:`simulate_plan` — pure-numpy all-rank reference (property
  tests: src -> dst -> src must be the identity);
- :func:`execute_plan` — this rank's slice of the plan over the eager
  host collectives (the checkpoint-resharding path);
- :func:`redistribute` — jax arrays between ``NamedSharding``s
  (zero-copy when the shardings agree; XLA moves the bytes otherwise,
  and the plan prices what the movement costs on the host planes).
"""

import dataclasses
import math

import numpy as np

__all__ = [
    "Layout",
    "ReshardPlan",
    "ReshardStep",
    "plan_redistribute",
    "simulate_plan",
    "execute_plan",
    "redistribute",
    "layout_from_sharding",
    "even_row_layout",
    "hier_wire_bytes",
    "flat_allreduce_wire_bytes",
]


def _ring_send_segment(rank, step, size, rot=0):
    """Python twin of ``csrc/ring_ops.h RingSendSegment`` (pinned
    against the C ABI in tests/single/test_reshard.py)."""
    return ((rank - step + rot) % size + 2 * size) % size


def _even_split(n_rows, n_shards):
    """The ONE row-split rule, shared with the core (q + remainder to
    lower ranks — csrc/operations.cc REDUCESCATTER and ring
    segmentation use the same arithmetic)."""
    q, r = divmod(n_rows, n_shards)
    return tuple(q + (1 if i < r else 0) for i in range(n_shards))


# ---- layouts ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Layout:
    """How one logical array is distributed over ``nranks`` ranks.

    ``kind``: ``"replicated"`` | ``"sharded"`` | ``"partial"``.
    ``rows``: for sharded, the per-rank ``(start, n)`` row ranges —
    required to be an ordered contiguous partition of ``[0, n_rows)``
    (rank r's rows all precede rank r+1's), which is what makes the
    sharded->sharded alltoallv receive rows already in order.
    """

    kind: str
    nranks: int
    rows: tuple = ()

    def __post_init__(self):
        if self.kind not in ("replicated", "sharded", "partial"):
            raise ValueError(f"unknown layout kind {self.kind!r}")
        if self.kind == "sharded":
            if len(self.rows) != self.nranks:
                raise ValueError(
                    f"sharded layout needs one (start, n) per rank: got "
                    f"{len(self.rows)} for {self.nranks} ranks")
            pos = 0
            for start, n in self.rows:
                if start != pos or n < 0:
                    raise ValueError(
                        f"rows {self.rows} are not an ordered contiguous "
                        f"partition (rank range starting at {start}, "
                        f"expected {pos})")
                pos += n
        elif self.rows:
            raise ValueError(f"{self.kind} layout carries no rows")

    @property
    def n_rows(self):
        return sum(n for _, n in self.rows)

    def range_of(self, rank):
        return self.rows[rank]

    @staticmethod
    def replicated(nranks):
        return Layout("replicated", nranks)

    @staticmethod
    def partial(nranks):
        return Layout("partial", nranks)

    @staticmethod
    def sharded(n_rows, nranks):
        """Even split, remainder to lower ranks — the core's rule."""
        starts, pos = [], 0
        for n in _even_split(n_rows, nranks):
            starts.append((pos, n))
            pos += n
        return Layout("sharded", nranks, tuple(starts))

    @staticmethod
    def from_rows(rows):
        return Layout("sharded", len(rows), tuple(tuple(r) for r in rows))


def even_row_layout(n_rows, n_shards):
    """Alias for :meth:`Layout.sharded` (the checkpoint-resharding
    entry point reads better with a verb-free name)."""
    return Layout.sharded(n_rows, n_shards)


# ---- plan ------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ReshardStep:
    """One collective of a plan. ``op`` in {slice, allgatherv,
    alltoallv, reducescatter, allreduce}; ``wire_tx``/``wire_rx`` are
    per-rank transport-byte predictions matching the core's WireTally
    accounting exactly (csrc/ring_ops.cc)."""

    op: str
    wire_tx: tuple
    wire_rx: tuple
    detail: str = ""


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    src: Layout
    dst: Layout
    shape: tuple
    itemsize: int
    steps: tuple

    @property
    def zero_copy(self):
        return not self.steps

    def wire_tx_bytes(self, rank=None):
        """Predicted transport tx bytes (this rank, or total)."""
        if rank is None:
            return sum(sum(s.wire_tx) for s in self.steps)
        return sum(s.wire_tx[rank] for s in self.steps)

    def wire_rx_bytes(self, rank=None):
        if rank is None:
            return sum(sum(s.wire_rx) for s in self.steps)
        return sum(s.wire_rx[rank] for s in self.steps)

    def expected_collectives(self, axis="shard"):
        """The in-graph collective signature this plan corresponds to,
        in hvdlint C5's ``expect_collectives`` shape — the static
        bridge between a plan and a registered redistribute program."""
        prims = {"allgatherv": "all_gather", "alltoallv": "all_to_all",
                 "reducescatter": "psum_scatter", "allreduce": "psum"}
        return [(prims[s.op], (axis,)) for s in self.steps
                if s.op in prims]

    def describe(self):
        if not self.steps:
            return "zero-copy (layouts agree)"
        return " -> ".join(
            f"{s.op}[{s.detail}]" if s.detail else s.op
            for s in self.steps)


def _row_bytes(shape, itemsize):
    return int(math.prod(shape[1:])) * itemsize if len(shape) > 1 \
        else itemsize


def _allgatherv_step(layout, shape, itemsize):
    """Ring allgatherv of per-rank row blocks: at step s rank r sends
    block (r - s) mod N and receives block (r - s - 1) mod N
    (csrc/ring_ops.cc Allgatherv)."""
    n = layout.nranks
    rb = _row_bytes(shape, itemsize)
    blk = [rows * rb for _, rows in layout.rows]
    tx = [sum(blk[(r - s + n) % n] for s in range(n - 1)) for r in range(n)]
    rx = [sum(blk[(r - s - 1 + n) % n] for s in range(n - 1))
          for r in range(n)]
    return ReshardStep("allgatherv", tuple(tx), tuple(rx),
                       detail=f"{n} blocks")


def _alltoallv_step(src, dst, shape, itemsize):
    """Pairwise exchange of intersection row ranges. Includes the
    8-byte-per-rank recv-splits exchange the eager ALLTOALL response
    performs before the payload (csrc/operations.cc)."""
    n = src.nranks
    rb = _row_bytes(shape, itemsize)
    send = [[0] * n for _ in range(n)]
    for r in range(n):
        s0, sn = src.range_of(r)
        for d in range(n):
            d0, dn = dst.range_of(d)
            lo, hi = max(s0, d0), min(s0 + sn, d0 + dn)
            if hi > lo:
                send[r][d] = (hi - lo) * rb
    # Splits exchange: Alltoallv of one int64 per rank (self skipped on
    # the wire), then the payload exchange (self handled by memcpy).
    tx = [8 * (n - 1) + sum(b for d, b in enumerate(send[r]) if d != r)
          for r in range(n)]
    rx = [8 * (n - 1) + sum(send[s][r] for s in range(n) if s != r)
          for r in range(n)]
    return ReshardStep("alltoallv", tuple(tx), tuple(rx),
                       detail="intersection rows")


def _ring_reduce_phase_bytes(counts, size, rot, rank):
    """tx elems of one N-1-step ring reduce phase at rotation ``rot``
    for ``rank`` (csrc PipelinedReduceChunks tally)."""
    return sum(counts[_ring_send_segment(rank, s, size, rot)]
               for s in range(size - 1))


def _reducescatter_step(layout, shape, itemsize, compressed=False):
    """Ring reduce-scatter at rot=-1 over the EVEN split (the core's
    REDUCESCATTER row rule). Wire halves when the f32 payload rides the
    bf16 codec."""
    n = layout.nranks
    rb = _row_bytes(shape, itemsize)
    counts = [rows * rb for rows in _even_split(shape[0], n)]
    scale = 0.5 if compressed else 1.0
    tx, rx = [], []
    for r in range(n):
        t = _ring_reduce_phase_bytes(counts, n, -1, r)
        v = sum(counts[_ring_send_segment(r, s + 1, n, -1)]
                for s in range(n - 1))
        tx.append(int(t * scale))
        rx.append(int(v * scale))
    return ReshardStep("reducescatter", tuple(tx), tuple(rx),
                       detail="even split")


def _allreduce_step(shape, itemsize, nranks, compressed=False):
    """Flat ring allreduce: reduce-scatter phase (rot=0) + allgather
    phase (send rot=1 / recv rot=0 segments)."""
    total = int(math.prod(shape)) if shape else 1
    counts = [c * itemsize for c in _even_split(total, nranks)]
    scale = 0.5 if compressed else 1.0
    tx, rx = [], []
    for r in range(nranks):
        t = _ring_reduce_phase_bytes(counts, nranks, 0, r)
        t += sum(counts[_ring_send_segment(r, s, nranks, 1)]
                 for s in range(nranks - 1))
        v = sum(counts[_ring_send_segment(r, s + 1, nranks, 0)]
                for s in range(nranks - 1))
        v += sum(counts[_ring_send_segment(r, s, nranks, 0)]
                 for s in range(nranks - 1))
        tx.append(int(t * scale))
        rx.append(int(v * scale))
    return ReshardStep("allreduce", tuple(tx), tuple(rx))


def flat_allreduce_wire_bytes(count, itemsize, size, rank,
                              compressed=False):
    """Per-rank transport tx bytes of one flat ring allreduce — the
    telemetry-predictor twin of the core's WireTally (docs/wire.md)."""
    step = _allreduce_step((count,), itemsize, size,
                           compressed=compressed)
    return step.wire_tx[rank]


def hier_wire_bytes(count, itemsize, size, local_size, rank,
                    compress_cross=False, compressed=False):
    """Per-rank wire tx bytes of the hierarchical cross-plane allreduce,
    split by plane: ``{"intra": ..., "cross": ...}``.

    Mirrors csrc/ring_ops.cc HierarchicalAllreduce exactly: intra-slice
    reduce-scatter (rot=-1) over ``local_size`` group members, flat
    allreduce of this rank's 1/local_size segment among the
    ``size/local_size`` same-local-rank peers (the CROSS plane —
    compressed when either knob engages the bf16 codec there), then the
    intra-slice ring allgatherv of the finalized segments.
    """
    L, M = local_size, size // local_size
    lr = rank % L
    seg = _even_split(count, L)
    seg_bytes = [c * itemsize for c in seg]
    intra_scale = 0.5 if compressed and itemsize == 4 else 1.0
    cross_scale = 0.5 if (compressed or compress_cross) and itemsize == 4 \
        else 1.0
    # Phase 1: local reduce-scatter at rot=-1.
    intra = _ring_reduce_phase_bytes(seg_bytes, L, -1, lr) * intra_scale
    # Phase 3: local allgatherv of the segment blocks (never compressed
    # — only reduce phases ride the codec).
    intra += sum(seg_bytes[(lr - s + L) % L] for s in range(L - 1))
    # Phase 2: flat allreduce of segment lr across M slices.
    my = seg[lr]
    cross_counts = [c * itemsize for c in _even_split(my, M)]
    cr = rank // L
    cross = _ring_reduce_phase_bytes(cross_counts, M, 0, cr)
    cross += sum(cross_counts[_ring_send_segment(cr, s, M, 1)]
                 for s in range(M - 1))
    return {"intra": int(intra), "cross": int(cross * cross_scale)}


def plan_redistribute(shape, dtype, src, dst, compressed=False):
    """Plan the minimal collective sequence moving a ``shape``/``dtype``
    array from layout ``src`` to layout ``dst`` (the table in the
    module docstring). Raises on rank-count mismatch or sharded layouts
    that do not cover the array's rows.

    ``compressed`` mirrors the runtime's ``HOROVOD_WIRE_COMPRESSION``
    knob: the reduce phases of the plan's allreduce/reduce-scatter
    steps then ride the bf16 codec (f32 payloads only), halving their
    predicted wire bytes — callers executing under the compressed wire
    must pass it or the byte reconciliation reads 2x. Gather/exchange
    steps never compress (the codec covers reduce phases only)."""
    if src.nranks != dst.nranks:
        raise ValueError(
            f"src ({src.nranks} ranks) and dst ({dst.nranks} ranks) must "
            "describe the same world — resizing the WORLD is the elastic "
            "layer's job; resharding redistributes within one world")
    shape = tuple(int(d) for d in shape)
    itemsize = np.dtype(dtype).itemsize
    for layout, name in ((src, "src"), (dst, "dst")):
        if layout.kind == "sharded" and layout.n_rows != shape[0]:
            raise ValueError(
                f"{name} layout covers {layout.n_rows} rows; array has "
                f"{shape[0]}")
    if dst.kind == "partial":
        raise ValueError("a partial (pending-reduction) destination is "
                         "not a materializable layout")
    n = src.nranks
    zeros = tuple(0 for _ in range(n))
    # The bf16 codec engages on f32 reduce phases only (docs/wire.md).
    comp = bool(compressed) and itemsize == 4

    def slice_step():
        return ReshardStep("slice", zeros, zeros, detail="local rows")

    steps = []
    if src == dst:
        pass  # zero-copy
    elif src.kind == "replicated":
        # dst sharded: every rank already holds its rows.
        steps.append(slice_step())
    elif src.kind == "sharded":
        if dst.kind == "replicated":
            steps.append(_allgatherv_step(src, shape, itemsize))
        else:  # sharded -> sharded, different rows
            steps.append(_alltoallv_step(src, dst, shape, itemsize))
    else:  # partial source
        if dst.kind == "replicated":
            steps.append(_allreduce_step(shape, itemsize, n,
                                         compressed=comp))
        else:
            even = Layout.sharded(shape[0], n)
            steps.append(_reducescatter_step(even, shape, itemsize,
                                             compressed=comp))
            if dst != even:
                steps.append(_alltoallv_step(even, dst, shape, itemsize))
    return ReshardPlan(src=src, dst=dst, shape=shape, itemsize=itemsize,
                       steps=tuple(steps))


# ---- executors -------------------------------------------------------

def simulate_plan(plan, locals_by_rank):
    """Pure-numpy all-rank reference executor (the property-test
    oracle): ``locals_by_rank[r]`` is rank r's local block under
    ``plan.src``; returns the per-rank blocks under ``plan.dst``.
    No wire, but the SAME data movement semantics as execute_plan."""
    n = plan.src.nranks
    src, dst = plan.src, plan.dst
    if src.kind == "replicated":
        full = locals_by_rank[0]
    elif src.kind == "sharded":
        full = np.concatenate([np.asarray(b) for b in locals_by_rank])
    else:  # partial: the logical value is the sum of addends
        full = np.sum([np.asarray(b) for b in locals_by_rank], axis=0)
    if dst.kind == "replicated":
        return [full.copy() for _ in range(n)]
    return [full[s:s + c].copy() for s, c in dst.rows]


def execute_plan(plan, local, name="reshard", eager_ops=None):
    """Run this rank's side of the plan over the eager host
    collectives; returns the local block under ``plan.dst``.

    ``local`` is this rank's block under ``plan.src`` (the full array
    for replicated/partial sources). Collective: every rank must call
    with the same ``name`` in the same order. ``eager_ops`` is
    injectable for tests; defaults to the process-wide module."""
    if eager_ops is None:
        from horovod_tpu.common import eager_ops as _ops
        eager_ops = _ops
    from horovod_tpu.common.basics import HorovodBasics

    rank = HorovodBasics().rank()
    local = np.ascontiguousarray(local)
    out = local
    for i, step in enumerate(plan.steps):
        sname = f"{name}.{i}.{step.op}"
        if step.op == "slice":
            s, c = plan.dst.range_of(rank)
            out = out[s:s + c].copy()
        elif step.op == "allgatherv":
            out = eager_ops.allgather_async(out, sname).synchronize()
        elif step.op == "alltoallv":
            # Contiguous ordered partitions on both sides: the rows this
            # rank sends to each new owner are consecutive runs of its
            # local block, and rows arrive already in dst order.
            src_layout = plan.src if i == 0 else \
                Layout.sharded(plan.shape[0], plan.src.nranks)
            s0, _ = src_layout.range_of(rank)
            splits = []
            for d in range(plan.dst.nranks):
                d0, dn = plan.dst.range_of(d)
                lo = max(s0, d0)
                hi = min(s0 + out.shape[0], d0 + dn)
                splits.append(max(hi - lo, 0))
            out = eager_ops.alltoall_async(out, splits,
                                           sname).synchronize()
        elif step.op == "reducescatter":
            out = eager_ops.reducescatter_async(out, sname).synchronize()
        elif step.op == "allreduce":
            out = eager_ops.allreduce_async(out, sname).synchronize()
        else:  # pragma: no cover — planner emits only the ops above
            raise ValueError(f"unknown plan step {step.op!r}")
    if plan.zero_copy:
        return local
    return out


def reshard_rows(local, rows_held, name="elastic.reshard",
                 eager_ops=None):
    """Re-balance a row-sharded array onto the even layout of the
    CURRENT world — the elastic state-flow primitive (docs/elastic.md).

    After a shrink or grow re-formation, each member passes the row
    count every NEW rank currently holds (``rows_held``, rank-ordered;
    a fresh joiner holds 0) and its own block ``local`` (a joiner: an
    empty ``(0, ...)`` array with the right trailing shape and dtype).
    Returns this rank's block under the fresh even partition, moved by
    the minimal planner sequence (a single alltoallv for
    sharded->sharded). Collective: every rank must call with identical
    ``rows_held`` and ``name`` — derive ``rows_held`` from synced state
    (e.g. the pre-fault even layout mapped through the survivor list),
    never from per-rank observation.
    """
    counts = [int(c) for c in rows_held]
    rows, pos = [], 0
    for c in counts:
        rows.append((pos, c))
        pos += c
    local = np.ascontiguousarray(local)
    src = Layout.from_rows(rows)
    dst = Layout.sharded(pos, len(counts))
    plan = plan_redistribute((pos,) + tuple(local.shape[1:]),
                             local.dtype, src, dst)
    return execute_plan(plan, local, name=name, eager_ops=eager_ops)


# ---- jax surface -----------------------------------------------------

def _spec_tuple(sharding):
    spec = getattr(sharding, "spec", None)
    return tuple(spec) if spec is not None else ()

def layout_from_sharding(sharding, shape):
    """Row :class:`Layout` of a ``NamedSharding`` whose axis-0 spec is
    the only sharded dimension (the repo's checkpoint/param layouts).
    Replicated specs map to the replicated layout; anything sharded on
    a later axis is rejected (redistribute plans rows)."""
    spec = _spec_tuple(sharding)
    if any(s is not None for s in spec[1:]):
        raise ValueError(
            f"redistribute plans axis-0 row layouts; spec {spec} shards "
            "a later axis (transpose it to axis 0 first)")
    axis0 = spec[0] if spec else None
    mesh = sharding.mesh
    nranks = int(math.prod(mesh.shape.values()))
    if axis0 is None:
        return Layout.replicated(nranks)
    names = (axis0,) if isinstance(axis0, str) else tuple(axis0)
    shards = int(math.prod(mesh.shape[a] for a in names))
    if nranks % shards:
        raise ValueError(f"mesh {dict(mesh.shape)} does not tile "
                         f"{shards} shards")
    # Device-order row ranges; replication across the remaining axes
    # does not change which rows exist, so the row layout is the
    # shards-way even split.
    return Layout.sharded(shape[0], shards)


def redistribute(array, src_sharding=None, dst_sharding=None):
    """``hvd.redistribute(array, src, dst)``: move a jax array between
    shardings with the minimal collective sequence.

    Zero-copy when the shardings agree (the SAME array object comes
    back — pinned in tests). Otherwise XLA executes the movement
    (``jax.device_put`` lowers to exactly the planner's collective on
    TPU meshes) while :func:`plan_redistribute` prices it for
    telemetry. ``src_sharding`` defaults to ``array.sharding``."""
    import jax

    if dst_sharding is None:
        raise ValueError("redistribute needs a destination sharding")
    if src_sharding is None:
        src_sharding = getattr(array, "sharding", None)
    if src_sharding is not None and (
            src_sharding == dst_sharding or
            (_spec_tuple(src_sharding) == _spec_tuple(dst_sharding) and
             getattr(src_sharding, "mesh", None) is
             getattr(dst_sharding, "mesh", None))):
        return array  # zero-copy: layouts already agree
    return jax.device_put(array, dst_sharding)
