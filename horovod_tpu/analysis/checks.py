"""The hvdlint check catalog (C1-C8) over an extracted signature.

Each check is a pure function ``(extraction, context) -> [Diagnostic]``;
:func:`run_all` applies every shipped check. See docs/analysis.md for
the catalog with before/after examples.
"""

import collections

from horovod_tpu.analysis import diagnostics as D
from horovod_tpu.analysis.extract import (
    Branches,
    Collective,
    Loop,
    iter_nodes,
    linearize,
)


def check_collective_divergence(ex, ctx):
    """C1: cond/switch branches whose collective sequences differ.

    Under SPMD every rank must issue the SAME ordered collective
    sequence; a data-dependent branch with differing sequences
    deadlocks the moment two ranks disagree on the predicate. When the
    predicate provably derives from ``lax.axis_index`` the disagreement
    is structural, not probabilistic — the message says so.
    """
    out = []
    for node in iter_nodes(ex.signature):
        if not isinstance(node, Branches):
            continue
        sigs = [tuple(c.key for c in linearize(opt))
                for opt in node.options]
        if len(set(sigs)) <= 1:
            continue
        counts = "/".join(str(len(s)) for s in sigs)
        cause = ("predicate derives from lax.axis_index — ranks WILL "
                 "take different branches"
                 if node.pred_rank_dependent else
                 "any cross-rank disagreement on the predicate deadlocks")
        out.append(D.make(
            "C1", node.path,
            f"cond/switch branches issue different collective "
            f"sequences ({counts} collectives per branch); {cause}",
            hint="hoist collectives out of the branches (compute "
                 "masked contributions and reduce unconditionally), or "
                 "make every branch issue the identical sequence",
            source=node.source))
    return out


def check_axis_validity(ex, ctx):
    """C2: collectives over axis names absent from the declared mesh.

    ``mesh_axes`` of ``None`` means the caller declared nothing at all
    (no mesh, no axis_env) — no ground truth, skip. An EMPTY declared
    set is different: every collective axis is then undeclared (the
    no-mesh typo'd-axis case) and must be flagged.
    """
    mesh_axes = ctx.get("mesh_axes")
    if mesh_axes is None:
        return []
    declared = set(mesh_axes)
    out = []
    for node in iter_nodes(ex.signature):
        if not isinstance(node, Collective):
            continue
        unknown = [a for a in node.axes if a not in declared]
        if unknown:
            out.append(D.make(
                "C2", node.path,
                f"{node.prim} over axis {unknown} not in the declared "
                f"mesh axes {sorted(declared)}",
                hint="add the axis to the mesh (parallel.mesh."
                     "create_mesh) or fix the axis name; on jax<0.6 "
                     "boxes a drifted vmap axis_name shows up exactly "
                     "like this",
                source=node.source))
    return out


def check_width_waste(ex, ctx):
    """C3: fp32 reductions fed by bf16/fp16 producers whose result is
    consumed at fp32 — the wire carries double the information the
    data holds. The f32-accumulate roundtrip (cast up, reduce, cast
    straight back down) is exempt: that is the numerically-recommended
    pattern and the cast is fused on TPU."""
    out = []
    for node in iter_nodes(ex.signature):
        if not isinstance(node, Collective):
            continue
        if node.upcast_from and not node.roundtrip:
            out.append(D.make(
                "C3", node.path,
                f"{node.prim} reduces float32 data upcast from "
                f"{node.upcast_from} ({node.nelems} elements) and the "
                f"result stays float32",
                hint=f"reduce in {node.upcast_from} (EQuARX-style "
                     "compressed allreduce is the cheapest ICI win), or "
                     "cast the result straight back to "
                     f"{node.upcast_from} if f32 was only for "
                     "accumulation",
                source=node.source))
    return out


def check_donation_hazards(ex, ctx):
    """C4: donated buffers that cannot alias any output — more donated
    buffers of a (shape, dtype) class than outputs of that class.
    XLA's "Some donated buffers were not usable" warning-class (the r6
    apply-jit bug: grads donated into an apply whose outputs are
    exactly params+opt) promoted to a pre-commit error.

    A donated invar the program never READS is fine by itself —
    ``fused_master_adam`` donates the previous compute-cast purely as
    output storage — so unconsumed donations are flagged only when
    they also fail the aliasing count; the message calls them out as
    the likely dead weight.
    """
    out = []
    for site in ex.donation_sites:
        jaxpr = site.jaxpr.jaxpr if hasattr(site.jaxpr, "jaxpr") \
            else site.jaxpr
        outvars = [v for v in jaxpr.outvars if hasattr(v, "count")]
        donated_vars = [v for v, d in zip(jaxpr.invars, site.donated)
                        if d]
        read = set()
        for eqn in jaxpr.eqns:
            read.update(v for v in eqn.invars if hasattr(v, "count"))
        read.update(outvars)

        buckets = collections.Counter(_bucket(v) for v in donated_vars)
        out_buckets = collections.Counter(_bucket(v) for v in outvars)
        for bucket, n_donated in sorted(buckets.items()):
            n_out = out_buckets.get(bucket, 0)
            excess = n_donated - n_out
            if excess <= 0:
                continue
            shape, dtype = bucket
            n_unread = sum(1 for v in donated_vars
                           if _bucket(v) == bucket and v not in read)
            unread = (f" ({n_unread} of them never read by the "
                      "program)" if n_unread else "")
            out.append(D.make(
                "C4", site.path,
                f"{excess} donated {dtype}{list(shape)} buffer(s) in "
                f"program '{site.name}' cannot alias any output "
                f"({n_donated} donated vs {n_out} outputs of that "
                f"shape/dtype){unread} — XLA will warn 'donated "
                "buffers were not usable' and silently keep them live",
                hint="donate only buffers an output can reuse 1:1 "
                     "(e.g. params/opt-state into their updated "
                     "versions); gradients feeding an apply program "
                     "usually must NOT be donated",
                source=site.source))
    return out


def check_schedule_conformance(ex, ctx):
    """C5: the traced collective sequence must equal the host-side
    prediction (``expect_collectives`` — built by
    ``parallel.pipeline.predicted_collectives`` from the same schedule
    tables the engines execute, or by
    ``parallel.ops.predicted_hier_collectives`` /
    ``ReshardPlan.expected_collectives`` for the composed-plane and
    redistribute programs). The reduce-scatter primitive is spelled
    differently across jax versions (``psum_scatter`` vs
    ``reduce_scatter``); both sides normalize so a version bump cannot
    fake a divergence."""
    expected = ctx.get("expect_collectives")
    if expected is None:
        return []

    def norm(p):
        return "reduce_scatter" if p in _SCATTER_PRIMS else p

    actual = [(norm(c.prim), tuple(c.axes))
              for c in linearize(ex.signature)]
    expected = [(norm(p), tuple(a) if isinstance(a, (tuple, list))
                 else (a,))
                for p, a in expected]
    if actual == expected:
        return []
    msg = _first_divergence(actual, expected)
    return [D.make(
        "C5", "<program>",
        f"collective sequence deviates from the schedule table's "
        f"prediction: {msg}",
        hint="the engine and its host schedule builder disagree — "
             "either the schedule table changed without the engine "
             "(or vice versa), or an extra/missing collective crept "
             "into the stage/loss functions")]


def _first_divergence(actual, expected):
    n = min(len(actual), len(expected))
    for i in range(n):
        if actual[i] != expected[i]:
            return (f"first divergence at collective #{i}: traced "
                    f"{actual[i]}, predicted {expected[i]} "
                    f"(traced {len(actual)} vs predicted "
                    f"{len(expected)} total)")
    return (f"traced {len(actual)} collectives vs predicted "
            f"{len(expected)} (prefix matches)")


def _bucket(v):
    aval = v.aval
    return (tuple(int(d) for d in aval.shape), str(aval.dtype))


#: the reduce-scatter primitive spellings across jax versions
_SCATTER_PRIMS = ("psum_scatter", "reduce_scatter")


def check_shard_collective_pairing(ex, ctx):
    """C6: every reduce-scatter must pair with an allgather over the
    SAME axes, AT OR AFTER it in program order — the ZeRO invariant
    (docs/zero.md): a program that scatters a tensor into shards and
    never gathers anything back on that axis leaves state silently
    sharded, which downstream replicated-semantics consumers read as
    garbage on N-1 ranks (and in the split-step shape means updated
    params never reassemble). Order matters: an FSDP-style param
    gather BEFORE the scatter cannot reassemble the scatter's result,
    so it must not mask the finding (pure per-axis counting would).
    Walked over the linearized signature so loop trip counts weigh in;
    extra allgathers alone are fine (they have no scatter side)."""
    pending = collections.Counter()   # axes -> scatters awaiting gather
    total = collections.Counter()
    sites = {}
    for c in linearize(ex.signature):
        if c.prim in _SCATTER_PRIMS:
            pending[c.axes] += 1
            total[c.axes] += 1
            sites.setdefault(c.axes, c)
        elif c.prim == "all_gather" and pending.get(c.axes, 0) > 0:
            pending[c.axes] -= 1
    out = []
    for axes, n_unpaired in sorted(pending.items()):
        if n_unpaired <= 0:
            continue
        site = sites[axes]
        out.append(D.make(
            "C6", site.path,
            f"{total[axes]} reduce-scatter(s) over axis {list(axes)} "
            f"but only {total[axes] - n_unpaired} subsequent "
            f"allgather(s) on that axis — {n_unpaired} shard "
            "collective(s) unpaired; the scattered result stays "
            "sharded while the program's consumers expect replicated "
            "values",
            hint="pair every reduce-scatter with an all_gather on the "
                 "same axis (the ZeRO apply shape: scatter grads, "
                 "update shards, gather params), or allowlist C6 if "
                 "the program deliberately keeps that state sharded",
            source=site.source))
    return out


#: C7's tail window: the check fires only when EVERY scatter-family
#: collective issues after this fraction of the program's flops is
#: already behind it — i.e. nothing is left to overlap with.
_C7_TAIL_FRACTION = 0.10


def check_collective_interleaving(ex, ctx):
    """C7: scatter-family collectives bunched after the compute tail.

    The fused jit-lane step only earns its keep when the per-bucket
    reduce-scatters issue WHILE backward compute remains — interleaved,
    XLA's async pipelining hides their wire time under the flops that
    follow; bunched after the last dot_general, every byte is exposed
    on the critical path (the eager lane's overlap ledger measures the
    same thing dynamically; C7 is its static twin over the jaxpr).

    Walks the extraction's compute/collective profile and fires when
    the program (a) does real arithmetic, (b) issues two or more
    scatter-family collectives — one bucket has nothing to interleave
    with — and (c) EVERY one of them sits after at least
    ``1 - _C7_TAIL_FRACTION`` of the total flop mass. Quiet by
    construction on the eager lane (collectives live outside the jaxpr,
    so the profile has no ``coll`` events), on the unfused shard apply
    (its first reduce-scatter leads the program: flops-before = 0), and
    on pure-wire programs like ``hier_allreduce`` (no flop mass).
    """
    profile = getattr(ex, "profile", ())
    flops_total = sum(ev[1] for ev in profile if ev[0] == "flops")
    if flops_total <= 0:
        return []
    scatters = []
    flops_before = 0
    for ev in profile:
        if ev[0] == "flops":
            flops_before += ev[1]
        elif ev[1] in _SCATTER_PRIMS:
            scatters.append((flops_before, ev))
    if len(scatters) < 2:
        return []
    threshold = (1.0 - _C7_TAIL_FRACTION) * flops_total
    if any(before < threshold for before, _ in scatters):
        return []
    first_before, (_, prim, axes, path, source) = scatters[0]
    pct = 100.0 * first_before / flops_total
    return [D.make(
        "C7", path,
        f"{len(scatters)} {prim} collective(s) over axis {list(axes)} "
        f"are bunched at the program tail: the first issues only after "
        f"{pct:.0f}% of the flops, so no remaining compute can hide "
        "their wire time — the reduce-scatters serialize onto the "
        "critical path",
        hint="emit each bucket's reduce-scatter as its gradients become "
             "ready (parallel.fusion.interleave_collectives reorders "
             "the fused step's jaxpr to do this; HOROVOD_JIT_FUSION=0 "
             "deliberately restores the bunched split step)",
        source=source)]


def check_rank_dependent_trip_count(ex, ctx):
    """C8: collectives inside a loop whose trip count is rank-tainted.

    C1 catches collective sequences that diverge across *branches*; a
    ``while_loop`` whose cond derives (transitively, through the
    carry) from ``lax.axis_index`` diverges across *iteration counts*
    — rank A runs the body k times, rank B k+1 times, so B's last
    collective rendezvouses with nothing and every rank deadlocks.
    extract.py's while walker runs the same carry-taint fixpoint scan
    uses and records cond-output taint as ``Loop.trip_rank_dependent``
    (scans have a static trip count and are always quiet).
    """
    out = []
    for node in iter_nodes(ex.signature):
        if not isinstance(node, Loop) or not node.trip_rank_dependent:
            continue
        colls = [c for c in iter_nodes(node.body)
                 if isinstance(c, Collective)]
        if not colls:
            continue
        prims = sorted({c.prim for c in colls})
        out.append(D.make(
            "C8", node.path,
            f"{len(colls)} collective(s) ({', '.join(prims)}) inside a "
            "while_loop whose trip count derives from lax.axis_index — "
            "ranks run different iteration counts, so the extra "
            "iterations' collectives rendezvous with nothing: "
            "guaranteed deadlock",
            hint="make the trip count rank-invariant (psum/pmax the "
                 "bound before the loop), or hoist the collective out "
                 "of the loop and mask per-iteration contributions",
            source=node.source or colls[0].source))
    return out


ALL_CHECKS = (
    check_collective_divergence,
    check_axis_validity,
    check_width_waste,
    check_donation_hazards,
    check_schedule_conformance,
    check_shard_collective_pairing,
    check_collective_interleaving,
    check_rank_dependent_trip_count,
)


def run_all(extraction, context=None):
    """Apply every check; returns the concatenated diagnostics."""
    context = context or {}
    out = []
    for check in ALL_CHECKS:
        out.extend(check(extraction, context))
    return out
